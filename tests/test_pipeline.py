"""Pipeline-parallelism tests: GPipe schedule numerics vs sequential
execution, gradient equivalence (autodiff'd backward pipeline), dp x pp
composition, and the full pipelined train step."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from flexflow_tpu.parallel.pipeline import (
    make_pipelined_transformer_step,
    pipelined_apply,
)


def _mesh(devices, dp, pp):
    return Mesh(np.array(devices[: dp * pp]).reshape(dp, pp), ("data", "pp"))


def _block(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked_params(layers, dim, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(layers, dim, dim) / np.sqrt(dim), jnp.float32),
        "b": jnp.asarray(rng.randn(layers, dim) * 0.1, jnp.float32),
    }


def _sequential(params, x):
    for i in range(params["w"].shape[0]):
        x = _block(jax.tree.map(lambda a: a[i], params), x)
    return x


@pytest.mark.parametrize("dp,pp,mb", [(1, 4, 8), (2, 4, 4), (1, 8, 8)])
def test_pipeline_matches_sequential(devices8, dp, pp, mb):
    mesh = _mesh(devices8, dp, pp)
    params = _stacked_params(layers=pp * 2, dim=16)
    x = np.random.RandomState(1).randn(16, 16).astype(np.float32)

    y_pipe = pipelined_apply(_block, params, jnp.asarray(x), mesh=mesh,
                             num_microbatches=mb)
    y_seq = _sequential(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y_pipe), np.asarray(y_seq),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential(devices8):
    mesh = _mesh(devices8, 2, 4)
    params = _stacked_params(layers=4, dim=8)
    x = np.random.RandomState(2).randn(8, 8).astype(np.float32)

    def loss_pipe(p):
        return pipelined_apply(_block, p, jnp.asarray(x), mesh=mesh,
                               num_microbatches=4).sum()

    def loss_seq(p):
        return _sequential(p, jnp.asarray(x)).sum()

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-4)


def test_pipeline_rejects_bad_shapes(devices8):
    mesh = _mesh(devices8, 1, 4)
    params = _stacked_params(layers=6, dim=8)  # 6 % 4 != 0
    x = jnp.zeros((8, 8))
    with pytest.raises(ValueError, match="not divisible by pp"):
        pipelined_apply(_block, params, x, mesh=mesh, num_microbatches=4)
    params4 = _stacked_params(layers=4, dim=8)
    with pytest.raises(ValueError, match="num_microbatches"):
        pipelined_apply(_block, params4, x, mesh=mesh, num_microbatches=3)


def test_pipelined_transformer_trains(devices8):
    mesh = _mesh(devices8, 2, 4)
    init_fn, step_fn = make_pipelined_transformer_step(
        mesh, layers=4, hidden=16, ffn=32, num_heads=4, num_classes=4,
        num_microbatches=4, lr=0.1,
    )
    params = init_fn(seed=0)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8, 16), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 16), jnp.int32)
    losses = []
    for _ in range(10):
        params, loss = step_fn(params, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


@pytest.mark.parametrize("dp,pp,mb", [(1, 4, 8), (2, 4, 4)])
def test_1f1b_matches_gpipe(devices8, dp, pp, mb):
    """The 1F1B schedule computes the SAME loss and parameter update as
    GPipe — same math, different activation residency."""
    mesh = _mesh(devices8, dp, pp)
    kw = dict(layers=4, hidden=16, ffn=32, num_heads=4, num_classes=4,
              num_microbatches=mb, lr=0.1)
    init_g, step_g = make_pipelined_transformer_step(
        mesh, schedule="gpipe", **kw)
    init_o, step_o = make_pipelined_transformer_step(
        mesh, schedule="1f1b", **kw)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8, 16), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 16), jnp.int32)
    pg, lg = step_g(init_g(seed=0), x, y)
    po, lo = step_o(init_o(seed=0), x, y)
    assert abs(float(lg) - float(lo)) < 1e-6
    for key in ("blocks", "head"):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6),
            pg[key], po[key],
        )


def test_1f1b_trains(devices8):
    mesh = _mesh(devices8, 2, 4)
    init_fn, step_fn = make_pipelined_transformer_step(
        mesh, layers=4, hidden=16, ffn=32, num_heads=4, num_classes=4,
        num_microbatches=4, lr=0.1, schedule="1f1b",
    )
    params = init_fn(seed=0)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 8, 16), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 16), jnp.int32)
    losses = []
    for _ in range(10):
        params, loss = step_fn(params, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] and np.isfinite(losses).all()


def test_1f1b_activation_memory_scales_with_stages_not_microbatches():
    """The schedule's reason to exist: saved-activation residency is
    O(S) per stage (the [2S-1] ring), independent of M.  At constant
    microbatch SIZE (batch grows with M), GPipe's saved boundaries grow
    linearly with M while the 1F1B ring stays flat.  Verified via
    compiled buffer analysis (measured on the CPU backend: gpipe
    522k->2415k temp bytes from M=4 to M=32, 1f1b 118448->118576)."""
    devices = jax.devices()[:4]
    mesh = Mesh(np.array(devices).reshape(1, 4), ("data", "pp"))
    kw = dict(layers=4, hidden=32, ffn=64, num_heads=4, num_classes=4,
              lr=0.1)

    def temp_bytes(schedule, mb):
        init_fn, step_fn = make_pipelined_transformer_step(
            mesh, num_microbatches=mb, schedule=schedule, **kw)
        params = init_fn(seed=0)
        x = jnp.zeros((4 * mb, 8, 32), jnp.float32)  # 4-row microbatches
        y = jnp.zeros((4 * mb,), jnp.int32)
        mem = step_fn.lower(params, x, y).compile().memory_analysis()
        return int(mem.temp_size_in_bytes)

    g8, g32 = temp_bytes("gpipe", 8), temp_bytes("gpipe", 32)
    o8, o32 = temp_bytes("1f1b", 8), temp_bytes("1f1b", 32)
    assert g32 > g8 * 2.0      # GPipe: saved boundaries grow with M
    assert o32 < o8 * 1.05     # 1F1B: ring is M-independent
    assert o8 < g8 / 3         # and far below GPipe at the same config
