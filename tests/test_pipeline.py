"""Pipeline-parallelism tests: GPipe schedule numerics vs sequential
execution, gradient equivalence (autodiff'd backward pipeline), dp x pp
composition, and the full pipelined train step."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from flexflow_tpu.parallel.pipeline import (
    make_pipelined_transformer_step,
    pipelined_apply,
)


def _mesh(devices, dp, pp):
    return Mesh(np.array(devices[: dp * pp]).reshape(dp, pp), ("data", "pp"))


def _block(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked_params(layers, dim, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(layers, dim, dim) / np.sqrt(dim), jnp.float32),
        "b": jnp.asarray(rng.randn(layers, dim) * 0.1, jnp.float32),
    }


def _sequential(params, x):
    for i in range(params["w"].shape[0]):
        x = _block(jax.tree.map(lambda a: a[i], params), x)
    return x


@pytest.mark.parametrize("dp,pp,mb", [(1, 4, 8), (2, 4, 4), (1, 8, 8)])
def test_pipeline_matches_sequential(devices8, dp, pp, mb):
    mesh = _mesh(devices8, dp, pp)
    params = _stacked_params(layers=pp * 2, dim=16)
    x = np.random.RandomState(1).randn(16, 16).astype(np.float32)

    y_pipe = pipelined_apply(_block, params, jnp.asarray(x), mesh=mesh,
                             num_microbatches=mb)
    y_seq = _sequential(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y_pipe), np.asarray(y_seq),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential(devices8):
    mesh = _mesh(devices8, 2, 4)
    params = _stacked_params(layers=4, dim=8)
    x = np.random.RandomState(2).randn(8, 8).astype(np.float32)

    def loss_pipe(p):
        return pipelined_apply(_block, p, jnp.asarray(x), mesh=mesh,
                               num_microbatches=4).sum()

    def loss_seq(p):
        return _sequential(p, jnp.asarray(x)).sum()

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-4)


def test_pipeline_rejects_bad_shapes(devices8):
    mesh = _mesh(devices8, 1, 4)
    params = _stacked_params(layers=6, dim=8)  # 6 % 4 != 0
    x = jnp.zeros((8, 8))
    with pytest.raises(ValueError, match="not divisible by pp"):
        pipelined_apply(_block, params, x, mesh=mesh, num_microbatches=4)
    params4 = _stacked_params(layers=4, dim=8)
    with pytest.raises(ValueError, match="num_microbatches"):
        pipelined_apply(_block, params4, x, mesh=mesh, num_microbatches=3)


def test_pipelined_transformer_trains(devices8):
    mesh = _mesh(devices8, 2, 4)
    init_fn, step_fn = make_pipelined_transformer_step(
        mesh, layers=4, hidden=16, ffn=32, num_heads=4, num_classes=4,
        num_microbatches=4, lr=0.1,
    )
    params = init_fn(seed=0)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8, 16), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 16), jnp.int32)
    losses = []
    for _ in range(10):
        params, loss = step_fn(params, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
