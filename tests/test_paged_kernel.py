"""Fused Pallas PagedAttention (ops/pallas/paged_attention.py) vs the
gather oracle (ops/attention.py `_attend_decode_paged`, the reference
formulation): interpret-mode parity across page sizes, partial tail
blocks, scratch rows, CoW-shared prefix blocks and the seq-C chunk
twin; the build-time ConfigError gate for pallas-less runtimes; the
jaxpr assertion that the kernel-path decode step materializes NO dense
[slots, decode_max_seq] K/V view; and scheduler-level greedy
token-identity between `--paged-kernel gather` and `pallas` on the
shared-prefix smoke workload (docs/SERVING.md "Fused paged
attention")."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.config import ConfigError, FFConfig, resolve_paged_kernel
from flexflow_tpu.ops.pallas import paged_attention as pk

V, S, B = 32, 16, 4


# -- kernel-level parity (interpret mode; no model compiles) ----------

def _gather_oracle(qh, k_pool, v_pool, btab, slen, scale):
    """The read math of ops/attention._attend_decode_paged, verbatim:
    dense per-row gather + per-position masked softmax."""
    b, s, h, _ = qh.shape
    page = k_pool.shape[1]
    n = btab.shape[1] * page
    key_pos = jnp.arange(n, dtype=jnp.int32)
    pos = slen.reshape(b).astype(jnp.int32)
    ctxs = []
    for j in range(s):
        pj = pos + j
        kv_k = jnp.take(k_pool, btab, axis=0).reshape(b, n, h, -1)
        kv_v = jnp.take(v_pool, btab, axis=0).reshape(b, n, h, -1)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qh[:, j:j + 1],
                            kv_k.astype(qh.dtype)) * scale
        mask = key_pos[None, :] <= pj[:, None]
        scores = jnp.where(mask[:, None, None, :], scores,
                           jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores, axis=-1)
        ctxs.append(jnp.einsum("bhqk,bkhd->bqhd", probs,
                               kv_v.astype(qh.dtype)))
    return ctxs[0] if s == 1 else jnp.concatenate(ctxs, axis=1)


def _random_case(rng, b, s, h, d, page, table_width, extra_blocks=0):
    """Pools + per-row tables with PARTIAL TAIL positions and one
    SCRATCH row (slot 0: seq_len 0, table all zeros — the idle-slot
    shape).  Every live row gets distinct non-contiguous blocks."""
    nb = 1 + (b * table_width) + extra_blocks
    k_pool = jnp.asarray(rng.randn(nb, page, h, d), jnp.float32)
    v_pool = jnp.asarray(rng.randn(nb, page, h, d), jnp.float32)
    qh = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    perm = rng.permutation(np.arange(1, nb))[:b * table_width]
    btab = perm.reshape(b, table_width).astype(np.int32)
    btab[0] = 0  # scratch row
    # partial tails on purpose: positions NOT page-aligned, and the
    # chunk must fit inside the table for every row
    top = table_width * page - s
    slen = np.array([0] + [1 + rng.randint(top - 1)
                           for _ in range(b - 1)], np.int32)
    return qh, k_pool, v_pool, jnp.asarray(btab), jnp.asarray(slen)


@pytest.mark.parametrize("page", [4, 8])
@pytest.mark.parametrize("chunk", [1, 4])
def test_kernel_parity_vs_gather_oracle(page, chunk):
    """fp32-tolerance parity of the fused kernel against the gather
    read math — page sizes {4, 8}, partial tail blocks, a scratch row,
    both the seq-1 decode twin and the seq-C chunk twin."""
    rng = np.random.RandomState(7 * page + chunk)
    qh, kp, vp, btab, slen = _random_case(
        rng, b=5, s=chunk, h=3, d=16, page=page, table_width=4)
    scale = 1.0 / np.sqrt(16)
    got = pk.paged_attention(qh, kp, vp, btab, slen, scale,
                             interpret=True)
    want = _gather_oracle(qh, kp, vp, btab, slen, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-6, atol=2e-6)


def test_kernel_parity_cow_shared_prefix_blocks():
    """Two rows whose tables map the SAME physical blocks (the prefix
    cache's CoW sharing shape) read identically to the oracle — the
    kernel must stream a shared page once per row without caring who
    else references it."""
    rng = np.random.RandomState(11)
    qh, kp, vp, btab, slen = _random_case(
        rng, b=4, s=1, h=2, d=8, page=4, table_width=4)
    btab = np.asarray(btab).copy()
    btab[2, :2] = btab[1, :2]  # rows 1 and 2 share their first 2 blocks
    btab[3, 0] = btab[1, 0]    # row 3 shares one
    slen = jnp.asarray([0, 9, 10, 5], jnp.int32)
    btab = jnp.asarray(btab)
    got = pk.paged_attention(qh, kp, vp, btab, slen, 0.25,
                             interpret=True)
    want = _gather_oracle(qh, kp, vp, btab, slen, 0.25)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-6, atol=2e-6)


def test_kernel_decode_and_chunk_twins_agree():
    """The seq-C chunk twin over a freshly scattered chunk equals C
    seq-1 decode calls at successive positions (the host-side twin
    relationship build_paged_chunk_step documents)."""
    rng = np.random.RandomState(3)
    C, page, tw = 4, 4, 4
    qh, kp, vp, btab, slen = _random_case(
        rng, b=3, s=C, h=2, d=8, page=page, table_width=tw)
    scale = 0.3
    chunk_out = np.asarray(pk.paged_chunk_attention(
        qh, kp, vp, btab, slen, scale, interpret=True))
    for j in range(C):
        one = np.asarray(pk.paged_decode_attention(
            qh[:, j:j + 1], kp, vp, btab, slen + j, scale,
            interpret=True))
        np.testing.assert_allclose(chunk_out[:, j:j + 1], one,
                                   rtol=2e-6, atol=2e-6)


def test_blocks_read_scales_with_live_tokens():
    """The host telemetry twin of the kernel's traffic discipline:
    per-step blocks follow live tokens, not the table width — the
    bench leg's 'KV bytes read' signal."""
    page, tw = 4, 8
    seq_lens = np.array([0, 5, 12, 0])
    live = np.array([False, True, True, True])
    # idle rows cost 0 (their scratch fetch is an elided repeat); pos 5
    # -> 2 blocks; pos 12 -> 4 blocks; live pos 0 -> 1 block
    assert pk.blocks_read(seq_lens, live, 1, page, tw) == 0 + 2 + 4 + 1
    # dense equivalent is ALWAYS slots * table width
    assert len(seq_lens) * tw == 32
    # widening the table does not change what live rows read
    assert pk.blocks_read(seq_lens, live, 1, page, 64) == 7
    # a chunk reaches chunk-1 positions further
    assert pk.blocks_read(np.array([3]), np.array([True]), 4, page, tw) \
        == 2
    # ...but never past the table
    assert pk.blocks_read(np.array([30]), np.array([True]), 4, page, tw) \
        == tw


# -- config gate ------------------------------------------------------

def test_selecting_kernel_without_pallas_is_config_error(monkeypatch):
    """The clean-fallback satellite: a pallas-less jax fails the flag
    at BUILD time with a ConfigError naming the fix — never a deep
    ImportError mid-compile."""
    assert resolve_paged_kernel("gather") == "gather"
    assert resolve_paged_kernel("pallas") == "pallas"  # this runtime has it
    monkeypatch.setattr(pk, "_HAVE_PALLAS", False)
    with pytest.raises(ConfigError, match="pallas"):
        resolve_paged_kernel("pallas")
    # the gather oracle never needs pallas
    assert resolve_paged_kernel("gather") == "gather"


def test_paged_kernel_flag_validated_and_parsed():
    with pytest.raises(ValueError, match="paged_kernel"):
        FFConfig(paged_kernel="fused")
    with pytest.raises(ConfigError, match="paged_kernel"):
        resolve_paged_kernel("fused")
    assert FFConfig.from_args([]).paged_kernel == "gather"
    assert FFConfig.from_args(
        ["--paged-kernel", "pallas"]).paged_kernel == "pallas"


def test_dense_cache_rejects_kernel_selection():
    """kv_kernel='pallas' without a paged pool has no block table to
    stream through — refused loudly at make_gpt_decoder time."""
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.decoding import make_gpt_decoder
    from flexflow_tpu.models.transformer import build_gpt

    ff = FFModel(FFConfig(batch_size=2, num_devices=1))
    build_gpt(ff, batch_size=2, seq_length=8, hidden_size=16,
              num_layers=1, num_heads=2, intermediate_size=32,
              vocab_size=16)
    with pytest.raises(ValueError, match="kv_page_size"):
        make_gpt_decoder(ff, kv_kernel="pallas")


# -- model-level: the compiled decode step ----------------------------

@pytest.fixture(scope="module")
def trained(devices8):
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt

    ff = FFModel(FFConfig(batch_size=B, num_devices=1))
    build_gpt(ff, batch_size=B, seq_length=S, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    rng = np.random.RandomState(0)
    start = rng.randint(0, V, (B, 1))
    step = rng.randint(1, 6, (B, 1))
    seq_ids = (start + step * np.arange(S + 1)) % V
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
    for _ in range(30):
        ff.train_step({"input": ids, "positions": pos}, labels)
    return ff, ids


def _collect_avals(jaxpr, acc):
    """Every intermediate aval in `jaxpr`, recursing into sub-jaxprs
    (pjit bodies, scan bodies, the pallas kernel jaxpr, ...)."""
    from jax.core import ClosedJaxpr, Jaxpr

    def subs(val):
        if isinstance(val, ClosedJaxpr):
            yield val.jaxpr
        elif isinstance(val, Jaxpr):
            yield val
        elif isinstance(val, (list, tuple)):
            for v in val:
                yield from subs(v)

    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            acc.append(v.aval)
        for val in eqn.params.values():
            for sub in subs(val):
                _collect_avals(sub, acc)
    return acc


def _decode_step_avals(ff, devices8, kv_kernel):
    from flexflow_tpu.decoding import (build_paged_decode_step,
                                       make_gpt_decoder)

    page = 4
    nb = 1 + B * (S // page)
    paged = make_gpt_decoder(ff, devices=devices8[:1], kv_page_size=page,
                             kv_num_blocks=nb, kv_kernel=kv_kernel)
    step = build_paged_decode_step(paged)
    btab = np.arange(1, nb, dtype=np.int32).reshape(B, S // page)
    args = (paged._weights, paged._state, jnp.zeros(B, jnp.int32),
            jnp.zeros(B, jnp.int32), jnp.asarray(btab))
    jaxpr = jax.make_jaxpr(lambda *a: step(*a))(*args)
    return _collect_avals(jaxpr.jaxpr, []), paged, step, btab


def test_jaxpr_kernel_step_has_no_dense_gather(trained, devices8):
    """THE traffic assertion: the kernel-path decode step's jaxpr
    contains NO [slots, decode_max_seq, heads, head_dim] intermediate
    — the dense K/V view the gather oracle materializes every step is
    structurally absent, not just optimized away."""
    ff, _ = trained
    dense_view = (B, S, 4, 8)  # [slots, decode_max_seq, heads, head_dim]

    gather_avals, _, _, _ = _decode_step_avals(ff, devices8, "gather")
    assert any(getattr(a, "shape", None) == dense_view
               for a in gather_avals), \
        "oracle sanity: the gather formulation must materialize the view"

    kernel_avals, _, _, _ = _decode_step_avals(ff, devices8, "pallas")
    offenders = [a for a in kernel_avals
                 if getattr(a, "shape", None) == dense_view]
    assert not offenders, (
        f"kernel decode step materializes dense K/V views: {offenders}")


def test_kernel_decode_step_matches_gather_through_model(trained,
                                                        devices8):
    """End-to-end fp32 parity of the compiled kernel-path decode step
    against the gather oracle, and identical greedy argmax over a full
    sequence (the property the scheduler-level identity test rides)."""
    ff, ids = trained
    _, g_paged, g_step, btab = _decode_step_avals(ff, devices8, "gather")
    _, k_paged, k_step, _ = _decode_step_avals(ff, devices8, "pallas")
    g_state, k_state = g_paged._state, k_paged._state
    for t in range(S - 1):
        toks = jnp.asarray(ids[:, t])
        slens = jnp.asarray(np.full(B, t, np.int32))
        bt = jnp.asarray(btab)
        g_logits, g_state = g_step(g_paged._weights, g_state, toks,
                                   slens, bt)
        k_logits, k_state = k_step(k_paged._weights, k_state, toks,
                                   slens, bt)
        g, k = np.asarray(g_logits), np.asarray(k_logits)
        np.testing.assert_allclose(k, g, rtol=2e-5, atol=2e-5)
        np.testing.assert_array_equal(k.argmax(-1), g.argmax(-1))
    # the kernel only replaces the READ side: the first attention
    # layer's pool bytes (whose k/v inputs are pure embeddings,
    # identical between formulations) must match BIT FOR BIT — deeper
    # layers legitimately drift at fp tolerance, because their k/v
    # inputs ride the previous layer's attention output
    for key in ("k_cache", "v_cache"):
        np.testing.assert_array_equal(
            np.asarray(g_state["attn_0"][key]),
            np.asarray(k_state["attn_0"][key]),
            err_msg=f"attn_0.{key} write bytes diverged — the kernel "
                    "path must scatter exactly like the oracle")
        np.testing.assert_allclose(
            np.asarray(k_state["attn_1"][key]),
            np.asarray(g_state["attn_1"][key]), rtol=2e-4, atol=2e-6)


def test_kernel_chunk_twin_matches_gather_chunk_twin(trained, devices8):
    """The seq-C chunk twin under the kernel (one fused dispatch per
    layer) matches the gather chunk twin to fp tolerance, and a chunk
    whose trailing PAD positions run past the position table never
    corrupts a real block — the kernel scatter carries the same
    scratch-routing clamp build_paged_prefill_step pins."""
    from flexflow_tpu.decoding import (build_paged_chunk_step,
                                       make_gpt_decoder)

    ff, ids = trained
    page, C = 4, 4
    max_blocks = S // page
    nb = 1 + B * max_blocks
    btab = np.arange(1, nb, dtype=np.int32).reshape(B, max_blocks)

    def twin(kv_kernel):
        m = make_gpt_decoder(ff, devices=devices8[:1], kv_page_size=page,
                             kv_num_blocks=nb, step_tokens=C,
                             kv_kernel=kv_kernel)
        return m, build_paged_chunk_step(m)

    g_twin, g_step = twin("gather")
    k_twin, k_step = twin("pallas")
    g_state, k_state = g_twin._state, k_twin._state
    for start in (0, C):  # two full chunks: positions 0..7
        toks = jnp.asarray(ids[:, start:start + C])
        pos = jnp.asarray(np.full(B, start, np.int32))
        bt = jnp.asarray(btab)
        g_logits, g_state = g_step(g_twin._weights, g_state, toks, pos, bt)
        k_logits, k_state = k_step(k_twin._weights, k_state, toks, pos, bt)
        np.testing.assert_allclose(np.asarray(k_logits),
                                   np.asarray(g_logits),
                                   rtol=2e-4, atol=2e-5)
    # pad overflow: a chunk at S-2 puts positions S, S+1 past the
    # table — the kernel path must not let those writes clamp onto the
    # row's last real block (key slot S-1 stays byte-stable)
    before = {key: np.asarray(k_state["attn_0"][key]).copy()
              for key in ("k_cache", "v_cache")}
    toks = jnp.asarray(ids[:, :C])
    pos = jnp.asarray(np.full(B, S - 2, np.int32))
    _, k_state = k_step(k_twin._weights, k_state, toks, pos,
                        jnp.asarray(btab))
    for key in ("k_cache", "v_cache"):
        after = np.asarray(k_state["attn_0"][key])
        for i in range(B):
            for t in range(S - 2):  # every position before the chunk
                blk, off = btab[i, t // page], t % page
                np.testing.assert_array_equal(
                    after[blk, off], before[key][blk, off],
                    err_msg=f"attn_0.{key} row {i} position {t} "
                            "corrupted by a pad write")


# -- scheduler-level: the serving smoke workload ----------------------

def test_scheduler_greedy_token_identical_gather_vs_kernel(trained,
                                                           devices8):
    """Acceptance: greedy completions on the shared-prefix smoke
    workload are token-identical under --paged-kernel pallas vs the
    gather oracle (prefix cache + chunked prefill ON in both), and the
    kernel's per-step KV reads actually undercut the dense-gather
    equivalent."""
    from flexflow_tpu.serving import ContinuousScheduler

    ff, _ = trained

    def run(paged_kernel):
        sched = ContinuousScheduler.from_trained(
            ff, batch_slots=B, page_size=4, devices=devices8[:1],
            prefix_cache=True, prefill_chunk=4,
            paged_kernel=paged_kernel, check_invariants=True)
        try:
            rng = np.random.RandomState(9)
            prefix = rng.randint(0, V, 8).tolist()  # 2 full pages
            prompts = [prefix]
            prompts += [prefix
                        + rng.randint(0, V, rng.randint(1, 5)).tolist()
                        for _ in range(6)]
            prompts.append(prefix)  # full-prompt COW rehit
            mnts = [int(rng.randint(2, 7)) for _ in prompts]
            handles = [sched.generate_async(p, m)
                       for p, m in zip(prompts, mnts)]
            got = [h.wait(120.0) for h in handles]
            sched.pool.check_invariants()
            return got, sched.stats()
        finally:
            sched.close()

    want, g_stats = run("gather")
    got, k_stats = run("pallas")
    assert got == want
    assert g_stats["paged_kernel"]["formulation"] == "gather"
    assert g_stats["paged_kernel"]["blocks_read"] == 0
    kk = k_stats["paged_kernel"]
    assert kk["formulation"] == "pallas"
    # reads happened, and they undercut the dense-gather equivalent
    assert 0 < kk["blocks_read"] < kk["dense_blocks_equiv"]
    assert kk["bytes_read"] > 0
    assert kk["dense_bytes_avoided"] > 0
