"""Paged KV-cache pool accounting (serving/kv_pool.py): block
conservation under arbitrary admit/extend/retire interleavings, the
reservation discipline (a full pool queues, never crashes), and the
occupancy/fragmentation telemetry the scheduler reports."""
import numpy as np
import pytest

from flexflow_tpu.serving.kv_pool import (KVPool, PoolExhausted,
                                          SCRATCH_BLOCK)


def test_basic_lifecycle():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    assert pool.usable_blocks == 8 and pool.used_blocks == 0
    assert pool.try_admit(1, 10)  # 3 blocks reserved
    assert pool.reserved_blocks == 3
    assert pool.used_blocks == 0  # allocate-on-extend, not on admit
    grown = pool.extend(1, 1)
    assert len(grown) == 1 and pool.used_blocks == 1
    assert pool.extend(1, 4) == []  # still inside block 1
    assert len(pool.extend(1, 5)) == 1  # crosses into block 2
    assert pool.table_of(1) == grown + pool.table_of(1)[1:]
    pool.retire(1)
    assert pool.used_blocks == 0 and pool.reserved_blocks == 0
    pool.check_invariants()


def test_full_pool_queues_not_crashes():
    pool = KVPool(num_blocks=5, page_size=2, max_blocks_per_seq=4)
    assert pool.try_admit(1, 8)  # 4 of 4 usable
    assert not pool.try_admit(2, 2)  # full: refused, caller queues
    pool.retire(1)
    assert pool.try_admit(2, 2)  # freed capacity admits
    pool.check_invariants()


def test_oversize_request_rejected_loudly():
    pool = KVPool(num_blocks=17, page_size=2, max_blocks_per_seq=4)
    with pytest.raises(ValueError, match="table width"):
        pool.try_admit(1, 10)  # 5 blocks > 4-wide table


def test_extension_past_reservation_is_a_bug():
    pool = KVPool(num_blocks=9, page_size=2, max_blocks_per_seq=4)
    assert pool.try_admit(1, 4)  # 2 blocks
    pool.extend(1, 4)
    with pytest.raises(PoolExhausted):
        pool.extend(1, 5)


def test_double_admit_rejected():
    pool = KVPool(num_blocks=9, page_size=2, max_blocks_per_seq=4)
    assert pool.try_admit(1, 2)
    with pytest.raises(ValueError, match="already admitted"):
        pool.try_admit(1, 2)


def test_table_row_pads_with_scratch():
    pool = KVPool(num_blocks=9, page_size=2, max_blocks_per_seq=4)
    assert pool.try_admit(7, 6)
    pool.extend(7, 3)  # 2 blocks
    row = pool.table_row(7)
    assert row.dtype == np.int32 and len(row) == 4
    assert list(row[:2]) == pool.table_of(7)
    assert all(b == SCRATCH_BLOCK for b in row[2:])
    assert all(b == SCRATCH_BLOCK for b in pool.table_row(None))


def test_property_random_interleaving():
    """The acceptance property: after ANY admit/extend/retire sequence,
    allocated blocks equal the sum of live block tables — no leaks, no
    double-frees — and exhaustion only ever refuses admission."""
    rng = np.random.RandomState(0)
    pool = KVPool(num_blocks=33, page_size=4, max_blocks_per_seq=8)
    live = {}  # seq id -> (target_tokens, current_tokens)
    next_id = 0
    admitted = refused = 0
    for _ in range(2000):
        op = rng.randint(3)
        if op == 0:  # admit
            target = int(rng.randint(1, 33))
            if pool.try_admit(next_id, target):
                live[next_id] = [target, 0]
                admitted += 1
            else:
                refused += 1
            next_id += 1
        elif op == 1 and live:  # extend one live sequence a token
            sid = list(live)[rng.randint(len(live))]
            target, cur = live[sid]
            if cur < target:
                live[sid][1] = cur + 1
                pool.extend(sid, cur + 1)
        elif op == 2 and live:  # retire one
            sid = list(live)[rng.randint(len(live))]
            del live[sid]
            pool.retire(sid)
        pool.check_invariants()
        assert pool.used_blocks == sum(
            len(pool.table_of(s)) for s in live)
        assert 0.0 <= pool.occupancy() <= 1.0
        frag = pool.fragmentation({s: live[s][1] for s in live})
        assert 0.0 <= frag <= 1.0
    assert admitted > 50 and refused > 10  # both paths exercised
    for sid in list(live):
        pool.retire(sid)
    pool.check_invariants()
    assert pool.used_blocks == 0 and pool.reserved_blocks == 0
    assert pool.peak_used > 0


def test_fragmentation_counts_last_block_waste():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    assert pool.try_admit(1, 5)
    pool.extend(1, 5)  # 2 blocks = 8 slots for 5 tokens
    assert pool.fragmentation({1: 5}) == pytest.approx(3 / 8)
    assert pool.fragmentation({1: 8}) == 0.0  # full blocks: no waste
