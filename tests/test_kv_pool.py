"""Paged KV-cache pool accounting (serving/kv_pool.py): block
conservation under arbitrary admit/extend/retire interleavings, the
reservation discipline (a full pool queues, never crashes), the
occupancy/fragmentation telemetry the scheduler reports, and the
prefix cache — refcounted copy-on-write block sharing, LRU eviction
of retired sequences' blocks, and the sharing-aware invariants
(refcount == live tables referencing, cached disjoint from free)."""
import numpy as np
import pytest

from flexflow_tpu.serving.kv_pool import (KVPool, PoolExhausted,
                                          SCRATCH_BLOCK)


def test_basic_lifecycle():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    assert pool.usable_blocks == 8 and pool.used_blocks == 0
    assert pool.try_admit(1, 10)  # 3 blocks reserved
    assert pool.reserved_blocks == 3
    assert pool.used_blocks == 0  # allocate-on-extend, not on admit
    grown = pool.extend(1, 1)
    assert len(grown) == 1 and pool.used_blocks == 1
    assert pool.extend(1, 4) == []  # still inside block 1
    assert len(pool.extend(1, 5)) == 1  # crosses into block 2
    assert pool.table_of(1) == grown + pool.table_of(1)[1:]
    pool.retire(1)
    assert pool.used_blocks == 0 and pool.reserved_blocks == 0
    pool.check_invariants()


def test_full_pool_queues_not_crashes():
    pool = KVPool(num_blocks=5, page_size=2, max_blocks_per_seq=4)
    assert pool.try_admit(1, 8)  # 4 of 4 usable
    assert not pool.try_admit(2, 2)  # full: refused, caller queues
    pool.retire(1)
    assert pool.try_admit(2, 2)  # freed capacity admits
    pool.check_invariants()


def test_oversize_request_rejected_loudly():
    pool = KVPool(num_blocks=17, page_size=2, max_blocks_per_seq=4)
    with pytest.raises(ValueError, match="table width"):
        pool.try_admit(1, 10)  # 5 blocks > 4-wide table


def test_extension_past_reservation_is_a_bug():
    pool = KVPool(num_blocks=9, page_size=2, max_blocks_per_seq=4)
    assert pool.try_admit(1, 4)  # 2 blocks
    pool.extend(1, 4)
    with pytest.raises(PoolExhausted):
        pool.extend(1, 5)


def test_double_admit_rejected():
    pool = KVPool(num_blocks=9, page_size=2, max_blocks_per_seq=4)
    assert pool.try_admit(1, 2)
    with pytest.raises(ValueError, match="already admitted"):
        pool.try_admit(1, 2)


def test_table_row_pads_with_scratch():
    pool = KVPool(num_blocks=9, page_size=2, max_blocks_per_seq=4)
    assert pool.try_admit(7, 6)
    pool.extend(7, 3)  # 2 blocks
    row = pool.table_row(7)
    assert row.dtype == np.int32 and len(row) == 4
    assert list(row[:2]) == pool.table_of(7)
    assert all(b == SCRATCH_BLOCK for b in row[2:])
    assert all(b == SCRATCH_BLOCK for b in pool.table_row(None))


def test_property_random_interleaving():
    """The acceptance property: after ANY admit/extend/retire sequence,
    allocated blocks equal the sum of live block tables — no leaks, no
    double-frees — and exhaustion only ever refuses admission."""
    rng = np.random.RandomState(0)
    pool = KVPool(num_blocks=33, page_size=4, max_blocks_per_seq=8)
    live = {}  # seq id -> (target_tokens, current_tokens)
    next_id = 0
    admitted = refused = 0
    for _ in range(2000):
        op = rng.randint(3)
        if op == 0:  # admit
            target = int(rng.randint(1, 33))
            if pool.try_admit(next_id, target):
                live[next_id] = [target, 0]
                admitted += 1
            else:
                refused += 1
            next_id += 1
        elif op == 1 and live:  # extend one live sequence a token
            sid = list(live)[rng.randint(len(live))]
            target, cur = live[sid]
            if cur < target:
                live[sid][1] = cur + 1
                pool.extend(sid, cur + 1)
        elif op == 2 and live:  # retire one
            sid = list(live)[rng.randint(len(live))]
            del live[sid]
            pool.retire(sid)
        pool.check_invariants()
        assert pool.used_blocks == sum(
            len(pool.table_of(s)) for s in live)
        assert 0.0 <= pool.occupancy() <= 1.0
        assert 0.0 <= pool.fragmentation() <= 1.0
    assert admitted > 50 and refused > 10  # both paths exercised
    for sid in list(live):
        pool.retire(sid)
    pool.check_invariants()
    assert pool.used_blocks == 0 and pool.reserved_blocks == 0
    assert pool.peak_used > 0


def test_fragmentation_counts_last_block_waste():
    """The pool tracks per-sequence written-token counts ITSELF
    (extend watermark + note_written), so fragmentation cannot drift
    from the tables under sharing — callers pass nothing."""
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    assert pool.try_admit(1, 5)
    pool.extend(1, 5)  # 2 blocks = 8 slots, covering a write at pos 4
    assert pool.fragmentation() == pytest.approx(4 / 8)  # 4 written
    pool.note_written(1, 5)
    assert pool.fragmentation() == pytest.approx(3 / 8)
    pool.note_written(1, 8)
    assert pool.fragmentation() == 0.0  # full blocks: no waste


# -- prefix cache: sharing, COW, eviction --------------------------------

def _run_seq(pool, sid, prompt, total=None):
    """Admit + extend a sequence through `total` tokens (default: the
    whole prompt) the way the scheduler would, then leave it live."""
    total = len(prompt) if total is None else total
    assert pool.try_admit(sid, total, prompt=prompt)
    start = pool.admit_hit_tokens(sid)
    for t in range(max(start, 1), total + 1):
        pool.extend(sid, t)
    pool.note_written(sid, total)
    return start


def test_retired_blocks_stay_cached_and_rehit():
    pool = KVPool(num_blocks=17, page_size=4, max_blocks_per_seq=4)
    prompt = list(range(10, 22))  # 12 tokens = 3 full blocks
    _run_seq(pool, 1, prompt)
    blocks = pool.table_of(1)
    pool.retire(1, tokens=prompt)
    assert pool.used_blocks == 0
    assert pool.cached_blocks == 3  # refcount 0, LRU-evictable
    pool.check_invariants()
    # same prompt again: full table mapped from cache, zero prefill
    assert pool.try_admit(2, 14, prompt=prompt)
    assert pool.admit_hit_tokens(2) == 12
    assert pool.table_of(2) == blocks
    assert pool.prefix_hits == 1 and pool.prefix_hit_tokens == 12
    pool.check_invariants()


def test_live_sharing_refcounts_two_tables():
    pool = KVPool(num_blocks=17, page_size=4, max_blocks_per_seq=4)
    shared = list(range(8))           # 2 full blocks once written
    _run_seq(pool, 1, shared + [8, 9])
    # seq 1 still live: its full prompt blocks are indexed live, so a
    # concurrent same-prefix request shares them (refcount 2)
    assert pool.try_admit(2, 12, prompt=shared + [30, 31])
    assert pool.admit_hit_tokens(2) == 8
    assert pool.table_of(2) == pool.table_of(1)[:2]
    assert pool.shared_blocks == 2
    pool.check_invariants()
    # first holder retires: blocks stay live through seq 2's refcount
    pool.retire(1)
    assert set(pool.table_of(2)) <= set(range(1, 17))
    pool.check_invariants()
    pool.retire(2)
    pool.check_invariants()


def test_full_prompt_hit_cow_tail_block():
    pool = KVPool(num_blocks=17, page_size=4, max_blocks_per_seq=4)
    prompt = list(range(8))  # exactly 2 blocks: a FULL-prompt hit
    _run_seq(pool, 1, prompt)
    pool.retire(1, tokens=prompt)
    assert pool.try_admit(2, 12, prompt=prompt)
    assert pool.admit_hit_tokens(2) == 8
    tail = pool.table_of(2)[1]
    # the write at plen-1 re-lands in the shared tail block: the COW
    # guard must swap in a fresh private copy (src stays cached)
    cow = pool.ensure_writable(2, 7)
    assert cow is not None
    src, dst = cow
    assert src == tail and dst != tail
    assert pool.table_of(2)[1] == dst
    assert pool.cow_copies == 1
    # a second write to the same position is now private: no-op
    assert pool.ensure_writable(2, 7) is None
    pool.check_invariants()
    # the ORIGINAL block's cached entry survives for the next hit
    pool.retire(2)
    assert pool.try_admit(3, 12, prompt=prompt)
    assert pool.admit_hit_tokens(3) == 8
    pool.check_invariants()


def test_cow_divergence_isolated():
    """Two requests sharing a full-prompt prefix then diverging must
    never corrupt each other: each COWs its own private tail, tables
    end disjoint past the shared region, invariants hold throughout."""
    pool = KVPool(num_blocks=33, page_size=4, max_blocks_per_seq=8)
    prompt = list(range(8))
    _run_seq(pool, 1, prompt)
    pool.retire(1, tokens=prompt)
    assert pool.try_admit(2, 16, prompt=prompt)
    assert pool.try_admit(3, 16, prompt=prompt)
    cow2 = pool.ensure_writable(2, 7)
    cow3 = pool.ensure_writable(3, 7)
    assert cow2 is not None and cow3 is not None
    assert cow2[1] != cow3[1]  # distinct private copies
    pool.check_invariants()
    # diverge: each grows its own blocks
    for t in range(9, 13):
        pool.extend(2, t)
        pool.extend(3, t)
    t2, t3 = pool.table_of(2), pool.table_of(3)
    assert t2[0] == t3[0]                      # still-shared first block
    assert not set(t2[1:]) & set(t3[1:])       # private pasts disjoint
    pool.check_invariants()
    pool.retire(2)
    pool.retire(3)
    pool.check_invariants()


def test_cow_ok_false_drops_tail_from_match():
    pool = KVPool(num_blocks=17, page_size=4, max_blocks_per_seq=4)
    prompt = list(range(8))
    _run_seq(pool, 1, prompt)
    pool.retire(1, tokens=prompt)
    assert pool.try_admit(2, 12, prompt=prompt, cow_ok=False)
    # full hit capped one block short: the tail re-prefills privately,
    # so an engine without a device block-copy never needs COW
    assert pool.admit_hit_tokens(2) == 4
    assert pool.ensure_writable(2, 7) is None or \
        pool.table_of(2)  # write pos 7 targets a private block
    pool.check_invariants()


def test_lru_eviction_reclaims_cached_blocks():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=8)
    a = list(range(100, 108))   # 2 blocks
    b = list(range(200, 208))   # 2 blocks
    _run_seq(pool, 1, a)
    pool.retire(1, tokens=a)
    _run_seq(pool, 2, b)
    pool.retire(2, tokens=b)
    assert pool.cached_blocks == 4
    # a new 4-block sequence needs the whole pool: cached blocks are
    # reclaimed LRU-first (a's, retired earlier), never refused
    assert pool.try_admit(3, 32)
    for t in range(1, 33):
        pool.extend(3, t)
    assert pool.prefix_evictions >= 4
    assert pool.cached_blocks + pool.used_blocks <= pool.usable_blocks
    pool.check_invariants()
    pool.retire(3)
    # a's entries were evicted; b's too (whole pool was needed)
    assert pool.cached_prefix_tokens(a) == 0
    pool.check_invariants()


def test_mru_survives_pressure_over_lru():
    pool = KVPool(num_blocks=13, page_size=4, max_blocks_per_seq=8)
    a, b = list(range(100, 108)), list(range(200, 208))
    _run_seq(pool, 1, a)
    pool.retire(1, tokens=a)
    _run_seq(pool, 2, b)
    pool.retire(2, tokens=b)
    # pressure for 2 blocks: evicts from a (older), keeps b
    _run_seq(pool, 3, list(range(300, 310)))
    assert pool.cached_prefix_tokens(b) == 8
    pool.check_invariants()


def test_probe_is_readonly():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    p = list(range(8))
    assert pool.cached_prefix_tokens(p) == 0
    _run_seq(pool, 1, p)
    pool.retire(1, tokens=p)
    before = pool.prefix_stats()
    assert pool.cached_prefix_tokens(p) == 8
    assert pool.prefix_stats() == before  # no counters, no LRU touch


def test_invalidate_prefix_cache_frees_everything():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    p = list(range(8))
    _run_seq(pool, 1, p)
    pool.retire(1, tokens=p)
    assert pool.cached_blocks == 2
    pool.invalidate_prefix_cache()
    assert pool.cached_blocks == 0
    assert pool.cached_prefix_tokens(p) == 0
    pool.check_invariants()


def test_prefix_cache_off_restores_pr6_behavior():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4,
                  prefix_cache=False)
    p = list(range(8))
    _run_seq(pool, 1, p)
    pool.retire(1, tokens=p)
    assert pool.cached_blocks == 0 and pool.used_blocks == 0
    assert pool.try_admit(2, 8, prompt=p)
    assert pool.admit_hit_tokens(2) == 0
    pool.check_invariants()


def test_rolling_hash_admission_linear_in_prompt_length(monkeypatch):
    """Satellite acceptance (rolling-hash prefix keys): building a
    plen-token prompt's admission keys costs O(plen) — ONE
    page-at-a-time hash extension per block boundary, each seeing
    exactly `page` tokens — for cold admissions, prefill-time
    registration, warm rehits AND the read-only probe.  (The old
    exact-bytes keys rebuilt the whole prefix per boundary:
    O(plen^2/page).)"""
    from flexflow_tpu.serving import kv_pool as kvp

    calls = []
    real = kvp._hash_block

    def counting(h, tokens):
        calls.append(len(list(tokens)))
        return real(h, tokens)

    monkeypatch.setattr(kvp, "_hash_block", counting)
    page, P = 4, 64  # 16 block boundaries
    nb = P // page
    pool = KVPool(num_blocks=2 * nb + 1, page_size=page,
                  max_blocks_per_seq=nb)
    prompt = [int(x) for x in np.random.RandomState(5).randint(
        0, 997, P)]
    calls.clear()
    assert pool.try_admit(1, P, prompt=prompt)
    assert len(calls) <= 1  # cold cache: the first extension misses
    calls.clear()  # prefill registration: one extension per boundary
    for t in range(1, P + 1):
        pool.extend(1, t)
    pool.note_written(1, P)
    assert len(calls) == nb and all(n == page for n in calls)
    pool.retire(1, tokens=prompt)
    calls.clear()  # read-only probe of the warm cache
    assert pool.cached_prefix_tokens(prompt) == P
    assert len(calls) == nb and all(n == page for n in calls)
    calls.clear()  # warm full-prompt rehit at admission
    assert pool.try_admit(2, P, prompt=prompt)
    assert pool.admit_hit_tokens(2) == P
    assert len(calls) == nb and all(n == page for n in calls)
    pool.retire(2)
    pool.check_invariants()


def test_rolling_hash_hit_verified_exactly(monkeypatch):
    """Collision-free story: a hash hit whose bytes DIFFER is a miss,
    never a false share — forced by making every page hash collide."""
    from flexflow_tpu.serving import kv_pool as kvp

    # hashes depend only on prefix LENGTH: any two same-length
    # prefixes collide, but a chain's own boundaries stay distinct
    monkeypatch.setattr(kvp, "_hash_block",
                        lambda h, tokens: (h + 1) % 997)
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    a, b = list(range(8)), list(range(50, 58))
    _run_seq(pool, 1, a)
    pool.retire(1, tokens=a)
    # same hash (forced), different bytes: the exact per-page compare
    # must refuse the match
    assert pool.cached_prefix_tokens(b) == 0
    assert pool.try_admit(2, 8, prompt=b)
    assert pool.admit_hit_tokens(2) == 0
    # identical bytes still match through the collision
    assert pool.cached_prefix_tokens(a) == 8
    pool.check_invariants()


def test_property_random_interleaving_with_sharing():
    """The refcounted acceptance property: under random admit (with a
    pool of shared prompts) / extend / COW-write / retire
    interleavings, every physical block's refcount equals the number
    of live tables referencing it, cached blocks stay disjoint from
    free blocks, and used_blocks counts shared blocks once."""
    rng = np.random.RandomState(7)
    page = 4
    pool = KVPool(num_blocks=33, page_size=page, max_blocks_per_seq=8)
    prefixes = [rng.randint(0, 999, 8).tolist() for _ in range(3)]
    live = {}  # sid -> [prompt, target_total, written]
    next_id = 0
    admitted = hits = 0
    for _ in range(2500):
        op = rng.randint(3)
        if op == 0:  # admit a prompt sharing one of the prefixes
            prefix = prefixes[rng.randint(len(prefixes))]
            tail = rng.randint(0, 999, rng.randint(0, 6)).tolist()
            prompt = prefix + tail
            total = len(prompt) + int(rng.randint(1, 9))
            if total > 8 * page:
                continue
            if pool.try_admit(next_id, total, prompt=prompt):
                start = pool.admit_hit_tokens(next_id)
                if start:
                    hits += 1
                start = min(start, len(prompt) - 1)
                pool.ensure_writable(next_id, start)
                pool.extend(next_id, start + 1)
                live[next_id] = [prompt, total, start + 1]
                admitted += 1
            next_id += 1
        elif op == 1 and live:  # grow one live sequence a token
            sid = list(live)[rng.randint(len(live))]
            prompt, target, cur = live[sid]
            if cur < target:
                pool.ensure_writable(sid, cur)
                pool.extend(sid, cur + 1)
                live[sid][2] = cur + 1
        elif op == 2 and live:  # retire one, caching its blocks
            sid = list(live)[rng.randint(len(live))]
            prompt, _, cur = live[sid]
            toks = (prompt + rng.randint(0, 999, 8).tolist())[:cur]
            del live[sid]
            pool.retire(sid, tokens=toks)
        pool.check_invariants()
        distinct = set()
        for s in live:
            distinct.update(pool.table_of(s))
        assert pool.used_blocks == len(distinct)
        assert 0.0 <= pool.occupancy() <= 1.0
        assert 0.0 <= pool.fragmentation() <= 1.0
    assert admitted > 100 and hits > 20  # sharing genuinely exercised
    for sid in list(live):
        pool.retire(sid)
    pool.check_invariants()
    assert pool.used_blocks == 0 and pool.reserved_blocks == 0
    assert pool.peak_shared > 0


# -- rollback (speculative reject / import unwind) -----------------------

def test_rollback_truncates_blocks_and_reextends():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    assert pool.try_admit(1, 16)
    pool.extend(1, 12, written=12)
    assert len(pool.table_of(1)) == 3
    assert pool.rollback(1, 5) is None  # nothing else vouches
    assert len(pool.table_of(1)) == 2  # ceil(5/4)
    pool.check_invariants()
    # the reservation survived: the sequence re-extends to its ceiling
    pool.extend(1, 16, written=16)
    assert len(pool.table_of(1)) == 4
    pool.retire(1)
    pool.check_invariants()
    assert pool.used_blocks == 0


def test_rollback_to_zero_keeps_no_blocks():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    assert pool.try_admit(1, 8)
    pool.extend(1, 8, written=8)
    pool.rollback(1, 0)
    assert pool.table_of(1) == []
    assert pool.used_blocks == 0
    pool.check_invariants()


def test_rollback_guards():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    with pytest.raises(ValueError, match="not admitted"):
        pool.rollback(42, 0)
    assert pool.try_admit(1, 8)
    pool.extend(1, 6, written=6)
    with pytest.raises(ValueError, match="past sequence"):
        pool.rollback(1, 7)  # only 6 tokens written


def test_rollback_never_cuts_into_shared_prefix():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8]
    assert pool.try_admit(1, 10, prompt=prompt)
    pool.extend(1, 8, written=8)
    pool.retire(1, tokens=prompt)  # blocks cached + indexed
    assert pool.try_admit(2, 10, prompt=prompt)  # shares block 0
    hit = pool.admit_hit_tokens(2)
    assert hit >= 4
    pool.extend(2, 8, written=8)
    with pytest.raises(ValueError, match="shared-"):
        pool.rollback(2, hit - 1)
    pool.check_invariants()


def test_rollback_unregisters_stale_index_entries():
    """A rolled-back boundary must leave the prefix index: its block's
    content is about to be overwritten, so a future prompt matching it
    would adopt garbage."""
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8]
    assert pool.try_admit(1, 12, prompt=prompt)
    pool.extend(1, 8, written=8)  # both prompt blocks indexed
    assert pool.cached_prefix_tokens(prompt) == 8
    pool.rollback(1, 4)
    assert pool.cached_prefix_tokens(prompt) == 4  # boundary 1 gone
    assert pool.prefix_stats()["invalidations"] >= 1
    pool.check_invariants()


def test_rollback_cow_tail_still_vouched_elsewhere():
    """Rolling back into a partial tail block another live table still
    maps must copy-on-write: the survivor's bytes stay immutable."""
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8]
    assert pool.try_admit(1, 12, prompt=prompt)
    pool.extend(1, 8, written=8)
    blk0 = pool.table_of(1)[0]
    assert pool.try_admit(2, 10, prompt=prompt)  # maps blk0 (ref 2)
    copy = pool.rollback(1, 2)  # partial tail inside shared blk0
    assert copy is not None and copy[0] == blk0
    assert pool.table_of(1)[0] == copy[1] != blk0
    assert blk0 in pool.table_of(2)  # survivor untouched
    pool.check_invariants()


def test_rollback_exactly_onto_shared_block_boundary():
    """Rolling back to EXACTLY the shared-prefix watermark is legal —
    the kept region is precisely the shared blocks, so nothing private
    remains, no COW is needed, and one more token is still a guard
    violation (the speculative verifier's floor case: every draft
    rejected on the first post-prefix position)."""
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8]
    assert pool.try_admit(1, 10, prompt=prompt)
    pool.extend(1, 8, written=8)
    pool.retire(1, tokens=prompt)
    assert pool.try_admit(2, 10, prompt=prompt)
    hit = pool.admit_hit_tokens(2)
    assert hit % 4 == 0 and hit >= 4  # block-aligned shared watermark
    pool.extend(2, hit + 3, written=hit + 3)  # private growth past it
    n_shared = hit // 4
    assert pool.rollback(2, hit) is None  # lands ON the boundary
    assert len(pool.table_of(2)) == n_shared  # private tail dropped
    with pytest.raises(ValueError, match="shared-"):
        pool.rollback(2, hit - 1)  # one past the boundary still guards
    pool.check_invariants()
    # the reservation survived: regrow past the boundary again
    pool.extend(2, hit + 1, written=hit + 1)
    assert len(pool.table_of(2)) == n_shared + 1
    pool.check_invariants()


def test_rollback_of_slot_holding_cow_blocks():
    """A slot whose tail block was already COW'd (full-prompt hit ->
    private copy) rolls back WITHIN that private block without another
    copy: the block is refcount-1, so truncation is free, and the
    cached original the other table vouches for keeps its bytes."""
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8]
    assert pool.try_admit(1, 12, prompt=prompt)
    pool.extend(1, 8, written=8)
    blk1 = pool.table_of(1)[1]
    pool.retire(1, tokens=prompt)  # both blocks cached + indexed
    assert pool.try_admit(2, 12, prompt=prompt)  # full-prompt hit
    assert pool.admit_hit_tokens(2) == 8
    cow = pool.ensure_writable(2, 7)  # divergence inside the tail
    assert cow is not None and cow[0] == blk1
    priv = cow[1]
    assert pool.table_of(2)[1] == priv != blk1
    pool.extend(2, 10, written=10)  # generate into a third block
    # rollback lands inside the COW'd private block: no (src, dst)
    # pair comes back — the copy already happened at divergence time
    assert pool.rollback(2, 6) is None
    assert pool.table_of(2)[1] == priv  # still the private copy
    assert pool.cached_prefix_tokens(prompt) == 8  # original intact
    pool.check_invariants()
    pool.retire(2)
    pool.check_invariants()


def test_rollback_then_extend_reregisters_new_content():
    """The speculative reject path end-to-end: generated positions are
    rolled back, the slot regrows DIFFERENT tokens over the freed
    positions, and retirement must index the final content — the chain
    bookkeeping rollback leaves behind must still let _register run
    (a broken-chain sentinel would silently stop indexing), and the
    rolled-back generation must never be matchable."""
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2]
    rejected = prompt + [9, 4, 1, 8]   # first speculative generation
    final = prompt + [6, 2, 6, 2]      # what actually got accepted
    assert pool.try_admit(1, 12, prompt=prompt)
    pool.extend(1, 8, written=8)  # prompt block indexed, gen block not
    assert pool.cached_prefix_tokens(rejected) == 4
    assert pool.rollback(1, 5) is None  # drafts rejected mid-block
    pool.extend(1, 8, written=8)  # regrow over the freed positions
    pool.retire(1, tokens=final)  # index the content that survived
    assert pool.cached_prefix_tokens(final) == 8
    assert pool.cached_prefix_tokens(rejected) == 4  # ghost unmatchable
    assert pool.try_admit(2, 12, prompt=final)
    assert pool.admit_hit_tokens(2) == 8
    pool.check_invariants()


def test_property_random_interleaving_with_rollback():
    """Block conservation under admit/extend/ROLLBACK/retire: rollback
    frees exactly the uncovered blocks and the reservation lets every
    rolled-back sequence regrow to its original ceiling."""
    rng = np.random.RandomState(11)
    pool = KVPool(num_blocks=33, page_size=4, max_blocks_per_seq=8)
    live = {}  # sid -> [target_tokens, written_tokens]
    next_id = 0
    rollbacks = 0
    for _ in range(2500):
        op = rng.randint(4)
        if op == 0:  # admit
            target = int(rng.randint(1, 33))
            if pool.try_admit(next_id, target):
                live[next_id] = [target, 0]
            next_id += 1
        elif op == 1 and live:  # grow a token
            sid = list(live)[rng.randint(len(live))]
            target, cur = live[sid]
            if cur < target:
                pool.extend(sid, cur + 1)
                pool.note_written(sid, cur + 1)
                live[sid][1] = cur + 1
        elif op == 2 and live:  # roll back to a random watermark
            sid = list(live)[rng.randint(len(live))]
            cur = live[sid][1]
            if cur:
                to = int(rng.randint(0, cur + 1))
                assert pool.rollback(sid, to) is None  # nothing shared
                live[sid][1] = to
                rollbacks += 1
        elif op == 3 and live:  # retire
            sid = list(live)[rng.randint(len(live))]
            del live[sid]
            pool.retire(sid)
        pool.check_invariants()
        assert pool.used_blocks == sum(
            len(pool.table_of(s)) for s in live)
    assert rollbacks > 100
    for sid in list(live):
        pool.retire(sid)
    pool.check_invariants()
    assert pool.used_blocks == 0 and pool.reserved_blocks == 0


# -- KV export / adopt (cross-replica migration) -------------------------

def test_export_prefix_returns_indexed_blocks_and_pages():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8, 6]
    assert pool.try_admit(1, 12, prompt=prompt)
    pool.extend(1, 9, written=9)  # 2 full prompt blocks indexed
    blocks, pages = pool.export_prefix(prompt)
    assert blocks == pool.table_of(1)[:2]
    assert pages == [[3, 5, 7, 2], [9, 4, 1, 8]]  # sub-page 6 excluded
    assert pool.export_prefix([9] * 8) == ([], [])  # foreign prompt


def test_adopt_prefix_is_a_real_cache_hit():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8]
    pairs = pool.adopt_prefix(prompt, 2)
    assert [j for j, _ in pairs] == [0, 1]
    assert pool.cached_prefix_tokens(prompt) == 8
    assert pool.prefix_stats()["imported_blocks"] == 2
    # a real admission maps the adopted blocks
    assert pool.try_admit(1, 10, prompt=prompt)
    assert pool.admit_hit_tokens(1) >= 4
    pool.check_invariants()


def test_adopt_prefix_reuses_existing_boundaries():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8]
    assert len(pool.adopt_prefix(prompt, 2)) == 2
    assert pool.adopt_prefix(prompt, 2) == []  # nothing new to write
    assert pool.cached_prefix_tokens(prompt) == 8
    pool.check_invariants()


def test_adopt_prefix_partial_on_capacity_exhaustion():
    pool = KVPool(num_blocks=4, page_size=4, max_blocks_per_seq=3)
    assert pool.try_admit(1, 8)
    pool.extend(1, 8)  # 2 of 3 usable blocks pinned live
    pairs = pool.adopt_prefix([3, 5, 7, 2, 9, 4, 1, 8], 2)
    assert len(pairs) == 1  # partial adoption is still a prefix
    assert pool.cached_prefix_tokens([3, 5, 7, 2, 9, 4, 1, 8]) == 4
    pool.check_invariants()


def test_drop_adopted_unwinds_cleanly():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    prompt = [3, 5, 7, 2, 9, 4, 1, 8]
    pairs = pool.adopt_prefix(prompt, 2)
    pool.drop_adopted([blk for _, blk in pairs])
    assert pool.cached_prefix_tokens(prompt) == 0
    pool.check_invariants()
    # every block is reclaimable again
    assert pool.try_admit(1, 16)
    assert pool.try_admit(2, 16)
    pool.extend(1, 16)
    pool.extend(2, 16)
    assert pool.used_blocks == 8


# -- live export (mid-decode handoff) ------------------------------------

def test_export_live_includes_the_partial_tail_page():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    toks = [3, 5, 7, 2, 9, 4, 1, 8, 6, 2]  # 2 full pages + 2-token tail
    assert pool.try_admit(1, 12, prompt=toks[:7])
    pool.extend(1, 10, written=10)
    blocks, pages = pool.export_live(1, toks)
    assert blocks == pool.table_of(1)[:3]  # ceil-block: tail included
    assert pages == [[3, 5, 7, 2], [9, 4, 1, 8], [6, 2]]
    # a shorter snapshot of the same sequence is also exact
    blocks2, pages2 = pool.export_live(1, toks[:8])
    assert blocks2 == pool.table_of(1)[:2]
    assert pages2 == [[3, 5, 7, 2], [9, 4, 1, 8]]
    pool.check_invariants()


def test_export_live_guards_liveness_and_watermark():
    pool = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    toks = [3, 5, 7, 2, 9, 4]
    assert pool.try_admit(1, 8, prompt=toks)
    pool.extend(1, 6, written=5)
    with pytest.raises(ValueError, match="only 5 are written"):
        pool.export_live(1, toks)  # unwritten device bytes = garbage
    with pytest.raises(KeyError, match="not live"):
        pool.export_live(2, toks)
    pool.retire(1)
    with pytest.raises(KeyError, match="not live"):
        pool.export_live(1, toks)  # retirement revokes the export


def test_export_live_then_adopt_is_a_resume_cache_hit():
    """The live handoff round trip at pool level: export a mid-decode
    sequence, adopt its FULL pages on a second pool, and the replay
    tokens admit there as a prefix hit covering everything but the
    sub-page tail — which the resume lands in its private block."""
    src = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    dst = KVPool(num_blocks=9, page_size=4, max_blocks_per_seq=4)
    toks = [3, 5, 7, 2, 9, 4, 1, 8, 6, 2]
    assert src.try_admit(1, 12, prompt=toks[:7])
    src.extend(1, 10, written=10)
    blocks, pages = src.export_live(1, toks)
    n_full = len(toks) // 4
    pairs = dst.adopt_prefix(toks, n_full)
    assert [j for j, _ in pairs] == list(range(n_full))
    assert dst.cached_prefix_tokens(toks) == n_full * 4
    assert dst.try_admit(7, 12, prompt=toks)
    assert dst.admit_hit_tokens(7) >= n_full * 4
    src.check_invariants()
    dst.check_invariants()


def test_property_random_interleaving_with_export_adopt():
    """Block conservation under admit/grow/EXPORT/ADOPT/retire: a live
    export never perturbs the source pool's accounting, repeated
    adoption into a second pool never double-bills a block on either
    side, and both pools hold their invariants after every op — the
    no-fault-path-double-bills bar for the handoff paths."""
    rng = np.random.RandomState(17)
    src = KVPool(num_blocks=33, page_size=4, max_blocks_per_seq=8)
    dst = KVPool(num_blocks=17, page_size=4, max_blocks_per_seq=8)
    live = {}  # sid -> [written tokens...]
    next_id = 0
    exports = adopts = 0
    for _ in range(4000):
        op = rng.randint(5)
        if op == 0:  # admit
            target = int(rng.randint(1, 33))
            if src.try_admit(next_id, target):
                live[next_id] = {"target": target, "toks": []}
            next_id += 1
        elif op == 1 and live:  # grow a few tokens
            sid = list(live)[rng.randint(len(live))]
            st = live[sid]
            room = st["target"] - len(st["toks"])
            for _ in range(min(room, int(rng.randint(1, 5)))):
                st["toks"].append(int(rng.randint(16)))
            src.extend(sid, len(st["toks"]),
                       written=len(st["toks"]))
        elif op == 2 and live:  # live export: a pure read
            sid = list(live)[rng.randint(len(live))]
            toks = live[sid]["toks"]
            n = int(rng.randint(0, len(toks) + 1))
            if n:
                used = src.used_blocks
                blocks, pages = src.export_live(sid, toks[:n])
                assert blocks == src.table_of(sid)[:-(-n // 4)]
                assert sum(len(p) for p in pages) == n
                assert src.used_blocks == used  # export bills nothing
                exports += 1
        elif op == 3 and live:  # adopt full pages into the dest pool
            sid = list(live)[rng.randint(len(live))]
            toks = live[sid]["toks"]
            if len(toks) >= 4:
                dst.adopt_prefix(toks, len(toks) // 4)
                dst.check_invariants()  # refuses double-billed blocks
                adopts += 1
        elif op == 4 and live:  # retire
            sid = list(live)[rng.randint(len(live))]
            del live[sid]
            src.retire(sid)
        src.check_invariants()
        assert src.used_blocks == sum(
            len(src.table_of(s)) for s in live)
        assert src.used_blocks + len(src._free) <= src.num_blocks
    assert exports > 100 and adopts > 100
    for sid in list(live):
        src.retire(sid)
    src.check_invariants()
    assert src.used_blocks == 0 and src.reserved_blocks == 0
