"""Hybrid-parallelism tests on the hermetic 8-device CPU mesh.

These exercise the strategy machinery the way the reference's search
output would: channel (tensor) parallelism on Linear, attention head
parallelism, embedding attribute parallelism, expert parallelism — each
checked for numerical equivalence against the single-device model.
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.ops.op import ShardConfig
from flexflow_tpu.strategy import Strategy


def build_mlp(ff):
    x = ff.create_tensor([16, 32], name="x")
    t = ff.dense(x, 64, activation=ActiMode.RELU, name="fc1")
    t = ff.dense(t, 64, activation=ActiMode.RELU, name="fc2")
    t = ff.dense(t, 4, name="fc3")
    return ff


def tp_strategy(dp: int, tp: int) -> Strategy:
    # Megatron-style MLP: fc1 column-parallel (out-channels sharded),
    # fc2 row-parallel automatically (its in-dim inherits fc1's channel
    # sharding; output becomes partial-sum -> psum by SPMD).
    s = Strategy(mesh_axes={"data": dp, "model": tp})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": dp})]
    s.shard_configs["fc1"] = ShardConfig(channel=tp)
    return s


def test_tensor_parallel_linear_matches_single(devices8):
    ff_tp = build_mlp(FFModel(FFConfig(num_devices=8)))
    ff_tp.compile(strategy=tp_strategy(4, 2), devices=devices8, seed=11)
    ff_1 = build_mlp(FFModel(FFConfig(num_devices=1)))
    ff_1.compile(devices=devices8[:1], seed=11)
    xs = np.random.RandomState(0).randn(16, 32).astype(np.float32)
    y_tp = np.asarray(ff_tp.forward({"x": xs}))
    y_1 = np.asarray(ff_1.forward({"x": xs}))
    np.testing.assert_allclose(y_tp, y_1, rtol=2e-5, atol=2e-5)


def test_tensor_parallel_training_matches_single(devices8):
    def train(ff, devs, strategy=None):
        ff.compile(
            optimizer=SGDOptimizer(lr=0.1),
            loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
            strategy=strategy,
            devices=devs,
            seed=5,
        )
        xs = np.random.RandomState(3).randn(16, 32).astype(np.float32)
        ys = np.random.RandomState(4).randint(0, 4, 16).astype(np.int32)
        for _ in range(3):
            m = ff.train_step({"x": xs}, ys)
        return float(m["loss"]), ff.get_parameter("fc1", "kernel")

    loss_tp, k_tp = train(build_mlp(FFModel(FFConfig())), devices8, tp_strategy(4, 2))
    loss_1, k_1 = train(build_mlp(FFModel(FFConfig())), devices8[:1], None)
    assert abs(loss_tp - loss_1) < 1e-4
    np.testing.assert_allclose(k_tp, k_1, rtol=5e-5, atol=5e-5)


def test_attention_head_parallel(devices8):
    def build(ff):
        x = ff.create_tensor([4, 16, 32], name="x")
        t = ff.multihead_attention(x, x, x, 32, 8, name="attn")
        t = ff.dense(t, 8, name="out")
        return ff

    s = Strategy(mesh_axes={"data": 2, "model": 4})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 2})]
    s.shard_configs["attn"] = ShardConfig(channel=4)
    ff_tp = build(FFModel(FFConfig()))
    ff_tp.compile(strategy=s, devices=devices8, seed=2)
    ff_1 = build(FFModel(FFConfig()))
    ff_1.compile(devices=devices8[:1], seed=2)
    xs = np.random.RandomState(1).randn(4, 16, 32).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff_tp.forward({"x": xs})),
        np.asarray(ff_1.forward({"x": xs})),
        rtol=2e-5,
        atol=2e-5,
    )


def test_embedding_attribute_parallel(devices8):
    """Vocab-sharded embedding (reference attribute parallelism,
    embedding.cc:132-196)."""

    def build(ff):
        ids = ff.create_tensor([16, 8], dtype="int32", name="ids")
        t = ff.embedding(ids, 100, 32, name="emb")
        t = ff.dense(t, 4, name="head")
        return ff

    s = Strategy(mesh_axes={"data": 2, "model": 4})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 2})]
    s.shard_configs["emb"] = ShardConfig(attribute=4)
    ff_ap = build(FFModel(FFConfig()))
    ff_ap.compile(strategy=s, devices=devices8, seed=9)
    ff_1 = build(FFModel(FFConfig()))
    ff_1.compile(devices=devices8[:1], seed=9)
    ids = np.random.RandomState(2).randint(0, 100, (16, 8)).astype(np.int32)
    np.testing.assert_allclose(
        np.asarray(ff_ap.forward({"ids": ids})),
        np.asarray(ff_1.forward({"ids": ids})),
        rtol=2e-5,
        atol=2e-5,
    )


def test_moe_expert_parallel(devices8):
    def build(ff):
        x = ff.create_tensor([32, 16], name="x")
        t = ff.moe(x, num_exp=4, num_select=2, expert_hidden_size=8, alpha=2.0)
        return ff

    s = Strategy(mesh_axes={"data": 2, "expert": 4})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 2})]
    s.shard_configs["group_by_0"] = ShardConfig(expert=4)
    s.shard_configs["experts_dense_0"] = ShardConfig(expert=4)
    ff_ep = build(FFModel(FFConfig()))
    ff_ep.compile(strategy=s, devices=devices8, seed=4,
                  loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
    ff_1 = build(FFModel(FFConfig()))
    ff_1.compile(devices=devices8[:1], seed=4,
                 loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
    xs = np.random.RandomState(5).randn(32, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff_ep.forward({"x": xs})),
        np.asarray(ff_1.forward({"x": xs})),
        rtol=2e-5,
        atol=2e-5,
    )
