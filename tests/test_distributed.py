"""flexflow_tpu.distributed: multi-host bring-up helpers.

Reference counterpart: python/flexflow/driver.py (mpirun launcher) +
MULTI-NODE.md.  Single-process here; the per-host batch assembly runs
against a real 8-device mesh sharding, and the env-var resolution is
exercised without touching the network.
"""
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from flexflow_tpu import distributed
from flexflow_tpu.parallel.machine import make_mesh


def test_initialize_single_process_fallback(monkeypatch):
    monkeypatch.setattr(distributed, "_initialized", False)
    assert distributed.initialize() is False  # one process -> False
    # idempotent second call
    assert distributed.initialize() is False


def test_initialize_requires_coordinator(monkeypatch):
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.setenv("FLEXFLOW_NUM_PROCS", "4")
    with pytest.raises(ValueError, match="coordinator"):
        distributed.initialize()


def test_shard_host_batch_against_global_sharding(devices8):
    mesh = make_mesh({"data": 8}, devices8)
    sharding = NamedSharding(mesh, PartitionSpec("data"))
    x = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    out = distributed.shard_host_batch({"input": x}, {"input": sharding})
    arr = out["input"]
    assert arr.shape == (16, 4)
    assert arr.sharding == sharding
    np.testing.assert_array_equal(np.asarray(arr), x)
    # each device holds a 2-row shard
    assert {s.data.shape for s in arr.addressable_shards} == {(2, 4)}


def test_local_batch_slice_single_host():
    assert distributed.local_batch_slice(64) == slice(0, 64)
