"""flexflow_tpu.distributed: multi-host bring-up helpers.

Reference counterpart: python/flexflow/driver.py (mpirun launcher) +
MULTI-NODE.md.  Single-process here; the per-host batch assembly runs
against a real 8-device mesh sharding, and the env-var resolution is
exercised without touching the network.
"""
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from flexflow_tpu import distributed
from flexflow_tpu.parallel.machine import make_mesh


def test_initialize_single_process_fallback(monkeypatch):
    monkeypatch.setattr(distributed, "_initialized", False)
    assert distributed.initialize() is False  # one process -> False
    # idempotent second call
    assert distributed.initialize() is False


def test_initialize_requires_coordinator(monkeypatch):
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.setenv("FLEXFLOW_NUM_PROCS", "4")
    with pytest.raises(ValueError, match="coordinator"):
        distributed.initialize()


def test_shard_host_batch_against_global_sharding(devices8):
    mesh = make_mesh({"data": 8}, devices8)
    sharding = NamedSharding(mesh, PartitionSpec("data"))
    x = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    out = distributed.shard_host_batch({"input": x}, {"input": sharding})
    arr = out["input"]
    assert arr.shape == (16, 4)
    assert arr.sharding == sharding
    np.testing.assert_array_equal(np.asarray(arr), x)
    # each device holds a 2-row shard
    assert {s.data.shape for s in arr.addressable_shards} == {(2, 4)}


def test_local_batch_slice_single_host():
    assert distributed.local_batch_slice(64) == slice(0, 64)


def test_two_process_training():
    """REAL multi-process run: two workers join via
    distributed.initialize (explicit coordinator), build one 8-device
    global mesh (4 CPU devices each), assemble per-host batches with
    shard_host_batch, and train — loss decreases on both ranks.  The
    reference proves multi-node through its mpi_wrapper test tier; this
    is the TPU-native equivalent, hermetic on CPU."""
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    worker = os.path.join(os.path.dirname(__file__), "helpers",
                          "dist2proc_worker.py")
    env = {
        k: v for k, v in os.environ.items()
        if not (k.startswith("AXON") or k.startswith("PALLAS_AXON")
                or k in ("TPU_LIBRARY_PATH", "TPU_NAME",
                         "TPU_SKIP_MDS_QUERY", "XLA_FLAGS",
                         "JAX_PLATFORMS"))
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    if any("Multiprocess computations aren't implemented" in out
           for out in outs):
        pytest.skip(
            "this jaxlib's CPU backend has no cross-process collective "
            "transport (XLA: \"Multiprocess computations aren't "
            "implemented on the CPU backend\") — the workers join the "
            "coordinator and build the global mesh, but the first "
            "jitted computation over it cannot run; the two-process "
            "path is only executable on accelerator backends (or CPU "
            "jaxlibs with gloo collectives)"
        )
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-2000:]}"
        assert f"rank {rank}: OK" in out
