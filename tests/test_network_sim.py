"""Topology-aware simulator tests: generators, shortest-path/ECMP
routing, NetworkedMachineModel transfer estimates, and routed task-graph
simulation (reference network.cc + LogicalTaskgraphBasedSimulator)."""
import numpy as np
import pytest

from flexflow_tpu.sim.network import (
    NetworkedMachineModel,
    WeightedShortestPathRouting,
    big_switch,
    flat_degree_constrained,
    fully_connected,
    torus,
)
from flexflow_tpu.sim.taskgraph import TaskGraphBuilder, simulate_python


def _connected(conn):
    n = conn.shape[0]
    seen = {0}
    stack = [0]
    while stack:
        u = stack.pop()
        for v in np.nonzero(conn[u])[0]:
            if int(v) not in seen:
                seen.add(int(v))
                stack.append(int(v))
    return len(seen) == n


def test_generators_shapes_and_connectivity():
    fc = fully_connected(5)
    assert fc.shape == (5, 5) and fc.diagonal().sum() == 0 and _connected(fc)

    bs = big_switch(6)
    assert bs.shape == (7, 7) and _connected(bs)
    assert bs[:6, :6].sum() == 0  # hosts only talk via the switch

    for seed in range(3):
        fd = flat_degree_constrained(8, degree=3, seed=seed)
        assert _connected(fd)
        assert (fd.sum(axis=1) <= 3).all()
        assert (fd == fd.T).all()


def test_torus_generator():
    t = torus((4, 4))
    assert t.shape == (16, 16) and _connected(t)
    assert (t.sum(axis=1) == 4).all()  # 2 neighbors per axis
    t3 = torus((2, 2, 2))
    assert _connected(t3)
    # size-2 axes: single wraparound link per axis
    assert (t3.sum(axis=1) == 3).all()


def test_multi_slice_torus_generator():
    from flexflow_tpu.sim.network import multi_slice_torus

    conn = multi_slice_torus((2, 2), slices=3, dcn_links=2)
    assert conn.shape == (12, 12) and _connected(conn)
    # intra-slice blocks are the plain torus
    assert (conn[:4, :4] == torus((2, 2))).all()
    # chip i of slice a links chip i of slice b with dcn_links links
    assert conn[0, 4] == 2 and conn[4, 8] == 2
    # no cross-slice links between different chip indices
    assert conn[0, 5] == 0
    assert (conn == conn.T).all()


def test_shortest_path_routing():
    # path graph 0-1-2-3
    conn = np.zeros((4, 4), np.int32)
    for i in range(3):
        conn[i, i + 1] = conn[i + 1, i] = 1
    r = WeightedShortestPathRouting(conn)
    routes = r.get_routes(0, 3)
    assert routes == [[(0, 1), (1, 2), (2, 3)]]
    hops, narrow = r.hop_count(0, 3)
    assert hops == 3 and narrow == 1
    assert r.get_routes(2, 2) == []
    assert r.get_routes(1, 2) == [[(1, 2)]]


def test_ecmp_multiple_routes():
    # diamond: 0-1-3 and 0-2-3
    conn = np.zeros((4, 4), np.int32)
    for u, v in [(0, 1), (1, 3), (0, 2), (2, 3)]:
        conn[u, v] = conn[v, u] = 1
    r = WeightedShortestPathRouting(conn)
    routes = r.get_routes(0, 3)
    assert len(routes) == 2
    assert sorted(tuple(x) for x in routes) == [
        ((0, 1), (1, 3)), ((0, 2), (2, 3))
    ]


def test_networked_machine_model_times():
    conn = torus((4,))  # ring of 4
    m = NetworkedMachineModel(conn, link_bandwidth=1e9, link_latency=1e-6)
    direct = m.p2p_time(1 << 20, 0, 1)
    two_hop = m.p2p_time(1 << 20, 0, 2)
    assert two_hop > direct  # extra hop latency
    assert np.isclose(direct, 1e-6 + (1 << 20) / 1e9)

    ar = m.allreduce_time(1 << 20, [0, 1, 2, 3])
    ag = m.allgather_time(1 << 20, [0, 1, 2, 3])
    assert ar > ag > 0
    assert np.isclose(ar / ag, 2.0)
    assert m.allreduce_time(1 << 20, [0]) == 0.0


def test_bigger_links_are_faster():
    fat = NetworkedMachineModel(2 * torus((4,)), link_bandwidth=1e9)
    thin = NetworkedMachineModel(torus((4,)), link_bandwidth=1e9)
    assert fat.p2p_time(1 << 20, 0, 1) < thin.p2p_time(1 << 20, 0, 1)


def test_routed_taskgraph_contention():
    """Two transfers sharing a link serialize; disjoint ones overlap."""
    conn = np.zeros((3, 3), np.int32)
    conn[0, 1] = conn[1, 0] = 1
    conn[1, 2] = conn[2, 1] = 1
    m = NetworkedMachineModel(conn, link_bandwidth=1e9, link_latency=0.0,
                              compute_tflops=1.0)

    def run(pairs):
        b = TaskGraphBuilder(3, m)
        srcs = {}
        for s, _ in pairs:
            if s not in srcs:
                srcs[s] = b.add_task(0.0, s)
        for s, d in pairs:
            t = b.add_task(0.0, d)
            b.add_edge(srcs[s], t, 1e6, s, d)
        total, _ = simulate_python(b.finalize())
        return total

    shared = run([(0, 2), (0, 2)])       # both cross links 0-1 and 1-2
    single = run([(0, 2)])
    # single: 2 sequential 1ms hops = 2ms; shared: second transfer queues
    # behind the first on both links, finishing at 3ms
    assert np.isclose(single, 2e-3)
    assert np.isclose(shared, 3e-3)


def test_native_sim_agrees_on_routed_topology():
    from flexflow_tpu.sim.taskgraph import simulate_native

    conn = flat_degree_constrained(6, degree=3, seed=1)
    m = NetworkedMachineModel(conn, link_bandwidth=1e9, link_latency=1e-6,
                              compute_tflops=1.0)
    rng = np.random.RandomState(0)
    b = TaskGraphBuilder(6, m)
    prev = [b.add_task(rng.rand() * 1e-3, d) for d in range(6)]
    for step in range(4):
        cur = []
        for d in range(6):
            t = b.add_task(rng.rand() * 1e-3, d, [prev[d]])
            src = int(rng.randint(6))
            b.add_edge(prev[src], t, rng.rand() * 1e6, src, d)
            cur.append(t)
        prev = cur
    tg = b.finalize()
    res = simulate_native(tg)
    if res is None:
        pytest.skip("native lib unavailable")
    total_n, busy_n = res
    total_p, busy_p = simulate_python(tg)
    assert np.isclose(total_n, total_p, rtol=1e-12)
    np.testing.assert_allclose(busy_n, busy_p, rtol=1e-12)


def test_taskgraph_ring_fallback_still_works():
    from flexflow_tpu.sim.machine_model import SimpleMachineModel

    m = SimpleMachineModel(num_nodes=1, devices_per_node=4)
    b = TaskGraphBuilder(4, m)
    t0 = b.add_task(1e-3, 0)
    t1 = b.add_task(1e-3, 2, [t0])
    b.add_edge(t0, t1, 1e6, 0, 2)
    total, _ = simulate_python(b.finalize())
    assert np.isfinite(total) and total > 0
