"""SingleDataLoader tests: gather/shuffle correctness (native C++ path
vs numpy), prefetch pipeline, fit() integration, and sharded placement
on the 8-device mesh."""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.dataloader import (
    SingleDataLoader,
    _gather,
    _py_shuffle,
    shuffle_indices,
)
from flexflow_tpu.fftype import ActiMode


def _small_model(devices, batch=16, in_dim=8):
    cfg = FFConfig(batch_size=batch, num_devices=len(devices))
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, in_dim], name="x")
    t = ff.dense(x, 16, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    ff.softmax(t)
    from flexflow_tpu import MetricsType

    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices)
    return ff


def test_gather_matches_numpy():
    rng = np.random.RandomState(0)
    for shape in [(100, 7), (50, 3, 4), (64,)]:
        src = rng.randn(*shape).astype(np.float32)
        idx = rng.randint(0, shape[0], size=33).astype(np.int64)
        np.testing.assert_array_equal(_gather(src, idx), np.take(src, idx, axis=0))


def test_native_and_python_shuffle_agree():
    for n, seed in [(10, 1), (1000, 42), (7, 0)]:
        a = shuffle_indices(n, seed)
        b = _py_shuffle(n, seed)
        np.testing.assert_array_equal(a, b)
        assert sorted(a.tolist()) == list(range(n))


def test_loader_epoch_order_and_shuffle(devices8):
    ff = _small_model(devices8)
    n = 64
    xs = np.arange(n * 8, dtype=np.float32).reshape(n, 8)
    ys = np.arange(n, dtype=np.int32) % 4
    dl = SingleDataLoader(ff, xs, ys, batch_size=16, shuffle=False)
    assert len(dl) == 4 and dl.num_samples == n
    seen = []
    for inputs, labels in dl:
        seen.append(np.asarray(inputs["x"]))
    np.testing.assert_array_equal(np.concatenate(seen), xs)

    dl_shuf = SingleDataLoader(ff, xs, ys, batch_size=16, shuffle=True, seed=3)
    got = []
    for inputs, labels in dl_shuf:
        x_np = np.asarray(inputs["x"])
        y_np = np.asarray(labels)
        # pairing preserved under shuffle: row i is [8i..8i+7], label i%4
        np.testing.assert_array_equal(
            (x_np[:, 0] / 8).astype(np.int32) % 4, y_np
        )
        got.append(x_np)
    flat = np.concatenate(got)
    assert not np.array_equal(flat, xs)  # order changed
    np.testing.assert_array_equal(np.sort(flat[:, 0]), xs[:, 0])  # same set

    # second epoch reshuffles differently
    got2 = np.concatenate([np.asarray(i["x"]) for i, _ in dl_shuf])
    assert not np.array_equal(flat, got2)


def test_loader_sharded_placement(devices8):
    ff = _small_model(devices8)
    xs = np.random.RandomState(0).randn(32, 8).astype(np.float32)
    ys = np.zeros(32, dtype=np.int32)
    dl = SingleDataLoader(ff, xs, ys, batch_size=16)
    inputs, labels = dl.next_batch()
    assert inputs["x"].sharding == ff.executor.input_shardings()["x"]


def test_fit_with_shuffle_trains(devices8):
    ff = _small_model(devices8)
    rng = np.random.RandomState(0)
    n = 128
    w = rng.randn(8, 4)
    xs = rng.randn(n, 8).astype(np.float32)
    ys = np.argmax(xs @ w, axis=1).astype(np.int32)
    hist = ff.fit(xs, ys, batch_size=16, epochs=5, verbose=False, shuffle=True)
    assert hist[-1].sparse_cce_loss < hist[0].sparse_cce_loss
