"""Keras frontend tests (reference python/flexflow/keras surface:
Sequential, functional Model, callbacks)."""
import os
import numpy as np
import pytest

from flexflow_tpu import FFConfig
from flexflow_tpu.keras import (
    Add,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    EarlyStopping,
    Flatten,
    Input,
    LearningRateScheduler,
    MaxPooling2D,
    Model,
    Sequential,
)


def test_sequential_mlp_trains(devices8):
    m = Sequential([
        Dense(32, activation="relu"),
        Dense(4),
    ], input_shape=(16,), config=FFConfig(batch_size=16))
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"], devices=devices8)
    rng = np.random.RandomState(0)
    x = rng.randn(128, 16).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    hist = m.fit(x, y, epochs=5, verbose=False)
    assert hist[-1].accuracy > hist[0].accuracy
    preds = m.predict(x[:16])
    assert preds.shape == (16, 4)


def test_sequential_cnn_compiles():
    m = Sequential([
        Conv2D(8, 3, activation="relu"),
        MaxPooling2D(2),
        Flatten(),
        Dense(10),
    ], input_shape=(3, 16, 16), config=FFConfig(batch_size=8))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    x = np.random.RandomState(0).randn(8, 3, 16, 16).astype(np.float32)
    assert m.predict(x).shape == (8, 10)


def test_functional_multi_branch(devices8):
    a = Input((8,), name="a")
    b = Input((8,), name="b")
    ha = Dense(16, activation="relu")(a)
    hb = Dense(16, activation="relu")(b)
    merged = Concatenate()( [ha, hb] )
    res = Add()([ha, hb])
    out = Dense(4)(Concatenate()([merged, res]))
    m = Model(inputs=[a, b], outputs=out, config=FFConfig(batch_size=16))
    m.compile(devices=devices8)
    rng = np.random.RandomState(1)
    xa = rng.randn(64, 8).astype(np.float32)
    xb = rng.randn(64, 8).astype(np.float32)
    y = ((xa.sum(1) + xb.sum(1)) > 0).astype(np.int32)
    hist = m.fit({"a": xa, "b": xb}, y, epochs=3, verbose=False)
    assert len(hist) == 3
    assert "Dense" in m.summary()


def test_lr_scheduler_and_early_stopping(devices8):
    m = Sequential([Dense(8, activation="relu"), Dense(2)],
                   input_shape=(4,), config=FFConfig(batch_size=8))
    m.compile(devices=devices8)
    lrs = []

    def sched(epoch, lr):
        lrs.append(lr)
        return lr * 0.5

    rng = np.random.RandomState(0)
    x = rng.randn(32, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    m.fit(x, y, epochs=3, verbose=False,
          callbacks=[LearningRateScheduler(sched)])
    assert lrs == [0.01, 0.005, 0.0025]

    es = EarlyStopping(monitor="accuracy", patience=1)
    hist = m.fit(x, y, epochs=50, verbose=False, callbacks=[es])
    assert len(hist) < 50  # stopped early


def test_keras_lstm_reuters_style(devices8):
    """Embedding -> LSTM -> Dense classifier over the reuters loader —
    the reference's keras dataset workload shape."""
    from flexflow_tpu.keras import LSTM, Dense, Embedding, Sequential
    from flexflow_tpu.keras.datasets import reuters

    (x_train, y_train), _ = reuters.load_data(num_words=200, maxlen=16,
                                              num_samples=64)
    model = Sequential([
        Embedding(200, 16, input_length=16),
        LSTM(16, return_sequences=False),
        Dense(46, activation="softmax"),
    ])
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=16, devices=devices8)
    hist = model.fit(x_train.astype("int32"), y_train.astype("int32"),
                     batch_size=16, epochs=2, verbose=False)
    assert len(hist) == 2


def test_cifar10_canonical_tar_parse(tmp_path, monkeypatch):
    """The canonical cifar-10-python.tar.gz parse path executes against
    the vendored sample shard: real wire format (pickled batch dicts,
    byte keys, row-major RGB planes) decodes to the documented
    shapes/dtypes and the loader reports non-synthetic data
    (VERDICT r03 Weak #6 — CI previously never exercised parsing)."""
    import shutil

    import flexflow_tpu.keras.datasets as ds

    shard = os.path.join(os.path.dirname(__file__), "..", "examples",
                         "data", "cifar10_sample.tar.gz")
    cache = tmp_path / "keras_cache"
    cache.mkdir()
    shutil.copy(shard, cache / "cifar-10-python.tar.gz")
    monkeypatch.setattr(ds, "_CACHE", str(cache))
    (xtr, ytr), (xte, yte) = ds.cifar10.load_data()
    assert ds.cifar10.synthetic is False
    assert xtr.shape == (64, 3, 32, 32) and xtr.dtype == np.uint8
    assert ytr.shape == (64, 1) and set(np.unique(ytr)) <= set(range(10))
    assert xte.shape == (16, 3, 32, 32) and yte.shape == (16, 1)
    # bytes really decoded: plane layout means deterministic content,
    # not zeros, and train/test differ
    assert xtr.any() and xte.any()
    assert not np.array_equal(xtr[:16], xte)


# -- preprocessing (dependency-free keras_preprocessing parity) ----------

def test_pad_sequences_modes():
    from flexflow_tpu.keras.preprocessing import pad_sequences

    seqs = [[1, 2, 3], [4], []]
    np.testing.assert_array_equal(
        pad_sequences(seqs, maxlen=2),
        [[2, 3], [0, 4], [0, 0]])  # pre-pad, pre-truncate (defaults)
    np.testing.assert_array_equal(
        pad_sequences(seqs, maxlen=2, padding="post", truncating="post"),
        [[1, 2], [4, 0], [0, 0]])
    out = pad_sequences(seqs, value=9)
    assert out.shape == (3, 3) and out[2].tolist() == [9, 9, 9]
    with pytest.raises(ValueError):
        pad_sequences(seqs, padding="sideways")


def test_tokenizer_round_trip():
    from flexflow_tpu.keras.preprocessing import Tokenizer

    texts = ["the cat sat on the mat", "the dog sat", "cat and dog!"]
    tok = Tokenizer(num_words=5)
    tok.fit_on_texts(texts)
    assert tok.word_index["the"] == 1  # most frequent first
    seqs = tok.texts_to_sequences(texts)
    assert all(all(0 < i < 5 for i in s) for s in seqs)
    m = tok.texts_to_matrix(texts, mode="binary")
    assert m.shape == (3, 5) and set(np.unique(m)) <= {0.0, 1.0}
    counts = tok.texts_to_matrix(texts, mode="count")
    assert counts[0, 1] == 2.0  # "the" twice in the first text


def test_tokenizer_oov():
    from flexflow_tpu.keras.preprocessing import Tokenizer

    tok = Tokenizer(num_words=3, oov_token="<oov>")
    tok.fit_on_texts(["aa bb cc"])
    (seq,) = tok.texts_to_sequences(["aa zz"])
    assert len(seq) == 2 and seq[1] == tok.word_index["<oov>"]


def test_skipgrams_window():
    from flexflow_tpu.keras.preprocessing import skipgrams

    couples, labels = skipgrams([1, 2, 3], 10, window_size=1,
                                negative_samples=1.0, shuffle=False)
    pos = [tuple(c) for c, l in zip(couples, labels) if l == 1]
    assert set(pos) == {(1, 2), (2, 1), (2, 3), (3, 2)}
    assert sum(1 for l in labels if l == 0) == len(pos)


def test_verify_metrics_callback(devices8):
    from flexflow_tpu.keras import Dense, Sequential, VerifyMetrics

    rng = np.random.RandomState(0)
    x = rng.randn(256, 16).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    model = Sequential([Dense(8, activation="relu"),
                        Dense(2, activation="softmax")], input_shape=(16,))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=32)
    with pytest.raises(AssertionError, match="accuracy"):
        model.fit(x, y, epochs=1, verbose=False,
                  callbacks=[VerifyMetrics(floor=1.01)])
    model.fit(x, y, epochs=3, verbose=False,
              callbacks=[VerifyMetrics(floor=0.4, each_epoch=True)])


def test_tokenizer_tfidf_batch_independent():
    """idf comes from fit-time document frequencies, so the same text
    featurizes identically whatever batch it rides in."""
    from flexflow_tpu.keras.preprocessing import Tokenizer

    corpus = ["a b c", "a b", "a", "d d d"]
    tok = Tokenizer()
    tok.fit_on_texts(corpus)
    alone = tok.texts_to_matrix(["a b"], mode="tfidf")[0]
    batched = tok.texts_to_matrix(["a b", "d"], mode="tfidf")[0]
    np.testing.assert_allclose(alone, batched)
    # rarer term ("b": 2 docs) outweighs the ubiquitous one ("a": 3)
    ia, ib = tok.word_index["a"], tok.word_index["b"]
    assert alone[ib] > alone[ia] > 0
