"""Checkpoint/resume tests: orbax round trip, bitwise training resume,
cross-mesh restore, and the plain npz weight path."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.checkpoint import (
    CheckpointManager,
    load_weights_npz,
    save_weights_npz,
)
from flexflow_tpu.fftype import ActiMode


def _model(devices, seed=0):
    cfg = FFConfig(batch_size=16, num_devices=len(devices), seed=seed)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 32, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices, seed=seed)
    return ff


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 8).astype(np.float32)
    ys = rng.randint(0, 4, size=n).astype(np.int32)
    return xs, ys


def _weights_equal(a, b):
    import jax

    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_round_trip(devices8, tmp_path):
    ff = _model(devices8)
    xs, ys = _data()
    ff.fit(xs, ys, epochs=1, verbose=False)
    saved = ff.get_weights()

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(ff, step=1)
    assert mgr.latest_step() == 1

    ff.fit(xs, ys, epochs=1, verbose=False)  # diverge
    step = mgr.restore(ff)
    assert step == 1
    _weights_equal(ff.get_weights(), saved)
    meta = mgr.restore_meta()
    assert meta["step"] == 1 and meta["num_devices"] == 8
    mgr.close()


def test_resume_training_is_deterministic(devices8, tmp_path):
    xs, ys = _data(128)

    # uninterrupted: 4 epochs
    ff_a = _model(devices8, seed=11)
    ff_a.fit(xs, ys, epochs=2, verbose=False)
    mgr = CheckpointManager(str(tmp_path / "c1"))
    mgr.save(ff_a, step=2)
    ff_a.fit(xs, ys, epochs=2, verbose=False)

    # interrupted: fresh process-equivalent restores then continues
    ff_b = _model(devices8, seed=99)  # different init — must be overwritten
    mgr.restore(ff_b)
    ff_b.fit(xs, ys, epochs=2, verbose=False)

    _weights_equal(ff_a.get_weights(), ff_b.get_weights())
    mgr.close()


def test_cross_mesh_restore(devices8, tmp_path):
    """Checkpoint on 8 devices, restore into a 1-device model."""
    ff8 = _model(devices8)
    xs, ys = _data()
    ff8.fit(xs, ys, epochs=1, verbose=False)
    mgr = CheckpointManager(str(tmp_path / "c2"))
    mgr.save(ff8, step=0)

    ff1 = _model(devices8[:1], seed=5)
    mgr.restore(ff1)
    _weights_equal(ff1.get_weights(), ff8.get_weights())

    y8 = np.asarray(ff8.forward({"x": xs[:16]}))
    y1 = np.asarray(ff1.forward({"x": xs[:16]}))
    np.testing.assert_allclose(y8, y1, rtol=2e-5, atol=2e-5)
    mgr.close()


def test_npz_weights_round_trip(devices8, tmp_path):
    ff = _model(devices8)
    xs, ys = _data()
    ff.fit(xs, ys, epochs=1, verbose=False)
    path = str(tmp_path / "w.npz")
    save_weights_npz(ff, path)
    saved = ff.get_weights()

    ff.fit(xs, ys, epochs=1, verbose=False)
    load_weights_npz(ff, path)
    _weights_equal(ff.get_weights(), saved)


def test_local_manager_round_trip_and_retention(devices8, tmp_path):
    from flexflow_tpu.checkpoint import LocalCheckpointManager

    ff = _model(devices8)
    xs, ys = _data()
    ff.fit(xs, ys, epochs=1, verbose=False)
    saved = ff.get_weights()

    mgr = LocalCheckpointManager(str(tmp_path / "lc"), max_to_keep=2)
    mgr.save(ff, step=1)
    assert mgr.latest_step() == 1
    meta = mgr.restore_meta()
    assert meta["step"] == 1 and meta["num_devices"] == 8

    ff.fit(xs, ys, epochs=1, verbose=False)  # diverge
    step = mgr.restore(ff)
    assert step == 1
    _weights_equal(ff.get_weights(), saved)

    # keep-last-k pruning: saving steps 2 and 3 drops step 1
    mgr.save(ff, step=2)
    mgr.save(ff, step=3)
    assert mgr.all_steps() == [2, 3]


def test_local_manager_corrupt_latest_falls_back(devices8, tmp_path):
    """A corrupt/partial latest checkpoint is skipped: restore lands on
    the previous intact one."""
    import os

    from flexflow_tpu.checkpoint import LocalCheckpointManager

    ff = _model(devices8)
    xs, ys = _data()
    ff.fit(xs, ys, epochs=1, verbose=False)
    w1 = ff.get_weights()
    mgr = LocalCheckpointManager(str(tmp_path / "lc"))
    mgr.save(ff, step=1)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr.save(ff, step=2)

    # simulate a torn write: step 2's npz is garbage
    npz = os.path.join(str(tmp_path / "lc"), "step_00000002", "state.npz")
    with open(npz, "wb") as f:
        f.write(b"not a checkpoint")
    ff.fit(xs, ys, epochs=1, verbose=False)  # diverge further
    step = mgr.restore(ff)
    assert step == 1
    _weights_equal(ff.get_weights(), w1)

    # an explicitly requested corrupt step stays strict
    import pytest as _pytest
    with _pytest.raises(Exception):
        mgr.restore(ff, step=2)


def test_local_manager_cross_mesh_restore(devices8, tmp_path):
    from flexflow_tpu.checkpoint import LocalCheckpointManager

    ff8 = _model(devices8)
    xs, ys = _data()
    ff8.fit(xs, ys, epochs=1, verbose=False)
    mgr = LocalCheckpointManager(str(tmp_path / "lc"))
    mgr.save(ff8, step=0)

    ff1 = _model(devices8[:1], seed=5)
    mgr.restore(ff1)
    _weights_equal(ff1.get_weights(), ff8.get_weights())
    y8 = np.asarray(ff8.forward({"x": xs[:16]}))
    y1 = np.asarray(ff1.forward({"x": xs[:16]}))
    np.testing.assert_allclose(y8, y1, rtol=2e-5, atol=2e-5)


def test_orbax_restore_falls_back_on_corrupt(devices8, tmp_path):
    """The orbax manager's latest-restore also skips a torn step."""
    import shutil

    ff = _model(devices8)
    xs, ys = _data()
    ff.fit(xs, ys, epochs=1, verbose=False)
    w1 = ff.get_weights()
    mgr = CheckpointManager(str(tmp_path / "oc"))
    mgr.save(ff, step=1)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr.save(ff, step=2)

    shutil.rmtree(str(tmp_path / "oc" / "2" / "state"))
    ff.fit(xs, ys, epochs=1, verbose=False)
    step = mgr.restore(ff)
    assert step == 1
    _weights_equal(ff.get_weights(), w1)
    mgr.close()


def test_model_checkpoint_callback(devices8, tmp_path):
    from flexflow_tpu.checkpoint import ModelCheckpoint

    ff = _model(devices8)
    xs, ys = _data()
    cb = ModelCheckpoint(str(tmp_path / "cb"), max_to_keep=2)
    ff.fit(xs, ys, epochs=3, verbose=False, callbacks=[cb])
    mgr = CheckpointManager(str(tmp_path / "cb"))
    assert mgr.latest_step() == 2          # epochs 0,1,2 -> keep last 2
    assert len(mgr.all_steps()) == 2
    mgr.close()
