"""Event-simulator re-ranking of search candidates.

Reference parity: candidates in the reference are ultimately judged by
the event-driven `simulate_runtime` (simulator.cc:822-1250) with ring
allreduce expansion over routed links (:1690-1800), not by analytic
estimates.  Round 1 ranked with the analytic model plus a flat
overlap_fraction credit (VERDICT Weak #3); these tests pin the event
sim into the loop: a contended case where the rankings genuinely differ
and the search follows the event sim, plus the ring-attention KV term
riding the event graph instead of the old flat allgather charge
(Weak #7).
"""
import numpy as np

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.pcg.unity import UnitySearch
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import OpCostModel
from flexflow_tpu.sim.taskgraph import TaskGraphSimulator
from flexflow_tpu.strategy import apply_strategy, assign_views


def _branchy(batch=2048, width=1024, nb=3):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor([batch, width], name="x")
    outs = []
    for i in range(nb):
        outs.append(
            ff.dense(x, width, activation=ActiMode.RELU, name=f"br{i}")
        )
    t = ff.concat(outs, axis=1)
    t = ff.dense(t, 64, name="h")
    ff.softmax(t)
    return ff


def _search(ff, n=8, **kw):
    machine = TpuPodModel(topology=(2, 4))
    return UnitySearch(ff.layers, n, machine, OpCostModel(machine),
                       rewrite_max_variants=1, **kw)


def test_contended_case_event_ranking_differs_and_search_follows():
    """Concurrent branch collectives contend on the ICI ring: the
    analytic model (flat overlap credit) prefers dp=4 x tp=2, the event
    sim shows its collectives serialize and prefers dp=2 x tp=4.  The
    search must follow the event sim."""
    ff = _branchy()
    s = _search(ff, event_rerank=False)
    collector = []
    s._optimize_graph(0.0, collector)
    collector.sort(key=lambda c: c[0])
    assert len(collector) >= 2
    analytic_best = collector[0]
    # event-rank the analytic top candidates
    ranked = []
    for obj, strat, g in collector[:4]:
        e = s._event_objective(strat, g, 0.0)
        if e is not None:
            ranked.append((e, strat))
    assert len(ranked) >= 2
    event_best = min(ranked, key=lambda r: r[0])[1]
    assert event_best.mesh_axes != analytic_best[1].mesh_axes, (
        "expected a contended case where event and analytic rankings "
        f"differ; both chose {event_best.mesh_axes}"
    )

    # search WITHOUT event rerank follows the analytic ranking ...
    s_analytic = _search(ff, event_rerank=False)
    chosen_a = s_analytic.optimize()
    assert chosen_a.mesh_axes == analytic_best[1].mesh_axes
    # ... and WITH it (the default) follows the event sim
    s_event = _search(ff)
    chosen_e = s_event.optimize()
    assert chosen_e.mesh_axes == event_best.mesh_axes


def test_event_objective_handles_pipeline():
    """pp candidates get an event-scale objective (block share of the
    event makespan scaled by the GPipe bubble factor), not their
    optimistic analytic number; unpipelineable graphs fall back to
    None (analytic)."""
    from flexflow_tpu.strategy import Strategy

    def pp_strategy():
        return Strategy(
            mesh_axes={"pipe": 2},
            pipeline={"degree": 2, "num_microbatches": 4,
                      "axis": "pipe", "dp_axis": None},
        )

    # stacked model: valid plan -> finite event objective, cheaper than
    # the unpipelined event run of the same graph
    ff = FFModel(FFConfig(batch_size=16))
    x = ff.create_tensor([16, 64], name="x")
    t = x
    for i in range(4):
        t = ff.dense(t, 64, activation=ActiMode.RELU, name=f"blk{i}")
    t = ff.dense(t, 4, name="head")
    ff.softmax(t)
    s = _search(ff, n=2)
    e_pp = s._event_objective(pp_strategy(), ff.layers, 0.0)
    assert e_pp is not None and np.isfinite(e_pp) and e_pp > 0
    from flexflow_tpu.strategy import Strategy as S2

    plain = S2(mesh_axes={"pipe": 2})
    e_plain = s._event_objective(plain, ff.layers, 0.0)
    assert e_plain is not None and e_pp < e_plain

    # branchy graph: no block stack -> plan fails -> None
    ffb = _branchy(batch=32, width=64, nb=2)
    sb = _search(ffb, n=2)
    assert sb._event_objective(pp_strategy(), ffb.layers, 0.0) is None


def test_ring_attention_kv_rides_event_graph():
    """Seq-sharded attention adds KV-rotation ring phases to the event
    graph (replacing unity's old flat '3x allgather' charge)."""
    from flexflow_tpu.models.transformer import (
        bert_sp_strategy,
        build_bert,
    )

    ff = FFModel(FFConfig(batch_size=8))
    build_bert(ff, batch_size=8, seq_length=32, hidden_size=64,
               num_layers=1, num_heads=4, intermediate_size=128)
    machine = TpuPodModel(topology=(2, 4))
    cm = OpCostModel(machine)
    sim = TaskGraphSimulator(machine, cm)
    sim_no_ring = TaskGraphSimulator(machine, cm, ring_attention=False)

    sp = bert_sp_strategy(8, sp=4)
    g_sp = apply_strategy(ff.layers, sp)
    assign_views(g_sp, sp.mesh_axes)

    tg_with = sim.build(g_sp, sp.mesh_axes)
    tg_without = sim_no_ring.build(g_sp, sp.mesh_axes)
    # the KV rotation adds ring phases (tasks + edges) to the graph ...
    assert len(tg_with.compute_time) > len(tg_without.compute_time)
    assert len(tg_with.edge_src) > len(tg_without.edge_src)
    # ... and real makespan: seq-sharded attention is not free comm
    r_with = sim.simulate(g_sp, sp.mesh_axes)
    r_without = sim_no_ring.simulate(g_sp, sp.mesh_axes)
    assert np.isfinite(r_with.total_time)
    assert r_with.total_time > r_without.total_time
