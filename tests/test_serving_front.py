"""Replicated serving front (serving/front.py + serving/replica.py):
queue handoff on replica death, supervised restarts under the
resilience primitives (FaultPlan / StepWatchdog / RetryPolicy),
bounded per-request requeues, load shedding with Retry-After, and the
/v2/health ok|degraded|down aggregation — all against the
deterministic fake step model (no compiles)."""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from flexflow_tpu.obs.metrics import MetricsRegistry
from flexflow_tpu.resilience.faults import Fault, FaultKind, FaultPlan
from flexflow_tpu.serving import ServiceUnavailable, ServingFront
from flexflow_tpu.serving.server import serve_http

V = 16
NO_SLEEP = lambda s: None  # noqa: E731


class FakeStepModel:
    """Deterministic stand-in for PagedKVDecodeModel: next token is
    (input + 1) % vocab as one-hot logits, so greedy expectations are
    closed-form — which makes requeue-after-death TOKEN-IDENTITY
    directly checkable.  Optional per-step delay simulates a hung
    device dispatch for the watchdog."""

    def __init__(self, batch_slots=2, max_seq=32, page_size=4,
                 delay_s=0.0):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = 1 + batch_slots * self.max_blocks_per_seq
        self.vocab = V
        self.delay_s = delay_s
        self.steps = 0

    def reset(self):
        pass

    def step(self, tokens, seq_lens, block_tables):
        self.steps += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        logits = np.zeros((self.batch_slots, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits


def expected(prompt, mnt):
    out = list(prompt)
    t = prompt[-1]
    for _ in range(mnt):
        t = (t + 1) % V
        out.append(t)
    return out


def factory(replica_id, survivors=None):
    return FakeStepModel()


def kill_on_steps(steps, kind=FaultKind.HUNG_STEP):
    return FaultPlan([Fault(step=s, kind=kind) for s in steps])


def _wait_for(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


# -- basic replicated serving -------------------------------------------

def test_front_serves_across_replicas():
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP)
    try:
        reqs = [([1, 2, 3], 4), ([5], 9), ([7, 8], 2), ([2, 4, 6, 8], 5),
                ([11], 3), ([3], 6)]
        hs = [front.generate_async(p, m) for p, m in reqs]
        for h, (p, m) in zip(hs, reqs):
            assert h.wait(30.0) == expected(p, m)
        assert front.requests_done == len(reqs)
        assert front.health()["status"] == "ok"
        st = front.stats()
        assert st["mode"] == "replicated"
        assert len(st["replicas"]) == 2
        # the dispatcher spread work: both replicas stepped
        assert all(r["batches_run"] > 0 for r in st["replicas"])
    finally:
        front.close()


def test_front_validates_at_admission():
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    try:
        with pytest.raises(ValueError, match="prompt length"):
            front.generate_async([], 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            front.generate_async([1], 0)
    finally:
        front.close()


# -- replica death: requeue + token identity ----------------------------

def test_replica_death_requeues_inflight_token_identical():
    """ISSUE 8: injected replica death mid-stream — in-flight requests
    are requeued and complete TOKEN-IDENTICALLY (greedy) on a
    surviving replica; queued requests are untouched; the dead replica
    restarts under supervision."""
    reg = MetricsRegistry()
    front = ServingFront(
        factory, num_replicas=2, registry=reg, sleep=NO_SLEEP,
        retry_backoff=0.0,
        fault_plans={0: kill_on_steps([2])},
    )
    try:
        # more requests than both replicas' slots: some queue at front
        reqs = [([1 + i, 2], 8) for i in range(6)]
        hs = [front.generate_async(p, m) for p, m in reqs]
        for h, (p, m) in zip(hs, reqs):
            assert h.wait(30.0) == expected(p, m)  # fault-free tokens
        assert front.requeued_requests >= 1
        assert reg.counter("serving/replica_deaths").value == 1
        assert front.replicas[0].deaths == 1
        assert _wait_for(lambda: front.replicas[0].state == "live")
        assert reg.counter("serving/replica_restarts").value == 1
        assert front.health()["status"] == "ok"
        # the front never returned a non-retriable error for an
        # admitted request
        assert front.requests_done == len(reqs)
    finally:
        front.close()


def test_device_loss_rebuilds_on_survivors():
    """A DeviceLossFault carries the surviving device count into the
    replica's rebuild factory (the degraded-mesh path)."""
    seen = []

    def recording_factory(replica_id, survivors=None):
        seen.append((replica_id, survivors))
        return FakeStepModel()

    plan = FaultPlan.single(1, FaultKind.DEVICE_LOSS, survivors=4)
    front = ServingFront(recording_factory, num_replicas=1,
                         sleep=NO_SLEEP, retry_backoff=0.0,
                         fault_plans={0: plan})
    try:
        assert front.generate([1, 2], 5, timeout=30.0) == \
            expected([1, 2], 5)
        assert _wait_for(lambda: front.replicas[0].restarts == 1)
        assert seen[0] == (0, None)
        assert seen[1] == (0, 4)  # rebuilt on the surviving count
    finally:
        front.close()


def test_hung_decode_step_routes_through_watchdog():
    """A REAL hang (step blocks past serving_step_timeout) raises
    HungStepTimeout via the StepWatchdog, kills the engine, and the
    supervisor restarts it — requests complete on the restarted
    replica instead of waiting forever."""
    built = []

    def hang_once_factory(replica_id, survivors=None):
        m = FakeStepModel(delay_s=5.0 if not built else 0.0)
        built.append(m)
        return m

    front = ServingFront(hang_once_factory, num_replicas=1,
                         step_timeout=0.3, sleep=NO_SLEEP,
                         retry_backoff=0.0)
    try:
        h = front.generate_async([1, 2], 4)
        assert h.wait(30.0) == expected([1, 2], 4)
        assert front.replicas[0].deaths == 1
        assert front.replicas[0].restarts == 1
        from flexflow_tpu.resilience.watchdog import HungStepTimeout

        assert isinstance(front.replicas[0].last_error, HungStepTimeout)
        assert front.requeued_requests == 1
    finally:
        front.close()


# -- shedding and budgets -----------------------------------------------

def test_all_replicas_down_sheds_with_retry_after():
    reg = MetricsRegistry()
    front = ServingFront(
        factory, num_replicas=2, registry=reg, sleep=NO_SLEEP,
        retry_backoff=0.0, max_restarts=0, request_retry_limit=5,
        fault_plans={0: kill_on_steps(range(50)),
                     1: kill_on_steps(range(50))},
    )
    try:
        h = front.generate_async([1, 2], 4)  # drives both to death
        with pytest.raises(ServiceUnavailable):
            h.wait(30.0)
        assert _wait_for(
            lambda: front.health()["status"] == "down")
        assert all(r["state"] == "dead"
                   for r in front.health()["replicas"])
        with pytest.raises(ServiceUnavailable) as ei:
            front.generate_async([1], 2)
        assert ei.value.retry_after_s > 0
        assert front.shed_requests == 1
        assert reg.counter("serving/shed_requests").value == 1
    finally:
        front.close()


def test_restart_budget_exhaustion_marks_replica_dead():
    """One poisoned replica exhausts its budget and goes PERMANENTLY
    dead; the front keeps serving on the survivor and reports
    degraded."""
    front = ServingFront(
        factory, num_replicas=2, sleep=NO_SLEEP, retry_backoff=0.0,
        max_restarts=1, request_retry_limit=5,
        fault_plans={0: kill_on_steps(range(100))},
    )
    try:
        for i in range(6):
            assert front.generate([1 + i], 4, timeout=30.0) == \
                expected([1 + i], 4)
        assert _wait_for(lambda: front.replicas[0].state == "dead")
        health = front.health()
        assert health["status"] == "degraded"
        assert health["replicas"][0]["state"] == "dead"
        # still serving on the survivor
        assert front.generate([9], 3, timeout=30.0) == expected([9], 3)
    finally:
        front.close()


def test_request_retry_limit_exhaustion_is_retriable():
    """A request that keeps landing on dying replicas fails with a
    RETRIABLE ServiceUnavailable after request_retry_limit requeues —
    never a client error."""
    front = ServingFront(
        factory, num_replicas=1, sleep=NO_SLEEP, retry_backoff=0.0,
        max_restarts=100, request_retry_limit=2,
        fault_plans={0: kill_on_steps(range(200))},
    )
    try:
        h = front.generate_async([1, 2], 6)
        with pytest.raises(ServiceUnavailable, match="3 times"):
            h.wait(30.0)
        assert h.retries == 3  # initial + 2 requeues, all consumed
        assert front.requeued_requests == 2
    finally:
        front.close()


# -- shutdown -----------------------------------------------------------

def test_front_close_bounded_with_wedged_replica():
    """A replica wedged inside a decode step (no watchdog armed)
    cannot hang front shutdown: every close is bounded."""

    def wedged_factory(replica_id, survivors=None):
        return FakeStepModel(delay_s=30.0)

    front = ServingFront(wedged_factory, num_replicas=2,
                         sleep=NO_SLEEP, close_timeout_s=0.5)
    h = front.generate_async([1, 2], 4)
    time.sleep(0.2)  # let a step wedge
    t0 = time.monotonic()
    front.close()
    assert time.monotonic() - t0 < 10.0
    with pytest.raises(RuntimeError):
        h.wait(1.0)
    with pytest.raises(RuntimeError, match="closed"):
        front.generate_async([1], 1)


# -- metrics ------------------------------------------------------------

def test_front_metrics_and_summary(tmp_path):
    reg = MetricsRegistry()
    front = ServingFront(
        factory, num_replicas=2, registry=reg, sleep=NO_SLEEP,
        retry_backoff=0.0, fault_plans={0: kill_on_steps([2])},
    )
    try:
        hs = [front.generate_async([1 + i], 6) for i in range(4)]
        for h in hs:
            h.wait(30.0)
        front.stats()  # refreshes the replicas_live gauge
    finally:
        front.close()
    names = {m for m in reg._metrics}
    assert "serving/replica_deaths" in names
    assert "serving/replica_restarts" in names
    assert "serving/requeued_requests" in names
    assert "serving/replica/0/queue_depth" in names
    assert "serving/replica/1/queue_depth" in names
    assert "serving/replicas_live" in names
    path = tmp_path / "run_telemetry.jsonl"
    assert reg.write_jsonl(str(path)) > 0
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    import importlib

    summary = importlib.import_module("tools.telemetry_summary")
    text = summary.summarize(recs)
    assert "replica_deaths" in text and "requeued_requests" in text


# -- HTTP surface -------------------------------------------------------

def _post(port, payload, path="/v2/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_http_front_health_stats_and_shed():
    front = ServingFront(
        factory, num_replicas=2, sleep=NO_SLEEP, retry_backoff=0.0,
        max_restarts=0, request_retry_limit=3,
        fault_plans={0: kill_on_steps(range(50)),
                     1: kill_on_steps(range(50))},
    )
    server = serve_http(generator=front, port=0, block=False)
    port = server.server_address[1]
    try:
        health = _get(port, "/v2/health")
        assert health["status"] == "ok"
        assert [r["state"] for r in health["replicas"]] == ["live"] * 2
        # the first request drives both replicas to permanent death
        # (every step is a kill; max_restarts=0): its retries exhaust
        # into a 503 retriable with a Retry-After
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"prompt": [1, 2], "max_new_tokens": 3})
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
        assert json.loads(ei.value.read())["retriable"]
        assert _wait_for(lambda: front.health()["status"] == "down")
        # down rides a 503 for status-code-only probes
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/v2/health")
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["status"] == "down"
        # shed new requests: 503 + Retry-After
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"prompt": [1], "max_new_tokens": 2})
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
        assert json.loads(ei.value.read())["retriable"]
        # stats carries the per-replica block
        stats = _get(port, "/v2/stats")
        reps = stats["continuous"]["replicas"]
        assert [r["state"] for r in reps] == ["dead", "dead"]
    finally:
        server.shutdown()
        front.close()


def test_http_front_serves_and_degrades():
    front = ServingFront(
        factory, num_replicas=2, sleep=NO_SLEEP, retry_backoff=0.0,
        max_restarts=0, request_retry_limit=3,
        fault_plans={0: kill_on_steps(range(50))},  # replica 0 dies
    )
    server = serve_http(generator=front, port=0, block=False)
    port = server.server_address[1]
    try:
        status, out = _post(port, {"prompts": [[1, 2], [5]],
                                   "max_new_tokens": 4})
        assert status == 200
        assert out["tokens"] == [expected([1, 2], 4), expected([5], 4)]
        assert _wait_for(lambda: front.replicas[0].state == "dead")
        # degraded still SERVES, so it rides a 200 (unlike the
        # single-engine degraded, which cannot serve at all)
        health = _get(port, "/v2/health")
        assert health["status"] == "degraded"
        status, out = _post(port, {"prompt": [3], "max_new_tokens": 2})
        assert status == 200 and out["tokens"] == [expected([3], 2)]
    finally:
        server.shutdown()
        front.close()


# -- tensor-parallel replicas: chip budget + cache-affine dispatch ------

def _tp_factory(tp):
    """FakeStepModel dressed with the tensor-parallel surface a
    PagedKVDecodeModel exposes (tp / mesh_shape / per-chip KV bytes)."""
    def f(replica_id, survivors=None):
        m = FakeStepModel()
        m.tp = tp
        m.mesh_shape = {"data": 1, "model": tp}
        m.kv_block_bytes = 1024
        m.kv_block_bytes_per_chip = 1024 // tp
        return m
    return f


def test_front_chip_budget_refuses_add_replica():
    """Fleet chips = replicas x tp; an add_replica that would exceed
    --serving-chip-budget is refused BEFORE any compile and counted."""
    reg = MetricsRegistry()
    front = ServingFront(_tp_factory(2), num_replicas=1, chip_budget=4,
                         registry=reg, sleep=NO_SLEEP)
    try:
        assert front.chips_per_replica == 2
        front.add_replica()  # 4 chips: fits exactly
        with pytest.raises(RuntimeError, match="chip budget exhausted"):
            front.add_replica()
        assert reg.counter("serving/chip_budget_refused").value == 1
        st = front.stats()
        assert st["chips_per_replica"] == 2
        assert st["chip_budget"] == 4
        assert st["fleet_chips"] == 4
        # the per-replica tp block rides /v2/stats
        tp = st["replicas"][0]["tp"]
        assert tp["degree"] == 2
        assert tp["mesh_shape"] == {"data": 1, "model": 2}
        assert tp["kv_block_bytes_per_chip"] * 2 == tp["kv_block_bytes"]
    finally:
        front.close()


def test_front_chip_budget_validates_initial_fleet():
    with pytest.raises(ValueError, match="chip budget"):
        ServingFront(_tp_factory(4), num_replicas=2, chip_budget=4,
                     sleep=NO_SLEEP)


def test_front_without_budget_keeps_prior_behavior():
    front = ServingFront(_tp_factory(2), num_replicas=1, sleep=NO_SLEEP)
    try:
        for _ in range(3):
            front.add_replica()  # unbounded: no refusal
        assert len(front.replicas) == 4
        assert front.stats()["chip_budget"] == 0
    finally:
        front.close()


def test_dispatch_is_cache_affine():
    """The dispatcher routes a request to the replica whose prefix
    cache holds the longest prefix of its prompt — not least-loaded —
    and falls back to least-loaded for cold prompts."""
    reg = MetricsRegistry()
    front = ServingFront(factory, num_replicas=2, registry=reg,
                         sleep=NO_SLEEP)
    try:
        r0, r1 = front.replicas
        # pretend replica 1 (NOT first in rotation) holds the blocks
        r1.scheduler.cached_prefix_tokens = (
            lambda p: 4 if list(p)[:4] == [1, 2, 3, 4] else 0)
        r0.scheduler.cached_prefix_tokens = lambda p: 0
        h = front.generate_async([1, 2, 3, 4, 5], 3)
        assert h.wait(30.0) == expected([1, 2, 3, 4, 5], 3)
        assert r1.stats()["batches_run"] > 0
        assert r0.stats()["batches_run"] == 0
        assert reg.counter("serving/cache_affine_routed").value == 1
        # a cold prompt falls back to least-loaded (replica 0 first)
        h = front.generate_async([9, 9], 2)
        assert h.wait(30.0) == expected([9, 9], 2)
        assert r0.stats()["batches_run"] > 0
    finally:
        front.close()


def test_cache_affinity_follows_real_prefix_cache():
    """End to end on the real block pool: the first shared-prefix
    request warms ONE replica's prefix cache; every later request with
    the same prefix routes to that same replica (its prefill becomes a
    block-table metadata hit), leaving the other replica cold."""
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP)
    try:
        prefix = [1, 2, 3, 4]  # one full page (page_size=4)
        assert front.generate(prefix + [5], 3) == \
            expected(prefix + [5], 3)
        warm = [r for r in front.replicas
                if r.stats()["batches_run"] > 0]
        assert len(warm) == 1
        for tail in ([6], [7, 8], [5]):
            assert front.generate(prefix + tail, 3) == \
                expected(prefix + tail, 3)
        cold = [r for r in front.replicas if r is not warm[0]]
        assert cold[0].stats()["batches_run"] == 0
        assert warm[0].scheduler.cached_prefix_tokens(prefix + [5]) >= 4
    finally:
        front.close()
