"""Test configuration: hermetic 8-device CPU mesh.

The reference cannot test distributed execution without real GPUs
(SURVEY §4); we exploit jax's virtual CPU devices so every parallelism
strategy test runs hermetically.

IMPORTANT: tests must never initialize the `axon` TPU backend — the
tunneled chip is single-tenant, and a second process touching it hangs
until the first exits.  The axon sitecustomize hook registers the
backend before conftest runs, so setting the env var alone is not
enough; we also force jax_platforms=cpu through jax.config, which keeps
`backends()` from ever creating the TPU client.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices("cpu")
    assert len(devs) >= 8
    return devs[:8]
