"""Searched rematerialization (ISSUE 13): per-segment activation
checkpointing as a simulator-costed strategy dimension.

Pins the PR's contracts:

  * delta_eval == full_eval bit-for-bit across remat flips at
    COST_MODEL_VERSION 4 (the remat plan, like the ZeRO stage, changes
    only how cached OpTerms aggregate — never the applied graph);
  * the executor lowers a per-segment plan (only the named segments
    wrap in jax.checkpoint) with loss bit-identity vs the dense
    (no-remat) oracle, including the ZeRO-3 interaction;
  * both searches choose a NON-TRIVIAL plan under memory pressure
    whose simulated cost beats all-on and all-off;
  * remat-free strategies keep byte-identical serialization and
    flat configs keep bucket-free store keys (the single-slice key
    guarantee's pattern);
  * DCN grad-sync bucketing: latency-sublinear in leaf count, total
    bytes unchanged.
"""
import dataclasses

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.optimizer import AdamOptimizer, SGDOptimizer
from flexflow_tpu.pcg.evaluator import (
    IncrementalEvaluator,
    strategy_signature,
)
from flexflow_tpu.pcg.mcmc import MCMCSearch, remat_stats
from flexflow_tpu.pcg.unity import UnitySearch
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import (
    COST_MODEL_VERSION,
    OpCostModel,
    Simulator,
    remat_segments,
)
from flexflow_tpu.strategy import Strategy, data_parallel_strategy


def _residual_mlp(batch=32, width=256, layers=6, **cfg_kw):
    """Residual MLP: each block is a multi-op single-tensor segment
    (the residual edge forbids interior cuts) — the graph shape where
    per-segment remat actually trades internals for recompute."""
    cfg_kw.setdefault("num_devices", 1)
    ff = FFModel(FFConfig(batch_size=batch, **cfg_kw))
    x = ff.create_tensor([batch, width], name="input")
    t = x
    for i in range(layers):
        h = ff.dense(t, width * 2, name=f"up{i}")
        h = ff.relu(h, name=f"act{i}")
        h = ff.dense(h, width, name=f"down{i}")
        t = ff.add(t, h, name=f"res{i}")
    t = ff.dense(t, 8, name="head")
    ff.softmax(t)
    return ff


def _pure_segment_count(ev, strategy):
    res = ev.evaluate(strategy)
    return sum(1 for _, pure in remat_segments(res.ops) if pure)


# -- simulator economics --------------------------------------------------

def test_remat_plan_trades_memory_for_recompute():
    """All-off is bit-identical to the dense accounting; all-on drops
    activation residuals and pays recompute seconds; a single ON
    segment trades its residual for an equal-size recompute window
    (no net memory win until >= 2 segments are on — Checkmate
    semantics, arXiv:1910.02653)."""
    assert COST_MODEL_VERSION >= 4
    g = _residual_mlp().layers
    ev = IncrementalEvaluator(g, Simulator(TpuPodModel(topology=(8,))))
    dp = data_parallel_strategy(8)
    dense = ev.evaluate(dp)
    n = _pure_segment_count(ev, dp)
    assert n >= 6
    r_off = ev.evaluate(dataclasses.replace(dp, remat=[]))
    assert r_off.total_time == dense.total_time
    assert r_off.per_device_memory == dense.per_device_memory
    r_on = ev.evaluate(dataclasses.replace(dp, remat=list(range(n + 1))))
    assert r_on.total_time > dense.total_time
    assert r_on.per_device_memory < dense.per_device_memory
    assert r_on.recompute_s > 0
    assert dense.recompute_s == 0
    r_one = ev.evaluate(dataclasses.replace(dp, remat=[3]))
    assert dense.total_time < r_one.total_time < r_on.total_time
    assert r_one.per_device_memory == dense.per_device_memory
    r_two = ev.evaluate(dataclasses.replace(dp, remat=[3, 4]))
    assert r_on.per_device_memory < r_two.per_device_memory \
        < dense.per_device_memory
    # activation telemetry: the plan's saved bytes shrink with coverage
    assert r_on.activation_bytes < r_two.activation_bytes \
        < dense.activation_bytes


def test_inference_costing_unaffected_by_remat_dimension():
    """training=False simulation (inference liveness costing) must not
    consult the remat machinery — regression for the v4 aggregation."""
    from flexflow_tpu.strategy import apply_strategy, assign_views

    g = _residual_mlp().layers
    sim = Simulator(TpuPodModel(topology=(8,)))
    dp = data_parallel_strategy(8)
    applied = apply_strategy(g, dp)
    assign_views(applied, dp.mesh_axes)
    res = sim.simulate(applied, dp.mesh_axes, training=False)
    assert res.total_time > 0
    assert res.recompute_s == 0
    ev = IncrementalEvaluator(g, sim, training=False)
    assert ev.evaluate(dp) is not None


def test_delta_eval_matches_full_eval_across_remat_flips():
    """The exactness invariant extends to the remat dimension: a remat
    flip is a zero-frontier delta (the applied graph is plan-invariant)
    and must agree with the always-full reference path bit-for-bit."""
    g = _residual_mlp().layers
    machine = TpuPodModel(topology=(8,))
    ev_delta = IncrementalEvaluator(g, Simulator(machine), use_cache=True)
    ev_full = IncrementalEvaluator(g, Simulator(machine), use_cache=False)
    dp = data_parallel_strategy(8)
    plans = [None, [], [2], [1, 4], list(range(8)), [2], None, [0, 2, 6]]
    stages = [None, 3, None, 2, None, 3, None, None]
    delta_seen = 0
    for plan, stage in zip(plans, stages):
        s = dataclasses.replace(
            dp,
            remat=list(plan) if plan is not None else None,
            zero_stage=stage,
        )
        rd = ev_delta.evaluate(s)
        rf = ev_full.evaluate(dataclasses.replace(
            s, remat=list(plan) if plan is not None else None))
        assert rd.total_time == rf.total_time
        assert rd.per_device_memory == rf.per_device_memory
        assert rd.recompute_s == rf.recompute_s
        delta_seen = ev_delta.stats.delta_evals
    assert delta_seen > 0  # remat flips actually rode the delta path


def test_signature_and_serialization_separate_plans():
    dp = data_parallel_strategy(8)
    sigs = {
        strategy_signature(dataclasses.replace(dp, remat=p))
        for p in (None, [], [1], [1, 2])
    }
    assert len(sigs) == 4
    s = dataclasses.replace(dp, remat=[1, 3])
    s2 = Strategy.from_json(s.to_json())
    assert s2.remat == [1, 3]
    assert strategy_signature(s) == strategy_signature(s2)
    assert remat_stats(s) == {"remat": "1,3", "remat_segments_on": 2}


# -- store-key / serialization stability for remat-free strategies --------

def test_remat_free_strategies_keep_stable_keys():
    """No plan -> no 'remat' key in the JSON body (store-entry digests
    of remat-free strategies are unchanged), and flat configs carry no
    dcn_bucket field in the simulator key while multi-slice configs do
    (the single-slice key guarantee's pattern)."""
    import json

    from flexflow_tpu.store.key import simulator_version

    body = json.loads(data_parallel_strategy(8).to_json())
    assert "remat" not in body
    planned = json.loads(
        dataclasses.replace(data_parallel_strategy(8), remat=[2]).to_json()
    )
    assert planned["remat"] == [2]

    flat = simulator_version(FFConfig())
    assert flat["cost_model_version"] == COST_MODEL_VERSION >= 4
    assert "dcn_bucket_mb" not in flat["search"]
    sliced = simulator_version(FFConfig(slices=2, num_devices=8))
    assert sliced["search"]["dcn_bucket_mb"] == 25.0
    # the bucket knob splits multi-slice keys only
    sliced_b = simulator_version(
        FFConfig(slices=2, num_devices=8, dcn_bucket_mb=50.0)
    )
    assert sliced != sliced_b
    assert simulator_version(FFConfig(dcn_bucket_mb=50.0)) == flat


# -- the searches choose the plan -----------------------------------------

def _pressure_setup():
    """dp-8 residual MLP whose activations dominate memory, with a
    budget strictly between the all-on and all-off footprints — the
    deterministic face of the remat decision."""
    g = _residual_mlp(batch=4096, width=512).layers
    machine = TpuPodModel(topology=(8,))
    ev = IncrementalEvaluator(g, Simulator(machine))
    dp = data_parallel_strategy(8)
    dense = ev.evaluate(dp)
    n = _pure_segment_count(ev, dp)
    r_on = ev.evaluate(dataclasses.replace(dp, remat=list(range(n))))
    assert r_on.per_device_memory < dense.per_device_memory
    budget = r_on.per_device_memory + (
        dense.per_device_memory - r_on.per_device_memory
    ) // 4
    return g, machine, ev, dp, dense, r_on, n, budget


def test_unity_chooses_nontrivial_plan_under_memory_pressure(monkeypatch):
    """Unity's remat variants land on a partial plan: fits the budget
    (beats all-off, which does not) at less simulated time than all-on."""
    import flexflow_tpu.pcg.unity as unity_mod

    g, machine, ev, dp, dense, r_on, n, budget = _pressure_setup()
    monkeypatch.setattr(
        unity_mod, "_factorizations",
        lambda nn, allow_expert=True: [(nn, 1, 1)],
    )
    search = UnitySearch(g, 8, machine, OpCostModel(machine),
                         memory_budget=budget, enable_pipeline=False,
                         remat_search=True)
    best = search.optimize_with_memory()
    assert best is not None and best.remat
    assert 0 < len(best.remat) < n  # some on, some off
    res = ev.evaluate(best)
    assert res.per_device_memory <= budget < dense.per_device_memory
    assert res.total_time < r_on.total_time
    assert best.search_stats["remat_segments_on"] == len(best.remat)
    assert best.search_stats["remat"] == ",".join(map(str, best.remat))


def test_mcmc_flip_segment_move_lands_plan_under_memory_pressure():
    g, machine, ev, dp, dense, r_on, n, budget = _pressure_setup()
    search = MCMCSearch(g, 8, lambda: Simulator(machine), budget=150,
                        seed=0, memory_budget=budget, memory_lambda=3.0,
                        remat_search=True)
    search.factorizations = [(8, 1, 1)]
    best = search.optimize()
    assert best.remat
    res = search.evaluator.evaluate(best)
    assert res.per_device_memory <= budget
    assert res.total_time < r_on.total_time
    assert best.search_stats["remat"] == ",".join(map(str, best.remat))


def test_remat_dimension_gated_on_memory_search():
    from flexflow_tpu.pcg.mcmc import search_remat_enabled

    assert search_remat_enabled(FFConfig(memory_search=True))
    assert not search_remat_enabled(FFConfig())
    # a global --remat floor does NOT close the dimension: the search
    # may still find a cheaper partial plan
    assert search_remat_enabled(FFConfig(memory_search=True, remat=True))


# -- ZeRO-3 interaction ----------------------------------------------------

def test_stage3_regather_rides_recompute_only_when_on():
    """At ZeRO-3 a remat'd segment's backward re-gather runs inside the
    checkpointed region (no prefetch), so an ON plan at stage 3 pays
    more recompute than at stage 0 — while OFF plans price gather_xfer
    exactly as before (time-identical across plans=None/[])."""
    g = _residual_mlp(batch=4096, width=512).layers
    ev = IncrementalEvaluator(g, Simulator(TpuPodModel(topology=(8,))))
    dp = data_parallel_strategy(8)
    n = _pure_segment_count(ev, dp)
    plan = list(range(n))

    def res(stage, remat):
        return ev.evaluate(dataclasses.replace(
            dp, zero_stage=stage, remat=remat))

    extra_s0 = res(0, plan).total_time - res(0, []).total_time
    extra_s3 = res(3, plan).total_time - res(3, []).total_time
    assert extra_s3 > extra_s0  # the lost prefetch credit is priced
    assert res(3, []).total_time == res(3, None).total_time


# -- executor lowering -----------------------------------------------------

def _exec_model(batch=16, width=32, layers=3, **cfg_kw):
    return _residual_mlp(batch=batch, width=width, layers=layers, **cfg_kw)


def _fit(ff, strategy, devices, seed=0, steps=4, optimizer=None):
    """Compile under `strategy` and run `steps` real train steps,
    returning the PER-STEP loss values read off the device (the
    PerfMetrics loss fields are not populated without the loss metric
    configured, so reading them would make the comparison vacuous)."""
    ff.compile(
        optimizer=optimizer or SGDOptimizer(lr=0.05),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        strategy=strategy, devices=devices, seed=seed,
    )
    rng = np.random.RandomState(0)
    width = ff.layers.source_ops()[0].outputs[0].shape.logical_shape[1]
    xs = rng.randn(64, width).astype(np.float32)
    ys = rng.randint(0, 8, 64).astype(np.int32)
    losses = []
    for _ in range(steps):
        m = ff.train_step({"input": xs}, ys)
        losses.append(float(np.asarray(m["loss"])))
    assert losses[-1] < losses[0]  # actually training, not zeros
    return losses


def test_partial_plan_lowers_and_matches_dense_numerics(devices8):
    """A strategy-carried partial plan wraps ONLY the named segments in
    jax.checkpoint; the loss trajectory is bit-compatible with the
    dense oracle (remat never changes math)."""
    dp = data_parallel_strategy(8)
    ff_dense = _exec_model(num_devices=8)
    losses_dense = _fit(ff_dense, dp, devices8)
    assert ff_dense.executor._remat_plan is None

    plan = [2, 3]
    ff_plan = _exec_model(num_devices=8)
    losses_plan = _fit(
        ff_plan, dataclasses.replace(dp, remat=plan), devices8
    )
    ex = ff_plan.executor
    assert ex._remat_plan is not None
    wrapped = [i for i, (_, _, _, pure) in enumerate(ex._remat_plan)
               if pure]
    assert wrapped == plan  # only the named segments checkpoint
    np.testing.assert_allclose(losses_plan, losses_dense, rtol=1e-6)

    # a plan naming every segment == the legacy --remat lowering
    ff_all = _exec_model(num_devices=8, remat=True)
    losses_all = _fit(
        ff_all, dataclasses.replace(
            dp, remat=list(range(32))), devices8,
    )
    legacy = _exec_model(num_devices=8, remat=True)
    losses_legacy = _fit(legacy, data_parallel_strategy(8), devices8)
    pure_plan = [p for *_, p in ff_all.executor._remat_plan]
    pure_legacy = [p for *_, p in legacy.executor._remat_plan]
    assert pure_plan == pure_legacy  # identical segment wrapping
    np.testing.assert_allclose(losses_all, losses_legacy, rtol=1e-6)


def test_zero3_with_partial_remat_matches_stage0_dense(devices8):
    """ZeRO-3 + per-segment remat: gathers re-emitted inside the
    checkpointed segments still produce stage-0 dense numerics."""
    dp = data_parallel_strategy(8)
    base = _fit(_exec_model(num_devices=8, zero_stage=0), dp, devices8,
                optimizer=AdamOptimizer(alpha=0.01))
    z3 = _fit(
        _exec_model(num_devices=8, zero_stage=3),
        dataclasses.replace(dp, remat=[1, 3]), devices8,
        optimizer=AdamOptimizer(alpha=0.01),
    )
    np.testing.assert_allclose(z3, base, rtol=2e-5)


# -- DCN grad-sync bucketing ----------------------------------------------

def test_dcn_bucketing_latency_sublinear_bytes_unchanged():
    """Many small grad leaves stop over-paying the per-leaf DCN latency
    term: with bucketing the summed DCN time of N small leaves is
    latency-sublinear in N (well under N x the unbucketed per-leaf
    cost), while per-device ring bytes are unchanged.  A leaf at or
    above the bucket size pays the full latency exactly as before."""
    from flexflow_tpu.topology.hierarchy import SliceHierarchy

    m = SliceHierarchy(topology=(4,), slices=2, dcn_bw_per_host=4e9,
                       dcn_latency=10e-6)
    bucket = 25 * 2**20
    sim_b = Simulator(m, dcn_bucket_bytes=bucket)
    sim_0 = Simulator(m, dcn_bucket_bytes=0)
    leaf = 16 * 1024  # 16KB leaves, latency-dominated on DCN
    n_leaves = 64
    cc_b = [sim_b._collective("allreduce", leaf, 8, cross=True,
                              grad_bucket=True) for _ in range(n_leaves)]
    cc_0 = [sim_0._collective("allreduce", leaf, 8, cross=True,
                              grad_bucket=True) for _ in range(n_leaves)]
    t_b = sum(c.dcn_time for c in cc_b)
    t_0 = sum(c.dcn_time for c in cc_0)
    assert sum(c.dcn_bytes for c in cc_b) == sum(c.dcn_bytes for c in cc_0)
    assert sum(c.ici_time for c in cc_b) == sum(c.ici_time for c in cc_0)
    assert t_b < t_0 / 8  # latency-sublinear in leaf count
    # the bandwidth term is a floor the bucketing never crosses
    bw_only = sum(
        c.dcn_bytes / m.dcn_bw for c in cc_0
    )
    assert t_b >= bw_only
    # a bucket-sized leaf pays the full unbucketed cost
    big = sim_b._collective("allreduce", bucket * 8, 8, cross=True,
                            grad_bucket=True)
    big0 = sim_0._collective("allreduce", bucket * 8, 8, cross=True,
                             grad_bucket=True)
    assert big.dcn_time == big0.dcn_time
    # activation/resharding collectives are never bucketed
    x = sim_b._collective("allreduce", leaf, 8, cross=True)
    x0 = sim_0._collective("allreduce", leaf, 8, cross=True)
    assert x.dcn_time == x0.dcn_time


def test_dcn_bucket_config_knob():
    with pytest.raises(ValueError):
        FFConfig(dcn_bucket_mb=0)
    cfg = FFConfig.from_args(["--dcn-bucket-mb", "50"])
    assert cfg.dcn_bucket_mb == 50.0
