"""Cross-replica weight-update sharding (ZeRO-1, arXiv:2004.13336).

Pins the tentpole contract on the hermetic 8-device CPU mesh:

  * --weight-update-sharding trains to the SAME weights as the
    replicated update (SGD momentum and Adam, k steps, tight tolerance);
  * optimizer slots are NamedSharding-sharded along the wus axis —
    1/dp per-device bytes, asserted via the sharding specs;
  * checkpoint save -> restore round-trips, including an 8 -> 4 elastic
    reshard onto a fresh mesh;
  * the simulator scores the sharded update (numel/N update cost +
    reduce-scatter/all-gather terms) so predicted step time and memory
    change consistently when the knob flips, and the choice rides
    strategy.search_stats.
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.optimizer import AdamOptimizer, SGDOptimizer


def _model(devices, wus=False, opt=None, seed=0, num_devices=None,
           stage=None):
    cfg = FFConfig(
        batch_size=16,
        num_devices=num_devices or len(devices),
        weight_update_sharding=wus,
        zero_stage=stage if stage is not None else 0,
        seed=seed,
    )
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="x")
    t = ff.dense(x, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 8)
    ff.softmax(t)
    ff.compile(
        optimizer=opt,
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
        devices=devices,
        seed=seed,
    )
    return ff


def _data(n=96, seed=0):
    rng = np.random.RandomState(seed)
    return (
        rng.randn(n, 32).astype(np.float32),
        rng.randint(0, 8, n).astype(np.int32),
    )


def _assert_trees_close(a, b, rtol=2e-5, atol=2e-6):
    import jax

    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol
        )


def _slot_shard_bytes(opt_state):
    """(per-device, total) bytes over the weight-mirroring slot trees."""
    import jax

    shard = total = 0
    for key, sub in opt_state.items():
        if not isinstance(sub, dict):
            continue
        for leaf in jax.tree.leaves(sub):
            sh = leaf.sharding
            shard += int(
                np.prod(sh.shard_shape(leaf.shape)) * leaf.dtype.itemsize
            )
            total += int(np.prod(leaf.shape) * leaf.dtype.itemsize)
    return shard, total


@pytest.mark.parametrize(
    "make_opt",
    [
        lambda: SGDOptimizer(lr=0.05, momentum=0.9),
        lambda: SGDOptimizer(lr=0.05, momentum=0.9, nesterov=True),
        lambda: AdamOptimizer(alpha=0.01),
    ],
    ids=["sgd_momentum", "sgd_nesterov", "adam"],
)
def test_sharded_update_matches_replicated(devices8, make_opt):
    """Same data, same seeds: k steps under --weight-update-sharding
    must land on the replicated path's weights and slots."""
    xs, ys = _data()
    ff_rep = _model(devices8, wus=False, opt=make_opt())
    ff_wus = _model(devices8, wus=True, opt=make_opt())
    ff_rep.fit(xs, ys, epochs=2, verbose=False)
    ff_wus.fit(xs, ys, epochs=2, verbose=False)
    _assert_trees_close(ff_rep.get_weights(), ff_wus.get_weights())
    import jax

    _assert_trees_close(
        jax.tree.map(np.asarray, ff_rep._opt_state),
        jax.tree.map(np.asarray, ff_wus._opt_state),
    )


def test_opt_state_sharded_one_over_dp(devices8):
    """Adam m/v land on NamedShardings carrying the wus axis: every
    evenly-divisible slot holds 1/8 of its bytes per device, and the
    aggregate per-device footprint shrinks by ~1/dp."""
    from jax.sharding import NamedSharding

    ff = _model(devices8, wus=True, opt=AdamOptimizer(alpha=0.01))
    dp = 8
    for op_name, entry in ff._opt_state["m"].items():
        for wname, leaf in entry.items():
            sh = leaf.sharding
            assert isinstance(sh, NamedSharding)
            if any(d % dp == 0 for d in leaf.shape):
                # every slot with an evenly-divisible dim is sharded
                assert "data" in [
                    a
                    for e in sh.spec
                    if e is not None
                    for a in (e if isinstance(e, tuple) else (e,))
                ], (op_name, wname, sh.spec)
    shard, total = _slot_shard_bytes(ff._opt_state)
    # the three kernels dominate; biases may stay replicated
    assert shard <= total // dp + total // 20, (shard, total)

    ff_rep = _model(devices8, wus=False, opt=AdamOptimizer(alpha=0.01))
    shard_rep, total_rep = _slot_shard_bytes(ff_rep._opt_state)
    assert total_rep == total
    assert shard_rep == total_rep  # replicated: full copy per device
    assert shard * 4 < shard_rep  # >= 4x shrink on the 8-way mesh


def test_scalar_slot_replicated_without_wus(devices8):
    """Adam's scalar t is mesh-replicated even with weight-update
    sharding OFF: an eagerly created scalar is committed to one device,
    and a checkpoint restore that commits to the live sharding (the
    remote-mirror materialize path does) would wedge the multi-device
    step with mixed device sets."""
    from jax.sharding import NamedSharding

    ff = _model(devices8, wus=False, opt=AdamOptimizer(alpha=0.01))
    t = ff._opt_state["t"]
    assert isinstance(t.sharding, NamedSharding)
    assert len(t.sharding.device_set) == len(devices8)
    # a committed round-trip through the live shardings must still step
    import jax

    ff._opt_state = jax.tree.map(
        lambda x: jax.device_put(np.asarray(x), x.sharding), ff._opt_state
    )
    xs = np.random.RandomState(0).randn(16, 32).astype(np.float32)
    ys = np.zeros(16, dtype=np.int32)
    ff.fit(xs, ys, epochs=1)


def test_unshardable_leaves_fall_back_per_leaf():
    """A dim that doesn't divide by the wus axis keeps its strategy
    sharding (replicated update for that leaf only)."""
    from jax.sharding import PartitionSpec

    from flexflow_tpu.parallel.zero import shard_update_spec

    assert shard_update_spec(PartitionSpec(), (64, 32), "data", 8) == \
        PartitionSpec("data", None)
    assert shard_update_spec(PartitionSpec(), (10,), "data", 8) is None
    # axis already used by the strategy -> no double-sharding
    assert shard_update_spec(PartitionSpec("data"), (64,), "data", 8) is None
    # first free divisible dim wins; sharded dims are skipped
    assert shard_update_spec(
        PartitionSpec("model", None), (64, 24), "data", 8
    ) == PartitionSpec("model", "data")


def test_checkpoint_roundtrip_and_elastic_reshard(devices8, tmp_path):
    """Sharded slots save and restore; an 8 -> 4 elastic restore
    reshards them onto the survivor mesh's ZeRO-1 layout."""
    import jax

    from flexflow_tpu.checkpoint import LocalCheckpointManager

    xs, ys = _data()
    ff = _model(devices8, wus=True, opt=AdamOptimizer(alpha=0.01))
    ff.fit(xs, ys, epochs=1, verbose=False)
    saved_w = ff.get_weights()
    saved_opt = jax.tree.map(np.asarray, ff._opt_state)

    mgr = LocalCheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(ff, step=1)
    meta = mgr.restore_meta()
    assert meta["weight_update_sharding"] is True
    assert meta["wus_axis"] == "data"

    ff.fit(xs, ys, epochs=1, verbose=False)  # diverge
    assert mgr.restore(ff) == 1
    _assert_trees_close(ff.get_weights(), saved_w, rtol=0, atol=0)
    _assert_trees_close(
        jax.tree.map(np.asarray, ff._opt_state), saved_opt, rtol=0, atol=0
    )

    # elastic: restore into a fresh 4-device model (wus still on)
    ff4 = _model(devices8[:4], wus=True, opt=AdamOptimizer(alpha=0.01),
                 seed=7)
    assert mgr.restore(ff4) == 1
    _assert_trees_close(ff4.get_weights(), saved_w, rtol=0, atol=0)
    _assert_trees_close(
        jax.tree.map(np.asarray, ff4._opt_state), saved_opt, rtol=0, atol=0
    )
    shard4, total4 = _slot_shard_bytes(ff4._opt_state)
    assert shard4 < total4  # still sharded, now 1/4 per device
    # the restored 4-device model keeps training
    ff4.fit(xs, ys, epochs=1, verbose=False)


def test_wus_noop_without_data_axis(devices8):
    """A mesh without the wus axis (tp-only strategy) disables the
    sharded update instead of failing."""
    from flexflow_tpu.strategy import Strategy

    cfg = FFConfig(batch_size=16, num_devices=2,
                   weight_update_sharding=True)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="x")
    t = ff.dense(x, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 8)
    ff.softmax(t)
    from flexflow_tpu.ops.op import ShardConfig

    s = Strategy(mesh_axes={"model": 2})
    s.shard_configs["dense_0"] = ShardConfig(channel=2)
    s.shard_configs["dense_1"] = ShardConfig(reduction=2)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy=s, devices=devices8[:2])
    assert ff.executor.wus_axis is None
    xs, ys = _data(32)
    m = ff.train_step({"x": xs[:16]}, ys[:16])
    assert np.isfinite(float(m["loss"]))


# -- simulator parity ----------------------------------------------------

def _transformer_graph():
    from flexflow_tpu.models.transformer import build_transformer

    ff = FFModel(FFConfig())
    build_transformer(ff, batch_size=8, seq_length=16, hidden_size=32,
                      num_layers=2, num_heads=4)
    return ff.layers


def test_simulator_scores_sharded_update(devices8):
    """Flipping the knob changes the predicted step time the right way:
    the update term shrinks by ~1/dp while the grad ring bytes stay
    (all-reduce == reduce-scatter + all-gather), and modeled per-device
    memory drops by the slot shard savings."""
    from flexflow_tpu.pcg.evaluator import IncrementalEvaluator
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import Simulator
    from flexflow_tpu.strategy import data_parallel_strategy

    graph = _transformer_graph()
    machine = TpuPodModel(topology=(8,))
    s = data_parallel_strategy(8)
    ev_off = IncrementalEvaluator(graph, Simulator(machine))
    ev_on = IncrementalEvaluator(
        graph, Simulator(machine, weight_update_sharding=True)
    )
    off, on = ev_off.evaluate(s), ev_on.evaluate(s)
    assert off is not None and on is not None
    # numel/N update cost: strictly cheaper with replicated weights
    assert on.total_time < off.total_time
    # RS+AG == AR in the ring model: comm/sync totals stay consistent
    assert on.compute_time < off.compute_time
    # slots shrink ~1/dp; weights+grads+activations unchanged
    assert on.per_device_memory < off.per_device_memory

    # the delta vs the whole-graph optimizer_update_cost agree on scale
    sim_off = Simulator(machine)
    sim_on = Simulator(machine, weight_update_sharding=True)
    from flexflow_tpu.strategy import apply_strategy, assign_views

    g = apply_strategy(graph, s)
    assign_views(g, s.mesh_axes)
    c_off = sim_off.optimizer_update_cost(g)
    c_on = sim_on.optimizer_update_cost(g)
    assert c_on < c_off
    assert c_off / c_on == pytest.approx(8.0, rel=0.2)


def test_simulator_mirrors_per_leaf_fallback():
    """A weight with no free dim divisible by the wus group keeps
    replicated cost/memory in the simulator — the executor falls back
    to the replicated update for exactly those leaves — and the group
    is the SINGLE configured wus axis, not the whole replica product
    (mixed meshes), vanishing entirely on meshes without that axis."""
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import Simulator
    from flexflow_tpu.strategy import (
        Strategy,
        apply_strategy,
        assign_views,
        data_parallel_strategy,
    )

    cfg = FFConfig()
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="x")
    t = ff.dense(x, 7)  # kernel (32,7), bias (7): bias can't shard by 8
    ff.softmax(t)
    s = data_parallel_strategy(8)
    g = apply_strategy(ff.layers, s)
    assign_views(g, s.mesh_axes)
    dense = next(op for op in g.ops if op.name == "dense_0")
    kernel, bias = dense.weights[0], dense.weights[1]
    machine = TpuPodModel(topology=(8,))
    sim_on = Simulator(machine, weight_update_sharding=True)
    sim_off = Simulator(machine)
    assert sim_on.wus_group(kernel, s.mesh_axes) == 8  # 32 % 8 == 0
    assert sim_on.wus_group(bias, s.mesh_axes) == 1   # 7: no divisible dim
    assert sim_off.wus_group(kernel, s.mesh_axes) == 1  # knob off

    # bias numel stays whole in the sharded-update accounting
    kb = kernel.shape.shard_bytes() / 4
    bb = bias.shape.shard_bytes() / 4
    expected = (kb / 8 + bb) / (kb + bb)
    assert (sim_on.optimizer_update_cost(g, s.mesh_axes)
            / sim_off.optimizer_update_cost(g, s.mesh_axes)
            ) == pytest.approx(expected, rel=1e-6)

    # mixed mesh: the executor shards over the 'data' axis only, so an
    # 8-way-replicated weight shards 4-ways, not 8
    from flexflow_tpu.ops.op import ShardConfig

    ff2 = FFModel(FFConfig())
    x2 = ff2.create_tensor([16, 32], name="x")
    t2 = ff2.dense(x2, 64)
    ff2.softmax(t2)
    s2 = Strategy(mesh_axes={"data": 4, "model": 2})
    s2.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 4})]
    g2 = apply_strategy(ff2.layers, s2)
    assign_views(g2, s2.mesh_axes)
    dense2 = next(op for op in g2.ops if op.name == "dense_0")
    k2 = dense2.weights[0]
    if k2.shape.replica_degree == 8:  # replicated over both axes
        assert sim_on.wus_group(k2, s2.mesh_axes) == 4

    # tp-only mesh: executor disables wus (no 'data' axis) — so must we
    ff3 = FFModel(FFConfig())
    x3 = ff3.create_tensor([16, 32], name="x")
    t3 = ff3.dense(x3, 64)
    t3 = ff3.dense(t3, 8)
    ff3.softmax(t3)
    s3 = Strategy(mesh_axes={"model": 8})
    s3.shard_configs["dense_0"] = ShardConfig(channel=8)
    s3.shard_configs["dense_1"] = ShardConfig(reduction=8)
    g3 = apply_strategy(ff3.layers, s3)
    assign_views(g3, s3.mesh_axes)
    assert any(w.shape.replica_degree > 1
               for op in g3.ops for w in op.weights)
    for op in g3.ops:
        for w in op.weights:
            assert sim_on.wus_group(w, s3.mesh_axes) == 1


def test_search_stats_surface_the_choice(devices8):
    """The winning strategy's search_stats record the update-sharding
    mode candidates were scored under (both searches)."""
    for algo in ("mcmc", "unity"):
        cfg = FFConfig(batch_size=32, num_devices=8, search_budget=8,
                       search_algo=algo, search_calibrate=False,
                       weight_update_sharding=True)
        ff = FFModel(cfg)
        x = ff.create_tensor([32, 16], name="x")
        t = ff.dense(x, 32, activation=ActiMode.RELU)
        t = ff.dense(t, 8)
        ff.softmax(t)
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   devices=devices8)
        assert ff.strategy.search_stats["weight_update_sharding"] is True


def test_config_cli_flags():
    cfg = FFConfig.from_args(["--weight-update-sharding"])
    assert cfg.weight_update_sharding is True and cfg.wus_axis == "data"
    cfg = FFConfig.from_args(["--weight-update-sharding", "--wus-axis", "dp"])
    assert cfg.wus_axis == "dp"
    assert FFConfig.from_args([]).weight_update_sharding is False
    with pytest.raises(ValueError):
        FFConfig(wus_axis="")


def test_zero_stage_cli_and_deprecation_shim():
    """--zero-stage is the unified ladder knob; the pre-ladder
    --weight-update-sharding flag is a deprecation shim for stage 1 and
    the bool always mirrors `zero_stage >= 1` after init."""
    cfg = FFConfig.from_args(["--zero-stage", "2"])
    assert cfg.zero_stage == 2 and cfg.weight_update_sharding is True
    assert FFConfig.from_args([]).zero_stage == 0
    # deprecated flag maps to stage 1
    cfg = FFConfig.from_args(["--weight-update-sharding"])
    assert cfg.zero_stage == 1
    # an explicit stage wins over the shim
    cfg = FFConfig.from_args(["--weight-update-sharding", "--zero-stage", "3"])
    assert cfg.zero_stage == 3 and cfg.weight_update_sharding is True
    assert FFConfig(zero_stage=3).weight_update_sharding is True
    assert FFConfig(zero_stage=0).weight_update_sharding is False
    assert FFConfig(weight_update_sharding=True).zero_stage == 1
    with pytest.raises(ValueError):
        FFConfig(zero_stage=4)
    with pytest.raises(ValueError):
        FFConfig(zero_stage=-1)


# -- the ZeRO ladder: stages 2/3 (arXiv:1910.02054) ----------------------

def _axes_of(spec):
    return [a for e in spec if e is not None
            for a in (e if isinstance(e, tuple) else (e,))]


def _tree_shard_bytes(shardings, leaves):
    """(per-device, total) bytes of `leaves` laid out per `shardings`
    (both {op: {weight: _}} trees)."""
    shard = total = 0
    for op_name, entry in shardings.items():
        for wname, sh in entry.items():
            leaf = leaves[op_name][wname]
            shard += int(
                np.prod(sh.shard_shape(leaf.shape)) * leaf.dtype.itemsize
            )
            total += int(np.prod(leaf.shape) * leaf.dtype.itemsize)
    return shard, total


@pytest.mark.parametrize(
    "make_opt",
    [
        lambda: SGDOptimizer(lr=0.05, momentum=0.9),
        lambda: AdamOptimizer(alpha=0.01),
    ],
    ids=["sgd_momentum", "adam"],
)
@pytest.mark.parametrize("stage", [1, 2, 3])
def test_ladder_stage_matches_replicated(devices8, make_opt, stage):
    """Every rung of the ladder trains to the stage-0 weights, slots
    AND per-epoch loss trajectory on the same data: the ladder changes
    residency and collectives, never numerics."""
    import jax

    xs, ys = _data()
    ff0 = _model(devices8, opt=make_opt())
    ffs = _model(devices8, opt=make_opt(), stage=stage)
    h0 = ff0.fit(xs, ys, epochs=2, verbose=False)
    hs = ffs.fit(xs, ys, epochs=2, verbose=False)
    np.testing.assert_allclose(
        [pm.sparse_cce_loss for pm in h0],
        [pm.sparse_cce_loss for pm in hs],
        rtol=2e-5,
    )
    _assert_trees_close(ff0.get_weights(), ffs.get_weights())
    _assert_trees_close(
        jax.tree.map(np.asarray, ff0._opt_state),
        jax.tree.map(np.asarray, ffs._opt_state),
    )


def test_stage2_grad_buffer_scattered(devices8):
    """At stage >= 2 the gradient buffer the step carries is the
    scattered (wus) layout — per-device grad bytes drop by ~1/dp, and
    no pre-update gather of the grads exists (they feed the 1/dp-shard
    update directly).  Below stage 2 grads keep the strategy layout."""
    from jax.sharding import NamedSharding

    dp = 8
    ff = _model(devices8, opt=AdamOptimizer(alpha=0.01), stage=2)
    gsh = ff.executor.grad_shardings()
    for op_name, entry in gsh.items():
        for wname, sh in entry.items():
            assert isinstance(sh, NamedSharding)
    # the three kernels all scatter along the wus axis
    for op in ("dense_0", "dense_1", "dense_2"):
        assert "data" in _axes_of(gsh[op]["kernel"].spec), op
    shard, total = _tree_shard_bytes(gsh, ff._weights)
    assert shard <= total // dp + total // 20, (shard, total)
    # stages 0/1 keep the strategy (replicated) grad layout
    for s in (0, 1):
        ffl = _model(devices8, opt=AdamOptimizer(alpha=0.01), stage=s)
        assert ffl.executor.grad_shardings() == \
            ffl.executor.weight_shardings()
    # and the scattered-grad step still trains
    xs, ys = _data(32)
    m = ff.train_step({"x": xs[:16]}, ys[:16])
    assert np.isfinite(float(m["loss"]))


def test_stage3_master_weights_resident_scattered(devices8):
    """At stage 3 master weights LIVE scattered along the wus axis —
    weight-resident bytes drop by ~1/dp per device (asserted via the
    NamedShardings the weights actually carry) — and they stay
    scattered after an update step (no post-update gather-back)."""
    from jax.sharding import NamedSharding

    dp = 8
    ff = _model(devices8, opt=AdamOptimizer(alpha=0.01), stage=3)
    shard = total = 0
    for op_name, entry in ff._weights.items():
        for wname, leaf in entry.items():
            assert isinstance(leaf.sharding, NamedSharding)
            shard += int(
                np.prod(leaf.sharding.shard_shape(leaf.shape))
                * leaf.dtype.itemsize
            )
            total += int(np.prod(leaf.shape) * leaf.dtype.itemsize)
    assert shard <= total // dp + total // 20, (shard, total)
    for op in ("dense_0", "dense_1", "dense_2"):
        assert "data" in _axes_of(ff._weights[op]["kernel"].sharding.spec)
    # below stage 3 the resident layout is the strategy sharding
    ff1 = _model(devices8, opt=AdamOptimizer(alpha=0.01), stage=1)
    assert ff1.executor.master_weight_shardings() == \
        ff1.executor.weight_shardings()
    assert _axes_of(ff1._weights["dense_0"]["kernel"].sharding.spec) == []
    # a step keeps the scattered residency (the update emits no gather)
    xs, ys = _data(32)
    m = ff.train_step({"x": xs[:16]}, ys[:16])
    assert np.isfinite(float(m["loss"]))
    assert "data" in _axes_of(ff._weights["dense_0"]["kernel"].sharding.spec)
    # get_weights still surfaces full global arrays
    w = ff.get_weights()
    assert w["dense_0"]["kernel"].shape == (32, 64)


def test_stage3_checkpoint_elastic_reshard(devices8, tmp_path):
    """A stage-3 run's scattered master weights round-trip through a
    checkpoint, including the 8 -> 4 elastic reshard and a cross-stage
    restore into a stage-0 model."""
    import jax

    from flexflow_tpu.checkpoint import LocalCheckpointManager

    xs, ys = _data()
    ff = _model(devices8, opt=AdamOptimizer(alpha=0.01), stage=3)
    ff.fit(xs, ys, epochs=1, verbose=False)
    saved_w = ff.get_weights()
    saved_opt = jax.tree.map(np.asarray, ff._opt_state)

    mgr = LocalCheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(ff, step=1)
    assert mgr.restore_meta()["zero_stage"] == 3

    # elastic: 8 -> 4 survivors, still stage 3
    ff4 = _model(devices8[:4], opt=AdamOptimizer(alpha=0.01), stage=3,
                 seed=7)
    assert mgr.restore(ff4) == 1
    _assert_trees_close(ff4.get_weights(), saved_w, rtol=0, atol=0)
    _assert_trees_close(
        jax.tree.map(np.asarray, ff4._opt_state), saved_opt, rtol=0, atol=0
    )
    # master weights resident-scattered on the survivor mesh (1/4 now)
    k4 = ff4._weights["dense_0"]["kernel"]
    assert "data" in _axes_of(k4.sharding.spec)
    ff4.fit(xs, ys, epochs=1, verbose=False)  # keeps training

    # cross-stage: the same artifact restores into a stage-0 model
    # (leaves are saved as GLOBAL arrays; restore reshards onto the
    # current executor's layouts)
    ff0 = _model(devices8[:4], opt=AdamOptimizer(alpha=0.01), seed=9)
    assert mgr.restore(ff0) == 1
    _assert_trees_close(ff0.get_weights(), saved_w, rtol=0, atol=0)
