"""Unity search with sequence-parallel candidates: the search must
consider dp x sp (ring attention) meshes, pick SP when attention
dominates at long sequence, and its chosen strategy must execute
correctly end-to-end."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.models.transformer import build_bert
from flexflow_tpu.pcg.unity import UnitySearch
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import OpCostModel


def _bert(seq, hidden=32, heads=4, layers=1, batch=8):
    ff = FFModel(FFConfig(batch_size=batch))
    build_bert(ff, batch_size=batch, seq_length=seq, hidden_size=hidden,
               num_layers=layers, num_heads=heads,
               intermediate_size=hidden * 2)
    return ff


def _search(ff, n=8):
    m = TpuPodModel()
    return UnitySearch(ff.layers, n, m, OpCostModel(m))


def test_sp_candidates_enumerated():
    ff = _bert(seq=512)
    s = _search(ff)
    cands = list(s._sp_candidates())
    degrees = sorted(int(lbl.split("sp=")[1].split(" ")[0])
                     for _, _, _, lbl in cands)
    assert degrees == [2, 4, 8]
    for strat, time, mem, _ in cands:
        assert "seq" in strat.mesh_axes
        assert np.isfinite(time) and time > 0
        assert mem > 0


def test_sp_not_offered_without_attention():
    ff = FFModel(FFConfig(batch_size=8))
    from flexflow_tpu.fftype import ActiMode

    x = ff.create_tensor([8, 16, 8], name="x")
    t = ff.dense(x, 8, activation=ActiMode.RELU)
    ff.softmax(t)
    s = _search(ff)
    assert list(s._sp_candidates()) == []


def test_search_returns_valid_strategy_with_sp_in_space():
    ff = _bert(seq=256)
    s = _search(ff)
    best = s.optimize()
    assert best is not None
    # whatever won, it must apply + execute (validated inside optimize,
    # re-checked here through compile)
    import jax

    devs = jax.devices("cpu")[:8]
    ff2 = _bert(seq=256)
    ff2.compile(optimizer=SGDOptimizer(lr=0.01), strategy=best, devices=devs)
    xs = np.random.RandomState(0).randn(8, 256, 32).astype(np.float32)
    out = np.asarray(ff2.forward({"input": xs}))
    assert np.isfinite(out).all()


def test_sp_strategy_from_search_matches_single_device(devices8):
    """Force the SP winner by costing: long seq, tiny hidden makes
    attention (O(s^2)) dominate, and verify numerics of the searched
    strategy against 1 device."""
    ff = _bert(seq=512, hidden=16, heads=2)
    s = _search(ff)
    cands = list(s._sp_candidates())
    strat = min(cands, key=lambda c: c[1])[0]  # fastest SP mesh

    ff_sp = _bert(seq=512, hidden=16, heads=2)
    ff_sp.compile(optimizer=SGDOptimizer(lr=0.01), strategy=strat,
                  devices=devices8, seed=3)
    ff_1 = _bert(seq=512, hidden=16, heads=2)
    ff_1.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1], seed=3)

    xs = np.random.RandomState(1).randn(8, 512, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff_sp.forward({"input": xs})),
        np.asarray(ff_1.forward({"input": xs})),
        rtol=2e-4, atol=2e-4,
    )
