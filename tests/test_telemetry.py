"""Unified run telemetry (flexflow_tpu/obs/): trace-event schema,
metrics-registry semantics, named_scope HLO attribution, fidelity
records, and the zero-cost disabled path."""
import json
import logging
import os

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.obs import (
    MetricsRegistry,
    RunTelemetry,
    parse_profile_steps,
    span_allocations,
)
from flexflow_tpu.obs.metrics import emit_counters


def _build_mlp(cfg, in_dim=32, classes=10):
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, in_dim], name="input")
    h = ff.dense(x, 64)
    h = ff.relu(h)
    ff.dense(h, classes)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _data(n=64, in_dim=32, classes=10):
    rng = np.random.RandomState(0)
    return (rng.randn(n, in_dim).astype(np.float32),
            rng.randint(0, classes, n).astype(np.int32))


def _match_be_pairs(events):
    """Walk B/E events per (pid, tid) with stack discipline; returns
    the matched (name, dur) list and asserts nothing dangles."""
    stacks, pairs = {}, []
    for ev in events:
        key = (ev["pid"], ev["tid"])
        if ev["ph"] == "B":
            stacks.setdefault(key, []).append(ev)
        elif ev["ph"] == "E":
            stack = stacks.get(key)
            assert stack, f"E event with empty stack: {ev}"
            b = stack.pop()
            assert ev["ts"] >= b["ts"]
            pairs.append((b["name"], ev["ts"] - b["ts"]))
    for key, stack in stacks.items():
        assert not stack, f"unclosed B events on {key}: {stack}"
    return pairs


# ---------------------------------------------------------------------------
# tentpole: trace-event timeline + JSONL + fidelity from an 8-device fit
# ---------------------------------------------------------------------------

def test_fit_trace_and_telemetry_8dev(tmp_path, devices8):
    """Acceptance: an 8-device CPU-mesh fit with --trace-dir produces a
    loadable Chrome trace (>= one span per step, plus compile spans) and
    a run_telemetry.jsonl with unified metrics + a fidelity record."""
    td = str(tmp_path / "telem")
    cfg = FFConfig(batch_size=16, num_devices=8, trace_dir=td)
    ff = _build_mlp(cfg)
    X, y = _data(64)
    ff.fit(X, y, batch_size=16, epochs=2, verbose=False)

    with open(os.path.join(td, "trace.json")) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert events
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)  # serialized sorted by timestamp
    pairs = _match_be_pairs(events)
    names = [n for n, _ in pairs]
    # 4 batches/epoch x 2 epochs; one step + one host_transfer span each
    assert names.count("step") == 8
    assert names.count("host_transfer") == 8
    assert "compile" in names
    assert "init_weights" in names  # the eager XLA compile inside compile()
    assert all(d >= 0 for _, d in pairs)

    recs = [json.loads(line)
            for line in open(os.path.join(td, "run_telemetry.jsonl"))]
    assert all(r["schema"] == 1 for r in recs)
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    hists = {r["name"]: r for r in by_kind["histogram"]}
    assert hists["fit/step_ms"]["count"] == 8
    gauges = {r["name"]: r for r in by_kind["gauge"]}
    assert gauges["compile/total_ms"]["value"] > 0
    assert "fit/metrics/train_all" in gauges  # PerfMetrics unified
    (fid,) = by_kind["fidelity"]
    assert fid["predicted_step_ms"] > 0
    assert fid["measured_step_ms"] > 0
    assert fid["predicted_vs_measured"] == pytest.approx(
        fid["predicted_step_ms"] / fid["measured_step_ms"], abs=1e-4
    )  # record values are rounded to 4 decimals
    assert fid["mesh_axes"] == {"data": 8}
    assert fid["num_devices"] == 8
    assert fid["source"] == "fit"


def test_supervisor_emits_checkpoint_and_restart_spans(tmp_path, devices8):
    from flexflow_tpu.resilience import FaultKind, FaultPlan, TrainingSupervisor

    td = str(tmp_path / "telem")
    cfg = FFConfig(batch_size=8, num_devices=8, trace_dir=td,
                   checkpoint_every=2, max_restarts=3, retry_backoff=0.0)
    ff = _build_mlp(cfg)
    X, y = _data(32)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "ckpt"),
        fault_plan=FaultPlan.single(3, FaultKind.STEP_EXCEPTION),
        sleep=lambda s: None,
    )
    report = sup.run(X, y, num_steps=4)
    assert report.counters["restarts"] == 1

    with open(os.path.join(td, "trace.json")) as f:
        events = json.load(f)["traceEvents"]
    names = [n for n, _ in _match_be_pairs(events)]
    assert "checkpoint_write" in names
    assert "restart" in names
    recs = [json.loads(line)
            for line in open(os.path.join(td, "run_telemetry.jsonl"))]
    gauges = {r["name"]: r["value"] for r in recs if r["kind"] == "gauge"}
    # supervisor counters unified into the registry
    assert gauges["resilience/restarts"] == 1
    assert gauges["resilience/checkpoints"] >= 1
    # the supervisor's restore log line captured as an event record
    logs = [r for r in recs
            if r["kind"] == "event" and r["name"] == "log"]
    assert any("restored step" in r["fields"]["message"] for r in logs)


def test_crashed_fit_still_writes_artifacts(tmp_path, devices8):
    """A traced run that dies mid-training is exactly the run whose
    telemetry matters: fit's finally clause must flush the artifacts."""

    class Boom(Exception):
        pass

    class Crasher:
        def on_train_begin(self, ff):
            pass

        def on_epoch_end(self, ff, epoch, pm):
            raise Boom()

    td = str(tmp_path / "telem")
    cfg = FFConfig(batch_size=16, num_devices=8, trace_dir=td)
    ff = _build_mlp(cfg)
    X, y = _data(64)
    with pytest.raises(Boom):
        ff.fit(X, y, batch_size=16, epochs=2, verbose=False,
               callbacks=[Crasher()])
    with open(os.path.join(td, "trace.json")) as f:
        events = json.load(f)["traceEvents"]
    names = [n for n, _ in _match_be_pairs(events)]
    assert names.count("step") == 4  # epoch 0's steps made it to disk
    assert os.path.exists(os.path.join(td, "run_telemetry.jsonl"))


# ---------------------------------------------------------------------------
# metrics registry semantics
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("c")
    c.inc()
    c.inc(4)
    assert reg.counter("c") is c and c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g")
    g.set(2.5)
    g.set(1.0)
    assert g.value == 1.0
    h = reg.histogram("h")
    for v in (1.0, 3.0, 2.0):
        h.observe(v)
    assert (h.count, h.sum, h.min, h.max) == (3, 6.0, 1.0, 3.0)
    assert h.mean == pytest.approx(2.0)
    with pytest.raises(TypeError):
        reg.gauge("c")  # same name, different type

    recs = {(r["kind"], r["name"]): r for r in reg.drain()}
    assert recs[("counter", "c")]["value"] == 5
    assert recs[("histogram", "h")]["mean"] == pytest.approx(2.0)
    assert all(r["schema"] == 1 and "ts" in r for r in recs.values())


def test_emit_counters_keeps_log_line_format(caplog):
    """The migrated call sites must emit the EXACT RecursiveLogger
    `label: k=v ...` line (float -> %.4g) while also folding into the
    registry."""
    from flexflow_tpu.logger import search_logger

    reg = MetricsRegistry()
    stats = {"evals": 12, "evals_per_sec": 123.4567, "flag": True}
    with caplog.at_level(logging.INFO, logger="flexflow_tpu.search"):
        emit_counters(search_logger, "mcmc eval stats", stats,
                      registry=reg, group="search/mcmc")
    assert caplog.messages == ["mcmc eval stats: evals=12 evals_per_sec=123.5 flag=True"]
    gauges = {r["name"]: r["value"] for r in reg.drain()
              if r["kind"] == "gauge"}
    assert gauges["search/mcmc/evals"] == 12
    assert gauges["search/mcmc/evals_per_sec"] == pytest.approx(123.4567)
    assert gauges["search/mcmc/flag"] == 1


def test_search_stats_reach_registry(devices8):
    cfg = FFConfig(batch_size=16, num_devices=2, telemetry=True,
                   search_budget=2, search_algo="mcmc",
                   search_calibrate=False)
    ff = _build_mlp(cfg)
    assert ff.strategy.search_stats  # dict API unchanged
    names = [r["name"] for r in ff.telemetry.metrics.drain()
             if r["kind"] == "gauge"]
    assert any(n.startswith("search/mcmc/") for n in names)
    assert "compile/search_ms" in names


def test_calib_logger_lands_in_telemetry():
    from flexflow_tpu.logger import calib_logger

    tel = RunTelemetry(enabled=True)
    try:
        calib_logger.info("region %s failed: %r", ["dense_0"], "boom")
        events = [r for r in tel.metrics.drain() if r["kind"] == "event"]
        assert any(
            r["fields"]["logger"] == "flexflow_tpu.calib"
            and "dense_0" in r["fields"]["message"]
            for r in events
        )
    finally:
        tel.close()


# ---------------------------------------------------------------------------
# named_scope: op names in the compiled step HLO
# ---------------------------------------------------------------------------

def test_named_scope_op_names_in_step_hlo():
    import jax

    cfg = FFConfig(batch_size=8, num_devices=1)
    ff = _build_mlp(cfg)
    X, y = _data(8)
    put_inputs, put_labels = ff._device_put_batch({"input": X}, y)
    rng = jax.random.key(0)
    lowered = ff._step_fn.lower(
        ff._weights, ff._opt_state, ff._state, put_inputs, put_labels, rng
    )
    hlo = lowered.compile().as_text()
    for op in ff.operators.topo_order():
        if op.name.startswith("dense"):
            assert op.name in hlo  # named_scope carried into op metadata


# ---------------------------------------------------------------------------
# disabled path: zero allocation on the step hot path
# ---------------------------------------------------------------------------

def test_disabled_fit_allocates_no_spans():
    cfg = FFConfig(batch_size=16, num_devices=1)
    ff = _build_mlp(cfg)
    assert not ff.telemetry.enabled
    X, y = _data(64)
    before = span_allocations()
    ff.fit(X, y, batch_size=16, epochs=2, verbose=False)
    assert span_allocations() == before


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_cli_knobs(tmp_path):
    td = str(tmp_path / "t")
    cfg = FFConfig.from_args(
        ["--trace-dir", td, "--profile-steps", "3:2", "--telemetry"]
    )
    assert cfg.trace_dir == td
    assert cfg.telemetry is True
    assert cfg.profile_steps == "3:2"
    assert parse_profile_steps("3:2") == (3, 5)

    assert FFConfig.from_args([]).trace_dir is None

    with pytest.raises(ValueError):
        FFConfig(profile_steps="3:2")  # needs trace_dir
    with pytest.raises(ValueError):
        FFConfig(trace_dir=td, profile_steps="nope")
    with pytest.raises(ValueError):
        FFConfig(trace_dir=td, profile_steps="3:0")


def test_print_profile_total_excludes_unmeasured(capsys):
    from flexflow_tpu.profiler import print_profile

    rows = [
        {"name": "a", "type": "LINEAR", "fwd_ms": 1.5, "flops": 1e9},
        {"name": "b", "type": "CACHE", "fwd_ms": None, "flops": 0.0},
        {"name": "c", "type": "LINEAR", "fwd_ms": 0.5, "flops": 1e9},
    ]
    print_profile(rows)
    out = capsys.readouterr().out
    assert "2.000" in out  # 1.5 + 0.5, Nones excluded
    assert "(2 measured / 3 total ops, 1 excluded)" in out
