"""Per-op machine-view placement (VERDICT r1 Missing #4).

Reference: each op owns a MachineView (dim, degree, start, stride —
machine_view.h:31) so different ops can live on different device
subsets.  TPU-native realization: FACTORED mesh axes ("model0"/"model1")
let ops shard at different degrees — i.e. occupy different submeshes —
inside one SPMD program, with assign_axes factoring each tensor's
degrees onto axis subsets (SURVEY §7 hard-part 4's mesh-realizable
views).
"""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.ops.op import ShardConfig
from flexflow_tpu.pcg.substitution import axis_degrees
from flexflow_tpu.strategy import Strategy


def test_axis_degrees_subset_products():
    assert axis_degrees({"model": 4}, "model") == [4]
    assert axis_degrees({"model0": 2, "model1": 2}, "model") == [2, 4]
    assert axis_degrees({"model0": 2, "model1": 3}, "model") == [2, 3, 6]
    assert axis_degrees({"data": 8}, "model") == []


def _mixed_model(n):
    ff = FFModel(FFConfig(batch_size=8, num_devices=n))
    x = ff.create_tensor([8, 16], name="x")
    t = ff.dense(x, 32, activation=ActiMode.RELU, name="fa")
    t = ff.dense(t, 64, activation=ActiMode.RELU, name="fb")
    t = ff.dense(t, 4, name="head")
    ff.softmax(t)
    return ff


def test_mixed_degree_per_op_views_match_single_device(devices8):
    """fa shards channel over model1 (degree 2), fb over model1+model0
    (degree 4) — different submeshes, exact numerics."""
    s = Strategy(mesh_axes={"data": 2, "model0": 2, "model1": 2})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 2})]
    s.shard_configs["fa"] = ShardConfig(channel=2)
    s.edge_ops["fa.out0"] = [("combine", {"dim": 1, "degree": 2})]
    s.shard_configs["fb"] = ShardConfig(channel=4)
    s.edge_ops["fb.out0"] = [("combine", {"dim": 1, "degree": 4})]
    ff = _mixed_model(8)
    ff.compile(optimizer=SGDOptimizer(lr=0.05), strategy=s,
               devices=devices8[:8])
    fa = next(op for op in ff.operators.ops if op.name == "fa")
    fb = next(op for op in ff.operators.ops if op.name == "fb")
    assert fa.weights[0].machine_view.used_axes() != \
        fb.weights[0].machine_view.used_axes()

    ff1 = _mixed_model(1)
    ff1.compile(optimizer=SGDOptimizer(lr=0.05), devices=devices8[:1])
    ff1.set_weights(ff.get_weights())
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": x})), np.asarray(ff1.forward({"x": x})),
        rtol=2e-5, atol=2e-5,
    )
    y = np.random.RandomState(1).randint(0, 4, (8,))
    l0 = float(ff.train_step({"x": x}, y)["loss"])
    for _ in range(5):
        m = ff.train_step({"x": x}, y)
    assert float(m["loss"]) < l0


def test_search_explores_factored_mesh_mixed_degrees():
    """With one op only 2-shardable (width 6) and another 4-shardable,
    the plain {"model": 4} mesh can't shard the first at all; the
    factored variant lets the search assign DIFFERENT degrees per op."""
    from flexflow_tpu.pcg.unity import UnitySearch
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel

    ff = FFModel(FFConfig(batch_size=16))
    x = ff.create_tensor([16, 2048], name="x")
    # 1026 = 2*513: shardable at degree 2 only; 4096 shards at 4/8
    t = ff.dense(x, 1026, activation=ActiMode.RELU, name="narrow")
    t = ff.dense(t, 4096, activation=ActiMode.RELU, name="wide")
    t = ff.dense(t, 4, name="head")
    ff.softmax(t)
    machine = TpuPodModel(topology=(2, 4))
    search = UnitySearch(ff.layers, 8, machine, OpCostModel(machine),
                         rewrite_max_variants=1, event_rerank=False)
    collector = []
    search._optimize_graph(0.0, collector)
    collector.sort(key=lambda c: c[0])
    best = collector[0][1]
    assert any(k.startswith("model0") for k in best.mesh_axes), best.mesh_axes
    degrees = {k: v.channel for k, v in best.shard_configs.items()
               if v.channel > 1}
    assert len(set(degrees.values())) >= 2, (
        f"expected mixed per-op degrees, got {best.mesh_axes} {degrees}"
    )
    # and the winning mixed-degree strategy lowers end to end
    from flexflow_tpu.strategy import apply_strategy, assign_views

    g = apply_strategy(ff.layers, best)
    assign_views(g, best.mesh_axes)
