"""The ZeRO ladder as a search-costed strategy dimension (ISSUE 10).

Pins four contracts:

  * the OpTerms decomposition is version-locked: changing the field set
    without bumping sim.simulator.COST_MODEL_VERSION fails here, so
    stale strategy-store entries always invalidate fleet-wide;
  * the simulator's ladder economics — per-device memory strictly falls
    rung over rung (slots /dp at 1, grads /dp at 2, master weights /dp
    at 3) while stage 3 pays per-layer all-gather traffic on top of the
    time-identical stages 1/2;
  * the searches CHOOSE the stage: a memory-constrained config lands on
    stage >= 2, the unconstrained config stays at stage <= 1, and the
    choice rides strategy.zero_stage / search_stats;
  * per-leaf replicated-update fallback is counted and surfaced, not
    silent.
"""
import dataclasses
import hashlib

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.optimizer import AdamOptimizer, SGDOptimizer
from flexflow_tpu.pcg.evaluator import IncrementalEvaluator
from flexflow_tpu.pcg.mcmc import MCMCSearch, search_stage_candidates
from flexflow_tpu.pcg.unity import UnitySearch
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import (
    COST_MODEL_VERSION,
    OpCostModel,
    OpTerms,
    Simulator,
)
from flexflow_tpu.strategy import data_parallel_strategy


# -- cost-model version guard (CI satellite) -----------------------------

#: sha256 prefix of OpTerms' comma-joined field names, pinned per
#: COST_MODEL_VERSION.  Changing the per-op decomposition re-prices
#: every stored strategy, so the version MUST bump in the same change —
#: that's what invalidates stale store entries fleet-wide (store/key.py
#: embeds the version in every strategy key).
_OPTERMS_DIGEST_BY_VERSION = {
    # v2: the ZeRO ladder — mem_master/mem_grad/mem_gather/gather_xfer
    2: "361bfd29c5f8ec36",
    # v3: the multi-slice topology subsystem — ici_xfer/dcn_xfer/
    # ici_bytes/dcn_bytes per-tier split + placement-aware estimators
    3: "99b6da36d6b61866",
    # v4: searched rematerialization — mem_activation/recompute (plus
    # the DCN grad-sync bucketing change to the comm estimators)
    4: "baf98457befeaf37",
}


def test_opterms_field_set_pinned_to_cost_model_version():
    fields = ",".join(f.name for f in dataclasses.fields(OpTerms))
    digest = hashlib.sha256(fields.encode()).hexdigest()[:16]
    assert COST_MODEL_VERSION in _OPTERMS_DIGEST_BY_VERSION, (
        f"COST_MODEL_VERSION={COST_MODEL_VERSION} has no pinned OpTerms "
        "digest — add it here IN THE SAME CHANGE that bumps the version"
    )
    assert digest == _OPTERMS_DIGEST_BY_VERSION[COST_MODEL_VERSION], (
        f"OpTerms fields changed ({fields}) without bumping "
        f"COST_MODEL_VERSION (= {COST_MODEL_VERSION}): stored strategies "
        "ranked under the old decomposition would replay stale.  Bump the "
        "version and pin the new digest "
        f"{digest!r} in _OPTERMS_DIGEST_BY_VERSION."
    )


def test_store_key_invalidates_on_stage_change():
    """The strategy-store key sees the configured stage (a stage-blind
    key would replay a stage-0 winner into a stage-3 fleet)."""
    from flexflow_tpu.store.key import simulator_version

    v0 = simulator_version(FFConfig(zero_stage=0))
    v2 = simulator_version(FFConfig(zero_stage=2))
    assert v0 != v2
    assert v0["search"]["zero_stage"] == 0
    assert v2["search"]["zero_stage"] == 2
    assert v0["cost_model_version"] == COST_MODEL_VERSION >= 3


# -- simulator ladder economics ------------------------------------------

def _transformer_graph(batch=16):
    ff = FFModel(FFConfig())
    build_transformer(ff, batch_size=batch, seq_length=16, hidden_size=32,
                      num_layers=2, num_heads=4)
    return ff.layers


def _dp8_result(graph, stage):
    machine = TpuPodModel(topology=(8,))
    ev = IncrementalEvaluator(graph, Simulator(machine, zero_stage=stage))
    return ev.evaluate(data_parallel_strategy(8))


def test_ladder_memory_falls_and_stage3_pays_gathers():
    """Per-device memory strictly falls up the ladder; stages 1 and 2
    are time-identical (stage 2 is a residency change only), stage 1
    beats stage 0 on time (numel/dp update), and stage 3 trades the
    post-update gather for costlier per-layer gathers — which is what
    keeps unconstrained searches on stages <= 1."""
    graph = _transformer_graph()
    r = {s: _dp8_result(graph, s) for s in (0, 1, 2, 3)}
    assert all(v is not None for v in r.values())
    mem = {s: v.per_device_memory for s, v in r.items()}
    assert mem[0] > mem[1] > mem[2] > mem[3], mem
    assert r[1].total_time < r[0].total_time
    assert r[2].total_time == r[1].total_time
    assert r[3].total_time > r[2].total_time
    # the grad reduce-scatter replaces the all-reduce at stage >= 1
    assert r[1].sync_time < r[0].sync_time
    assert r[3].sync_time == r[1].sync_time


def test_stage_override_beats_simulator_default():
    """A strategy-carried stage overrides the simulator's own: costing
    the ladder never needs a second Simulator."""
    graph = _transformer_graph()
    machine = TpuPodModel(topology=(8,))
    ev = IncrementalEvaluator(graph, Simulator(machine, zero_stage=0))
    s3 = dataclasses.replace(data_parallel_strategy(8), zero_stage=3)
    base = ev.evaluate(data_parallel_strategy(8))
    over = ev.evaluate(s3)
    ref = _dp8_result(graph, 3)
    assert over.per_device_memory == ref.per_device_memory
    assert over.total_time == ref.total_time
    assert over.per_device_memory < base.per_device_memory


# -- the search chooses the stage ----------------------------------------

def _mlp(batch=16):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor([batch, 32], name="x")
    t = ff.dense(x, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 8)
    ff.softmax(t)
    return ff


def test_search_stage_candidates_gating():
    """The ladder opens to the search only under --memory-search; the
    configured stage is always the floor."""
    assert search_stage_candidates(FFConfig(zero_stage=0)) == (0,)
    assert search_stage_candidates(FFConfig(zero_stage=3)) == (3,)
    cfg = FFConfig(zero_stage=0, memory_search=True)
    assert search_stage_candidates(cfg) == (0, 1, 2, 3)
    cfg = FFConfig(zero_stage=2, memory_search=True)
    assert search_stage_candidates(cfg) == (2, 3)


def _dp_only(monkeypatch):
    """Pin the mesh enumeration to pure-dp so the ZeRO stage is the only
    memory lever — the deterministic face of the ladder decision."""
    import flexflow_tpu.pcg.unity as unity_mod

    monkeypatch.setattr(
        unity_mod, "_factorizations",
        lambda n, allow_expert=True: [(n, 1, 1)],
    )


def test_unity_chooses_high_stage_under_memory_pressure(monkeypatch):
    """With a per-device budget between the stage-1 and stage-2
    footprints of the dp-8 mesh, unity's lambda search must climb the
    ladder (stage >= 2); without a budget it stays at stage <= 1
    because stage 3's gather traffic costs time."""
    _dp_only(monkeypatch)
    graph = _mlp().layers
    machine = TpuPodModel(topology=(8,))

    def search(budget):
        return UnitySearch(
            graph, 8, machine, OpCostModel(machine),
            zero_stage=0, zero_stages=(0, 1, 2, 3),
            memory_budget=budget, enable_pipeline=False,
        )

    free = search(None).optimize()
    assert free is not None
    assert (free.zero_stage or 0) <= 1

    mems = {
        s: _dp8_result_for(graph, machine, s).per_device_memory
        for s in (1, 2)
    }
    assert mems[2] < mems[1]
    budget = (mems[1] + mems[2]) // 2
    tight = search(budget).optimize_with_memory()
    assert tight is not None
    assert tight.zero_stage >= 2
    sim = Simulator(machine, zero_stage=tight.zero_stage)
    ev = IncrementalEvaluator(graph, sim)
    assert ev.evaluate(tight).per_device_memory <= budget


def _dp8_result_for(graph, machine, stage):
    ev = IncrementalEvaluator(graph, Simulator(machine, zero_stage=stage))
    return ev.evaluate(data_parallel_strategy(8))


def test_mcmc_chooses_high_stage_under_memory_pressure():
    """The MCMC chain's stage move lands memory-pressured models on
    stage >= 2 (budget between the stage-1 and stage-2 dp-8
    footprints); the winner records the stage in search_stats."""
    graph = _mlp().layers
    machine = TpuPodModel(topology=(8,))
    mems = {
        s: _dp8_result_for(graph, machine, s).per_device_memory
        for s in (1, 2)
    }
    budget = (mems[1] + mems[2]) // 2
    search = MCMCSearch(
        graph, 8, lambda: Simulator(machine), budget=60, seed=0,
        zero_stages=(0, 1, 2, 3), memory_budget=budget,
    )
    search.factorizations = [(8, 1, 1)]  # dp-only: the stage decides
    best = search.optimize()
    assert best.zero_stage is not None and best.zero_stage >= 2
    assert search.evaluator.evaluate(best).per_device_memory <= budget


def test_compile_surfaces_stage_in_search_stats(devices8):
    """End to end through FFModel.compile: the searched winner records
    the stage it was costed under for both search algorithms."""
    for algo in ("mcmc", "unity"):
        cfg = FFConfig(batch_size=16, num_devices=8, search_budget=8,
                       search_algo=algo, search_calibrate=False,
                       zero_stage=2)
        ff = FFModel(cfg)
        x = ff.create_tensor([16, 32], name="x")
        t = ff.dense(x, 64, activation=ActiMode.RELU)
        t = ff.dense(t, 8)
        ff.softmax(t)
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   devices=devices8)
        assert ff.strategy.search_stats["zero_stage"] == 2
        assert ff.strategy.search_stats["weight_update_sharding"] is True
        assert ff.executor.zero_stage == 2 or ff.executor.wus_axis is None


# -- per-leaf fallback observability -------------------------------------

def test_fallback_leaves_counted_and_surfaced(devices8):
    """A leaf with no free dim divisible by the wus axis falls back to
    the replicated update — counted into obs metrics and search_stats
    instead of silently."""
    cfg = FFConfig(batch_size=16, num_devices=8, zero_stage=1)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="x")
    t = ff.dense(x, 7)  # kernel (32, 7) shards dim 0; bias (7,) cannot
    ff.softmax(t)
    s = data_parallel_strategy(8)
    s.search_stats = {}
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy=s, devices=devices8)
    assert ff.executor.zero_fallback_leaves() == ["dense_0.bias"]
    assert s.search_stats["zero_fallback_leaves"] == 1
    assert ff.telemetry.metrics.counter(
        "parallel/zero_fallback_leaves"
    ).value == 1
    # the ladder off -> no fallback bookkeeping at all
    ff0 = FFModel(FFConfig(batch_size=16, num_devices=8, zero_stage=0))
    x0 = ff0.create_tensor([16, 32], name="x")
    ff0.softmax(ff0.dense(x0, 7))
    ff0.compile(optimizer=SGDOptimizer(lr=0.05),
                loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                strategy=data_parallel_strategy(8), devices=devices8)
    assert ff0.executor.zero_fallback_leaves() == []
    assert ff0.telemetry.metrics.counter(
        "parallel/zero_fallback_leaves"
    ).value == 0


def test_zero3_loss_matches_adamw_with_fallback_leaf(devices8):
    """Stage 3 with a fallback leaf in the tree (the 7-wide bias stays
    resident + replicated) still matches stage 0 numerics."""
    cfg3 = FFConfig(batch_size=16, num_devices=8, zero_stage=3)
    cfg0 = FFConfig(batch_size=16, num_devices=8, zero_stage=0)

    def build(cfg):
        ff = FFModel(cfg)
        x = ff.create_tensor([16, 32], name="x")
        t = ff.dense(x, 64, activation=ActiMode.RELU)
        t = ff.dense(t, 7)
        ff.softmax(t)
        ff.compile(optimizer=AdamOptimizer(alpha=0.01),
                   loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   strategy=data_parallel_strategy(8), devices=devices8,
                   seed=0)
        return ff

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 32).astype(np.float32)
    ys = rng.randint(0, 7, 64).astype(np.int32)
    ff3, ff0 = build(cfg3), build(cfg0)
    h3 = ff3.fit(xs, ys, epochs=2, verbose=False)
    h0 = ff0.fit(xs, ys, epochs=2, verbose=False)
    np.testing.assert_allclose(
        [pm.sparse_cce_loss for pm in h3],
        [pm.sparse_cce_loss for pm in h0], rtol=2e-5,
    )
