"""Model-zoo parity tests: every reference example family
(/root/reference/examples/cpp/*) builds, compiles data-parallel on the
8-device CPU mesh, and runs a train step with finite loss.

Tiny configs keep CPU compile time bounded; architecture shape logic is
identical to the full-size builders.
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import (
    build_candle_uno,
    build_dlrm,
    build_inception_v3,
    build_mlp_unify,
    build_moe_mlp,
    build_resnet50,
    build_resnext50,
    build_xdl,
)

BATCH = 8


def _compile(ff, devices, loss=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
             metrics=(MetricsType.ACCURACY,)):
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=loss,
        metrics=list(metrics),
        devices=devices,
    )


def _step_classification(ff, inputs, num_classes=4):
    rng = np.random.RandomState(0)
    y = rng.randint(0, num_classes, size=BATCH).astype(np.int32)
    m = ff.train_step(inputs, y)
    key = "sparse_cce_loss" if "sparse_cce_loss" in m else "loss"
    loss = float(m[key])
    assert np.isfinite(loss)
    return loss


def test_resnet50_tiny(devices8):
    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_resnet50(ff, batch_size=BATCH, num_classes=4, image_size=32,
                   stage_blocks=(1, 1), base_channels=8)
    _compile(ff, devices8)
    x = np.random.RandomState(1).randn(BATCH, 3, 32, 32).astype(np.float32)
    _step_classification(ff, {"input": x})


def test_resnext50_tiny(devices8):
    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_resnext50(ff, batch_size=BATCH, num_classes=4, image_size=32,
                    stage_blocks=(1, 1), groups=4, base_channels=8)
    _compile(ff, devices8)
    x = np.random.RandomState(1).randn(BATCH, 3, 32, 32).astype(np.float32)
    _step_classification(ff, {"input": x})


def test_inception_v3_tiny(devices8):
    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_inception_v3(ff, batch_size=BATCH, num_classes=4, image_size=75,
                       channel_scale=1 / 16)
    _compile(ff, devices8)
    x = np.random.RandomState(1).randn(BATCH, 3, 75, 75).astype(np.float32)
    _step_classification(ff, {"input": x})


def test_dlrm_tiny(devices8):
    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_dlrm(ff, batch_size=BATCH, embedding_size=(50, 60, 70),
               sparse_feature_size=8, dense_feature_dim=8,
               mlp_bot=[8, 8], mlp_top=[16, 2])
    _compile(ff, devices8, loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
             metrics=(MetricsType.MEAN_SQUARED_ERROR,))
    rng = np.random.RandomState(1)
    inputs = {
        f"sparse_input_{i}": rng.randint(0, v, size=(BATCH, 1)).astype(np.int32)
        for i, v in enumerate((50, 60, 70))
    }
    inputs["dense_input"] = rng.randn(BATCH, 8).astype(np.float32)
    y = rng.rand(BATCH, 2).astype(np.float32)
    m = ff.train_step(inputs, y)
    assert np.isfinite(float(m["mse_loss"]))


def test_xdl_tiny(devices8):
    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_xdl(ff, batch_size=BATCH, embedding_size=(40, 40),
              sparse_feature_size=8, mlp_dims=[16, 2])
    _compile(ff, devices8, loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
             metrics=(MetricsType.MEAN_SQUARED_ERROR,))
    rng = np.random.RandomState(1)
    inputs = {
        f"sparse_input_{i}": rng.randint(0, 40, size=(BATCH, 1)).astype(np.int32)
        for i in range(2)
    }
    y = rng.rand(BATCH, 2).astype(np.float32)
    m = ff.train_step(inputs, y)
    assert np.isfinite(float(m["mse_loss"]))


def test_candle_uno_tiny(devices8):
    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_candle_uno(ff, batch_size=BATCH, input_dims=[12, 20, 8],
                     dense_layers=[16, 16], dense_feature_layers=[16, 16])
    _compile(ff, devices8, loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
             metrics=(MetricsType.MEAN_SQUARED_ERROR,))
    rng = np.random.RandomState(1)
    inputs = {f"input_{i}": rng.randn(BATCH, d).astype(np.float32)
              for i, d in enumerate((12, 20, 8))}
    y = rng.randn(BATCH, 1).astype(np.float32)
    m = ff.train_step(inputs, y)
    assert np.isfinite(float(m["mse_loss"]))


def test_mlp_unify_tiny(devices8):
    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_mlp_unify(ff, batch_size=BATCH, input_dim=16, hidden_dims=[32, 32, 4])
    _compile(ff, devices8)
    rng = np.random.RandomState(1)
    inputs = {
        "input1": rng.randn(BATCH, 16).astype(np.float32),
        "input2": rng.randn(BATCH, 16).astype(np.float32),
    }
    _step_classification(ff, inputs)


def test_moe_mlp_tiny(devices8):
    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_moe_mlp(ff, batch_size=BATCH, input_dim=16, num_classes=4,
                  num_exp=4, num_select=2, hidden_size=16)
    _compile(ff, devices8)
    x = np.random.RandomState(1).randn(BATCH, 16).astype(np.float32)
    _step_classification(ff, {"input": x})


def test_moe_encoder_tiny(devices8):
    from flexflow_tpu.models import build_moe_encoder

    cfg = FFConfig(batch_size=BATCH, num_devices=8)
    ff = FFModel(cfg)
    build_moe_encoder(ff, batch_size=BATCH, seq_length=8, hidden_size=16,
                      num_layers=1, num_heads=4, num_exp=4, num_select=2,
                      num_classes=4)
    _compile(ff, devices8)
    x = np.random.RandomState(1).randn(BATCH, 8, 16).astype(np.float32)
    rng = np.random.RandomState(0)
    y = rng.randint(0, 4, size=(BATCH, 8)).astype(np.int32)
    m = ff.train_step({"input": x}, y)
    key = "sparse_cce_loss" if "sparse_cce_loss" in m else "loss"
    assert np.isfinite(float(m[key]))
