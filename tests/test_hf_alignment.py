"""End-to-end alignment of a REAL transformers-library encoder through
the torch.fx frontend (VERDICT r4 #7; reference tests/align/mt5_encoder
aligns an mt5 encoder FF-vs-torch, tests/align/README.md:1-20).

Deviation from the reference, documented: the reference loads
pretrained mt5-small weights; this image has zero egress and no model
cache, so the encoder uses the library's own deterministic random init
instead.  The alignment claim is unchanged — the architecture is the
stock HuggingFace implementation (eager attention), its weights
transfer tensor-for-tensor, and the forward numerics must agree with
torch at fp32 — pretrained values would exercise the identical code
path with different constants.
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only


torch = pytest.importorskip("torch")
tf_mod = pytest.importorskip("transformers.models.bert.modeling_bert")

from flexflow_tpu import CompMode, FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel

B, S, H = 4, 12, 128


class _EncoderOnly(torch.nn.Module):
    """BertEncoder returns a ModelOutput; fx-friendly tensor wrapper."""

    def __init__(self, enc):
        super().__init__()
        self.enc = enc

    def forward(self, x):
        return self.enc(x).last_hidden_state


def _hf_encoder(layers=4, dropout=0.0):
    cfg = tf_mod.BertConfig(
        hidden_size=H, num_hidden_layers=layers, num_attention_heads=8,
        intermediate_size=4 * H, vocab_size=128,
        hidden_dropout_prob=dropout, attention_probs_dropout_prob=dropout,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    return _EncoderOnly(tf_mod.BertEncoder(cfg).eval())


def test_hf_bert_encoder_forward_aligns(devices8):
    """The stock HF BERT encoder stack imports (view/transpose/matmul/
    softmax/gelu/LayerNorm/shape-arithmetic trace) and matches torch
    forward numerics at fp32."""
    m = _hf_encoder()
    x = torch.from_numpy(
        np.random.RandomState(0).randn(B, S, H).astype(np.float32))
    with torch.no_grad():
        want = m(x).numpy()

    ff = FFModel(FFConfig(batch_size=B, num_devices=1))
    t = ff.create_tensor([B, S, H], name="input")
    pt = PyTorchModel(m)
    (out,) = pt.torch_to_ff(ff, [t])
    ff.compile(comp_mode=CompMode.INFERENCE, devices=devices8[:1])
    pt.copy_weights(ff)
    got = np.asarray(ff.forward({"input": x.numpy()}))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=5e-4)


def test_hf_bert_encoder_trains_on_mesh(devices8):
    """The imported encoder trains data-parallel on the 8-device mesh
    (transferred weights as the starting point, loss decreases)."""
    m = _hf_encoder(layers=2)
    ff = FFModel(FFConfig(batch_size=8, num_devices=8,
                          only_data_parallel=True))
    t = ff.create_tensor([8, S, H], name="input")
    pt = PyTorchModel(m)
    (out,) = pt.torch_to_ff(ff, [t])
    pooled = ff.mean(out, axes=[1])
    ff.dense(pooled, 4, name="probe_head")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8)
    pt.copy_weights(ff)
    rng = np.random.RandomState(1)
    x = rng.randn(8, S, H).astype(np.float32)
    y = rng.randint(0, 4, 8).astype(np.int32)
    losses = [float(ff.train_step({"input": x}, y)["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0] and np.isfinite(losses).all()
