"""Durable offload tier tests: the blob-store abstraction + fault
wrapper, the REMOTE_LATEST verify-then-advance protocol, the upload
fault matrix (partial/transient/unavailable), two-tier restore
fallback, the strategy-store fleet mirror, the cross-host preemption
barrier, and the full host-loss drill — all hermetic on the 8-device
CPU mesh with a filesystem blob backend.
"""
import io
import json
import os
import shutil
import zlib

import numpy as np
import pytest

from flexflow_tpu import (
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.checkpoint import LocalCheckpointManager
from flexflow_tpu.distributed import preemption_barrier
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.optimizer import AdamOptimizer
from flexflow_tpu.resilience import (
    CheckpointOffloader,
    Fault,
    FaultKind,
    FaultPlan,
    RemoteCheckpointStore,
    RemoteVerifyError,
    RetryPolicy,
    TrainingSupervisor,
)
from flexflow_tpu.store.blobstore import (
    BlobNotFound,
    BlobPreconditionFailed,
    BlobUnavailableError,
    FaultyBlobStore,
    LocalBlobStore,
    blobstore_from_uri,
)

NO_SLEEP = lambda s: None  # noqa: E731


def _model(devices, seed=0, optimizer=None, **cfg_over):
    cfg = FFConfig(batch_size=16, num_devices=len(devices), seed=seed,
                   **cfg_over)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 32, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=optimizer or SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices, seed=seed)
    return ff


def _data(n=128, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 8).astype(np.float32)
    ys = rng.randint(0, 4, size=n).astype(np.int32)
    return xs, ys


def _weights_equal(a, b):
    import jax

    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _offloader(blob, **kw):
    kw.setdefault("retry", RetryPolicy(max_restarts=3, base_backoff=0.0))
    kw.setdefault("sleep", NO_SLEEP)
    return CheckpointOffloader(RemoteCheckpointStore(blob), **kw)


def _fake_step_files(step, value=1.0):
    arr = np.full(8, value, dtype=np.float32)
    buf = io.BytesIO()
    np.savez(buf, **{"['weights']['d']['k']": arr})
    state = buf.getvalue()
    manifest = {
        "manifest_version": 1,
        "step": step,
        "leaves": {
            "['weights']['d']['k']": {
                "crc32": zlib.crc32(
                    np.ascontiguousarray(arr).view(np.uint8).reshape(-1)
                ),
                "bytes": int(arr.nbytes),
                "shape": [8],
                "dtype": "float32",
            }
        },
    }
    return {
        "state.npz": state,
        "meta.json": json.dumps({"step": step}).encode(),
        "manifest.json": json.dumps(manifest).encode(),
    }


# -- blob store units ----------------------------------------------------

def test_local_blobstore_round_trip(tmp_path):
    b = LocalBlobStore(str(tmp_path))
    gen = b.put("ckpt/a.bin", b"hello")
    assert gen == 1
    assert b.get("ckpt/a.bin") == b"hello"
    assert b.list("") == ["ckpt/a.bin"]
    assert b.list("ckpt/") == ["ckpt/a.bin"]
    assert b.list("other/") == []
    info = b.stat("ckpt/a.bin")
    assert info.size == 5 and info.generation == 1
    assert b.delete("ckpt/a.bin") is True
    assert b.delete("ckpt/a.bin") is False
    assert b.stat("ckpt/a.bin") is None
    with pytest.raises(BlobNotFound):
        b.get("ckpt/a.bin")


def test_local_blobstore_conditional_put(tmp_path):
    b = LocalBlobStore(str(tmp_path))
    # create-if-absent
    gen = b.put("p", b"v1", if_generation_match=0)
    assert gen == 1
    with pytest.raises(BlobPreconditionFailed):
        b.put("p", b"v2", if_generation_match=0)
    gen = b.put("p", b"v2", if_generation_match=gen)
    assert gen == 2 and b.get("p") == b"v2"
    with pytest.raises(BlobPreconditionFailed):
        b.put("p", b"v3", if_generation_match=1)


def test_local_blobstore_rejects_bad_keys(tmp_path):
    b = LocalBlobStore(str(tmp_path))
    for bad in ("", "/abs", "a//b", "a/../b", "trailing/"):
        with pytest.raises(ValueError):
            b.put(bad, b"x")


def test_blobstore_from_uri(tmp_path):
    assert isinstance(blobstore_from_uri(str(tmp_path)), LocalBlobStore)
    s = blobstore_from_uri(f"file://{tmp_path}")
    assert isinstance(s, LocalBlobStore) and s.root == str(tmp_path)
    with pytest.raises(NotImplementedError):
        blobstore_from_uri("gs://bucket/prefix")


# -- fault wrapper -------------------------------------------------------

def test_faulty_blobstore_transient_fires_once(tmp_path):
    plan = FaultPlan.single(1, FaultKind.BLOB_TRANSIENT)
    b = FaultyBlobStore(LocalBlobStore(str(tmp_path)), plan, sleep=NO_SLEEP)
    with pytest.raises(BlobUnavailableError):
        b.put("k", b"v")
    # transient: the retry succeeds and the object lands intact
    b.put("k", b"v")
    assert b.get("k") == b"v"
    assert b.counters["transient_errors"] == 1


def test_faulty_blobstore_partial_upload_truncates(tmp_path):
    plan = FaultPlan.single(1, FaultKind.BLOB_PARTIAL_UPLOAD, fraction=0.25)
    b = FaultyBlobStore(LocalBlobStore(str(tmp_path)), plan, sleep=NO_SLEEP)
    b.put("k", b"x" * 100)  # lands TRUNCATED, no error raised
    assert len(b.get("k")) == 25
    assert b.counters["partial_uploads"] == 1
    b.put("k", b"y" * 100)  # fault fired once; full bytes now
    assert len(b.get("k")) == 100


def test_faulty_blobstore_unavailability_window(tmp_path):
    plan = FaultPlan.single(2, FaultKind.BLOB_UNAVAILABLE, ops=3)
    b = FaultyBlobStore(LocalBlobStore(str(tmp_path)), plan, sleep=NO_SLEEP)
    b.put("a", b"1")  # op 1: before the window
    for _ in range(4):  # op 2 opens the window; ops 3-5 inside it
        with pytest.raises(BlobUnavailableError):
            b.put("b", b"2")
    b.put("b", b"2")  # window over
    assert b.counters["unavailable_rejections"] == 4


def test_faulty_blobstore_latency_calls_sleep(tmp_path):
    slept = []
    plan = FaultPlan.single(1, FaultKind.BLOB_LATENCY, delay_s=0.123)
    b = FaultyBlobStore(LocalBlobStore(str(tmp_path)), plan,
                        sleep=slept.append)
    b.put("k", b"v")
    assert slept == [0.123]
    assert b.counters["latency_injections"] == 1


# -- FaultPlan support for the new kinds (satellite) ---------------------

def test_fault_plan_blob_kinds_round_trip():
    plan = FaultPlan([
        Fault(step=3, kind=FaultKind.BLOB_PARTIAL_UPLOAD,
              payload={"fraction": 0.25}),
        Fault(step=5, kind=FaultKind.BLOB_UNAVAILABLE, payload={"ops": 7}),
        Fault(step=1, kind=FaultKind.BLOB_TRANSIENT),
        Fault(step=2, kind=FaultKind.BLOB_LATENCY,
              payload={"delay_s": 0.5}),
    ])
    loaded = FaultPlan.from_json(plan.to_json())
    assert [(f.step, f.kind, f.payload) for f in loaded.faults] == \
        [(f.step, f.kind, f.payload) for f in plan.faults]
    single = FaultPlan.single(4, FaultKind.BLOB_PARTIAL_UPLOAD, fraction=0.1)
    reloaded = FaultPlan.from_json(single.to_json())
    assert reloaded.faults[0].kind == FaultKind.BLOB_PARTIAL_UPLOAD
    assert reloaded.faults[0].payload == {"fraction": 0.1}


def test_fault_plan_seeded_supports_blob_kinds():
    kinds = (FaultKind.BLOB_TRANSIENT, FaultKind.BLOB_UNAVAILABLE)
    a = FaultPlan.seeded(seed=7, num_steps=30, kinds=kinds, count=4)
    b = FaultPlan.seeded(seed=7, num_steps=30, kinds=kinds, count=4)
    assert [(f.step, f.kind) for f in a.faults] == \
        [(f.step, f.kind) for f in b.faults]
    assert all(f.kind in kinds for f in a.faults)
    assert a.blob_faults() == a.faults


def test_fault_plan_offload_target_separation():
    """CheckpointWriteFault with target=remote fires only on the
    uploader path; the plain kind only on local saves."""
    from flexflow_tpu.resilience import CheckpointWriteFault

    plan = FaultPlan([
        Fault(step=2, kind=FaultKind.CHECKPOINT_WRITE),
        Fault(step=2, kind=FaultKind.CHECKPOINT_WRITE,
              payload={"target": "remote"}),
    ])
    plan.check_offload(1)  # before either fault's step: silent
    with pytest.raises(CheckpointWriteFault):
        plan.check_checkpoint(2)
    plan.check_checkpoint(3)  # local fault spent; remote one untouched
    with pytest.raises(CheckpointWriteFault):
        plan.check_offload(2)
    plan.check_offload(3)  # both spent
    assert plan.remaining() == []


# -- REMOTE_LATEST protocol ---------------------------------------------

def test_remote_store_upload_verify_advance(tmp_path):
    r = RemoteCheckpointStore(LocalBlobStore(str(tmp_path)))
    assert r.list_steps() == [] and r.latest_verified_step() is None
    r.upload_step(2, _fake_step_files(2))
    r.upload_step(4, _fake_step_files(4))
    assert r.list_steps() == [2, 4]
    assert r.latest_verified_step() == 4
    # pointer is monotonic: re-uploading an older step can't regress it
    r.advance_latest(2)
    assert r.latest_verified_step() == 4
    man = r.verify_step(4)
    assert man["step"] == 4


def test_remote_store_partial_upload_never_advances_pointer(tmp_path):
    """Acceptance: a seeded partial/truncated upload leaves
    REMOTE_LATEST on the previous verified step, and the corrupted
    remote step is quarantined as a miss."""
    blob = LocalBlobStore(str(tmp_path))
    r = RemoteCheckpointStore(blob)
    r.upload_step(2, _fake_step_files(2))
    assert r.latest_verified_step() == 2
    # op 1 of the NEXT upload is state.npz: truncate it
    faulty = FaultyBlobStore(
        blob, FaultPlan.single(1, FaultKind.BLOB_PARTIAL_UPLOAD),
        sleep=NO_SLEEP,
    )
    rf = RemoteCheckpointStore(faulty)
    with pytest.raises(RemoteVerifyError):
        rf.upload_step(4, _fake_step_files(4))
    assert faulty.counters["partial_uploads"] == 1
    # pointer still on the previous verified step; step 4 quarantined
    assert r.latest_verified_step() == 2
    assert r.list_steps() == [2]
    assert blob.list("ckpt/step_00000004/") == []


def test_remote_store_prune_keeps_pointer_step(tmp_path):
    r = RemoteCheckpointStore(LocalBlobStore(str(tmp_path)))
    for s in (2, 4, 6, 8):
        r.upload_step(s, _fake_step_files(s))
    r.prune(keep=2)
    assert r.list_steps() == [6, 8]
    # pointer step survives pruning even out of the retention window
    r.advance_latest(6, force=True)
    r.prune(keep=1)
    assert 6 in r.list_steps() and r.list_steps()[-1] == 8


# -- offloader through the supervisor ------------------------------------

def test_supervised_run_mirrors_checkpoints(devices8, tmp_path):
    blob = LocalBlobStore(str(tmp_path / "remote"))
    ff = _model(devices8)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "ckpt"), checkpoint_every=2,
        offloader=_offloader(blob), sleep=NO_SLEEP,
    )
    xs, ys = _data(128)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    remote = RemoteCheckpointStore(blob)
    # anchor (0) is mirrored too; keep-last-3 remote retention
    assert remote.latest_verified_step() == 6
    assert rep.counters["offload_uploads"] >= 3
    assert rep.counters["offload_failures"] == 0
    assert rep.counters["offload_bytes"] > 0


def test_offload_cadence_and_keep(devices8, tmp_path):
    blob = LocalBlobStore(str(tmp_path / "remote"))
    ff = _model(devices8)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "ckpt"), checkpoint_every=1,
        offloader=_offloader(blob, every=2, keep=2), sleep=NO_SLEEP,
    )
    xs, ys = _data(128)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    remote = RemoteCheckpointStore(blob)
    steps = remote.list_steps()
    assert len(steps) <= 3  # keep=2 plus possibly the pointer step
    # every=2: half the publishes mirrored (anchor + every other step)
    assert rep.counters["offload_uploads"] <= 4


def test_unavailability_degrades_to_local_only(devices8, tmp_path):
    """Acceptance: an unavailability window degrades to local-only
    with a counter — it never stalls or fails the training run."""
    blob = FaultyBlobStore(
        LocalBlobStore(str(tmp_path / "remote")),
        FaultPlan.single(1, FaultKind.BLOB_UNAVAILABLE, ops=10_000),
        sleep=NO_SLEEP,
    )
    ff = _model(devices8)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "ckpt"), checkpoint_every=2,
        offloader=_offloader(
            blob, retry=RetryPolicy(max_restarts=1, base_backoff=0.0),
        ),
        sleep=NO_SLEEP,
    )
    xs, ys = _data(128)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6  # the run NEVER stalls on the mirror
    assert rep.counters["offload_unavailable"] >= 1
    assert rep.counters["offload_uploads"] == 0
    # local tier is intact: restore still works
    assert sup.manager.latest_verified_step() == 6


def test_transient_upload_errors_retry_within_budget(devices8, tmp_path):
    blob = FaultyBlobStore(
        LocalBlobStore(str(tmp_path / "remote")),
        FaultPlan([
            Fault(step=1, kind=FaultKind.BLOB_TRANSIENT),
            Fault(step=4, kind=FaultKind.BLOB_TRANSIENT),
        ]),
        sleep=NO_SLEEP,
    )
    ff = _model(devices8)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "ckpt"), checkpoint_every=2,
        offloader=_offloader(blob), sleep=NO_SLEEP,
    )
    xs, ys = _data(128)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert rep.counters["offload_retries"] >= 1
    assert rep.counters["offload_failures"] == 0
    assert RemoteCheckpointStore(blob.inner).latest_verified_step() == 6


def test_uploader_checkpoint_write_fault_retries(devices8, tmp_path):
    """Satellite: CheckpointWriteFault injection covers the uploader
    path (target=remote) without touching local saves."""
    blob = LocalBlobStore(str(tmp_path / "remote"))
    plan = FaultPlan([
        Fault(step=2, kind=FaultKind.CHECKPOINT_WRITE,
              payload={"target": "remote"}),
    ])
    ff = _model(devices8)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "ckpt"), checkpoint_every=2, fault_plan=plan,
        offloader=_offloader(blob, fault_plan=plan), sleep=NO_SLEEP,
    )
    xs, ys = _data(128)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    # local saves never failed; the upload retried past the injection
    assert rep.counters["checkpoint_failures"] == 0
    assert rep.counters["offload_retries"] >= 1
    assert RemoteCheckpointStore(blob).latest_verified_step() == 6


# -- two-tier restore ----------------------------------------------------

def test_restore_prefers_local_falls_back_per_checkpoint(devices8, tmp_path):
    """Acceptance: restore prefers local bytes; a corrupt local step
    falls back to ITS remote mirror (same step — no progress lost)
    rather than an older local step."""
    blob = LocalBlobStore(str(tmp_path / "remote"))
    ckpt = str(tmp_path / "ckpt")
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, ckpt, checkpoint_every=2,
                             offloader=_offloader(blob), sleep=NO_SLEEP)
    xs, ys = _data(128)
    sup.run(xs, ys, num_steps=6)
    w6 = ff.get_weights()
    # corrupt the newest LOCAL step's bytes
    state = os.path.join(ckpt, "step_00000006", "state.npz")
    blob_bytes = bytearray(open(state, "rb").read())
    blob_bytes[len(blob_bytes) // 2] ^= 0xFF
    with open(state, "wb") as f:
        f.write(bytes(blob_bytes))
    mgr = LocalCheckpointManager(
        ckpt, offloader=None, remote=RemoteCheckpointStore(blob),
    )
    step = mgr.restore(ff)
    assert step == 6  # the SAME step, served by the mirror
    _weights_equal(ff.get_weights(), w6)
    # and the mirror's verified bytes were re-materialized locally
    assert LocalCheckpointManager(ckpt).restore(ff) == 6


def test_fresh_host_restores_from_remote_only(devices8, tmp_path):
    blob = LocalBlobStore(str(tmp_path / "remote"))
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, str(tmp_path / "ckpt"), checkpoint_every=2,
                             offloader=_offloader(blob), sleep=NO_SLEEP)
    xs, ys = _data(128)
    sup.run(xs, ys, num_steps=4)
    w4 = ff.get_weights()
    # a brand-new host: fresh model, EMPTY local directory
    ff2 = _model(devices8)
    mgr = LocalCheckpointManager(str(tmp_path / "fresh"),
                                 remote=RemoteCheckpointStore(blob))
    assert mgr.any_restorable()
    step = mgr.restore(ff2)
    assert step == 4
    _weights_equal(ff2.get_weights(), w4)


def test_orbax_restore_prefers_newer_remote_step(devices8, tmp_path):
    """The orbax manager's default restore walks BOTH tiers newest
    first: an older local step must not win over a newer verified
    remote-only mirror (progress would silently be lost)."""
    from flexflow_tpu.checkpoint import CheckpointManager

    blob = LocalBlobStore(str(tmp_path / "remote"))
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, str(tmp_path / "ckpt"), checkpoint_every=2,
                             offloader=_offloader(blob), sleep=NO_SLEEP)
    xs, ys = _data(128)
    sup.run(xs, ys, num_steps=6)  # the mirror holds steps 2, 4, 6
    w6 = ff.get_weights()
    # an orbax directory that only ever saw step 2 (stale local tier)
    ff2 = _model(devices8, seed=1)
    mgr = CheckpointManager(str(tmp_path / "oc"),
                            remote=RemoteCheckpointStore(blob))
    mgr.save(ff2, step=2)
    step = mgr.restore(ff2)
    assert step == 6  # the newer remote-only step wins
    _weights_equal(ff2.get_weights(), w6)
    mgr.close()


def test_host_loss_drill_bit_identical(devices8, tmp_path):
    """THE acceptance drill: train with offload under a seeded mid-run
    upload fault, destroy the entire local checkpoint directory, resume
    on a fresh directory from the remote tier, and continue to weights
    BIT-IDENTICAL to an uninterrupted run — including ZeRO-1 sharded
    Adam optimizer slots."""
    def make_model():
        return _model(devices8, optimizer=AdamOptimizer(alpha=0.01),
                      weight_update_sharding=True)

    xs, ys = _data(128)
    # the uninterrupted reference: 8 steps straight through
    ref = make_model()
    ref_sup = TrainingSupervisor(ref, str(tmp_path / "ref"),
                                 checkpoint_every=0, sleep=NO_SLEEP)
    ref_rep = ref_sup.run(xs, ys, num_steps=8)
    assert ref_rep.final_step == 8

    # host A: train 6 steps with offload, a transient fault mid-run
    blob_inner = LocalBlobStore(str(tmp_path / "remote"))
    blob = FaultyBlobStore(
        blob_inner, FaultPlan.single(4, FaultKind.BLOB_TRANSIENT),
        sleep=NO_SLEEP,
    )
    ckpt_a = str(tmp_path / "host_a")
    ff_a = make_model()
    sup_a = TrainingSupervisor(ff_a, ckpt_a, checkpoint_every=2,
                               offloader=_offloader(blob), sleep=NO_SLEEP)
    rep_a = sup_a.run(xs, ys, num_steps=6)
    assert rep_a.final_step == 6
    assert rep_a.counters["offload_uploads"] >= 3

    # the host dies: local checkpoints AND the model are gone
    shutil.rmtree(ckpt_a)
    del ff_a, sup_a

    # host B: brand-new process, EMPTY directory, same remote store
    ckpt_b = str(tmp_path / "host_b")
    ff_b = make_model()
    sup_b = TrainingSupervisor(ff_b, ckpt_b, checkpoint_every=2,
                               offloader=_offloader(blob_inner),
                               sleep=NO_SLEEP)
    rep_b = sup_b.run(xs, ys, num_steps=8, resume=True)
    assert rep_b.final_step == 8
    assert rep_b.counters["restarts"] == 0  # resume, not crash-recovery

    _weights_equal(ff_b.get_weights(), ref.get_weights())
    # ZeRO-1 optimizer slots carried bit-identically too
    import jax

    _weights_equal(
        jax.tree.map(np.asarray, ff_b._opt_state),
        jax.tree.map(np.asarray, ref._opt_state),
    )


# -- strategy store fleet mirror -----------------------------------------

def _searchable_model(devices, store_root, remote_uri, seed=0):
    cfg = FFConfig(batch_size=16, num_devices=len(devices), seed=seed,
                   search_budget=5, rewrite_depth=1, rewrite_max_variants=1,
                   strategy_store=store_root, remote_store=remote_uri)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 32, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices, seed=seed)
    return ff


def test_strategy_store_fleet_mirror_warms_fresh_host(devices8, tmp_path):
    remote_uri = str(tmp_path / "blob")
    # host A: cold compile pays the search, publishes locally AND through
    ff_a = _searchable_model(devices8, str(tmp_path / "store_a"),
                             remote_uri)
    assert not ff_a.strategy.search_stats.get("store_hit")
    blob = LocalBlobStore(remote_uri)
    assert any(k.startswith("strategies/") for k in blob.list(""))
    # host B: EMPTY local store, warms from the fleet mirror — no search
    ff_b = _searchable_model(devices8, str(tmp_path / "store_b"),
                             remote_uri)
    stats = ff_b.strategy.search_stats
    assert stats.get("store_hit") and stats.get("store_remote_hit")
    assert ff_b.strategy.to_json() == ff_a.strategy.to_json()
    # the remote hit materialized locally: a third compile on host B's
    # store is a plain LOCAL hit
    ff_b2 = _searchable_model(devices8, str(tmp_path / "store_b"),
                              remote_uri)
    assert ff_b2.strategy.search_stats.get("store_hit")
    assert not ff_b2.strategy.search_stats.get("store_remote_hit")


def test_fleet_mirror_best_cost_upgrade(tmp_path):
    from flexflow_tpu.store.store import RemoteStrategyMirror

    blob = LocalBlobStore(str(tmp_path))
    mirror = RemoteStrategyMirror(blob)
    from flexflow_tpu.store.key import strategy_sha256
    from flexflow_tpu.strategy import Strategy

    def manifest_for(text, cost):
        return {
            "manifest_version": 1,
            "key_digest": "d" * 64,
            "strategy_sha256": strategy_sha256(text),
            "searched_cost": cost,
            "search_stats": {},
            "created_at": 1.0,
        }

    t1 = Strategy(mesh_axes={"data": 4}).to_json()
    t2 = Strategy(mesh_axes={"data": 8}).to_json()
    assert mirror.push("d" * 64, manifest_for(t1, 10.0), t1) is True
    # equal/worse costs lose to the incumbent
    assert mirror.push("d" * 64, manifest_for(t2, 10.0), t2) is False
    assert mirror.push("d" * 64, manifest_for(t2, 11.0), t2) is False
    # strictly better replaces
    assert mirror.push("d" * 64, manifest_for(t2, 9.0), t2) is True
    manifest, text = mirror.fetch("d" * 64)
    assert manifest["searched_cost"] == 9.0 and text == t2


def test_fleet_mirror_torn_pair_quarantined(tmp_path):
    from flexflow_tpu.store.store import RemoteStrategyMirror

    blob = LocalBlobStore(str(tmp_path))
    mirror = RemoteStrategyMirror(blob)
    from flexflow_tpu.store.key import strategy_sha256
    from flexflow_tpu.strategy import Strategy

    text = Strategy(mesh_axes={"data": 4}).to_json()
    digest = "e" * 64
    mirror.push(digest, {
        "manifest_version": 1, "key_digest": digest,
        "strategy_sha256": strategy_sha256(text), "searched_cost": None,
        "search_stats": {}, "created_at": 1.0,
    }, text)
    # tear the pair: strategy bytes no longer match the manifest sha
    blob.put(f"strategies/{digest}/strategy.json", b"{garbage")
    assert mirror.fetch(digest) is None
    # quarantined: the whole entry is gone, a future push repairs it
    assert blob.list(f"strategies/{digest}/") == []


# -- preemption barrier --------------------------------------------------

def test_preemption_barrier_single_host_is_instant(tmp_path):
    blob = LocalBlobStore(str(tmp_path))
    assert preemption_barrier(blob, "run1", 7, host_id=0, num_hosts=1,
                              sleep=NO_SLEEP) == 7
    assert blob.list("barrier/") == []  # no rendezvous needed


def test_preemption_barrier_agrees_on_max_step(tmp_path):
    """Workers at steps 5/6/6 rendezvous; everyone commits 6 — the
    newest state any host holds (laggards run forward to it; nobody
    can rewind)."""
    blob = LocalBlobStore(str(tmp_path))
    # hosts 1 and 2 post first (simulated sequentially: their barrier
    # calls would block polling, so post their records directly)
    for host, step in ((1, 6), (2, 6)):
        blob.put(f"barrier/run2/host_{host:05d}",
                 json.dumps({"host": host, "step": step}).encode())
    agreed = preemption_barrier(blob, "run2", 5, host_id=0,
                                num_hosts=3, sleep=NO_SLEEP)
    assert agreed == 6


def test_preemption_barrier_cleared_between_incarnations(tmp_path):
    """A previous incarnation's posts must never satisfy a later
    quorum: the supervisor clears barrier/<run_id>/ at run() start."""
    from flexflow_tpu.distributed import clear_preemption_barrier

    blob = LocalBlobStore(str(tmp_path))
    for host in (0, 1):
        blob.put(f"barrier/runX/host_{host:05d}",
                 json.dumps({"host": host, "step": 100}).encode())
    assert clear_preemption_barrier(blob, "runX") == 2
    assert blob.list("barrier/runX/") == []
    # with the stale posts gone, a new rendezvous must time out (no
    # peer) instead of instantly agreeing on the obsolete step 100
    agreed = preemption_barrier(blob, "runX", 500, host_id=0, num_hosts=2,
                                timeout_s=0.05, poll_s=0.01)
    assert agreed == 500


def test_preemption_runs_forward_to_agreed_step(devices8, tmp_path,
                                                monkeypatch):
    """A host behind the fleet's agreed emergency step keeps stepping
    to it before the emergency save, so every host commits the SAME
    step (the barrier's whole point)."""
    blob = LocalBlobStore(str(tmp_path / "remote"))
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, str(tmp_path / "ckpt"), checkpoint_every=2,
                             offloader=_offloader(blob), sleep=NO_SLEEP)
    rendezvous_at = []

    def fake_rendezvous(step):
        rendezvous_at.append(step)
        return step + 2  # the fleet is two steps ahead of this host

    monkeypatch.setattr(sup, "_preempt_rendezvous", fake_rendezvous)
    orig_step = ff.train_step
    calls = {"n": 0}

    def stepper(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:  # "SIGTERM" lands mid-step-1
            sup._preempt = "SIGTERM"
        return orig_step(*a, **kw)

    monkeypatch.setattr(ff, "train_step", stepper)
    xs, ys = _data(128)
    rep = sup.run(xs, ys, num_steps=8)
    assert rep.preempted == "SIGTERM"
    assert rendezvous_at == [2]  # barrier ran once, at the notice step
    assert rep.final_step == 4   # ran FORWARD to the agreed step
    # the agreed emergency step is durable in BOTH tiers
    assert sup.manager.latest_verified_step() == 4
    assert RemoteCheckpointStore(blob).latest_verified_step() == 4


def test_preemption_on_final_step_still_posts_barrier(devices8, tmp_path,
                                                      monkeypatch):
    """A SIGTERM during the FINAL step exits the run loop before the
    top-of-loop rendezvous ever runs — the host must still post, or
    its peers stall to the barrier deadline and commit a divergent
    step."""
    blob = LocalBlobStore(str(tmp_path / "remote"))
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, str(tmp_path / "ckpt"), checkpoint_every=2,
                             offloader=_offloader(blob), sleep=NO_SLEEP)
    rendezvous_at = []

    def fake_rendezvous(step):
        rendezvous_at.append(step)
        return step

    monkeypatch.setattr(sup, "_preempt_rendezvous", fake_rendezvous)
    orig_step = ff.train_step
    calls = {"n": 0}

    def stepper(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 4:  # "SIGTERM" lands during the last step
            sup._preempt = "SIGTERM"
        return orig_step(*a, **kw)

    monkeypatch.setattr(ff, "train_step", stepper)
    xs, ys = _data(128)
    rep = sup.run(xs, ys, num_steps=4)
    assert rep.preempted == "SIGTERM"
    assert rep.final_step == 4
    assert rendezvous_at == [4]  # posted at loop exit, not skipped


def test_local_blobstore_oserror_wraps_unavailable(tmp_path):
    """Filesystem trouble surfaces as BlobUnavailableError from every
    verb, so `except BlobStoreError` handlers (the supervisor's barrier
    clear, the offloader's retry classifier) see it — a raw OSError
    would crash fit_resilient at run start."""
    blob = LocalBlobStore(str(tmp_path))
    # a directory squatting on the object path defeats put and delete
    (tmp_path / "ckpt" / "obj").mkdir(parents=True)
    with pytest.raises(BlobUnavailableError):
        blob.put("ckpt/obj", b"data")
    with pytest.raises(BlobUnavailableError):
        blob.delete("ckpt/obj")


def test_fleet_mirror_orphan_manifest_repaired(tmp_path):
    """A manifest without its strategy.json (a quarantine raced a
    concurrent push) must be quarantined on fetch — left in place,
    push()'s first-write-wins would honor the orphan forever and the
    key would be a permanent fleet-wide miss."""
    from flexflow_tpu.store.key import strategy_sha256
    from flexflow_tpu.store.store import RemoteStrategyMirror
    from flexflow_tpu.strategy import Strategy

    blob = LocalBlobStore(str(tmp_path))
    mirror = RemoteStrategyMirror(blob)
    text = Strategy(mesh_axes={"data": 4}).to_json()
    digest = "f" * 64
    manifest = {
        "manifest_version": 1, "key_digest": digest,
        "strategy_sha256": strategy_sha256(text), "searched_cost": None,
        "search_stats": {}, "created_at": 1.0,
    }
    blob.put(f"strategies/{digest}/manifest.json",
             json.dumps(manifest).encode())
    assert mirror.fetch(digest) is None
    assert blob.list(f"strategies/{digest}/") == []  # orphan quarantined
    assert mirror.push(digest, manifest, text) is True  # repair succeeds
    assert mirror.fetch(digest) == (manifest, text)


def test_force_resubmit_after_abandoned_upload(tmp_path):
    """An emergency force-mirror of a step whose earlier upload was
    abandoned (outage past the retry budget) must re-upload, not hit
    the queued-step dedupe."""
    inner = LocalBlobStore(str(tmp_path))
    faulty = FaultyBlobStore(
        inner, FaultPlan.single(1, FaultKind.BLOB_TRANSIENT),
        sleep=NO_SLEEP,
    )
    off = CheckpointOffloader(
        RemoteCheckpointStore(faulty),
        retry=RetryPolicy(max_restarts=0, base_backoff=0.0), sleep=NO_SLEEP,
    )
    files = _fake_step_files(4)
    assert off.maybe_submit(4, files) is True
    off.drain()
    assert off.counters["offload_failures"] == 1  # abandoned: zero budget
    assert RemoteCheckpointStore(inner).latest_verified_step() is None
    # the store recovers; the emergency force-mirror gets its retry
    assert off.maybe_submit(4, files, force=True) is True
    off.drain()
    assert RemoteCheckpointStore(inner).latest_verified_step() == 4
    # a force re-submit of an ALREADY-mirrored step is a no-op
    assert off.maybe_submit(4, files, force=True) is False


def test_barrier_timeout_threaded_from_config(devices8, tmp_path):
    ff = _model(devices8, barrier_timeout=2.5)
    sup = TrainingSupervisor(ff, str(tmp_path / "c"), sleep=NO_SLEEP)
    assert sup.barrier_timeout == 2.5


def test_force_submit_skips_already_queued_duplicate(tmp_path):
    """An emergency force-submit racing the cadence upload of the SAME
    step must not upload the payload twice — the duplicate job skips at
    execution time once the first lands verified (the grace window is
    too precious to re-upload identical bytes)."""
    inner = LocalBlobStore(str(tmp_path))
    off = CheckpointOffloader(
        RemoteCheckpointStore(inner),
        retry=RetryPolicy(max_restarts=3, base_backoff=0.0), sleep=NO_SLEEP,
    )
    files = _fake_step_files(2)
    assert off.maybe_submit(2, files) is True            # cadence upload
    assert off.maybe_submit(2, files, force=True) is True  # emergency
    off.drain()
    assert off.counters["offload_uploads"] == 1
    assert RemoteCheckpointStore(inner).latest_verified_step() == 2


def test_upload_rejects_unmanifested_leaf(tmp_path):
    """A state.npz leaf the manifest can't vouch for must fail the
    upload verify — restore refuses such a leaf, so blessing it would
    advance REMOTE_LATEST to a step that cannot actually restore."""
    store = RemoteCheckpointStore(LocalBlobStore(str(tmp_path)))
    files = _fake_step_files(3)
    with np.load(io.BytesIO(files["state.npz"])) as d:
        arrays = {k: d[k] for k in d.files}
    arrays["rogue"] = np.ones(3, np.float32)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    files["state.npz"] = buf.getvalue()
    with pytest.raises(RemoteVerifyError, match="rogue"):
        store.upload_step(3, files)
    assert store.latest_verified_step() is None


def test_preemption_barrier_times_out_conservatively(tmp_path):
    """A quorum that never completes returns the best agreement so far
    instead of hanging through the preemption deadline."""
    blob = LocalBlobStore(str(tmp_path))
    agreed = preemption_barrier(blob, "run3", 9, host_id=0, num_hosts=2,
                                timeout_s=0.05, poll_s=0.01)
    assert agreed == 9  # only our own post: agree with ourselves


# -- fsck tool -----------------------------------------------------------

def test_checkpoint_fsck_clean_and_corrupt(devices8, tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "checkpoint_fsck",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "checkpoint_fsck.py"),
    )
    fsck = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fsck)

    blob_root = str(tmp_path / "remote")
    ckpt = str(tmp_path / "ckpt")
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, ckpt, checkpoint_every=2,
                             offloader=_offloader(LocalBlobStore(blob_root)),
                             sleep=NO_SLEEP)
    xs, ys = _data(128)
    sup.run(xs, ys, num_steps=4)

    assert fsck.main([ckpt, "--remote", blob_root]) == 0

    # corrupt one local leaf -> nonzero exit, the step named
    state = os.path.join(ckpt, "step_00000004", "state.npz")
    raw = bytearray(open(state, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(state, "wb") as f:
        f.write(bytes(raw))
    assert fsck.main([ckpt, "--remote", blob_root]) == 1

    # dangling LATEST in an otherwise-empty dir
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    with open(os.path.join(empty, "LATEST"), "w") as f:
        f.write("42")
    assert fsck.main([empty]) == 1


# -- telemetry: the Durability summary section ---------------------------

def test_telemetry_summary_renders_durability_section(devices8, tmp_path):
    import subprocess
    import sys

    trace_dir = tmp_path / "trace"
    blob = LocalBlobStore(str(tmp_path / "remote"))
    ff = _model(devices8, trace_dir=str(trace_dir))
    offl = _offloader(blob, registry=ff.telemetry.metrics)
    sup = TrainingSupervisor(ff, str(tmp_path / "ckpt"), checkpoint_every=2,
                             offloader=offl, sleep=NO_SLEEP)
    xs, ys = _data(128)
    rep = sup.run(xs, ys, num_steps=4)
    assert rep.counters["offload_uploads"] >= 2
    ff.telemetry.flush()
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "telemetry_summary.py"),
         str(trace_dir)],
        capture_output=True, text=True, check=True,
    ).stdout
    assert "Durability" in out
    assert "offload_uploads" in out and "offload_bytes" in out
    assert "offload_upload_ms" in out


# -- per-leaf delta mirror (ISSUE 12 satellite) ---------------------------

def _multi_leaf_files(step, a_value, b_value):
    leaves = {"['weights']['a']['k']": np.full(8, a_value, np.float32),
              "['weights']['b']['k']": np.full(8, b_value, np.float32)}
    buf = io.BytesIO()
    np.savez(buf, **leaves)
    manifest = {
        "manifest_version": 1, "step": step,
        "leaves": {
            k: {
                "crc32": zlib.crc32(
                    np.ascontiguousarray(v).view(np.uint8).reshape(-1)
                ),
                "bytes": int(v.nbytes), "shape": [8], "dtype": "float32",
            }
            for k, v in leaves.items()
        },
    }
    return {
        "state.npz": buf.getvalue(),
        "meta.json": json.dumps({"step": step}).encode(),
        "manifest.json": json.dumps(manifest).encode(),
    }


def test_delta_mirror_skips_unchanged_leaves(tmp_path):
    """The second upload drops the leaf whose crc matched the previous
    mirrored step, annotates it in the remote manifest, and restore
    reassembles the FULL step bit-identically."""
    from flexflow_tpu.resilience.offload import RemoteCheckpointStore

    r = RemoteCheckpointStore(LocalBlobStore(str(tmp_path)))
    rep1 = r.upload_step(2, _multi_leaf_files(2, 1.0, 5.0))
    assert rep1.leaves_skipped == 0
    # leaf 'a' unchanged, leaf 'b' changed
    rep2 = r.upload_step(4, _multi_leaf_files(4, 1.0, 7.0),
                         base_step=2, base_manifest=rep1.manifest)
    assert rep2.leaves_skipped == 1
    assert rep2.bytes_uploaded < rep1.bytes_uploaded
    # the remote state.npz really lacks the unchanged leaf
    raw = r.blob.get(r._step_prefix(4) + "state.npz")
    with np.load(io.BytesIO(raw)) as data:
        assert list(data.files) == ["['weights']['b']['k']"]
    # verify passes (base vouches for the delta leaf)...
    man = r.verify_step(4)
    assert man["leaves"]["['weights']['a']['k']"]["base_step"] == 2
    # ...and download reassembles a SELF-CONTAINED full step
    files = r.download_step(4)
    with np.load(io.BytesIO(files["state.npz"])) as data:
        np.testing.assert_array_equal(
            data["['weights']['a']['k']"], np.full(8, 1.0, np.float32))
        np.testing.assert_array_equal(
            data["['weights']['b']['k']"], np.full(8, 7.0, np.float32))
    out_man = json.loads(files["manifest.json"])
    assert "base_step" not in out_man["leaves"]["['weights']['a']['k']"]


def test_delta_mirror_prune_keeps_referenced_base(tmp_path):
    """keep-last-1 pruning must NOT delete the base step a kept delta
    still resolves its leaves through."""
    from flexflow_tpu.resilience.offload import RemoteCheckpointStore

    r = RemoteCheckpointStore(LocalBlobStore(str(tmp_path)))
    rep1 = r.upload_step(2, _multi_leaf_files(2, 1.0, 5.0))
    r.upload_step(4, _multi_leaf_files(4, 1.0, 7.0),
                  base_step=2, base_manifest=rep1.manifest)
    r.prune(keep=1)
    assert r.list_steps() == [2, 4]  # base survives the prune
    files = r.download_step(4)      # and the delta still reassembles
    with np.load(io.BytesIO(files["state.npz"])) as data:
        assert len(data.files) == 2


def test_delta_chain_reanchors_at_bound(tmp_path):
    """A delta chain re-uploads the full step once the bound is hit, so
    restores never chase unbounded base chains."""
    from flexflow_tpu.resilience.offload import (
        MAX_DELTA_CHAIN, RemoteCheckpointStore,
    )

    r = RemoteCheckpointStore(LocalBlobStore(str(tmp_path)))
    rep = r.upload_step(0, _multi_leaf_files(0, 1.0, 0.0))
    step, deltas = 0, []
    for i in range(1, MAX_DELTA_CHAIN + 3):
        step = 2 * i
        rep2 = r.upload_step(step, _multi_leaf_files(step, 1.0, float(i)),
                             base_step=step - 2, base_manifest=rep.manifest)
        deltas.append(rep2.leaves_skipped > 0)
        rep = rep2
    # MAX deltas, then one full re-anchor, then the chain restarts
    assert deltas == [True] * MAX_DELTA_CHAIN + [False, True]
    files = r.download_step(step)
    with np.load(io.BytesIO(files["state.npz"])) as data:
        assert len(data.files) == 2


def test_offloader_counts_skipped_leaves(tmp_path):
    """End to end through the offloader thread: the second cadence
    upload skips the unchanged leaf and counts it."""
    from flexflow_tpu.resilience.offload import (
        CheckpointOffloader, RemoteCheckpointStore,
    )

    r = RemoteCheckpointStore(LocalBlobStore(str(tmp_path)))
    off = CheckpointOffloader(r, every=1, keep=3, sleep=NO_SLEEP)
    try:
        off.maybe_submit(2, _multi_leaf_files(2, 1.0, 5.0))
        off.drain()
        off.maybe_submit(4, _multi_leaf_files(4, 1.0, 7.0))
        off.drain()
    finally:
        off.close()
    assert off.counters["offload_uploads"] == 2
    assert off.counters["offload_leaves_skipped"] == 1
    assert r.latest_verified_step() == 4


def test_delta_mirror_prune_aborts_on_unreadable_manifest(tmp_path):
    """A transient store fault while resolving a kept delta's bases
    must SKIP the prune round, not delete the base (review finding:
    deleting it would leave REMOTE_LATEST unrestorable)."""
    from flexflow_tpu.resilience.offload import RemoteCheckpointStore
    from flexflow_tpu.store.blobstore import BlobUnavailableError

    blob = LocalBlobStore(str(tmp_path))
    r = RemoteCheckpointStore(blob)
    rep1 = r.upload_step(2, _multi_leaf_files(2, 1.0, 5.0))
    r.upload_step(4, _multi_leaf_files(4, 1.0, 7.0),
                  base_step=2, base_manifest=rep1.manifest)

    real_get = blob.get

    def flaky_get(key):
        if key.endswith("step_00000004/manifest.json"):
            raise BlobUnavailableError("store blip")
        return real_get(key)

    blob.get = flaky_get
    try:
        assert r.prune(keep=1) == 0  # aborted, nothing deleted
    finally:
        blob.get = real_get
    assert r.list_steps() == [2, 4]
    files = r.download_step(4)  # base intact: delta still reassembles
    with np.load(io.BytesIO(files["state.npz"])) as data:
        assert len(data.files) == 2


def test_delta_chain_flattens_to_the_anchor_step(tmp_path):
    """Delta annotations point at the step that HOLDS the bytes (the
    anchor), not the immediately previous delta — one base fetch per
    restore, and prune retains anchors only (review finding)."""
    from flexflow_tpu.resilience.offload import RemoteCheckpointStore

    r = RemoteCheckpointStore(LocalBlobStore(str(tmp_path)))
    rep = r.upload_step(0, _multi_leaf_files(0, 1.0, 0.0))
    for i in (1, 2, 3):
        rep = r.upload_step(2 * i, _multi_leaf_files(2 * i, 1.0, float(i)),
                            base_step=2 * (i - 1), base_manifest=rep.manifest)
    man = json.loads(
        r.blob.get(r._step_prefix(6) + "manifest.json")
    )
    # leaf 'a' unchanged since step 0: annotated straight to the anchor
    assert man["leaves"]["['weights']['a']['k']"]["base_step"] == 0
    assert r._base_steps_of(6) == [0]
