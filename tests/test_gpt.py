"""Decoder-only causal LM (models/transformer.py build_gpt).

Covers: next-token training convergence on the CPU mesh, causality of
the logits (token t's logits must not depend on tokens > t), the
dp x tp / dp x sp strategies reusing the bert helpers (causal ring
attention under a sharded sequence), and dp x pp GPipe over the
decoder blocks.
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only


from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.transformer import (
    bert_sp_strategy,
    bert_tp_strategy,
    build_gpt,
)


def _data(rng, n, seq, vocab):
    start = rng.randint(0, vocab, (n, 1))
    step = rng.randint(1, 6, (n, 1))
    seq_ids = (start + step * np.arange(seq + 1)) % vocab
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(seq, dtype=np.int32), (n, seq)).copy()
    return ids, pos, labels


def _build(devices, n_dev, batch, seq=16, vocab=32, strategy=None,
           num_layers=2, lr=0.5):
    ff = FFModel(FFConfig(batch_size=batch, num_devices=n_dev))
    build_gpt(ff, batch_size=batch, seq_length=seq, hidden_size=32,
              num_layers=num_layers, num_heads=4, intermediate_size=64,
              vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=lr),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy=strategy, devices=devices[:n_dev])
    return ff


def test_gpt_next_token_training(devices8):
    rng = np.random.RandomState(0)
    ff = _build(devices8, 1, batch=16)
    ids, pos, labels = _data(rng, 16, 16, 32)
    losses = [
        float(ff.train_step({"input": ids, "positions": pos}, labels)["loss"])
        for _ in range(30)
    ]
    # a modular progression is fully predictable: the causal LM must
    # drive next-token loss well below the uniform floor log(32)=3.47
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_gpt_causality(devices8):
    """Perturbing a future token must not change earlier logits."""
    rng = np.random.RandomState(1)
    ff = _build(devices8, 1, batch=2)
    ids, pos, _ = _data(rng, 2, 16, 32)
    base = np.asarray(ff.forward({"input": ids, "positions": pos}))
    ids2 = ids.copy()
    ids2[:, 10:] = (ids2[:, 10:] + 7) % 32
    pert = np.asarray(ff.forward({"input": ids2, "positions": pos}))
    np.testing.assert_allclose(base[:, :10], pert[:, :10],
                               rtol=1e-5, atol=1e-5)
    assert np.abs(base[:, 10:] - pert[:, 10:]).max() > 1e-3


@pytest.mark.parametrize("strategy_fn", [
    lambda: bert_tp_strategy(8, tp=2, num_layers=2),
    lambda: bert_sp_strategy(8, sp=4),
], ids=["dp4xtp2", "dp2xsp4"])
def test_gpt_parallel_matches_single(devices8, strategy_fn):
    rng = np.random.RandomState(2)
    ids, pos, labels = _data(rng, 8, 16, 32)
    ff1 = _build(devices8, 1, batch=8)
    ffN = _build(devices8, 8, batch=8, strategy=strategy_fn())
    out1 = np.asarray(ff1.forward({"input": ids, "positions": pos}))
    outN = np.asarray(ffN.forward({"input": ids, "positions": pos}))
    np.testing.assert_allclose(out1, outN, rtol=2e-4, atol=2e-4)
    m = ffN.train_step({"input": ids, "positions": pos}, labels)
    assert np.isfinite(float(m["loss"]))


def test_gpt_pipeline_strategy(devices8):
    """Causal LM under dp2 x pp4 GPipe: the fourth parallelism family
    (after dp/tp/sp) composing with the decoder blocks."""
    from flexflow_tpu.strategy import Strategy

    rng = np.random.RandomState(3)
    batch = 8
    s = Strategy(
        mesh_axes={"data": 2, "pipe": 4},
        pipeline={"degree": 4, "num_microbatches": 4, "axis": "pipe",
                  "dp_axis": "data"},
    )
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 2})]
    ff = _build(devices8, 8, batch=batch, strategy=s, num_layers=4, lr=0.3)
    ids, pos, labels = _data(rng, batch, 16, 32)
    losses = [
        float(ff.train_step({"input": ids, "positions": pos}, labels)["loss"])
        for _ in range(10)
    ]
    assert losses[-1] < losses[0], losses


def test_gpt_generate_greedy_and_sampled(devices8):
    """Autoregressive generation on the fixed-shape GPT graph: the
    prompt is preserved, new ids are valid, greedy decoding is
    deterministic, causal masking makes right-padding irrelevant
    (generating from a shorter prompt prefix of the same ids yields the
    same first continuation token), and temperature sampling runs."""
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt, gpt_generate

    V, S = 32, 12
    ff = FFModel(FFConfig(batch_size=4, num_devices=1))
    build_gpt(ff, batch_size=4, seq_length=S, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])

    rs = np.random.RandomState(0)
    prompt = rs.randint(1, V, size=(4, 5)).astype(np.int32)
    out = gpt_generate(ff, prompt, max_new_tokens=4)
    assert out.shape == (4, 9)
    np.testing.assert_array_equal(out[:, :5], prompt)
    assert (out >= 0).all() and (out < V).all()
    # greedy is deterministic
    np.testing.assert_array_equal(out, gpt_generate(ff, prompt, 4))
    # causal masking: the step-5 next-token distribution must not
    # depend on buffer content at positions >= 5 — compare forwards on
    # zero-padded vs junk-padded suffixes
    pos = np.tile(np.arange(S, dtype=np.int32), (4, 1))
    buf_zero = np.zeros((4, S), np.int32)
    buf_zero[:, :5] = prompt
    buf_junk = buf_zero.copy()
    buf_junk[:, 5:] = rs.randint(1, V, size=(4, S - 5))
    lz = np.asarray(ff.forward({"input": buf_zero, "positions": pos}))
    lj = np.asarray(ff.forward({"input": buf_junk, "positions": pos}))
    np.testing.assert_allclose(lz[:, 4], lj[:, 4], rtol=2e-5, atol=2e-5)
    # temperature path runs and stays in-vocab
    s1 = gpt_generate(ff, prompt, 4, temperature=1.0, seed=1)
    assert s1.shape == (4, 9) and (s1 < V).all()


def test_gpt_sampling_filters(devices8):
    """top_k / top_p filtering: top_k=1 at any temperature reproduces
    greedy exactly; top_p in (0,1) stays in-vocab and deterministic
    under a fixed seed; filters are no-ops at temperature 0."""
    import numpy as np

    from flexflow_tpu.models.transformer import build_gpt, gpt_generate

    V, S = 32, 12
    ff = _build(devices8, 1, batch=4, seq=S, vocab=V)
    rs = np.random.RandomState(3)
    prompt = rs.randint(1, V, size=(4, 5)).astype(np.int32)
    greedy = gpt_generate(ff, prompt, 4)
    topk1 = gpt_generate(ff, prompt, 4, temperature=1.0, seed=7, top_k=1)
    np.testing.assert_array_equal(greedy, topk1)
    nucleus = gpt_generate(ff, prompt, 4, temperature=1.0, seed=7, top_p=0.8)
    assert nucleus.shape == (4, 9) and (nucleus >= 0).all() and (nucleus < V).all()
    np.testing.assert_array_equal(
        nucleus, gpt_generate(ff, prompt, 4, temperature=1.0, seed=7, top_p=0.8))
    # tiny nucleus collapses to near-greedy head: still valid ids
    tight = gpt_generate(ff, prompt, 4, temperature=1.0, seed=7,
                         top_k=4, top_p=0.05)
    assert (tight >= 0).all() and (tight < V).all()


def test_gpt_beam_search(devices8):
    """Beam search: beam=1 equals greedy; a wider beam's sequence
    log-prob is >= the greedy sequence's (beam keeps the greedy path as
    a candidate at every step); eos freezing stops expansion."""
    import numpy as np

    from flexflow_tpu.models.transformer import (
        build_gpt,
        gpt_beam_search,
        gpt_generate,
    )

    V, S = 32, 12
    ff = _build(devices8, 1, batch=4, seq=S, vocab=V)
    rs = np.random.RandomState(5)
    prompt = rs.randint(1, V, size=(1, 5)).astype(np.int32)

    toks1, score1 = gpt_beam_search(ff, prompt, max_new_tokens=4, beam_size=1)
    greedy = gpt_generate(ff, np.repeat(prompt, 4, axis=0), 4)[0]
    np.testing.assert_array_equal(toks1, greedy)

    toks3, score3 = gpt_beam_search(ff, prompt, max_new_tokens=4, beam_size=3)
    assert toks3.shape == toks1.shape
    np.testing.assert_array_equal(toks3[:5], prompt[0])
    assert (toks3 >= 0).all() and (toks3 < V).all()
    assert np.isfinite(score3)
    # (no >= greedy-score assertion: beam search may legitimately prune
    # the greedy path, so monotonicity in beam width is not an invariant)

    # length penalty runs and returns a valid hypothesis
    tlp, _ = gpt_beam_search(ff, prompt, 4, beam_size=3, length_penalty=0.6)
    assert tlp.shape == toks1.shape

    # an eos id freezes beams: emitted suffix after an eos stays padding
    te, _ = gpt_beam_search(ff, prompt, 6, beam_size=3,
                            eos_id=int(toks1[5]))
    hit = np.where(te[5:] == int(toks1[5]))[0]
    if hit.size:
        assert (te[5 + hit[0] + 1:] == 0).all()

    # beam wider than the compiled batch is rejected
    import pytest as _pytest
    with _pytest.raises(ValueError):
        gpt_beam_search(ff, prompt, 2, beam_size=5)
