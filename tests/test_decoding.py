"""KV-cache incremental decoding (flexflow_tpu/decoding.py).

The decode twin must reproduce the O(T^2) re-forward generation
exactly: same weights, same math, one attention row at a time.  Covers
the host-loop driver, the single-program lax.scan driver, weight
transfer/introspection, and cache-state reset between sequences.
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only


from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.decoding import (
    gpt_beam_search_cached,
    gpt_generate_cached,
    gpt_generate_scan,
    make_gpt_decoder,
)
from flexflow_tpu.models.transformer import (
    build_gpt,
    gpt_beam_search,
    gpt_generate,
)

V, S, B = 32, 12, 4


def _trained_gpt(devices8, steps=40):
    ff = FFModel(FFConfig(batch_size=B, num_devices=1))
    build_gpt(ff, batch_size=B, seq_length=S, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    rng = np.random.RandomState(0)
    start = rng.randint(0, V, (B, 1))
    step = rng.randint(1, 6, (B, 1))
    seq_ids = (start + step * np.arange(S + 1)) % V
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
    for _ in range(steps):
        ff.train_step({"input": ids, "positions": pos}, labels)
    return ff, ids


def test_cached_decode_matches_full_forward(devices8):
    ff, ids = _trained_gpt(devices8)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    prompt = ids[:, :5]
    full = gpt_generate(ff, prompt, max_new_tokens=6)
    cached = gpt_generate_cached(ffd, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(full, cached)


def test_scan_decode_matches_full_forward(devices8):
    ff, ids = _trained_gpt(devices8)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    prompt = ids[:, :5]
    full = gpt_generate(ff, prompt, max_new_tokens=6)
    scanned = gpt_generate_scan(ffd, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(full, scanned)


def test_cache_reset_between_sequences(devices8):
    """A second generation with a different prompt must not see stale
    cache rows from the first."""
    ff, ids = _trained_gpt(devices8)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    p1, p2 = ids[:, :5], ids[:, 3:8]
    out2_fresh = gpt_generate_cached(ffd, p2, 4)
    _ = gpt_generate_cached(ffd, p1, 4)
    out2_again = gpt_generate_cached(ffd, p2, 4)
    np.testing.assert_array_equal(out2_fresh, out2_again)


def test_cached_sampling_runs(devices8):
    ff, ids = _trained_gpt(devices8, steps=5)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    prompt = ids[:, :4]
    out = gpt_generate_cached(ffd, prompt, 5, temperature=0.8,
                              top_k=8, top_p=0.9, seed=3)
    assert out.shape == (B, 9)
    assert (out >= 0).all() and (out < V).all()
    np.testing.assert_array_equal(out[:, :4], prompt)
    # scan path with temperature
    s = gpt_generate_scan(ffd, prompt, 5, temperature=0.8, seed=3)
    assert s.shape == (B, 9) and (s >= 0).all() and (s < V).all()


def test_decoder_introspection_rejects_non_gpt(devices8):
    ff = FFModel(FFConfig(batch_size=2, num_devices=1))
    x = ff.create_tensor([2, 8], name="x")
    ff.dense(x, 4)
    with pytest.raises(ValueError):
        make_gpt_decoder(ff)


def test_decode_graph_rejects_kv_append():
    """decode mode refuses add_bias_kv/add_zero_attn (the cache layout
    has no slot for appended bias rows)."""
    from flexflow_tpu.ops.op import ShapeError

    ff = FFModel(FFConfig(batch_size=2, num_devices=1))
    t = ff.create_tensor([2, 1, 32], name="x")
    with pytest.raises(ShapeError):
        ff.multihead_attention(t, t, t, 32, 4, add_bias_kv=True,
                               decode_max_seq=16)


def test_forward_refuses_decode_graph(devices8):
    """forward()/eval on a decode graph would drop the cache updates
    and compute against cache_pos=0 forever — it must raise."""
    ff, ids = _trained_gpt(devices8, steps=1)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    with pytest.raises(RuntimeError, match="decode_step"):
        ffd.forward({"input": ids[:, :1],
                     "positions": np.zeros((B, 1), np.int32)})


def test_decode_guard_syncs_from_device_state(devices8):
    """The host-side overflow-guard counter rebuilds from the device
    cache_pos after an external state swap (checkpoint restore path)."""
    ff, ids = _trained_gpt(devices8, steps=1)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    ffd.reset_decode_state()
    for t in range(3):
        ffd.decode_step({"input": ids[:, t:t + 1],
                         "positions": np.full((B, 1), t, np.int32)})
    saved = ffd._state
    ffd.reset_decode_state()
    ffd._state = saved          # external swap, shadow counter stale at 0
    ffd.sync_decode_pos()       # what checkpoint.restore now does
    assert ffd._decode_pos == 3


def test_decode_cache_uses_compute_dtype(devices8):
    """KV caches materialize in the compute dtype (bf16) — an f32 cache
    would double HBM footprint and cast the whole cache every token."""
    import jax.numpy as jnp

    from flexflow_tpu.models.transformer import build_gpt

    ff = FFModel(FFConfig(batch_size=2, num_devices=1,
                          compute_dtype="bfloat16"))
    build_gpt(ff, batch_size=2, seq_length=8, hidden_size=16,
              num_layers=1, num_heads=2, intermediate_size=32,
              vocab_size=V, decode_max_seq=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    caches = [v for entries in ff._state.values()
              for k, v in entries.items() if k in ("k_cache", "v_cache")]
    assert caches and all(c.dtype == jnp.bfloat16 for c in caches)


def test_scan_generate_one_program_per_total(devices8):
    """Prompt length is a traced operand: two different plens with the
    same total reuse one compiled scan program."""
    ff, ids = _trained_gpt(devices8, steps=1)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    gpt_generate_scan(ffd, ids[:, :4], max_new_tokens=5)   # total 9
    gpt_generate_scan(ffd, ids[:, :6], max_new_tokens=3)   # total 9
    assert len(ffd._scan_gen_cache) == 1
    # and the varying-plen outputs still match the host-loop driver
    a = gpt_generate_scan(ffd, ids[:, :6], max_new_tokens=3)
    b = gpt_generate_cached(ffd, ids[:, :6], max_new_tokens=3)
    np.testing.assert_array_equal(a, b)


def test_cached_beam_search_matches_full_forward(devices8):
    """The O(T) KV-cached beam search reproduces the O(T^2) reference
    path exactly: same tokens, same score (single prompt)."""
    ff, ids = _trained_gpt(devices8)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])  # batch B=4 beams
    prompt = ids[:1, :5]
    want_toks, want_score = gpt_beam_search(ff, prompt, max_new_tokens=6,
                                            beam_size=4)
    got_toks, got_scores = gpt_beam_search_cached(
        ffd, prompt, max_new_tokens=6, beam_size=4)
    np.testing.assert_array_equal(got_toks[0], want_toks)
    assert abs(got_scores[0] - want_score) < 1e-4


def test_cached_beam_search_eos_and_length_penalty(devices8):
    """eos freezing and GNMT length normalization agree with the
    reference path (frozen beams compete at their final score)."""
    ff, ids = _trained_gpt(devices8)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    prompt = ids[:1, :4]
    eos = int(ids[0, 6])  # an id the greedy continuation will hit
    want_toks, want_score = gpt_beam_search(
        ff, prompt, max_new_tokens=7, beam_size=4,
        length_penalty=0.6, eos_id=eos)
    got_toks, got_scores = gpt_beam_search_cached(
        ffd, prompt, max_new_tokens=7, beam_size=4,
        length_penalty=0.6, eos_id=eos)
    np.testing.assert_array_equal(got_toks[0], want_toks)
    assert abs(got_scores[0] - want_score) < 1e-4


def test_cached_beam_search_batched_prompts(devices8):
    """A batch of prompts decodes in one pass and matches per-prompt
    full-forward beam search (cache-row reordering keeps each row's
    cache consistent with its hypothesis)."""
    ff, ids = _trained_gpt(devices8)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])  # batch 4 = 2x2
    prompts = np.stack([ids[0, :5], ids[2, 1:6]])
    got_toks, got_scores = gpt_beam_search_cached(
        ffd, prompts, max_new_tokens=5, beam_size=2)
    for p in range(2):
        want_toks, want_score = gpt_beam_search(
            ff, prompts[p], max_new_tokens=5, beam_size=2)
        np.testing.assert_array_equal(got_toks[p], want_toks)
        assert abs(got_scores[p] - want_score) < 1e-4


def test_decode_overflow_guard(devices8):
    """Stepping past decode_max_seq raises instead of silently
    clamping the cache write (device dynamic_update_slice clamps)."""
    ff, ids = _trained_gpt(devices8, steps=1)
    ffd = make_gpt_decoder(ff, devices=devices8[:1])
    ffd.reset_decode_state()
    for t in range(S):
        ffd.decode_step({"input": ids[:, t:t + 1],
                         "positions": np.full((B, 1), t, np.int32)})
    with pytest.raises(ValueError, match="decode_max_seq"):
        ffd.decode_step({"input": ids[:, :1],
                         "positions": np.full((B, 1), S - 1, np.int32)})
    ffd.reset_decode_state()  # guard resets with the caches
    ffd.decode_step({"input": ids[:, :1],
                     "positions": np.zeros((B, 1), np.int32)})
