"""LSTM op + NMT seq2seq tests (reference nmt/ legacy subtree) and the
Keras dataset loaders."""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models.nmt import build_nmt
import pytest

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only


def test_lstm_op_shapes_and_numerics(devices8):
    cfg = FFConfig(batch_size=8, num_devices=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 5, 6], name="x")
    t = ff.lstm(x, 12, return_sequences=True)
    assert t.shape.logical_shape == (8, 5, 12)
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8)
    xs = np.random.RandomState(0).randn(8, 5, 6).astype(np.float32)
    out = np.asarray(ff.forward({"x": xs}))
    assert out.shape == (8, 5, 12)
    assert np.isfinite(out).all()
    # tanh-bounded cell output
    assert np.abs(out).max() <= 1.0

    # last-step-only variant agrees with the full-sequence one
    ff2 = FFModel(FFConfig(batch_size=8, num_devices=1, seed=cfg.seed))
    x2 = ff2.create_tensor([8, 5, 6], name="x")
    ff2.lstm(x2, 12, return_sequences=False)
    ff2.compile(optimizer=SGDOptimizer(lr=0.01),
                devices=devices8[:1], seed=0)


def test_lstm_gradients_flow(devices8):
    cfg = FFConfig(batch_size=8, num_devices=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 4, 6], name="x")
    t = ff.lstm(x, 8)
    t = ff.mean(t, axes=[1])
    t = ff.dense(t, 3)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices8)
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 4, 6).astype(np.float32)
    ys = (xs.mean(axis=(1, 2)) > 0).astype(np.int32)
    hist = ff.fit(xs, ys, epochs=8, verbose=False)
    assert hist[-1].sparse_cce_loss < hist[0].sparse_cce_loss


def test_nmt_seq2seq_trains(devices8):
    cfg = FFConfig(batch_size=8, num_devices=8)
    ff = FFModel(cfg)
    build_nmt(ff, batch_size=8, src_len=6, tgt_len=6, src_vocab=50,
              tgt_vocab=40, embed_dim=16, hidden_size=16, num_layers=1)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices8)
    rng = np.random.RandomState(0)
    n = 32
    src = rng.randint(0, 50, size=(n, 6)).astype(np.int32)
    # copy-task labels: target tokens shifted source (learnable signal)
    tgt_in = rng.randint(0, 40, size=(n, 6)).astype(np.int32)
    labels = tgt_in  # predict the teacher-forced input (identity task)
    m = ff.train_step({"src": src[:8], "tgt": tgt_in[:8]}, labels[:8])
    assert np.isfinite(float(m["loss"]))
    hist = ff.fit({"src": src, "tgt": tgt_in}, labels, epochs=5, verbose=False)
    assert hist[-1].sparse_cce_loss < hist[0].sparse_cce_loss


def test_keras_datasets_synthetic_shapes():
    from flexflow_tpu.keras import datasets

    (xtr, ytr), (xte, yte) = datasets.cifar10.load_data(num_samples=64)
    assert xtr.shape == (64, 3, 32, 32) and xtr.dtype == np.uint8
    assert ytr.shape == (64, 1) and set(np.unique(ytr)) <= set(range(10))

    (xm, ym), _ = datasets.mnist.load_data(num_samples=32)
    assert xm.shape == (32, 28, 28) and ym.shape == (32,)

    (xr, yr), _ = datasets.reuters.load_data(num_words=1000, maxlen=50,
                                             num_samples=16)
    assert xr.shape == (16, 50) and xr.max() < 1000
    assert yr.max() < 46

    # deterministic across calls
    (xtr2, ytr2), _ = datasets.cifar10.load_data(num_samples=64)
    np.testing.assert_array_equal(xtr, xtr2)


def test_nmt_attention_trains_and_decodes(devices8):
    """The attention NMT (Luong dot-product over encoder states, built
    from first-class PCG ops) trains on a next-token copy task; the
    greedy decoding loop runs the compiled graph autoregressively."""
    from flexflow_tpu.models.nmt import greedy_decode
    from flexflow_tpu.optimizer import AdamOptimizer

    V = 12
    cfg = FFConfig(batch_size=16, num_devices=8)
    ff = FFModel(cfg)
    build_nmt(ff, batch_size=16, src_len=6, tgt_len=6, src_vocab=V,
              tgt_vocab=V, embed_dim=24, hidden_size=32, num_layers=1,
              attention=True)
    # attention subgraph really present
    kinds = [op.name for op in ff.layers.ops]
    assert "attn_weights" in kinds and "attn_context" in kinds
    ff.compile(optimizer=AdamOptimizer(alpha=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices8)
    rng = np.random.RandomState(1)
    n = 64
    src = rng.randint(2, V, size=(n, 6)).astype(np.int32)
    # teacher forcing: tgt_in = [BOS, y_0..y_4], labels = src — position
    # t must be read off the ENCODER via attention
    tgt_in = np.concatenate(
        [np.ones((n, 1), np.int32), src[:, :-1]], axis=1)
    hist = ff.fit({"src": src, "tgt": tgt_in}, src, epochs=40,
                  verbose=False)
    assert hist[-1].sparse_cce_loss < 0.75 * hist[0].sparse_cce_loss

    # teacher-forced prediction beats chance after training
    probs = np.asarray(ff.forward({"src": src[:16], "tgt": tgt_in[:16]}),
                       np.float32)
    tf_acc = float(np.mean(probs.argmax(-1) == src[:16]))
    assert tf_acc > 2.0 / V, f"teacher-forced acc {tf_acc}"

    # greedy decode mechanism: shapes, valid ids, BOS fixed, determinism
    out = greedy_decode(ff, src[:16], bos_id=1)
    assert out.shape == (16, 6) and out.dtype == np.int32
    assert (out >= 0).all() and (out < V).all() and (out[:, 0] == 1).all()
    np.testing.assert_array_equal(out, greedy_decode(ff, src[:16], bos_id=1))
