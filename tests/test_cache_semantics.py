"""Cache op semantics (VERDICT r1 Missing/Weak #10).

Reference: src/ops/cache.cc — per-batch cache_update folds a score
function over (current batch, cached batch) with an EMA (default_score,
cache.cc:38-55; the MoE example's expert-assignment set-compare,
moe.cc:40-63), refreshes the ring slot, and load_cached forward replays
the cached batch (cache.cc:214-231, use_cached :259).
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.ops.moe import Cache, CacheParams, default_cache_score


def _model(num_batches=1, score_fn=None):
    cfg = FFConfig(batch_size=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16], name="x")
    t = ff.cache(x, num_batches=num_batches, score_fn=score_fn)
    t = ff.dense(t, 32, activation=ActiMode.RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t)
    return ff


def test_default_score_ema_rises_on_repeats_and_decays_on_drift(devices8):
    ff = _model()
    ff.compile(optimizer=SGDOptimizer(lr=0.0), devices=devices8[:1])
    op = ff._cache_ops[0]
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y = np.random.randint(0, 4, (8,))
    # identical batch repeated: EMA climbs toward 1 (cache.cc:38-55)
    for _ in range(30):
        ff.train_step({"x": x}, y)
    hot = op.trigger
    assert hot > 0.2
    # drifting batches: score decays (0.99 gamma, no match credit)
    rs = np.random.RandomState(1)
    for _ in range(30):
        ff.train_step({"x": rs.randn(8, 16).astype(np.float32)}, y)
    assert op.trigger < hot * 0.8


def test_moe_style_set_compare_scorer(devices8):
    """moe.cc:40-63 shape: a 4-arg scorer comparing expert-assignment
    sets per sample plugs straight in."""
    num_select = 2

    def moe_score(cached_score, input_arr, cached_arr, vol):
        gamma = 0.99
        cached_score *= gamma
        b = input_arr.shape[0]
        frac = (1.0 - gamma) / b
        for i in range(b):
            if set(np.asarray(input_arr[i]).ravel()[:num_select]) == set(
                np.asarray(cached_arr[i]).ravel()[:num_select]
            ):
                cached_score += frac
        return cached_score

    ff = _model(score_fn=moe_score)
    ff.compile(optimizer=SGDOptimizer(lr=0.0), devices=devices8[:1])
    op = ff._cache_ops[0]
    assert not op._is_legacy_score()
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y = np.random.randint(0, 4, (8,))
    for _ in range(10):
        ff.train_step({"x": x}, y)
    assert op.trigger > 0.05  # all samples matched every batch


def test_legacy_model_level_score_fn_still_polls(devices8):
    ff = _model(score_fn=lambda m: 0.75)
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])
    op = ff._cache_ops[0]
    assert op._is_legacy_score()
    x = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    y = np.random.randint(0, 4, (32,))
    ff.fit(x, y, batch_size=8, epochs=1, verbose=False)
    assert op.trigger == pytest.approx(0.75)


def test_use_cached_replays_cached_batch(devices8):
    """With load_cached on, forward consumes the CACHED batch, not the
    live input (reference cache.cc:214-231)."""
    ff = _model()
    ff.compile(optimizer=SGDOptimizer(lr=0.0), devices=devices8[:1])
    op = ff._cache_ops[0]
    xa = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    xb = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    y = np.zeros(8, np.int64)
    ff.train_step({"x": xa}, y)  # ring now holds xa
    out_a = np.asarray(ff.forward({"x": xa}))

    ff.use_cached(True)
    out_cached = np.asarray(ff.forward({"x": xb}))  # live input ignored
    np.testing.assert_allclose(out_cached, out_a, rtol=1e-5, atol=1e-6)

    ff.use_cached(False)
    out_b = np.asarray(ff.forward({"x": xb}))
    assert np.abs(out_b - out_a).max() > 1e-4


def test_cache_ring_cycles_slots():
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    pt = ParallelTensor(ParallelTensorShape.make((4, 3)))
    op = Cache(CacheParams(num_batches=2), [pt], name="c")
    a = np.ones((4, 3), np.float32)
    b = np.zeros((4, 3), np.float32)
    op.update(a)   # slot 0 <- a
    op.update(b)   # slot 1 <- b
    assert np.array_equal(op.cached_value(), a)  # next slot is 0
    op.update(a)   # slot 0: a vs a -> match credit
    assert op.cache_score > 0
    s = op.cache_score
    op.update(a)   # slot 1: a vs b -> decay only
    assert op.cache_score < s


def test_replay_mode_training_does_not_refresh_ring(devices8):
    """Training with load_cached on must NOT overwrite the ring with
    live batches (reference load_cached forward performs no cache
    refresh, cache.cc:214-231)."""
    ff = _model()
    ff.compile(optimizer=SGDOptimizer(lr=0.0), devices=devices8[:1])
    op = ff._cache_ops[0]
    xa = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    xb = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    y = np.zeros(8, np.int64)
    ff.train_step({"x": xa}, y)
    ff.use_cached(True)  # flushes the pending tap: ring holds xa
    np.testing.assert_array_equal(op.cached_value(), xa)
    for _ in range(3):
        ff.train_step({"x": xb}, y)  # live batches must not leak in
    ff.use_cached(False)
    np.testing.assert_array_equal(op.cached_value(), xa)


def test_negative_slice_bounds_import():
    """x[:, :-1] and x[:, -2:] lower correctly (causal-shift pattern)."""
    import pytest

    torch = pytest.importorskip("torch")
    import torch.nn as nn

    from flexflow_tpu import LossType
    from flexflow_tpu.torch_frontend import PyTorchModel

    class M(nn.Module):
        def forward(self, x):
            return x[:, :-1] * x[:, 1:] + x[:, -2:-1]

    import jax

    m = M()
    ff = FFModel(FFConfig(batch_size=4))
    xt = ff.create_tensor([4, 6], name="x")
    PyTorchModel(m).torch_to_ff(ff, [xt])
    ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               devices=jax.devices("cpu")[:1])
    x = np.random.RandomState(2).randn(4, 6).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_view_minus_one_import():
    import pytest

    torch = pytest.importorskip("torch")
    import torch.nn as nn

    from flexflow_tpu import LossType
    from flexflow_tpu.torch_frontend import PyTorchModel

    class M(nn.Module):
        def forward(self, x):  # [b, 4, 6]
            return x.reshape(x.size(0), -1)

    import jax

    m = M()
    ff = FFModel(FFConfig(batch_size=4))
    xt = ff.create_tensor([4, 4, 6], name="x")
    (out,) = PyTorchModel(m).torch_to_ff(ff, [xt])
    assert out.shape.logical_shape == (4, 24)
    ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               devices=jax.devices("cpu")[:1])
