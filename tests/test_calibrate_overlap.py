"""Overlap/scale calibration tests (VERDICT r03 Weak #4: the search's
overlap_fraction/sync_overlap constants were unfitted heuristics).

Fits c·compute + u·comm + v·sync against measured dp / dp x tp / tp
step times on the hermetic 8-device CPU mesh and checks the fit
actually explains the measurements better than the priors, persists,
and is backend-gated.
"""
import json
import os

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.ops.op import ShardConfig
from flexflow_tpu.sim.calibrate import (calibrate_overlap,
                                        fit_cost_scales,
                                        load_overlap_constants,
                                        save_overlap_constants)
from flexflow_tpu.sim.machine_model import SimpleMachineModel
from flexflow_tpu.sim.simulator import make_cost_model
from flexflow_tpu.strategy import Strategy, data_parallel_strategy

N, BATCH, HIDDEN = 8, 64, 512


def _build():
    ff = FFModel(FFConfig(batch_size=BATCH, num_devices=N))
    x = ff.create_tensor([BATCH, HIDDEN], name="x")
    t = x
    for i in range(4):
        t = ff.dense(t, HIDDEN, activation=ActiMode.RELU, name=f"fc{i}")
    ff.dense(t, 8, name="head")
    return ff


def _megatron(tp, dp):
    axes = ({"data": dp} if dp > 1 else {})
    axes["model"] = tp
    s = Strategy(mesh_axes=axes)
    if dp > 1:
        s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": dp})]
    for i in range(4):
        s.shard_configs[f"fc{i}"] = ShardConfig(
            channel=tp if i % 2 == 0 else 1,
            reduction=1 if i % 2 == 0 else tp,
        )
    return s


def test_fit_cost_scales_recovers_known_constants():
    """On synthetic records generated from known (c, u, v), the fit
    recovers them."""
    rng = np.random.RandomState(0)
    c, u, v = 3.0, 0.5, 0.25
    records = []
    for _ in range(6):
        comp, comm, sync = rng.rand(3) * [10e-3, 4e-3, 2e-3]
        records.append((c * comp + u * comm + v * sync, comp, comm, sync))
    fit = fit_cost_scales(records)
    assert abs(fit["compute_scale"] - c) < 1e-6
    assert abs(fit["comm_scale"] - u) < 1e-6
    assert abs(fit["sync_scale"] - v) < 1e-6
    assert fit["mean_rel_error"] < 1e-9


def test_calibrate_on_cpu_mesh_improves_fidelity(devices8):
    """The fitted scales predict measured dp/tp/dp x tp step times far
    better than the unfitted priors (c=1 assumes the v5p roofline; the
    CPU mesh is orders of magnitude slower)."""
    import jax

    machine = SimpleMachineModel(num_nodes=1, devices_per_node=N)
    cost_model = make_cost_model(FFConfig(num_devices=N), machine)

    def make_inputs(ff):
        rs = np.random.RandomState(0)
        xs = jax.device_put(rs.randn(BATCH, HIDDEN).astype(np.float32),
                            ff.executor.input_shardings()["x"])
        ys = jax.device_put(rs.randint(0, 8, BATCH).astype(np.int32),
                            ff.executor.label_sharding())
        return {"x": xs}, ys

    strategies = [
        (data_parallel_strategy(1), 1),
        (data_parallel_strategy(N), N),
        (_megatron(N // 2, 2), N),
        (_megatron(N, 1), N),
    ]
    fit = calibrate_overlap(_build, strategies, devices8, machine,
                            cost_model, make_inputs, iters=6, windows=2)
    assert fit["fitted_on"] == "cpu"
    assert fit["num_strategies"] == 4
    assert fit["compute_scale"] > 1.0  # CPU is slower than the roofline
    # the fitted model explains the measurements; the priors are off by
    # the full compute-scale factor (rel error ~1.0)
    assert fit["mean_rel_error"] < 0.6


def test_persistence_and_backend_gating(tmp_path):
    fit = {"compute_scale": 2.0, "comm_scale": 0.5, "sync_scale": 0.25,
           "overlap_fraction": 0.5, "sync_overlap_fraction": 0.75,
           "mean_rel_error": 0.1, "num_strategies": 3,
           "fitted_on": "cpu"}
    path = str(tmp_path / "overlap_constants.json")
    save_overlap_constants(fit, path)
    assert load_overlap_constants(path, backend="cpu") == fit
    # a chip must NOT pick up CPU-fitted constants
    assert load_overlap_constants(path, backend="tpu") is None
    # corrupt scales are rejected
    bad = dict(fit, compute_scale=-1.0)
    save_overlap_constants(bad, path)
    assert load_overlap_constants(path, backend="cpu") is None


def test_unity_search_applies_fitted_constants(tmp_path, monkeypatch,
                                               devices8):
    """unity_optimize reads persisted constants (matching backend) and
    runs the search with them (smoke: path executes end-to-end and the
    result is a valid strategy)."""
    import jax

    cache = tmp_path / "cache"
    cache.mkdir()
    monkeypatch.setenv("FLEXFLOW_TPU_CACHE_DIR", str(cache))
    save_overlap_constants({
        "compute_scale": 2.0, "comm_scale": 0.4, "sync_scale": 0.2,
        "overlap_fraction": 0.6, "sync_overlap_fraction": 0.8,
        "mean_rel_error": 0.1, "num_strategies": 4, "fitted_on": "cpu",
    })
    ff = _build()
    ff.config.search_budget = 50
    from flexflow_tpu.pcg.unity import unity_optimize

    s = unity_optimize(ff, 4)
    assert s is not None
    total = 1
    for v in s.mesh_axes.values():
        total *= v
    assert total in (1, 2, 4)
