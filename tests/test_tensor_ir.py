"""Unit tests for the parallel-tensor IR (SURVEY §4 tier: C++ unit tests
— machine-view hashing / parallel-config equivalents)."""
import pytest

from flexflow_tpu.fftype import DataType
from flexflow_tpu.parallel.machine import MachineView, assign_axes, validate_view
from flexflow_tpu.tensor import ParallelDim, ParallelTensorShape


def test_shape_make_and_logical():
    s = ParallelTensorShape.make([32, 64], DataType.FLOAT)
    assert s.logical_shape == (32, 64)
    assert s.replica_degree == 1
    assert s.total_degree == 1
    assert s.num_elements() == 32 * 64
    assert s.size_bytes() == 32 * 64 * 4


def test_data_parallel_shape():
    s = ParallelTensorShape.make([32, 64]).data_parallel(4)
    assert s.degrees == (4, 1)
    assert s.shard_shape == (8, 64)
    assert s.total_degree == 4


def test_replica_dims():
    s = ParallelTensorShape.make([10, 10], replica_degree=4)
    assert s.replica_degree == 4
    assert s.logical_shape == (10, 10)
    assert s.total_degree == 4


def test_invalid_degree():
    with pytest.raises(ValueError):
        ParallelDim(10, 3)


def test_shape_hashable():
    a = ParallelTensorShape.make([4, 4])
    b = ParallelTensorShape.make([4, 4])
    assert a == b and hash(a) == hash(b)
    c = a.data_parallel(2)
    assert a != c


def test_assign_axes_dp():
    s = ParallelTensorShape.make([32, 64]).data_parallel(8)
    view = assign_axes(s, {"data": 8})
    assert view.axes == (("data",), (), ())
    validate_view(view, s, {"data": 8})


def test_assign_axes_2d():
    s = ParallelTensorShape.make([32, 64], degrees=[4, 2])
    view = assign_axes(s, {"data": 4, "model": 2})
    assert view.axes == (("data",), ("model",), ())
    validate_view(view, s, {"data": 4, "model": 2})


def test_assign_axes_factored():
    # one dim of degree 8 over a 4x2 mesh consumes both axes
    s = ParallelTensorShape.make([32, 64], degrees=[8, 1])
    view = assign_axes(s, {"a": 4, "b": 2})
    assert view.axes[0] == ("a", "b")


def test_assign_axes_replica():
    s = ParallelTensorShape.make([32], replica_degree=8)
    view = assign_axes(s, {"data": 8})
    assert view.axes == ((), ("data",))


def test_batch_matmul_seq_length_truncation(devices8):
    """FFIterationConfig.seq_length parity (batch_matmul.cc:70-77):
    positions past seq_length on the declared seq dim are masked."""
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer

    def build():
        ff = FFModel(FFConfig(batch_size=2))
        a = ff.create_tensor([2, 8, 4], name="a")
        b = ff.create_tensor([2, 4, 8], name="b")
        ff.batch_matmul(a, b, a_seq_length_dim=1, b_seq_length_dim=2)
        ff.compile(optimizer=SGDOptimizer(lr=0.1), devices=devices8[:1])
        return ff

    rng = np.random.RandomState(0)
    a = rng.randn(2, 8, 4).astype(np.float32)
    b = rng.randn(2, 4, 8).astype(np.float32)

    ff = build()
    full = np.asarray(ff.forward({"a": a, "b": b}))
    np.testing.assert_allclose(full, a @ b, rtol=1e-5, atol=1e-5)

    trunc = np.asarray(ff.forward({"a": a, "b": b}, seq_length=3))
    a3 = a.copy()
    a3[:, 3:, :] = 0.0
    b3 = b.copy()
    b3[:, :, 3:] = 0.0
    np.testing.assert_allclose(trunc, a3 @ b3, rtol=1e-5, atol=1e-5)
    assert ff.iter_config.seq_length == 3

    # resetting to full length restores the untruncated program
    again = np.asarray(ff.forward({"a": a, "b": b}, seq_length=8))
    np.testing.assert_allclose(again, a @ b, rtol=1e-5, atol=1e-5)
