"""Unit tests for the parallel-tensor IR (SURVEY §4 tier: C++ unit tests
— machine-view hashing / parallel-config equivalents)."""
import pytest

from flexflow_tpu.fftype import DataType
from flexflow_tpu.parallel.machine import MachineView, assign_axes, validate_view
from flexflow_tpu.tensor import ParallelDim, ParallelTensorShape


def test_shape_make_and_logical():
    s = ParallelTensorShape.make([32, 64], DataType.FLOAT)
    assert s.logical_shape == (32, 64)
    assert s.replica_degree == 1
    assert s.total_degree == 1
    assert s.num_elements() == 32 * 64
    assert s.size_bytes() == 32 * 64 * 4


def test_data_parallel_shape():
    s = ParallelTensorShape.make([32, 64]).data_parallel(4)
    assert s.degrees == (4, 1)
    assert s.shard_shape == (8, 64)
    assert s.total_degree == 4


def test_replica_dims():
    s = ParallelTensorShape.make([10, 10], replica_degree=4)
    assert s.replica_degree == 4
    assert s.logical_shape == (10, 10)
    assert s.total_degree == 4


def test_invalid_degree():
    with pytest.raises(ValueError):
        ParallelDim(10, 3)


def test_shape_hashable():
    a = ParallelTensorShape.make([4, 4])
    b = ParallelTensorShape.make([4, 4])
    assert a == b and hash(a) == hash(b)
    c = a.data_parallel(2)
    assert a != c


def test_assign_axes_dp():
    s = ParallelTensorShape.make([32, 64]).data_parallel(8)
    view = assign_axes(s, {"data": 8})
    assert view.axes == (("data",), (), ())
    validate_view(view, s, {"data": 8})


def test_assign_axes_2d():
    s = ParallelTensorShape.make([32, 64], degrees=[4, 2])
    view = assign_axes(s, {"data": 4, "model": 2})
    assert view.axes == (("data",), ("model",), ())
    validate_view(view, s, {"data": 4, "model": 2})


def test_assign_axes_factored():
    # one dim of degree 8 over a 4x2 mesh consumes both axes
    s = ParallelTensorShape.make([32, 64], degrees=[8, 1])
    view = assign_axes(s, {"a": 4, "b": 2})
    assert view.axes[0] == ("a", "b")


def test_assign_axes_replica():
    s = ParallelTensorShape.make([32], replica_degree=8)
    view = assign_axes(s, {"data": 8})
    assert view.axes == ((), ("data",))
