"""Profiler + observability tests: per-op timing harness, OpCostModel
measured-override wiring, dot exports, recursive logger."""
import logging

import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.logger import RecursiveLogger
from flexflow_tpu.profiler import (
    make_measure_fn,
    measure_op_forward,
    profile_operators,
)


def _model(devices):
    cfg = FFConfig(batch_size=8, num_devices=len(devices))
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16], name="x")
    t = ff.dense(x, 32, activation=ActiMode.RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices)
    return ff


def test_measure_op_forward(devices8):
    ff = _model(devices8[:1])
    ops = [op for op in ff.operators.topo_order() if op.name.startswith("fc")]
    t = measure_op_forward(ops[0], warmup=1, repeats=2)
    assert t is not None and 0 < t < 1.0


def test_profile_operators_table(devices8, capsys):
    from flexflow_tpu.profiler import print_profile

    ff = _model(devices8)
    rows = profile_operators(ff, warmup=1, repeats=1)
    names = [r["name"] for r in rows]
    assert "fc1" in names and "fc2" in names
    assert all(r["fwd_ms"] is None or r["fwd_ms"] > 0 for r in rows)
    print_profile(rows)
    out = capsys.readouterr().out
    assert "fc1" in out and "TOTAL" in out


def test_measure_fn_feeds_cost_model(devices8):
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel

    ff = _model(devices8[:1])
    cm = OpCostModel(TpuPodModel(), measure_fn=make_measure_fn(warmup=1, repeats=1))
    op = next(op for op in ff.operators.topo_order() if op.name == "fc1")
    c = cm.cost(op)
    assert c.forward_time > 0
    assert cm.cost(op) is c  # cached


def test_profiling_flag_prints_table(devices8, capsys):
    cfg = FFConfig(batch_size=8, num_devices=8, profiling=True)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 8], name="x")
    ff.softmax(ff.dense(x, 4, name="fc"))
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8)
    xs = np.zeros((16, 8), np.float32)
    ys = np.zeros(16, np.int32)
    ff.fit(xs, ys, epochs=1, verbose=False)
    assert "fc" in capsys.readouterr().out


def test_dot_exports(devices8, tmp_path):
    cfg = FFConfig(batch_size=8, num_devices=8,
                   export_compgraph_file=str(tmp_path / "comp.dot"),
                   export_taskgraph_file=str(tmp_path / "task.dot"))
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 8], name="x")
    ff.softmax(ff.dense(x, 4, name="fc"))
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8)
    comp = (tmp_path / "comp.dot").read_text()
    task = (tmp_path / "task.dot").read_text()
    assert "digraph" in comp and "fc" in comp
    assert "digraph" in task


def test_recursive_logger_indents(caplog):
    log = RecursiveLogger("test.recursive")
    log.set_level(logging.DEBUG)
    with caplog.at_level(logging.DEBUG, logger="test.recursive"):
        log.debug("outer")
        with log.enter("scope"):
            log.debug("inner")
            assert log.depth == 1
    msgs = [r.getMessage() for r in caplog.records]
    assert "outer" in msgs[0]
    assert msgs[1] == "scope {"
    assert msgs[2] == "  inner"
    assert msgs[3] == "  }" or msgs[3] == "}"
