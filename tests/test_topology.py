"""Multi-slice topology subsystem (ISSUE 12, docs/TOPOLOGY.md).

Pins the tentpole contracts:

  * the two-level machine model's hierarchical collective costs sit
    strictly between the pure-ICI and pure-DCN bounds, and the
    multi-slice torus generator routes cross-slice paths through one
    DCN hop (hand-computed estimates);
  * *placement* is a searched, costed strategy dimension: with 2
    slices and a DCN >= 10x slower than ICI both searches keep the
    tensor-parallel groups intra-slice (placement = the data axis) and
    choose the hierarchical reduction, surfaced in
    search_stats["placement"];
  * the executor lowers the cross-slice grad reduction to the
    hierarchical form on a two-level mesh, numerically equivalent to
    the flat reduction on the same global mesh (the ZeRO-ladder
    equivalence standard: float32 reduction-order noise only);
  * store keys are hierarchy-aware without invalidating single-slice
    entries: --slices 1 fingerprints carry NO slice fields and ignore
    the DCN knobs;
  * the cross-slice rendezvous generalizes the preemption barrier
    (epoch agreement = MAX, health census).
"""
import dataclasses
import json

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.pcg.evaluator import IncrementalEvaluator, strategy_signature
from flexflow_tpu.pcg.mcmc import MCMCSearch
from flexflow_tpu.pcg.unity import UnitySearch
from flexflow_tpu.sim.simulator import OpCostModel, Simulator
from flexflow_tpu.strategy import Strategy, data_parallel_strategy
from flexflow_tpu.topology.hierarchy import (
    SLICE_AXIS,
    SliceHierarchy,
    expand_mesh_axes,
    hierarchy_from_config,
    legal_placements,
    parse_slice_topology,
    resolve_placement,
)


def _hier(dcn_bw=4e9, dcn_lat=2e-6, slices=2, topo=(4,)):
    return SliceHierarchy(topology=topo, slices=slices,
                          dcn_bw_per_host=dcn_bw, dcn_latency=dcn_lat)


# -- machine model -------------------------------------------------------

def test_hierarchical_allreduce_between_pure_bounds():
    """RS(ICI) -> AR(DCN on the shard) -> AG(ICI) must cost strictly
    more than an all-ICI ring and strictly less than an all-DCN ring
    whenever DCN is the slower tier."""
    m = _hier()
    size = 64 * 2**20
    for intra, inter in [(4, 2), (2, 4), (8, 2)]:
        n = intra * inter
        ici = m.tier_collective("allreduce", size, n).time
        dcn = m.tier_collective("allreduce", size, n, over_dcn=True).time
        hier = m.hierarchical_allreduce_time(size, intra, inter)
        assert ici < hier < dcn, (intra, inter, ici, hier, dcn)


def test_hierarchical_cost_degenerates_at_trivial_legs():
    m = _hier()
    size = 1 << 20
    # no intra remainder -> the pure DCN ring
    assert m.hierarchical_cost("allreduce", size, 1, 2).time == \
        m.tier_collective("allreduce", size, 2, over_dcn=True).time
    # no inter leg -> the pure ICI ring
    assert m.hierarchical_cost("allreduce", size, 4, 1).time == \
        m.tier_collective("allreduce", size, 4).time


def test_collective_cost_tier_split_accounting():
    """The CommCost split carries the hierarchical decomposition: the
    DCN leg moves only the scattered shard's ring bytes."""
    m = _hier()
    size = 8 * 2**20
    cc = m.collective_cost("allreduce", size, 8, cross=True)  # (4, 2)
    # DCN all-reduce of size/4 over 2: 2 * (1/2) * size/4
    assert cc.dcn_bytes == pytest.approx(size / 4.0)
    # ICI RS + AG of the full size over 4: 2 * (3/4) * size
    assert cc.ici_bytes == pytest.approx(2 * 0.75 * size)
    flat = m.collective_cost("allreduce", size, 8, cross=False)
    assert flat.dcn_bytes == 0 and flat.dcn_time == 0
    assert flat.time < cc.time  # the hierarchy pays for the DCN leg


def test_split_group_and_unfactorable_fallback():
    m = _hier(slices=2)
    assert m.split_group(8) == (4, 2)
    assert m.split_group(2) == (1, 2)
    assert m.split_group(3) == (1, 3)  # unfactorable: pure DCN


def test_multi_slice_torus_routing_hand_computed():
    """Generator + routed p2p: intra-slice rides per-hop ICI links,
    cross-slice exactly one DCN-tier hop between same-index chips."""
    from flexflow_tpu.sim.network import (
        NetworkedMachineModel, multi_slice_torus,
    )

    conn = multi_slice_torus((4,), slices=2)
    assert conn.shape == (8, 8)
    # chip 0 of slice 0 <-> chip 0 of slice 1 directly linked
    assert conn[0, 4] == 1 and conn[4, 0] == 1
    # no diagonal cross-slice shortcuts
    assert conn[0, 5] == 0
    m = NetworkedMachineModel(conn, link_bandwidth=1e9, link_latency=1e-6)
    size = 1 << 20
    # ring neighbors inside a slice: one hop
    assert np.isclose(m.p2p_time(size, 0, 1), 1e-6 + size / 1e9)
    # cross-slice same index: one (DCN) hop
    assert np.isclose(m.p2p_time(size, 0, 4), 1e-6 + size / 1e9)
    # cross-slice different index: DCN hop + intra hop
    assert np.isclose(m.p2p_time(size, 0, 5), 2e-6 + size / 1e9)


def test_flat_costs_unchanged_on_single_slice():
    """A SliceHierarchy with cross=False and a plain TpuPodModel agree
    exactly — slices=1 (and every intra-slice group) is the flat
    pre-topology cost model."""
    from flexflow_tpu.sim.machine_model import TpuPodModel

    flat = TpuPodModel(topology=(4,))
    m = _hier(topo=(4,))
    size = 3 << 20
    for n in (2, 4):
        assert m.collective_cost("allreduce", size, n).time == \
            flat.axis_allreduce_time(size, n)
        assert m.collective_cost("allgather", size, n).time == \
            flat.axis_allgather_time(size, n)


# -- placement helpers ---------------------------------------------------

def test_placement_helpers():
    axes = {"data": 4, "model": 2}
    assert legal_placements(axes, 2) == ["data", "model"]
    assert legal_placements(axes, 4) == ["data"]
    assert legal_placements(axes, 3) == []
    assert resolve_placement(axes, 2) == "data"
    assert resolve_placement({"model": 3}, 2) is None
    assert legal_placements(axes, 1) == []


def test_expand_mesh_axes_splits_and_reorders():
    # intra remainder: leading slice axis + reduced placement axis
    exec_axes, hier = expand_mesh_axes({"data": 8}, 2, "data")
    assert exec_axes == {SLICE_AXIS: 2, "data": 4}
    assert hier == "data"
    exec_axes, hier = expand_mesh_axes({"model": 2, "data": 4}, 2, "data")
    assert list(exec_axes) == [SLICE_AXIS, "model", "data"]
    assert exec_axes["data"] == 2 and hier == "data"
    # placement axis exactly the slice count: moved first, no split
    exec_axes, hier = expand_mesh_axes({"model": 4, "data": 2}, 2, "data")
    assert list(exec_axes) == ["data", "model"]
    assert exec_axes["data"] == 2 and hier is None
    with pytest.raises(ValueError):
        expand_mesh_axes({"data": 3}, 2, "data")


def test_parse_slice_topology():
    assert parse_slice_topology("4x4") == (4, 4)
    assert parse_slice_topology("2,2,2") == (2, 2, 2)
    for bad in ("", "axb", "0,4", "-1"):
        with pytest.raises(ValueError):
            parse_slice_topology(bad)


def test_hierarchy_from_config_validates():
    cfg = FFConfig(slices=2, slice_topology="2,2")
    m = hierarchy_from_config(cfg, 8)
    assert m.slices == 2 and m.topology == (2, 2)
    with pytest.raises(ValueError):
        hierarchy_from_config(FFConfig(slices=3), 8)  # 8 % 3
    with pytest.raises(ValueError):
        hierarchy_from_config(FFConfig(slices=2, slice_topology="4x4"), 8)


# -- placement as a searched dimension -----------------------------------

def _wide_mlp(batch=1024, h=64):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor([batch, h], name="x")
    t = ff.dense(x, h, activation=ActiMode.RELU)
    t = ff.dense(t, h, activation=ActiMode.RELU)
    t = ff.dense(t, 8)
    ff.softmax(t)
    return ff


def test_placement_is_a_costed_dimension():
    """The same sharding under different placements simulates to
    different costs, and the strategy signature separates them."""
    graph = _wide_mlp().layers
    m = _hier()
    ev = IncrementalEvaluator(graph, Simulator(m))
    s = MCMCSearch(graph, 8, lambda: Simulator(m), budget=0)
    flags = {c.name: True for c in s.candidates if c.name != "dense_2"}
    r = {}
    for p in ("data", "model"):
        cand = s._build(4, 2, 1, flags, None, p)
        assert cand.placement == p
        r[p] = ev.evaluate(cand)
    assert r["data"].total_time != r["model"].total_time
    # tensor-parallel partial sums crossing DCN cost more than the
    # once-per-step hierarchical grad sync at these activation sizes
    assert r["data"].total_time < r["model"].total_time
    base = data_parallel_strategy(8)
    sigs = {
        strategy_signature(dataclasses.replace(base, placement=p))
        for p in (None, "data")
    }
    assert len(sigs) == 2


def test_both_searches_choose_intra_slice_tp_and_hierarchical_reduction(
        monkeypatch):
    """The acceptance scenario: 2 slices, DCN >= 10x slower than the
    effective ICI — both searches keep the tensor-parallel groups
    intra-slice (the data axis crosses) and choose the hierarchical
    reduction, surfaced in search_stats."""
    ff = _wide_mlp()
    m = _hier(dcn_bw=4e9, dcn_lat=2e-6)  # ICI eff 180e9: 45x slower
    mcmc = MCMCSearch(ff.layers, 8, lambda: Simulator(m), budget=100,
                      seed=0)
    mcmc.factorizations = [(4, 2, 1)]  # dp x tp: placement decides
    best = mcmc.optimize()
    assert best.search_stats["placement"] == "data"
    assert best.search_stats["hierarchical_reduction"] is True

    import flexflow_tpu.pcg.unity as unity_mod

    monkeypatch.setattr(
        unity_mod, "_factorizations",
        lambda n, allow_expert=True: [(4, 2, 1)],
    )
    # dcn_bucket_bytes=0 pins the PR-12 estimator this scenario was
    # built for: at these toy leaf sizes the DCN latency term decides
    # the tie, and v4's grad-sync bucketing (tests/test_remat_search.py
    # covers it) amortizes exactly that term away
    unity = UnitySearch(ff.layers, 8, m, OpCostModel(m),
                        enable_pipeline=False, dcn_bucket_bytes=0)
    ub = unity.optimize()
    assert ub.search_stats["placement"] == "data"
    assert ub.search_stats["hierarchical_reduction"] is True
    # the winner's predicted traffic keeps the DCN tier light: dp bytes
    # cross scattered, tp bytes stay on ICI
    ev = IncrementalEvaluator(ff.layers, Simulator(m))
    res = ev.evaluate(ub)
    tiers = res.comm_tiers
    assert tiers["dcn_bytes"] > 0
    assert tiers["dcn_bytes"] < tiers["ici_bytes"]


def test_flat_machine_searches_carry_empty_placement():
    from flexflow_tpu.sim.machine_model import TpuPodModel

    graph = _wide_mlp(batch=16).layers
    m = TpuPodModel(topology=(8,))
    best = MCMCSearch(graph, 8, lambda: Simulator(m), budget=10,
                      seed=0).optimize()
    assert best.search_stats["placement"] == ""
    assert best.search_stats["hierarchical_reduction"] is False
    assert best.placement is None


def test_placement_round_trips_serialization():
    s = data_parallel_strategy(8)
    s.placement = "data"
    s2 = Strategy.from_json(s.to_json())
    assert s2.placement == "data"
    assert strategy_signature(s) == strategy_signature(s2)


# -- executor: hierarchical reduction on the two-level mesh ---------------

def _fit_model(cfg, devices8, wrapper=True):
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="x")
    t = ff.dense(x, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 8)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy=data_parallel_strategy(8), devices=devices8,
               seed=0)
    if not wrapper:
        # the flat-reduction baseline on the SAME two-level mesh:
        # disable the hierarchical re-spec and rebuild the step
        assert ff.executor.hier_axis is not None
        ff.executor.hier_axis = None
        ff._step_fn = ff.executor.build_step()
        ff._step_cache[ff.iter_config.seq_length] = (
            ff._step_fn, ff._eval_fn, ff._fwd_fn,
        )
    return ff


def _weights(ff):
    import jax

    return jax.tree.leaves(jax.tree.map(np.asarray, ff._weights))


def _assert_trees_close(a, b, rtol=2e-5, atol=2e-6):
    for x, y in zip(a, b):
        np.testing.assert_allclose(x, y, rtol=rtol, atol=atol)


def test_two_level_mesh_and_hier_axis(devices8):
    cfg = FFConfig(batch_size=16, num_devices=8, slices=2)
    ff = _fit_model(cfg, devices8)
    assert ff.mesh.axis_names == (SLICE_AXIS, "data")
    assert ff.mesh.devices.shape == (2, 4)
    assert ff.executor.hier_axis == "data"
    # strategy-facing surfaces keep the UNEXPANDED axes
    assert ff.strategy.mesh_axes == {"data": 8}


def test_hierarchical_reduction_matches_flat_on_same_global_mesh(devices8):
    """The synthesized RS(ICI)->AR(DCN)->AG(ICI) grad reduction against
    the flat XLA psum on the SAME two-level mesh: equivalent to within
    float32 reduction-order noise (the ZeRO-ladder equivalence bar —
    XLA owns the lowering, so summation order is a hint, not a
    contract; docs/TOPOLOGY.md)."""
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 32).astype(np.float32)
    ys = rng.randint(0, 8, 64).astype(np.int32)
    mk = lambda: FFConfig(batch_size=16, num_devices=8, slices=2)  # noqa
    ff_hier = _fit_model(mk(), devices8)
    ff_flat = _fit_model(mk(), devices8, wrapper=False)
    for ff in (ff_hier, ff_flat):
        ff.fit(xs, ys, epochs=2, verbose=False)
    _assert_trees_close(_weights(ff_hier), _weights(ff_flat))


def test_single_slice_execution_is_bit_identical_to_pre_topology(devices8):
    """--slices 1 is EXACTLY the current behavior: same mesh, no
    wrapper, bit-identical training."""
    rng = np.random.RandomState(1)
    xs = rng.randn(64, 32).astype(np.float32)
    ys = rng.randint(0, 8, 64).astype(np.int32)
    ff1 = _fit_model(FFConfig(batch_size=16, num_devices=8), devices8)
    ffs = _fit_model(FFConfig(batch_size=16, num_devices=8, slices=1),
                     devices8)
    assert ff1.mesh.axis_names == ffs.mesh.axis_names == ("data",)
    assert ffs.executor.hier_axis is None
    for ff in (ff1, ffs):
        ff.fit(xs, ys, epochs=2, verbose=False)
    for a, b in zip(_weights(ff1), _weights(ffs)):
        np.testing.assert_array_equal(a, b)


def test_multi_slice_with_zero_stage_shards_over_intra_axis(devices8):
    """ZeRO stage >= 1 on a two-level mesh scatters over the INTRA
    slice remainder (the reduced data axis): the wus machinery itself
    produces the hierarchical form, numerics match stage 0."""
    rng = np.random.RandomState(2)
    xs = rng.randn(64, 32).astype(np.float32)
    ys = rng.randint(0, 8, 64).astype(np.int32)
    ff0 = _fit_model(FFConfig(batch_size=16, num_devices=8, slices=2),
                     devices8)
    ff1 = _fit_model(FFConfig(batch_size=16, num_devices=8, slices=2,
                              zero_stage=1), devices8)
    assert ff1.executor.wus_axis == "data"
    assert ff1.executor.hier_axis is None  # wus already hierarchical
    for ff in (ff0, ff1):
        ff.fit(xs, ys, epochs=2, verbose=False)
    _assert_trees_close(_weights(ff0), _weights(ff1))


# -- simulator fidelity of the intra-slice wus group ----------------------

def test_wus_group_shrinks_to_intra_remainder():
    m = _hier()
    sim = Simulator(m, zero_stage=1)
    graph = _wide_mlp(batch=16).layers
    s = data_parallel_strategy(8)
    ev = IncrementalEvaluator(graph, sim)
    res = ev.evaluate(s)  # assigns views
    w = next(op for op in res.ops if op.weights).weights[0]
    # placement=data (default): the executor scatters over the intra
    # remainder 8/2 = 4, not the whole axis
    assert sim.wus_group(w, {"data": 8}, placement="data") == 4
    assert sim.wus_group(w, {"data": 8}, placement=None) == 8


# -- store keys -----------------------------------------------------------

def test_single_slice_store_keys_unchanged():
    """--slices 1 mesh fingerprints carry NO hierarchy fields and are
    invariant to the DCN knobs — existing flat-store entries survive
    the topology subsystem."""
    from flexflow_tpu.store.key import mesh_fingerprint

    base = mesh_fingerprint(FFConfig(), 8)
    assert "slices" not in base and "dcn_bandwidth" not in base
    tweaked = mesh_fingerprint(FFConfig(dcn_bandwidth=1e9,
                                        dcn_latency=1e-3), 8)
    assert tweaked == base


def test_multi_slice_store_keys_split_by_hierarchy():
    from flexflow_tpu.store.key import mesh_fingerprint

    a = mesh_fingerprint(FFConfig(slices=2), 8)
    b = mesh_fingerprint(FFConfig(slices=4), 8)
    c = mesh_fingerprint(FFConfig(slices=2, dcn_bandwidth=1e9), 8)
    assert a["slices"] == 2
    assert a != b and a != c


# -- cross-slice rendezvous ----------------------------------------------

def _blob(tmp_path):
    from flexflow_tpu.store.blobstore import LocalBlobStore

    return LocalBlobStore(str(tmp_path / "blob"))


def test_epoch_rendezvous_agrees_on_max(tmp_path):
    from flexflow_tpu.topology.rendezvous import epoch_rendezvous

    blob = _blob(tmp_path)
    for sl, ep in [(1, 7), (2, 9)]:
        blob.put(f"rendezvous/run1/epoch_00000000/host_{sl:05d}",
                 json.dumps({"host": sl, "epoch": ep}).encode())
    agreed = epoch_rendezvous(blob, "run1", 5, slice_id=0, num_slices=3,
                              timeout_s=5.0, sleep=lambda s: None)
    assert agreed == 9  # laggards run forward, nobody rewinds
    # a later elastic EVENT uses a fresh round: round 0's posts can't
    # satisfy its quorum or pollute its agreement (review finding)
    agreed2 = epoch_rendezvous(blob, "run1", 3, slice_id=0, num_slices=3,
                               round_id=1, timeout_s=0.2,
                               sleep=lambda s: None)
    assert agreed2 == 3  # only our own post this round


def test_health_census_reports_posted_slices(tmp_path):
    from flexflow_tpu.topology.rendezvous import health_census

    blob = _blob(tmp_path)
    blob.put("rendezvous/runh/health_00000000/host_00001",
             json.dumps({"host": 1, "healthy": False}).encode())
    seen = health_census(blob, "runh", slice_id=0, num_slices=3,
                         timeout_s=0.2, sleep=lambda s: None)
    assert seen[0] is True and seen[1] is False
    assert 2 not in seen  # absent slice: presumed dead by the caller
    # a new census round does NOT see round 0's stale posts — a slice
    # that died since then is correctly presumed dead
    seen2 = health_census(blob, "runh", slice_id=0, num_slices=3,
                          round_id=1, timeout_s=0.2, sleep=lambda s: None)
    assert 1 not in seen2


def test_rendezvous_reduce_counts_own_value_once(tmp_path):
    """The caller's own post is excluded from the reduced peer values
    (its local value joins exactly once), so non-idempotent reductions
    like sum stay correct (review finding)."""
    from flexflow_tpu.topology.rendezvous import post_and_agree

    blob = _blob(tmp_path)
    blob.put("rendezvous/runs/cap/host_00001",
             json.dumps({"host": 1, "step": 10}).encode())
    total = post_and_agree(blob, "runs", "cap", 5, host_id=0, num_hosts=2,
                           reduce=sum, timeout_s=5.0,
                           sleep=lambda s: None)
    assert total == 15  # 10 + 5, NOT 10 + 5 + 5


def test_placement_stats_empty_for_pipeline_winners():
    """A pipeline winner executes flat on multi-slice runs — its stats
    must not claim a placement/hierarchical reduction (review
    finding)."""
    from flexflow_tpu.topology.hierarchy import placement_stats

    s = Strategy(mesh_axes={"data": 2, "pipe": 4},
                 pipeline={"degree": 4, "num_microbatches": 8,
                           "axis": "pipe", "dp_axis": "data"})
    assert placement_stats(s, 2) == {
        "placement": "", "hierarchical_reduction": False,
    }


def test_clear_rendezvous(tmp_path):
    from flexflow_tpu.topology.rendezvous import (
        clear_rendezvous, post_and_agree,
    )

    blob = _blob(tmp_path)
    post_and_agree(blob, "runc", "epoch", 3, host_id=0, num_hosts=1)
    blob.put("rendezvous/runc/epoch/host_00001", b'{"host":1,"step":3}')
    assert clear_rendezvous(blob, "runc") >= 1
    assert blob.list("rendezvous/runc/") == []


def test_preemption_barrier_still_rides_legacy_layout(tmp_path):
    """The barrier delegates to the generic rendezvous but keeps its
    `barrier/<run_id>/` keys — on-store compatibility with PR 9."""
    from flexflow_tpu.distributed import preemption_barrier

    blob = _blob(tmp_path)
    blob.put("barrier/runz/host_00001",
             json.dumps({"host": 1, "step": 12}).encode())
    agreed = preemption_barrier(blob, "runz", 10, host_id=0, num_hosts=2,
                                timeout_s=5.0, sleep=lambda s: None)
    assert agreed == 12
    assert any(k.startswith("barrier/runz/host_00000")
               for k in blob.list("barrier/runz/"))


# -- obs: per-tier comm telemetry ----------------------------------------

def test_fidelity_record_carries_tier_split(devices8):
    from flexflow_tpu.obs.fidelity import report_fidelity

    cfg = FFConfig(batch_size=16, num_devices=8, slices=2,
                   dcn_bandwidth=2e9, telemetry=True)
    ff = _fit_model(cfg, devices8)
    rec = report_fidelity(ff, measured_step_s=1e-3, steps_measured=1)
    assert rec is not None
    assert rec["predicted_dcn_bytes"] > 0
    assert rec["predicted_ici_bytes"] > 0
    assert ff.telemetry.metrics.counter("comm/dcn_bytes").value == \
        rec["predicted_dcn_bytes"]
    assert ff.telemetry.metrics.counter("comm/ici_bytes").value == \
        rec["predicted_ici_bytes"]


def test_flat_fidelity_tier_split_is_all_ici(devices8):
    from flexflow_tpu.obs.fidelity import report_fidelity

    cfg = FFConfig(batch_size=16, num_devices=8, telemetry=True)
    ff = _fit_model(cfg, devices8)
    rec = report_fidelity(ff, measured_step_s=1e-3, steps_measured=1)
    assert rec["predicted_dcn_bytes"] == 0
    assert rec["predicted_ici_bytes"] > 0


def test_telemetry_summary_renders_comm_section(tmp_path):
    from flexflow_tpu.obs.metrics import MetricsRegistry
    from tools.telemetry_summary import summarize

    reg = MetricsRegistry()
    reg.counter("comm/ici_bytes").inc(1024)
    reg.counter("comm/dcn_bytes").inc(64)
    out = summarize(reg.drain())
    assert "Comm" in out
    assert "ici_bytes" in out and "dcn_bytes" in out


def test_degraded_mesh_machine_model_degrades_to_flat():
    """Elastic recovery on survivors the hierarchy cannot fit (review
    finding): make_machine_model degrades to the flat model instead of
    failing the re-search — both for an indivisible device count and
    for a slice_topology whose chip product no longer matches."""
    from flexflow_tpu.sim.machine_model import make_machine_model

    m = make_machine_model(FFConfig(slices=3), 8)  # 8 % 3
    assert not isinstance(m, SliceHierarchy)
    m = make_machine_model(FFConfig(slices=2, slice_topology="4"), 4)
    assert not isinstance(m, SliceHierarchy)  # 4/2=2 chips != product 4
    # healthy counts still get the hierarchy
    assert isinstance(
        make_machine_model(FFConfig(slices=2, slice_topology="4"), 8),
        SliceHierarchy,
    )
