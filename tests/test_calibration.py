"""Measured-cost calibration of the strategy search.

Reference: the search times real kernels per candidate and caches by
(params, view) — inner_measure_operator_cost model.cu:38-75, cost cache
simulator.cc:550-560.  Here: profiler.make_measure_fn -> OpCostModel
measured override, persisted to disk across searches.
"""
import json

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import OpCostModel, make_cost_model


def build_mlp(hidden=1024, batch=64, layers=2):
    ff = FFModel(FFConfig())
    x = ff.create_tensor([batch, hidden], name="x")
    t = x
    for i in range(layers):
        t = ff.dense(t, hidden, activation=ActiMode.RELU, name=f"fc{i}")
    return ff


def big_op(ff):
    """An op above OpCostModel.MEASURE_MIN_FLOPS."""
    op = next(o for o in ff.layers.ops if o.name == "fc0")
    assert op.flops() >= OpCostModel.MEASURE_MIN_FLOPS
    return op


def test_measured_override_and_persistence(tmp_path):
    ff = build_mlp()
    op = big_op(ff)
    machine = TpuPodModel(topology=(4,))
    calls = []

    def fake_measure(o):
        calls.append(o.name)
        return 123e-6

    path = str(tmp_path / "costs.json")
    cm = OpCostModel(machine, measure_fn=fake_measure, cache_path=path)
    c = cm.cost(op)
    assert c.forward_time == pytest.approx(123e-6)
    assert c.backward_time == pytest.approx(246e-6)
    assert cm.measured_hits == 1 and calls == ["fc0"]
    # cached in-memory: no re-measure
    cm.cost(op)
    assert calls == ["fc0"]
    cm.save_persistent()
    data = json.loads(open(path).read())
    assert list(data.values()) == [pytest.approx(123e-6)]

    # a fresh model consults the DISK cache, never the measure_fn
    calls2 = []
    cm2 = OpCostModel(
        machine, measure_fn=lambda o: calls2.append(o.name) or 1.0,
        cache_path=path,
    )
    c2 = cm2.cost(build_mlp().layers.ops[1])  # equal node_key, new objects
    assert c2.forward_time == pytest.approx(123e-6)
    assert calls2 == [] and cm2.measured_hits == 1


def test_small_ops_stay_analytic():
    ff = FFModel(FFConfig())
    x = ff.create_tensor([4, 8], name="x")
    ff.dense(x, 8, name="tiny")
    machine = TpuPodModel(topology=(4,))
    cm = OpCostModel(machine, measure_fn=lambda o: 1.0)
    op = next(o for o in ff.layers.ops if o.name == "tiny")
    c = cm.cost(op)
    assert c.forward_time < 1.0 and cm.measured_hits == 0


def test_unity_search_consults_measured_costs(tmp_path, monkeypatch):
    """End-to-end: unity_optimize with calibration on must route costs
    through the measured path and persist them."""
    import flexflow_tpu.profiler as profiler

    measured = []

    def fake_make_measure_fn(*a, **kw):
        def fn(op):
            measured.append(op.name)
            return 50e-6

        return fn

    monkeypatch.setattr(profiler, "make_measure_fn", fake_make_measure_fn)
    path = str(tmp_path / "search_costs.json")
    ff = build_mlp(hidden=1024, batch=32, layers=2)
    ff.config.search_calibrate = True
    ff.config.op_cost_cache_file = path

    from flexflow_tpu.pcg.unity import unity_optimize

    s = unity_optimize(ff, 4)
    assert s is not None
    assert measured, "search never consulted the measured cost path"
    data = json.loads(open(path).read())
    assert data and all(v == pytest.approx(50e-6) for v in data.values())


def test_make_cost_model_off_on_cpu_auto():
    cfg = FFConfig()  # search_calibrate=None -> auto; tests force CPU
    cm = make_cost_model(cfg, TpuPodModel(topology=(4,)))
    assert cm.measure_fn is None


def test_measure_op_forward_real_kernel():
    """The chain-timed profiler returns a sane positive time on CPU."""
    from flexflow_tpu.profiler import measure_op_forward

    ff = build_mlp(hidden=256, batch=32, layers=1)
    op = next(o for o in ff.layers.ops if o.name == "fc0")
    t = measure_op_forward(op, chain=4, warmup=1, repeats=2)
    assert t is not None and 0.0 <= t < 1.0
