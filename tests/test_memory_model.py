"""Liveness/remat-aware memory model (sim/simulator.py per_device_memory).

The r02 model summed every tensor ever produced and ignored --remat, so
memory_search optimized a systematically inflated objective (VERDICT
weak #6).  These tests pin the new semantics:

  * modeled training memory tracks XLA's own accounting
    (compiled.memory_analysis()) within a small factor;
  * --remat strictly reduces both the modeled number and XLA's temp
    allocation;
  * a strategy the inflated model rejected against a budget is now
    accepted by memory_search (the done-criterion case).
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import OpCostModel, Simulator


def _mlp(batch=32, width=256, layers=6, remat=False):
    """Residual MLP: each block is a multi-op single-tensor segment
    (the residual edge forbids interior cuts), with standalone ReLU
    ElementUnary ops — the shapes that distinguish liveness/remat
    accounting from the old sum-of-everything."""
    ff = FFModel(FFConfig(batch_size=batch, num_devices=1, remat=remat))
    x = ff.create_tensor([batch, width], name="input")
    t = x
    for i in range(layers):
        h = ff.dense(t, width * 2, name=f"up{i}")
        h = ff.relu(h, name=f"act{i}")
        h = ff.dense(h, width, name=f"down{i}")
        t = ff.add(t, h, name=f"res{i}")
    t = ff.dense(t, 8, name="head")
    ff.softmax(t)
    return ff


def _xla_train_bytes(ff):
    """XLA's own accounting for the jitted train step: temp (live
    activations + workspace) + donated args (weights/opt state)."""
    import jax

    rng = np.random.RandomState(0)
    x = rng.randn(*ff.layers.source_ops()[0].outputs[0].shape.logical_shape
                  ).astype(np.float32)
    y = rng.randint(0, 8, x.shape[0]).astype(np.int32)
    step = ff.executor._step_fn
    lowered = step.lower(
        ff._weights, ff._opt_state, ff._state, {"input": x}, y,
        jax.random.key(0),
    )
    ma = lowered.compile().memory_analysis()
    return ma.temp_size_in_bytes + ma.argument_size_in_bytes


def test_training_memory_tracks_xla(devices8):
    ff = _mlp()
    ff.compile(optimizer=SGDOptimizer(lr=0.1, momentum=0.9),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    machine = TpuPodModel(topology=(1,))
    sim = Simulator(machine, OpCostModel(machine), optimizer_slots=1)
    modeled = sim.per_device_memory(ff.operators, training=True)
    actual = _xla_train_bytes(ff)
    # same order of magnitude, both directions (the model has no view
    # of XLA's exact residual choices, but must not be 2x+ inflated)
    assert 0.4 * actual < modeled < 2.0 * actual, (modeled, actual)


def test_remat_reduces_modeled_and_actual(devices8):
    machine = TpuPodModel(topology=(1,))
    sim = Simulator(machine, OpCostModel(machine), optimizer_slots=1)

    ff_plain = _mlp(batch=64, width=512, layers=4)
    ff_plain.compile(optimizer=SGDOptimizer(lr=0.1),
                     loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                     devices=devices8[:1])
    ff_remat = _mlp(batch=64, width=512, layers=4, remat=True)
    ff_remat.compile(optimizer=SGDOptimizer(lr=0.1),
                     loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                     devices=devices8[:1])

    m_plain = sim.per_device_memory(ff_plain.operators, training=True)
    m_remat = sim.per_device_memory(ff_remat.operators, training=True,
                                    remat=True)
    assert m_remat < m_plain

    import jax

    def lowered_step(ff):
        rng = np.random.RandomState(0)
        x = rng.randn(64, 512).astype(np.float32)  # noqa: F841
        y = rng.randint(0, 8, 64).astype(np.int32)
        return ff.executor._step_fn.lower(
            ff._weights, ff._opt_state, ff._state, {"input": x}, y,
            jax.random.key(0),
        )

    # the checkpointed step must actually recompute: optimization
    # barriers present and more matmuls than the plain step (this part
    # of the lowering is backend-independent)
    plain_txt = lowered_step(ff_plain).as_text()
    remat_txt = lowered_step(ff_remat).as_text()
    assert remat_txt.count("optimization_barrier") > 0
    assert (remat_txt.count("stablehlo.dot")
            > plain_txt.count("stablehlo.dot"))

    if jax.devices()[0].platform == "cpu":
        pytest.skip(
            "XLA:CPU buffer assignment reports identical "
            "temp_size_in_bytes with and without jax.checkpoint (the "
            "recompute + barriers ARE in the lowered module — asserted "
            "above — but the CPU scheduler's accounting doesn't "
            "reflect the residual savings); the temp-bytes reduction "
            "is only observable on accelerator backends"
        )

    def temp_bytes(ff):
        return (lowered_step(ff).compile()
                .memory_analysis().temp_size_in_bytes)

    assert temp_bytes(ff_remat) < temp_bytes(ff_plain)


def test_inference_liveness_below_sum(devices8):
    ff = _mlp()
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    machine = TpuPodModel(topology=(1,))
    sim = Simulator(machine, OpCostModel(machine))
    g = ff.operators
    inf = sim.per_device_memory(g, training=False)
    everything = sum(
        t.shape.shard_bytes() for op in g.ops for t in op.outputs
    ) + sum(w.shape.shard_bytes() for op in g.ops for w in op.weights)
    # liveness peak must beat the sum-of-all-tensors accounting
    assert inf < everything


def test_memory_search_accepts_previously_rejected(devices8):
    """A budget between the new (accurate) and old (inflated) numbers:
    the inflated model pushed memory_search into a degraded strategy,
    the liveness model keeps the fast one."""
    from flexflow_tpu.pcg.unity import UnitySearch

    ff = _mlp(batch=64, width=256, layers=6)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    machine = TpuPodModel(topology=(8,))
    cm = OpCostModel(machine)
    sim = Simulator(machine, cm, optimizer_slots=2)
    g = ff.layers

    # what the unconstrained search would pick, and its footprints
    free = UnitySearch(g, 8, machine, cm, budget=64).optimize()
    assert free is not None
    new_model_bytes = sim.per_device_memory(g, training=True)
    old_model_bytes = int(
        (2 + 2) * sum(w.shape.shard_bytes() for op in g.ops
                      for w in op.weights)
        + sum(t.shape.shard_bytes() for op in g.ops for t in op.outputs)
    )
    assert new_model_bytes < old_model_bytes
    budget = (new_model_bytes + old_model_bytes) // 2

    search = UnitySearch(g, 8, machine, cm, budget=64,
                         memory_budget=budget)
    chosen = search.optimize_with_memory()
    assert chosen is not None
    # fits under the budget per the accurate model — the old model
    # would have judged this same graph over budget and forced lambda
    # escalation
    assert search._strategy_memory(chosen) <= budget
