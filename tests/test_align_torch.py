"""Op-level numerical alignment vs CPU PyTorch (reference tests/align:
run the op in FF and in torch, compare tensors).  Each case builds a
single-op FFModel, copies torch's weights in, and compares forward
outputs on the same inputs."""
import numpy as np
import pytest
import torch
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer

RTOL, ATOL = 2e-5, 2e-5


def _compile(ff, devices):
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices[:1])
    return ff


def test_align_dense(devices8):
    torch.manual_seed(0)
    tm = nn.Linear(16, 32)
    ff = FFModel(FFConfig(batch_size=8))
    x = ff.create_tensor([8, 16], name="x")
    ff.dense(x, 32, name="fc")
    _compile(ff, devices8)
    ff.set_weights({"fc": {
        "kernel": tm.weight.detach().numpy().T,
        "bias": tm.bias.detach().numpy(),
    }})
    xs = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_align_conv2d(devices8):
    torch.manual_seed(1)
    tm = nn.Conv2d(3, 8, 3, stride=2, padding=1)
    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 3, 16, 16], name="x")
    ff.conv2d(x, 8, 3, 3, 2, 2, 1, 1, name="conv")
    _compile(ff, devices8)
    ff.set_weights({"conv": {
        "kernel": tm.weight.detach().numpy(),
        "bias": tm.bias.detach().numpy(),
    }})
    xs = np.random.RandomState(1).randn(4, 3, 16, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_align_layernorm(devices8):
    torch.manual_seed(2)
    tm = nn.LayerNorm(12)
    with torch.no_grad():
        tm.weight.mul_(1.5).add_(0.1)
        tm.bias.add_(0.2)
    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 6, 12], name="x")
    ff.layer_norm(x, axes=[-1], name="ln")
    _compile(ff, devices8)
    ff.set_weights({"ln": {
        "gamma": tm.weight.detach().numpy(),
        "beta": tm.bias.detach().numpy(),
    }})
    xs = np.random.RandomState(2).randn(4, 6, 12).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_align_pool_softmax_activations(devices8):
    xs = np.random.RandomState(3).randn(4, 3, 8, 8).astype(np.float32)
    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 3, 8, 8], name="x")
    t = ff.pool2d(x, 2, 2, 2, 2, name="pool")
    t = ff.relu(t, inplace=False)
    t = ff.flat(t)
    ff.softmax(t)
    _compile(ff, devices8)
    want = torch.nn.functional.max_pool2d(torch.from_numpy(xs), 2, 2)
    want = torch.relu(want).flatten(1)
    want = torch.softmax(want, dim=-1).numpy()
    np.testing.assert_allclose(np.asarray(ff.forward({"x": xs})), want,
                               rtol=RTOL, atol=ATOL)


def test_align_embedding(devices8):
    torch.manual_seed(4)
    tm = nn.Embedding(50, 12)
    ff = FFModel(FFConfig(batch_size=4))
    from flexflow_tpu.fftype import AggrMode

    x = ff.create_tensor([4, 6], dtype="int32", name="x")
    ff.embedding(x, 50, 12, aggr=AggrMode.NONE, name="emb")
    _compile(ff, devices8)
    ff.set_weights({"emb": {"weight": tm.weight.detach().numpy()}})
    xs = np.random.RandomState(4).randint(0, 50, (4, 6)).astype(np.int32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs.astype(np.int64))).detach().numpy(),
        rtol=RTOL, atol=ATOL)


def test_align_lstm(devices8):
    """LSTM vs torch.nn.LSTM (single layer, batch_first)."""
    torch.manual_seed(5)
    hidden, din = 8, 6
    tm = nn.LSTM(din, hidden, batch_first=True)
    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 5, din], name="x")
    ff.lstm(x, hidden, name="lstm")
    _compile(ff, devices8)

    # torch packs gates as [i, f, g, o] over 4H rows; our kernel is
    # [din+hidden, 4H] with the same gate order
    w_ih = tm.weight_ih_l0.detach().numpy()  # [4H, din]
    w_hh = tm.weight_hh_l0.detach().numpy()  # [4H, H]
    kernel = np.concatenate([w_ih.T, w_hh.T], axis=0)  # [din+H, 4H]
    bias = (tm.bias_ih_l0 + tm.bias_hh_l0).detach().numpy()
    ff.set_weights({"lstm": {"kernel": kernel, "bias": bias}})

    xs = np.random.RandomState(5).randn(4, 5, din).astype(np.float32)
    want, _ = tm(torch.from_numpy(xs))
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})), want.detach().numpy(),
        rtol=1e-4, atol=1e-4)


def test_align_batch_matmul(devices8):
    a = np.random.RandomState(6).randn(3, 4, 5).astype(np.float32)
    b = np.random.RandomState(7).randn(3, 5, 6).astype(np.float32)
    ff = FFModel(FFConfig(batch_size=3))
    ta = ff.create_tensor([3, 4, 5], name="a")
    tb = ff.create_tensor([3, 5, 6], name="b")
    ff.batch_matmul(ta, tb)
    _compile(ff, devices8)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"a": a, "b": b})),
        torch.bmm(torch.from_numpy(a), torch.from_numpy(b)).numpy(),
        rtol=RTOL, atol=ATOL)


# -- r04 additions (VERDICT Weak #7): attention, MoE quartet, GPT block --

def _mha_weights_from_torch(tm, num_heads):
    """torch nn.MultiheadAttention in_proj/out_proj -> our per-head
    wq/wk/wv [E, H, C] and wo [H, C, E] layout."""
    E = tm.embed_dim
    C = E // num_heads
    ipw = tm.in_proj_weight.detach().numpy()       # [3E, E]
    ipb = tm.in_proj_bias.detach().numpy()         # [3E]
    opw = tm.out_proj.weight.detach().numpy()      # [E, E]
    opb = tm.out_proj.bias.detach().numpy()        # [E]
    wq, wk, wv = ipw[:E], ipw[E:2 * E], ipw[2 * E:]
    bq, bk, bv = ipb[:E], ipb[E:2 * E], ipb[2 * E:]

    def per_head(w):  # [E_out, E_in] -> [E_in, H, C]
        return w.reshape(num_heads, C, E).transpose(2, 0, 1)

    return {
        "wq": per_head(wq), "wk": per_head(wk), "wv": per_head(wv),
        "bq": bq.reshape(num_heads, C), "bk": bk.reshape(num_heads, C),
        "bv": bv.reshape(num_heads, C),
        "wo": opw.reshape(E, num_heads, C).transpose(1, 2, 0),
        "bo": opb,
    }


@pytest.mark.parametrize("causal", [False, True])
def test_align_multihead_attention(devices8, causal):
    torch.manual_seed(4)
    B, S, E, H = 4, 10, 32, 4
    tm = nn.MultiheadAttention(E, H, bias=True, batch_first=True)
    ff = FFModel(FFConfig(batch_size=B))
    x = ff.create_tensor([B, S, E], name="x")
    ff.multihead_attention(x, x, x, E, H, bias=True, causal=causal,
                           name="attn")
    _compile(ff, devices8)
    ff.set_weights({"attn": _mha_weights_from_torch(tm, H)})
    xs = np.random.RandomState(4).randn(B, S, E).astype(np.float32)
    xt = torch.from_numpy(xs)
    mask = (torch.triu(torch.ones(S, S), diagonal=1).bool()
            if causal else None)
    want = tm(xt, xt, xt, attn_mask=mask, need_weights=False)[0]
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        want.detach().numpy(), rtol=1e-4, atol=1e-4)


def _torch_moe_dispatch(x, scores, assign, n, cap):
    """Reference dispatch semantics in plain torch: flat token-slot
    order is the priority (rank within expert by flat index), tokens
    beyond capacity dropped; combine renormalizes scores over ALL k
    (dropped slots keep their denominator share and contribute zero)."""
    b, k = assign.shape
    d = x.shape[1]
    flat = assign.reshape(-1)
    grouped = torch.zeros(n, cap, d)
    rank = torch.zeros(b * k, dtype=torch.long)
    counts = torch.zeros(n, dtype=torch.long)
    for i in range(b * k):
        e = int(flat[i])
        rank[i] = counts[e]
        counts[e] += 1
        if rank[i] < cap:
            grouped[e, rank[i]] = x[i // k]
    return grouped, rank


def test_align_moe_quartet(devices8):
    """topk -> group_by -> experts_dense -> aggregate vs a plain-torch
    replica of the reference's capacity-bounded dispatch
    (group_by.cu/aggregate.cu semantics)."""
    torch.manual_seed(5)
    B, D, N, K, HID = 16, 8, 4, 2, 12
    ALPHA = 1.0
    import math
    CAP = max(1, int(math.ceil(ALPHA * K * B / N)))

    ff = FFModel(FFConfig(batch_size=B))
    x = ff.create_tensor([B, D], name="x")
    logits = ff.create_tensor([B, N], name="logits")
    sm = ff.softmax(logits)
    values, assign = ff.top_k(sm, K)
    grouped = ff.group_by(x, assign, N, ALPHA, name="grp")
    hidden = ff.experts_dense(grouped, HID, name="experts")
    ff.aggregate(values, assign, sm, hidden, N, name="agg")
    _compile(ff, devices8)

    rs = np.random.RandomState(5)
    ew = rs.randn(N, D, HID).astype(np.float32) * 0.3
    eb = rs.randn(N, HID).astype(np.float32) * 0.1
    ff.set_weights({"experts": {"kernel": ew, "bias": eb}})

    xs = rs.randn(B, D).astype(np.float32)
    lg = rs.randn(B, N).astype(np.float32)
    got = np.asarray(ff.forward({"x": xs, "logits": lg}))

    smt = torch.softmax(torch.from_numpy(lg), dim=-1)
    scores, assign_t = torch.topk(smt, K, dim=-1)
    grouped_t, rank = _torch_moe_dispatch(
        torch.from_numpy(xs), scores, assign_t, N, CAP)
    hid = torch.einsum("ncd,ndo->nco", grouped_t, torch.from_numpy(ew)) \
        + torch.from_numpy(eb)[:, None, :]
    norm = scores / (scores.sum(-1, keepdim=True) + 1e-9)
    out = torch.zeros(B, HID)
    flat = assign_t.reshape(-1)
    for i in range(B * K):
        if rank[i] < CAP:
            out[i // K] += norm.reshape(-1)[i] * hid[int(flat[i]), rank[i]]
    np.testing.assert_allclose(got, out.numpy(), rtol=1e-4, atol=1e-4)


class _TorchGPT2Block(nn.Module):
    """Pre-LN GPT-2 block; GELU tanh-approx to match jax.nn.gelu."""

    def __init__(self, E, H):
        super().__init__()
        self.ln1 = nn.LayerNorm(E)
        self.attn = nn.MultiheadAttention(E, H, bias=True, batch_first=True)
        self.ln2 = nn.LayerNorm(E)
        self.fc1 = nn.Linear(E, 4 * E)
        self.fc2 = nn.Linear(4 * E, E)
        self.act = nn.GELU(approximate="tanh")

    def forward(self, x):
        S = x.shape[1]
        mask = torch.triu(torch.ones(S, S), diagonal=1).bool()
        h = self.ln1(x)
        a = self.attn(h, h, h, attn_mask=mask, need_weights=False)[0]
        x = x + a
        return x + self.fc2(self.act(self.fc1(self.ln2(x))))


def test_align_gpt2_block(devices8):
    """A full causal pre-LN transformer block aligns end-to-end
    (reference tests/align runs a whole mt5 encoder; this is the GPT
    analogue)."""
    torch.manual_seed(6)
    B, S, E, H = 2, 12, 32, 4
    tm = _TorchGPT2Block(E, H)

    ff = FFModel(FFConfig(batch_size=B))
    x = ff.create_tensor([B, S, E], name="x")
    h = ff.layer_norm(x, axes=[-1], name="ln1")
    a = ff.multihead_attention(h, h, h, E, H, bias=True, causal=True,
                               name="attn")
    t = ff.add(x, a)
    m = ff.layer_norm(t, axes=[-1], name="ln2")
    m = ff.dense(m, 4 * E, name="fc1")
    m = ff.gelu(m)
    m = ff.dense(m, E, name="fc2")
    ff.add(t, m)
    _compile(ff, devices8)

    ff.set_weights({
        "ln1": {"gamma": tm.ln1.weight.detach().numpy(),
                "beta": tm.ln1.bias.detach().numpy()},
        "ln2": {"gamma": tm.ln2.weight.detach().numpy(),
                "beta": tm.ln2.bias.detach().numpy()},
        "attn": _mha_weights_from_torch(tm.attn, H),
        "fc1": {"kernel": tm.fc1.weight.detach().numpy().T,
                "bias": tm.fc1.bias.detach().numpy()},
        "fc2": {"kernel": tm.fc2.weight.detach().numpy().T,
                "bias": tm.fc2.bias.detach().numpy()},
    })
    xs = np.random.RandomState(6).randn(B, S, E).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=1e-4, atol=1e-4)
