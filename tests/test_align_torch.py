"""Op-level numerical alignment vs CPU PyTorch (reference tests/align:
run the op in FF and in torch, compare tensors).  Each case builds a
single-op FFModel, copies torch's weights in, and compares forward
outputs on the same inputs."""
import numpy as np
import pytest
import torch
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer

RTOL, ATOL = 2e-5, 2e-5


def _compile(ff, devices):
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices[:1])
    return ff


def test_align_dense(devices8):
    torch.manual_seed(0)
    tm = nn.Linear(16, 32)
    ff = FFModel(FFConfig(batch_size=8))
    x = ff.create_tensor([8, 16], name="x")
    ff.dense(x, 32, name="fc")
    _compile(ff, devices8)
    ff.set_weights({"fc": {
        "kernel": tm.weight.detach().numpy().T,
        "bias": tm.bias.detach().numpy(),
    }})
    xs = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_align_conv2d(devices8):
    torch.manual_seed(1)
    tm = nn.Conv2d(3, 8, 3, stride=2, padding=1)
    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 3, 16, 16], name="x")
    ff.conv2d(x, 8, 3, 3, 2, 2, 1, 1, name="conv")
    _compile(ff, devices8)
    ff.set_weights({"conv": {
        "kernel": tm.weight.detach().numpy(),
        "bias": tm.bias.detach().numpy(),
    }})
    xs = np.random.RandomState(1).randn(4, 3, 16, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_align_layernorm(devices8):
    torch.manual_seed(2)
    tm = nn.LayerNorm(12)
    with torch.no_grad():
        tm.weight.mul_(1.5).add_(0.1)
        tm.bias.add_(0.2)
    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 6, 12], name="x")
    ff.layer_norm(x, axes=[-1], name="ln")
    _compile(ff, devices8)
    ff.set_weights({"ln": {
        "gamma": tm.weight.detach().numpy(),
        "beta": tm.bias.detach().numpy(),
    }})
    xs = np.random.RandomState(2).randn(4, 6, 12).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=RTOL, atol=ATOL)


def test_align_pool_softmax_activations(devices8):
    xs = np.random.RandomState(3).randn(4, 3, 8, 8).astype(np.float32)
    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 3, 8, 8], name="x")
    t = ff.pool2d(x, 2, 2, 2, 2, name="pool")
    t = ff.relu(t, inplace=False)
    t = ff.flat(t)
    ff.softmax(t)
    _compile(ff, devices8)
    want = torch.nn.functional.max_pool2d(torch.from_numpy(xs), 2, 2)
    want = torch.relu(want).flatten(1)
    want = torch.softmax(want, dim=-1).numpy()
    np.testing.assert_allclose(np.asarray(ff.forward({"x": xs})), want,
                               rtol=RTOL, atol=ATOL)


def test_align_embedding(devices8):
    torch.manual_seed(4)
    tm = nn.Embedding(50, 12)
    ff = FFModel(FFConfig(batch_size=4))
    from flexflow_tpu.fftype import AggrMode

    x = ff.create_tensor([4, 6], dtype="int32", name="x")
    ff.embedding(x, 50, 12, aggr=AggrMode.NONE, name="emb")
    _compile(ff, devices8)
    ff.set_weights({"emb": {"weight": tm.weight.detach().numpy()}})
    xs = np.random.RandomState(4).randint(0, 50, (4, 6)).astype(np.int32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})),
        tm(torch.from_numpy(xs.astype(np.int64))).detach().numpy(),
        rtol=RTOL, atol=ATOL)


def test_align_lstm(devices8):
    """LSTM vs torch.nn.LSTM (single layer, batch_first)."""
    torch.manual_seed(5)
    hidden, din = 8, 6
    tm = nn.LSTM(din, hidden, batch_first=True)
    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 5, din], name="x")
    ff.lstm(x, hidden, name="lstm")
    _compile(ff, devices8)

    # torch packs gates as [i, f, g, o] over 4H rows; our kernel is
    # [din+hidden, 4H] with the same gate order
    w_ih = tm.weight_ih_l0.detach().numpy()  # [4H, din]
    w_hh = tm.weight_hh_l0.detach().numpy()  # [4H, H]
    kernel = np.concatenate([w_ih.T, w_hh.T], axis=0)  # [din+H, 4H]
    bias = (tm.bias_ih_l0 + tm.bias_hh_l0).detach().numpy()
    ff.set_weights({"lstm": {"kernel": kernel, "bias": bias}})

    xs = np.random.RandomState(5).randn(4, 5, din).astype(np.float32)
    want, _ = tm(torch.from_numpy(xs))
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": xs})), want.detach().numpy(),
        rtol=1e-4, atol=1e-4)


def test_align_batch_matmul(devices8):
    a = np.random.RandomState(6).randn(3, 4, 5).astype(np.float32)
    b = np.random.RandomState(7).randn(3, 5, 6).astype(np.float32)
    ff = FFModel(FFConfig(batch_size=3))
    ta = ff.create_tensor([3, 4, 5], name="a")
    tb = ff.create_tensor([3, 5, 6], name="b")
    ff.batch_matmul(ta, tb)
    _compile(ff, devices8)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"a": a, "b": b})),
        torch.bmm(torch.from_numpy(a), torch.from_numpy(b)).numpy(),
        rtol=RTOL, atol=ATOL)
