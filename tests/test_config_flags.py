"""Every FFConfig field has a consumer (VERDICT r1 Weak #4).

Reference flag semantics: config.h:92-160 + parse_args
model.cc:3556-3720.  Covers: weight_decay -> default optimizer,
--fusion compile pass, sample parallelism, ParameterSyncType PS cost
model, --search-overlap-backward-update sync credit,
--simulator-segment-size search cap, --include-costs-dot-graph, and
strategy-reachable FusedParallelOp.
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import ActiMode, OperatorType, ParameterSyncType
from flexflow_tpu.strategy import Strategy, data_parallel_strategy


def _mlp_relu(cfg):
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 16], name="x")
    t = ff.dense(x, 32, name="fc1")
    t = ff.relu(t)
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t)
    return ff


def test_weight_decay_reaches_default_optimizer(devices8):
    cfg = FFConfig(batch_size=8, weight_decay=0.123)
    ff = _mlp_relu(cfg)
    ff.compile(devices=devices8[:1])
    assert ff.optimizer.weight_decay == pytest.approx(0.123)


def test_perform_fusion_folds_activations(devices8):
    cfg = FFConfig(batch_size=8, perform_fusion=True)
    ff = _mlp_relu(cfg)
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])
    types = [op.op_type for op in ff.operators.ops]
    assert OperatorType.ELEMENT_UNARY not in types
    fused = next(op for op in ff.operators.ops if op.name == "fc1")
    assert fused.params.activation == ActiMode.RELU
    x = np.random.randn(8, 16).astype(np.float32)
    y = np.random.randint(0, 4, (8,))
    assert np.isfinite(float(ff.train_step({"x": x}, y)["loss"]))


def test_perform_fusion_respects_strategy_references(devices8):
    """A strategy edge chain on the relu output tensor protects it."""
    cfg = FFConfig(batch_size=8, num_devices=2, perform_fusion=True)
    ff = _mlp_relu(cfg)
    relu_out = next(
        op for op in ff.layers.ops
        if op.op_type == OperatorType.ELEMENT_UNARY
    ).outputs[0].name
    s = Strategy(mesh_axes={"data": 2})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 2})]
    s.edge_ops[relu_out] = [
        ("combine", {"dim": 0, "degree": 2}),
        ("repartition", {"dim": 0, "degree": 2}),
    ]
    ff.compile(optimizer=SGDOptimizer(lr=0.01), strategy=s,
               devices=devices8[:2])
    assert any(
        op.op_type == OperatorType.ELEMENT_UNARY for op in ff.operators.ops
    )


def test_sample_parallel_candidates_and_training(devices8):
    from flexflow_tpu.pcg.unity import UnitySearch
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel

    cfg = FFConfig(batch_size=8, num_devices=8, enable_sample_parallel=True)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16, 32], name="x")  # [b, rows, d]
    t = ff.dense(x, 32, activation=ActiMode.RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    t = ff.softmax(t)
    machine = TpuPodModel(topology=(2, 4))
    search = UnitySearch(ff.layers, 8, machine, OpCostModel(machine),
                         enable_sample_parallel=True,
                         rewrite_max_variants=1)
    cands = list(search._sample_candidates())
    assert cands, "sample-parallel candidates missing"
    meshes = [s.mesh_axes for s, _, _, _ in cands]
    assert any("sample" in m for m in meshes)
    # disabled flag -> no candidates
    search_off = UnitySearch(ff.layers, 8, machine, OpCostModel(machine),
                             rewrite_max_variants=1)
    assert not list(search_off._sample_candidates())
    # one of them trains end to end on the CPU mesh
    s = next(s for s, _, _, _ in cands if s.total_devices == 8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01), strategy=s,
               devices=devices8[:8])
    xx = np.random.randn(8, 16, 32).astype(np.float32)
    yy = np.random.randint(0, 4, (8, 16))  # per-row labels
    assert np.isfinite(float(ff.train_step({"x": xx}, yy)["loss"]))


def test_parameter_sync_ps_changes_sync_cost():
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import Simulator

    m = TpuPodModel(topology=(2, 4))
    ar = Simulator(m)
    ps = Simulator(m, parameter_sync="ps")
    size = 64 * 1024**2
    assert ar.sync_time(size, 8) != ps.sync_time(size, 8)
    # PS estimate is the reference's flat 2*size/BW + latency
    bw, lat = m.ps_link()
    assert ps.sync_time(size, 8) == pytest.approx(2 * lat + 2 * size / bw)
    # NONE means no gradient sync at all (reference config.h:55)
    none = Simulator(m, parameter_sync="none")
    assert none.sync_time(size, 8) == 0.0


def test_search_overlap_backward_update_credits_sync():
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import Simulator
    from flexflow_tpu.strategy import apply_strategy, assign_views

    cfg = FFConfig(batch_size=64)
    ff = _mlp_relu(cfg)
    s = data_parallel_strategy(8)
    g = apply_strategy(ff.layers, s)
    assign_views(g, s.mesh_axes)
    m = TpuPodModel(topology=(2, 4))
    base = Simulator(m).simulate(g, s.mesh_axes)
    overlapped = Simulator(m, sync_overlap_fraction=0.7).simulate(
        g, s.mesh_axes
    )
    assert base.sync_time > 0
    assert overlapped.total_time < base.total_time


def test_simulator_segment_size_lowers_search_cap():
    from flexflow_tpu.pcg.unity import UnitySearch, _MAX_SEGMENT_ASSIGNMENTS
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel

    cfg = FFConfig(batch_size=8)
    ff = _mlp_relu(cfg)
    m = TpuPodModel(topology=(2, 4))
    s = UnitySearch(ff.layers, 8, m, OpCostModel(m), max_assignments=7)
    assert s._cap() == 7
    s2 = UnitySearch(ff.layers, 8, m, OpCostModel(m),
                     max_assignments=10 ** 12)
    assert s2._cap() == _MAX_SEGMENT_ASSIGNMENTS


def test_include_costs_dot_graph(tmp_path, devices8):
    path = str(tmp_path / "taskgraph.dot")
    cfg = FFConfig(batch_size=8, export_taskgraph_file=path,
                   include_costs_dot_graph=True)
    ff = _mlp_relu(cfg)
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])
    text = open(path).read()
    assert "cost=" in text


def test_fused_parallel_op_strategy_reachable(devices8):
    """FusedParallelOp is emittable from a Strategy edge chain, costed
    by the simulator, and JSON round-trips (reference
    fused_parallel_op.cc)."""
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import Simulator

    cfg = FFConfig(batch_size=8, num_devices=4)
    ff = _mlp_relu(cfg)
    s = Strategy(mesh_axes={"data": 4})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 4})]
    s.edge_ops["fc1.out0"] = [(
        "fused",
        {"ops": [["combine", {"dim": 0, "degree": 2}],
                 ["repartition", {"dim": 0, "degree": 2}]]},
    )]
    text = s.to_json()
    s2 = Strategy.from_json(text)
    assert s2.edge_ops["fc1.out0"][0][0] == "fused"

    ff.compile(optimizer=SGDOptimizer(lr=0.01), strategy=s2,
               devices=devices8[:4])
    fused_ops = [
        op for op in ff.operators.ops
        if op.op_type == OperatorType.FUSED_PARALLEL
    ]
    assert fused_ops
    m = TpuPodModel(topology=(2, 2))
    assert Simulator(m).xfer_cost(fused_ops[0], s2.mesh_axes) > 0
    x = np.random.randn(8, 16).astype(np.float32)
    y = np.random.randint(0, 4, (8,))
    assert np.isfinite(float(ff.train_step({"x": x}, y)["loss"]))


def test_cli_flags_parse():
    cfg = FFConfig.from_args([
        "--enable-sample-parallel", "--search-overlap-backward-update",
        "--parameter-sync", "ps", "--fusion",
        "--simulator-segment-size", "128",
    ])
    assert cfg.enable_sample_parallel
    assert cfg.search_overlap_backward_update
    assert cfg.parameter_sync == ParameterSyncType.PS
    assert cfg.perform_fusion
    assert cfg.simulator_segment_size == 128


def test_resilience_cli_flags_parse():
    cfg = FFConfig.from_args([
        "--checkpoint-every", "5", "--checkpoint-dir", "/tmp/ckpt",
        "--checkpoint-keep", "2", "--max-restarts", "7",
        "--retry-backoff", "0.5", "--nan-policy", "skip_step",
    ])
    assert cfg.checkpoint_every == 5
    assert cfg.checkpoint_dir == "/tmp/ckpt"
    assert cfg.checkpoint_keep == 2
    assert cfg.max_restarts == 7
    assert cfg.retry_backoff == pytest.approx(0.5)
    assert cfg.nan_policy == "skip_step"
    # defaults: resilience off until opted into
    base = FFConfig.from_args([])
    assert base.checkpoint_every == 0 and base.nan_policy == "raise"


def test_durability_cli_flags_parse():
    cfg = FFConfig.from_args([
        "--checkpoint-async", "--step-timeout", "45.5", "--no-preempt-grace",
    ])
    assert cfg.checkpoint_async is True
    assert cfg.step_timeout == pytest.approx(45.5)
    assert cfg.preempt_grace is False
    # defaults: sync saves, watchdog off, grace on
    base = FFConfig.from_args([])
    assert base.checkpoint_async is False
    assert base.step_timeout == 0.0
    assert base.preempt_grace is True


def test_offload_cli_flags_parse():
    cfg = FFConfig.from_args([
        "--remote-store", "file:///fleet/ckpt", "--offload-every", "4",
        "--remote-keep", "5",
    ])
    assert cfg.remote_store == "file:///fleet/ckpt"
    assert cfg.offload_every == 4
    assert cfg.remote_keep == 5
    # defaults: no remote tier, mirror every verified save, keep 3
    base = FFConfig.from_args([])
    assert base.remote_store is None
    assert base.offload_every == 1
    assert base.remote_keep == 3
    # explicit opt-out (the --no-strategy-store pattern)
    off = FFConfig.from_args(["--no-remote-store"])
    assert off.remote_store == "none"
    from flexflow_tpu.resilience.offload import offloader_from_config

    assert offloader_from_config(off) is None
    assert offloader_from_config(base) is None


def test_offload_config_validated():
    with pytest.raises(ValueError):
        FFConfig(offload_every=0)
    with pytest.raises(ValueError):
        FFConfig(remote_keep=0)
    with pytest.raises(ValueError):
        FFConfig(barrier_timeout=0.0)


def test_barrier_timeout_flag_parses():
    cfg = FFConfig.from_args(["--barrier-timeout", "5.5"])
    assert cfg.barrier_timeout == 5.5
    assert FFConfig.from_args([]).barrier_timeout == 30.0


def test_serving_cli_flags_parse():
    cfg = FFConfig.from_args([
        "--serving-mode", "static", "--kv-page-size", "8",
        "--kv-pool-blocks", "65", "--serving-slots", "16",
    ])
    assert cfg.serving_mode == "static"
    assert cfg.kv_page_size == 8
    assert cfg.kv_pool_blocks == 65
    assert cfg.serving_slots == 16
    # defaults: continuous with auto-sized pool
    base = FFConfig.from_args([])
    assert base.serving_mode == "continuous"
    assert base.kv_page_size == 16
    assert base.kv_pool_blocks == 0
    assert base.serving_slots == 8


def test_serving_config_validated():
    with pytest.raises(ValueError):
        FFConfig(serving_mode="bogus")
    with pytest.raises(ValueError):
        FFConfig(kv_page_size=0)
    with pytest.raises(ValueError):
        FFConfig(kv_pool_blocks=-1)
    with pytest.raises(ValueError):
        FFConfig(serving_slots=0)
    with pytest.raises(ValueError):
        FFConfig(prefill_chunk=-1)


def test_prefix_cache_cli_flags_parse():
    cfg = FFConfig.from_args(["--prefill-chunk", "16",
                              "--no-prefix-cache"])
    assert cfg.prefill_chunk == 16
    assert cfg.prefix_cache is False
    base = FFConfig.from_args([])
    assert base.prefill_chunk == 8      # chunked prefill on by default
    assert base.prefix_cache is True    # sharing on by default
    assert FFConfig.from_args(["--prefill-chunk", "0"]).prefill_chunk == 0


def test_serving_front_cli_flags_parse():
    cfg = FFConfig.from_args([
        "--serving-replicas", "3", "--serving-step-timeout", "2.5",
        "--serving-max-restarts", "5", "--request-retry-limit", "4",
    ])
    assert cfg.serving_replicas == 3
    assert cfg.serving_step_timeout == 2.5
    assert cfg.serving_max_restarts == 5
    assert cfg.request_retry_limit == 4
    base = FFConfig.from_args([])
    assert base.serving_replicas == 1
    assert base.serving_step_timeout == 0.0  # decode watchdog off
    assert base.serving_max_restarts == 3
    assert base.request_retry_limit == 2


def test_serving_front_config_validated():
    with pytest.raises(ValueError):
        FFConfig(serving_replicas=0)
    with pytest.raises(ValueError):
        FFConfig(serving_step_timeout=-1.0)
    with pytest.raises(ValueError):
        FFConfig(serving_max_restarts=-1)
    with pytest.raises(ValueError):
        FFConfig(request_retry_limit=-1)


def test_store_cli_flags_parse(monkeypatch):
    cfg = FFConfig.from_args([
        "--strategy-store", "/tmp/fleet_store",
        "--compilation-cache", "/tmp/xla",
    ])
    assert cfg.strategy_store == "/tmp/fleet_store"
    assert cfg.resolve_store_dir() == "/tmp/fleet_store"
    assert cfg.compilation_cache == "/tmp/xla"
    # bare --compilation-cache ties the XLA cache to the store root
    auto = FFConfig.from_args(["--strategy-store", "/tmp/s",
                               "--compilation-cache"])
    assert auto.compilation_cache == "auto"
    # --no-strategy-store opts out even when the fleet env var is set
    monkeypatch.setenv("FLEXFLOW_TPU_STORE_DIR", "/tmp/fleet_store")
    off = FFConfig.from_args(["--no-strategy-store"])
    assert off.strategy_store == "none"
    assert off.resolve_store_dir() is None
    # defaults: no store unless the env var names one
    base = FFConfig.from_args([])
    assert base.strategy_store is None
    assert base.compilation_cache is None
    assert base.resolve_store_dir() == "/tmp/fleet_store"  # env fallback
    monkeypatch.delenv("FLEXFLOW_TPU_STORE_DIR")
    assert base.resolve_store_dir() is None


def test_store_config_validated():
    with pytest.raises(ValueError):
        FFConfig(compilation_cache="")
    with pytest.raises(ValueError):
        FFConfig(compilation_cache="   ")
    # None disables, paths and "auto" are fine
    FFConfig(compilation_cache=None)
    FFConfig(compilation_cache="auto")
    FFConfig(compilation_cache="/tmp/xla")


def test_resilience_config_validated():
    with pytest.raises(ValueError):
        FFConfig(nan_policy="bogus")
    with pytest.raises(ValueError):
        FFConfig(checkpoint_every=-1)
    with pytest.raises(ValueError):
        FFConfig(checkpoint_keep=0)
    with pytest.raises(ValueError):
        FFConfig(max_restarts=-2)
    with pytest.raises(ValueError):
        FFConfig(retry_backoff=-0.1)
    with pytest.raises(ValueError):
        FFConfig(step_timeout=-1.0)


def test_remat_matches_nonremat_numerics_and_inserts_checkpoint(devices8):
    """--remat wraps pure segments in jax.checkpoint: identical math,
    recomputed backward (TPU-native HBM/FLOPs trade)."""
    import jax

    def build(remat):
        cfg = FFConfig(batch_size=8, remat=remat)
        ff = _mlp_relu(cfg)
        ff.compile(optimizer=SGDOptimizer(lr=0.05), devices=devices8[:1],
                   seed=3)
        return ff

    ff_a, ff_b = build(False), build(True)
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, (8,))
    la = [float(ff_a.train_step({"x": x}, y)["loss"]) for _ in range(4)]
    lb = [float(ff_b.train_step({"x": x}, y)["loss"]) for _ in range(4)]
    np.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-6)

    # the remat step's jaxpr actually carries checkpoint/remat regions
    ex = ff_b.executor
    xx, yy = ff_b._device_put_batch({"x": x}, y)
    jaxpr = str(jax.make_jaxpr(ex.build_step())(
        ff_b._weights, ff_b._opt_state, ff_b._state, xx, yy,
        jax.random.key(0),
    ))
    assert "remat" in jaxpr
    assert ex._remat_plan is not None
    assert any(pure for _, _, _, pure in ex._remat_plan)
    assert ff_a.executor._remat_plan is None


def test_topology_cli_flags_parse():
    cfg = FFConfig.from_args([
        "--slices", "2", "--dcn-bandwidth", "5e9",
        "--dcn-latency", "2e-5", "--slice-topology", "2,2",
    ])
    assert cfg.slices == 2
    assert cfg.dcn_bandwidth == pytest.approx(5e9)
    assert cfg.dcn_latency == pytest.approx(2e-5)
    assert cfg.slice_topology == "2,2"
    # defaults: 1 slice = exactly the flat pre-topology behavior
    d = FFConfig.from_args([])
    assert d.slices == 1 and d.slice_topology is None
    assert d.dcn_bandwidth == pytest.approx(25e9)
    assert d.dcn_latency == pytest.approx(10e-6)


def test_topology_config_validated():
    with pytest.raises(ValueError):
        FFConfig(slices=0)
    with pytest.raises(ValueError):
        FFConfig(dcn_bandwidth=0.0)
    with pytest.raises(ValueError):
        FFConfig(dcn_latency=-1e-6)
    with pytest.raises(ValueError):
        FFConfig(slice_topology="zero,4")
    FFConfig(slices=2, slice_topology="4x4")  # valid hierarchy config


def test_slices_selects_hierarchy_machine_model():
    from flexflow_tpu.sim.machine_model import make_machine_model
    from flexflow_tpu.topology.hierarchy import SliceHierarchy

    m = make_machine_model(FFConfig(slices=2, dcn_bandwidth=3e9), 8)
    assert isinstance(m, SliceHierarchy)
    assert m.slices == 2 and m.dcn_bw == pytest.approx(3e9)
    assert m.num_devices() == 8
    flat = make_machine_model(FFConfig(), 8)
    assert not isinstance(flat, SliceHierarchy)


def test_serving_tp_cli_flags_parse():
    cfg = FFConfig.from_args(
        ["--serving-tp", "4", "--serving-chip-budget", "16"])
    assert cfg.serving_tp == 4
    assert cfg.serving_chip_budget == 16
    d = FFConfig.from_args([])
    assert d.serving_tp == 1 and d.serving_chip_budget == 0


def test_serving_tp_config_validated():
    with pytest.raises(ValueError):
        FFConfig(serving_tp=0)
    with pytest.raises(ValueError):
        FFConfig(serving_chip_budget=-1)
    FFConfig(serving_tp=2, serving_chip_budget=8)  # valid


def test_resolve_serving_tp_rejects_bad_degrees():
    """--serving-tp misconfigurations must fail at BUILD time with a
    ConfigError naming the flag, never surface as a mid-compile shape
    error (the resolve_paged_kernel discipline)."""
    from flexflow_tpu.config import ConfigError, resolve_serving_tp

    assert resolve_serving_tp(1) == 1
    assert resolve_serving_tp(2, num_heads=4, visible_devices=8) == 2
    with pytest.raises(ConfigError, match="must be >= 1"):
        resolve_serving_tp(0)
    with pytest.raises(ConfigError, match="does not divide"):
        resolve_serving_tp(3, num_heads=4, visible_devices=8)
    with pytest.raises(ConfigError, match="exceeds the 2 visible"):
        resolve_serving_tp(4, num_heads=4, visible_devices=2)


def test_spec_decode_cli_flags_parse():
    cfg = FFConfig.from_args(["--spec-decode", "ngram", "--spec-k", "6"])
    assert cfg.spec_decode == "ngram"
    assert cfg.spec_k == 6
    cfg = FFConfig.from_args(["--spec-decode", "draft"])
    assert cfg.spec_decode == "draft" and cfg.spec_k == 4
    base = FFConfig.from_args([])
    assert base.spec_decode == "off"  # speculation is opt-in
    assert base.spec_k == 4


def test_spec_decode_config_validated():
    with pytest.raises(ValueError, match="spec_decode"):
        FFConfig(spec_decode="lookahead")
    with pytest.raises(ValueError, match="spec_k"):
        FFConfig(spec_k=0)
    assert FFConfig(spec_decode="ngram", spec_k=8) is not None


def test_resolve_spec_decode_rejects_bad_combos():
    """--spec-decode misconfigurations must fail at BUILD time with a
    ConfigError naming the flag (the resolve_paged_kernel discipline):
    unknown modes, a draft budget under 1, and — because verification
    accepts the longest GREEDY-matching prefix, meaningless across
    beam hypotheses — any combination with beam search."""
    from flexflow_tpu.config import ConfigError, resolve_spec_decode

    assert resolve_spec_decode("off", 4) == "off"
    assert resolve_spec_decode("ngram", 1) == "ngram"
    assert resolve_spec_decode("draft", 4, beam_size=1) == "draft"
    # off tolerates any k/beam — nothing speculative runs
    assert resolve_spec_decode("off", 0, beam_size=4) == "off"
    with pytest.raises(ConfigError, match="--spec-decode must be one"):
        resolve_spec_decode("medusa", 4)
    with pytest.raises(ConfigError, match="--spec-k must be >= 1"):
        resolve_spec_decode("ngram", 0)
    with pytest.raises(ConfigError, match="beam"):
        resolve_spec_decode("ngram", 4, beam_size=4)
    with pytest.raises(ConfigError, match="beam"):
        resolve_spec_decode("draft", 4, beam_size=2)


def test_disagg_cli_flags_parse():
    cfg = FFConfig.from_args([
        "--serving-roles", "prefill=1,decode=2",
        "--kv-transfer", "blob",
        "--migration-cost-cap", "2.5",
        "--autoscale-predictive",
    ])
    assert cfg.serving_roles == "prefill=1,decode=2"
    assert cfg.kv_transfer == "blob"
    assert cfg.migration_cost_cap == 2.5
    assert cfg.autoscale_predictive is True
    base = FFConfig.from_args([])
    assert base.serving_roles == ""  # colocated fleet
    assert base.kv_transfer == "inproc"
    assert base.migration_cost_cap == 1.0
    assert base.autoscale_predictive is False


def test_disagg_config_validated():
    with pytest.raises(ValueError, match="decode-capable"):
        FFConfig(serving_roles="prefill=2")
    with pytest.raises(ValueError, match="unknown role"):
        FFConfig(serving_roles="verify=1")
    with pytest.raises(ValueError, match="kv_transfer"):
        FFConfig(kv_transfer="ftp")
    with pytest.raises(ValueError, match="cost"):
        FFConfig(migration_cost_cap=0.0)
    # a valid roles spec constructs fine
    assert FFConfig(serving_roles="prefill=1,decode=1") is not None
