"""ONNX frontend tests — gated on the onnx package (not baked into this
image; the frontend raises a clear ImportError then)."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType

try:
    import onnx

    HAS_ONNX = True
except ImportError:
    HAS_ONNX = False


def test_onnx_missing_gives_clear_error():
    if HAS_ONNX:
        pytest.skip("onnx present")
    from flexflow_tpu.onnx_frontend import ONNXModel

    with pytest.raises(ImportError, match="torch.fx frontend"):
        ONNXModel("/nonexistent.onnx")


@pytest.mark.skipif(not HAS_ONNX, reason="onnx not installed")
def test_onnx_mlp_roundtrip():
    import onnx.helper as oh

    # tiny Gemm+Relu+Gemm graph built by hand
    w1 = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    w2 = np.random.RandomState(1).randn(4, 16).astype(np.float32)
    nodes = [
        oh.make_node("Gemm", ["x", "w1"], ["h"], transB=1, name="fc1"),
        oh.make_node("Relu", ["h"], ["hr"], name="relu1"),
        oh.make_node("Gemm", ["hr", "w2"], ["y"], transB=1, name="fc2"),
    ]
    graph = oh.make_graph(
        nodes, "mlp",
        [oh.make_tensor_value_info("x", onnx.TensorProto.FLOAT, [8, 8])],
        [oh.make_tensor_value_info("y", onnx.TensorProto.FLOAT, [8, 4])],
        initializer=[
            onnx.numpy_helper.from_array(w1, "w1"),
            onnx.numpy_helper.from_array(w2, "w2"),
        ],
    )
    model = oh.make_model(graph)
    from flexflow_tpu.onnx_frontend import ONNXModel

    ff = FFModel(FFConfig(batch_size=8))
    x = ff.create_tensor([8, 8], name="x")
    om = ONNXModel(model)
    om.apply(ff, [x])
    ff.compile(loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    om.copy_weights(ff)
    xs = np.random.RandomState(2).randn(8, 8).astype(np.float32)
    got = np.asarray(ff.forward({"x": xs}))
    want = np.maximum(xs @ w1.T, 0) @ w2.T
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
