"""ONNX frontend: real serialized graphs through the vendored
wire-format codec (protowire.py — no `onnx` dependency), parsed by
ONNXModel, trained on the CPU mesh, with weight-transfer numerical
parity against direct numpy computation.

Reference counterpart: python/flexflow/onnx/model.py (the CI-run
importer this handler table mirrors).
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.onnx_frontend import ONNXModel
from flexflow_tpu.onnx_frontend import protowire as pw


def _mlp_model_bytes(rng):
    w1 = rng.randn(16, 8).astype(np.float32)
    b1 = rng.randn(16).astype(np.float32)
    w2 = rng.randn(4, 16).astype(np.float32)
    nodes = [
        pw.encode_node("Gemm", ["x", "w1", "b1"], ["h"], name="fc1",
                       transB=1),
        pw.encode_node("Relu", ["h"], ["hr"], name="relu1"),
        pw.encode_node("Gemm", ["hr", "w2"], ["y"], name="fc2", transB=1),
        pw.encode_node("Softmax", ["y"], ["p"], name="sm", axis=-1),
    ]
    data = pw.encode_model(nodes, ["x"], ["p"],
                           {"w1": w1, "b1": b1, "w2": w2})
    return data, (w1, b1, w2)


def test_wire_roundtrip_parses_structure():
    data, _ = _mlp_model_bytes(np.random.RandomState(0))
    m = pw.load_model(data)
    assert [n.op_type for n in m.graph.node] == [
        "Gemm", "Relu", "Gemm", "Softmax"
    ]
    assert [i.name for i in m.graph.input] == ["x"]
    assert [o.name for o in m.graph.output] == ["p"]
    inits = {t.name: t.array for t in m.graph.initializer}
    assert inits["w1"].shape == (16, 8)
    assert inits["w1"].dtype == np.float32
    # attributes decode with type info
    gemm_attrs = {a.name: a.value for a in m.graph.node[0].attribute}
    assert gemm_attrs == {"transB": 1}


def test_wire_tensor_edge_cases():
    # int32_data container with negatives (sign-converted varints)
    t = pw._vi(1, 3) + pw._vi(2, 6)  # dims=[3], data_type=INT32
    for v in (-2, 0, 7):
        t += pw._vi(5, v)
    t += pw._ld(8, b"neg")
    parsed = pw._parse_tensor(t)
    np.testing.assert_array_equal(parsed.array,
                                  np.asarray([-2, 0, 7], np.int32))
    # float16 bit-packed in int32_data
    bits = np.asarray([1.5, -0.25], np.float16).view(np.uint16)
    t2 = pw._vi(1, 2) + pw._vi(2, 10)
    for b in bits:
        t2 += pw._vi(5, int(b))
    parsed2 = pw._parse_tensor(t2)
    np.testing.assert_array_equal(parsed2.array,
                                  np.asarray([1.5, -0.25], np.float16))
    # rank-0 scalar (empty dims + raw_data) decodes 0-d like numpy_helper
    t3 = pw._vi(2, 1) + pw._ld(9, np.float32(3.5).tobytes())
    parsed3 = pw._parse_tensor(t3)
    assert parsed3.array.shape == ()
    assert float(parsed3.array) == 3.5


def test_onnx_mlp_forward_parity_and_training(devices8):
    rng = np.random.RandomState(0)
    data, (w1, b1, w2) = _mlp_model_bytes(rng)

    ff = FFModel(FFConfig(batch_size=8))
    x = ff.create_tensor([8, 8], name="x")
    om = ONNXModel(data)  # bytes -> vendored wire parser
    om.apply(ff, [x])
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    om.copy_weights(ff)

    xs = rng.randn(8, 8).astype(np.float32)
    got = np.asarray(ff.forward({"x": xs}))
    logits = np.maximum(xs @ w1.T + b1, 0) @ w2.T
    want = np.exp(logits - logits.max(-1, keepdims=True))
    want /= want.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    # trains: loss decreases over a few steps on a fixed batch
    ys = rng.randint(0, 4, 8).astype(np.int32)
    losses = [float(ff.train_step({"x": xs}, ys)["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0]


def test_onnx_cnn_forward_parity_and_training(devices8):
    rng = np.random.RandomState(3)
    wc = (rng.randn(4, 3, 3, 3) * 0.2).astype(np.float32)
    bc = rng.randn(4).astype(np.float32)
    wf = (rng.randn(10, 4 * 4 * 4) * 0.2).astype(np.float32)
    nodes = [
        pw.encode_node("Conv", ["x", "wc", "bc"], ["c"], name="conv1",
                       kernel_shape=[3, 3], strides=[1, 1],
                       pads=[1, 1, 1, 1]),
        pw.encode_node("Relu", ["c"], ["cr"], name="relu1"),
        pw.encode_node("MaxPool", ["cr"], ["p1"], name="pool1",
                       kernel_shape=[2, 2], strides=[2, 2]),
        pw.encode_node("Flatten", ["p1"], ["f"], name="flat1"),
        pw.encode_node("Gemm", ["f", "wf"], ["y"], name="fc", transB=1),
        pw.encode_node("Softmax", ["y"], ["out"], name="sm", axis=-1),
    ]
    data = pw.encode_model(nodes, ["x"], ["out"],
                           {"wc": wc, "bc": bc, "wf": wf})

    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 3, 8, 8], name="x")
    om = ONNXModel(data)
    om.apply(ff, [x])
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    om.copy_weights(ff)

    xs = rng.randn(4, 3, 8, 8).astype(np.float32)
    got = np.asarray(ff.forward({"x": xs}))

    # numpy reference: conv 3x3 pad 1 -> relu -> 2x2 maxpool -> fc
    def conv_ref(x, w, b):
        n, cin, h, wdt = x.shape
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        out = np.zeros((n, w.shape[0], h, wdt), np.float32)
        for co in range(w.shape[0]):
            for i in range(h):
                for j in range(wdt):
                    out[:, co, i, j] = np.sum(
                        xp[:, :, i:i + 3, j:j + 3] * w[co], axis=(1, 2, 3)
                    ) + b[co]
        return out

    c = np.maximum(conv_ref(xs, wc, bc), 0)
    p = c.reshape(4, 4, 4, 2, 4, 2).max(axis=(3, 5))
    logits = p.reshape(4, -1) @ wf.T
    want = np.exp(logits - logits.max(-1, keepdims=True))
    want /= want.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    ys = rng.randint(0, 10, 4).astype(np.int32)
    losses = [float(ff.train_step({"x": xs}, ys)["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0]


def test_onnx_elementwise_and_shape_handlers(devices8):
    """Cover the remaining handler set on a real serialized graph:
    MatMul(init) / Add / Mul / Sub / Concat / Transpose / Reshape /
    AveragePool / Sigmoid / Tanh / Identity / Split."""
    rng = np.random.RandomState(5)
    wm = rng.randn(6, 6).astype(np.float32)
    nodes = [
        pw.encode_node("MatMul", ["x", "wm"], ["m"], name="mm"),
        pw.encode_node("Sigmoid", ["m"], ["s"], name="sig"),
        pw.encode_node("Tanh", ["m"], ["t"], name="tanh"),
        pw.encode_node("Add", ["s", "t"], ["a"], name="add"),
        pw.encode_node("Mul", ["s", "t"], ["mu"], name="mul"),
        pw.encode_node("Sub", ["a", "mu"], ["su"], name="sub"),
        pw.encode_node("Identity", ["su"], ["idn"], name="idn"),
        pw.encode_node("Concat", ["idn", "mu"], ["cc"], name="cat", axis=1),
        pw.encode_node("Split", ["cc"], ["s0", "s1"], name="split",
                       split=[6, 6], axis=1),
    ]
    data = pw.encode_model(nodes, ["x"], ["s0"], {"wm": wm})

    ff = FFModel(FFConfig(batch_size=4))
    x = ff.create_tensor([4, 6], name="x")
    om = ONNXModel(data)
    om.apply(ff, [x])
    ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               devices=devices8[:1])
    om.copy_weights(ff)

    xs = rng.randn(4, 6).astype(np.float32)
    got = np.asarray(ff.forward({"x": xs}))
    m = xs @ wm
    s = 1 / (1 + np.exp(-m))
    t = np.tanh(m)
    want = (s + t) - s * t
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_onnx_prefers_installed_package_path():
    """When `onnx` is absent the vendored parser handles str paths too."""
    import os
    import tempfile

    data, _ = _mlp_model_bytes(np.random.RandomState(0))
    with tempfile.NamedTemporaryFile(suffix=".onnx", delete=False) as f:
        f.write(data)
        path = f.name
    try:
        om = ONNXModel(path)
        assert [n.op_type for n in om.graph.node][0] == "Gemm"
    finally:
        os.unlink(path)


def test_wire_truncated_raises_clear_error():
    """A truncated/corrupt buffer raises ValueError('truncated...')
    instead of silently misparsing short slices (ADVICE r03)."""
    data, _ = _mlp_model_bytes(np.random.RandomState(0))
    with pytest.raises(ValueError, match="truncated"):
        pw.load_model(data[: len(data) - 7])
    # a varint that runs off the end
    with pytest.raises(ValueError, match="truncated"):
        list(pw._fields(b"\x08\xff"))


def test_wire_string_attributes_are_bytes():
    """STRING/STRINGS attributes decode to bytes, matching
    onnx.helper.get_attribute_value (ADVICE r03: a handler comparing
    against b"..." must behave the same under either parser)."""
    attr = pw._ld(1, b"mode") + pw._ld(4, b"constant") + pw._vi(20, 3)
    a = pw._parse_attribute(attr)
    assert a.value == b"constant"
    attrs = pw._ld(1, b"names") + pw._ld(9, b"a") + pw._ld(9, b"b") + pw._vi(20, 8)
    a2 = pw._parse_attribute(attrs)
    assert a2.value == [b"a", b"b"]
