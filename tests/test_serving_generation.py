"""Generation serving (flexflow_tpu/serving/generation.py): the
KV-cache scan decoder behind the batcher/HTTP surface — the scope the
reference's triton/ backend never reached (triton/README.md:3-6,
forward-only).
"""
import json
import threading
import urllib.request

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.decoding import gpt_generate_cached, make_gpt_decoder
from flexflow_tpu.models.transformer import build_gpt, gpt_generate
from flexflow_tpu.serving import GenerationBatcher, GenerationEngine
from flexflow_tpu.serving.server import serve_http

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only

V, S, B = 32, 16, 4


@pytest.fixture(scope="module")
def trained(devices8):
    ff = FFModel(FFConfig(batch_size=B, num_devices=1))
    build_gpt(ff, batch_size=B, seq_length=S, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    rng = np.random.RandomState(0)
    start = rng.randint(0, V, (B, 1))
    step = rng.randint(1, 6, (B, 1))
    seq_ids = (start + step * np.arange(S + 1)) % V
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
    for _ in range(40):
        ff.train_step({"input": ids, "positions": pos}, labels)
    return ff, ids


@pytest.fixture(scope="module")
def gen_engine(trained, devices8):
    ff, _ = trained
    return GenerationEngine(ff, batch_size=B, devices=devices8[:1])


def test_engine_matches_reference_decode(trained, gen_engine):
    """Same-length prompts through the serving engine equal the
    host-loop KV decoder (and thus the full-forward path)."""
    ff, ids = trained
    prompts = [ids[i, :5].tolist() for i in range(B)]
    got = gen_engine.generate(prompts, max_new_tokens=6)
    ffd = make_gpt_decoder(ff, devices=None)
    want = gpt_generate_cached(ffd, ids[:, :5], max_new_tokens=6)
    for i in range(B):
        np.testing.assert_array_equal(got[i], want[i])


def test_engine_mixed_prompt_lengths(trained, gen_engine):
    """One scan serves different prompt lengths and per-request
    max_new_tokens; each row matches its own full-forward run."""
    ff, ids = trained
    prompts = [ids[0, :3].tolist(), ids[1, :7].tolist(), ids[2, :5].tolist()]
    mnts = [5, 3, 6]
    got = gen_engine.generate(prompts, mnts)
    for p, mnt, row in zip(prompts, mnts, got):
        # full-forward reference: duplicate the prompt across the batch
        full = gpt_generate(ff, np.tile(np.asarray(p, np.int32), (B, 1)),
                            max_new_tokens=mnt)
        assert row == full[0, :len(p) + mnt].tolist()
    # one program per total bucket: both calls below reuse total=16
    runs_before = gen_engine.generations_run
    gen_engine.generate([ids[3, :2].tolist()], 4)
    assert gen_engine.generations_run == runs_before + 1


def test_engine_eos_trimming(trained, devices8):
    ff, ids = trained
    ffd_ref = make_gpt_decoder(ff, devices=None)
    want = gpt_generate_cached(ffd_ref, ids[:, :4], max_new_tokens=8)
    eos = int(want[0, 6])  # force a hit inside row 0's continuation
    eng = GenerationEngine(ff, batch_size=B, devices=devices8[:1],
                           eos_id=eos)
    got = eng.generate([ids[i, :4].tolist() for i in range(B)], 8)
    row = got[0]
    assert row[-1] == eos and len(row) == 7  # trimmed at first eos
    np.testing.assert_array_equal(row, want[0, :7])


def test_batcher_coalesces_concurrent_generates(gen_engine):
    batcher = GenerationBatcher(gen_engine, flush_timeout_s=0.05)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, V, rng.randint(2, 7)).tolist()
               for _ in range(10)]
    direct = [gen_engine.generate([p], 5)[0] for p in prompts]
    results = [None] * len(prompts)

    def worker(i):
        results[i] = batcher.generate(prompts[i], 5)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    try:
        assert all(r is not None for r in results)
        for got, want in zip(results, direct):
            assert got == want
        assert batcher.requests_done == len(prompts)
        # coalescing happened: fewer scans than requests
        assert batcher.batches_run < len(prompts)
        stats = batcher.latency_stats()
        assert stats["n"] == len(prompts) and stats["p99_ms"] > 0
    finally:
        batcher.close()


def test_generate_http_endpoint(gen_engine):
    batcher = GenerationBatcher(gen_engine, flush_timeout_s=0.02)
    server = serve_http(generator=batcher, port=0, block=False)
    port = server.server_address[1]
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v2/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                return json.loads(r.read())

        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, V, 4).tolist() for _ in range(3)]
        out = post({"prompts": prompts, "max_new_tokens": 5})
        want = [gen_engine.generate([p], 5)[0] for p in prompts]
        assert out["tokens"] == want
        single = post({"prompt": prompts[0], "max_new_tokens": 5})
        assert single["tokens"] == [want[0]]

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v2/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["requests_done"] >= 4
        assert stats["latency"]["n"] >= 4
    finally:
        server.shutdown()
        batcher.close()
