"""Sort-based MoE dispatch vs the dense one-hot reference formulation:
identical grouping, combine, capacity-drop priority, and gradients."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.ops.moe import _dispatch_mask
from flexflow_tpu.ops.moe_dispatch import (
    dispatch_indices,
    sort_combine,
    sort_group_by,
)


def _cases():
    rng = np.random.RandomState(0)
    yield rng.randint(0, 4, size=(16, 2)), 4, 5     # drops some
    yield rng.randint(0, 8, size=(32, 1)), 8, 32    # no drops
    yield np.zeros((8, 2), np.int64), 4, 3          # all one expert, heavy drop
    yield rng.randint(0, 3, size=(6, 3)), 3, 2      # tiny capacity


@pytest.mark.parametrize("case", list(range(4)))
def test_group_by_matches_mask_path(case):
    assign, n, cap = list(_cases())[case]
    assign = jnp.asarray(assign)
    rng = np.random.RandomState(1)
    data = jnp.asarray(rng.randn(assign.shape[0], 7).astype(np.float32))

    got = sort_group_by(data, assign, n, cap)
    disp = _dispatch_mask(assign, n, cap)
    want = jnp.einsum("bknc,bd->ncd", disp, data)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("case", list(range(4)))
def test_combine_matches_mask_path(case):
    assign, n, cap = list(_cases())[case]
    assign = jnp.asarray(assign)
    rng = np.random.RandomState(2)
    expert_out = jnp.asarray(rng.randn(n, cap, 5).astype(np.float32))

    rows, keep = sort_combine(expert_out, assign, cap)
    disp = _dispatch_mask(assign, n, cap)
    want = jnp.einsum("bknc,nce->bke", disp, expert_out)
    np.testing.assert_allclose(
        np.asarray(rows), np.asarray(want).reshape(rows.shape),
        rtol=1e-6, atol=1e-6,
    )


def test_priority_order_is_flat_order():
    """With capacity 1, the FIRST flat (sample-major) token per expert
    wins — the reference's cumsum priority."""
    assign = jnp.asarray([[0], [0], [1], [0]])
    slot, keep = dispatch_indices(assign, capacity=1, n=2)
    np.testing.assert_array_equal(np.asarray(keep), [True, False, True, False])
    assert int(slot[0]) == 0 and int(slot[2]) == 1


def test_gradients_match_mask_path():
    assign = jnp.asarray(np.random.RandomState(3).randint(0, 4, size=(12, 2)))
    n, cap = 4, 4
    data = jnp.asarray(np.random.RandomState(4).randn(12, 6).astype(np.float32))

    def loss_sort(d):
        return jnp.sum(sort_group_by(d, assign, n, cap) ** 2)

    def loss_mask(d):
        disp = _dispatch_mask(assign, n, cap)
        return jnp.sum(jnp.einsum("bknc,bd->ncd", disp, d) ** 2)

    g1 = jax.grad(loss_sort)(data)
    g2 = jax.grad(loss_mask)(data)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)


def test_moe_model_still_trains(devices8):
    from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
    from flexflow_tpu.models import build_moe_mlp

    cfg = FFConfig(batch_size=16, num_devices=8)
    ff = FFModel(cfg)
    build_moe_mlp(ff, batch_size=16, input_dim=16, num_classes=4,
                  num_exp=4, num_select=2, hidden_size=16)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices8)
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 16).astype(np.float32)
    ys = (xs.sum(axis=1) > 0).astype(np.int32)
    hist = ff.fit(xs, ys, epochs=6, verbose=False)
    assert hist[-1].sparse_cce_loss < hist[0].sparse_cce_loss
