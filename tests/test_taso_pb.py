"""Binary TASO catalog reader (flexflow_tpu/pcg/taso_pb.py) and
default-on catalog resolution (rewrite.catalog_for_config).

The reference loads substitutions/graph_subst_3_v2.pb (proto2 wire
bytes) and ships a JSON twin via tools/protobuf_to_json; our .pb
reader must parse the binary form to rule-for-rule the same IR as the
JSON parse, and tools/pb_to_json.py must emit the converter's exact
schema.
"""
import json
import os
import subprocess
import sys

import pytest

from flexflow_tpu.pcg.taso import is_taso_rule_file, parse_rule_collection
from flexflow_tpu.pcg.taso_pb import looks_like_pb, pb_to_dict

PB = "/root/reference/substitutions/graph_subst_3_v2.pb"
JS = "/root/reference/substitutions/graph_subst_3_v2.json"

pytestmark = pytest.mark.skipif(
    not os.path.exists(PB), reason="reference catalog not mounted"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pb_parses_identically_to_json():
    """Every one of the 640 rules decodes from wire bytes to exactly
    the rule the JSON twin yields (names, ops, params, mappings)."""
    a = parse_rule_collection(PB)
    b = parse_rule_collection(JS)
    assert len(a) == len(b) == 640
    assert a == b


def test_pb_dict_matches_converter_schema():
    """pb_to_dict emits the protobuf_to_json.cc structure verbatim —
    byte-equal JSON after normalization."""
    d = pb_to_dict(PB)
    with open(JS) as f:
        ref = json.load(f)
    assert d == ref


def test_pb_detection():
    assert looks_like_pb(PB) and not looks_like_pb(JS)
    assert is_taso_rule_file(PB) and is_taso_rule_file(JS)


def test_converter_cli_round_trip(tmp_path):
    out = tmp_path / "subst.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pb_to_json.py"),
         PB, str(out)],
        capture_output=True, text=True, check=True,
    )
    assert "Loaded 640 rules." in r.stdout
    with open(out) as f:
        assert json.load(f) == pb_to_dict(PB)


def test_default_catalog_resolution(monkeypatch):
    """Default-on: no --substitution-json resolves to a findable
    catalog; ""/"none" disables; env override wins."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.pcg.rewrite import catalog_for_config

    monkeypatch.delenv("FLEXFLOW_TPU_SUBSTITUTIONS", raising=False)
    assert catalog_for_config(FFConfig()) is not None
    assert catalog_for_config(FFConfig(substitution_json="none")) is None
    assert catalog_for_config(FFConfig(substitution_json="")) is None
    assert catalog_for_config(
        FFConfig(substitution_json=JS)) == JS
    monkeypatch.setenv("FLEXFLOW_TPU_SUBSTITUTIONS", "")
    assert catalog_for_config(FFConfig()) is None
    monkeypatch.setenv("FLEXFLOW_TPU_SUBSTITUTIONS", PB)
    assert catalog_for_config(FFConfig()) == PB


def test_strategy_replay_pins_catalog(monkeypatch):
    """A strategy whose trace references catalog rules records the
    catalog identity; replay must load byte-identical rules or fail
    loudly (match indices would silently select different subgraphs)."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.pcg.rewrite import (
        catalog_fingerprint,
        rules_for_replay,
    )
    from flexflow_tpu.strategy import Strategy

    monkeypatch.delenv("FLEXFLOW_TPU_SUBSTITUTIONS", raising=False)
    fp = catalog_fingerprint(PB)
    s = Strategy(mesh_axes={"data": 2},
                 rewrites=[("taso_rule_0@2", 0)], catalog=fp)
    rules = rules_for_replay(FFConfig(), s)
    assert any(r.name.startswith("taso_rule_") for r in rules)

    bad = Strategy(mesh_axes={"data": 2}, rewrites=[("taso_rule_0@2", 0)],
                   catalog=dict(fp, sha256="0" * 64))
    with pytest.raises(ValueError, match="differs"):
        rules_for_replay(FFConfig(), bad)

    old = Strategy(mesh_axes={"data": 2}, rewrites=[("taso_rule_0@2", 0)],
                   catalog=dict(fp, engine=-1))
    with pytest.raises(ValueError, match="engine"):
        rules_for_replay(FFConfig(), old)

    # no catalog findable anywhere -> clear error, not silent mis-replay
    monkeypatch.setenv("FLEXFLOW_TPU_SUBSTITUTIONS", "")
    gone = Strategy(mesh_axes={"data": 2}, rewrites=[("taso_rule_0@2", 0)],
                    catalog=dict(fp, path="/nonexistent/catalog.pb"))
    with pytest.raises(ValueError, match="no catalog"):
        rules_for_replay(FFConfig(), gone)

    # traces without catalog rules replay exactly as before
    plain = Strategy(mesh_axes={"data": 2},
                     rewrites=[("fuse_linear_activation", 0)])
    assert rules_for_replay(FFConfig(substitution_json="none"), plain)


def test_default_catalog_loads_in_search_rule_list():
    """rules_for_config with the default config includes compiled
    catalog pattern rules (the flagship feature is live by default)."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.pcg.rewrite import rules_for_config

    rules = rules_for_config(FFConfig())
    assert any(r.name.startswith("taso_rule_") for r in rules)
