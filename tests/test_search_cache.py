"""Incremental-evaluator property tests (ISSUE 1 tentpole invariant):
delta evaluation must match full evaluation bit-for-bit across random
move sequences, the strategy memo must answer revisited states, and the
memo+delta path must do measurably fewer full-graph simulations than
evaluations on a fixed-seed BERT-base search."""
import random

import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.moe import build_moe_encoder
from flexflow_tpu.models.transformer import build_bert, build_transformer
from flexflow_tpu.pcg.evaluator import IncrementalEvaluator, strategy_signature
from flexflow_tpu.pcg.mcmc import MCMCSearch
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import Simulator
from flexflow_tpu.strategy import Strategy, data_parallel_strategy


def _transformer():
    ff = FFModel(FFConfig())
    build_transformer(ff, batch_size=4, seq_length=16, hidden_size=32,
                      num_layers=2, num_heads=4)
    return ff


def _moe():
    ff = FFModel(FFConfig())
    build_moe_encoder(ff, batch_size=4, seq_length=8, hidden_size=32,
                      num_layers=2, num_heads=4, num_exp=4, num_select=2)
    return ff


def _machine():
    return TpuPodModel(topology=(8,))


def _random_strategies(graph, n_moves=60, seed=7):
    """A seeded MCMC-like move sequence: mostly single-op ShardConfig
    flips (delta-eligible), occasional mesh refactorizations (full
    re-evals), with revisits (memo hits) by construction."""
    search = MCMCSearch(graph, 8, lambda: Simulator(_machine()), budget=0)
    rng = random.Random(seed)
    dp, tp, ep = 8, 1, 1
    flags = {}
    out = [search._build(dp, tp, ep, flags)]
    for _ in range(n_moves):
        if rng.random() < 0.2 or not search.candidates:
            dp, tp, ep = rng.choice(search.factorizations)
        else:
            c = rng.choice(search.candidates)
            flags[c.name] = not flags.get(c.name, False)
        out.append(search._build(dp, tp, ep, dict(flags)))
    return out


@pytest.mark.parametrize("stage", [0, 1, 2, 3],
                         ids=["zero0", "zero1", "zero2", "zero3"])
@pytest.mark.parametrize("build", [_transformer, _moe],
                         ids=["transformer", "moe"])
def test_delta_eval_matches_full_eval_bit_for_bit(build, stage):
    """delta_eval(state) == full_eval(state), exactly, for every state
    of a random move sequence — including the lazy memory term.  Runs
    at every rung of the ZeRO ladder (ISSUE 3 shipped stage 1, ISSUE 10
    stages 2/3) since each stage produces different OpTerms."""
    graph = build().layers
    ev_delta = IncrementalEvaluator(
        graph, Simulator(_machine(), zero_stage=stage),
        use_cache=True)
    ev_full = IncrementalEvaluator(
        graph, Simulator(_machine(), zero_stage=stage),
        use_cache=False)
    legal = 0
    for s in _random_strategies(graph):
        rd = ev_delta.evaluate(s)
        rf = ev_full.evaluate(s)
        assert (rd is None) == (rf is None)
        if rd is None:
            continue
        legal += 1
        assert rd.total_time == rf.total_time
        assert rd.compute_time == rf.compute_time
        assert rd.comm_time == rf.comm_time
        assert rd.sync_time == rf.sync_time
        assert rd.per_device_memory == rf.per_device_memory
    assert legal > 10
    assert ev_delta.stats.delta_evals > 0
    assert ev_delta.stats.memo_hits > 0
    assert ev_full.stats.full_evals == ev_full.stats.evals - \
        ev_full.stats.illegal_evals
    st = ev_delta.stats
    assert st.memo_hits + st.full_evals + st.delta_evals + \
        st.illegal_evals == st.evals


@pytest.mark.parametrize("placement", [None, "data", "model"],
                         ids=["pdefault", "pdata", "pmodel"])
def test_delta_eval_matches_full_eval_over_placements(placement):
    """ISSUE 12 satellite: the multi-slice placement dimension keeps
    the delta_eval == full_eval invariant bit-for-bit — a strategy's
    placement re-tiers every comm term through the cached OpTerms, and
    both paths must sum identical terms in identical order on a
    SliceHierarchy machine."""
    import dataclasses

    from flexflow_tpu.topology.hierarchy import SliceHierarchy

    graph = _transformer().layers
    machine = SliceHierarchy(topology=(4,), slices=2, dcn_bw_per_host=4e9)
    ev_delta = IncrementalEvaluator(graph, Simulator(machine),
                                    use_cache=True)
    ev_full = IncrementalEvaluator(graph, Simulator(machine),
                                   use_cache=False)
    legal = 0
    for s in _random_strategies(graph, n_moves=30):
        if placement is not None and s.mesh_axes.get(placement, 0) % 2:
            continue  # illegal placement for this mesh: skip the pin
        c = dataclasses.replace(s, placement=placement)
        rd = ev_delta.evaluate(c)
        rf = ev_full.evaluate(c)
        assert (rd is None) == (rf is None)
        if rd is None:
            continue
        legal += 1
        assert rd.total_time == rf.total_time
        assert rd.comm_time == rf.comm_time
        assert rd.sync_time == rf.sync_time
        assert rd.per_device_memory == rf.per_device_memory
        assert rd.comm_tiers == rf.comm_tiers
    assert legal > 5
    assert ev_delta.stats.memo_hits + ev_delta.stats.delta_evals > 0
    # placements never alias in the memo
    base = data_parallel_strategy(8)
    sigs = {
        strategy_signature(dataclasses.replace(base, placement=p))
        for p in (None, "data")
    }
    assert len(sigs) == 2


def test_delta_eval_matches_full_eval_with_strategy_stage():
    """A strategy-carried zero_stage (how unity's stage variants and
    store-restored winners cost themselves) overrides the simulator
    default, stays delta == full bit-for-bit, and is part of the memo
    key — stage variants of one sharding never alias."""
    import dataclasses

    graph = _transformer().layers
    ev_d = IncrementalEvaluator(graph, Simulator(_machine()), use_cache=True)
    ev_f = IncrementalEvaluator(graph, Simulator(_machine()), use_cache=False)
    for s in _random_strategies(graph, n_moves=12):
        for stage in (None, 0, 1, 2, 3):
            c = dataclasses.replace(s, zero_stage=stage)
            rd, rf = ev_d.evaluate(c), ev_f.evaluate(c)
            assert (rd is None) == (rf is None)
            if rd is None:
                continue
            assert rd.total_time == rf.total_time
            assert rd.sync_time == rf.sync_time
            assert rd.per_device_memory == rf.per_device_memory
    base = data_parallel_strategy(8)
    sigs = {
        strategy_signature(dataclasses.replace(base, zero_stage=s))
        for s in (None, 0, 1, 2, 3)
    }
    assert len(sigs) == 5


def test_memo_hit_on_revisited_strategy():
    graph = _transformer().layers
    ev = IncrementalEvaluator(graph, Simulator(_machine()), use_cache=True)
    s = data_parallel_strategy(4)
    r1 = ev.evaluate(s)
    r2 = ev.evaluate(Strategy.from_json(s.to_json()))  # equal, distinct obj
    assert r1 is r2  # answered by the memo, not re-simulated
    assert ev.stats.memo_hits == 1 and ev.stats.full_evals == 1
    assert strategy_signature(s) == strategy_signature(
        Strategy.from_json(s.to_json())
    )


def test_signature_normalizes_trivial_configs():
    from flexflow_tpu.ops.op import ShardConfig

    a = data_parallel_strategy(8)
    b = data_parallel_strategy(8)
    b.shard_configs["fc_anything"] = ShardConfig()  # trivial == absent
    assert strategy_signature(a) == strategy_signature(b)
    c = data_parallel_strategy(8)
    c.shard_configs["fc_anything"] = ShardConfig(channel=2)
    assert strategy_signature(a) != strategy_signature(c)


def test_mcmc_cached_matches_uncached_search():
    """Same seed, same budget: the memoized+delta search must return the
    same best strategy at the same cost as the always-full evaluator,
    while doing fewer full simulations."""
    machine = _machine()
    ff1, ff2 = _transformer(), _transformer()
    s1 = MCMCSearch(ff1.layers, 8, lambda: Simulator(machine), budget=40,
                    seed=3)
    s2 = MCMCSearch(ff2.layers, 8, lambda: Simulator(machine), budget=40,
                    seed=3, use_eval_cache=False)
    b1, b2 = s1.optimize(), s2.optimize()
    assert b1.search_stats["evals"] == s1.stats.evals  # riding the result
    assert b1.mesh_axes == b2.mesh_axes
    assert b1.shard_configs == b2.shard_configs
    assert s1.evaluate(b1) == s2.evaluate(b2)
    assert s1.stats.full_evals < s1.stats.evals
    assert s1.stats.memo_hits > 0


@pytest.mark.slow
def test_mcmc_bert_base_throughput_guard():
    """Search-throughput smoke test (ISSUE 1 CI satellite): a fixed-seed
    200-eval MCMC search on BERT-base must answer most evaluations from
    the memo or the delta path — full-graph simulations strictly fewer
    than evaluations — and still return the exact result of the
    always-full reference evaluator."""
    machine = _machine()

    def bert():
        ff = FFModel(FFConfig())
        build_bert(ff)  # BERT-base dims (hidden 768, 12 layers)
        return ff

    fast = MCMCSearch(bert().layers, 8, lambda: Simulator(machine),
                      budget=200, seed=0)
    best = fast.optimize()
    st = fast.stats
    assert st.memo_hits + st.full_evals + st.delta_evals + \
        st.illegal_evals == st.evals
    assert st.full_evals < st.evals, st.summary()  # the cache regression guard
    assert st.memo_hits > 0 and st.delta_evals > 0, st.summary()

    ref = MCMCSearch(bert().layers, 8, lambda: Simulator(machine),
                     budget=200, seed=0, use_eval_cache=False)
    best_ref = ref.optimize()
    assert best.mesh_axes == best_ref.mesh_axes
    assert best.shard_configs == best_ref.shard_configs
    assert fast.evaluate(best) == ref.evaluate(best_ref)
