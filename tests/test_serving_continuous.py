"""Continuous-batching serving on a REAL trained GPT
(serving/scheduler.py + serving/kv_pool.py + the paged decode mode of
ops/attention.py): greedy token-identity against the static scan tier,
bit-identity of the paged decode step against the dense KV cache,
fault recovery through the donated-state reset path, and the Poisson
loadgen end to end."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.decoding import build_paged_decode_step, make_gpt_decoder
from flexflow_tpu.models.transformer import build_gpt
from flexflow_tpu.serving import ContinuousScheduler, GenerationEngine
from flexflow_tpu.serving.loadgen import run_loadgen, sample_workload

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only

V, S, B = 32, 16, 4


@pytest.fixture(scope="module")
def trained(devices8):
    ff = FFModel(FFConfig(batch_size=B, num_devices=1))
    build_gpt(ff, batch_size=B, seq_length=S, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    rng = np.random.RandomState(0)
    start = rng.randint(0, V, (B, 1))
    step = rng.randint(1, 6, (B, 1))
    seq_ids = (start + step * np.arange(S + 1)) % V
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
    for _ in range(40):
        ff.train_step({"input": ids, "positions": pos}, labels)
    return ff, ids


def test_paged_decode_step_bit_identical_to_dense(trained, devices8):
    """The paged attention gather is shape-identical to the dense
    cache read, so logits match BIT FOR BIT at matching positions —
    the invariant everything else rides on."""
    import jax.numpy as jnp

    ff, ids = trained
    dense = make_gpt_decoder(ff, devices=devices8[:1])
    page = 4
    max_blocks = S // page
    paged = make_gpt_decoder(ff, devices=devices8[:1], kv_page_size=page,
                             kv_num_blocks=1 + B * max_blocks)
    step = build_paged_decode_step(paged)

    # non-contiguous physical blocks on purpose: row-major interleaved
    btab = np.zeros((B, max_blocks), np.int32)
    blocks = list(range(1, 1 + B * max_blocks))
    for j in range(max_blocks):
        for i in range(B):
            btab[i, j] = blocks.pop(0)
    state = paged._state
    for t in range(S - 1):
        toks = ids[:, t]
        slens = np.full(B, t, np.int32)
        logits, state = step(paged._weights, state,
                             jnp.asarray(toks), jnp.asarray(slens),
                             jnp.asarray(btab))
        want = np.asarray(dense.decode_step({
            "input": toks[:, None],
            "positions": np.full((B, 1), t, np.int32),
        }))[:, 0]
        np.testing.assert_array_equal(np.asarray(logits), want)


def test_continuous_token_identical_to_static_greedy(trained, devices8):
    """Acceptance criterion: continuous mode is token-identical to
    static mode for greedy decoding on the same prompts — mixed
    prompt lengths, mixed max_new_tokens, admissions interleaved with
    decode."""
    ff, _ = trained
    static = GenerationEngine(ff, batch_size=B, devices=devices8[:1])
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1])
    try:
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, V, rng.randint(2, 8)).tolist()
                   for _ in range(12)]
        mnts = [int(rng.randint(2, 9)) for _ in range(12)]
        handles = [sched.generate_async(p, m)
                   for p, m in zip(prompts, mnts)]
        got = [h.wait(120.0) for h in handles]
        for p, m, g in zip(prompts, mnts, got):
            assert g == static.generate([p], m)[0]
        # 12 requests through 4 slots: iteration-level retirement
        # must have reused slots, and the pool must end empty
        assert sched.requests_done == 12
        sched.pool.check_invariants()
        assert sched.pool.used_blocks == 0
    finally:
        sched.close()


def test_continuous_eos_trimming_matches_static(trained, devices8):
    ff, ids = trained
    ref = GenerationEngine(ff, batch_size=B, devices=devices8[:1])
    want = ref.generate([ids[0, :4].tolist()], 8)[0]
    eos = int(want[6])  # force a hit inside the continuation
    static = GenerationEngine(ff, batch_size=B, devices=devices8[:1],
                              eos_id=eos)
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1],
        eos_id=eos)
    try:
        p = ids[0, :4].tolist()
        got = sched.generate(p, 8, timeout=120.0)
        assert got == static.generate([p], 8)[0]
        assert got[-1] == eos and len(got) == 7
    finally:
        sched.close()


def test_real_fault_recovery_with_donated_state(trained, devices8):
    """A step exception mid-decode fails only the in-flight requests;
    the engine rebuilds its (donated) state and completes queued +
    subsequent requests correctly."""
    ff, _ = trained
    static = GenerationEngine(ff, batch_size=B, devices=devices8[:1])
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1])
    real_step = sched.model.step
    calls = {"n": 0}

    def flaky_step(tokens, seq_lens, block_tables):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("injected mid-decode fault")
        return real_step(tokens, seq_lens, block_tables)

    sched.model.step = flaky_step
    try:
        hs = [sched.generate_async([1 + i, 2, 3], 6) for i in range(B)]
        failed = ok = 0
        for h in hs:
            try:
                h.wait(120.0)
                ok += 1
            except RuntimeError:
                failed += 1
        assert failed >= 1  # the in-flight batch died
        assert sched.step_failures == 1
        # post-fault request is still bit-correct vs static
        p = [5, 6, 7]
        assert sched.generate(p, 5, timeout=120.0) == \
            static.generate([p], 5)[0]
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_loadgen_end_to_end_continuous(trained, devices8):
    ff, _ = trained
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1])
    try:
        sched.generate([1, 2], 2, timeout=120.0)  # pay the compile
        rng = np.random.RandomState(5)
        wl = sample_workload(rng, 10, V, prompt_len_range=(2, 6),
                             max_new_range=(2, 6), long_frac=0.3,
                             long_max_new_range=(8, 10))
        report = run_loadgen(sched, wl, rate_rps=100.0, seed=2,
                             timeout_s=120.0)
        assert report["completed"] == 10 and report["failures"] == 0
        assert report["tokens_generated"] == sum(m for _, m in wl)
        assert report["tokens_per_s"] > 0
        assert report["ttft"]["n"] == 10
        st = sched.stats()
        assert st["kv_pool"]["peak_used_blocks"] > 0
        assert st["kv_pool"]["used_blocks"] == 0
    finally:
        sched.close()
