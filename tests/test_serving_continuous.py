"""Continuous-batching serving on a REAL trained GPT
(serving/scheduler.py + serving/kv_pool.py + the paged decode mode of
ops/attention.py): greedy token-identity against the static scan tier,
bit-identity of the paged decode step against the dense KV cache,
fault recovery through the donated-state reset path, and the Poisson
loadgen end to end."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.decoding import build_paged_decode_step, make_gpt_decoder
from flexflow_tpu.models.transformer import build_gpt
from flexflow_tpu.serving import ContinuousScheduler, GenerationEngine
from flexflow_tpu.serving.loadgen import run_loadgen, sample_workload

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only

V, S, B = 32, 16, 4


@pytest.fixture(scope="module")
def trained(devices8):
    ff = FFModel(FFConfig(batch_size=B, num_devices=1))
    build_gpt(ff, batch_size=B, seq_length=S, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    rng = np.random.RandomState(0)
    start = rng.randint(0, V, (B, 1))
    step = rng.randint(1, 6, (B, 1))
    seq_ids = (start + step * np.arange(S + 1)) % V
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
    for _ in range(40):
        ff.train_step({"input": ids, "positions": pos}, labels)
    return ff, ids


def test_paged_decode_step_bit_identical_to_dense(trained, devices8):
    """The paged attention gather is shape-identical to the dense
    cache read, so logits match BIT FOR BIT at matching positions —
    the invariant everything else rides on."""
    import jax.numpy as jnp

    ff, ids = trained
    dense = make_gpt_decoder(ff, devices=devices8[:1])
    page = 4
    max_blocks = S // page
    paged = make_gpt_decoder(ff, devices=devices8[:1], kv_page_size=page,
                             kv_num_blocks=1 + B * max_blocks)
    step = build_paged_decode_step(paged)

    # non-contiguous physical blocks on purpose: row-major interleaved
    btab = np.zeros((B, max_blocks), np.int32)
    blocks = list(range(1, 1 + B * max_blocks))
    for j in range(max_blocks):
        for i in range(B):
            btab[i, j] = blocks.pop(0)
    state = paged._state
    for t in range(S - 1):
        toks = ids[:, t]
        slens = np.full(B, t, np.int32)
        logits, state = step(paged._weights, state,
                             jnp.asarray(toks), jnp.asarray(slens),
                             jnp.asarray(btab))
        want = np.asarray(dense.decode_step({
            "input": toks[:, None],
            "positions": np.full((B, 1), t, np.int32),
        }))[:, 0]
        np.testing.assert_array_equal(np.asarray(logits), want)


def test_continuous_token_identical_to_static_greedy(trained, devices8):
    """Acceptance criterion: continuous mode is token-identical to
    static mode for greedy decoding on the same prompts — mixed
    prompt lengths, mixed max_new_tokens, admissions interleaved with
    decode."""
    ff, _ = trained
    static = GenerationEngine(ff, batch_size=B, devices=devices8[:1])
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1])
    try:
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, V, rng.randint(2, 8)).tolist()
                   for _ in range(12)]
        mnts = [int(rng.randint(2, 9)) for _ in range(12)]
        handles = [sched.generate_async(p, m)
                   for p, m in zip(prompts, mnts)]
        got = [h.wait(120.0) for h in handles]
        for p, m, g in zip(prompts, mnts, got):
            assert g == static.generate([p], m)[0]
        # 12 requests through 4 slots: iteration-level retirement
        # must have reused slots, and the pool must end empty
        assert sched.requests_done == 12
        sched.pool.check_invariants()
        assert sched.pool.used_blocks == 0
    finally:
        sched.close()


def test_continuous_eos_trimming_matches_static(trained, devices8):
    ff, ids = trained
    ref = GenerationEngine(ff, batch_size=B, devices=devices8[:1])
    want = ref.generate([ids[0, :4].tolist()], 8)[0]
    eos = int(want[6])  # force a hit inside the continuation
    static = GenerationEngine(ff, batch_size=B, devices=devices8[:1],
                              eos_id=eos)
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1],
        eos_id=eos)
    try:
        p = ids[0, :4].tolist()
        got = sched.generate(p, 8, timeout=120.0)
        assert got == static.generate([p], 8)[0]
        assert got[-1] == eos and len(got) == 7
    finally:
        sched.close()


def test_real_fault_recovery_with_donated_state(trained, devices8):
    """A step exception mid-decode fails only the in-flight requests;
    the engine rebuilds its (donated) state and completes queued +
    subsequent requests correctly."""
    ff, _ = trained
    static = GenerationEngine(ff, batch_size=B, devices=devices8[:1])
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1])
    real_step = sched.model.step
    calls = {"n": 0}

    def flaky_step(tokens, seq_lens, block_tables):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("injected mid-decode fault")
        return real_step(tokens, seq_lens, block_tables)

    sched.model.step = flaky_step
    try:
        hs = [sched.generate_async([1 + i, 2, 3], 6) for i in range(B)]
        failed = ok = 0
        for h in hs:
            try:
                h.wait(120.0)
                ok += 1
            except RuntimeError:
                failed += 1
        assert failed >= 1  # the in-flight batch died
        assert sched.step_failures == 1
        # post-fault request is still bit-correct vs static
        p = [5, 6, 7]
        assert sched.generate(p, 5, timeout=120.0) == \
            static.generate([p], 5)[0]
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_chunked_prefill_writes_bit_identical_cache(trained, devices8):
    """The [slots, C] chunked-prefill program (a lax.scan of the seq-1
    decode graph) must write BIT-IDENTICAL K/V bytes to one-token
    prefill: after prefilling the same prompt both ways, the next
    decode step's logits match exactly."""
    import jax.numpy as jnp

    from flexflow_tpu.decoding import build_paged_prefill_step

    ff, ids = trained
    page, C = 4, 4
    max_blocks = S // page

    def fresh():
        paged = make_gpt_decoder(ff, devices=devices8[:1],
                                 kv_page_size=page,
                                 kv_num_blocks=1 + B * max_blocks)
        btab = np.zeros((B, max_blocks), np.int32)
        blocks = list(range(1, 1 + B * max_blocks))
        for j in range(max_blocks):
            for i in range(B):
                btab[i, j] = blocks.pop(0)
        return paged, btab

    plen = 9  # not chunk-aligned on purpose: the pad path is live
    # one-token prefill of positions 0..plen-2
    ref, btab = fresh()
    ref_step = build_paged_decode_step(ref)
    state = ref._state
    for t in range(plen - 1):
        _, state = ref_step(ref._weights, state,
                            jnp.asarray(ids[:, t]),
                            jnp.asarray(np.full(B, t, np.int32)),
                            jnp.asarray(btab))
    want, _ = ref_step(ref._weights, state,
                       jnp.asarray(ids[:, plen - 1]),
                       jnp.asarray(np.full(B, plen - 1, np.int32)),
                       jnp.asarray(btab))

    # chunked prefill of the same positions (2 chunks: 4 + 4)
    chk, btab2 = fresh()
    np.testing.assert_array_equal(btab, btab2)
    chk_prefill = build_paged_prefill_step(chk, C)
    chk_step = build_paged_decode_step(chk)
    state = chk._state
    for start in range(0, plen - 1, C):
        upto = min(start + C, plen - 1)
        tok = np.zeros((B, C), np.int32)
        tok[:, :upto - start] = ids[:, start:upto]
        state = chk_prefill(chk._weights, state, jnp.asarray(tok),
                            jnp.asarray(np.full(B, start, np.int32)),
                            jnp.asarray(btab))
    got, _ = chk_step(chk._weights, state,
                      jnp.asarray(ids[:, plen - 1]),
                      jnp.asarray(np.full(B, plen - 1, np.int32)),
                      jnp.asarray(btab))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_chunk_twin_multi_token_attention_matches(trained, devices8):
    """The true seq-C paged twin (make_gpt_decoder(step_tokens=C) +
    build_paged_chunk_step — the fused TPU-native prefill shape)
    agrees with one-token stepping to float tolerance (its batched
    matmuls are not rowwise-bitwise-stable on XLA:CPU, which is
    exactly why the engine's oracle path uses the scan program)."""
    import jax.numpy as jnp

    from flexflow_tpu.decoding import build_paged_chunk_step

    ff, ids = trained
    page, C = 4, 4
    max_blocks = S // page
    nb = 1 + B * max_blocks
    btab = np.zeros((B, max_blocks), np.int32)
    blocks = list(range(1, nb))
    for j in range(max_blocks):
        for i in range(B):
            btab[i, j] = blocks.pop(0)

    ref = make_gpt_decoder(ff, devices=devices8[:1], kv_page_size=page,
                           kv_num_blocks=nb)
    ref_step = build_paged_decode_step(ref)
    state = ref._state
    want = []
    for t in range(C):
        logits, state = ref_step(ref._weights, state,
                                 jnp.asarray(ids[:, t]),
                                 jnp.asarray(np.full(B, t, np.int32)),
                                 jnp.asarray(btab))
        want.append(np.asarray(logits))

    twin = make_gpt_decoder(ff, devices=devices8[:1], kv_page_size=page,
                            kv_num_blocks=nb, step_tokens=C)
    chunk_step = build_paged_chunk_step(twin)
    logits, _ = chunk_step(twin._weights, twin._state,
                           jnp.asarray(ids[:, :C]),
                           jnp.asarray(np.zeros(B, np.int32)),
                           jnp.asarray(btab))
    got = np.asarray(logits)  # [B, C, vocab]
    for t in range(C):
        np.testing.assert_allclose(got[:, t], want[t], rtol=2e-5,
                                   atol=2e-5)


def test_sharing_and_chunking_token_identical_to_baseline(trained,
                                                          devices8):
    """THE acceptance invariant: greedy output with prefix sharing +
    chunked prefill ON is token-identical to the PR 6 baseline
    (sharing OFF, one-token prefill) — including full-prompt hits
    (COW) and partial hits, with the pool invariants checked at every
    scheduler step."""
    ff, _ = trained
    base = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1],
        prefix_cache=False, prefill_chunk=0)
    shared = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1],
        prefix_cache=True, prefill_chunk=4, check_invariants=True)
    try:
        rng = np.random.RandomState(9)
        prefix = rng.randint(0, V, 8).tolist()  # 2 full pages
        prompts = [prefix]  # a FULL-prompt rehit once cached
        prompts += [prefix + rng.randint(0, V, rng.randint(1, 5)).tolist()
                    for _ in range(7)]
        prompts.append(prefix)  # full hit again, later in the stream
        mnts = [int(rng.randint(2, 7)) for _ in prompts]
        want = [base.generate(p, m, timeout=120.0)
                for p, m in zip(prompts, mnts)]
        handles = [shared.generate_async(p, m)
                   for p, m in zip(prompts, mnts)]
        got = [h.wait(120.0) for h in handles]
        assert got == want
        # sharing actually happened, and everything retired cleanly
        st = shared.stats()["prefix_cache"]
        assert st["hit_tokens"] > 0
        assert st["cow_copies"] >= 1  # the repeated full prompt
        shared.pool.check_invariants()
        assert shared.pool.used_blocks == 0
    finally:
        base.close()
        shared.close()


def test_chunk_pad_overflow_never_writes_real_blocks(trained, devices8):
    """Contract: a chunk whose trailing PAD positions run past the
    position table (a near-max_seq prompt's last chunk) must never
    write a real block — the prefill program routes them to scratch
    explicitly (and jax's current fill-mode gather would drop them
    anyway; the explicit guard keeps the contract independent of
    indexing-mode defaults, which differ between gather styles).
    Checked at the CACHE-BYTE level (not greedy tokens, which can
    survive a one-position corruption on a peaked model): after a
    chunk at pos 13 with pads at 14/15/16 (max_seq 16, page 4), every
    slot holding positions 0..13 must be byte-equal to the one-token
    reference."""
    import jax.numpy as jnp

    from flexflow_tpu.decoding import build_paged_prefill_step

    ff, ids = trained
    page, C = 4, 4
    max_blocks = S // page  # 4 columns: positions 0..15

    def fresh():
        paged = make_gpt_decoder(ff, devices=devices8[:1],
                                 kv_page_size=page,
                                 kv_num_blocks=1 + B * max_blocks)
        btab = np.arange(1, 1 + B * max_blocks,
                         dtype=np.int32).reshape(B, max_blocks)
        return paged, btab

    def decode_to(paged, btab, upto):
        step = build_paged_decode_step(paged)
        state = paged._state
        for t in range(upto):
            _, state = step(paged._weights, state,
                            jnp.asarray(ids[:, t]),
                            jnp.asarray(np.full(B, t, np.int32)),
                            jnp.asarray(btab))
        return state

    # reference: positions 0..13 written one token at a time
    ref, btab = fresh()
    ref_state = decode_to(ref, btab, 14)
    # under test: 0..12 one at a time, then ONE chunk at pos 13 —
    # real token at 13, pads at positions 14, 15, and 16 (= max_seq)
    chk, _ = fresh()
    state = decode_to(chk, btab, 13)
    prefill = build_paged_prefill_step(chk, C)
    tok = np.zeros((B, C), np.int32)
    tok[:, 0] = ids[:, 13]
    state = prefill(chk._weights, state, jnp.asarray(tok),
                    jnp.asarray(np.full(B, 13, np.int32)),
                    jnp.asarray(btab))
    for op in ref_state:
        for k in ("k_cache", "v_cache"):
            if k not in ref_state[op]:
                continue
            want = np.asarray(ref_state[op][k])
            got = np.asarray(state[op][k])
            for i in range(B):
                for col in range(max_blocks):
                    blk = btab[i, col]
                    for off in range(page):
                        if col * page + off > 13:
                            continue  # pads 14/15 may hold garbage
                        np.testing.assert_array_equal(
                            got[blk, off], want[blk, off],
                            err_msg=f"{op}.{k} row {i} position "
                                    f"{col * page + off} corrupted "
                                    "by a pad write")


def test_cow_divergence_bit_identical_to_independent(trained, devices8):
    """Two requests sharing a full-prompt prefix then DIVERGING
    (different sampling seeds) must each match a fully-independent
    run bit for bit — the COW copies isolate their tails."""
    ff, _ = trained
    rng = np.random.RandomState(4)
    prompt = rng.randint(0, V, 8).tolist()  # exactly 2 pages

    def run_pair(prefix_cache):
        sched = ContinuousScheduler.from_trained(
            ff, batch_slots=B, page_size=4, devices=devices8[:1],
            prefix_cache=prefix_cache, check_invariants=prefix_cache,
            seed=123)
        try:
            warm = sched.generate(prompt, 2, timeout=120.0)
            # submitted together: both full-prompt hits when sharing,
            # diverging immediately via per-request sampling seeds
            h1 = sched.generate_async(prompt, 6, temperature=0.8)
            h2 = sched.generate_async(prompt, 6, temperature=0.8)
            r1, r2 = h1.wait(120.0), h2.wait(120.0)
            if prefix_cache:
                assert h1.prefix_hit_tokens == 8
                assert h2.prefix_hit_tokens == 8
            sched.pool.check_invariants()
            return warm, r1, r2
        finally:
            sched.close()

    shared = run_pair(True)
    independent = run_pair(False)
    assert shared == independent
    assert shared[1] != shared[2]  # the seeds genuinely diverged


def test_loadgen_end_to_end_continuous(trained, devices8):
    ff, _ = trained
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=B, page_size=4, devices=devices8[:1])
    try:
        sched.generate([1, 2], 2, timeout=120.0)  # pay the compile
        rng = np.random.RandomState(5)
        wl = sample_workload(rng, 10, V, prompt_len_range=(2, 6),
                             max_new_range=(2, 6), long_frac=0.3,
                             long_max_new_range=(8, 10))
        report = run_loadgen(sched, wl, rate_rps=100.0, seed=2,
                             timeout_s=120.0)
        assert report["completed"] == 10 and report["failures"] == 0
        assert report["tokens_generated"] == sum(m for _, m in wl)
        assert report["tokens_per_s"] > 0
        assert report["ttft"]["n"] == 10
        st = sched.stats()
        assert st["kv_pool"]["peak_used_blocks"] > 0
        assert st["kv_pool"]["used_blocks"] == 0
    finally:
        sched.close()
