"""Shipped pre-searched strategies load and run (reference parity:
examples/cpp/DLRM/strategies/*.pb distributed with the repo and loaded
via --import-strategy)."""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer  # noqa: E402
from flexflow_tpu.strategy import Strategy  # noqa: E402

ART = os.path.join(os.path.dirname(__file__), "..", "examples", "strategies")


import search_strategies as _SS  # noqa: E402


@pytest.mark.parametrize("name,builder,batch,cfg_kw", _SS.JOBS)
def test_shipped_strategy_loads_and_trains(devices8, name, builder, batch,
                                           cfg_kw):
    path = os.path.join(ART, f"{name}.json")
    assert os.path.exists(path), f"missing shipped strategy {path}"
    s = Strategy.load(path)
    assert s.total_devices == 8

    cfg = FFConfig(batch_size=batch, num_devices=8, **cfg_kw)
    ff = FFModel(cfg)
    getattr(_SS, builder)(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy=s, devices=devices8)
    rs = np.random.RandomState(0)
    inputs = {}
    for op in ff.layers.source_ops():
        shp = op.outputs[0].shape.logical_shape
        if op.outputs[0].dtype.np_dtype.kind == "i":
            hi = 100
            inputs[op.name] = rs.randint(0, hi, shp).astype(np.int32)
        else:
            inputs[op.name] = rs.randn(*shp).astype(np.float32)
    n_cls = ff.layers.sink_op().outputs[0].shape.logical_shape[-1]
    y = rs.randint(0, max(2, n_cls), (batch,))
    m = ff.train_step(inputs, y)
    assert np.isfinite(float(m["loss"]))


def test_v5p32_artifacts_validate_on_16_device_mesh():
    """Every shipped v5p-32 artifact (searched at BASELINE workload
    scale under the v5p-32 torus machine model) applies to its
    reduced-size twin graph and trains one step on a 16-device CPU
    mesh.  Runs in a subprocess: this process's conftest pins 8
    devices (VERDICT r03 Missing #2)."""
    import subprocess

    helper = os.path.join(os.path.dirname(__file__), "helpers",
                          "validate_v5p32.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # helper sets its own device count
    res = subprocess.run([sys.executable, helper], env=env,
                         capture_output=True, text=True, timeout=1800)
    assert res.returncode == 0, res.stdout + res.stderr
    for name in _SS._v5p32_models():
        assert f"v5p32[{name}]" in res.stdout, (name, res.stdout)
