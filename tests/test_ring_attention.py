"""Sequence parallelism: ring attention + flash attention tests.

New TPU-native capability (SURVEY §5: the reference has no context
parallelism) — validated hermetically on the 8-device CPU mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.transformer import bert_sp_strategy, build_bert
from flexflow_tpu.ops.pallas.flash_attention import _ref_attention, flash_attention


# ---------------------------------------------------------------------------
# flash attention (jnp fallback path on CPU; same custom_vjp as TPU)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(4, 32, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(4, 48, 16).astype(np.float32))
    v = jnp.asarray(rng.randn(4, 48, 16).astype(np.float32))
    scale = 0.25
    out = flash_attention(q, k, v, scale, causal)
    ref = _ref_attention(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads_match(causal):
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 16, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 16, 8).astype(np.float32))

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, 0.3, causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, 0.3, causal) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# ring attention end-to-end through the PCG
# ---------------------------------------------------------------------------

def _tiny_bert(causal=False, layers=1):
    ff = FFModel(FFConfig())
    build_bert(ff, batch_size=4, seq_length=32, hidden_size=32,
               num_layers=layers, num_heads=4, intermediate_size=64)
    return ff


def test_ring_attention_forward_matches_single(devices8):
    xs = np.random.RandomState(0).randn(4, 32, 32).astype(np.float32)
    ff1 = _tiny_bert()
    ff1.compile(devices=devices8[:1], seed=7)
    ref = np.asarray(ff1.forward({"input": xs}))

    ff_sp = _tiny_bert()
    ff_sp.compile(strategy=bert_sp_strategy(8, sp=4), devices=devices8, seed=7)
    out = np.asarray(ff_sp.forward({"input": xs}))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_ring_attention_causal_matches_vanilla(devices8):
    """Causal masking across ring steps (the subtle block-offset case)."""
    from flexflow_tpu.fftype import ActiMode

    def build(ff):
        x = ff.create_tensor([2, 32, 16], name="x")
        t = ff.multihead_attention(x, x, x, 16, 4, causal=True, name="attn")
        return ff.dense(t, 8, name="out")

    xs = np.random.RandomState(2).randn(2, 32, 16).astype(np.float32)
    ff1 = FFModel(FFConfig())
    build(ff1)
    ff1.compile(devices=devices8[:1], seed=3)
    ref = np.asarray(ff1.forward({"x": xs}))

    ff_sp = FFModel(FFConfig())
    build(ff_sp)
    ff_sp.compile(strategy=bert_sp_strategy(8, sp=8), devices=devices8, seed=3)
    out = np.asarray(ff_sp.forward({"x": xs}))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_ring_attention_training_step(devices8):
    """Gradients flow through shard_map + ppermute; loss decreases."""
    ff = _tiny_bert()
    ff.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        strategy=bert_sp_strategy(8, sp=4),
        devices=devices8,
        seed=0,
    )
    xs = np.random.RandomState(1).randn(4, 32, 32).astype(np.float32)
    ys = np.random.RandomState(2).randint(0, 2, 4).astype(np.int32)
    losses = [float(ff.train_step({"input": xs}, ys)["loss"]) for _ in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_flash_path_through_model_layer(devices8):
    """flash_min_seq=0 forces the Pallas/flash branch in
    MultiHeadAttention._attend through the full model path (coverage
    guard: the default threshold routes short seqs to plain XLA)."""
    import numpy as np

    def build(flash_min):
        ff = FFModel(FFConfig(batch_size=4, num_devices=1,
                              flash_min_seq=flash_min))
        build_bert(ff, batch_size=4, seq_length=32, hidden_size=32,
                   num_layers=1, num_heads=4, intermediate_size=64)
        ff.compile(devices=devices8[:1], seed=11)
        return ff

    xs = np.random.RandomState(0).randn(4, 32, 32).astype(np.float32)
    out_flash = np.asarray(build(0).forward({"input": xs}))
    out_plain = np.asarray(build(10_000).forward({"input": xs}))
    np.testing.assert_allclose(out_flash, out_plain, rtol=2e-4, atol=2e-4)


def test_ring_flash_blocks_match_dense(devices8):
    """Non-causal ring steps can run the Pallas flash kernel per block
    (interpret mode on CPU): the (out, lse) log-sum-exp merge must
    reproduce the dense block path exactly."""
    from jax.sharding import Mesh

    from flexflow_tpu.parallel.ring_attention import ring_attention

    sp = 4
    b, s, h, d = 2, 128 * sp, 2, 64  # >=128-wide shards, lane-friendly d
    rng = np.random.RandomState(5)
    qh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    kh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    vh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    mesh = Mesh(np.array(devices8[:sp]), ("seq",))
    scale = 1.0 / np.sqrt(d)
    dense = ring_attention(qh, kh, vh, mesh, "seq", scale=scale,
                           block_impl="dense")
    flash = ring_attention(qh, kh, vh, mesh, "seq", scale=scale,
                           block_impl="flash")
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)
    # and both agree with plain single-device attention
    ref = _ref_attention(
        qh.transpose(0, 2, 1, 3).reshape(b * h, s, d),
        kh.transpose(0, 2, 1, 3).reshape(b * h, s, d),
        vh.transpose(0, 2, 1, 3).reshape(b * h, s, d), scale, False,
    ).reshape(b, h, s, d).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # forced flash refuses shapes the kernel cannot tile rather than
    # silently running dense
    tiny = jnp.asarray(rng.randn(2, 4 * sp, 2, 8).astype(np.float32))
    with pytest.raises(ValueError, match="unsupported"):
        ring_attention(tiny, tiny, tiny, mesh, "seq", scale=scale,
                       block_impl="flash")
    # the support check must see SHARD shapes: global 128*sp-divisible
    # but shard 96-long has no >=128 tile -> refuse, not crash
    odd = jnp.asarray(rng.randn(2, 96 * sp, 2, 64).astype(np.float32))
    with pytest.raises(ValueError, match="unsupported"):
        ring_attention(odd, odd, odd, mesh, "seq", scale=scale,
                       block_impl="flash")


def test_ring_flash_gradients_match_dense(devices8):
    """The flash ring is fully differentiable: the manual ring backward
    (rotating dk/dv partial sums, Pallas bwd kernels per block against
    the global lse) must reproduce the dense ring's autodiff gradients."""
    from jax.sharding import Mesh

    from flexflow_tpu.parallel.ring_attention import ring_attention

    sp = 4
    b, s, h, d = 2, 128 * sp, 2, 64
    rng = np.random.RandomState(7)
    qh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    kh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    vh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    mesh = Mesh(np.array(devices8[:sp]), ("seq",))
    scale = 1.0 / np.sqrt(d)

    def loss(impl):
        def f(q, k, v):
            o = ring_attention(q, k, v, mesh, "seq", scale=scale,
                               block_impl=impl)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        return jax.grad(f, argnums=(0, 1, 2))(qh, kh, vh)

    g_dense = loss("dense")
    g_flash = loss("flash")
    for gd, gf in zip(g_dense, g_flash):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_causal_matches_dense(devices8, causal):
    """Causal flash rings: the diagonal step uses the kernel's static
    causal mask, off-diagonal steps gate a traced visibility bit — both
    forward and the manual backward must match the dense causal ring."""
    from jax.sharding import Mesh

    from flexflow_tpu.parallel.ring_attention import ring_attention

    sp = 4
    b, s, h, d = 2, 128 * sp, 2, 64
    rng = np.random.RandomState(11)
    qh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    kh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    vh = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    mesh = Mesh(np.array(devices8[:sp]), ("seq",))
    scale = 1.0 / np.sqrt(d)

    def run(impl):
        def f(q, k, v):
            o = ring_attention(q, k, v, mesh, "seq", scale=scale,
                               causal=causal, block_impl=impl)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        (loss, o), grads = jax.value_and_grad(
            f, argnums=(0, 1, 2), has_aux=True)(qh, kh, vh)
        return o, grads

    o_dense, g_dense = run("dense")
    o_flash, g_flash = run("flash")
    np.testing.assert_allclose(np.asarray(o_flash), np.asarray(o_dense),
                               rtol=2e-4, atol=2e-4)
    for gd, gf in zip(g_dense, g_flash):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=3e-4, atol=3e-4)
