"""Disaggregated prefill/decode serving fleet (serving/disagg.py,
docs/SERVING.md "Disaggregated fleet"): --serving-roles parsing, the
migrate-vs-re-prefill cost model, the DisaggServingFront dispatcher's
divert/migrate/requeue pipeline on a deterministic fake KV model —
both cost decisions reachable, completions token-identical to the
colocated fleet — and the transfer fault matrix (BLOB_PARTIAL_UPLOAD /
BLOB_TRANSIENT / BLOB_UNAVAILABLE through a FaultyBlobStore fabric):
every mid-stream fault degrades to a re-prefill that still yields the
exact tokens, never corrupt output.  The slow section reruns the token
-identity oracle through real trained engines on both paged-attention
kernels with the pool invariant checker armed."""
import numpy as np
import pytest

from flexflow_tpu.obs.metrics import MetricsRegistry
from flexflow_tpu.resilience.faults import Fault, FaultKind, FaultPlan
from flexflow_tpu.serving import (
    BlobStoreFabric, DisaggServingFront, InProcessFabric,
    MigrationCostModel, ServingFront, parse_serving_roles)
from flexflow_tpu.store.blobstore import FaultyBlobStore, LocalBlobStore

V = 16
NO_SLEEP = lambda s: None  # noqa: E731


# -- role spec parsing ---------------------------------------------------

def test_parse_roles_counts_and_bare_names():
    assert parse_serving_roles("prefill=1,decode=2") == \
        ["prefill", "decode", "decode"]
    assert parse_serving_roles("prefill,decode") == ["prefill", "decode"]
    assert parse_serving_roles("mixed=2") == ["mixed", "mixed"]
    assert parse_serving_roles("") is None
    assert parse_serving_roles(None) is None
    assert parse_serving_roles("prefill=0,decode=1") == ["decode"]


def test_parse_roles_rejects_bad_specs():
    with pytest.raises(ValueError, match="bad count"):
        parse_serving_roles("prefill=x")
    with pytest.raises(ValueError, match="unknown role"):
        parse_serving_roles("verify=1")
    with pytest.raises(ValueError, match="must be >= 0"):
        parse_serving_roles("decode=-1")
    with pytest.raises(ValueError, match="empty spec"):
        parse_serving_roles(" , ")
    with pytest.raises(ValueError, match="decode-capable"):
        parse_serving_roles("prefill=2")
    with pytest.raises(ValueError, match="names 3"):
        parse_serving_roles("prefill=1,decode=2", num_replicas=2)


def test_front_rejects_decode_free_and_missized_roles():
    factory = lambda rid, survivors=None: FakeKVModel()  # noqa: E731
    with pytest.raises(ValueError, match="decode-capable"):
        ServingFront(factory, 2, roles=["prefill", "prefill"],
                     sleep=NO_SLEEP)
    with pytest.raises(ValueError, match="every replica"):
        ServingFront(factory, 2, roles=["mixed"], sleep=NO_SLEEP)
    with pytest.raises(ValueError, match="unknown replica role"):
        ServingFront(factory, 1, roles=["verify"], sleep=NO_SLEEP)


# -- cost model ----------------------------------------------------------

def test_cost_model_subpage_prompt_always_reprefills():
    m = MigrationCostModel()
    d = m.decide(prompt_len=3, new_blocks=0, page_size=4,
                 block_bytes=1 << 20, chunk=0, step_s=5e-3)
    assert d["decision"] == "reprefill" and d["new_blocks"] == 0


def test_cost_model_cheap_hop_migrates():
    m = MigrationCostModel(fabric_kind="inproc")
    d = m.decide(prompt_len=8, new_blocks=2, page_size=4,
                 block_bytes=4096, chunk=0, step_s=5e-3)
    # 2 blocks over ICI ~ microseconds vs 8 decode steps ~ 40ms
    assert d["decision"] == "migrate"
    assert d["migrate_s"] < d["reprefill_s"]


def test_cost_model_expensive_stream_reprefills():
    # a giant KV payload over DCN costs more than recomputing it
    m = MigrationCostModel(fabric_kind="blob")
    d = m.decide(prompt_len=8, new_blocks=2, page_size=4,
                 block_bytes=10 << 30, chunk=0, step_s=5e-3)
    assert d["decision"] == "reprefill"
    assert d["migrate_s"] > d["reprefill_s"]


def test_cost_model_cap_scales_the_threshold():
    # same workload: a generous cap admits the migration a strict
    # cap refuses
    kw = dict(prompt_len=8, new_blocks=2, page_size=4,
              block_bytes=45 << 20, chunk=0, step_s=5e-3)
    lax = MigrationCostModel(cost_cap=20.0, fabric_kind="blob")
    strict = MigrationCostModel(cost_cap=0.01, fabric_kind="blob")
    assert lax.decide(**kw)["decision"] == "migrate"
    assert strict.decide(**kw)["decision"] == "reprefill"


def test_cost_model_tail_tokens_price_into_migrate():
    m = MigrationCostModel()
    aligned = m.decide(prompt_len=8, new_blocks=2, page_size=4,
                       block_bytes=0, chunk=0, step_s=5e-3)
    tailed = m.decide(prompt_len=10, new_blocks=2, page_size=4,
                      block_bytes=0, chunk=0, step_s=5e-3)
    # the 2-token sub-page tail still re-prefills on the adopter
    assert tailed["migrate_s"] > aligned["migrate_s"]


def test_cost_model_rejects_bad_cap():
    with pytest.raises(ValueError, match="cost cap"):
        MigrationCostModel(cost_cap=0)


# -- fake-model fleet ----------------------------------------------------

class FakeKVModel:
    """Deterministic next-token model with an exportable KV surface:
    token t emits t+1 mod V, so completions have a closed form and any
    corruption shows up as wrong tokens."""

    def __init__(self, batch_slots=2, max_seq=32, page_size=4):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = 1 + batch_slots * self.max_blocks_per_seq
        self.vocab = V
        self.steps = 0
        self.kv = np.zeros((self.num_blocks, page_size, 2), np.float32)

    def reset(self):
        pass

    def step(self, tokens, seq_lens, block_tables):
        self.steps += 1
        logits = np.zeros((self.batch_slots, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits

    def export_block(self, block):
        return {"kv": np.array(self.kv[block])}

    def import_block(self, block, arrays):
        self.kv[block] = arrays["kv"]


def expected(prompt, mnt):
    out = list(prompt)
    t = prompt[-1]
    for _ in range(mnt):
        t = (t + 1) % V
        out.append(t)
    return out


def factory(rid, survivors=None):
    return FakeKVModel()


# multi-page prompts migrate (fake kv_block_bytes=0 prices the stream
# at ~one hop latency); the sub-page prompt has new_blocks=0 so it
# always re-prefills — both dispatcher decisions are deterministic
REQS = [([1, 2, 3, 4, 5, 6, 7, 8], 4), ([5], 3),
        ([1, 2, 3, 4, 5, 6, 7, 8], 4), ([9, 10, 11, 12], 5)]


def run_fleet(front, reqs=REQS, timeout=30.0):
    hs = [front.generate_async(p, m) for p, m in reqs]
    outs = [h.wait(timeout) for h in hs]
    return hs, outs


def test_disagg_fleet_token_identity_and_both_decisions():
    reg = MetricsRegistry()
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               registry=reg, sleep=NO_SLEEP)
    try:
        hs, outs = run_fleet(front)
        st = front.stats()
        h = front.health()
    finally:
        front.close()
    for (p, m), got in zip(REQS, outs):
        assert got == expected(p, m)
    assert st["mode"] == "disaggregated"
    dg = st["disagg"]
    assert dg["migrate_decisions"] > 0
    assert dg["reprefill_decisions"] > 0  # the [5] sub-page prompt
    assert dg["migrations_ok"] > 0 and dg["migrations_failed"] == 0
    assert dg["kv_transfer"]["fabric"] == "inproc"
    assert dg["kv_transfer"]["bytes_streamed"] > 0
    assert reg.counter("serving/disagg_migrate_decisions").value == \
        dg["migrate_decisions"]
    assert reg.counter("serving/kv_migration_done").value == \
        dg["migrations_ok"]
    # prefill replicas never serve client decodes
    assert all(h_.served_role == "decode" for h_ in hs)
    # per-class fleet accounting in stats + health
    assert set(st["roles"]) == {"prefill", "decode"}
    assert st["roles"]["decode"]["live"] == 1
    assert h["status"] == "ok" and set(h["roles"]) == \
        {"prefill", "decode"}


def test_disagg_migration_is_a_prefix_cache_hit():
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               sleep=NO_SLEEP)
    try:
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        h = front.generate_async(prompt, 4)
        assert h.wait(30.0) == expected(prompt, 4)
        rec = h.migration
    finally:
        front.close()
    assert rec is not None and rec["decision"] == "migrate"
    assert rec["ok"] is True
    # the adopted blocks made the re-dispatched prompt a cache hit
    # (capped at plen-1 page-aligned: the last token still computes)
    assert h.prefix_hit_tokens >= ((len(prompt) - 1) // 4) * 4


def test_disagg_migrates_at_most_once_per_request():
    """The one-migration guard: a request whose migration already ran
    (ok or not) dispatches normally on requeue instead of ping-ponging
    through the prefill class forever."""
    class DeadFabric(InProcessFabric):
        def transfer(self, key, data):
            raise RuntimeError("fabric down")

    reg = MetricsRegistry()
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               fabric=DeadFabric(),
                               registry=reg, sleep=NO_SLEEP)
    try:
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        h = front.generate_async(prompt, 4)
        got = h.wait(30.0)
        st = front.stats()
    finally:
        front.close()
    assert got == expected(prompt, 4)  # re-prefill, correct tokens
    assert h.migration["decision"] == "migrate"
    assert h.migration["ok"] is False
    assert st["disagg"]["migrations_failed"] == 1
    assert st["disagg"]["migrate_decisions"] == 1  # no second divert
    assert reg.counter("serving/kv_migration_failed").value == 1


def test_mixed_fleet_stays_colocated():
    """No prefill class -> the divert hook never fires and the front
    behaves exactly like the base ServingFront."""
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["mixed", "mixed"], sleep=NO_SLEEP)
    try:
        hs, outs = run_fleet(front)
        st = front.stats()
    finally:
        front.close()
    for (p, m), got in zip(REQS, outs):
        assert got == expected(p, m)
    assert st["disagg"]["migrate_decisions"] == 0
    assert all(h.migration is None for h in hs)


def test_colocated_front_oracle_token_identity():
    """The acceptance oracle at fake-model scale: greedy completions
    through the disagg fleet byte-identical to the colocated front."""
    colo = ServingFront(factory, 2, sleep=NO_SLEEP)
    try:
        _, want = run_fleet(colo)
    finally:
        colo.close()
    disagg = DisaggServingFront(factory, num_replicas=2,
                                roles=["prefill", "decode"],
                                sleep=NO_SLEEP)
    try:
        _, got = run_fleet(disagg)
        assert disagg.stats()["disagg"]["migrate_decisions"] > 0
    finally:
        disagg.close()
    assert got == want


# -- transfer fault matrix -----------------------------------------------

def faulty_blob_fabric(tmp_path, *faults):
    store = FaultyBlobStore(LocalBlobStore(str(tmp_path)),
                            FaultPlan(list(faults)), sleep=NO_SLEEP)
    return BlobStoreFabric(store), store


@pytest.mark.parametrize("kind,expect_failed", [
    (FaultKind.BLOB_PARTIAL_UPLOAD, True),   # torn object LANDS; only
                                             # the reader crc catches it
    (FaultKind.BLOB_TRANSIENT, True),        # put/get raises once
    (FaultKind.BLOB_UNAVAILABLE, True),      # outage window
    (FaultKind.BLOB_LATENCY, False),         # slow but correct
])
def test_fault_matrix_degrades_to_reprefill_token_identical(
        tmp_path, kind, expect_failed):
    fab, store = faulty_blob_fabric(
        tmp_path, Fault(step=1, kind=kind))
    reg = MetricsRegistry()
    # a huge cost cap keeps the decision "migrate" despite DCN pricing,
    # so the fault actually lands on the streaming path
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               fabric=fab, migration_cost_cap=1e9,
                               registry=reg, sleep=NO_SLEEP)
    try:
        hs, outs = run_fleet(front)
        st = front.stats()
    finally:
        front.close()
    # the acceptance bar: a mid-stream fault NEVER produces wrong
    # tokens — worst case is a re-prefill of the same prompt
    for (p, m), got in zip(REQS, outs):
        assert got == expected(p, m)
    assert st["disagg"]["migrate_decisions"] > 0
    if expect_failed:
        assert st["disagg"]["migrations_failed"] >= 1
        assert reg.counter("serving/kv_migration_failed").value >= 1
    else:
        assert st["disagg"]["migrations_failed"] == 0
        assert st["disagg"]["migrations_ok"] > 0


def test_fault_matrix_counters_match_store_injections(tmp_path):
    fab, store = faulty_blob_fabric(
        tmp_path,
        Fault(step=1, kind=FaultKind.BLOB_PARTIAL_UPLOAD,
              payload={"fraction": 0.5}))
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               fabric=fab, migration_cost_cap=1e9,
                               sleep=NO_SLEEP)
    try:
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        h = front.generate_async(prompt, 4)
        got = h.wait(30.0)
        st = front.stats()
    finally:
        front.close()
    assert got == expected(prompt, 4)
    assert store.counters["partial_uploads"] == 1
    assert h.migration["ok"] is False
    assert st["disagg"]["kv_transfer"]["fabric"] == "blob"


# -- real engines (full tier) --------------------------------------------

V_GPT, S_GPT, B_GPT = 32, 16, 4
PREFIX = [3, 5, 7, 2]
PROMPTS = [PREFIX + [9, 4], PREFIX + [9, 11], PREFIX + [1], [8, 2]]
MNT = [6, 6, 5, 4]


@pytest.fixture(scope="module")
def trained(devices8):
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt

    ff = FFModel(FFConfig(batch_size=B_GPT, num_devices=1))
    build_gpt(ff, batch_size=B_GPT, seq_length=S_GPT, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V_GPT)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    rng = np.random.RandomState(0)
    start = rng.randint(0, V_GPT, (B_GPT, 1))
    step = rng.randint(1, 6, (B_GPT, 1))
    seq_ids = (start + step * np.arange(S_GPT + 1)) % V_GPT
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S_GPT, dtype=np.int32),
                          (B_GPT, S_GPT)).copy()
    for _ in range(40):
        ff.train_step({"input": ids, "positions": pos}, labels)
    return ff


def configure_serving(ff, kernel):
    cfg = ff.config
    cfg.serving_slots = 2
    cfg.kv_page_size = 4
    cfg.kv_pool_blocks = 12
    cfg.paged_kernel = kernel
    cfg.prefill_chunk = 4 if kernel == "pallas" else 0
    return cfg


def run_real(front):
    try:
        hs = [front.generate_async(p, m)
              for p, m in zip(PROMPTS, MNT)]
        return [h.wait(240.0) for h in hs], front.stats()
    finally:
        front.close()


@pytest.mark.slow
@pytest.mark.parametrize("kernel", ["gather", "pallas"])
def test_disagg_token_identity_vs_colocated_engine(
        trained, devices8, kernel):
    """The PR's acceptance oracle on real engines: greedy completions
    through a 1-prefill + 1-decode disagg fleet byte-identical to the
    colocated 2-mixed front, on BOTH paged-attention formulations,
    with the pool invariant checker armed at every scheduler step and
    at least one migration actually streamed."""
    configure_serving(trained, kernel)
    colo = ServingFront.from_trained(
        trained, num_replicas=2, devices=devices8[:1],
        check_invariants=True)
    want, _ = run_real(colo)

    disagg = DisaggServingFront.from_trained(
        trained, num_replicas=2, devices=devices8[:1],
        roles=["prefill", "decode"], check_invariants=True)
    got, st = run_real(disagg)

    assert got == want
    assert st["disagg"]["migrate_decisions"] > 0
    assert st["disagg"]["migrations_ok"] > 0
    assert st["disagg"]["kv_transfer"]["blocks_streamed"] > 0


def test_telemetry_summary_renders_disagg_line(tmp_path):
    import importlib
    import json

    reg = MetricsRegistry()
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               registry=reg, sleep=NO_SLEEP)
    try:
        front.generate_async([1, 2, 3, 4, 5, 6, 7, 8], 4).wait(30.0)
        front.generate_async([5], 3).wait(30.0)
    finally:
        front.close()
    path = tmp_path / "run_telemetry.jsonl"
    assert reg.write_jsonl(str(path)) > 0
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    summary = importlib.import_module("tools.telemetry_summary")
    text = summary.summarize(recs)
    assert "disaggregated fleet" in text
    assert "migrate=1" in text and "reprefill=1" in text
    assert "migrations_done=1" in text
