"""Simulator + MCMC search tests (SURVEY §4 improvement: the reference
has no isolated search/simulator tests — we do, hermetically)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only


from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.ops.op import ShardConfig
from flexflow_tpu.pcg.mcmc import MCMCSearch, _factorizations, find_candidates
from flexflow_tpu.sim.machine_model import (
    DeviceSpec,
    SimpleMachineModel,
    TpuPodModel,
)
from flexflow_tpu.sim.simulator import OpCostModel, Simulator
from flexflow_tpu.strategy import (
    Strategy,
    apply_strategy,
    assign_views,
    data_parallel_strategy,
)


def build_mlp(hidden=4096, batch=64):
    ff = FFModel(FFConfig())
    x = ff.create_tensor([batch, hidden], name="x")
    t = ff.dense(x, hidden, activation=ActiMode.RELU, name="fc1")
    t = ff.dense(t, hidden, name="fc2")
    return ff


def test_tpu_pod_model_basics():
    m = TpuPodModel(topology=(4, 4))
    assert m.num_devices() == 16
    assert m.coords(0) == (0, 0)
    assert m.coords(5) == (1, 1)
    # wraparound: 0 -> 3 on a 4-ring is 1 hop
    t_wrap = m.p2p_time(1 << 20, 0, 3)
    t_mid = m.p2p_time(1 << 20, 0, 2)
    assert t_wrap < t_mid
    # collectives scale with axis length
    assert m.axis_allreduce_time(1 << 24, 4) > m.axis_allreduce_time(1 << 24, 2)
    assert m.axis_allreduce_time(1 << 20, 1) == 0.0


def test_simulator_dp_scales_compute():
    """DP over 8 devices should cut compute for a flops-bound model
    (large batch, modest weights)."""
    machine = TpuPodModel(topology=(8,))
    sim = Simulator(machine)
    # hidden large enough that even the 1/8 shard stays flops-bound
    # (at hidden=512 the shard goes HBM-bound and scaling tops out ~4x)
    ff = build_mlp(hidden=2048, batch=8192)
    g1 = apply_strategy(ff.layers, data_parallel_strategy(1))
    assign_views(g1, {"data": 1})
    g8 = apply_strategy(ff.layers, data_parallel_strategy(8))
    assign_views(g8, {"data": 8})
    r1 = sim.simulate(g1, {"data": 1})
    sim2 = Simulator(machine)
    r8 = sim2.simulate(g8, {"data": 8})
    assert r8.compute_time < r1.compute_time / 4
    assert r8.sync_time > 0  # grad all-reduce appears
    assert r1.sync_time == 0


def test_simulator_memory_tp_shards_weights():
    machine = TpuPodModel(topology=(8,))
    ff = build_mlp()
    s_tp = Strategy(mesh_axes={"data": 4, "model": 2})
    s_tp.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 4})]
    s_tp.shard_configs["fc1"] = ShardConfig(channel=2)
    g_tp = apply_strategy(ff.layers, s_tp)
    assign_views(g_tp, s_tp.mesh_axes)
    g_dp = apply_strategy(ff.layers, data_parallel_strategy(8))
    assign_views(g_dp, {"data": 8})
    sim = Simulator(machine)
    mem_tp = sim.per_device_memory(g_tp)
    mem_dp = sim.per_device_memory(g_dp)
    assert mem_tp < mem_dp  # fc1+fc2 weights sharded 2-way


def test_factorizations():
    f = _factorizations(8)
    assert (8, 1, 1) in f and (4, 2, 1) in f and (1, 1, 8) in f
    assert all(a * b * c == 8 for a, b, c in f)


def test_find_candidates():
    ff = build_mlp()
    cands = find_candidates(ff.layers)
    assert {c.name for c in cands} == {"fc1", "fc2"}


def test_mcmc_improves_on_dp_when_memory_bound():
    """With a tiny HBM budget, pure DP (replicated weights) exceeds
    memory and the search must discover tensor parallelism."""
    machine = TpuPodModel(topology=(8,))
    ff = build_mlp(hidden=8192, batch=8)

    def sim_factory():
        return Simulator(machine)

    # per-device budget that DP (full 8192x8192 x2 weights x4 copies) busts
    budget = 600 * 2**20
    search = MCMCSearch(
        ff.layers, 8, sim_factory, budget=60, alpha=0.05,
        memory_budget=budget, memory_lambda=4.0, seed=1,
    )
    best = search.optimize()
    dp_cost = search.evaluate(data_parallel_strategy(8))
    best_cost = search.evaluate(best)
    assert best_cost < dp_cost
    assert best.shard_configs  # some op got sharded


def test_mcmc_strategy_runs_e2e(devices8):
    """Whatever the search returns must execute correctly."""
    ff = build_mlp(hidden=64, batch=16)
    cfg = ff.config
    cfg.search_budget = 20
    cfg.num_devices = 8
    ff.compile(devices=devices8, seed=0)
    xs = np.random.RandomState(0).randn(16, 64).astype(np.float32)
    out = np.asarray(ff.forward({"x": xs}))
    assert out.shape == (16, 64)
    assert np.isfinite(out).all()


def _deep_mlp(layers=24):
    ff = FFModel(FFConfig())
    x = ff.create_tensor([64, 1024], name="x")
    t = x
    for i in range(layers):
        t = ff.dense(t, 1024, activation=ActiMode.RELU, name=f"enc{i}")
    ff.dense(t, 8, name="head")
    return ff


def test_mcmc_megatron_pairing_makes_adjacent_shards_legal():
    """_build's column->row pairing: consecutively sharded linears get
    channel, reduction, channel, ... — without it, channel+channel on
    adjacent linears is an illegal degree blow-up, and the cost
    improves monotonically as more of the run is sharded."""
    machine = TpuPodModel(topology=(8,))
    ff = _deep_mlp(12)
    s = MCMCSearch(ff.layers, 8, lambda: Simulator(machine), budget=1)
    costs = []
    for k in (0, 2, 6, 12):
        flags = {f"enc{i}": True for i in range(k)}
        st = s._build(4, 2, 1, flags)
        c = s.evaluate(st)
        assert c != float("inf"), f"k={k} infeasible"
        costs.append(c)
    assert costs[-1] < costs[0]  # all-sharded beats none under dp4xtp2
    st = s._build(4, 2, 1, {f"enc{i}": True for i in range(4)})
    kinds = [(n, ("channel" if v.channel > 1 else "reduction"))
             for n, v in sorted(st.shard_configs.items())]
    assert kinds == [("enc0", "channel"), ("enc1", "reduction"),
                     ("enc2", "channel"), ("enc3", "reduction")]


def test_mcmc_propagate_converges_faster_on_deep_net():
    """FF_USE_PROPAGATE (reference model.cc:3180-3258): the propagate
    move harmonizes a run of structurally identical layers toward one
    config in a single evaluation.  On a 24-layer net at matched budget
    it must win (better cost, or equal cost no later) on a majority of
    seeds and never lose badly in aggregate."""
    machine = TpuPodModel(topology=(8,))
    ff = _deep_mlp(24)

    def sim_factory():
        return Simulator(machine)

    wins, costs_p, costs_n = 0, [], []
    seeds = range(1, 11)
    for seed in seeds:
        sp = MCMCSearch(ff.layers, 8, sim_factory, budget=60, alpha=0.05,
                        seed=seed, propagate=True, continue_chance=0.9)
        bp = sp.optimize()
        cp = sp.evaluate(bp)
        sn = MCMCSearch(ff.layers, 8, sim_factory, budget=60, alpha=0.05,
                        seed=seed, propagate=False)
        bn = sn.optimize()
        cn = sn.evaluate(bn)
        costs_p.append(cp)
        costs_n.append(cn)
        if cp < cn * (1 - 1e-9) or (
            abs(cp - cn) <= 1e-9 * cn
            and sp.best_iteration <= sn.best_iteration
        ):
            wins += 1
    assert wins >= 6, (wins, costs_p, costs_n)
    assert sum(costs_p) <= sum(costs_n) * 1.08
