"""Speculative decoding (serving/speculative.py + the scheduler's
verify rounds): proposer units (n-gram lookup, draft-engine lifecycle,
adaptive k), scheduler spec rounds against the deterministic fake step
model (token identity, acceptance bookkeeping, rejection rollback,
empty-round fallback, verify-fault degradation), and slow real-engine
byte-identity + supervised-fault tests over a trained tiny GPT."""
import time

import numpy as np
import pytest

from flexflow_tpu.obs.metrics import MetricsRegistry
from flexflow_tpu.serving import ContinuousScheduler
from flexflow_tpu.serving.speculative import (AdaptiveK,
                                              DraftModelProposer,
                                              NGramProposer,
                                              build_proposer)

V = 16


# -- n-gram proposer -----------------------------------------------------

def test_ngram_prefers_longest_then_most_recent():
    p = NGramProposer(max_ngram=3, min_ngram=1)
    # trigram [7, 8, 9] occurs once, earlier — its continuation wins
    # over any shorter suffix match
    ctx = [7, 8, 9, 1, 2, 3, 7, 8, 9]
    assert p.propose({0: ctx}, 3) == {0: [1, 2, 3]}
    # two occurrences of the suffix bigram: the MOST RECENT match's
    # continuation is proposed (5, not 4)
    ctx = [1, 2, 4, 1, 2, 5, 9, 1, 2]
    assert p.propose({0: ctx}, 2) == {0: [5, 9]}


def test_ngram_no_match_omits_slot():
    p = NGramProposer()
    out = p.propose({0: [1, 2, 3, 4], 1: [5, 5, 5, 5]}, 4)
    assert 0 not in out          # all tokens distinct: nothing recurs
    assert out[1] == [5]         # degenerate self-overlap still drafts


def test_ngram_k_caps_draft_length():
    p = NGramProposer()
    ctx = [3, 4, 5, 6, 7, 8, 3, 4]
    assert p.propose({0: ctx}, 2) == {0: [5, 6]}
    assert p.propose({0: ctx}, 10) == {0: [5, 6, 7, 8, 3, 4]}


def test_ngram_window_bounds_lookback():
    # the only match sits outside the window: no proposal
    far = [1, 2, 9, 9] + [int(t) for t in np.arange(100) % 7 + 3]
    p = NGramProposer(max_window=50)
    assert 0 not in p.propose({0: far + [1, 2]}, 4)
    wide = NGramProposer(max_window=4096)
    assert wide.propose({0: far + [1, 2]}, 2) == {0: [9, 9]}


def test_ngram_validates_bounds_and_tolerates_lifecycle():
    with pytest.raises(ValueError, match="min_ngram"):
        NGramProposer(max_ngram=2, min_ngram=3)
    p = NGramProposer()
    p.release(42)   # unknown slot: no-op
    p.reset()       # stateless: no-op
    assert p.stats() == {}


# -- adaptive k ----------------------------------------------------------

def test_adaptive_k_shrinks_on_misses_and_regrows():
    ak = AdaptiveK(4)
    assert ak.k == 4  # optimistic start: first rounds draft fully
    for _ in range(20):
        ak.update(4, 0)  # nothing lands
    assert ak.k == 1     # shrunk to the never-worse floor, not 0
    for _ in range(20):
        ak.update(1, 1)  # everything lands
    assert ak.k == 4     # regrown to the CLI cap, not past it


def test_adaptive_k_ignores_empty_rounds():
    ak = AdaptiveK(3)
    ak.update(0, 0)  # a round with no proposals carries no signal
    assert ak.k == 3 and ak.rate == 1.0


# -- draft-model proposer ------------------------------------------------

class FakeDraftModel:
    """Draft-engine stand-in with the PagedKVDecodeModel step
    contract: argmax of the returned one-hot logits is (token + 1 +
    off) % V, so off=0 drafts the same successor chain as the fake
    target and off!=0 is an always-wrong drafter."""

    def __init__(self, batch_slots=2, max_seq=32, page_size=4, off=0):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = 1 + batch_slots * self.max_blocks_per_seq
        self.vocab = V
        self.off = off
        self.steps = 0
        self.resets = 0
        self.fail_at_steps = set()

    def reset(self):
        self.resets += 1

    def step(self, tokens, seq_lens, block_tables):
        self.steps += 1
        if self.steps in self.fail_at_steps:
            raise RuntimeError(f"injected draft fault @{self.steps}")
        logits = np.zeros((self.batch_slots, V), np.float32)
        nxt = (np.asarray(tokens) + 1 + self.off) % V
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits


def test_draft_proposer_free_runs_successor_chain():
    p = DraftModelProposer(FakeDraftModel())
    out = p.propose({0: [3, 4, 5]}, 3)
    assert out == {0: [6, 7, 8]}  # fed the context, free-ran 3 drafts
    # context advanced by an accept: only the delta is re-fed (the
    # last accepted token reseeds the first draft)
    steps_before = p.model.steps
    out = p.propose({0: [3, 4, 5, 6, 7, 8]}, 2)
    assert out == {0: [9, 10]}
    assert p.model.steps - steps_before <= 4  # no full-prompt replay
    assert p.stats()["live_draft_seqs"] == 1


def test_draft_proposer_reconciles_after_rejection():
    p = DraftModelProposer(FakeDraftModel())
    assert p.propose({0: [3, 4, 5]}, 3) == {0: [6, 7, 8]}
    # the verifier rejected the tail and corrected to 9: the draft
    # pool rolls back past the divergence and re-feeds from there
    out = p.propose({0: [3, 4, 5, 6, 9]}, 2)
    assert out == {0: [10, 11]}
    p.pool.check_invariants()


def test_draft_proposer_batches_slots_per_dispatch():
    p = DraftModelProposer(FakeDraftModel(batch_slots=2))
    out = p.propose({0: [3, 4], 1: [8, 9, 10]}, 2)
    assert out == {0: [5, 6], 1: [11, 12]}
    # slot 1's context is one token longer, so it pays one extra
    # catch-up dispatch; everything else shares dispatches
    assert p.model.steps <= 5


def test_draft_proposer_respects_limits_and_release():
    p = DraftModelProposer(FakeDraftModel())
    # a cap at the context length leaves the draft pool no room at
    # all: the slot is skipped entirely
    assert 0 not in p.propose({0: [1, 2, 3]}, 4, limits={0: 3})
    # one position of headroom: one written draft plus the free final
    # draft that rides the last dispatch's logits
    out = p.propose({0: [1, 2, 3]}, 4, limits={0: 4})
    assert out == {0: [4, 5]}
    p.release(0)
    assert p.stats()["live_draft_seqs"] == 0
    p.pool.check_invariants()
    assert p.pool.used_blocks == 0


def test_draft_fault_degrades_to_dead_and_reset_revives():
    model = FakeDraftModel()
    model.fail_at_steps = {2}
    p = DraftModelProposer(model)
    assert p.propose({0: [3, 4, 5]}, 3) == {}  # died mid-round
    assert p.stats()["dead"] and p.stats()["draft_faults"] == 1
    assert p.propose({0: [3, 4, 5, 6]}, 3) == {}  # stays dead
    p.reset()
    assert model.resets == 1
    assert not p.stats()["dead"]
    assert p.propose({0: [3, 4, 5]}, 2) == {0: [6, 7]}


def test_build_proposer_wiring():
    from flexflow_tpu.config import ConfigError

    assert isinstance(build_proposer("ngram"), NGramProposer)
    assert isinstance(build_proposer("draft", FakeDraftModel()),
                      DraftModelProposer)
    with pytest.raises(ConfigError, match="draft model"):
        build_proposer("draft")
    with pytest.raises(ConfigError, match="no proposer"):
        build_proposer("off")


# -- scheduler spec rounds against the fake model ------------------------

class FakeSpecModel:
    """FakeStepModel (tests/test_continuous_scheduler.py) plus the
    speculative surface: verify_step scores every fed position with
    the same (token + 1) % V successor rule the plain step uses, so a
    successor-chain draft is always accepted and anything else is
    rejected at its first wrong position."""

    def __init__(self, batch_slots=2, max_seq=32, page_size=4,
                 num_blocks=None, prefill_chunk=0, spec_decode="ngram",
                 spec_k=4, draft_model=None):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = (num_blocks if num_blocks is not None
                           else 1 + batch_slots * self.max_blocks_per_seq)
        self.vocab = V
        self.prefill_chunk = prefill_chunk
        self.prefix_cache = True
        self.spec_decode = spec_decode
        self.spec_k = spec_k
        self.verify_chunk = spec_k + 1
        self.draft_model = draft_model
        self.steps = 0
        self.verify_calls = 0
        self.prefill_calls = 0
        self.copied_blocks = []
        self.fail_at_steps = set()
        self.fail_verify_at = set()
        self.resets = 0

    def reset(self):
        self.resets += 1

    def step(self, tokens, seq_lens, block_tables):
        self.steps += 1
        if self.steps in self.fail_at_steps:
            raise RuntimeError(f"injected step fault @{self.steps}")
        logits = np.zeros((self.batch_slots, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits

    def prefill_step(self, tokens, positions, block_tables):
        self.prefill_calls += 1

    def verify_step(self, tokens, seq_lens, counts, block_tables):
        self.verify_calls += 1
        if self.verify_calls in self.fail_verify_at:
            raise RuntimeError(
                f"injected verify fault @{self.verify_calls}")
        C = tokens.shape[1]
        logits = np.zeros((self.batch_slots, C, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        for j in range(C):
            logits[np.arange(self.batch_slots), j, nxt[:, j]] = 1.0
        return logits

    def copy_block(self, src, dst):
        self.copied_blocks.append((src, dst))


def expected(prompt, mnt):
    out = list(prompt)
    t = prompt[-1]
    for _ in range(mnt):
        t = (t + 1) % V
        out.append(t)
    return out


def cyclic(start, n):
    return [(start + i) % V for i in range(n)]


def test_spec_rounds_accept_ngram_drafts_token_identical():
    """A full-cycle prompt makes every successor continuation visible
    to the n-gram drafter, so verify rounds accept whole windows —
    far fewer dispatches than tokens — while the output stays the
    plain closed form."""
    reg = MetricsRegistry()
    model = FakeSpecModel(batch_slots=2, max_seq=64, spec_k=4)
    sched = ContinuousScheduler(model, registry=reg)
    try:
        reqs = [(cyclic(3, V + 2), 30), (cyclic(9, V + 2), 26)]
        hs = [sched.generate_async(p, m) for p, m in reqs]
        for h, (p, m) in zip(hs, reqs):
            assert h.wait(30.0) == expected(p, m)
        st = sched.stats()["speculative"]
        assert st["mode"] == "ngram"
        assert st["rounds"] > 0
        assert st["accepted"] == st["proposed"] > 0  # perfect drafter
        assert st["accepted_per_round"] > 1.5
        assert not st["degraded"]
        # the point of the feature: generated tokens out-number the
        # decode dispatches that produced them
        decode_dispatches = model.verify_calls + model.steps
        assert sched.tokens_generated > decode_dispatches
        # per-request accounting reached the handles
        assert all(h.spec_accepted == h.spec_proposed > 0 for h in hs)
        assert reg.counter("serving/spec_accepted").value == \
            st["accepted"]
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_spec_rejection_rolls_back_and_stays_token_identical():
    """An always-wrong drafter: every draft is rejected at its first
    position, the pool rolls the rejected window back out every round,
    and the output is still EXACTLY the plain closed form — the
    never-worse contract under a hostile proposer."""
    draft = FakeDraftModel(batch_slots=2, off=7)  # always wrong
    model = FakeSpecModel(batch_slots=2, spec_decode="draft",
                          spec_k=3, draft_model=draft)
    sched = ContinuousScheduler(model)
    try:
        reqs = [([3, 4, 5], 9), ([11], 7)]
        hs = [sched.generate_async(p, m) for p, m in reqs]
        for h, (p, m) in zip(hs, reqs):
            assert h.wait(30.0) == expected(p, m)
        st = sched.stats()["speculative"]
        assert st["proposed"] > 0 and st["accepted"] == 0
        assert st["k_current"] == 1  # adaptive k hit the floor
        sched.pool.check_invariants()
        assert sched.pool.used_blocks == 0
    finally:
        sched.close()


def test_spec_draft_mode_accepts_and_reconciles():
    draft = FakeDraftModel(batch_slots=2, off=0)  # perfect drafter
    model = FakeSpecModel(batch_slots=2, spec_decode="draft",
                          spec_k=4, draft_model=draft)
    sched = ContinuousScheduler(model)
    try:
        reqs = [([3, 4], 12), ([8], 10)]
        hs = [sched.generate_async(p, m) for p, m in reqs]
        for h, (p, m) in zip(hs, reqs):
            assert h.wait(30.0) == expected(p, m)
        st = sched.stats()["speculative"]
        assert st["accepted"] == st["proposed"] > 0
        assert st["proposer"]["draft_steps"] > 0
        assert st["proposer"]["live_draft_seqs"] == 0  # all released
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_spec_falls_back_to_plain_decode_without_proposals():
    """Sampled requests are never spec-eligible; rounds with no
    proposals anywhere must take the plain [slots, 1] step."""
    model = FakeSpecModel(batch_slots=2, spec_decode="ngram")
    sched = ContinuousScheduler(model, seed=5)
    try:
        h = sched.generate_async([3, 4], 6, temperature=1.0)
        toks = h.wait(30.0)
        assert len(toks) == 8
        assert model.verify_calls == 0  # nothing eligible, no verify
        assert model.steps > 0
        assert sched.spec_fallback_rounds > 0
        assert sched.stats()["speculative"]["rounds"] == 0
    finally:
        sched.close()


def test_spec_mixes_chunked_prefill_and_verify_rounds():
    """A long-prompt request rides chunked prefill while a decoding
    slot speculates; both finish token-identical to the closed form."""
    model = FakeSpecModel(batch_slots=2, max_seq=64, prefill_chunk=4,
                          spec_k=4)
    sched = ContinuousScheduler(model)
    try:
        short = sched.generate_async(cyclic(2, V + 2), 12)
        long = sched.generate_async(cyclic(5, 33), 6)
        assert short.wait(30.0) == expected(cyclic(2, V + 2), 12)
        assert long.wait(30.0) == expected(cyclic(5, 33), 6)
        assert model.prefill_calls > 0          # chunk program ran
        assert sched.stats()["speculative"]["rounds"] > 0
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_transient_verify_fault_degrades_to_plain_decode():
    """ISSUE 18 fault bar: a transient verify-step fault must DEGRADE
    the engine to plain decode — in-flight requests finish
    token-identically, nothing is failed, speculation stays off for
    this engine instance."""
    reg = MetricsRegistry()
    model = FakeSpecModel(batch_slots=2, spec_k=4)
    model.fail_verify_at = {1}
    sched = ContinuousScheduler(model, registry=reg)
    try:
        reqs = [(cyclic(3, V + 2), 10), (cyclic(7, V + 2), 8)]
        hs = [sched.generate_async(p, m) for p, m in reqs]
        for h, (p, m) in zip(hs, reqs):
            assert h.wait(30.0) == expected(p, m)  # nobody failed
        st = sched.stats()["speculative"]
        assert st["degraded"] and st["verify_faults"] == 1
        assert st["rounds"] == 0  # the faulted round never counted
        assert reg.counter("serving/spec_verify_faults").value == 1
        assert model.verify_calls == 1  # speculation never retried
        assert sched.requests_done == len(reqs)
        # degradation is engine-scoped, not request-scoped: later
        # requests run plain and correct
        assert sched.generate(cyclic(1, V + 2), 5, timeout=30.0) == \
            expected(cyclic(1, V + 2), 5)
        assert model.verify_calls == 1
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_step_fault_resets_proposer_state():
    """_fail_inflight (transient plain-step fault) zeroes the KV pool,
    so the draft proposer's mirrored state must reset with it —
    otherwise its next reconcile would roll back against ghosts."""
    draft = FakeDraftModel(batch_slots=2, off=0)
    model = FakeSpecModel(batch_slots=2, spec_decode="draft",
                          spec_k=2, draft_model=draft)
    # sampled request so rounds take the plain path (verify untouched)
    model.fail_at_steps = {2}
    sched = ContinuousScheduler(model, seed=3)
    try:
        h1 = sched.generate_async([3, 4], 6, temperature=1.0)
        with pytest.raises(RuntimeError, match="injected step fault"):
            h1.wait(30.0)
        assert model.resets == 1
        assert draft.resets == 1  # proposer.reset() rode the recovery
        # the engine keeps serving — greedy + speculative still works
        assert sched.generate(cyclic(4, V + 2), 8, timeout=30.0) == \
            expected(cyclic(4, V + 2), 8)
        assert not sched.stats()["speculative"]["degraded"]
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_spec_eos_inside_accepted_window_truncates():
    """EOS landing mid-window ends the request at EOS: tokens past it
    in the same verify round are rolled back, never emitted."""
    model = FakeSpecModel(batch_slots=2, spec_k=4)
    sched = ContinuousScheduler(model, eos_id=9)
    try:
        # successor chain from the full-cycle prompt runs ...7, 8, 9:
        # EOS (9) falls inside an accepted draft window
        prompt = cyclic(3, V + 2)  # ends at 4 -> generates 5, 6, ...
        toks = sched.generate(prompt, 12, timeout=30.0)
        assert toks == prompt + [5, 6, 7, 8, 9]
        assert sched.stats()["speculative"]["rounds"] > 0
        sched.pool.check_invariants()
        assert sched.pool.used_blocks == 0
    finally:
        sched.close()


def test_spec_off_never_builds_verify_surface():
    model = FakeSpecModel(batch_slots=2, spec_decode="off")
    sched = ContinuousScheduler(model)
    try:
        assert sched.generate([3, 4], 6, timeout=30.0) == \
            expected([3, 4], 6)
        assert model.verify_calls == 0
        assert sched.stats()["speculative"]["mode"] == "off"
    finally:
        sched.close()


# -- supervised replica: verify faults under the fault plan --------------

def test_hung_verify_is_fatal_and_replica_recovers_identically():
    """A HUNG verify dispatch (watchdog timeout) is fatal-to-engine:
    the replica drains-and-dies, the supervisor restarts it with
    speculation re-enabled, and requeued requests complete
    token-identically."""
    from flexflow_tpu.serving import ServingFront

    built = []

    def spec_factory(replica_id, survivors=None):
        m = FakeSpecModel(batch_slots=2, spec_k=4)
        if not built:
            m.verify_delay_s = 5.0

            real = m.verify_step

            def slow_verify(tokens, seq_lens, counts, block_tables):
                time.sleep(m.verify_delay_s)
                return real(tokens, seq_lens, counts, block_tables)

            m.verify_step = slow_verify
        built.append(m)
        return m

    front = ServingFront(spec_factory, num_replicas=1,
                         step_timeout=0.3, sleep=lambda s: None,
                         retry_backoff=0.0)
    try:
        p = cyclic(3, V + 2)  # spec-eligible immediately
        h = front.generate_async(p, 8)
        assert h.wait(30.0) == expected(p, 8)
        assert front.replicas[0].deaths == 1
        assert front.replicas[0].restarts == 1
        from flexflow_tpu.resilience.watchdog import HungStepTimeout

        assert isinstance(front.replicas[0].last_error, HungStepTimeout)
        assert len(built) == 2
        # the hang fired on the FIRST build's verify dispatch, and the
        # restarted engine re-enabled speculation and used it
        assert built[1].verify_calls > 0
    finally:
        front.close()


def test_injected_transient_fault_on_verify_step_degrades_not_dies():
    """A seeded STEP_EXCEPTION landing on a verify dispatch through the
    SupervisedDecodeModel wrapper takes the degrade path: no replica
    death, token-identical completions, speculation off."""
    from flexflow_tpu.resilience.faults import (Fault, FaultKind,
                                                FaultPlan)
    from flexflow_tpu.serving import ServingFront

    built = []

    def spec_factory(replica_id, survivors=None):
        m = FakeSpecModel(batch_slots=2, spec_k=4)
        built.append(m)
        return m

    # the prompt spends its first len(p) - 1 dispatches advancing
    # through prefill; the dispatch right after is the first
    # spec-eligible round, i.e. the first verify — seed the fault there
    p = cyclic(3, V + 2)
    front = ServingFront(spec_factory, num_replicas=1,
                         sleep=lambda s: None, retry_backoff=0.0,
                         fault_plans={0: FaultPlan(
                             [Fault(step=len(p) - 1,
                                    kind=FaultKind.STEP_EXCEPTION)])})
    try:
        h = front.generate_async(p, 8)
        assert h.wait(30.0) == expected(p, 8)
        assert front.replicas[0].deaths == 0  # degraded, not dead
        assert front.requeued_requests == 0
        assert len(built) == 1
        assert built[0].verify_calls == 0  # fault fired pre-dispatch
        assert built[0].steps > 0          # plain decode finished it
    finally:
        front.close()


# -- real engine: byte identity + accepted-per-round ---------------------

def _train_cyclic_gpt(dev, hidden, layers, heads, inter,
                      vocab=32, max_seq=64, slots=4, steps=120):
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt

    cfg = FFConfig(batch_size=slots, num_devices=1)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    rng = np.random.RandomState(7)
    for _ in range(steps):
        starts = rng.randint(0, vocab, (slots, 1))
        ids = ((starts + np.arange(max_seq)) % vocab).astype(np.int32)
        ff.train_step({"input": ids, "positions": pos},
                      ((ids + 1) % vocab).astype(np.int32))
    return ff


@pytest.mark.slow
@pytest.mark.parametrize("kernel", ["gather", "pallas"])
def test_real_engine_byte_identity_across_spec_modes(kernel):
    """ISSUE 18 acceptance: greedy completions with ngram AND draft
    speculation are byte-identical to the non-speculative engine for
    both paged formulations, invariant checker on at every step, and
    the speculative runs accept > 1.5 tokens per verify round on the
    cyclic workload."""
    import jax

    dev = jax.devices()[0]
    ff = _train_cyclic_gpt(dev, 64, 2, 4, 128)
    draft_ff = _train_cyclic_gpt(dev, 32, 1, 2, 64)
    prompts = [[3, 4, 5, 6], [10, 11], [30, 31, 0, 1, 2], [7, 8, 9]]
    mnts = [40, 30, 24, 36]

    def run(spec, d=None):
        sched = ContinuousScheduler.from_trained(
            ff, batch_slots=4, page_size=8, devices=[dev],
            prefill_chunk=4, spec_decode=spec, spec_k=4, draft_ff=d,
            paged_kernel=kernel, check_invariants=True)
        try:
            hs = [sched.generate_async(p, m)
                  for p, m in zip(prompts, mnts)]
            outs = [h.wait(120.0) for h in hs]
            return outs, sched.stats()["speculative"]
        finally:
            sched.close()

    off, _ = run("off")
    ng, st_ng = run("ngram")
    dr, st_dr = run("draft", draft_ff)
    assert ng == off, "ngram speculation changed greedy output"
    assert dr == off, "draft speculation changed greedy output"
    for st in (st_ng, st_dr):
        assert st["rounds"] > 0 and not st["degraded"]
        assert st["accepted_per_round"] > 1.5
        assert st["verify_faults"] == 0


@pytest.mark.slow
def test_real_engine_transient_verify_fault_token_identical():
    """A transient fault injected on the REAL verify dispatch: the
    engine degrades to plain decode mid-request and the completions
    still match the fault-free run byte-for-byte."""
    import jax

    dev = jax.devices()[0]
    ff = _train_cyclic_gpt(dev, 64, 2, 4, 128)
    # full-cycle prompts (vocab 32): the n-gram drafter matches from
    # the very first decode round, so the clean run speculates
    prompts = [[(3 + i) % 32 for i in range(34)],
               [(10 + i) % 32 for i in range(34)]]
    mnts = [24, 20]

    def run(fail_verify):
        sched = ContinuousScheduler.from_trained(
            ff, batch_slots=4, page_size=8, devices=[dev],
            spec_decode="ngram", spec_k=4, check_invariants=True)
        if fail_verify:
            calls = {"n": 0}
            real = sched.model.verify_step

            def flaky(tokens, seq_lens, counts, block_tables):
                calls["n"] += 1
                if calls["n"] == 2:
                    raise RuntimeError("injected verify fault")
                return real(tokens, seq_lens, counts, block_tables)

            sched.model.verify_step = flaky
        try:
            hs = [sched.generate_async(p, m)
                  for p, m in zip(prompts, mnts)]
            outs = [h.wait(120.0) for h in hs]
            return outs, sched.stats()["speculative"]
        finally:
            sched.close()

    clean, st_clean = run(False)
    faulted, st_faulted = run(True)
    assert faulted == clean
    assert st_clean["rounds"] > 1 and not st_clean["degraded"]
    assert st_faulted["degraded"]
    assert st_faulted["verify_faults"] == 1
