"""torch.fx frontend tests: trace -> lower -> numerical parity with the
torch original (reference tests/align's FF-vs-PyTorch comparison tier,
but hermetic and exact via copy_weights)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, LossType  # noqa: E402
from flexflow_tpu.torch_frontend import PyTorchModel  # noqa: E402


def compile_from_torch(module, input_shape, batch=8, devices=None, dtype="float32"):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor([batch] + list(input_shape), name="x", dtype=dtype)
    pt = PyTorchModel(module)
    outs = pt.torch_to_ff(ff, [x])
    ff.compile(loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices)
    pt.copy_weights(ff)
    return ff, pt, outs


def test_mlp_forward_parity():
    torch.manual_seed(0)
    m = nn.Sequential(
        nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 64), nn.GELU(),
        nn.Linear(64, 10),
    )
    ff, pt, outs = compile_from_torch(m, [32])
    x = np.random.RandomState(0).randn(8, 32).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_cnn_forward_parity():
    torch.manual_seed(0)

    class CNN(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 8, 3, padding=1)
            self.relu = nn.ReLU()
            self.pool = nn.MaxPool2d(2, 2)
            self.conv2 = nn.Conv2d(8, 16, 3)
            self.flatten = nn.Flatten()
            self.fc = nn.Linear(16 * 6 * 6, 10)

        def forward(self, x):
            x = self.pool(self.relu(self.conv1(x)))
            x = self.relu(self.conv2(x))
            x = self.flatten(x)
            return self.fc(x)

    m = CNN()
    ff, pt, outs = compile_from_torch(m, [3, 16, 16])
    x = np.random.RandomState(1).randn(8, 3, 16, 16).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_functional_ops_residual():
    torch.manual_seed(0)

    class Res(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 16)
            self.fc2 = nn.Linear(16, 16)
            self.head = nn.Linear(32, 4)

        def forward(self, x):
            h = torch.relu(self.fc1(x))
            h = h + x  # residual via operator.add
            h2 = torch.tanh(self.fc2(h)) * 0.5  # scalar mul
            cat = torch.cat([h, h2], dim=1)
            return self.head(cat)

    m = Res()
    ff, pt, outs = compile_from_torch(m, [16])
    x = np.random.RandomState(2).randn(8, 16).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_view_permute_methods():
    torch.manual_seed(0)

    class VP(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(12, 12)

        def forward(self, x):  # x: [b, 3, 4]
            b = x.size(0)
            h = x.reshape(b, 12)
            h = self.fc(h)
            h = h.view(b, 4, 3)
            h = h.permute(0, 2, 1)
            return h.flatten()

    m = VP()
    # full .flatten() merges the batch dim — illegal when batch is
    # DP-sharded, so compile single-device
    import jax

    ff, pt, outs = compile_from_torch(m, [3, 4], devices=jax.devices("cpu")[:1])
    x = np.random.RandomState(3).randn(8, 3, 4).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_imported_model_trains(devices8):
    torch.manual_seed(0)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    ff, pt, outs = compile_from_torch(m, [16], batch=16, devices=devices8)
    x = np.random.RandomState(0).randn(64, 16).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32) * 3
    hist = ff.fit(x, y, batch_size=16, epochs=5, verbose=False)
    # accuracy improves across epochs (default metrics = accuracy only)
    assert hist[-1].accuracy > hist[0].accuracy


def test_resnet50_example_imports_and_trains(devices8):
    """BASELINE north-star config 1's model: the examples/ ResNet-50
    (inline torchvision-equivalent) fx-imports and runs a train step
    with numerical-parity weights."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples", "python", "pytorch"))
    from resnet50_search import ResNet50

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.torch_frontend.model import PyTorchModel

    cfg = FFConfig(batch_size=8, num_devices=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 3, 64, 64], name="input")
    pt = PyTorchModel(ResNet50(classes=10))
    (out,) = pt.torch_to_ff(ff, [x])
    ff.softmax(out)
    assert len(ff.layers.topo_order()) > 100  # full 16-block tower
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8)
    rng = np.random.RandomState(0)
    m = ff.train_step(
        {"input": rng.randn(8, 3, 64, 64).astype(np.float32)},
        rng.randint(0, 10, 8).astype(np.int32),
    )
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# Round-2 breadth: the reference's remaining node kinds
# (python/flexflow/torch/model.py:248-2441) — pow/sqrt/rsqrt/erf, expand,
# unsqueeze/squeeze, getitem slicing, chunk, functional linear/conv,
# floordiv/neg/maximum, .float()/type_as, sum — each verified by exact
# alignment against the torch original.
# ---------------------------------------------------------------------------

def test_elementwise_math_node_parity():
    torch.manual_seed(2)

    class M(nn.Module):
        def forward(self, x):
            a = torch.sqrt(torch.relu(x) + 1.0)
            b = torch.rsqrt(x * x + 1.0)
            c = torch.erf(x)
            d = torch.pow(x, 2.0) - a
            e = -b
            return torch.maximum(d, e) + c + x.float()

    m = M()
    ff, pt, outs = compile_from_torch(m, [24])
    x = np.random.RandomState(3).randn(8, 24).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_shape_node_parity():
    torch.manual_seed(3)

    class M(nn.Module):
        def forward(self, x):            # x: [b, 6, 10]
            a = x[:, 1:5, :]             # getitem slicing
            b = a.unsqueeze(1)           # [b, 1, 4, 10]
            c = b.expand(-1, 3, -1, -1)  # broadcast
            d = c.sum(1)                 # [b, 4, 10]
            e = d.unsqueeze(2).squeeze(2)
            p1, p2 = torch.chunk(e, 2, dim=1)
            return (p1 * p2).flatten(1)

    m = M()
    ff, pt, outs = compile_from_torch(m, [6, 10])
    x = np.random.RandomState(4).randn(8, 6, 10).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_functional_linear_conv_parity():
    torch.manual_seed(4)
    import torch.nn.functional as F

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.w1 = nn.Parameter(torch.randn(20, 12) * 0.1)
            self.b1 = nn.Parameter(torch.zeros(20))
            self.wc = nn.Parameter(torch.randn(8, 4, 3, 3) * 0.1)

        def forward(self, x, img):
            h = F.relu(F.linear(x, self.w1, self.b1))
            c = F.conv2d(img, self.wc, stride=1, padding=1)
            return h.sum(1) + c.mean([1, 2, 3])

    m = M()
    ff = FFModel(FFConfig(batch_size=8))
    x_t = ff.create_tensor([8, 12], name="x")
    img_t = ff.create_tensor([8, 4, 6, 6], name="img")
    pt = PyTorchModel(m)
    pt.torch_to_ff(ff, [x_t, img_t])
    ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
    # functional weights are pinned via ArrayInitializer at trace time
    rs = np.random.RandomState(5)
    x = rs.randn(8, 12).astype(np.float32)
    img = rs.randn(8, 4, 6, 6).astype(np.float32)
    got = np.asarray(ff.forward({"x": x, "img": img}))
    want = m(torch.from_numpy(x), torch.from_numpy(img)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ir_file_roundtrip_torch_free_replay(tmp_path):
    """torch_to_file -> file_to_ff replay matches the live lowering
    (reference PyTorchModel file format, model.py:2442+)."""
    torch.manual_seed(5)

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 32)
            self.fc2 = nn.Linear(32, 4)
            self.w = nn.Parameter(torch.randn(4) * 0.1)

        def forward(self, x):
            h = torch.relu(self.fc1(x))
            h = self.fc2(h)
            return h * self.w + h[:, 0:2].sum(1, keepdim=True)

    m = M()
    path = str(tmp_path / "model.ir")
    pt = PyTorchModel(m)
    pt.torch_to_file(path)

    from flexflow_tpu.torch_frontend.model import file_to_ff

    # live path
    ff_a = FFModel(FFConfig(batch_size=8))
    xa = ff_a.create_tensor([8, 16], name="x")
    pt.torch_to_ff(ff_a, [xa])
    ff_a.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
    pt.copy_weights(ff_a)

    # replayed path
    ff_b = FFModel(FFConfig(batch_size=8))
    xb = ff_b.create_tensor([8, 16], name="x")
    file_to_ff(path, ff_b, [xb])
    ff_b.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
    ff_b.set_weights(ff_a.get_weights())

    x = np.random.RandomState(6).randn(8, 16).astype(np.float32)
    got_a = np.asarray(ff_a.forward({"x": x}))
    got_b = np.asarray(ff_b.forward({"x": x}))
    np.testing.assert_allclose(got_a, got_b, rtol=1e-6, atol=1e-6)
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got_a, want, rtol=2e-5, atol=2e-5)


def test_mha_tuple_unpack_and_scalar_div_parity():
    torch.manual_seed(6)

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.attn = nn.MultiheadAttention(16, 2, batch_first=True)

        def forward(self, x):
            out, _ = self.attn(x, x, x)   # tuple unpack -> getitem(0)
            return 2.0 / (out * out + 1.0)  # scalar-first division

    m = M()
    import jax

    dev1 = jax.devices("cpu")[:1]
    ff = FFModel(FFConfig(batch_size=4))
    x_t = ff.create_tensor([4, 6, 16], name="x")
    pt = PyTorchModel(m)
    (out,) = pt.torch_to_ff(ff, [x_t])
    assert out.shape.logical_shape == (4, 6, 16)  # batch dim intact
    ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               devices=dev1)
    x = np.random.RandomState(7).randn(4, 6, 16).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    assert got.shape == (4, 6, 16)
    # scalar-first div must not silently compute x/2
    class D(nn.Module):
        def forward(self, x):
            return 2.0 / x
    ffd = FFModel(FFConfig(batch_size=4))
    xd = ffd.create_tensor([4, 8], name="x")
    PyTorchModel(D()).torch_to_ff(ffd, [xd])
    ffd.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                devices=dev1)
    xv = np.full((4, 8), 4.0, np.float32)
    np.testing.assert_allclose(
        np.asarray(ffd.forward({"x": xv})), np.full((4, 8), 0.5), rtol=1e-6
    )


def test_frozen_buffer_not_trained():
    """register_buffer constants import as FROZEN weights: no gradient
    updates, no weight decay."""
    torch.manual_seed(7)

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)
            self.register_buffer("scale", torch.full((8,), 3.0))

        def forward(self, x):
            return self.fc(x) * self.scale

    from flexflow_tpu import SGDOptimizer

    m = M()
    ff = FFModel(FFConfig(batch_size=4, weight_decay=0.1))
    x_t = ff.create_tensor([4, 8], name="x")
    pt = PyTorchModel(m)
    pt.torch_to_ff(ff, [x_t])
    import jax

    ff.compile(optimizer=SGDOptimizer(lr=0.5, weight_decay=0.1),
               loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               devices=jax.devices("cpu")[:1])
    # the buffer landed in state, not trainable weights
    w_names = set(ff._weights)
    buf_ops = [k for k in ff._state if k.startswith("mul")]
    assert buf_ops, f"buffer op missing from state: {list(ff._state)}"
    assert all(not k.startswith("mul") for k in w_names)
    x = np.random.RandomState(8).randn(4, 8).astype(np.float32)
    y = np.random.RandomState(9).randn(4, 8).astype(np.float32)
    for _ in range(5):
        ff.train_step({"x": x}, y)
    buf = ff._state[buf_ops[0]]["value"]
    np.testing.assert_allclose(np.asarray(buf), np.full(8, 3.0), rtol=1e-6)


def test_fx_transformer_block_weight_transfer(devices8):
    """fx-import a torch transformer block containing
    nn.MultiheadAttention and transfer ALL weights (incl. the packed
    in_proj/out_proj -> per-head mapping): forward parity with torch
    (the reference's tests/align mt5-encoder role through the
    frontend)."""
    import torch
    import torch.nn as nn

    class Block(nn.Module):
        def __init__(self, E=32, H=4):
            super().__init__()
            self.ln1 = nn.LayerNorm(E)
            self.attn = nn.MultiheadAttention(E, H, batch_first=True)
            self.ln2 = nn.LayerNorm(E)
            self.fc1 = nn.Linear(E, 2 * E)
            self.fc2 = nn.Linear(2 * E, E)

        def forward(self, x):
            h = self.ln1(x)
            a, _ = self.attn(h, h, h)
            x = x + a
            return x + self.fc2(torch.relu(self.fc1(self.ln2(x))))

    torch.manual_seed(11)
    tm = Block()
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.torch_frontend.model import PyTorchModel

    ff = FFModel(FFConfig(batch_size=2))
    x = ff.create_tensor([2, 6, 32], name="input")
    pt = PyTorchModel(tm)
    (out,) = pt.torch_to_ff(ff, [x])
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])
    pt.copy_weights(ff)

    xs = np.random.RandomState(11).randn(2, 6, 32).astype(np.float32)
    got = np.asarray(ff.forward({"input": xs}))
    want = tm(torch.from_numpy(xs)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_fx_batchnorm_running_stats_transfer(devices8):
    """copy_weights transfers BatchNorm running stats into the op-state
    pytree: eval-mode forward parity with a torch model whose stats
    were trained (previously the stats stayed at init mean=0/var=1)."""
    import torch
    import torch.nn as nn

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.torch_frontend.model import PyTorchModel

    torch.manual_seed(3)
    tm = nn.Sequential(nn.Conv2d(3, 8, 3, padding=1),
                       nn.BatchNorm2d(8), nn.ReLU())
    tm.train()
    for _ in range(5):
        tm(torch.randn(4, 3, 8, 8))
    tm.eval()

    ff = FFModel(FFConfig(batch_size=2))
    x = ff.create_tensor([2, 3, 8, 8], name="input")
    pt = PyTorchModel(tm)
    pt.torch_to_ff(ff, [x])
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])
    pt.copy_weights(ff)
    xs = np.random.RandomState(3).randn(2, 3, 8, 8).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"input": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=1e-4, atol=1e-4)


def test_fx_mha_bias_kv_weight_transfer(devices8):
    """add_bias_kv MultiheadAttention transfers its appended bias token
    weights too (review r04: previously left at random init, silently
    diverging from torch)."""
    import torch
    import torch.nn as nn

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.torch_frontend.model import PyTorchModel

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.attn = nn.MultiheadAttention(16, 4, bias=True,
                                              add_bias_kv=True,
                                              batch_first=True)

        def forward(self, x):
            return self.attn(x, x, x)[0]

    torch.manual_seed(5)
    tm = M()
    ff = FFModel(FFConfig(batch_size=2))
    x = ff.create_tensor([2, 6, 16], name="input")
    pt = PyTorchModel(tm)
    pt.torch_to_ff(ff, [x])
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])
    pt.copy_weights(ff)
    xs = np.random.RandomState(5).randn(2, 6, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ff.forward({"input": xs})),
        tm(torch.from_numpy(xs)).detach().numpy(), rtol=1e-4, atol=1e-4)
