"""torch.fx frontend tests: trace -> lower -> numerical parity with the
torch original (reference tests/align's FF-vs-PyTorch comparison tier,
but hermetic and exact via copy_weights)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, LossType  # noqa: E402
from flexflow_tpu.torch_frontend import PyTorchModel  # noqa: E402


def compile_from_torch(module, input_shape, batch=8, devices=None, dtype="float32"):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor([batch] + list(input_shape), name="x", dtype=dtype)
    pt = PyTorchModel(module)
    outs = pt.torch_to_ff(ff, [x])
    ff.compile(loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices)
    pt.copy_weights(ff)
    return ff, pt, outs


def test_mlp_forward_parity():
    torch.manual_seed(0)
    m = nn.Sequential(
        nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 64), nn.GELU(),
        nn.Linear(64, 10),
    )
    ff, pt, outs = compile_from_torch(m, [32])
    x = np.random.RandomState(0).randn(8, 32).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_cnn_forward_parity():
    torch.manual_seed(0)

    class CNN(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 8, 3, padding=1)
            self.relu = nn.ReLU()
            self.pool = nn.MaxPool2d(2, 2)
            self.conv2 = nn.Conv2d(8, 16, 3)
            self.flatten = nn.Flatten()
            self.fc = nn.Linear(16 * 6 * 6, 10)

        def forward(self, x):
            x = self.pool(self.relu(self.conv1(x)))
            x = self.relu(self.conv2(x))
            x = self.flatten(x)
            return self.fc(x)

    m = CNN()
    ff, pt, outs = compile_from_torch(m, [3, 16, 16])
    x = np.random.RandomState(1).randn(8, 3, 16, 16).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_functional_ops_residual():
    torch.manual_seed(0)

    class Res(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 16)
            self.fc2 = nn.Linear(16, 16)
            self.head = nn.Linear(32, 4)

        def forward(self, x):
            h = torch.relu(self.fc1(x))
            h = h + x  # residual via operator.add
            h2 = torch.tanh(self.fc2(h)) * 0.5  # scalar mul
            cat = torch.cat([h, h2], dim=1)
            return self.head(cat)

    m = Res()
    ff, pt, outs = compile_from_torch(m, [16])
    x = np.random.RandomState(2).randn(8, 16).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_view_permute_methods():
    torch.manual_seed(0)

    class VP(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(12, 12)

        def forward(self, x):  # x: [b, 3, 4]
            b = x.size(0)
            h = x.reshape(b, 12)
            h = self.fc(h)
            h = h.view(b, 4, 3)
            h = h.permute(0, 2, 1)
            return h.flatten()

    m = VP()
    # full .flatten() merges the batch dim — illegal when batch is
    # DP-sharded, so compile single-device
    import jax

    ff, pt, outs = compile_from_torch(m, [3, 4], devices=jax.devices("cpu")[:1])
    x = np.random.RandomState(3).randn(8, 3, 4).astype(np.float32)
    got = np.asarray(ff.forward({"x": x}))
    want = m(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_imported_model_trains(devices8):
    torch.manual_seed(0)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    ff, pt, outs = compile_from_torch(m, [16], batch=16, devices=devices8)
    x = np.random.RandomState(0).randn(64, 16).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32) * 3
    hist = ff.fit(x, y, batch_size=16, epochs=5, verbose=False)
    # accuracy improves across epochs (default metrics = accuracy only)
    assert hist[-1].accuracy > hist[0].accuracy


def test_resnet50_example_imports_and_trains(devices8):
    """BASELINE north-star config 1's model: the examples/ ResNet-50
    (inline torchvision-equivalent) fx-imports and runs a train step
    with numerical-parity weights."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples", "python", "pytorch"))
    from resnet50_search import ResNet50

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.torch_frontend.model import PyTorchModel

    cfg = FFConfig(batch_size=8, num_devices=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 3, 64, 64], name="input")
    pt = PyTorchModel(ResNet50(classes=10))
    (out,) = pt.torch_to_ff(ff, [x])
    ff.softmax(out)
    assert len(ff.layers.topo_order()) > 100  # full 16-block tower
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8)
    rng = np.random.RandomState(0)
    m = ff.train_step(
        {"input": rng.randn(8, 3, 64, 64).astype(np.float32)},
        rng.randint(0, 10, 8).astype(np.int32),
    )
    assert np.isfinite(float(m["loss"]))
