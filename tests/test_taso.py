"""TASO substitution-catalog ingestion tests.

Reference parity: substitution_loader.{h,cc} (the JSON schema; 640
rules in substitutions/graph_subst_3_v2.json), create_xfer/create_xfers
(substitution.cc:1456-1680), GraphXfer match/apply (substitution.cc:
235-414, :832-1120).  Beyond parity: every ingested rule is NUMERICALLY
verified (TASO verifies generated rules; the reference ingests the
JSON unverified — and its linear/concat rule families can never match,
see pcg/taso.py docstring).
"""
import collections
import os

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import OperatorType
from flexflow_tpu.pcg.rewrite import (
    CancelSplitConcat,
    enumerate_variants,
    generate_rewrite_rules,
    load_rewrite_rules,
)
from flexflow_tpu.pcg.taso import (
    PatternRule,
    UnsupportedRule,
    convert_rules,
    load_taso_rules,
    parse_rule_collection,
    verify_rule,
)

CATALOG = "/root/reference/substitutions/graph_subst_3_v2.json"

pytestmark = [
    pytest.mark.skipif(
        not os.path.exists(CATALOG),
        reason="reference catalog not mounted",
    ),
    pytest.mark.slow,  # search/train-heavy: full tier only
]


# -- loader ----------------------------------------------------------------

def test_parse_full_catalog():
    """The real reference rule file parses completely: 640 rules."""
    rules = parse_rule_collection(CATALOG)
    assert len(rules) == 640
    types = collections.Counter(
        op.type for r in rules for op in r.src_ops + r.dst_ops
    )
    # catalog census (independently computed from the raw JSON)
    assert types["OP_REPLICATE"] == 866
    assert types["OP_LINEAR"] == 562
    assert types["OP_PARTITION"] == 492
    assert all(r.mapped_outputs for r in rules)


def test_conversion_report_accounts_for_every_rule():
    prules, report = load_taso_rules(CATALOG, degrees=(2,))
    skipped = sum(v for k, v in report.items() if k.startswith("skip"))
    assert report["converted"] + skipped == 640
    # the usable pool is large (>60% of the catalog), and every skip
    # reason is one of the documented structural/verification classes
    assert report["converted"] >= 400
    for k in report:
        if k.startswith("skip: "):
            assert any(
                s in k
                for s in ("disconnected", "dst linear", "unbound by src",
                          "verification", "1->1", "unmapped")
            ), k


def test_degree_instantiation():
    rules = parse_rule_collection(CATALOG)
    one, _ = convert_rules(rules[:80], degrees=(2,))
    three, _ = convert_rules(rules[:80], degrees=(2, 4, 8))
    parallel = [p for p in one if p.uses_parallel]
    algebraic = [p for p in one if not p.uses_parallel]
    # parallel rules triple; algebraic rules are degree-independent
    assert len(three) == 3 * len(parallel) + len(algebraic)


def test_load_rewrite_rules_autodetects_taso_schema():
    rules = load_rewrite_rules(CATALOG, degrees=(2,))
    assert len(rules) >= 400
    assert all(isinstance(r, PatternRule) for r in rules)


# -- per-rule verification (the correctness core) --------------------------

def test_every_ingested_rule_verifies():
    """Every rule the engine keeps round-trips: instantiate its src
    pattern -> self-match -> apply -> numerics.  'exact' rules are
    numerical identities; 'family' rules are weight-repacking
    equivalences (a linear's input was restructured)."""
    prules, _ = load_taso_rules(CATALOG, degrees=(2,), verify=True)
    verdicts = collections.Counter(verify_rule(p) for p in prules)
    assert set(verdicts) <= {"exact", "family"}, verdicts
    assert verdicts["exact"] >= 380
    assert verdicts["family"] <= 20


def test_rejected_rules_fail_verification():
    """The verification gate rejects exactly the rules whose catalog
    equivalence holds only in the layout-free parallel-tensor algebra,
    not under the realized StackReplicate/FoldReduce semantics."""
    rules = {r.name: r for r in parse_rule_collection(CATALOG)}
    # taso_rule_427: concat(fold(x), fold(y)) vs fold(concat(x, y)) —
    # true only if the fold groups pairs, while StackReplicate/FoldReduce
    # commit to block order (which taso_rule_489 requires)
    pr = PatternRule(rules["taso_rule_427"], degree=2)
    assert verify_rule(pr).startswith("fail")
    pr = PatternRule(rules["taso_rule_489"], degree=2)
    assert verify_rule(pr) == "exact"


def test_unsupported_rule_reasons():
    rules = parse_rule_collection(CATALOG)
    reasons = collections.Counter()
    for r in rules:
        try:
            PatternRule(r, degree=2)
        except UnsupportedRule as e:
            reasons[e.args[0].split(",")[0]] += 1
    # the three documented structural rejection classes all occur
    assert any("disconnected" in k for k in reasons), reasons
    assert any("unbound by src" in k for k in reasons), reasons
    assert any("dst linear" in k for k in reasons), reasons
    with pytest.raises(UnsupportedRule, match="unbound by src"):
        PatternRule(next(r for r in rules if r.name == "taso_rule_597"),
                    degree=2)


# -- matching semantics ----------------------------------------------------

def _branchy_rank3(feature_axis_concat=True):
    cfg = FFConfig(batch_size=8, num_devices=1)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 4, 16], name="x")
    a = ff.relu(ff.dense(x, 32, name="fa"))
    b = ff.relu(ff.dense(x, 32, name="fb"))
    t = ff.concat([a, b], axis=2 if feature_axis_concat else 1)
    t = ff.dense(t, 8, name="head")
    ff.softmax(t)
    return ff


def test_relu_concat_hoist_matches_and_applies():
    """taso_rule_543: concat(relu, relu) on the innermost axis (catalog
    col-major axis 0) -> relu(concat)."""
    prules, _ = load_taso_rules(CATALOG, degrees=(2,))
    r543 = next(p for p in prules if p.name == "taso_rule_543@2")
    ff = _branchy_rank3()
    matches = r543.find_matches(ff.layers)
    assert len(matches) == 1
    g2 = r543.apply(ff.layers, matches[0])
    assert g2 is not None
    relus = [op for op in g2.ops if op.op_type == OperatorType.ELEMENT_UNARY]
    assert len(relus) == 1
    assert relus[0].inputs[0].owner_op.op_type == OperatorType.CONCAT


def test_axis_convention_respected():
    """The same rule must NOT match a concat on a non-innermost axis
    (catalog dims are column-major)."""
    prules, _ = load_taso_rules(CATALOG, degrees=(2,))
    r543 = next(p for p in prules if p.name == "taso_rule_543@2")
    ff = _branchy_rank3(feature_axis_concat=False)
    assert r543.find_matches(ff.layers) == []
    # ...but its axis-1 sibling (catalog col-major 1 = logical 1 of rank
    # 3) does match
    r453 = next(p for p in prules if p.name == "taso_rule_453@2")
    assert len(r453.find_matches(ff.layers)) == 1


def test_external_binding_consistency():
    """A pattern external used twice must bind one tensor: rules over
    add(x, y); add(x, z) shapes only fire when the shared operand is
    actually shared."""
    prules, _ = load_taso_rules(CATALOG, degrees=(2,))
    r305 = next(p for p in prules if p.name == "taso_rule_305@2")
    # src: add(-1,-2); add(-3, prev) — a chain of two adds
    cfg = FFConfig(batch_size=4, num_devices=1)
    ff = FFModel(cfg)
    x = ff.create_tensor([4, 4, 8], name="x")
    y = ff.create_tensor([4, 4, 8], name="y")
    z = ff.create_tensor([4, 4, 8], name="z")
    ff.add(z, ff.add(x, y))  # pattern is positional: chain is operand 1
    assert len(r305.find_matches(ff.layers)) >= 1
    # flipped operand order does not match (positional, like the
    # reference's can_match input wiring)
    ff2 = FFModel(FFConfig(batch_size=4, num_devices=1))
    x2 = ff2.create_tensor([4, 4, 8], name="x")
    y2 = ff2.create_tensor([4, 4, 8], name="y")
    z2 = ff2.create_tensor([4, 4, 8], name="z")
    ff2.add(ff2.add(x2, y2), z2)
    assert r305.find_matches(ff2.layers) == []


# -- the end-to-end story --------------------------------------------------

def test_merge_chain_reaches_single_matmul():
    """The TASO merge cascade: merge_parallel_linear + taso_rule_543 +
    cancel_split_concat collapse two sibling dense+relu branches into
    ONE dense+relu (the rewrite the 5-rule r03 engine could not reach)."""
    prules, _ = load_taso_rules(CATALOG, degrees=(2,))
    rules = generate_rewrite_rules() + prules
    ff = _branchy_rank3()
    variants = enumerate_variants(ff.layers, rules, max_depth=3,
                                  max_variants=24)
    best = None
    for g, trace in variants:
        kinds = [op.op_type.value for op in g.compute_ops()]
        if (kinds.count("linear") == 2 and kinds.count("concat") == 0
                and kinds.count("split") == 0):
            best = (g, trace)
    assert best is not None, "merged variant not reachable"
    assert ["taso_rule_543@2", 0] in [list(t) for t in best[1]]


def test_merged_variant_numeric_equivalence(devices8):
    """Compiling with the catalog-rule rewrite trace preserves the
    model function (weights transfer by name for the kept ops)."""
    from flexflow_tpu.strategy import data_parallel_strategy

    x = np.random.RandomState(0).randn(8, 4, 16).astype(np.float32)
    ff_a = _branchy_rank3()
    ff_a.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])
    out_a = np.asarray(ff_a.forward({"x": x}))

    cfg = FFConfig(batch_size=8, num_devices=1,
                   substitution_json=CATALOG)
    ff_b = _branchy_rank3()
    ff_b.config = cfg
    s = data_parallel_strategy(1)
    s.rewrites = [["taso_rule_543@2", 0]]
    ff_b.compile(optimizer=SGDOptimizer(lr=0.01), strategy=s,
                 devices=devices8[:1])
    ff_b.set_weights(ff_a.get_weights())
    out_b = np.asarray(ff_b.forward({"x": x}))
    np.testing.assert_allclose(out_a, out_b, rtol=1e-4, atol=1e-4)


def test_unity_search_with_catalog_improves_cost(devices8):
    """Unity search with the catalog enabled finds a strategy whose
    simulated cost is <= the no-catalog search on the branchy model,
    and the winning trace uses a catalog rule (the documented
    'searched-cost improvement from a catalog rule')."""
    from flexflow_tpu.pcg.unity import UnitySearch, generate_all_pcg_xfers
    from flexflow_tpu.sim.machine_model import make_machine_model
    from flexflow_tpu.sim.simulator import make_cost_model

    def search(with_catalog):
        ff = _branchy_rank3()
        cfg = ff.config
        machine = make_machine_model(cfg, 4)
        cost_model = make_cost_model(cfg, machine)
        rules = generate_rewrite_rules()
        if with_catalog:
            prules, _ = load_taso_rules(CATALOG, degrees=(2,))
            rules = rules + prules
        s = UnitySearch(ff.layers, 4, machine, cost_model,
                        xfers=generate_all_pcg_xfers(),
                        rewrite_rules=rules, rewrite_depth=3,
                        rewrite_max_variants=24)
        best = s.optimize()
        return best

    base = search(False)
    cat = search(True)
    assert cat is not None and base is not None
    assert cat.search_cost <= base.search_cost * (1 + 1e-9)
    used = {name for name, _ in (tuple(r) for r in cat.rewrites)}
    # either a catalog rule won, or the merged variant without it was
    # already optimal — require the catalog variant to at least tie; if
    # it strictly improved, a taso rule must appear in the trace
    if cat.search_cost < base.search_cost * (1 - 1e-6):
        assert any(n.startswith("taso_rule_") for n in used)


# -- stack/fold realization -------------------------------------------------

def test_stack_fold_ops_numerics():
    import jax.numpy as jnp

    from flexflow_tpu.ops.sources import InputOp, SourceParams
    from flexflow_tpu.parallel.parallel_op import (FoldReduce,
                                                   FoldReduceParams,
                                                   StackReplicate,
                                                   StackReplicateParams)
    from flexflow_tpu.tensor import ParallelTensorShape

    shape = ParallelTensorShape.make((4, 6), degrees=(1, 1))
    src = InputOp(SourceParams(shape=shape), [], name="x")
    st = StackReplicate(StackReplicateParams(axis=1, degree=3),
                        [src.outputs[0]])
    assert st.outputs[0].shape.logical_shape == (4, 18)
    x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
    y = np.asarray(st.forward([jnp.asarray(x)], [])[0])
    np.testing.assert_allclose(y, np.concatenate([x, x, x], axis=1))

    fd = FoldReduce(FoldReduceParams(axis=1, degree=3), [st.outputs[0]])
    assert fd.outputs[0].shape.logical_shape == (4, 6)
    z = np.asarray(fd.forward([jnp.asarray(y)], [])[0])
    np.testing.assert_allclose(z, 3 * x, rtol=1e-6)


def test_cancel_split_concat_rule():
    from flexflow_tpu.strategy import data_parallel_strategy

    cfg = FFConfig(batch_size=4, num_devices=1)
    ff = FFModel(cfg)
    x = ff.create_tensor([4, 16], name="x")
    parts = ff.split(x, [8, 8], axis=1)
    t = ff.concat(list(parts), axis=1)
    ff.dense(t, 4, name="head")
    rule = CancelSplitConcat()
    matches = rule.find_matches(ff.layers)
    assert len(matches) == 1
    g2 = rule.apply(ff.layers, matches[0])
    assert g2 is not None
    kinds = [op.op_type for op in g2.ops]
    assert OperatorType.SPLIT not in kinds
    assert OperatorType.CONCAT not in kinds


def test_random_graph_rewrites_preserve_forward():
    """Property test on REAL graphs (not the synthesized patterns the
    loader self-verifies on): random rank-3 op soups; every match an
    'exact'-verified algebraic rule finds must apply into a graph that
    computes the SAME function (weights transferred by name).  Guards
    the matcher against false-positive matches."""
    import jax

    from flexflow_tpu.fftype import ActiMode

    prules, _ = load_taso_rules(CATALOG, degrees=(2,))
    algebraic = [p for p in prules if not p.uses_parallel
                 and verify_rule(p) == "exact"]
    assert len(algebraic) >= 40

    checked = 0
    for seed in range(6):
        rs = np.random.RandomState(seed)
        ff = FFModel(FFConfig(batch_size=4, num_devices=1))
        same = [ff.create_tensor([4, 4, 8], name=f"in{k}")
                for k in range(3)]  # growth pool, all [4,4,8]
        for step in range(10):
            k = rs.randint(0, 5)
            if k == 0:
                # catalog shape: chain of ews with a SHARED operand
                # (rules 304-312/326-342 reassociate these)
                x, y, z = (same[i] for i in rs.randint(0, len(same), 3))
                op = ff.add if rs.rand() < 0.5 else ff.multiply
                c = op(x, y)
                t = op(z, c) if rs.rand() < 0.5 else op(c, z)
                same.append(c)
            elif k == 1:
                # catalog shape: concat(relu, relu) on the feature axis
                # (rules 428/453/543 hoist the relu)
                x, y = (same[i] for i in rs.randint(0, len(same), 2))
                t = ff.concat([ff.relu(x, inplace=False),
                               ff.relu(y, inplace=False)], axis=2)
            elif k == 2:
                x, y = (same[i] for i in rs.randint(0, len(same), 2))
                op = ff.add if rs.rand() < 0.5 else ff.multiply
                c1, c2 = op(x, y), op(y, same[rs.randint(0, len(same))])
                t = ff.concat([c1, c2], axis=2)
            elif k == 3:
                t = ff.dense(same[rs.randint(0, len(same))], 8,
                             name=f"d{seed}_{step}")
                same.append(t)
            else:
                t = ff.relu(same[rs.randint(0, len(same))],
                            inplace=False)
                same.append(t)

        g = ff.layers
        feeds = {f"in{k}": np.random.RandomState(100 + k)
                 .randn(4, 4, 8).astype(np.float32) for k in range(3)}

        def run(graph):
            vals = {}
            outs = {}
            consumed = set()
            for op in graph.ops:
                for t in op.inputs:
                    consumed.add(t.guid)
            for op in graph.topo_order():
                if op.op_type == OperatorType.INPUT:
                    vals[op.outputs[0].guid] = feeds[op.name]
                    continue
                ws = []
                for spec in op.weight_specs:
                    shape = tuple(d.size for d in spec.shape.dims
                                  if not d.is_replica_dim)
                    ws.append(np.random.RandomState(
                        abs(hash((op.name, spec.name))) % 2**31)
                        .randn(*shape).astype(np.float32) * 0.2)
                res = op.forward([vals[t.guid] for t in op.inputs], ws)
                for t, v in zip(op.outputs, res):
                    vals[t.guid] = np.asarray(v)
                    if t.guid not in consumed:
                        outs[t.guid] = vals[t.guid]
            return outs

        base = run(g)
        base_vals = [np.asarray(v).sum() for v in base.values()]
        for rule in algebraic:
            for m in rule.find_matches(g):
                g2 = rule.apply(g, m)
                if g2 is None:
                    continue
                checked += 1
                got = run(g2)
                # compare the survivors' dangling outputs by VALUE
                # (guids change across the rewrite)
                got_vals = [np.asarray(v).sum() for v in got.values()]
                # rewritten graph may fuse dangling intermediates; every
                # rewritten output must appear among the originals
                for gv in got_vals:
                    assert any(np.isclose(gv, bv, rtol=1e-3, atol=1e-3)
                               for bv in base_vals), (rule.name, seed)
    assert checked >= 5, f"property test exercised only {checked} applies"
