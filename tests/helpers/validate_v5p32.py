"""Subprocess body: validate every shipped v5p-32 strategy artifact on a
hermetic 16-device CPU mesh (the driver's dryrun pattern — conftest pins
the main test process to 8 devices, so 16 needs its own interpreter).

For each artifact: load -> apply to the structurally identical
reduced-size graph (scripts/search_strategies._v5p32_models 'validate'
builders: SAME op names as the searched full-scale graph) -> compile ->
one train step -> assert finite loss.  Prints one OK line per artifact.
"""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=16"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.join(_HERE, "..", "..")
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "scripts"))

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer  # noqa: E402
from flexflow_tpu.strategy import Strategy  # noqa: E402

import search_strategies as _SS  # noqa: E402


def main():
    devs = jax.devices("cpu")
    assert len(devs) >= 16, f"need 16 virtual devices, have {len(devs)}"
    art_dir = os.path.join(_ROOT, "examples", "strategies", "v5p32")
    only = sys.argv[1:] or None
    for name, job in _SS._v5p32_models().items():
        if only and name not in only:
            continue
        path = os.path.join(art_dir, f"{name}.json")
        assert os.path.exists(path), f"missing artifact {path}"
        s = Strategy.load(path)
        assert s.total_devices == 16, (name, s.mesh_axes)
        cfg = FFConfig(batch_size=32, num_devices=16, **job["cfg"])
        ff = FFModel(cfg)
        job["validate"](ff)
        loss = job["loss"] or LossType.SPARSE_CATEGORICAL_CROSSENTROPY
        ff.compile(optimizer=SGDOptimizer(lr=0.01), loss_type=loss,
                   strategy=s, devices=devs[:16])
        rs = np.random.RandomState(0)
        inputs = {}
        for op in ff.layers.source_ops():
            shp = op.outputs[0].shape.logical_shape
            if op.outputs[0].dtype.np_dtype.kind == "i":
                inputs[op.name] = rs.randint(0, 100, shp).astype(np.int32)
            else:
                inputs[op.name] = rs.randn(*shp).astype(np.float32)
        sink_shape = ff.layers.sink_op().outputs[0].shape.logical_shape
        if loss == LossType.MEAN_SQUARED_ERROR_AVG_REDUCE:
            y = rs.rand(*sink_shape).astype(np.float32)
        else:
            y = rs.randint(0, max(2, sink_shape[-1]),
                           sink_shape[:-1]).astype(np.int32)
        m = ff.train_step(inputs, y)
        val = float(m["loss"])
        assert np.isfinite(val), (name, val)
        print(f"v5p32[{name}]: mesh={s.mesh_axes} loss={val:.4f} OK",
              flush=True)


if __name__ == "__main__":
    main()
