"""Worker for the 2-process jax.distributed CPU test: argv = [rank, port].

Spawned by tests/test_distributed.py::test_two_process_training — the
multi-node path the reference proves via mpi_wrapper scripts
(/root/reference/tests/multinode_helpers/mpi_wrapper1.sh): both ranks
join one runtime, build an 8-device global mesh (4 local each), feed
per-host batches through flexflow_tpu.distributed, and train.
"""
import os
import sys

rank, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

import os as _os
sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
from flexflow_tpu import distributed as ffdist

multi = ffdist.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=2,
    process_id=rank,
)
assert multi, "expected multi-process runtime"
assert jax.process_count() == 2
assert len(jax.devices()) == 8, len(jax.devices())

import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.fftype import ActiMode

B = 32
ff = FFModel(FFConfig(batch_size=B, num_devices=8))
x = ff.create_tensor([B, 16], name="x")
t = ff.dense(x, 64, activation=ActiMode.RELU, name="fc1")
t = ff.dense(t, 4, name="head")
ff.softmax(t)
ff.compile(optimizer=SGDOptimizer(lr=0.1),
           loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
           devices=jax.devices())

# per-host data: this host loads only its slice of the global batch
rng = np.random.RandomState(0)  # same global data on both ranks
gx = rng.randn(B, 16).astype(np.float32)
gy = rng.randint(0, 4, B).astype(np.int32)
shardings = dict(ff.executor.input_shardings())
lab_sh = ff.executor.label_sharding()
sl_x = ffdist.local_batch_slice(B, shardings["x"])
sl_y = ffdist.local_batch_slice(B, lab_sh)
arrs = ffdist.shard_host_batch(
    {"x": gx[sl_x], "y": gy[sl_y]},
    {"x": shardings["x"], "y": lab_sh},
    global_batch_size=B,
)
batch = {"x": arrs["x"]}
y = arrs["y"]

losses = []
for _ in range(5):
    m = ff.train_step({"x": batch["x"]}, y)
    losses.append(float(m["loss"]))
print(f"rank {rank}: losses {losses[0]:.4f} -> {losses[-1]:.4f}", flush=True)
assert losses[-1] < losses[0], "loss must decrease"
print(f"rank {rank}: OK", flush=True)
