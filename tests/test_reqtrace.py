"""Request-scoped distributed tracing (obs/reqtrace.py,
docs/OBSERVABILITY.md "Request tracing"): tracer/context units
(sampling, the name-keyed open-span registry, wire adoption, Chrome
export, overflow), the E2E contract on a disaggregated fake-KV fleet
(a migrated request = ONE connected trace tree whose kv_adopt span
lands on the decode replica's track), speculative verify batch spans,
exemplar-linked SLO histograms + the Prometheus /metrics endpoint,
the zero-allocation disabled path, the cumulative-snapshot drain
contract, and the trace_analyze / telemetry_summary tools."""
import importlib
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from flexflow_tpu.obs.metrics import MetricsRegistry, to_prometheus
from flexflow_tpu.obs.reqtrace import (FRONT_PID, NULL_REQTRACER,
                                       ReqTracer)
from flexflow_tpu.obs.trace import span_allocations
from flexflow_tpu.serving import DisaggServingFront
from flexflow_tpu.serving.scheduler import ContinuousScheduler
from flexflow_tpu.serving.server import serve_http

ta = importlib.import_module("tools.trace_analyze")
summary = importlib.import_module("tools.telemetry_summary")

V = 16
NO_SLEEP = lambda s: None  # noqa: E731


def span_recs(reg):
    return [r for r in reg.drain() if r.get("kind") == "span"]


# -- tracer / context units ----------------------------------------------

def test_sampling_bounds_and_null_tracer():
    assert ReqTracer(sample=0.0).trace() is None
    assert ReqTracer(sample=1.0).trace() is not None
    with pytest.raises(ValueError, match="sample"):
        ReqTracer(sample=1.5)
    assert NULL_REQTRACER.trace() is None
    assert NULL_REQTRACER.begin_remote({"trace_id": "x"}, "kv") is None
    assert NULL_REQTRACER.enabled is False and not NULL_REQTRACER.sample


def test_partial_sampling_is_deterministic_per_seed():
    tr = ReqTracer(sample=0.5, seed=7)
    kept = sum(tr.trace() is not None for _ in range(200))
    assert 60 < kept < 140              # ~binomial(200, .5)
    assert tr.traces_started == kept    # rejected ones never count


def test_span_tree_schema_and_connectivity():
    reg = MetricsRegistry()
    tr = ReqTracer(registry=reg)
    ctx = tr.trace("request", prompt_len=3)
    ctx.begin("queue", depth=0)
    ctx.end("queue")
    ctx.begin("dispatch", replica=0)
    ctx.end("dispatch")
    ctx.finish(ok=True)
    recs = span_recs(reg)
    assert [r["name"] for r in recs] == ["queue", "dispatch", "request"]
    root = recs[-1]
    assert root["trace_id"] == "req-000001"
    assert root["parent_id"] is None and root["pid"] == FRONT_PID
    assert root["args"] == {"prompt_len": 3, "ok": True}
    for child in recs[:2]:
        assert child["parent_id"] == root["span_id"]
        assert child["dur_us"] >= 0
    traces, batch = ta.build_traces(recs)
    assert not batch
    ok, orphans = ta.check_connected(traces["req-000001"])
    assert ok and not orphans


def test_rebegin_truncates_and_finish_force_ends():
    reg = MetricsRegistry()
    tr = ReqTracer(registry=reg)
    ctx = tr.trace()
    ctx.begin("queue")
    ctx.begin("queue", requeued=True)   # stale one ends truncated
    ctx.begin("dispatch")               # never explicitly ended
    ctx.finish(ok=False)
    recs = span_recs(reg)
    by_name = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    assert by_name["queue"][0]["args"]["truncated"] is True
    assert len(by_name["queue"]) == 2
    assert len(by_name["dispatch"]) == 1  # force-ended exactly once
    ok, _ = ta.check_connected(recs)
    assert ok


def test_annotate_open_id_and_end_are_name_safe():
    tr = ReqTracer()
    ctx = tr.trace()
    ctx.annotate("nope", x=1)           # no such open span: no-op
    ctx.end("nope")
    assert ctx.open_id("nope") is None
    span = ctx.begin("dispatch")
    ctx.annotate("dispatch", decision="migrate")
    assert ctx.open_id("dispatch") == span.span_id
    ctx.end("dispatch")
    assert span.args["decision"] == "migrate"


def test_wire_round_trips_and_begin_remote_joins_tree():
    tr = ReqTracer()
    ctx = tr.trace()
    mig = ctx.begin("migration")
    wire = json.loads(json.dumps(ctx.wire(parent=mig.span_id, pid=1)))
    adopted = tr.begin_remote(wire, "kv_adopt", blocks=2)
    adopted.end(ok=True)
    assert adopted.trace_id == ctx.trace_id
    assert adopted.parent_id == mig.span_id
    assert adopted.pid == 1
    assert tr.begin_remote(None, "kv_adopt") is None
    assert tr.begin_remote({"parent": 3}, "kv_adopt") is None


def test_batch_spans_chrome_export_and_write(tmp_path):
    tr = ReqTracer(run_id="r0")
    ctx = tr.trace()
    b = tr.batch_span("decode_step", pid=2, rows=2)
    b.end()
    ctx.finish(ok=True)
    events = tr.chrome_events()
    x = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert {e["name"] for e in x} == {"decode_step", "request"}
    batch_ev = next(e for e in x if e["name"] == "decode_step")
    assert batch_ev["pid"] == 2 and "trace_id" not in batch_ev["args"]
    assert {e["args"]["name"] for e in meta} == \
        {"serving front", "serving replica 2"}
    path = tmp_path / "trace.json"
    assert tr.write(str(path)) == len(events)
    doc = json.loads(path.read_text())
    assert doc["otherData"]["run_id"] == "r0"
    assert len(doc["traceEvents"]) == len(events)


def test_span_overflow_drops_not_grows():
    tr = ReqTracer(max_spans=2)
    ctx = tr.trace()
    for i in range(3):
        ctx.begin(f"s{i}")
        ctx.end(f"s{i}")
    st = tr.stats()
    assert st["spans_recorded"] == 2 and st["spans_dropped"] == 1


# -- E2E: disaggregated fleet --------------------------------------------

class FakeKVModel:
    """tests/test_serving_disagg.py's deterministic next-token model
    with the exportable KV surface: token t emits (t+1) % V."""

    def __init__(self, batch_slots=2, max_seq=32, page_size=4):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = 1 + batch_slots * self.max_blocks_per_seq
        self.vocab = V
        self.kv = np.zeros((self.num_blocks, page_size, 2), np.float32)

    def reset(self):
        pass

    def step(self, tokens, seq_lens, block_tables):
        logits = np.zeros((self.batch_slots, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits

    def export_block(self, block):
        return {"kv": np.array(self.kv[block])}

    def import_block(self, block, arrays):
        self.kv[block] = arrays["kv"]


def expected(prompt, mnt):
    out = list(prompt)
    t = prompt[-1]
    for _ in range(mnt):
        t = (t + 1) % V
        out.append(t)
    return out


def factory(rid, survivors=None):
    return FakeKVModel()


def test_disagg_migrated_request_is_one_connected_tree():
    """THE acceptance criterion: a request the dispatcher diverts
    through the prefill class yields exactly one connected trace tree
    covering queue/dispatch (cost terms)/migration/kv_adopt (on the
    DECODE replica's track, via the FFKV frame header)/prefill/decode
    — plus a re-prefilled request whose tree has no migration child."""
    reg = MetricsRegistry()
    tracer = ReqTracer(registry=reg)
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               registry=reg, reqtrace=tracer,
                               sleep=NO_SLEEP)
    reqs = [([1, 2, 3, 4, 5, 6, 7, 8], 4), ([5], 3)]
    try:
        hs = [front.generate_async(p, m) for p, m in reqs]
        outs = [h.wait(30.0) for h in hs]
    finally:
        front.close()
    for (p, m), got in zip(reqs, outs):
        assert got == expected(p, m)
    assert hs[0].migration["decision"] == "migrate"
    assert hs[1].migration["decision"] == "reprefill"  # sub-page

    recs = span_recs(reg)
    traces, batch = ta.build_traces(recs)
    assert len(traces) == len(reqs)           # sample=1.0: all traced
    for h in hs:
        assert h.trace is not None
        ok, orphans = ta.check_connected(traces[h.trace.trace_id])
        assert ok, f"orphans: {orphans}"

    mig = traces[hs[0].trace.trace_id]
    names = {s["name"] for s in mig}
    assert {"request", "queue", "dispatch", "migration", "kv_adopt",
            "prefill", "decode"} <= names
    # the priced decision rides the dispatch span
    disp = next(s for s in mig if s["name"] == "dispatch"
                and "decision" in s["args"])
    assert disp["args"]["decision"] == "migrate"
    assert disp["args"]["migrate_s"] < disp["args"]["reprefill_s"]
    # the adopt span crossed the fabric onto the decode replica (id 1)
    adopt = next(s for s in mig if s["name"] == "kv_adopt")
    assert adopt["pid"] == 1
    assert adopt["args"]["ok"] is True and adopt["args"]["blocks"] > 0
    mig_span = next(s for s in mig if s["name"] == "migration")
    assert adopt["parent_id"] == mig_span["span_id"]
    assert mig_span["args"]["ok"] is True
    # root completion accounting
    root = next(s for s in mig if s["parent_id"] is None)
    assert root["args"]["ok"] is True
    assert root["args"]["n_generated"] == reqs[0][1]
    # phase spans reference shared batch spans instead of owning them
    dec = next(s for s in mig if s["name"] == "decode")
    refs = dec["args"]["batch_spans"]
    assert refs and all(batch[r]["trace_id"] is None for r in refs)

    # no migration child on the re-prefilled request's tree
    assert "migration" not in {
        s["name"] for s in traces[hs[1].trace.trace_id]}

    # the analyzer agrees end-to-end
    report = ta.analyze(recs)
    assert report["traces"] == 2 and not report["disconnected"]
    assert report["phases"]["decode"]["traces"] == 2
    assert report["phases"]["migration"]["traces"] == 1


def test_untraced_fleet_has_no_spans_and_no_allocations():
    reg = MetricsRegistry()
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               registry=reg, sleep=NO_SLEEP)
    try:
        before = span_allocations()
        h = front.generate_async([1, 2, 3, 4, 5, 6, 7, 8], 4)
        assert h.wait(30.0) == expected([1, 2, 3, 4, 5, 6, 7, 8], 4)
        assert span_allocations() == before   # zero-cost disabled path
        assert h.trace is None
    finally:
        front.close()
    assert not span_recs(reg)


# -- speculative verify rounds -------------------------------------------

class FakeSpecModel(FakeKVModel):
    """FakeKVModel plus the verify surface (same successor rule), so
    the n-gram drafter's chains are always accepted."""

    def __init__(self, spec_k=4, **kw):
        super().__init__(max_seq=64, **kw)
        self.prefix_cache = True
        self.spec_decode = "ngram"
        self.spec_k = spec_k
        self.verify_chunk = spec_k + 1

    def verify_step(self, tokens, seq_lens, counts, block_tables):
        C = tokens.shape[1]
        logits = np.zeros((self.batch_slots, C, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        for j in range(C):
            logits[np.arange(self.batch_slots), j, nxt[:, j]] = 1.0
        return logits


def test_spec_verify_rounds_ride_shared_batch_spans():
    reg = MetricsRegistry()
    tracer = ReqTracer(registry=reg)
    sched = ContinuousScheduler(FakeSpecModel(), registry=reg,
                                reqtrace=tracer, trace_pid=3)
    try:
        ctx = tracer.trace("request")
        prompt = [(3 + i) % V for i in range(V + 2)]
        h = sched.generate_async(prompt, 20, trace=ctx)
        assert h.wait(30.0) == expected(prompt, 20)
        ctx.finish(ok=True)
    finally:
        sched.close()
    recs = span_recs(reg)
    traces, batch = ta.build_traces(recs)
    spans = traces[ctx.trace_id]
    dec = next(s for s in spans if s["name"] == "decode")
    assert dec["pid"] == 3
    assert dec["args"]["spec_rounds"] > 0
    assert dec["args"]["spec_accepted"] == dec["args"]["spec_proposed"] > 0
    verify = [batch[r] for r in dec["args"]["batch_spans"]
              if batch[r]["name"] == "spec_verify"]
    assert verify
    assert all(v["args"]["proposer"] == "NGramProposer" for v in verify)
    # the analyzer buckets referenced verify time into spec_verify
    phases = ta.phase_breakdown(spans, batch)
    assert phases.get("spec_verify", 0.0) > 0.0


# -- exemplars, cumulative drains, /metrics ------------------------------

def test_slo_histograms_carry_worst_sample_exemplar():
    reg = MetricsRegistry()
    tracer = ReqTracer(registry=reg)
    front = DisaggServingFront(factory, num_replicas=2,
                               roles=["prefill", "decode"],
                               registry=reg, reqtrace=tracer,
                               sleep=NO_SLEEP)
    try:
        h = front.generate_async([1, 2, 3, 4, 5], 4)
        assert h.wait(30.0) == expected([1, 2, 3, 4, 5], 4)
    finally:
        front.close()
    recs = reg.drain()
    lat = [r for r in recs if r["kind"] == "histogram"
           and r["name"] == "serving/request_latency_ms"]
    assert lat and lat[-1]["exemplar"]["trace_id"] == h.trace.trace_id
    assert lat[-1]["exemplar"]["value"] > 0
    # exemplar resets at drain; count/sum stay cumulative snapshots
    again = [r for r in reg.drain() if r["kind"] == "histogram"
             and r["name"] == "serving/request_latency_ms"]
    assert again and "exemplar" not in again[-1]
    assert again[-1]["count"] == lat[-1]["count"]
    assert again[-1]["sum"] == lat[-1]["sum"]


def test_cumulative_drain_monotone_and_summary_dedupes():
    """The drain contract the doc promises: metric records are
    cumulative snapshots — a second flush re-appends current values,
    never resets — and telemetry_summary keeps the latest per name."""
    reg = MetricsRegistry()
    reg.counter("serving/requests_done").inc(2)
    reg.histogram("serving/ttft_ms").observe(5.0)
    first = {(r["name"]): r for r in reg.drain()
             if r["kind"] in ("counter", "histogram")}
    reg.counter("serving/requests_done").inc(3)
    reg.histogram("serving/ttft_ms").observe(7.0)
    second = {(r["name"]): r for r in reg.drain()
              if r["kind"] in ("counter", "histogram")}
    assert second["serving/requests_done"]["value"] == 5 > \
        first["serving/requests_done"]["value"]
    h1, h2 = first["serving/ttft_ms"], second["serving/ttft_ms"]
    assert h2["count"] == 2 > h1["count"]
    assert h2["sum"] == pytest.approx(12.0) and h2["sum"] > h1["sum"]
    # summarize sees both generations of records; latest must win
    recs = list(first.values()) + list(second.values())
    text = summary.summarize(recs)
    assert "5" in text  # requests_done reflects the later snapshot


def test_metrics_endpoint_serves_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("serving/requests_done").inc(4)
    reg.gauge("serving/queue_depth").set(1.0)
    reg.histogram("serving/ttft_ms").observe(812.4,
                                             exemplar="req-000042")
    sched = ContinuousScheduler(FakeKVModel(), registry=reg)
    server = serve_http(generator=sched, port=0, block=False,
                        registry=reg)
    port = server.server_address[1]
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            ctype = r.headers["Content-Type"]
            body = r.read().decode()
        assert ctype.startswith("text/plain; version=0.0.4")
        assert "# TYPE serving_requests_done counter" in body
        assert "serving_requests_done 4" in body
        assert "serving_queue_depth 1.0" in body
        assert "# TYPE serving_ttft_ms summary" in body
        assert "serving_ttft_ms_sum" in body
        # OpenMetrics exemplar annotation on the _count sample
        assert ('serving_ttft_ms_count 1 # {trace_id="req-000042"} '
                "812.4") in body
    finally:
        server.shutdown()
        sched.close()
    # every line parses as `name value [exemplar]` or a comment
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.split(" # ")[0].rsplit(" ", 1)
        float(value)
        assert "/" not in name  # sanitized for Prometheus


def test_metrics_endpoint_404_without_registry():
    sched = ContinuousScheduler(FakeKVModel())
    server = serve_http(generator=sched, port=0, block=False)
    port = server.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10)
        assert ei.value.code == 404
    finally:
        server.shutdown()
        sched.close()


def test_to_prometheus_unit():
    reg = MetricsRegistry()
    reg.histogram("serving/per_token_ms").observe(3.0)
    text = to_prometheus(reg)
    assert "# TYPE serving_per_token_ms summary" in text
    assert "serving_per_token_ms_count 1" in text
    assert "#" not in text.split("serving_per_token_ms_count 1")[1] \
        .splitlines()[0]  # no exemplar without one


# -- tools: trace_analyze CLI, telemetry_summary torn tails --------------

def _write_jsonl(path, recs, torn=None, torn_at=None):
    lines = [json.dumps(r) for r in recs]
    if torn is not None:
        lines.insert(len(lines) if torn_at is None else torn_at, torn)
    path.write_text("\n".join(lines) + "\n")


def make_trace_recs():
    reg = MetricsRegistry()
    tr = ReqTracer(registry=reg)
    for mnt in (3, 1):
        ctx = tr.trace("request")
        ctx.begin("queue")
        ctx.end("queue")
        ctx.begin("decode")
        ctx.end("decode")
        ctx.finish(ok=True, n_generated=mnt)
    return span_recs(reg)


def test_trace_analyze_cli_slowest_and_check(tmp_path, capsys):
    recs = make_trace_recs()
    path = tmp_path / "run_telemetry.jsonl"
    _write_jsonl(path, recs, torn='{"kind":')   # tolerated here
    assert ta.main([str(tmp_path), "--slowest", "1"]) == 0
    out = capsys.readouterr().out
    assert "Request traces: 2" in out
    assert "Slowest 1:" in out and "req-00000" in out
    assert ta.main([str(path), "--check"]) == 0

    # orphan a span: --check exits 2, plain run stays 0
    bad = [dict(r) for r in recs]
    for r in bad:
        if r["name"] == "queue" and r["trace_id"] == "req-000001":
            r["parent_id"] = 999999
    _write_jsonl(path, bad)
    assert ta.main([str(path)]) == 0
    assert ta.main([str(path), "--check"]) == 2
    assert "DISCONNECTED" in capsys.readouterr().out
    assert ta.main([str(tmp_path / "nope.jsonl")]) == 1


def test_telemetry_summary_tracing_section(tmp_path, capsys):
    path = tmp_path / "run_telemetry.jsonl"
    _write_jsonl(path, make_trace_recs())
    assert summary.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "Tracing" in out
    assert "traces recorded" in out and "slowest" in out


def test_telemetry_summary_rejects_mid_file_corruption(tmp_path,
                                                       capsys):
    path = tmp_path / "run_telemetry.jsonl"
    _write_jsonl(path, make_trace_recs(), torn="{garbage", torn_at=2)
    assert summary.main([str(path)]) == 1
    err = capsys.readouterr().err
    assert "[3]" in err and "mid-file" in err
    # mid-file corruption is NOT a torn tail: the escape hatch refuses
    assert summary.main([str(path), "--allow-torn-tail"]) == 1


def test_telemetry_summary_torn_tail_escape_hatch(tmp_path, capsys):
    path = tmp_path / "run_telemetry.jsonl"
    _write_jsonl(path, make_trace_recs(), torn='{"kind": "spa')  # tail
    assert summary.main([str(path)]) == 1
    assert "--allow-torn-tail" in capsys.readouterr().err
    assert summary.main([str(path), "--allow-torn-tail"]) == 0
    cap = capsys.readouterr()
    assert "Tracing" in cap.out
    assert "torn tail" in cap.err  # tolerated, but still called out
