"""Native C++ event simulator vs Python fallback: exact agreement,
plus sanity of the event model itself (contention, ring expansion).

The reference has no isolated simulator tests (SURVEY §4); and its
simulator core is C++ — ours is too (flexflow_tpu/native/
taskgraph_sim.cc), with the Python twin as the oracle.
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.native import get_lib
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import OpCostModel, Simulator
from flexflow_tpu.sim.taskgraph import (
    TaskGraphBuilder,
    TaskGraphSimulator,
    simulate_native,
    simulate_python,
)
from flexflow_tpu.strategy import apply_strategy, assign_views, data_parallel_strategy


def have_native():
    return get_lib() is not None


def test_native_lib_builds():
    """g++ is part of the baked toolchain — the native core must build."""
    assert have_native(), "libffnative.so failed to build/load"


def _random_taskgraph(rng, num_tasks=40, num_devices=4):
    b = TaskGraphBuilder(num_devices, TpuPodModel(topology=(num_devices,)))
    tids = []
    for i in range(num_tasks):
        deps = []
        if tids:
            for d in rng.choice(len(tids), size=min(2, len(tids)), replace=False):
                deps.append(tids[int(d)])
        t = b.add_task(float(rng.rand()) * 1e-3, int(rng.randint(num_devices)), deps)
        # random comm edge
        if tids and rng.rand() < 0.5:
            src = tids[int(rng.randint(len(tids)))]
            b.add_edge(src, t, float(rng.rand()) * 1e6,
                       int(rng.randint(num_devices)), int(rng.randint(num_devices)))
        tids.append(t)
    return b.finalize()


@pytest.mark.skipif(not have_native(), reason="native lib unavailable")
def test_native_matches_python_on_random_graphs():
    rng = np.random.RandomState(0)
    for trial in range(10):
        tg = _random_taskgraph(rng, num_tasks=30 + trial * 10)
        mk_n, busy_n = simulate_native(tg)
        mk_p, busy_p = simulate_python(tg)
        assert mk_n == pytest.approx(mk_p, rel=1e-12), f"trial {trial}"
        np.testing.assert_allclose(busy_n, busy_p, rtol=1e-12)


def test_event_sim_serializes_device():
    """Two independent tasks on one device must serialize."""
    b = TaskGraphBuilder(2, TpuPodModel(topology=(2,)))
    b.add_task(1.0, 0)
    b.add_task(1.0, 0)
    b.add_task(1.0, 1)
    mk, busy = simulate_python(b.finalize())
    assert mk == pytest.approx(2.0)
    assert busy[0] == pytest.approx(2.0)
    assert busy[1] == pytest.approx(1.0)


def test_event_sim_link_contention():
    """Two simultaneous transfers over the same link must serialize —
    the effect the analytic model can't see."""
    m = TpuPodModel(topology=(2,))
    nbytes = 1e6
    one = m.ici_lat + nbytes / m.ici_bw

    b = TaskGraphBuilder(2, m)
    p0 = b.add_task(0.0, 0)
    p1 = b.add_task(0.0, 0)
    c0 = b.add_task(0.0, 1)
    c1 = b.add_task(0.0, 1)
    b.add_edge(p0, c0, nbytes, 0, 1)
    b.add_edge(p1, c1, nbytes, 0, 1)
    mk, _ = simulate_python(b.finalize())
    assert mk == pytest.approx(2 * one, rel=1e-6)


def test_ring_allreduce_expansion_phases():
    """Ring allreduce over n devices: 2(n-1) phases of size/n chunks."""
    n = 4
    m = TpuPodModel(topology=(n,))
    b = TaskGraphBuilder(n, m)
    deps = {d: b.add_task(0.0, d) for d in range(n)}
    b.expand_allreduce(list(range(n)), 1e6, deps)
    mk, _ = simulate_python(b.finalize())
    expected = 2 * (n - 1) * (m.ici_lat + (1e6 / n) / m.ici_bw)
    assert mk == pytest.approx(expected, rel=1e-6)


def test_taskgraph_sim_on_pcg_dp_vs_tp():
    """End-to-end: expand a strategy-applied PCG and simulate; DP of a
    big-weight tiny-batch MLP should lose to TP (grad allreduce)."""
    ff = FFModel(FFConfig())
    x = ff.create_tensor([8, 2048], name="x")
    t = ff.dense(x, 8192, activation=ActiMode.RELU, name="fc1")
    t = ff.dense(t, 8, name="head")

    machine = TpuPodModel(topology=(8,))
    cm = OpCostModel(machine)
    sim = TaskGraphSimulator(machine, cm)

    g_dp = apply_strategy(ff.layers, data_parallel_strategy(8))
    assign_views(g_dp, {"data": 8})
    r_dp = sim.simulate(g_dp, {"data": 8})

    from flexflow_tpu.ops.op import ShardConfig
    from flexflow_tpu.strategy import Strategy

    s_tp = Strategy(mesh_axes={"model": 8})
    s_tp.shard_configs["fc1"] = ShardConfig(channel=8)
    g_tp = apply_strategy(ff.layers, s_tp)
    assign_views(g_tp, {"model": 8})
    r_tp = sim.simulate(g_tp, {"model": 8})

    assert r_tp.total_time < r_dp.total_time
    assert r_dp.total_time > 0.0


@pytest.mark.skipif(not have_native(), reason="native lib unavailable")
def test_taskgraph_native_python_agree_on_pcg():
    ff = FFModel(FFConfig())
    x = ff.create_tensor([64, 512], name="x")
    t = ff.dense(x, 512, activation=ActiMode.RELU, name="fc1")
    t = ff.dense(t, 512, name="fc2")
    machine = TpuPodModel(topology=(4,))
    cm = OpCostModel(machine)
    g = apply_strategy(ff.layers, data_parallel_strategy(4))
    assign_views(g, {"data": 4})
    r_native = TaskGraphSimulator(machine, cm).simulate(g, {"data": 4})
    r_python = TaskGraphSimulator(machine, cm, force_python=True).simulate(
        g, {"data": 4}
    )
    assert r_native.breakdown["native"] == 1.0
    assert r_python.breakdown["native"] == 0.0
    assert r_native.total_time == pytest.approx(r_python.total_time, rel=1e-12)
