"""Tier-1 guard: docs/OBSERVABILITY.md must name every metric the code
can emit under serving/, resilience/, store/, comm/ — via
tools/check_metric_docs.py, so the metric tables cannot drift."""
import importlib
import os

import pytest

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(scope="module")
def checker():
    return importlib.import_module("tools.check_metric_docs")


def test_all_emitted_metric_names_documented(checker, capsys):
    rc = checker.main(["--root", ROOT])
    err = capsys.readouterr().err
    assert rc == 0, f"undocumented metric names:\n{err}"


def test_scan_finds_known_call_sites(checker):
    """The scanner must actually see direct literals, helper
    indirections (_count/_observe_ms), and f-string templates — a
    regex regression that finds nothing would make the check vacuous."""
    emitted = checker.emitted_names(ROOT)
    assert "serving/ttft_ms" in emitted                     # direct literal
    assert "store/hits" in emitted                          # _count helper
    assert "resilience/offload_uploads" in emitted          # _count helper
    assert any("{" in n for n in emitted)                   # f-string kept
    assert len(emitted) > 50


def test_undocumented_name_is_flagged(checker):
    """A fresh metric name with no doc entry must fail the check."""
    with open(os.path.join(ROOT, "docs", "OBSERVABILITY.md")) as f:
        names, wild = checker.documented_forms(f.read())
    assert not checker.is_documented(
        "serving/definitely_not_documented_xyz", names, wild)
    # and the real, documented forms pass through all three paths:
    assert checker.is_documented("serving/ttft_ms", names, wild)
    assert checker.is_documented(                           # <i> placeholder
        'serving/replica/{replica.replica_id}/queue_depth', names, wild)
    assert checker.is_documented(                           # wildcard family
        "serving/autoscaler_{action}", names, wild)


def test_bare_group_wildcard_is_not_vacuous(checker):
    """The `serving/*` namespace header must not count as documenting
    arbitrary serving names."""
    names, wild = checker.documented_forms(
        "groups: `serving/*`, `store/*`\n")
    assert not checker.is_documented("serving/brand_new_name", names, wild)
