"""Durability-layer tests: async verified saves, integrity manifest +
per-leaf corruption fallback, preemption grace (SIGTERM emergency
checkpoints), restore-time layout validation, and the hung-step
watchdog — all on the hermetic 8-device CPU mesh.
"""
import json
import os
import shutil
import signal
import time

import numpy as np
import pytest

from flexflow_tpu import (
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.checkpoint import (
    CheckpointCompatibilityError,
    CheckpointManager,
    LocalCheckpointManager,
)
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.resilience import (
    FaultKind,
    FaultPlan,
    HungStepTimeout,
    RestartBudgetExhausted,
    RetryPolicy,
    StepWatchdog,
    TrainingSupervisor,
)

NO_SLEEP = lambda s: None  # noqa: E731


def _model(devices, seed=0, hidden=32, optimizer=None, **cfg_over):
    cfg = FFConfig(batch_size=16, num_devices=len(devices), seed=seed,
                   **cfg_over)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, hidden, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=optimizer or SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices, seed=seed)
    return ff


def _data(n=128, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 8).astype(np.float32)
    ys = rng.randint(0, 4, size=n).astype(np.int32)
    return xs, ys


def _weights_equal(a, b):
    import jax

    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- async verified saves ------------------------------------------------

def test_async_save_visible_after_drain(devices8, tmp_path):
    """Satellite: save(wait=False) is a real async save — the write
    lands in the background and is restorable after drain()."""
    xs, ys = _data()
    ff = _model(devices8)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr = LocalCheckpointManager(str(tmp_path / "a"))
    mgr.save(ff, step=5, wait=False)
    assert mgr.drain() == []  # no failures
    assert mgr.latest_step() == 5
    assert mgr.latest_verified_step() == 5
    saved = ff.get_weights()
    ff.fit(xs, ys, epochs=1, verbose=False)  # diverge
    assert mgr.restore(ff) == 5
    _weights_equal(ff.get_weights(), saved)
    mgr.close()


def test_manifest_written_and_latest_pointer(devices8, tmp_path):
    """Every save carries a per-leaf crc32 manifest; the LATEST pointer
    names the verified step."""
    import zlib

    xs, ys = _data()
    ff = _model(devices8)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr = LocalCheckpointManager(str(tmp_path / "m"))
    mgr.save(ff, step=3)
    step_dir = mgr._path(3)
    with open(os.path.join(step_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["manifest_version"] == 1
    assert manifest["step"] == 3
    assert manifest["leaves"]
    total = 0
    with np.load(os.path.join(step_dir, "state.npz")) as data:
        assert set(data.files) == set(manifest["leaves"])
        for key, spec in manifest["leaves"].items():
            arr = np.ascontiguousarray(data[key])
            assert zlib.crc32(arr.view(np.uint8).reshape(-1)) == spec["crc32"]
            assert list(arr.shape) == spec["shape"]
            total += arr.nbytes
    assert manifest["total_bytes"] == total
    with open(os.path.join(str(tmp_path / "m"), "LATEST")) as f:
        assert int(f.read()) == 3


def test_per_leaf_corruption_falls_back_to_verified(devices8, tmp_path):
    """Acceptance: a checkpoint whose npz still PARSES but whose bytes
    drifted (bit rot, torn page) fails crc re-verification on restore
    and falls back to the older verified step."""
    xs, ys = _data()
    ff = _model(devices8)
    ff.fit(xs, ys, epochs=1, verbose=False)
    w1 = ff.get_weights()
    mgr = LocalCheckpointManager(str(tmp_path / "c"))
    mgr.save(ff, step=1)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr.save(ff, step=2)

    # corrupt ONE leaf of step 2 in a way np.load cannot notice
    npz = os.path.join(mgr._path(2), "state.npz")
    with np.load(npz) as data:
        flat = {k: np.array(data[k]) for k in data.files}
    key = sorted(k for k in flat if flat[k].dtype == np.float32)[0]
    leaf = flat[key].reshape(-1)
    leaf[0] += 1.0
    np.savez(npz, **flat)

    ff.fit(xs, ys, epochs=1, verbose=False)  # diverge further
    assert mgr.restore(ff) == 1
    _weights_equal(ff.get_weights(), w1)
    # the pointer re-committed to the step that actually verified
    assert mgr.latest_verified_step() == 1
    # an explicitly requested corrupt step stays strict
    with pytest.raises(Exception):
        mgr.restore(ff, step=2)


def test_prune_never_deletes_newest_verified(devices8, tmp_path):
    """Satellite: keep-last-k pruning must not delete the newest
    VERIFIED checkpoint even when newer unverified (legacy-format)
    steps push it outside the retention window."""
    xs, ys = _data()
    ff = _model(devices8)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr = LocalCheckpointManager(str(tmp_path / "p"), max_to_keep=2)
    mgr.save(ff, step=1)
    assert mgr.latest_verified_step() == 1
    # newer steps written by an older (pointer-less, manifest-less) code
    # path: restorable but never verified
    for s in (2, 3, 4):
        shutil.copytree(mgr._path(1), mgr._path(s))
        os.remove(os.path.join(mgr._path(s), "manifest.json"))
    mgr._prune()
    steps = mgr.all_steps()
    assert 1 in steps  # the verified step survived out-of-window
    assert steps[-2:] == [3, 4]  # retention window unchanged otherwise
    assert mgr.latest_verified_step() == 1


def test_async_write_failure_surfaces_at_drain(devices8, tmp_path,
                                               monkeypatch):
    """A background write failure never kills training — it is logged
    and returned by drain() for the supervisor to count."""
    xs, ys = _data()
    ff = _model(devices8)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr = LocalCheckpointManager(str(tmp_path / "f"))
    monkeypatch.setattr(
        mgr, "_write_and_publish",
        lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")),
    )
    mgr.save(ff, step=1, wait=False)
    failures = mgr.drain()
    assert len(failures) == 1 and failures[0][0] == 1
    assert isinstance(failures[0][1], OSError)
    assert mgr.latest_step() is None  # nothing published
    mgr.close()


def test_supervisor_async_crash_restore_bit_identical(devices8, tmp_path):
    """Acceptance: with checkpoint_async on, a crash restores from an
    async-written checkpoint (drained before the restore) and replays
    to weights bit-identical to the fault-free run."""
    xs, ys = _data(128)
    ff_clean = _model(devices8, seed=21)
    clean = TrainingSupervisor(ff_clean, str(tmp_path / "clean"),
                               checkpoint_every=2, sleep=NO_SLEEP)
    rep_clean = clean.run(xs, ys, num_steps=7)

    ff = _model(devices8, seed=21, checkpoint_async=True)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "async"), checkpoint_every=2,
        fault_plan=FaultPlan.single(5, FaultKind.STEP_EXCEPTION),
        sleep=NO_SLEEP,
    )
    rep = sup.run(xs, ys, num_steps=7)
    assert rep.final_step == rep_clean.final_step == 7
    assert rep.counters["restarts"] == 1
    assert rep.losses == rep_clean.losses
    _weights_equal(ff_clean.get_weights(), ff.get_weights())
    # post-run drain landed every queued save
    assert sup.manager.latest_verified_step() == 6


def test_async_save_backpressure_bounds_queue(devices8, tmp_path,
                                              monkeypatch):
    """A writer slower than the save cadence must not accumulate
    full-state host copies unboundedly: save(wait=False) drains the
    backlog once MAX_PENDING_SAVES jobs are queued."""
    xs, ys = _data()
    ff = _model(devices8)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr = LocalCheckpointManager(str(tmp_path / "bp"))
    mgr.MAX_PENDING_SAVES = 1
    real_write = mgr._write_and_publish

    def slow_write(*a, **k):
        time.sleep(0.15)
        return real_write(*a, **k)

    monkeypatch.setattr(mgr, "_write_and_publish", slow_write)
    mgr.save(ff, step=1, wait=False)  # queues instantly
    t0 = time.perf_counter()
    mgr.save(ff, step=2, wait=False)  # backlog >= cap: waits step 1 out
    assert time.perf_counter() - t0 > 0.1
    assert mgr._writer_obj().queue_depth <= 1
    assert mgr.drain() == []
    assert mgr.latest_verified_step() == 2
    mgr.close()


# -- restore-time layout validation --------------------------------------

def test_compatibility_error_names_mismatched_fields(devices8, tmp_path):
    """Satellite: restoring into a structurally different model raises
    one clear CheckpointCompatibilityError naming the leaves, not a
    reshape/KeyError traceback."""
    xs, ys = _data()
    ff32 = _model(devices8, hidden=32)
    ff32.fit(xs, ys, epochs=1, verbose=False)
    mgr = LocalCheckpointManager(str(tmp_path / "lc"))
    mgr.save(ff32, step=1)

    ff64 = _model(devices8, hidden=64)
    with pytest.raises(CheckpointCompatibilityError) as ei:
        mgr.restore(ff64)
    msg = str(ei.value)
    assert "incompatible" in msg
    assert "dense_0" in msg and "shape" in msg
    # strict step request raises the same clear error
    with pytest.raises(CheckpointCompatibilityError):
        mgr.restore(ff64, step=1)
    # mesh-size changes stay COMPATIBLE (reshard-on-restore contract)
    ff1 = _model(devices8[:1], hidden=32, seed=5)
    assert mgr.restore(ff1) == 1


def test_compatibility_error_orbax(devices8, tmp_path):
    xs, ys = _data()
    ff32 = _model(devices8, hidden=32)
    ff32.fit(xs, ys, epochs=1, verbose=False)
    mgr = CheckpointManager(str(tmp_path / "oc"))
    mgr.save(ff32, step=1)
    ff64 = _model(devices8, hidden=64)
    with pytest.raises(CheckpointCompatibilityError) as ei:
        mgr.restore(ff64, step=1)
    assert "dense_0" in str(ei.value)
    mgr.close()


# -- hung-step watchdog --------------------------------------------------

def test_watchdog_unit():
    wd = StepWatchdog(0.05)
    assert wd.enabled
    with pytest.raises(HungStepTimeout) as ei:
        wd.sync(lambda: time.sleep(5.0), step=7)
    assert ei.value.step == 7
    assert wd.sync(lambda: 42, step=8) == 42
    with pytest.raises(ValueError, match="boom"):
        wd.sync(lambda: (_ for _ in ()).throw(ValueError("boom")))
    off = StepWatchdog(0.0)
    assert not off.enabled
    assert off.sync(lambda: "inline") == "inline"
    with pytest.raises(ValueError):
        StepWatchdog(-1.0)


def test_watchdog_recovers_after_timeout():
    """A timeout abandons the wedged worker; the next sync gets a
    fresh one and works (and the persistent worker is reused across
    calls — no thread spawn per step)."""
    wd = StepWatchdog(0.05)
    assert wd.sync(lambda: 1) == 1
    worker = wd._worker
    assert wd.sync(lambda: 2) == 2
    assert wd._worker is worker  # same worker served both
    with pytest.raises(HungStepTimeout):
        wd.sync(lambda: time.sleep(5.0))
    assert wd.sync(lambda: 3) == 3  # fresh worker after abandonment
    assert wd._worker is not worker


def test_check_step_health_watchdog_times_out():
    from flexflow_tpu.executor import check_step_health

    class SlowLoss:
        dtype = np.float32

        def __array__(self, dtype=None):
            time.sleep(5.0)
            return np.float32(1.0)

    with pytest.raises(HungStepTimeout):
        check_step_health({"loss": SlowLoss()}, step=3,
                          watchdog=StepWatchdog(0.05))
    # no watchdog/fast loss: unchanged semantics
    check_step_health({"loss": np.float32(1.0)}, step=3,
                      watchdog=StepWatchdog(5.0))


def test_hung_step_fault_recovers_bit_identical(devices8, tmp_path):
    """Satellite: an injected HungStepFault routes through the
    device-loss-style path (re-search + recompile the full mesh +
    reshard-restore) and the replay converges bit-identical to the
    fault-free run."""
    xs, ys = _data(128)
    ff_clean = _model(devices8, seed=11)
    clean = TrainingSupervisor(ff_clean, str(tmp_path / "clean"),
                               checkpoint_every=2, sleep=NO_SLEEP)
    rep_clean = clean.run(xs, ys, num_steps=7)

    ff = _model(devices8, seed=11)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "hung"), checkpoint_every=2,
        fault_plan=FaultPlan.single(5, FaultKind.HUNG_STEP),
        step_timeout=30.0,  # watchdog armed; nothing actually hangs
        sleep=NO_SLEEP,
    )
    rep = sup.run(xs, ys, num_steps=7)
    assert rep.final_step == 7
    assert rep.counters["hung_steps"] == 1
    assert rep.counters["re_searches"] == 1
    assert rep.counters["restarts"] == 1
    assert rep.counters["device_losses"] == 0  # classified, not conflated
    assert ff.mesh.devices.size == 8  # full mesh: nothing was lost
    assert rep.losses == rep_clean.losses
    _weights_equal(ff_clean.get_weights(), ff.get_weights())


def test_hung_step_exhausts_restart_budget(devices8, tmp_path):
    xs, ys = _data()
    ff = _model(devices8)
    plan = FaultPlan([
        {"step": s, "kind": FaultKind.HUNG_STEP} for s in (2, 3)
    ])
    sup = TrainingSupervisor(
        ff, str(tmp_path), checkpoint_every=2, fault_plan=plan,
        retry=RetryPolicy(max_restarts=1, base_backoff=0.0), sleep=NO_SLEEP,
    )
    with pytest.raises(RestartBudgetExhausted):
        sup.run(xs, ys, num_steps=6)
    assert sup.counters["hung_steps"] == 2


def test_sync_verify_failure_is_survivable(devices8, tmp_path, monkeypatch):
    """A write-time crc verification miss on a periodic SYNC save costs
    that save, never the run — same contract as CheckpointWriteFault."""
    from flexflow_tpu.checkpoint import CheckpointVerifyError

    xs, ys = _data()
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             sleep=NO_SLEEP)
    real_verify = type(sup.manager)._verify_dir  # staticmethod -> function
    calls = {"n": 0}

    def flaky_verify(path, manifest=None):
        calls["n"] += 1
        if calls["n"] == 2:  # fail exactly one save's verification
            raise CheckpointVerifyError("injected crc mismatch")
        return real_verify(path, manifest)

    monkeypatch.setattr(type(sup.manager), "_verify_dir",
                        staticmethod(flaky_verify))
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert rep.counters["checkpoint_failures"] == 1
    assert rep.counters["restarts"] == 0


# -- preemption grace ----------------------------------------------------

def _sigterm_at(plan: FaultPlan, step: int, signum=signal.SIGTERM):
    """Arrange for `signum` to be raised in-process at the given
    supervisor step (delivered synchronously in the main thread)."""
    orig = plan.check_step

    def check(s):
        if s == step:
            signal.raise_signal(signum)
        orig(s)

    plan.check_step = check
    return plan


def test_sigterm_emergency_save_round_trip(devices8, tmp_path):
    """Acceptance: SIGTERM mid-run finishes the in-flight step, writes
    an emergency checkpoint at the boundary, and a resumed run restores
    it and converges bit-identical to an uninterrupted run."""
    xs, ys = _data(128)
    ff_clean = _model(devices8, seed=3)
    clean = TrainingSupervisor(ff_clean, str(tmp_path / "clean"),
                               checkpoint_every=100, sleep=NO_SLEEP)
    rep_clean = clean.run(xs, ys, num_steps=7)

    ff = _model(devices8, seed=3)
    sup = TrainingSupervisor(ff, str(tmp_path / "pre"),
                             checkpoint_every=100,  # cadence never fires
                             fault_plan=_sigterm_at(FaultPlan(), 3),
                             sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=7)
    assert rep.preempted == "SIGTERM"
    assert rep.final_step == 4  # step 3 completed, then the boundary stop
    assert rep.counters["emergency_saves"] == 1
    assert sup.manager.latest_verified_step() == 4  # verified + restorable
    # the handler was uninstalled on exit
    assert signal.getsignal(signal.SIGTERM) not in (sup._on_grace_signal,)

    # replacement process: fresh model, resume from the emergency save
    ff2 = _model(devices8, seed=99)  # different init — must be overwritten
    sup2 = TrainingSupervisor(ff2, str(tmp_path / "pre"),
                              checkpoint_every=100, sleep=NO_SLEEP)
    rep2 = sup2.run(xs, ys, num_steps=7, resume=True)
    assert rep2.final_step == 7
    assert rep2.preempted is None
    _weights_equal(ff_clean.get_weights(), ff2.get_weights())
    assert rep_clean.losses[4:] == rep2.losses  # replayed tail matches


def test_sigterm_during_final_step_still_checkpoints(devices8, tmp_path):
    """A signal landing during the LAST step must still produce the
    emergency checkpoint report.preempted promises — the flag is
    handled after the loop, not only at its top."""
    xs, ys = _data()
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, str(tmp_path),
                             checkpoint_every=100,  # cadence never fires
                             fault_plan=_sigterm_at(FaultPlan(), 4),
                             sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=5)  # signal during step 4 == the last
    assert rep.preempted == "SIGTERM"
    assert rep.final_step == 5
    assert rep.counters["emergency_saves"] == 1
    assert sup.manager.latest_verified_step() == 5  # restorable promise


def test_sigint_grace_and_async_drain(devices8, tmp_path):
    """SIGINT takes the same grace path; pending async saves are
    drained before the supervisor returns."""
    xs, ys = _data()
    ff = _model(devices8, checkpoint_async=True)
    sup = TrainingSupervisor(ff, str(tmp_path),
                             checkpoint_every=2,
                             fault_plan=_sigterm_at(FaultPlan(), 3,
                                                    signal.SIGINT),
                             sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=8)
    assert rep.preempted == "SIGINT"
    assert rep.final_step == 4
    assert rep.counters["emergency_saves"] == 1
    # every queued save landed: the emergency step is verified on disk
    assert sup.manager.latest_verified_step() == 4
    assert signal.getsignal(signal.SIGINT) is signal.default_int_handler


def test_sigterm_zero1_sharded_slots_restore(devices8, tmp_path):
    """Acceptance: the emergency checkpoint round-trips ZeRO-1 sharded
    optimizer slots, including an 8 -> 4 elastic restore."""
    import jax

    from flexflow_tpu.optimizer import AdamOptimizer

    xs, ys = _data(128)
    ff = _model(devices8, seed=4, weight_update_sharding=True,
                optimizer=AdamOptimizer(alpha=0.01), checkpoint_async=True)
    sup = TrainingSupervisor(ff, str(tmp_path / "z"), checkpoint_every=100,
                             fault_plan=_sigterm_at(FaultPlan(), 3),
                             sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=8)
    assert rep.preempted == "SIGTERM"
    saved_w = ff.get_weights()
    saved_opt = jax.tree.map(np.asarray, ff._opt_state)

    # 8 -> 4 elastic: restore the emergency save onto a half-size mesh
    ff4 = _model(devices8[:4], seed=9, weight_update_sharding=True,
                 optimizer=AdamOptimizer(alpha=0.01))
    mgr = LocalCheckpointManager(str(tmp_path / "z"))
    assert mgr.restore(ff4) == rep.final_step
    _weights_equal(ff4.get_weights(), saved_w)
    _weights_equal(jax.tree.map(np.asarray, ff4._opt_state), saved_opt)
    # the restored model keeps training on the survivor mesh
    ff4.fit(xs, ys, epochs=1, verbose=False)


# -- observability -------------------------------------------------------

def test_ckpt_spans_and_counters(devices8, tmp_path):
    """Satellite: checkpoint_write splits into snapshot/flush child
    spans, and the resilience/ckpt_* metrics land in the registry."""
    xs, ys = _data()
    ff = _model(devices8, telemetry=True)
    ff.fit(xs, ys, epochs=1, verbose=False)
    mgr = LocalCheckpointManager(str(tmp_path / "t"))
    mgr.save(ff, step=1, wait=True)
    mgr.save(ff, step=2, wait=False)
    assert mgr.drain() == []

    names = [e["name"] for e in ff.telemetry.tracer.events if e["ph"] == "B"]
    assert names.count("checkpoint_write") == 2
    assert names.count("snapshot") == 2
    assert names.count("flush") == 2  # sync inline + async on the writer tid
    reg = ff.telemetry.metrics
    hist = reg.histogram("resilience/ckpt_write_latency_s")
    assert hist.count == 2 and hist.sum > 0
    assert reg.gauge("resilience/ckpt_queue_depth").value == 0  # drained
    mgr.close()


# -- pipeline <-> per-op restore layout mapping (ISSUE 9 satellite) ------

def _blocky_model(devices, strategy=None, seed=0, momentum=0.9):
    """4 identical dense blocks + head: the repeated-block graph the
    pipeline plan stacks, compiled per-op or under a pp strategy."""
    cfg = FFConfig(batch_size=16, num_devices=len(devices), seed=seed)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = x
    for i in range(4):
        t = ff.dense(t, 8, activation=ActiMode.RELU, name=f"blk{i}")
    t = ff.dense(t, 4, name="head")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05, momentum=momentum),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy=strategy, devices=devices, seed=seed)
    return ff


def _pp_strategy(dp, pp, M):
    from flexflow_tpu.strategy import Strategy

    axes = {"data": dp, "pipe": pp} if dp > 1 else {"pipe": pp}
    s = Strategy(
        mesh_axes=axes,
        pipeline={"degree": pp, "num_microbatches": M, "axis": "pipe",
                  "dp_axis": "data" if dp > 1 else None},
    )
    if dp > 1:
        s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": dp})]
    return s


def test_restore_per_op_checkpoint_onto_pipeline(devices8, tmp_path):
    """A checkpoint saved under a per-op strategy restores onto a
    `__pipeline__`-stacked executor: restore maps the weight AND
    momentum-slot trees through _adapt_weight_layout (the mapping that
    lets elastic re-search pick pipeline winners mid-run)."""
    xs, ys = _data()
    ff = _blocky_model(devices8)
    for i in range(2):
        ff.train_step({"x": xs[i * 16:(i + 1) * 16]}, ys[i * 16:(i + 1) * 16])
    w_saved = ff.get_weights()
    import jax

    v_saved = jax.tree.map(np.asarray, ff._opt_state)["v"]
    mgr = LocalCheckpointManager(str(tmp_path / "c"))
    mgr.save(ff, step=2, wait=True)

    pp = _blocky_model(devices8[:4], strategy=_pp_strategy(2, 2, 4))
    assert "__pipeline__" in pp._weights
    step = LocalCheckpointManager(str(tmp_path / "c")).restore(pp, step=2)
    assert step == 2
    w_pp = pp.get_weights()
    v_pp = jax.tree.map(np.asarray, pp._opt_state)["v"]
    for k in range(4):
        for name in ("kernel", "bias"):
            np.testing.assert_array_equal(
                w_pp["__pipeline__"][f"0.{name}"][k], w_saved[f"blk{k}"][name]
            )
            np.testing.assert_array_equal(
                v_pp["__pipeline__"][f"0.{name}"][k], v_saved[f"blk{k}"][name]
            )
    np.testing.assert_array_equal(w_pp["head"]["kernel"],
                                  w_saved["head"]["kernel"])


def test_restore_pipeline_checkpoint_onto_per_op(devices8, tmp_path):
    """The reverse mapping: a checkpoint saved under a pipeline
    strategy restores onto a freshly compiled per-op executor."""
    xs, ys = _data()
    pp = _blocky_model(devices8[:4], strategy=_pp_strategy(2, 2, 4))
    for i in range(2):
        pp.train_step({"x": xs[i * 16:(i + 1) * 16]}, ys[i * 16:(i + 1) * 16])
    w_saved = pp.get_weights()
    mgr = LocalCheckpointManager(str(tmp_path / "c"))
    mgr.save(pp, step=2, wait=True)

    ff = _blocky_model(devices8)
    assert "__pipeline__" not in ff._weights
    step = LocalCheckpointManager(str(tmp_path / "c")).restore(ff, step=2)
    assert step == 2
    w = ff.get_weights()
    for k in range(4):
        for name in ("kernel", "bias"):
            np.testing.assert_array_equal(
                w[f"blk{k}"][name], w_saved["__pipeline__"][f"0.{name}"][k]
            )


def test_manifest_missing_leaf_is_unverifiable(devices8, tmp_path):
    """A manifest listing FEWER leaves than state.npz must fail
    verification — uncovered bytes would otherwise restore with no
    integrity check at all."""
    ff = _model(devices8)
    mgr = LocalCheckpointManager(str(tmp_path))
    mgr.save(ff, step=1, wait=True)
    mpath = os.path.join(str(tmp_path), "step_00000001", "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    dropped = sorted(manifest["leaves"])[0]
    del manifest["leaves"][dropped]
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    from flexflow_tpu.checkpoint import CheckpointVerifyError

    with pytest.raises(CheckpointVerifyError, match="missing from the"):
        mgr.restore(ff, step=1)
