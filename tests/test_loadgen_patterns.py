"""Loadgen arrival patterns (serving/loadgen.py arrival_gaps): seeded
replayability for poisson/ramp/square, the shapes the autoscale bench
leg drives, and queue-depth-at-admit in detail records."""
import numpy as np
import pytest

from flexflow_tpu.serving import ServingFront
from flexflow_tpu.serving.loadgen import (
    arrival_gaps,
    run_loadgen,
    sample_shared_prefix_workload,
    sample_workload,
)

NO_SLEEP = lambda s: None  # noqa: E731


def test_patterns_are_seeded_and_replayable():
    for pattern in ("poisson", "ramp", "square"):
        a = arrival_gaps(np.random.RandomState(7), 200, 5.0, pattern)
        b = arrival_gaps(np.random.RandomState(7), 200, 5.0, pattern)
        np.testing.assert_array_equal(a, b)
        c = arrival_gaps(np.random.RandomState(8), 200, 5.0, pattern)
        assert not np.array_equal(a, c)  # the seed is the trace


def test_ramp_rate_climbs():
    """Mean gap over the last quarter of a ramp trace is well below
    the first quarter's (rate_rps -> ramp_to)."""
    gaps = arrival_gaps(np.random.RandomState(0), 2000, 2.0, "ramp",
                        ramp_to=20.0)
    q = len(gaps) // 4
    assert gaps[-q:].mean() < 0.5 * gaps[:q].mean()


def test_square_wave_alternates_rates():
    """Square bursts: gaps drawn during the burst phase are shorter on
    average; phase boundaries follow generated time, so the trace is
    self-consistent under replay."""
    rng = np.random.RandomState(3)
    gaps = arrival_gaps(rng, 4000, 4.0, "square", burst_factor=8.0,
                        period_s=2.0)
    t = np.cumsum(gaps) - gaps  # arrival times
    phase = (t / 2.0).astype(int) % 2
    calm = gaps[phase == 0]
    burst = gaps[phase == 1]
    assert len(calm) > 50 and len(burst) > 50
    assert burst.mean() < 0.4 * calm.mean()


def test_pattern_validation():
    rng = np.random.RandomState(0)
    with pytest.raises(ValueError, match="pattern"):
        arrival_gaps(rng, 10, 5.0, "sawtooth")
    with pytest.raises(ValueError, match="rate_rps"):
        arrival_gaps(rng, 10, 0.0, "poisson")
    with pytest.raises(ValueError, match="burst_factor"):
        arrival_gaps(rng, 10, 5.0, "square", burst_factor=0)
    with pytest.raises(ValueError, match="period_s"):
        arrival_gaps(rng, 10, 5.0, "square", period_s=0)
    assert len(arrival_gaps(rng, 0, 5.0, "poisson")) == 0


class _FakeStepModel:
    def __init__(self, batch_slots=2, max_seq=64, page_size=4):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = 1 + batch_slots * self.max_blocks_per_seq
        self.vocab = 16

    def reset(self):
        pass

    def step(self, tokens, seq_lens, block_tables):
        logits = np.zeros((self.batch_slots, 16), np.float32)
        nxt = (np.asarray(tokens) + 1) % 16
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits


def test_detail_records_carry_queue_depth_and_tokens():
    front = ServingFront(
        lambda rid, survivors=None: _FakeStepModel(),
        num_replicas=1, sleep=NO_SLEEP)
    try:
        reqs = sample_workload(np.random.RandomState(0), 12, 16,
                               prompt_len_range=(2, 6),
                               max_new_range=(2, 6))
        rep = run_loadgen(front, reqs, rate_rps=200.0, seed=1,
                          detail=True, record_tokens=True,
                          arrival="square", burst_factor=4.0,
                          period_s=0.05)
        assert rep["completed"] == len(reqs)
        assert rep["arrival"] == "square"
        recs = rep["records"]
        assert len(recs) == len(reqs)
        # the front stamps its backlog at admission on every handle
        assert all("queue_depth_at_admit" in r for r in recs)
        assert all(r["queue_depth_at_admit"] >= 0 for r in recs)
        # record_tokens keeps the completions for token-identity audits
        assert all(isinstance(r["tokens"], list) and r["tokens"]
                   for r in recs)
        assert all(r["idx"] == i for i, r in enumerate(recs))
    finally:
        front.close()


def test_shared_prefix_workload_seeded_and_shaped():
    rng = np.random.RandomState(5)
    reqs, prefixes = sample_shared_prefix_workload(
        rng, 20, 64, num_prefixes=3, prefix_len=16,
        tail_range=(1, 4), max_new_range=(2, 5))
    assert len(reqs) == 20 and len(prefixes) == 3
    keys = {tuple(p) for p in prefixes}
    for prompt, mnt in reqs:
        # every request = one shared prefix + a unique tail
        assert tuple(prompt[:16]) in keys
        assert 17 <= len(prompt) <= 20
        assert 2 <= mnt <= 5
    # same seed -> byte-identical trace (bench baseline parity)
    again, _ = sample_shared_prefix_workload(
        np.random.RandomState(5), 20, 64, num_prefixes=3,
        prefix_len=16, tail_range=(1, 4), max_new_range=(2, 5))
    assert again == reqs
    with pytest.raises(ValueError):
        sample_shared_prefix_workload(rng, 4, 64, num_prefixes=0)


def test_detail_records_carry_prefix_hit_tokens():
    from flexflow_tpu.serving import ContinuousScheduler

    sched = ContinuousScheduler(_FakeStepModel())
    try:
        reqs, _ = sample_shared_prefix_workload(
            np.random.RandomState(3), 10, 16, num_prefixes=2,
            prefix_len=8, tail_range=(1, 3), max_new_range=(2, 4))
        rep = run_loadgen(sched, reqs, rate_rps=300.0, seed=2,
                          detail=True)
        assert rep["completed"] == len(reqs)
        recs = rep["records"]
        assert all("prefix_hit_tokens" in r for r in recs)
        # the shared 8-token prefixes (2 full pages of 4) get re-hit
        assert sum(r["prefix_hit_tokens"] for r in recs) > 0
    finally:
        sched.close()
