"""ONNX handler parity on a stock ResNet-18 graph (VERDICT r4 #2).

The reference imports torch-exported CNNs through its onnx frontend
(python/flexflow/onnx/model.py) — but its BatchNormalization handler
drops the trained affine+stats and Pad/Cast/Unsqueeze are warned
pass-throughs.  Here a torch ResNet-18 is serialized to real .onnx
wire bytes (protowire's encoder — torch.onnx.export needs the `onnx`
package this image doesn't bake in) with the exact node sequence torch
exports (Conv/BatchNormalization/Relu/MaxPool/Add/GlobalAveragePool/
Flatten/Gemm), then imported, forward-aligned against torch in eval
mode, and trained one step on the 8-device CPU mesh.
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only


torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer  # noqa: E402
from flexflow_tpu.onnx_frontend import protowire  # noqa: E402
from flexflow_tpu.onnx_frontend.model import ONNXModel  # noqa: E402


# -- torch ResNet-18 (BasicBlock), torchvision-equivalent ----------------
class BasicBlock(nn.Module):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout),
            )

    def forward(self, x):
        idt = self.down(x) if self.down is not None else x
        y = torch.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return torch.relu(y + idt)


class ResNet18(nn.Module):
    def __init__(self, classes=10):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.pool = nn.MaxPool2d(3, 2, 1)
        cfg = [(64, 64, 1), (64, 128, 2), (128, 256, 2), (256, 512, 2)]
        blocks = []
        for cin, cout, s in cfg:
            blocks += [BasicBlock(cin, cout, s), BasicBlock(cout, cout, 1)]
        self.blocks = nn.ModuleList(blocks)
        self.fc = nn.Linear(512, classes)

    def forward(self, x):
        x = self.pool(torch.relu(self.bn1(self.conv1(x))))
        for b in self.blocks:
            x = b(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


# -- serialize to .onnx wire bytes (torch's export node sequence) --------
class _Enc:
    def __init__(self):
        self.nodes, self.inits, self.n = [], {}, 0

    def t(self, name, mod_tensor):
        self.inits[name] = mod_tensor.detach().numpy()
        return name

    def emit(self, op, inputs, n_out=1, **attrs):
        outs = [f"t{self.n + i}" for i in range(n_out)]
        self.n += n_out
        self.nodes.append(protowire.encode_node(op, inputs, outs, **attrs))
        return outs[0] if n_out == 1 else outs

    def conv(self, x, m, name):
        kh, kw = m.kernel_size
        args = [x, self.t(f"{name}.weight", m.weight)]
        if m.bias is not None:
            args.append(self.t(f"{name}.bias", m.bias))
        return self.emit("Conv", args, kernel_shape=[kh, kw],
                         strides=list(m.stride),
                         pads=list(m.padding) * 2, group=1)

    def bn(self, x, m, name):
        return self.emit(
            "BatchNormalization",
            [x, self.t(f"{name}.weight", m.weight),
             self.t(f"{name}.bias", m.bias),
             self.t(f"{name}.mean", m.running_mean),
             self.t(f"{name}.var", m.running_var)],
            epsilon=float(m.eps), momentum=0.9)


def resnet_to_onnx(model: ResNet18, in_shape) -> bytes:
    e = _Enc()
    x = e.conv("input", model.conv1, "conv1")
    x = e.bn(x, model.bn1, "bn1")
    x = e.emit("Relu", [x])
    x = e.emit("MaxPool", [x], kernel_shape=[3, 3], strides=[2, 2],
               pads=[1, 1, 1, 1])
    for i, b in enumerate(model.blocks):
        idt = x
        if b.down is not None:
            idt = e.conv(x, b.down[0], f"b{i}.down0")
            idt = e.bn(idt, b.down[1], f"b{i}.down1")
        y = e.conv(x, b.conv1, f"b{i}.conv1")
        y = e.bn(y, b.bn1, f"b{i}.bn1")
        y = e.emit("Relu", [y])
        y = e.conv(y, b.conv2, f"b{i}.conv2")
        y = e.bn(y, b.bn2, f"b{i}.bn2")
        y = e.emit("Add", [y, idt])
        x = e.emit("Relu", [y])
    x = e.emit("GlobalAveragePool", [x])
    x = e.emit("Flatten", [x], axis=1)
    x = e.emit("Gemm", [x, e.t("fc.weight", model.fc.weight),
                        e.t("fc.bias", model.fc.bias)],
               alpha=1.0, beta=1.0, transB=1)
    return protowire.encode_model(
        e.nodes, [("input", list(in_shape))], [x], e.inits)


B, HW, CLASSES = 8, 64, 10


@pytest.fixture(scope="module")
def imported(devices8):
    torch.manual_seed(0)
    tm = ResNet18(CLASSES).eval()
    # non-trivial running stats so eval-mode alignment proves transfer
    with torch.no_grad():
        tm.train()
        for _ in range(2):
            tm(torch.randn(4, 3, HW, HW))
        tm.eval()
    wire = resnet_to_onnx(tm, (B, 3, HW, HW))

    ff = FFModel(FFConfig(batch_size=B, num_devices=8,
                          only_data_parallel=True))
    x = ff.create_tensor([B, 3, HW, HW], name="input")
    m = ONNXModel(wire)
    (out,) = m.apply(ff, [x])
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8)
    m.copy_weights(ff)
    return tm, ff


def test_resnet18_onnx_forward_aligns(imported):
    tm, ff = imported
    x = np.random.RandomState(0).randn(B, 3, HW, HW).astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(x)).numpy()
    got = np.asarray(ff.forward({"input": x}))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-4)


def test_resnet18_onnx_trains(imported):
    _, ff = imported
    rng = np.random.RandomState(1)
    x = rng.randn(B, 3, HW, HW).astype(np.float32)
    y = rng.randint(0, CLASSES, (B,)).astype(np.int32)
    m1 = ff.train_step({"input": x}, y)
    m2 = ff.train_step({"input": x}, y)
    assert np.isfinite(m1["loss"]) and m2["loss"] < m1["loss"]


def test_handler_coverage_ops(devices8):
    """The r4-missing handlers (Pad/Cast/Unsqueeze/Squeeze/Constant/
    Range/Shape) import as real graph ops / constant folds — not the
    reference's warned pass-throughs."""
    nodes = [
        protowire.encode_node("Constant", [], ["pads"],
                              value=np.array([0, 0, 1, 1, 0, 0, 1, 1],
                                             np.int64)),
        protowire.encode_node("Pad", ["input", "pads"], ["p"],
                              mode="constant"),
        protowire.encode_node("Cast", ["p"], ["c"], to=1),
        protowire.encode_node("Unsqueeze", ["c"], ["u"], axes=[4]),
        protowire.encode_node("Squeeze", ["u"], ["s"], axes=[4]),
        protowire.encode_node("Shape", ["s"], ["shp"]),
        protowire.encode_node("Range", ["zero", "four", "one"], ["r"]),
    ]
    inits = {"zero": np.array(0, np.int64), "four": np.array(4, np.int64),
             "one": np.array(1, np.int64)}
    wire = protowire.encode_model(nodes, [("input", [2, 3, 4, 4])],
                                  ["s", "shp", "r"], inits)
    ff = FFModel(FFConfig(batch_size=2, num_devices=1))
    x = ff.create_tensor([2, 3, 4, 4], name="input")
    m = ONNXModel(wire)
    s, shp, r = m.apply(ff, [x])
    assert tuple(s.shape.logical_shape) == (2, 3, 6, 6)
    np.testing.assert_array_equal(shp, [2, 3, 6, 6])
    np.testing.assert_array_equal(r, [0, 1, 2, 3])
