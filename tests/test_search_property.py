"""Search optimality property tests (SURVEY hard-part 1: 'budget for
property tests against brute force on tiny graphs').

On tiny graphs, enumerate EVERY strategy in the search space (mesh
factorization x per-op option assignment), rank each with the full
Simulator — the search's own final judge — and assert the Unity DP's
winner is within tolerance of the brute-force optimum under that
metric."""
import itertools

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.pcg.mcmc import _factorizations
from flexflow_tpu.pcg.unity import UnitySearch
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import OpCostModel, Simulator
from flexflow_tpu.strategy import apply_strategy, assign_views


def _mlp(widths, batch=32, in_dim=32):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor([batch, in_dim], name="x")
    t = x
    for i, w in enumerate(widths):
        t = ff.dense(t, w, activation=ActiMode.RELU, name=f"fc{i}")
    ff.softmax(t)
    return ff


def _brute_force_best(search: UnitySearch, sim: Simulator):
    """Global enumeration over the identical candidate space."""
    best_obj, best = np.inf, None
    ops = search.graph.topo_order()
    for dp, tp, ep in _factorizations(search.n):
        if ep > 1:
            continue  # no MoE in these graphs
        mesh_axes = search._mesh_axes(dp, tp, ep)
        options = search._options_by_op(mesh_axes)
        opt_lists = [
            [(op.guid, c) for c in options[op.guid]]
            for op in ops if op.guid in options
        ]
        for combo in itertools.product(*opt_lists) if opt_lists else [()]:
            shard_configs = {}
            edges = {}
            for guid, choice in combo:
                op = next(o for o in ops if o.guid == guid)
                shard_configs[op.name] = choice.shard
                if choice.out_chain:
                    edges[op.outputs[0].name] = list(choice.out_chain)
            strategy = search._build_strategy(mesh_axes, dp, shard_configs,
                                              edges)
            try:
                g = apply_strategy(search.graph, strategy)
                assign_views(g, strategy.mesh_axes)
            except Exception:
                continue
            res = sim.simulate(g, mesh_axes)
            if res.total_time < best_obj:
                best_obj, best = res.total_time, strategy
    return best_obj, best


@pytest.mark.parametrize("widths,n", [
    ([64], 4), ([64, 128], 4), ([256, 64], 8),
])
def test_unity_within_tolerance_of_brute_force(widths, n):
    ff = _mlp(widths)
    machine = TpuPodModel()
    cm = OpCostModel(machine)
    search = UnitySearch(ff.layers, n, machine, cm)
    sim = Simulator(machine, cm)

    chosen = search.optimize()
    assert chosen is not None
    g = apply_strategy(ff.layers, chosen)
    assign_views(g, chosen.mesh_axes)
    chosen_time = sim.simulate(g, chosen.mesh_axes).total_time

    bf_time, bf = _brute_force_best(search, sim)
    assert bf is not None
    # the DP evaluates segments with the same cost terms; allow a small
    # slack for the chain-cost approximation at segment boundaries
    assert chosen_time <= bf_time * 1.25 + 1e-9, (
        f"search picked {chosen_time:.3e}s vs brute-force {bf_time:.3e}s "
        f"(mesh {chosen.mesh_axes} vs {bf.mesh_axes})"
    )


def _branchy_tower(n_branches, batch=32, in_dim=32):
    """Inception-style parallel branches joined by a concat."""
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor([batch, in_dim], name="x")
    outs = []
    for i in range(n_branches):
        t = ff.dense(x, 32 + 16 * i, activation=ActiMode.RELU, name=f"b{i}a")
        t = ff.dense(t, 64, activation=ActiMode.RELU, name=f"b{i}b")
        outs.append(t)
    t = ff.concat(outs, axis=1)
    t = ff.dense(t, 16, name="head")
    ff.softmax(t)
    return ff


def test_branchy_graph_decomposition_matches_brute_force(monkeypatch):
    """Reference split_horizontal/split_at_node (graph.h:346-349): with
    the assignment cap forced tiny, the branch region must decompose
    (per-branch choices, combined at the join) and still find the
    brute-force optimum instead of collapsing to grouped-uniform."""
    from flexflow_tpu.pcg import unity as unity_mod

    ff = _branchy_tower(2)
    machine = TpuPodModel()
    cm = OpCostModel(machine)
    search = UnitySearch(ff.layers, 4, machine, cm,
                         rewrite_max_variants=1)  # isolate decomposition
    sim = Simulator(machine, cm)

    monkeypatch.setattr(unity_mod, "_MAX_SEGMENT_ASSIGNMENTS", 4)
    horizontal_calls = []
    orig_h = search._eval_horizontal

    def spy(*a, **k):
        horizontal_calls.append(1)
        return orig_h(*a, **k)

    search._eval_horizontal = spy
    chosen = search.optimize()
    assert chosen is not None
    assert horizontal_calls, "branch region never split horizontally"

    g = apply_strategy(ff.layers, chosen)
    assign_views(g, chosen.mesh_axes)
    chosen_time = sim.simulate(g, chosen.mesh_axes).total_time

    monkeypatch.setattr(unity_mod, "_MAX_SEGMENT_ASSIGNMENTS", 10 ** 9)
    bf_time, bf = _brute_force_best(search, sim)
    assert bf is not None
    assert chosen_time <= bf_time * 1.25 + 1e-9, (
        f"decomposed search picked {chosen_time:.3e}s vs brute-force "
        f"{bf_time:.3e}s (mesh {chosen.mesh_axes} vs {bf.mesh_axes})"
    )
