"""Persistent strategy + compile artifact store (flexflow_tpu/store/,
docs/STORE.md): key invalidation matrix, warm-hit bit-identity, corrupt
entry tolerance, supervisor elastic fast path, store metrics, gc, the
shipped-artifact import tool, and the crash-safe merged op-cost
persistence it rides with."""
import json
import os
import shutil

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.store import (
    StrategyStore,
    cached_search,
    store_from_config,
    store_key_for,
)

BUDGET = 8  # tiny unity budget: enough to exercise the real search


def _mlp(cfg, extra_layer=False):
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 16], name="x")
    t = ff.dense(x, 32, name="fc1")
    t = ff.relu(t)
    if extra_layer:
        t = ff.dense(t, 32, name="fc_extra")
    t = ff.dense(t, 8, name="fc2")
    ff.softmax(t)
    return ff


def _cfg(store, n=4, **kw):
    return FFConfig(batch_size=8, num_devices=n, search_budget=BUDGET,
                    strategy_store=str(store), **kw)


def _compile(ff, devices):
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices)
    return ff


def _entries(store_dir):
    d = os.path.join(str(store_dir), "strategies")
    return sorted(
        n for n in os.listdir(d) if not n.startswith(".tmp-")
    ) if os.path.isdir(d) else []


# -- warm hit: search skipped, bit-identical strategy ----------------------

def test_warm_hit_is_bit_identical_and_trains_identically(
        tmp_path, devices8):
    devs = devices8[:4]
    ff1 = _compile(_mlp(_cfg(tmp_path)), devs)
    assert ff1.strategy.search_stats["store_hit"] is False
    assert len(_entries(tmp_path)) == 1

    ff2 = _compile(_mlp(_cfg(tmp_path)), devs)
    # the acceptance bar: warm compile skips the search entirely and
    # restores the PUBLISHED strategy bit-identically
    assert ff2.strategy.search_stats["store_hit"] is True
    assert ff2.strategy.to_json() == ff1.strategy.to_json()
    assert len(_entries(tmp_path)) == 1  # no duplicate publish

    # restored strategy applies and trains one step matching the fresh
    # search (same seed -> same init -> bit-identical loss)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 8, (8,))
    l1 = float(ff1.train_step({"x": x}, y)["loss"])
    l2 = float(ff2.train_step({"x": x}, y)["loss"])
    assert l1 == l2


def test_store_off_by_default(tmp_path, devices8, monkeypatch):
    monkeypatch.delenv("FLEXFLOW_TPU_STORE_DIR", raising=False)
    cfg = FFConfig(batch_size=8, num_devices=2, search_budget=BUDGET)
    ff = _compile(_mlp(cfg), devices8[:2])
    assert "store_hit" not in (ff.strategy.search_stats or {})


def test_env_var_store_and_explicit_off(tmp_path, devices8, monkeypatch):
    monkeypatch.setenv("FLEXFLOW_TPU_STORE_DIR", str(tmp_path))
    cfg = FFConfig(batch_size=8, num_devices=2, search_budget=BUDGET)
    assert cfg.resolve_store_dir() == str(tmp_path)
    ff = _compile(_mlp(cfg), devices8[:2])
    assert ff.strategy.search_stats["store_hit"] is False
    assert len(_entries(tmp_path)) == 1
    # --no-strategy-store wins over the env var
    off = FFConfig(batch_size=8, num_devices=2, search_budget=BUDGET,
                   strategy_store="none")
    assert off.resolve_store_dir() is None


# -- key invalidation matrix -----------------------------------------------

def test_changed_mesh_misses(tmp_path, devices8):
    _compile(_mlp(_cfg(tmp_path, n=4)), devices8[:4])
    ff = _compile(_mlp(_cfg(tmp_path, n=2)), devices8[:2])
    assert ff.strategy.search_stats["store_hit"] is False
    assert len(_entries(tmp_path)) == 2


def test_changed_graph_misses(tmp_path, devices8):
    devs = devices8[:4]
    _compile(_mlp(_cfg(tmp_path)), devs)
    ff = _compile(_mlp(_cfg(tmp_path), extra_layer=True), devs)
    assert ff.strategy.search_stats["store_hit"] is False
    assert len(_entries(tmp_path)) == 2


def test_changed_calibration_digest_misses(tmp_path, devices8,
                                           monkeypatch):
    devs = devices8[:4]
    _compile(_mlp(_cfg(tmp_path)), devs)
    # install a VALID fitted calibration table (load_overlap_constants
    # accepts it for the cpu backend) under a fresh cache dir: the
    # simulator-version digest changes, so the published entry is stale
    cache = tmp_path / "calib_cache"
    cache.mkdir()
    monkeypatch.setenv("FLEXFLOW_TPU_CACHE_DIR", str(cache))
    from flexflow_tpu.sim.calibrate import (load_overlap_constants,
                                            save_overlap_constants)

    save_overlap_constants({
        "compute_scale": 1.5, "comm_scale": 1.0, "sync_scale": 1.0,
        "overlap_fraction": 0.3, "sync_overlap_fraction": 0.3,
        "fitted_on": "cpu",
    })
    assert load_overlap_constants() is not None  # the table is live
    ff = _compile(_mlp(_cfg(tmp_path)), devs)
    assert ff.strategy.search_stats["store_hit"] is False
    assert len(_entries(tmp_path)) == 2


def test_changed_search_config_misses(tmp_path, devices8):
    devs = devices8[:4]
    _compile(_mlp(_cfg(tmp_path)), devs)
    ff = _compile(_mlp(_cfg(tmp_path, enable_parameter_parallel=True)),
                  devs)
    assert ff.strategy.search_stats["store_hit"] is False
    assert len(_entries(tmp_path)) == 2


# -- corruption tolerance --------------------------------------------------

@pytest.mark.parametrize("corruption", ["truncate", "garbage", "missing"])
def test_corrupt_entry_falls_back_to_search(tmp_path, devices8,
                                            corruption):
    devs = devices8[:4]
    _compile(_mlp(_cfg(tmp_path)), devs)
    (digest,) = _entries(tmp_path)
    spath = os.path.join(str(tmp_path), "strategies", digest,
                         "strategy.json")
    if corruption == "truncate":
        with open(spath) as f:
            text = f.read()
        with open(spath, "w") as f:
            f.write(text[: len(text) // 2])
    elif corruption == "garbage":
        with open(spath, "w") as f:
            f.write("{not json")
    else:
        os.unlink(spath)
    ff = _compile(_mlp(_cfg(tmp_path)), devs)  # no crash
    assert ff.strategy.search_stats["store_hit"] is False
    # the corrupt entry was quarantined and the fresh search re-published
    (redigest,) = _entries(tmp_path)
    assert redigest == digest
    ff3 = _compile(_mlp(_cfg(tmp_path)), devs)
    assert ff3.strategy.search_stats["store_hit"] is True


def test_unwritable_root_degrades_to_store_off(devices8):
    cfg = FFConfig(batch_size=8, num_devices=2, search_budget=BUDGET,
                   strategy_store="/proc/definitely/not/writable")
    assert store_from_config(cfg) is None
    ff = _compile(_mlp(cfg), devices8[:2])  # search still runs fine
    assert "store_hit" not in (ff.strategy.search_stats or {})


# -- supervisor elastic fast path ------------------------------------------

@pytest.mark.parametrize("warm", [False, True])
def test_supervisor_elastic_consults_store(tmp_path, devices8, warm):
    from flexflow_tpu.resilience import FaultPlan
    from flexflow_tpu.resilience.faults import FaultKind

    def run(ckpt_dir):
        cfg = _cfg(tmp_path, n=8, checkpoint_every=1, retry_backoff=0.0)
        ff = _compile(_mlp(cfg), devices8)
        rng = np.random.RandomState(0)
        x = rng.randn(32, 16).astype(np.float32)
        y = rng.randint(0, 8, (32,)).astype(np.int32)
        plan = FaultPlan.single(2, FaultKind.DEVICE_LOSS, survivors=4)
        report = ff.fit_resilient({"x": x}, y, num_steps=4, batch_size=8,
                                  directory=str(ckpt_dir),
                                  fault_plan=plan)
        return ff, report

    ff1, report1 = run(tmp_path / "ck1")
    # cold: the degraded-mesh key missed, the re-search ran and
    # published — recovery still correct
    assert report1.final_step == 4
    assert report1.counters["re_searches"] == 1
    assert report1.counters["re_search_store_hits"] == 0
    assert ff1.strategy.search_stats["store_hit"] is False
    assert len(_entries(tmp_path)) == 2  # 8-device + 4-survivor keys
    if warm:
        ff2, report2 = run(tmp_path / "ck2")
        assert report2.final_step == 4
        assert report2.counters["re_search_store_hits"] == 1
        # the recovered model runs under the RESTORED degraded strategy
        assert ff2.strategy.search_stats["store_hit"] is True
        assert ff2.strategy.to_json() == ff1.strategy.to_json()


# -- metrics ----------------------------------------------------------------

def test_store_metrics_reach_telemetry(tmp_path, devices8):
    devs = devices8[:4]
    cfg1 = _cfg(tmp_path, telemetry=True)
    _compile(_mlp(cfg1), devs)
    cfg2 = _cfg(tmp_path, telemetry=True)
    ff2 = _compile(_mlp(cfg2), devs)
    recs = {r["name"]: r for r in ff2.telemetry.metrics.drain()
            if r.get("name", "").startswith("store/")}
    assert recs["store/hits"]["value"] == 1
    assert recs["store/lookup_ms"]["count"] == 1
    # the miss + publish land on the searching model's own registry
    cfg3 = _cfg(tmp_path / "fresh", telemetry=True)
    ff3 = _compile(_mlp(cfg3), devs)
    recs3 = {r["name"]: r for r in ff3.telemetry.metrics.drain()
             if r.get("name", "").startswith("store/")}
    assert recs3["store/misses"]["value"] == 1
    assert recs3["store/publishes"]["value"] == 1


def test_telemetry_summary_renders_store_section(tmp_path, devices8):
    import subprocess
    import sys

    trace_dir = tmp_path / "trace"
    cfg = _cfg(tmp_path / "store", trace_dir=str(trace_dir))
    ff = _compile(_mlp(cfg), devices8[:4])
    ff.telemetry.flush()
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "telemetry_summary.py"),
         str(trace_dir)],
        capture_output=True, text=True, check=True,
    ).stdout
    assert "Store" in out
    assert "misses" in out and "publishes" in out


# -- direct store API: gc, import, first-write-wins ------------------------

def test_gc_keeps_newest_entries(tmp_path, devices8):
    store = StrategyStore(str(tmp_path))
    cfgs = [_cfg(tmp_path, n=n) for n in (1, 2, 4)]
    keys = []
    for cfg in cfgs:
        ff = _mlp(cfg)
        key = store_key_for(cfg, ff.layers, cfg.num_devices)
        keys.append(key)
    from flexflow_tpu.strategy import data_parallel_strategy

    for i, key in enumerate(keys):
        assert store.publish(key, data_parallel_strategy(2),
                             created_at=1000.0 + i)
    assert store.gc(keep_last=2) == 1
    kept = {d for d, _ in store.entries()}
    assert keys[0].digest not in kept
    assert {keys[1].digest, keys[2].digest} == kept
    # idempotent below the cap; keep_last=0 empties
    assert store.gc(keep_last=2) == 0
    assert store.gc(keep_last=0) == 2
    assert store.entries() == []


def test_newer_manifest_version_misses_without_quarantine(tmp_path):
    from flexflow_tpu.strategy import data_parallel_strategy

    cfg = _cfg(tmp_path, n=2)
    ff = _mlp(cfg)
    store = StrategyStore(str(tmp_path))
    key = store_key_for(cfg, ff.layers, 2)
    assert store.publish(key, data_parallel_strategy(2), created_at=1.0)
    mpath = os.path.join(str(tmp_path), "strategies", key.digest,
                         "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["manifest_version"] = 99  # a future writer's schema
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    assert store.lookup(key) is None  # miss for THIS reader...
    assert os.path.isdir(os.path.dirname(mpath))  # ...but NOT deleted


def test_gc_spares_young_tmp_dirs(tmp_path):
    store = StrategyStore(str(tmp_path))
    young = os.path.join(store.strategies_dir, ".tmp-young-1-1")
    stale = os.path.join(store.strategies_dir, ".tmp-stale-1-1")
    os.makedirs(young)
    os.makedirs(stale)
    os.utime(stale, (1.0, 1.0))  # writer long dead
    store.gc(keep_last=0)
    assert os.path.isdir(young)     # maybe a live concurrent publisher
    assert not os.path.isdir(stale)


def test_publish_first_write_wins_and_overwrite(tmp_path):
    from flexflow_tpu.strategy import data_parallel_strategy

    cfg = _cfg(tmp_path, n=2)
    ff = _mlp(cfg)
    store = StrategyStore(str(tmp_path))
    key = store_key_for(cfg, ff.layers, 2)
    s2, s4 = data_parallel_strategy(2), data_parallel_strategy(4)
    assert store.publish(key, s2, created_at=1.0)
    assert not store.publish(key, s4, created_at=2.0)  # kept existing
    assert store.lookup(key).to_json() == s2.to_json()
    assert store.publish(key, s4, created_at=3.0, overwrite=True)
    assert store.lookup(key).to_json() == s4.to_json()


def test_publish_best_cost_upgrades_entry(tmp_path):
    """ISSUE 8 satellite: a publish with a STRICTLY better
    searched_cost replaces the incumbent (so a replica's degraded-mesh
    re-search can improve the shared fleet entry); equal/worse/costless
    publishes still lose to first-write-wins."""
    from flexflow_tpu.obs.metrics import MetricsRegistry
    from flexflow_tpu.strategy import data_parallel_strategy

    cfg = _cfg(tmp_path, n=2)
    ff = _mlp(cfg)
    reg = MetricsRegistry()
    store = StrategyStore(str(tmp_path), registry=reg)
    key = store_key_for(cfg, ff.layers, 2)
    s2, s4 = data_parallel_strategy(2), data_parallel_strategy(4)
    assert store.publish(key, s2, searched_cost=10.0, created_at=1.0)
    # worse, equal, and cost-less publishes all keep the incumbent
    assert not store.publish(key, s4, searched_cost=11.0, created_at=2.0)
    assert not store.publish(key, s4, searched_cost=10.0, created_at=3.0)
    assert not store.publish(key, s4, created_at=4.0)
    assert store.lookup(key).to_json() == s2.to_json()
    assert reg.counter("store/best_cost_upgrades").value == 0
    # strictly better: the entry upgrades in place
    assert store.publish(key, s4, searched_cost=7.5, created_at=5.0)
    hit = store.lookup(key)
    assert hit.to_json() == s4.to_json()
    assert hit.search_cost == 7.5
    assert reg.counter("store/best_cost_upgrades").value == 1
    # and the upgraded entry defends its cost the same way
    assert not store.publish(key, s2, searched_cost=8.0, created_at=6.0)


def test_import_tool_promotes_shipped_artifacts(tmp_path, devices8):
    import sys

    tools_dir = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools_dir)
    try:
        from strategy_store_import import import_default_jobs
    finally:
        sys.path.remove(tools_dir)
    strategies_dir = os.path.join(os.path.dirname(__file__), "..",
                                  "examples", "strategies")
    results = import_default_jobs(str(tmp_path), strategies_dir, 8)
    assert len(results) == 3 and all(written for _, _, written in results)
    store = StrategyStore(str(tmp_path))
    assert len(store.entries()) == 3
    # Strategy.load stays the compatibility surface: the promoted entry
    # round-trips to exactly the shipped JSON's strategy
    from flexflow_tpu.strategy import Strategy

    name, digest, _ = results[0]
    shipped = Strategy.load(os.path.join(strategies_dir, f"{name}.json"))
    with open(os.path.join(str(tmp_path), "strategies", digest,
                           "strategy.json")) as f:
        assert Strategy.from_json(f.read()).to_json() == shipped.to_json()


# -- compilation cache knob -------------------------------------------------

def test_compilation_cache_auto_ties_to_store_root(tmp_path, devices8):
    import jax

    prev = jax.config.jax_compilation_cache_dir
    try:
        cfg = _cfg(tmp_path, n=2, compilation_cache="auto")
        _compile(_mlp(cfg), devices8[:2])
        cache_dir = os.path.join(str(tmp_path), "xla_cache")
        assert os.path.isdir(cache_dir)
        assert jax.config.jax_compilation_cache_dir == cache_dir
    finally:
        # global jax config: don't leak a tmp cache dir into the rest
        # of the test session
        jax.config.update("jax_compilation_cache_dir", prev)


def test_compilation_cache_auto_without_store_raises(monkeypatch):
    from flexflow_tpu.store import enable_compilation_cache

    monkeypatch.delenv("FLEXFLOW_TPU_STORE_DIR", raising=False)
    cfg = FFConfig(batch_size=8, strategy_store="none",
                   compilation_cache="auto")
    with pytest.raises(ValueError, match="no store is configured"):
        enable_compilation_cache(cfg)


# -- op-cost persistence: crash-safe + merge-on-save ------------------------

def test_save_persistent_merges_concurrent_entries(tmp_path):
    from flexflow_tpu.sim.machine_model import SimpleMachineModel
    from flexflow_tpu.sim.simulator import OpCostModel

    path = str(tmp_path / "op_costs.json")
    machine = SimpleMachineModel(devices_per_node=1)
    a = OpCostModel(machine, cache_path=path)
    b = OpCostModel(machine, cache_path=path)  # both loaded empty
    a._persistent["k_a"] = 1.0
    a._dirty = True
    b._persistent["k_b"] = 2.0
    b._dirty = True
    a.save_persistent()
    b.save_persistent()  # must NOT clobber a's entry (merge-on-save)
    with open(path) as f:
        data = json.load(f)
    assert data == {"k_a": 1.0, "k_b": 2.0}
    # our own fresher measurement wins a key collision
    a._persistent["k_b"] = 9.0
    a._dirty = True
    a.save_persistent()
    with open(path) as f:
        assert json.load(f)["k_b"] == 9.0


def test_save_persistent_tolerates_wrong_shape_file(tmp_path):
    from flexflow_tpu.sim.machine_model import SimpleMachineModel
    from flexflow_tpu.sim.simulator import OpCostModel

    path = str(tmp_path / "op_costs.json")
    with open(path, "w") as f:
        f.write("[1, 2, 3]")  # valid JSON, not a {key: float} mapping
    machine = SimpleMachineModel(devices_per_node=1)
    a = OpCostModel(machine, cache_path=path)
    a._persistent["k"] = 1.0
    a._dirty = True
    a.save_persistent()  # must not crash the end of a search
    with open(path) as f:
        assert json.load(f) == {"k": 1.0}


def test_save_persistent_crash_leaves_file_intact(tmp_path, monkeypatch):
    from flexflow_tpu.sim.machine_model import SimpleMachineModel
    from flexflow_tpu.sim.simulator import OpCostModel

    path = str(tmp_path / "op_costs.json")
    machine = SimpleMachineModel(devices_per_node=1)
    a = OpCostModel(machine, cache_path=path)
    a._persistent["k"] = 1.0
    a._dirty = True
    a.save_persistent()

    b = OpCostModel(machine, cache_path=path)
    b._persistent["k2"] = 2.0
    b._dirty = True
    monkeypatch.setattr(os, "replace",
                        lambda *a, **k: (_ for _ in ()).throw(OSError("kill")))
    with pytest.raises(OSError):
        b.save_persistent()
    monkeypatch.undo()
    # the published file never went through a torn state, and the
    # failed writer's tmp was cleaned up
    with open(path) as f:
        assert json.load(f) == {"k": 1.0}
    assert [n for n in os.listdir(str(tmp_path))
            if ".tmp-" in n] == []


def test_cached_search_passthrough_without_store(devices8):
    cfg = FFConfig(batch_size=8, num_devices=2, search_budget=0,
                   strategy_store="none")
    ff = _mlp(cfg)
    calls = []

    def fake_search():
        calls.append(1)
        from flexflow_tpu.strategy import data_parallel_strategy

        return data_parallel_strategy(2)

    s = cached_search(ff, 2, fake_search)
    assert calls == [1]
    assert getattr(s, "search_stats", None) is None  # untouched
