"""GSPMD tensor-parallel paged serving (docs/SERVING.md
"Tensor-parallel replicas"): one replica spans tp chips on a
{"data": 1, "model": tp} mesh — attention heads, FFN channels and the
paged KV block pools' head dims shard over the model axis, so per-chip
KV bytes are 1/tp while the host-owned block-table machinery (prefix
sharing, COW, chunked prefill) is untouched.  The acceptance bar is
greedy TOKEN-IDENTITY against the single-chip gather oracle at every
tp degree, with the pool invariant checker armed at every scheduler
step, plus NamedSharding inspection of the per-chip pool bytes and
fault recovery through the sharding-preserving reset path."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.decoding import make_gpt_decoder
from flexflow_tpu.models.transformer import build_gpt
from flexflow_tpu.serving import ContinuousScheduler

pytestmark = pytest.mark.slow  # compile-heavy: full tier only

V, S, B = 32, 16, 4

# shared-prefix workload: three requests share a full-page prefix (the
# COW + prefix-cache machinery engages), one is cold
PREFIX = [3, 5, 7, 2]
PROMPTS = [PREFIX + [9, 4], PREFIX + [9, 11], PREFIX + [1], [8, 2]]
MNT = [6, 6, 5, 4]


@pytest.fixture(scope="module")
def trained(devices8):
    ff = FFModel(FFConfig(batch_size=B, num_devices=1))
    build_gpt(ff, batch_size=B, seq_length=S, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    rng = np.random.RandomState(0)
    start = rng.randint(0, V, (B, 1))
    step = rng.randint(1, 6, (B, 1))
    seq_ids = (start + step * np.arange(S + 1)) % V
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
    for _ in range(40):
        ff.train_step({"input": ids, "positions": pos}, labels)
    return ff


def make_sched(ff, devices8, tp, **kw):
    kw.setdefault("batch_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_blocks", 12)
    kw.setdefault("check_invariants", True)  # pool audited every step
    return ContinuousScheduler.from_trained(
        ff, devices=devices8[:max(tp, 1)], tp=tp, **kw)


def run_workload(sched):
    try:
        return [sched.generate(p, m, timeout=240.0)
                for p, m in zip(PROMPTS, MNT)]
    finally:
        sched.close()


@pytest.fixture(scope="module")
def oracle(trained, devices8):
    """Single-chip gather formulation: the bit-identity reference every
    tp degree must reproduce token for token."""
    return run_workload(make_sched(trained, devices8, tp=1))


def test_tp_greedy_token_identity_vs_single_chip_oracle(
        trained, devices8, oracle):
    """tp in {2, 4}: head-sharded pools + GSPMD-partitioned decode
    step, gather formulation — greedy completions token-identical to
    the tp=1 oracle on the shared-prefix workload."""
    for tp in (2, 4):
        got = run_workload(make_sched(trained, devices8, tp=tp))
        assert got == oracle, f"tp={tp} diverged from the oracle"


def test_tp_pallas_chunked_prefill_token_identity(
        trained, devices8, oracle):
    """The full acceptance combo at tp=2: prefix sharing + chunked
    prefill + the Pallas paged kernel (shard_map over the head axis),
    still token-identical to the single-chip gather oracle."""
    sched = make_sched(trained, devices8, tp=2, paged_kernel="pallas",
                       prefill_chunk=2)
    stats = None
    try:
        got = [sched.generate(p, m, timeout=240.0)
               for p, m in zip(PROMPTS, MNT)]
        stats = sched.stats()
    finally:
        sched.close()
    assert got == oracle
    # the sharing machinery actually engaged on the sharded pool
    assert stats["prefix_cache"]["hits"] > 0
    assert stats["paged_kernel"]["formulation"] == "pallas"
    assert stats["tp"]["degree"] == 2


def test_pool_sharded_over_heads_per_chip_bytes(trained, devices8):
    """NamedSharding inspection: every layer's K/V block pool is
    [num_blocks, page, h, d] sharded P(None, None, 'model') over a
    2-chip mesh, so each chip holds exactly 1/2 of the pool bytes —
    the headline per-chip KV claim, checked on the actual buffers."""
    from jax.sharding import NamedSharding, PartitionSpec

    sched = make_sched(trained, devices8, tp=2)
    try:
        model = sched.model
        pools = [(name, entries[k])
                 for name, entries in model._state.items()
                 if name.startswith("attn_")
                 for k in ("k_cache", "v_cache")]
        assert len(pools) == 4  # 2 layers x k/v
        for name, pool in pools:
            sh = pool.sharding
            assert isinstance(sh, NamedSharding), (name, sh)
            assert len(sh.device_set) == 2
            assert sh.spec == PartitionSpec(None, None, "model"), name
            for shard in pool.addressable_shards:
                assert shard.data.nbytes * 2 == pool.nbytes
        # the telemetry agrees with the buffers
        tp_block = sched.stats()["tp"]
        assert tp_block["kv_block_bytes_per_chip"] * 2 == \
            tp_block["kv_block_bytes"]
        per_chip_pool = sum(p.nbytes for _, p in pools) // 2
        assert tp_block["kv_pool_bytes_per_chip"] == per_chip_pool
    finally:
        sched.close()


def test_prefix_cache_cow_parity_on_sharded_pool(trained, devices8,
                                                 oracle):
    """Prefix sharing and copy-on-write address only the UNSHARDED
    block/page axes, so they work unchanged on head-sharded physical
    blocks: shared-prefix requests hit the cache, diverge through COW
    copies, and stay token-identical."""
    sched = make_sched(trained, devices8, tp=2)
    stats = None
    try:
        got = [sched.generate(p, m, timeout=240.0)
               for p, m in zip(PROMPTS, MNT)]
        stats = sched.stats()
        sched.pool.check_invariants()
    finally:
        sched.close()
    assert got == oracle
    pc = stats["prefix_cache"]
    assert pc["hits"] > 0 and pc["hit_tokens"] >= len(PREFIX)


def test_fault_recovery_reset_preserves_sharding(trained, devices8,
                                                 oracle):
    """A mid-decode fault on the tp=2 engine: the donated-state reset
    rebuilds ZEROED pools that keep their NamedSharding (a bare
    jnp.zeros would silently gather them onto one chip), and post-fault
    requests are still token-identical to the oracle."""
    from jax.sharding import NamedSharding

    sched = make_sched(trained, devices8, tp=2)
    real_step = sched.model.step
    calls = {"n": 0}

    def flaky_step(tokens, seq_lens, block_tables):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("injected mid-decode fault")
        return real_step(tokens, seq_lens, block_tables)

    sched.model.step = flaky_step
    try:
        hs = [sched.generate_async(p, m)
              for p, m in zip(PROMPTS, MNT)]
        failed = ok = 0
        for h in hs:
            try:
                h.wait(240.0)
                ok += 1
            except RuntimeError:
                failed += 1
        assert failed >= 1  # the in-flight batch died
        # the reset state still spans both chips
        for name, entries in sched.model._state.items():
            for k, v in entries.items():
                assert isinstance(v.sharding, NamedSharding), (name, k)
                assert len(v.sharding.device_set) == 2, (name, k)
        # post-fault decode is still token-identical to the oracle
        for (p, m), want in zip(zip(PROMPTS, MNT), oracle):
            assert sched.generate(p, m, timeout=240.0) == want
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_tp_strategy_served_through_store(trained, devices8, tmp_path):
    """The searched tp decode strategy is keyed by the decode graph x
    the replica mesh fingerprint: a second replica at the same tp
    restores from the store, a different tp degree gets its own key."""
    store = tmp_path / "store"
    old = trained.config.strategy_store
    trained.config.strategy_store = str(store)
    try:
        d1 = make_gpt_decoder(trained, batch_size=2, kv_page_size=4,
                              kv_num_blocks=12, tp=2,
                              devices=devices8[:2])
        assert d1.strategy.search_stats["store_hit"] is False
        d2 = make_gpt_decoder(trained, batch_size=2, kv_page_size=4,
                              kv_num_blocks=12, tp=2,
                              devices=devices8[:2])
        assert d2.strategy.search_stats["store_hit"] is True
        assert d2.strategy.search_stats["store_key"] == \
            d1.strategy.search_stats["store_key"]
        # a different mesh degree is a different key — no false hit
        d4 = make_gpt_decoder(trained, batch_size=2, kv_page_size=4,
                              kv_num_blocks=12, tp=4,
                              devices=devices8[:4])
        assert d4.strategy.search_stats["store_hit"] is False
        assert d4.strategy.search_stats["store_key"] != \
            d1.strategy.search_stats["store_key"]
    finally:
        trained.config.strategy_store = old
