"""End-to-end minimum slice (SURVEY §7 step 4 exit criterion): an MLP
trained data-parallel on the 8-device CPU mesh — layer API -> compile
(DP strategy) -> jitted SPMD step with psum'd grads -> loss decreases."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.fftype import ActiMode


def make_blob_data(n=256, dim=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim) * 3
    y = rng.randint(0, classes, size=n)
    x = centers[y] + rng.randn(n, dim)
    return x.astype(np.float32), y.astype(np.int32)


def test_mlp_dp_loss_decreases(devices8):
    cfg = FFConfig(batch_size=32, epochs=5, learning_rate=0.05, num_devices=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 16], name="x")
    t = ff.dense(x, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    out = ff.softmax(t)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
        devices=devices8,
    )
    xs, ys = make_blob_data()
    history = ff.fit(xs, ys, batch_size=32, epochs=5, verbose=False)
    first, last = history[0], history[-1]
    assert last.sparse_cce_loss < first.sparse_cce_loss
    assert last.accuracy > 0.95


def test_mlp_outputs_match_single_device(devices8):
    """The 8-device DP model must compute the same function as 1-device."""
    import jax

    def build(devs):
        cfg = FFConfig(batch_size=16, num_devices=len(devs), seed=7)
        ff = FFModel(cfg)
        x = ff.create_tensor([16, 8], name="x")
        t = ff.dense(x, 32, activation=ActiMode.TANH)
        t = ff.dense(t, 3)
        ff.compile(devices=devs, seed=7)
        return ff

    ff8 = build(devices8)
    ff1 = build(devices8[:1])
    xs = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y8 = np.asarray(ff8.forward({"x": xs}))
    y1 = np.asarray(ff1.forward({"x": xs}))
    np.testing.assert_allclose(y8, y1, rtol=2e-5, atol=2e-5)


def test_strategy_roundtrip(tmp_path):
    from flexflow_tpu.strategy import Strategy, data_parallel_strategy

    s = data_parallel_strategy(8)
    p = tmp_path / "strategy.json"
    s.save(str(p))
    s2 = Strategy.load(str(p))
    assert s2.mesh_axes == {"data": 8}
    assert s2.edge_ops["__inputs__"][0][0] == "repartition"
