"""Cross-replica KV block streaming (serving/kv_transfer.py): the FFKV
wire format's per-block crc verification (a torn payload admits only
its intact prefix, a mangled header admits nothing), content-keyed
streams, the in-process and blob-store fabrics, the --kv-transfer
resolver gate, and the KVMigrator pipeline's exactly-once on_done
contract — including the close() drain that fails jobs the worker
never reached."""
import threading

import numpy as np
import pytest

from flexflow_tpu.obs.metrics import MetricsRegistry
from flexflow_tpu.serving.kv_pool import KVPool
from flexflow_tpu.serving.kv_transfer import (
    BlobStoreFabric, InProcessFabric, KVMigrator, KVTransferError,
    content_key, pack_kv_blocks, resolve_kv_transfer, unpack_kv_blocks)


def _blocks(n, seed=0, shape=(4, 2)):
    rng = np.random.RandomState(seed)
    return [{"attn_0/k": rng.randn(*shape).astype(np.float32),
             "attn_0/v": rng.randn(*shape).astype(np.float32)}
            for _ in range(n)]


def _pages(prompt, page):
    return [list(prompt[j * page:(j + 1) * page])
            for j in range(len(prompt) // page)]


PROMPT = [3, 5, 7, 2, 9, 4, 1, 8]
PAGE = 4


# -- wire format ---------------------------------------------------------

def test_pack_unpack_roundtrip():
    blocks = _blocks(2)
    data = pack_kv_blocks(_pages(PROMPT, PAGE), blocks, PAGE)
    got, complete = unpack_kv_blocks(data, PROMPT)
    assert complete and len(got) == 2
    for want, have in zip(blocks, got):
        for k in want:
            np.testing.assert_array_equal(want[k], have[k])


def test_truncated_payload_admits_verified_prefix_only():
    blocks = _blocks(2)
    data = pack_kv_blocks(_pages(PROMPT, PAGE), blocks, PAGE)
    torn = data[:len(data) - 8]  # BLOB_PARTIAL_UPLOAD shape: put "ok"
    got, complete = unpack_kv_blocks(torn, PROMPT)
    assert not complete and len(got) == 1  # block 0 intact, 1 torn
    np.testing.assert_array_equal(got[0]["attn_0/k"],
                                  blocks[0]["attn_0/k"])


def test_corrupt_block_stops_the_walk():
    data = bytearray(pack_kv_blocks(_pages(PROMPT, PAGE), _blocks(2),
                                    PAGE))
    data[-4] ^= 0xFF  # flip a byte inside the LAST block's payload
    got, complete = unpack_kv_blocks(bytes(data), PROMPT)
    assert not complete and len(got) == 1


def test_foreign_prompt_rejected_per_block():
    """The header's token pages are checked against the prompt the
    stream claims to serve — a payload for a different prompt can never
    be admitted as this one's prefix."""
    data = pack_kv_blocks(_pages(PROMPT, PAGE), _blocks(2), PAGE)
    other = [9] * len(PROMPT)
    got, complete = unpack_kv_blocks(data, other)
    assert not complete and got == []


def test_mangled_header_raises():
    data = pack_kv_blocks(_pages(PROMPT[:PAGE], PAGE), _blocks(1), PAGE)
    with pytest.raises(KVTransferError, match="magic"):
        unpack_kv_blocks(b"NOPE" + data[4:], PROMPT)
    with pytest.raises(KVTransferError, match="header"):
        unpack_kv_blocks(data[:12], PROMPT)  # magic ok, header cut
    mangled = bytearray(data)
    mangled[10] ^= 0xFF  # inside the JSON header
    with pytest.raises(KVTransferError):
        unpack_kv_blocks(bytes(mangled), PROMPT)


def test_empty_stream_roundtrip():
    data = pack_kv_blocks([], [], PAGE)
    got, complete = unpack_kv_blocks(data, PROMPT)
    assert complete and got == []


def test_content_key_is_prefix_content_address():
    k1 = content_key(PROMPT, 2, PAGE)
    k2 = content_key(list(PROMPT) + [1, 2], 2, PAGE)  # same 2 blocks
    k3 = content_key([9] + PROMPT[1:], 2, PAGE)
    assert k1 == k2 and k1 != k3
    assert content_key(PROMPT, 1, PAGE) != k1  # depth is part of the key


# -- fabrics -------------------------------------------------------------

def test_inprocess_fabric_counts():
    fab = InProcessFabric()
    data = pack_kv_blocks(_pages(PROMPT[:PAGE], PAGE), _blocks(1), PAGE)
    assert fab.transfer("k", data) == data
    assert fab.stats() == {"transfers": 1, "bytes_moved": len(data)}


def test_blobstore_fabric_roundtrip_and_cleanup(tmp_path):
    from flexflow_tpu.store.blobstore import LocalBlobStore

    store = LocalBlobStore(str(tmp_path))
    fab = BlobStoreFabric(store, prefix="kvstream/")
    data = pack_kv_blocks(_pages(PROMPT[:PAGE], PAGE), _blocks(1), PAGE)
    assert fab.transfer("abc", data) == data
    assert fab.kind == "blob" and fab.stats()["transfers"] == 1
    assert store.list("kvstream/") == []  # best-effort delete ran


def test_resolve_kv_transfer_gate(tmp_path):
    assert resolve_kv_transfer("inproc").kind == "inproc"
    assert resolve_kv_transfer("", root=None).kind == "inproc"
    assert resolve_kv_transfer("blob", root=str(tmp_path)).kind == "blob"
    with pytest.raises(ValueError, match="blob store"):
        resolve_kv_transfer("blob")
    with pytest.raises(ValueError, match="unknown kv transfer"):
        resolve_kv_transfer("ftp")


# -- migrator pipeline ---------------------------------------------------

class _Target:
    """ContinuousScheduler-shaped import surface: a real KVPool plus a
    model recording import_block writes; run_on_worker runs inline (the
    test thread IS the worker)."""

    def __init__(self, num_blocks=9, page=PAGE):
        self.pool = KVPool(num_blocks=num_blocks, page_size=page,
                           max_blocks_per_seq=4)
        self.imported = {}
        self.model = self

    def import_block(self, block, arrays):
        self.imported[block] = arrays

    def run_on_worker(self, fn, on_dropped=None):
        fn()


def _wait(pred, timeout=5.0):
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_migrator_success_adopts_and_counts():
    reg = MetricsRegistry()
    mig = KVMigrator(InProcessFabric(), registry=reg)
    target = _Target()
    done = []
    try:
        mig.migrate(prompt=PROMPT, pages=_pages(PROMPT, PAGE),
                    blocks=_blocks(2), page_size=PAGE, target=target,
                    on_done=lambda ok: done.append(ok))
        assert _wait(lambda: done)
    finally:
        mig.close()
    assert done == [True]
    assert len(target.imported) == 2
    assert target.pool.prefix_stats()["imported_blocks"] == 2
    # the adopted prefix is a real cache hit for the migrated prompt
    assert target.pool.cached_prefix_tokens(PROMPT) == len(PROMPT)
    assert reg.counter("serving/kv_migration_done").value == 1
    assert reg.counter("serving/kv_migration_blocks").value == 2
    assert mig.stats()["completed"] == 1


def test_migrator_torn_stream_partial_adopt_counts_failed():
    """A fabric landing truncated bytes: the verified prefix block
    still adopts (a prefix of a prefix is a prefix) but the migration
    counts FAILED — the front re-prefills the remainder."""
    class TearingFabric(InProcessFabric):
        def transfer(self, key, data):
            return super().transfer(key, data)[:-8]

    reg = MetricsRegistry()
    mig = KVMigrator(TearingFabric(), registry=reg)
    target = _Target()
    done = []
    try:
        mig.migrate(prompt=PROMPT, pages=_pages(PROMPT, PAGE),
                    blocks=_blocks(2), page_size=PAGE, target=target,
                    on_done=lambda ok: done.append(ok))
        assert _wait(lambda: done)
    finally:
        mig.close()
    assert done == [False]
    assert len(target.imported) == 1
    assert target.pool.cached_prefix_tokens(PROMPT) == PAGE
    assert reg.counter("serving/kv_migration_failed").value == 1


def test_migrator_fabric_error_fails_once():
    class DeadFabric(InProcessFabric):
        def transfer(self, key, data):
            raise RuntimeError("fabric down")

    reg = MetricsRegistry()
    mig = KVMigrator(DeadFabric(), registry=reg)
    target = _Target()
    done = []
    try:
        mig.migrate(prompt=PROMPT, pages=_pages(PROMPT, PAGE),
                    blocks=_blocks(2), page_size=PAGE, target=target,
                    on_done=lambda ok: done.append(ok))
        assert _wait(lambda: done)
    finally:
        mig.close()
    assert done == [False] and target.imported == {}
    assert reg.counter("serving/kv_migration_failed").value == 1


def test_migrator_failed_device_write_unwinds_adoption():
    class ExplodingTarget(_Target):
        def import_block(self, block, arrays):
            raise RuntimeError("device write failed")

    mig = KVMigrator(InProcessFabric())
    target = ExplodingTarget()
    done = []
    try:
        mig.migrate(prompt=PROMPT, pages=_pages(PROMPT, PAGE),
                    blocks=_blocks(2), page_size=PAGE, target=target,
                    on_done=lambda ok: done.append(ok))
        assert _wait(lambda: done)
    finally:
        mig.close()
    assert done == [False]
    # drop_adopted unwound: no admission can map the unwritten blocks
    assert target.pool.cached_prefix_tokens(PROMPT) == 0
    target.pool.check_invariants()


def test_migrator_close_drains_pending_on_done():
    """Jobs queued but never reached by the worker must still fire
    their on_done — a front-side request would otherwise wait forever
    on a migrator that is gone."""
    mig = KVMigrator(InProcessFabric())
    # retire the worker first so queued jobs are provably unreached
    mig._stop.set()
    mig._jobs.put(None)
    mig._worker.join(timeout=5.0)
    done = []
    mig.migrate(prompt=PROMPT, pages=_pages(PROMPT[:PAGE], PAGE),
                blocks=_blocks(1), page_size=PAGE, target=_Target(),
                on_done=lambda ok: done.append(ok))
    mig.close()
    assert done == [False]
    assert mig.stats()["failed"] == 1


def test_migrator_on_done_exception_never_kills_worker():
    mig = KVMigrator(InProcessFabric())
    target = _Target()
    done = []
    try:
        mig.migrate(prompt=PROMPT, pages=_pages(PROMPT[:PAGE], PAGE),
                    blocks=_blocks(1), page_size=PAGE, target=target,
                    on_done=lambda ok: (_ for _ in ()).throw(
                        RuntimeError("bad hook")))
        mig.migrate(prompt=PROMPT, pages=_pages(PROMPT[:PAGE], PAGE),
                    blocks=_blocks(1), page_size=PAGE, target=_Target(),
                    on_done=lambda ok: done.append(ok))
        assert _wait(lambda: done)  # the second job still completes
    finally:
        mig.close()
    assert done == [True]
