"""Tier-1 guard: bench_manifest.json's frozen schema must match what
bench.py actually emits — a new/renamed/removed leg without a manifest
entry + version bump silently breaks round-over-round comparability,
so it fails HERE instead."""
import ast
import json
import os
import re

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    with open(os.path.join(_ROOT, "bench_manifest.json")) as f:
        manifest = json.load(f)
    with open(os.path.join(_ROOT, "bench.py")) as f:
        source = f.read()
    return manifest, source


def _emitted_legs(source):
    """The keys of the `"legs": {...}` dict literal main() prints —
    pulled from the AST so formatting changes can't fool the guard."""
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        keys = [k.value for k in node.keys
                if isinstance(k, ast.Constant)]
        if "legs" in keys:
            legs_value = node.values[keys.index("legs")]
            assert isinstance(legs_value, ast.Dict), \
                "main()'s \"legs\" entry must be a dict literal"
            return {k.value for k in legs_value.keys
                    if isinstance(k, ast.Constant)}
    raise AssertionError("no \"legs\" dict literal found in bench.py")


def test_manifest_version_matches_emitted_legs():
    manifest, source = _load()
    emitted = _emitted_legs(source)
    frozen = set(manifest["legs"])
    assert emitted == frozen, (
        f"bench.py emits {sorted(emitted)} but bench_manifest.json "
        f"v{manifest['version']} freezes {sorted(frozen)} — add the "
        "manifest entry (with a note) and bump the version"
    )


def test_manifest_version_note_names_current_version():
    manifest, _ = _load()
    assert manifest["version_note"].startswith(
        f"v{manifest['version']}:"), (
        "version_note must lead with the current version's delta "
        f"(expected a 'v{manifest['version']}:' prefix)"
    )


def test_every_referenced_leg_config_exists():
    """Every MANIFEST["legs"]["name"] lookup in bench.py resolves."""
    manifest, source = _load()
    referenced = set(re.findall(
        r'MANIFEST\["legs"\]\["(\w+)"\]', source))
    missing = referenced - set(manifest["legs"])
    assert not missing, (
        f"bench.py reads manifest legs {sorted(missing)} that "
        "bench_manifest.json does not define"
    )


def test_bench_output_carries_manifest_version():
    _, source = _load()
    assert '"manifest_version": MANIFEST["version"]' in source


def test_serving_paged_kernel_leg_keys_frozen():
    """The v19 gather-vs-pallas leg is only round-over-round comparable
    if its workload geometry stays pinned: every TPU-shape key
    bench_serving_paged_kernel reads must exist, and it must mirror the
    serving_prefix leg's workload fields (same shared-prefix pitch, so
    the two legs' tokens/s stay cross-readable)."""
    manifest, _ = _load()
    leg = manifest["legs"]["serving_paged_kernel"]
    needed = {"vocab", "max_seq", "hidden", "layers", "heads",
              "intermediate", "slots", "kv_page_size", "requests",
              "offered_rps", "prefill_chunk", "num_prefixes",
              "prefix_len", "tail_range", "max_new_range"}
    assert needed <= set(leg), sorted(needed - set(leg))
    prefix_leg = manifest["legs"]["serving_prefix"]
    assert needed <= set(prefix_leg)


def test_serving_gspmd_leg_keys_frozen():
    """The v20 tensor-parallel leg stays round-over-round comparable
    only with its workload geometry pinned: every TPU-shape key
    bench_serving_gspmd reads must exist, it must mirror the
    serving_prefix workload fields (same shared-prefix pitch), and the
    tp degree itself is frozen — a silent tp bump would change the
    equal-per-chip-bytes capacity claim."""
    manifest, _ = _load()
    leg = manifest["legs"]["serving_gspmd"]
    needed = {"vocab", "max_seq", "hidden", "layers", "heads",
              "intermediate", "slots", "kv_page_size", "requests",
              "offered_rps", "prefill_chunk", "num_prefixes",
              "prefix_len", "tail_range", "max_new_range", "tp"}
    assert needed <= set(leg), sorted(needed - set(leg))
    assert leg["tp"] >= 2  # a tp=1 "replica mesh" measures nothing
    assert leg["heads"] % leg["tp"] == 0  # heads shard over the mesh


def test_serving_spec_leg_keys_frozen():
    """The v22 speculative-decoding leg is round-over-round comparable
    only with its workload AND drafter geometry pinned: every TPU-shape
    key bench_serving_spec reads must exist, the phrase pool must stay
    small enough to memorize (acceptance rates move with it), the draft
    model must actually be smaller than the target (or the draft tier
    measures nothing), and spec_k must clear the accepted-per-round bar
    it is asserted against."""
    manifest, _ = _load()
    leg = manifest["legs"]["serving_spec"]
    needed = {"vocab", "max_seq", "hidden", "layers", "heads",
              "intermediate", "slots", "kv_page_size", "requests",
              "offered_rps", "prefill_chunk", "spec_k",
              "num_templates", "phrases_per_template", "phrase_len",
              "prompt_phrases_range", "max_new_range",
              "draft_hidden", "draft_layers", "draft_heads",
              "draft_intermediate", "train_steps"}
    assert needed <= set(leg), sorted(needed - set(leg))
    # the accepted-per-round > 1.5 assertion needs headroom above 1
    assert leg["spec_k"] >= 2
    # n-gram lookup needs phrases longer than the trigram window
    assert leg["phrase_len"] >= 4
    # the draft tier only measures something if the drafter is smaller
    assert leg["draft_hidden"] < leg["hidden"]
    assert leg["draft_layers"] < leg["layers"]
    # verify windows (prompt + max_new + k) must fit the position table
    max_prompt = leg["prompt_phrases_range"][1] * leg["phrase_len"]
    assert (max_prompt + leg["max_new_range"][1] + leg["spec_k"]
            <= leg["max_seq"])


def test_serving_disagg_leg_keys_frozen():
    """The v21 disaggregated-fleet leg is round-over-round comparable
    only with its workload geometry AND its cost-model knobs pinned:
    every TPU-shape key bench_serving_disagg reads must exist, the
    sub-page mix must actually sit below the page size (or the
    guaranteed re-prefill side vanishes), and the fabric/cap are
    frozen — a silent change would move the migrate/re-prefill
    crossover."""
    manifest, _ = _load()
    leg = manifest["legs"]["serving_disagg"]
    needed = {"vocab", "max_seq", "hidden", "layers", "heads",
              "intermediate", "slots", "kv_page_size", "requests",
              "offered_rps", "prefill_chunk", "num_prefixes",
              "prefix_len", "tail_range", "max_new_range",
              "subpage_requests", "subpage_len_range", "roles",
              "kv_transfer", "migration_cost_cap"}
    assert needed <= set(leg), sorted(needed - set(leg))
    # the sub-page prompts must stay sub-page: randint's exclusive
    # high bound at most the page size
    assert leg["subpage_len_range"][1] <= leg["kv_page_size"]
    # multi-page shared prefixes: the migrate side needs blocks to ship
    assert leg["prefix_len"] >= 2 * leg["kv_page_size"]
    assert leg["roles"] == "prefill=1,decode=1"
    assert leg["migration_cost_cap"] > 0


def test_serving_trace_leg_keys_frozen():
    """The v23 request-tracing leg compares a traced fleet against its
    traced-off twin, so its workload must keep BOTH dispatcher
    decisions and the speculative path reachable: every TPU-shape key
    bench_serving_trace reads must exist, the shortest repetitive
    prompt must still span a full KV page (or the migrate side
    vanishes and the connected-tree assertion never sees a migration
    child), the sub-page mix must stay sub-page, and the sample rate
    must trace every request — the one-tree-per-completed-request
    assertion is only meaningful at sample 1.0."""
    manifest, _ = _load()
    leg = manifest["legs"]["serving_trace"]
    needed = {"vocab", "max_seq", "hidden", "layers", "heads",
              "intermediate", "slots", "kv_page_size", "requests",
              "offered_rps", "prefill_chunk", "spec_k",
              "num_templates", "phrases_per_template", "phrase_len",
              "prompt_phrases_range", "max_new_range",
              "subpage_requests", "subpage_len_range", "roles",
              "trace_sample"}
    assert needed <= set(leg), sorted(needed - set(leg))
    # migrate side: the shortest prompt must own >= 1 full page
    assert (leg["prompt_phrases_range"][0] * leg["phrase_len"]
            >= leg["kv_page_size"])
    # re-prefill side: sub-page prompts must stay sub-page
    assert leg["subpage_len_range"][1] <= leg["kv_page_size"]
    assert leg["roles"] == "prefill=1,decode=1"
    assert leg["trace_sample"] == 1.0
    # n-gram drafts need the trigram window inside one phrase
    assert leg["phrase_len"] >= 4 and leg["spec_k"] >= 2


def test_serving_handoff_leg_keys_frozen():
    """The v24 resumable-handoff leg pins a LONG generation mid-decode
    and drains its holder, so the geometry must keep that pin
    reachable: every TPU-shape key bench_serving_handoff reads must
    exist, the pinned generation must both fit max_seq and span
    multiple KV pages (or the stream ships no full blocks and the
    partial-tail path is all the leg measures), and it must dwarf the
    background replies — a "long" generation shorter than the
    background mix can complete before the drain lands."""
    manifest, _ = _load()
    leg = manifest["legs"]["serving_handoff"]
    needed = {"vocab", "max_seq", "hidden", "layers", "heads",
              "intermediate", "slots", "kv_page_size", "prefill_chunk",
              "background_requests", "background_len_range",
              "background_max_new_range", "long_prompt_len",
              "long_max_new"}
    assert needed <= set(leg), sorted(needed - set(leg))
    # the pinned sequence must fit the engine...
    assert leg["long_prompt_len"] + leg["long_max_new"] <= leg["max_seq"]
    # ...and span multiple pages so full blocks actually stream
    assert leg["long_prompt_len"] + leg["long_max_new"] \
        >= 4 * leg["kv_page_size"]
    # the pin only holds if the generation outlives the drain call
    assert leg["long_max_new"] >= 4 * leg["background_max_new_range"][1]
