"""SLO-driven autoscaling of the serving front (serving/autoscaler.py)
plus the replica drain lifecycle (READY -> DRAINING -> RETIRED) it
rides on: policy hysteresis/cooldown/bounds as pure unit tests, real
scale-up/scale-down against the fake step model, drain races (late
submit, wedged DRAINING replica, death-while-draining), token identity
of requests completed on a draining engine, overload admission
control, SIGTERM-grace terminate(), and the HTTP surfaces
(/v2/health draining state, /v2/stats autoscaler block)."""
import json
import os
import signal
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.obs.metrics import MetricsRegistry
from flexflow_tpu.serving import (
    ContinuousScheduler,
    ServiceUnavailable,
    ServingAutoscaler,
    ServingFront,
)
from flexflow_tpu.serving.server import serve_http

V = 16
NO_SLEEP = lambda s: None  # noqa: E731


class FakeStepModel:
    """Deterministic PagedKVDecodeModel stand-in: next token is
    (input + 1) % vocab as one-hot logits — greedy expectations are
    closed-form, so drain TOKEN-IDENTITY is directly checkable."""

    def __init__(self, batch_slots=2, max_seq=32, page_size=4,
                 delay_s=0.0):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = 1 + batch_slots * self.max_blocks_per_seq
        self.vocab = V
        self.delay_s = delay_s
        self.steps = 0

    def reset(self):
        pass

    def step(self, tokens, seq_lens, block_tables):
        self.steps += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        logits = np.zeros((self.batch_slots, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits


def expected(prompt, mnt):
    out = list(prompt)
    t = prompt[-1]
    for _ in range(mnt):
        t = (t + 1) % V
        out.append(t)
    return out


def factory(replica_id, survivors=None):
    return FakeStepModel()


def _wait_for(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def make_scaler(**kw):
    """Autoscaler around a minimal fake front — enough for the PURE
    policy surface (decide/target_replicas), which never touches the
    front."""
    front = types.SimpleNamespace(registry=None)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    return ServingAutoscaler(front, **kw)


def sig(**kw):
    s = {"t": 100.0, "live": 2, "draining": 0, "restarting": 0,
         "fleet": 2,
         "queue_depth": 0, "outstanding": 0, "queue_per_replica": 0.0,
         "p99_ttft_s": 0.0, "kv_occupancy": 0.0}
    s.update(kw)
    return s


# -- policy: pure decide() unit tests -----------------------------------

def test_policy_holds_within_bands():
    sc = make_scaler()
    action, reason = sc.decide(sig(queue_per_replica=1.0))
    assert action == "hold" and "within bands" in reason


def test_policy_scales_up_on_queue_breach():
    sc = make_scaler(queue_high=4.0)
    action, reason = sc.decide(sig(queue_per_replica=5.0))
    assert action == "up" and "queue/replica" in reason


def test_policy_scales_up_on_slo_breach():
    sc = make_scaler(slo_ttft_s=0.5)
    action, reason = sc.decide(sig(p99_ttft_s=0.9, outstanding=1))
    assert action == "up" and "TTFT" in reason


def test_policy_stale_ttft_cannot_pin_idle_fleet():
    """The TTFT window is count-based: after a burst ends, no new
    completions refresh it.  An IDLE fleet (no queue, nothing
    outstanding) must neither scale up on the stale p99 nor be blocked
    from draining by it."""
    sc = make_scaler(slo_ttft_s=0.5)
    stale = sig(p99_ttft_s=9.9)  # way past SLO, but queue=outstanding=0
    action, _ = sc.decide(stale)
    assert action == "down"  # calm + above min -> drains despite p99
    action, _ = sc.decide(sig(p99_ttft_s=9.9, live=1, fleet=1))
    assert action == "hold"  # at min: nothing to drain, never "up"


def test_policy_scales_up_on_kv_pressure():
    sc = make_scaler(kv_high=0.9)
    action, reason = sc.decide(sig(kv_occupancy=0.95))
    assert action == "up" and "KV occupancy" in reason


def test_policy_hysteresis_band_between_up_and_down():
    """Signals BETWEEN the bands (above queue_low, below queue_high)
    hold — an oscillation around either threshold can't flap the
    fleet."""
    sc = make_scaler(queue_low=0.5, queue_high=4.0)
    for q in (0.6, 1.0, 2.0, 3.9):
        action, _ = sc.decide(sig(queue_per_replica=q))
        assert action == "hold", q
    assert sc.decide(sig(queue_per_replica=0.1))[0] == "down"
    assert sc.decide(sig(queue_per_replica=4.1))[0] == "up"


def test_policy_down_requires_every_signal_calm():
    sc = make_scaler(slo_ttft_s=1.0)
    # queue calm but TTFT at 80% of SLO under live traffic: not
    # comfortable -> hold
    action, _ = sc.decide(sig(queue_per_replica=0.0, p99_ttft_s=0.8,
                              outstanding=1))
    assert action == "hold"
    action, _ = sc.decide(sig(queue_per_replica=0.0, p99_ttft_s=0.1,
                              outstanding=1))
    assert action == "down"


def test_policy_respects_bounds():
    sc = make_scaler(min_replicas=2, max_replicas=3)
    # at max: an up signal holds (with the reason naming the bound)
    action, reason = sc.decide(
        sig(live=3, fleet=3, queue_per_replica=10.0))
    assert action == "hold" and "max_replicas" in reason
    # at min: calm holds
    action, _ = sc.decide(sig(live=2, fleet=2, queue_per_replica=0.0))
    assert action == "hold"


def test_policy_restores_min_replicas_after_permanent_death():
    """A permanently-dead replica leaves the fleet below its
    contracted floor with no load signal to grow it back: the policy
    must scale up on the bound itself, not wait for queue pressure."""
    sc = make_scaler(min_replicas=2, max_replicas=4)
    action, reason = sc.decide(sig(live=1, fleet=2))  # calm traffic
    assert action == "up" and "min_replicas" in reason
    # a restarting replica is coming back on its own: no spawn
    action, _ = sc.decide(sig(live=1, restarting=1, fleet=2))
    assert action == "hold"


def test_policy_max_counts_restarting_replicas():
    """A restarting replica returns live after its rebuild: scaling up
    past it would grow the fleet to max_replicas+1 live engines with
    no corrective path (the calm condition never holds under the load
    that drove the up signal)."""
    sc = make_scaler(min_replicas=1, max_replicas=2)
    action, reason = sc.decide(
        sig(live=1, restarting=1, fleet=2, queue_per_replica=10.0))
    assert action == "hold" and "max_replicas" in reason
    # a permanently-dead replica holds no engine and never returns —
    # it must NOT consume headroom (restarting=0 excludes it)
    action, _ = sc.decide(
        sig(live=1, restarting=0, fleet=2, queue_per_replica=10.0))
    assert action == "up"


def test_policy_cooldown_and_drain_in_flight_hold():
    sc = make_scaler(cooldown_s=5.0)
    sc.last_action_t = 98.0  # 2s ago at t=100
    action, reason = sc.decide(sig(queue_per_replica=10.0))
    assert action == "hold" and reason == "cooldown"
    sc.last_action_t = None
    sc._draining = (object(), 0.0)
    action, reason = sc.decide(sig(queue_per_replica=10.0))
    assert action == "hold" and reason == "drain in flight"


def test_policy_zero_live_is_supervisions_problem():
    sc = make_scaler()
    action, reason = sc.decide(
        sig(live=0, fleet=2, queue_per_replica=50.0))
    assert action == "hold" and "no live replicas" in reason


def test_scaler_validates_construction():
    with pytest.raises(ValueError, match="min_replicas"):
        make_scaler(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        make_scaler(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="hysteresis"):
        make_scaler(queue_low=4.0, queue_high=4.0)
    with pytest.raises(ValueError, match="interval_s"):
        make_scaler(interval_s=0)
    with pytest.raises(ValueError, match="drain_timeout_s"):
        make_scaler(drain_timeout_s=0)


# -- scale-up / scale-down against the real front -----------------------

def test_scale_up_on_backlog_and_new_replica_serves():
    tm = [0.0]
    reg = MetricsRegistry()
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.01),
        num_replicas=1, registry=reg, sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 3, cooldown_s=5.0,
                           time_fn=lambda: tm[0], registry=reg)
    try:
        hs = [front.generate_async([1 + i], 6) for i in range(12)]
        entry = sc.tick()
        assert entry["action"] == "up"
        assert len(front.replicas) == 2
        assert sc.scale_ups == 1
        # cooldown: an immediate second tick holds even under backlog
        tm[0] += 1.0
        assert sc.tick()["action"] == "hold"
        for h, i in zip(hs, range(12)):
            assert h.wait(30.0) == expected([1 + i], 6)
        # both replicas served
        st = front.stats()
        assert all(r["batches_run"] > 0 for r in st["replicas"])
        assert reg.counter("serving/replicas_added").value == 1
        assert reg.counter("serving/autoscaler_up").value == 1
    finally:
        front.close()


def test_scale_down_drains_least_loaded_and_retires():
    tm = [0.0]
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 4, cooldown_s=0.0,
                           time_fn=lambda: tm[0])
    try:
        assert front.generate([1, 2], 4, timeout=30.0) == \
            expected([1, 2], 4)
        entry = sc.tick()
        assert entry["action"] == "down"
        assert sc.scale_downs == 1
        assert _wait_for(lambda: len(front.replicas) == 1)
        assert len(front.retired) == 1
        assert front.retired[0].state == "retired"
        # the retired engine released its scheduler (and KV pool)
        assert front.retired[0].scheduler is None
        # the survivor still serves
        assert front.generate([7], 3, timeout=30.0) == expected([7], 3)
        # at min_replicas now: calm no longer drains
        tm[0] += 10.0
        sc._sweep_drain()
        assert sc.tick()["action"] == "hold"
        assert len(front.replicas) == 1
    finally:
        front.close()


def test_drain_completes_inflight_token_identical():
    """Scale-down drain with requests mid-generation: the dispatcher
    stops routing to the draining replica, its in-flight slots run to
    completion TOKEN-IDENTICALLY (closed-form greedy check), nothing
    is requeued or lost."""
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.01),
        num_replicas=2, sleep=NO_SLEEP)
    try:
        reqs = [([1 + i], 12) for i in range(4)]
        hs = [front.generate_async(p, m) for p, m in reqs]
        # wait until work is actually in flight, then drain the busier
        # replica mid-generation
        assert _wait_for(
            lambda: any(r.outstanding for r in front.replicas))
        target = max(front.replicas, key=lambda r: r.outstanding)
        assert front.drain_replica(target)
        assert target.state in ("draining", "retired")
        for h, (p, m) in zip(hs, reqs):
            assert h.wait(30.0) == expected(p, m)  # token-identical
        assert front.requeued_requests == 0  # graceful, not requeue
        assert _wait_for(lambda: target.state == "retired")
        assert front.health()["replicas_retired"] == 1
    finally:
        front.close()


def test_retired_replica_releases_supervisor_thread():
    """A cleanly drained replica must not park its supervisor thread
    on _death_evt until process exit — front.close() only sweeps fleet
    members, so each scale-down would otherwise leak one daemon
    thread."""
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP)
    try:
        r = front.replicas[0]
        assert front.drain_replica(r)
        assert _wait_for(lambda: r.state == "retired")
        assert _wait_for(lambda: not r._supervisor.is_alive())
    finally:
        front.close()


def test_retired_history_bounded_counters_preserved():
    """front.retired is a bounded window: a long-lived autoscaled
    front cycles replicas indefinitely, and an unbounded list grows
    stats() cost and memory forever.  Dropped replicas must keep
    counting in the aggregates."""
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    front.retired_keep = 2
    try:
        for i in range(4):
            r = front.add_replica()
            assert front.generate([1 + i], 3, timeout=30.0) == \
                expected([1 + i], 3)
            assert front.drain_replica(r)
            assert _wait_for(lambda: r.state in ("retired", "closed"))
        assert _wait_for(lambda: len(front.retired) <= 2)
        stats = front.stats()
        assert stats["replicas_retired"] == 4  # dropped still counted
        assert front.health()["replicas_retired"] == 4
        # work done on since-dropped replicas stays in the aggregates
        assert stats["tokens_generated"] == front.tokens_generated
        assert front.tokens_generated >= 4 * 3
    finally:
        front.close()


def test_add_replica_aborts_when_close_races_build():
    """close() sweeping the fleet while add_replica is mid-compile:
    the late append must be refused and the fresh engine closed, not
    leaked into a closed front."""
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    orig = front._build_replica
    built = []

    def build_then_lose_race(rid, fault_plan=None, role="mixed"):
        r = orig(rid, fault_plan=fault_plan, role=role)
        built.append(r)
        front.close()  # the fleet sweep happens while we "compiled"
        return r

    front._build_replica = build_then_lose_race
    with pytest.raises(RuntimeError, match="closing"):
        front.add_replica()
    (replica,) = built
    assert replica.state == "closed"
    assert replica.scheduler is None
    assert replica not in front.replicas


def test_drain_refuses_nonlive_and_double_drain():
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP)
    try:
        r = front.replicas[0]
        assert front.drain_replica(r) is True
        # a second drain of the same replica is a no-op refusal
        assert front.drain_replica(r) is False
        assert _wait_for(lambda: r.state == "retired")
        assert front.drain_replica(r) is False
    finally:
        front.close()


# -- drain races --------------------------------------------------------

def test_sched_drain_races_late_submit():
    """A submit racing drain() either refuses synchronously (the
    caller requeues elsewhere) or is accepted and runs to full
    token-identical completion — never accepted-then-dropped."""
    for trial in range(5):
        sched = ContinuousScheduler(FakeStepModel(batch_slots=2))
        drained = threading.Event()
        accepted = []
        refused = []

        def submitter(i):
            try:
                h = sched.generate_async([1 + i], 6)
                accepted.append((h, [1 + i], 6))
            except RuntimeError:
                refused.append(i)

        threads = [threading.Thread(target=submitter, args=(i,))
                   for i in range(4)]
        for i, t in enumerate(threads):
            if i == 2:  # flip the drain mid-burst
                sched.drain(on_drained=drained.set)
            t.start()
        for t in threads:
            t.join()
        # everything ACCEPTED completes token-identically; the drain
        # still finishes (the worker exits once the queue is empty)
        for h, p, m in accepted:
            assert h.wait(30.0) == expected(p, m)
        assert drained.wait(10.0)
        assert not sched.worker_alive
        # post-drain the engine refuses like a closed one
        with pytest.raises(RuntimeError):
            sched.generate_async([1], 2)
        assert len(accepted) + len(refused) == 4
        sched.close()


def test_front_close_bounded_with_wedged_draining_replica():
    """close(timeout_s=) with a replica wedged in DRAINING (its decode
    step blocks forever): shutdown stays bounded."""

    def wedged_factory(replica_id, survivors=None):
        return FakeStepModel(delay_s=30.0)

    front = ServingFront(wedged_factory, num_replicas=2,
                         sleep=NO_SLEEP, close_timeout_s=0.5)
    h = front.generate_async([1, 2], 4)
    time.sleep(0.2)  # let a step wedge
    target = max(front.replicas, key=lambda r: r.outstanding)
    front.drain_replica(target)
    assert target.state == "draining"  # wedged: drain can't finish
    t0 = time.monotonic()
    front.close(timeout_s=0.5)
    assert time.monotonic() - t0 < 10.0
    with pytest.raises(Exception):
        h.wait(1.0)


def test_autoscaler_force_retires_wedged_drain():
    """A drain that outlives drain_timeout_s is force-retired: the
    engine closes (bounded), the in-flight request requeues onto the
    survivor, and the fleet shrinks anyway."""
    tm = [0.0]

    def mixed_factory(replica_id, survivors=None):
        # replica 0 wedges mid-step; later builds are healthy
        return FakeStepModel(delay_s=20.0 if replica_id == 0 else 0.0)

    front = ServingFront(mixed_factory, num_replicas=2, sleep=NO_SLEEP,
                         close_timeout_s=0.2, retry_backoff=0.0)
    sc = ServingAutoscaler(front, 1, 4, cooldown_s=0.0,
                           drain_timeout_s=5.0, time_fn=lambda: tm[0])
    try:
        h = front.generate_async([1, 2], 4)
        assert _wait_for(
            lambda: front.replicas[0].outstanding > 0)
        wedged = front.replicas[0]
        assert front.drain_replica(wedged)
        sc._draining = (wedged, tm[0])
        tm[0] += 10.0  # past the drain deadline
        sc.tick()
        assert sc.forced_retires == 1
        assert _wait_for(lambda: wedged.state in ("retired", "closed"))
        # the stranded request completed on the survivor,
        # token-identically
        assert h.wait(30.0) == expected([1, 2], 4)
        assert front.requeued_requests >= 1
    finally:
        front.close()


def test_death_while_draining_retires_instead_of_rebuilding():
    """A fault killing a DRAINING engine must not resurrect it: the
    front requeues the in-flight strand onto survivors and the
    replica retires."""
    from flexflow_tpu.resilience.faults import Fault, FaultKind, FaultPlan

    plan = FaultPlan([Fault(step=3, kind=FaultKind.HUNG_STEP)])
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP,
                         retry_backoff=0.0, fault_plans={0: plan})
    try:
        assert _wait_for(lambda: all(r.state == "live"
                                     for r in front.replicas))
        victim = front.replicas[0]
        hs = [front.generate_async([1 + i], 8) for i in range(4)]
        front.drain_replica(victim)
        for h, i in zip(hs, range(4)):
            assert h.wait(30.0) == expected([1 + i], 8)
        assert _wait_for(
            lambda: victim.state in ("draining", "retired"))
        assert _wait_for(lambda: victim.state == "retired", 15.0)
        assert victim.restarts == 0  # never rebuilt
    finally:
        front.close()


# -- overload admission control -----------------------------------------

def _prime_service_rate(front, n=3):
    for i in range(n):
        front.generate([1 + i], 2, timeout=30.0)
    assert front.service_rate() is not None


def test_admission_control_sheds_predicted_ttft_breach():
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.05),
        num_replicas=1, sleep=NO_SLEEP)
    try:
        _prime_service_rate(front)
        # build a DEEP backlog and let several completions land under
        # pressure (the capacity gate wants a trailing run of >= 3
        # busy samples), then ask for an impossible deadline
        done0 = front.requests_done
        hs = [front.generate_async([1 + i], 8) for i in range(12)]
        assert _wait_for(lambda: front.requests_done >= done0 + 4)
        assert front.admission_depth > 0  # still queued behind slots
        with pytest.raises(ServiceUnavailable) as ei:
            front.generate_async([9], 4, deadline_s=1e-4)
        assert "predicted TTFT" in str(ei.value)
        assert ei.value.retry_after_s > 0
        assert front.admission_shed == 1
        # no deadline -> still admitted under the same backlog
        h = front.generate_async([9], 4)
        for hh, i in zip(hs, range(12)):
            assert hh.wait(30.0) == expected([1 + i], 8)
        assert h.wait(30.0) == expected([9], 4)
        assert front.stats()["admission_shed"] == 1
    finally:
        front.close()


def test_admission_deadline_needs_measured_rate():
    """Before any completion there is no measured service rate —
    admission control must NOT shed on a guess."""
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP,
                         admission_deadline_s=0.001)
    try:
        assert front.service_rate() is None
        h = front.generate_async([1, 2], 4)  # admitted, not shed
        assert h.wait(30.0) == expected([1, 2], 4)
        assert front.admission_shed == 0
    finally:
        front.close()


def test_admission_never_sheds_on_arrival_paced_rate():
    """Steady calm traffic (completions pacing arrivals, queue empty
    throughout) must not arm admission control: the measured window
    says ~N rps but that is the LOAD, not what the fleet could do —
    the first burst after a calm stretch must be admitted, not
    condemned on an arrival-paced rate."""
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.02),
        num_replicas=1, sleep=NO_SLEEP)
    try:
        for i in range(6):  # sequential: queue empty at every settle
            front.generate([1 + i], 2, timeout=30.0)
        assert front.service_rate() is not None  # measured: arrivals
        assert front._capacity_rate() is None    # ...not capacity
        # burst: momentary backlog + tight deadline -> still admitted
        # (no capacity measurement to shed on; completions are slow
        # enough that none lands before the deadline submit)
        hs = [front.generate_async([1 + i], 4) for i in range(4)]
        h = front.generate_async([9], 3, deadline_s=1e-3)
        assert h.wait(30.0) == expected([9], 3)
        assert front.admission_shed == 0
        for hh, i in zip(hs, range(4)):
            assert hh.wait(30.0) == expected([1 + i], 4)
    finally:
        front.close()


def test_admission_never_sheds_an_empty_queue():
    """With no FRONT backlog the request dispatches immediately — the
    measured rate is arrival-limited and must not condemn it."""
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.02),
        num_replicas=1, sleep=NO_SLEEP)
    try:
        _prime_service_rate(front)  # slow-ish measured rate
        # empty queue + tiny deadline: admitted, completes fine
        h = front.generate_async([1, 2], 3, deadline_s=1e-4)
        assert h.wait(30.0) == expected([1, 2], 3)
        assert front.admission_shed == 0
    finally:
        front.close()


def test_service_rate_goes_stale_after_idle_gap():
    """After an idle gap the old completion span measures arrivals,
    not capacity: service_rate() must return None (and admission
    control must not shed) instead of a near-zero stale rate."""
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP,
                         rate_staleness_s=0.05)
    try:
        _prime_service_rate(front)
        time.sleep(0.15)  # idle past the staleness window
        assert front.service_rate() is None
        hs = [front.generate_async([1 + i], 6) for i in range(4)]
        h = front.generate_async([9], 3, deadline_s=1e-4)
        assert h.wait(30.0) == expected([9], 3)  # admitted, not shed
        assert front.admission_shed == 0
        for hh, i in zip(hs, range(4)):
            assert hh.wait(30.0) == expected([1 + i], 6)
    finally:
        front.close()


def test_admission_deadline_validation():
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    try:
        with pytest.raises(ValueError, match="deadline_s"):
            front.generate_async([1], 2, deadline_s=-1.0)
    finally:
        front.close()


# -- SIGTERM grace ------------------------------------------------------

def test_terminate_drains_under_load_no_silent_drops():
    """terminate() during active load: every admitted request either
    completes token-identically or settles 503-retriable with a
    Retry-After — none hangs, none silently drops."""
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.005),
        num_replicas=2, sleep=NO_SLEEP)
    reqs = [([1 + i], 8) for i in range(10)]
    hs = [front.generate_async(p, m) for p, m in reqs]
    report = front.terminate(deadline_s=30.0)
    completed = failed = 0
    for h, (p, m) in zip(hs, reqs):
        try:
            assert h.wait(5.0) == expected(p, m)
            completed += 1
        except ServiceUnavailable as e:
            assert e.retry_after_s > 0
            failed += 1
    assert completed + failed == len(reqs)
    assert report["deadline_met"]
    assert report["completed_during_drain"] == completed
    assert completed == len(reqs)  # generous deadline: all complete
    # new submissions shed 503 (the front is gone)
    with pytest.raises((ServiceUnavailable, RuntimeError)):
        front.generate_async([1], 2)


def test_terminate_tight_deadline_sheds_residue_with_retry_after():
    """A deadline too tight for the backlog: the residue is shed as
    503 + Retry-After (measured drain rate), nothing hangs past the
    deadline, and the report says deadline_met=False."""
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.2),
        num_replicas=1, sleep=NO_SLEEP, close_timeout_s=0.3)
    reqs = [([1 + i], 10) for i in range(8)]
    hs = [front.generate_async(p, m) for p, m in reqs]
    t0 = time.monotonic()
    report = front.terminate(deadline_s=1.0)
    assert time.monotonic() - t0 < 15.0  # bounded
    outcomes = []
    for h, (p, m) in zip(hs, reqs):
        try:
            assert h.wait(5.0) == expected(p, m)
            outcomes.append("ok")
        except ServiceUnavailable as e:
            assert e.retry_after_s > 0
            outcomes.append("shed")
        except RuntimeError:
            outcomes.append("closed")
    assert len(outcomes) == len(reqs)  # every handle SETTLED
    assert "shed" in outcomes  # the tight deadline shed something
    assert report["shed"] > 0


def test_terminate_drains_replica_that_returns_live_mid_drain():
    """A replica mid-rebuild when terminate() snapshots the fleet
    refuses its drain() and comes back 'live' afterwards: the settle
    loop must drain it too, not spin to the full deadline."""
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    r = front.replicas[0]
    r.state = "restarting"  # mid-rebuild at terminate time
    threading.Timer(0.15, lambda: setattr(r, "state", "live")).start()
    t0 = time.monotonic()
    report = front.terminate(deadline_s=20.0)
    assert report["deadline_met"] is True
    assert time.monotonic() - t0 < 10.0  # settled, not deadline-bound
    assert r.state in ("retired", "dead", "closed")


def test_spawn_failure_logged_and_cooled_down():
    """A persistent replica-build failure must not be retried with a
    full compile every tick: the failed attempt starts the cooldown
    (and is logged + counted)."""
    tm = [100.0]
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    reg = MetricsRegistry()
    sc = ServingAutoscaler(front, 1, 4, cooldown_s=5.0,
                           registry=reg, time_fn=lambda: tm[0])
    try:
        front.add_replica = lambda role="mixed": (_ for _ in ()).throw(
            RuntimeError("device OOM"))
        sc.observe = lambda: sig(t=tm[0], live=1, fleet=1,
                                 queue_per_replica=10.0)
        entry = sc.tick()
        assert entry["action"] == "hold"
        assert "spawn failed" in entry["reason"]
        assert reg.counter(
            "serving/autoscaler_spawn_failed").value == 1
        tm[0] += 1.0  # within cooldown: no new build attempt
        assert sc.tick()["reason"] == "cooldown"
        tm[0] += 10.0  # past cooldown: the policy may try again
        assert "spawn failed" in sc.tick()["reason"]
    finally:
        front.close()


def test_terminate_sheds_new_submissions_while_draining():
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.05),
        num_replicas=1, sleep=NO_SLEEP)
    _prime_service_rate(front)
    hs = [front.generate_async([1 + i], 10) for i in range(4)]
    done = []
    t = threading.Thread(
        target=lambda: done.append(front.terminate(deadline_s=20.0)))
    t.start()
    assert _wait_for(lambda: front._terminating)
    with pytest.raises(ServiceUnavailable) as ei:
        front.generate_async([9], 2)
    assert "terminating" in str(ei.value)
    # Retry-After rides the MEASURED drain rate (>= the floor)
    assert ei.value.retry_after_s >= front.shed_retry_after_s
    for h, i in zip(hs, range(4)):
        assert h.wait(30.0) == expected([1 + i], 10)
    t.join(timeout=30.0)
    assert done and done[0]["deadline_met"]


@pytest.mark.skipif(
    threading.current_thread() is not threading.main_thread(),
    reason="signal delivery needs the main thread")
def test_sigterm_triggers_graceful_drain():
    """A real SIGTERM mid-load: the installed handler drains the front
    under the deadline — admitted requests complete, the process isn't
    killed, and the displaced handler is restored after."""
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.005),
        num_replicas=1, sleep=NO_SLEEP)
    installed = front.install_grace_handlers(deadline_s=20.0)
    assert signal.SIGTERM in installed
    try:
        hs = [front.generate_async([1 + i], 6) for i in range(4)]
        os.kill(os.getpid(), signal.SIGTERM)
        for h, i in zip(hs, range(4)):
            assert h.wait(30.0) == expected([1 + i], 6)
        assert _wait_for(lambda: front._closed, 20.0)
    finally:
        for sig_num, old in installed.items():
            signal.signal(sig_num, old)
        front.close()


# -- observation + surfaces ---------------------------------------------

def test_observe_reads_front_gauges():
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 4)
    try:
        s = sc.observe()
        assert s["live"] == 2 and s["fleet"] == 2
        assert s["queue_depth"] == 0
        assert s["queue_per_replica"] == 0.0
        assert 0.0 <= s["kv_occupancy"] <= 1.0
    finally:
        front.close()


def test_stats_block_and_history():
    tm = [0.0]
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 4, cooldown_s=0.0,
                           time_fn=lambda: tm[0])
    try:
        sc.tick()  # calm 2-replica fleet -> down
        st = front.stats()["autoscaler"]
        assert st["min_replicas"] == 1 and st["max_replicas"] == 4
        assert st["scale_downs"] == 1
        assert st["last_decision"]["action"] == "down"
        assert st["last_decision"]["reason"]
        assert st["ticks"] == 1
        assert len(sc.history) == 1
    finally:
        front.close()


def test_health_reports_draining_then_retired():
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.02),
        num_replicas=2, sleep=NO_SLEEP)
    try:
        h = front.generate_async([1, 2], 30)
        assert _wait_for(
            lambda: any(r.outstanding for r in front.replicas))
        target = max(front.replicas, key=lambda r: r.outstanding)
        front.drain_replica(target)
        health = front.health()
        # draining is INTENTIONAL: still "ok", not degraded
        assert health["status"] == "ok"
        assert health["replicas_draining"] == 1
        assert any(r["state"] == "draining"
                   for r in health["replicas"])
        assert h.wait(30.0) == expected([1, 2], 30)
        assert _wait_for(
            lambda: front.health()["replicas_retired"] == 1)
        assert front.health()["status"] == "ok"
    finally:
        front.close()


def test_http_health_draining_and_stats_autoscaler_block():
    front = ServingFront(
        lambda rid, survivors=None: FakeStepModel(delay_s=0.02),
        num_replicas=2, sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 4)
    server = serve_http(generator=front, port=0, block=False)
    port = server.server_address[1]

    def _get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return json.loads(r.read())

    try:
        h = front.generate_async([1, 2], 30)
        assert _wait_for(
            lambda: any(r.outstanding for r in front.replicas))
        target = max(front.replicas, key=lambda r: r.outstanding)
        front.drain_replica(target)
        health = _get("/v2/health")
        assert health["status"] == "ok"
        assert health["replicas_draining"] == 1
        stats = _get("/v2/stats")
        blk = stats["continuous"]["autoscaler"]
        assert blk["current_replicas"] == 2
        assert blk["min_replicas"] == 1
        assert blk["max_replicas"] == 4
        assert h.wait(30.0) == expected([1, 2], 30)
    finally:
        server.shutdown()
        front.close()


def test_autoscaler_metrics_emitted():
    tm = [0.0]
    reg = MetricsRegistry()
    front = ServingFront(factory, num_replicas=2, registry=reg,
                         sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 4, cooldown_s=0.0,
                           time_fn=lambda: tm[0], registry=reg)
    try:
        sc.tick()
        assert _wait_for(lambda: len(front.retired) == 1)
        names = set(reg._metrics)
        assert "serving/autoscaler_replicas" in names
        assert "serving/autoscaler_target" in names
        assert "serving/autoscaler_down" in names
        assert "serving/replica_drains" in names
        assert "serving/replica_retired" in names
        assert "serving/drain_ms" in names
        # the retired replica's per-id gauge is dropped (ids are
        # monotonic — dead names would otherwise accumulate forever)
        rid = front.retired[0].replica_id
        assert f"serving/replica/{rid}/queue_depth" not in names
    finally:
        front.close()


# -- loop plumbing ------------------------------------------------------

def test_start_stop_background_loop():
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 2, interval_s=0.02)
    try:
        sc.start()
        assert _wait_for(lambda: sc.ticks >= 2)
        sc.stop()
        ticks = sc.ticks
        time.sleep(0.1)
        assert sc.ticks == ticks  # loop actually stopped
    finally:
        front.close()


def test_front_close_stops_attached_autoscaler():
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 2, interval_s=0.02).start()
    front.close()
    assert sc._thread is None  # close() stopped the loop


# -- config / CLI -------------------------------------------------------

def test_autoscale_config_knobs_parse_and_validate():
    cfg = FFConfig.from_args([
        "--serving-min-replicas", "2", "--serving-max-replicas", "6",
        "--autoscale-interval", "0.5", "--autoscale-cooldown", "3",
        "--serving-slo-ttft", "0.25", "--serving-drain-timeout", "12",
        "--admission-deadline", "2.5",
    ])
    assert cfg.serving_min_replicas == 2
    assert cfg.serving_max_replicas == 6
    assert cfg.autoscale_interval == 0.5
    assert cfg.autoscale_cooldown == 3.0
    assert cfg.serving_slo_ttft == 0.25
    assert cfg.serving_drain_timeout == 12.0
    assert cfg.admission_deadline_s == 2.5
    # defaults: autoscaling OFF (max 0), admission control OFF
    base = FFConfig.from_args([])
    assert base.serving_max_replicas == 0
    assert base.admission_deadline_s == 0.0

    with pytest.raises(ValueError, match="serving_min_replicas"):
        FFConfig(serving_min_replicas=0)
    with pytest.raises(ValueError, match="serving_max_replicas"):
        FFConfig(serving_min_replicas=3, serving_max_replicas=2)
    with pytest.raises(ValueError, match="autoscale_interval"):
        FFConfig(autoscale_interval=0)
    with pytest.raises(ValueError, match="autoscale_cooldown"):
        FFConfig(autoscale_cooldown=-1)
    with pytest.raises(ValueError, match="serving_slo_ttft"):
        FFConfig(serving_slo_ttft=-0.5)
    with pytest.raises(ValueError, match="serving_drain_timeout"):
        FFConfig(serving_drain_timeout=0)
    with pytest.raises(ValueError, match="admission_deadline_s"):
        FFConfig(admission_deadline_s=-1)


def test_from_config_refuses_autoscaling_off():
    """serving_max_replicas=0 is the documented 'autoscaling off'
    contract — from_config must refuse instead of building a scaler
    that would drain a static --serving-replicas fleet to min."""
    cfg = FFConfig.from_args([])  # default: max 0
    front = ServingFront(factory, num_replicas=2, sleep=NO_SLEEP)
    try:
        with pytest.raises(ValueError, match="autoscaling is off"):
            ServingAutoscaler.from_config(front, cfg)
    finally:
        front.close()


def test_from_config_wires_knobs():
    cfg = FFConfig.from_args([
        "--serving-min-replicas", "1", "--serving-max-replicas", "3",
        "--autoscale-interval", "0.7", "--autoscale-cooldown", "2",
        "--serving-slo-ttft", "0.4", "--serving-drain-timeout", "9",
    ])
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    try:
        sc = ServingAutoscaler.from_config(front, cfg)
        assert sc.min_replicas == 1 and sc.max_replicas == 3
        assert sc.interval_s == 0.7
        assert sc.cooldown_s == 2.0
        assert sc.slo_ttft_s == 0.4
        assert sc.drain_timeout_s == 9.0
    finally:
        front.close()


# -- chip budget: chips-per-replica aware fleet sizing ------------------

def _tp_factory(tp):
    def f(replica_id, survivors=None):
        m = FakeStepModel()
        m.tp = tp
        m.mesh_shape = {"data": 1, "model": tp}
        m.kv_block_bytes = 1024
        m.kv_block_bytes_per_chip = 1024 // tp
        return m
    return f


def test_chip_budget_caps_fleet_below_max_replicas():
    """A chip budget of B holds at most B // tp engines: the policy
    holds at that cap (with the budget named in the reason) instead of
    paying a spawn attempt that the front would refuse every tick."""
    front = ServingFront(_tp_factory(2), num_replicas=2, chip_budget=4,
                         sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 8, time_fn=lambda: 100.0)
    try:
        assert sc._max_fleet() == 2
        action, reason = sc.decide(sig(live=2, fleet=2,
                                       queue_per_replica=10.0))
        assert action == "hold"
        assert "chip budget 4 caps the fleet at 2" in reason
        # below the cap the same pressure still scales up
        action, _ = sc.decide(sig(live=1, fleet=1,
                                  queue_per_replica=10.0))
        assert action == "up"
        st = sc.stats()
        assert st["max_fleet"] == 2
        assert st["chips_per_replica"] == 2
        assert st["chip_budget"] == 4
        assert st["fleet_chips"] == 4
        assert all(m["mesh_shape"] == {"data": 1, "model": 2}
                   for m in st["replica_meshes"])
    finally:
        front.close()


def test_spawn_failures_surface_in_autoscaler_stats():
    """add_replica refusals (chip budget, compile errors) observed by
    tick() are counted on the scaler itself, not only the registry."""
    tm = [100.0]
    front = ServingFront(factory, num_replicas=1, sleep=NO_SLEEP)
    sc = ServingAutoscaler(front, 1, 4, cooldown_s=1.0,
                           time_fn=lambda: tm[0])
    try:
        front.add_replica = lambda role="mixed": (_ for _ in ()).throw(
            RuntimeError("chip budget exhausted: 4 of 4 chip(s) in "
                         "use and a new replica spans 2"))
        sc.observe = lambda: sig(t=tm[0], live=1, fleet=1,
                                 queue_per_replica=10.0)
        entry = sc.tick()
        assert entry["action"] == "hold"
        assert "chip budget exhausted" in entry["reason"]
        assert sc.spawn_failures == 1
        assert sc.stats()["spawn_failures"] == 1
    finally:
        front.close()


# -- predictive scaling (--autoscale-predictive) -------------------------

def test_predictive_projects_queue_breach_before_reactive():
    """An admission-rate slope outpacing the drain rate scales up
    while the instantaneous queue is still inside the band."""
    sc = make_scaler(predictive=True, predict_horizon_s=10.0,
                     queue_high=4.0)
    action, reason = sc.decide(sig(
        queue_depth=2, queue_per_replica=1.0,
        admit_rate_rps=2.0, drain_rate_rps=1.0))
    assert action == "up" and "projected queue" in reason


def test_predictive_off_by_default():
    sc = make_scaler(queue_high=4.0)
    action, _ = sc.decide(sig(
        queue_depth=2, queue_per_replica=1.0,
        admit_rate_rps=2.0, drain_rate_rps=1.0))
    assert action == "hold"
    assert sc.predictive is False


def test_predictive_holds_without_growth_or_rate():
    sc = make_scaler(predictive=True, predict_horizon_s=10.0,
                     queue_high=4.0)
    # drain keeps pace: no projected breach
    action, _ = sc.decide(sig(
        queue_depth=2, queue_per_replica=1.0,
        admit_rate_rps=1.0, drain_rate_rps=1.5))
    assert action == "hold"
    # no slope measured yet (single sample): predictive stays silent
    action, _ = sc.decide(sig(
        queue_depth=2, queue_per_replica=1.0, admit_rate_rps=None))
    assert action == "hold"


def test_observe_measures_admission_slope_with_fake_clock():
    front = ServingFront(factory, 1, sleep=NO_SLEEP)
    try:
        clock = [100.0]
        sc = ServingAutoscaler(front, min_replicas=1, max_replicas=2,
                               predictive=True,
                               time_fn=lambda: clock[0])
        s0 = sc.observe()
        assert s0["admit_rate_rps"] is None  # one sample, no slope
        for p in ([1, 2], [3, 4], [5, 6], [7, 8]):
            front.generate_async(p, 2).wait(10.0)
        clock[0] = 102.0
        s1 = sc.observe()
        assert s1["admit_rate_rps"] == pytest.approx(2.0)  # 4 in 2s
        assert s1["drain_rate_rps"] is not None  # completions flowed
    finally:
        front.close()


# -- role-aware scaling (disaggregated fleets) ---------------------------

def test_roles_queue_breach_grows_prefill_class():
    sc = make_scaler(queue_high=4.0)
    action, _ = sc.decide(sig(roles_active=True, queue_per_replica=5.0))
    assert action == "up" and sc.up_role == "prefill"


def test_roles_kv_pressure_grows_decode_class():
    sc = make_scaler(kv_high=0.85)
    action, reason = sc.decide(sig(roles_active=True,
                                   kv_occupancy=0.95))
    assert action == "up" and sc.up_role == "decode"


def test_roles_decode_per_token_slo_grows_decode_class():
    sc = make_scaler(slo_per_token_s=0.05)
    action, reason = sc.decide(sig(
        roles_active=True, outstanding=2, decode_per_token_s=0.2))
    assert action == "up" and sc.up_role == "decode"
    assert "per-token" in reason
    # idle fleet: the per-token window never refreshes, so it is
    # gated on load exactly like TTFT — never an "up"
    action, _ = sc.decide(sig(
        roles_active=True, decode_per_token_s=0.2))
    assert action != "up"


def test_roles_capacity_breach_outranks_ingest_breach():
    sc = make_scaler(queue_high=4.0, kv_high=0.85)
    action, _ = sc.decide(sig(roles_active=True, queue_per_replica=9.0,
                              kv_occupancy=0.95))
    assert action == "up" and sc.up_role == "decode"


def test_mixed_fleet_never_sets_up_role():
    sc = make_scaler(queue_high=4.0)
    action, _ = sc.decide(sig(queue_per_replica=5.0))
    assert action == "up" and sc.up_role is None


def test_tick_passes_role_to_add_replica():
    front = ServingFront(factory, 2, roles=["prefill", "decode"],
                         sleep=NO_SLEEP)
    try:
        added = []
        real_add = front.add_replica
        front.add_replica = lambda role="mixed": (
            added.append(role), real_add(role=role))[1]
        sc = ServingAutoscaler(front, min_replicas=2, max_replicas=4,
                               kv_high=0.85, cooldown_s=0.0)
        sc.observe = lambda: sig(t=float(sc.ticks), live=2, fleet=2,
                                 roles_active=True, kv_occupancy=0.95)
        entry = sc.tick()
        assert entry["action"] == "up" and entry["role"] == "decode"
        assert added == ["decode"]
        assert front.replicas[-1].role == "decode"
    finally:
        front.close()


def test_drain_never_retires_last_decode_capable_replica():
    front = ServingFront(factory, 2, roles=["prefill", "decode"],
                         sleep=NO_SLEEP)
    try:
        sc = ServingAutoscaler(front, min_replicas=1, max_replicas=4)
        target = sc._pick_drain_target()
        # the decode replica may be least loaded, but retiring it
        # leaves a fleet that can admit and never serve
        assert target is not None and target.role == "prefill"
    finally:
        front.close()


def test_drain_prefers_idle_prefill_over_last_decode():
    """With the decode class at its floor, the drain target is the
    least-loaded PREFILL replica even when decode is idler."""
    p1 = types.SimpleNamespace(role="prefill", outstanding=3)
    p2 = types.SimpleNamespace(role="prefill", outstanding=1)
    d = types.SimpleNamespace(role="decode", outstanding=0)
    front = types.SimpleNamespace(registry=None,
                                  _live=lambda: [p1, p2, d])
    sc = ServingAutoscaler(front, min_replicas=1, max_replicas=4)
    assert sc._pick_drain_target() is p2
    # with two decode-capable replicas the idlest decode is fair game
    d2 = types.SimpleNamespace(role="decode", outstanding=2)
    front._live = lambda: [p1, d, d2]
    assert sc._pick_drain_target() is d


def test_from_config_wires_predictive():
    front = ServingFront(factory, 1, sleep=NO_SLEEP)
    try:
        cfg = FFConfig(serving_max_replicas=2,
                       autoscale_predictive=True)
        sc = ServingAutoscaler.from_config(front, cfg)
        assert sc.predictive is True
        cfg2 = FFConfig(serving_max_replicas=2)
        assert ServingAutoscaler.from_config(
            front, cfg2).predictive is False
    finally:
        front.close()
