"""Pallas flash kernel parity on CPU via pallas_call(interpret=True).

The TPU kernels never execute in the CPU-pinned suite, so without this
file a tiling or math bug in the forward/backward kernels would pass
every test and surface on hardware as silently wrong gradients.
Interpret mode runs the same kernel jaxprs through the evaluator,
checking block index maps, masks, and the dq/dkv math against the jnp
reference implementation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flexflow_tpu.ops.pallas.flash_attention as fa

if not fa._HAVE_PALLAS:  # pragma: no cover
    pytest.skip("pallas unavailable", allow_module_level=True)


def _mk(bh, s, d, seed=0):
    rng = np.random.RandomState(seed)
    return [
        jnp.asarray(rng.randn(bh, s, d) * 0.4, jnp.float32)
        for _ in range(3)
    ]


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_q,block_k", [(128, 128), (128, 256)])
def test_fwd_kernel_parity(causal, block_q, block_k):
    q, k, v = _mk(2, 256, 64)
    scale = 0.125
    out, lse = fa._flash_fwd_pallas(
        q, k, v, scale, causal, block_q, block_k, interpret=True
    )
    ref = fa._ref_attention(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # lse parity against the jnp forward's residual
    _, lse_ref = fa._flash_fwd(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_q,block_k", [(128, 128), (256, 128)])
def test_bwd_kernel_parity(causal, block_q, block_k):
    q, k, v = _mk(2, 256, 64, seed=1)
    scale = 0.125
    out, lse = fa._flash_fwd(q, k, v, scale, causal)
    rng = np.random.RandomState(2)
    dout = jnp.asarray(rng.randn(*out.shape) * 0.3, jnp.float32)
    got = fa._flash_bwd_pallas(
        q, k, v, out, lse, dout, scale, causal, block_q, block_k,
        interpret=True,
    )
    want = fa._flash_vjp_bwd(scale, causal, (q, k, v, out, lse), dout)
    for name, a, b in zip("qkv", got, want):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name} mismatch (causal={causal})",
        )
