"""Pipeline parallelism as a first-class Strategy.

Reference parity: the reference reserves PIPELINE_INIT/FWD/BWD task ids
(include/flexflow/model.h:190-192) but implements no pipeline op; SURVEY
§2.3 directs this build to make PP a build-fresh searchable strategy.
Covers: plan validation, pp Strategy training matching single-device
numerics (GPipe via shard_map+ppermute, parallel/pipeline.py), strategy
JSON round-trip, and the Unity search emitting a pp strategy when
neither dp nor tp can use the mesh.
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.strategy import Strategy


def _stacked(n, layers=4, batch=16, hidden=32, classes=4):
    cfg = FFConfig(batch_size=batch, num_devices=n)
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, hidden], name="x")
    t = x
    for i in range(layers):
        t = ff.dense(t, hidden, activation=ActiMode.RELU, name=f"blk{i}")
    t = ff.dense(t, classes, name="head")
    ff.softmax(t)
    return ff


def _pp_strategy(dp, pp, M):
    axes = {"data": dp, "pipe": pp} if dp > 1 else {"pipe": pp}
    s = Strategy(
        mesh_axes=axes,
        pipeline={"degree": pp, "num_microbatches": M, "axis": "pipe",
                  "dp_axis": "data" if dp > 1 else None},
    )
    if dp > 1:
        s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": dp})]
    return s


def test_pp_strategy_matches_single_device(devices8):
    """dp=2 x pp=2 GPipe training matches the 1-device model step for
    step when weights are transferred from the stacked pp layout."""
    ff = _stacked(4)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               strategy=_pp_strategy(2, 2, 4), devices=devices8[:4])
    w = ff.get_weights()
    assert set(w) == {"__pipeline__", "head"}
    assert w["__pipeline__"]["0.kernel"].shape == (4, 32, 32)

    ff1 = _stacked(1)
    ff1.compile(optimizer=SGDOptimizer(lr=0.05), devices=devices8[:1])
    w1 = ff1.get_weights()
    for k in range(4):
        w1[f"blk{k}"]["kernel"] = w["__pipeline__"]["0.kernel"][k]
        w1[f"blk{k}"]["bias"] = w["__pipeline__"]["0.bias"][k]
    w1["head"] = w["head"]
    ff1.set_weights(w1)

    rs = np.random.RandomState(0)
    x = rs.randn(16, 32).astype(np.float32)
    y = rs.randint(0, 4, size=(16,))
    np.testing.assert_allclose(
        np.asarray(ff.forward({"x": x})), np.asarray(ff1.forward({"x": x})),
        rtol=2e-5, atol=2e-5,
    )
    losses_pp = [float(ff.train_step({"x": x}, y)["loss"]) for _ in range(5)]
    losses_1d = [float(ff1.train_step({"x": x}, y)["loss"]) for _ in range(5)]
    np.testing.assert_allclose(losses_pp, losses_1d, rtol=1e-4, atol=1e-5)
    assert losses_pp[-1] < losses_pp[0]


def test_pp_strategy_pipe_only_mesh(devices8):
    """pp without a data axis (mesh {'pipe': 4})."""
    ff = _stacked(4)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               strategy=_pp_strategy(1, 4, 4), devices=devices8[:4])
    x = np.random.randn(16, 32).astype(np.float32)
    y = np.random.randint(0, 4, size=(16,))
    m = ff.train_step({"x": x}, y)
    assert np.isfinite(float(m["loss"]))


def test_pp_plan_validation_errors():
    from flexflow_tpu.parallel.pipeline_plan import plan_pipeline

    ff = _stacked(4, layers=3)  # 3 blocks, not divisible by pp=2
    with pytest.raises(ValueError, match="not divisible"):
        plan_pipeline(
            ff.layers,
            {"degree": 2, "num_microbatches": 4, "axis": "pipe",
             "dp_axis": None},
            {"pipe": 2},
        )
    # no repeated blocks at all
    cfg = FFConfig(batch_size=8)
    ff2 = FFModel(cfg)
    x = ff2.create_tensor([8, 16], name="x")
    t = ff2.dense(x, 32, name="a")
    ff2.softmax(t)
    with pytest.raises(ValueError, match="block"):
        plan_pipeline(
            ff2.layers,
            {"degree": 2, "num_microbatches": 2, "axis": "pipe",
             "dp_axis": None},
            {"pipe": 2},
        )


def test_pp_strategy_json_roundtrip(tmp_path):
    s = _pp_strategy(2, 2, 8)
    p = tmp_path / "pp.json"
    s.save(str(p))
    s2 = Strategy.load(str(p))
    assert s2.pipeline == s.pipeline
    assert s2.mesh_axes == s.mesh_axes


def test_unity_search_emits_pipeline(devices8):
    """With a prime hidden width (no tp options) and a batch smaller
    than the device count (no pure-dp factorization), the only viable
    8-device strategy is GPipe — the search must find and emit it, and
    the result must compile + train."""
    from flexflow_tpu.pcg.unity import UnitySearch
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel

    ff = _stacked(8, layers=8, batch=2, hidden=31, classes=5)
    machine = TpuPodModel(topology=(2, 4))
    search = UnitySearch(ff.layers, 8, machine, OpCostModel(machine))
    best = search.optimize()
    assert best is not None
    assert best.pipeline is not None, f"expected pp strategy, got {best}"
    assert best.mesh_axes.get("pipe") == best.pipeline["degree"]

    ff.compile(optimizer=SGDOptimizer(lr=0.05), strategy=best,
               devices=devices8[:8])
    x = np.random.randn(2, 31).astype(np.float32)
    y = np.random.randint(0, 5, size=(2,))
    m = ff.train_step({"x": x}, y)
    assert np.isfinite(float(m["loss"]))


def test_pp_remat_matches_non_remat(devices8):
    """--remat through the pipeline region (jax.checkpoint per block:
    backward recomputes block internals, storing only boundary
    activations per in-flight microbatch) is numerically identical to
    the plain GPipe autodiff path, step for step."""

    def build(remat):
        ff = _stacked(4)
        ff.config.remat = remat
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   strategy=_pp_strategy(2, 2, 4), devices=devices8[:4])
        return ff

    ff_a, ff_b = build(False), build(True)
    ff_b.set_weights(ff_a.get_weights())
    rs = np.random.RandomState(3)
    x = rs.randn(16, 32).astype(np.float32)
    y = rs.randint(0, 4, size=(16,))
    np.testing.assert_allclose(
        np.asarray(ff_a.forward({"x": x})),
        np.asarray(ff_b.forward({"x": x})), rtol=2e-5, atol=2e-5)
    la = [float(ff_a.train_step({"x": x}, y)["loss"]) for _ in range(5)]
    lb = [float(ff_b.train_step({"x": x}, y)["loss"]) for _ in range(5)]
    np.testing.assert_allclose(la, lb, rtol=1e-4, atol=1e-5)
    assert la[-1] < la[0]
