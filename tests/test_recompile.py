"""RecompileState / FFModel.recompile tests (reference recompile.h +
moe.cc trigger/alter usage): strategy swap mid-training preserves
weights and training continues."""
import numpy as np
import pytest

from flexflow_tpu import (
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    RecompileState,
    SGDOptimizer,
)
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.strategy import data_parallel_strategy


def _model(devices):
    cfg = FFConfig(batch_size=16, num_devices=len(devices))
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 32, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices)
    return ff


def test_trigger_alter_counter(devices8):
    ff = _model(devices8)
    fired = []
    r = RecompileState(
        trigger_func=lambda m: len(fired) < 2,
        alter_func=lambda m: fired.append(1),
        ff=ff,
    )
    assert ff.recompile_on_condition(r) is True
    assert ff.recompile_on_condition(r) is True
    assert ff.recompile_on_condition(r) is False
    assert r.recompilations == 2 and len(fired) == 2


def test_recompile_preserves_weights_and_outputs(devices8):
    ff = _model(devices8)
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 8).astype(np.float32)
    ys = rng.randint(0, 4, 64).astype(np.int32)
    ff.fit(xs, ys, epochs=1, verbose=False)
    before = np.asarray(ff.forward({"x": xs[:16]}))

    # alter: shrink to fewer devices (new mesh + shardings)
    ff.recompile(strategy=data_parallel_strategy(4),
                 devices=list(ff.mesh.devices.flat)[:4])
    after = np.asarray(ff.forward({"x": xs[:16]}))
    np.testing.assert_allclose(before, after, rtol=2e-5, atol=2e-5)

    # training continues after the swap
    hist = ff.fit(xs, ys, epochs=2, verbose=False)
    assert np.isfinite(hist[-1].sparse_cce_loss)


def test_recompile_preserves_bn_state_and_rng(devices8):
    """Non-trainable state (BatchNorm running stats) and the training
    RNG stream must survive a recompile."""
    cfg = FFConfig(batch_size=16, num_devices=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 4, 4, 4], name="x")
    t = ff.batch_norm(x, relu=True)
    t = ff.flat(t)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8)
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 4, 4, 4).astype(np.float32) * 3 + 1
    ys = rng.randint(0, 4, 32).astype(np.int32)
    ff.fit(xs, ys, epochs=2, verbose=False)

    import jax

    state_before = jax.tree.map(np.asarray, ff._state)
    rng_before = np.asarray(jax.random.key_data(ff._rng))
    ff.recompile(strategy=data_parallel_strategy(4),
                 devices=list(ff.mesh.devices.flat)[:4])
    state_after = jax.tree.map(np.asarray, ff._state)
    for a, b in zip(jax.tree.leaves(state_before), jax.tree.leaves(state_after)):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(
        rng_before, np.asarray(jax.random.key_data(ff._rng))
    )
    # running stats actually moved away from init during training
    leaves = jax.tree.leaves(state_before)
    assert any(not np.allclose(l, 0.0) and not np.allclose(l, 1.0)
               for l in leaves)


def test_recompile_in_training_loop_via_cache_score(devices8):
    """moe.cc-style usage: a trigger watching a score, alter swapping
    strategy once the score crosses a threshold."""
    ff = _model(devices8)
    score = {"v": 0.0}

    def trigger(m):
        return score["v"] > 0.5 and r.recompilations == 0

    def alter(m):
        m.recompile(strategy=data_parallel_strategy(2),
                    devices=list(m.mesh.devices.flat)[:2])

    r = RecompileState(trigger, alter, ff)
    rng = np.random.RandomState(1)
    xs = rng.randn(32, 8).astype(np.float32)
    ys = rng.randint(0, 4, 32).astype(np.int32)
    for it in range(4):
        ff.train_step({"x": xs[:16]}, ys[:16])
        score["v"] = it * 0.3
        ff.recompile_on_condition(r)
    assert r.recompilations == 1
    assert ff.mesh.devices.size == 2


def _stacked_model(devices, layers=4, batch=16, hidden=32, classes=4,
                   momentum=0.9):
    cfg = FFConfig(batch_size=batch, num_devices=len(devices))
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, hidden], name="x")
    t = x
    for i in range(layers):
        t = ff.dense(t, hidden, activation=ActiMode.RELU, name=f"blk{i}")
    t = ff.dense(t, classes, name="head")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05, momentum=momentum),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               devices=devices)
    return ff


def _pp_strategy(dp, pp, M):
    from flexflow_tpu.strategy import Strategy

    axes = {"data": dp, "pipe": pp} if dp > 1 else {"pipe": pp}
    s = Strategy(
        mesh_axes=axes,
        pipeline={"degree": pp, "num_microbatches": M, "axis": "pipe",
                  "dp_axis": "data" if dp > 1 else None},
    )
    if dp > 1:
        s.edge_ops["__inputs__"] = [("repartition",
                                     {"dim": 0, "degree": dp})]
    return s


def test_recompile_onto_pipeline_carries_weights(devices8):
    """ROADMAP pre-existing bug: recompile's weight carry died on the
    '__pipeline__' vs per-op key mismatch in set_weights.  The layout
    adaptation maps per-op trained weights onto the GPipe stacked
    layout (and the optimizer slots with them): outputs match across
    the swap and training continues."""
    ff = _stacked_model(devices8[:4])
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 32).astype(np.float32)
    ys = rng.randint(0, 4, 64).astype(np.int32)
    ff.fit(xs, ys, epochs=1, verbose=False)
    w_before = ff.get_weights()
    before = np.asarray(ff.forward({"x": xs[:16]}))

    ff.recompile(strategy=_pp_strategy(2, 2, 4),
                 devices=list(ff.mesh.devices.flat)[:4])
    assert set(ff.get_weights()) == {"__pipeline__", "head"}
    stacked = ff.get_weights()["__pipeline__"]
    for k in range(4):
        np.testing.assert_array_equal(stacked["0.kernel"][k],
                                      w_before[f"blk{k}"]["kernel"])
        np.testing.assert_array_equal(stacked["0.bias"][k],
                                      w_before[f"blk{k}"]["bias"])
    after = np.asarray(ff.forward({"x": xs[:16]}))
    np.testing.assert_allclose(before, after, rtol=2e-5, atol=2e-5)
    hist = ff.fit(xs, ys, epochs=1, verbose=False)
    assert np.isfinite(hist[-1].sparse_cce_loss)


def test_recompile_off_pipeline_carries_weights(devices8):
    """The reverse mapping: a pipeline-compiled model recompiles onto a
    per-op strategy with the stacked weights unstacked by block."""
    ff = _stacked_model(devices8[:4])
    # swap to pipeline first, train a step there, then come back
    ff.recompile(strategy=_pp_strategy(2, 2, 4),
                 devices=list(ff.mesh.devices.flat)[:4])
    rng = np.random.RandomState(1)
    xs = rng.randn(32, 32).astype(np.float32)
    ys = rng.randint(0, 4, 32).astype(np.int32)
    ff.train_step({"x": xs[:16]}, ys[:16])
    stacked = ff.get_weights()["__pipeline__"]
    before = np.asarray(ff.forward({"x": xs[:16]}))

    ff.recompile(strategy=data_parallel_strategy(2),
                 devices=list(ff.mesh.devices.flat)[:2])
    w = ff.get_weights()
    assert "__pipeline__" not in w
    for k in range(4):
        np.testing.assert_array_equal(w[f"blk{k}"]["kernel"],
                                      stacked["0.kernel"][k])
    after = np.asarray(ff.forward({"x": xs[:16]}))
    np.testing.assert_allclose(before, after, rtol=2e-5, atol=2e-5)
    m = ff.train_step({"x": xs[:16]}, ys[:16])
    assert np.isfinite(float(m["loss"]))


def test_cache_score_drives_recompile_trigger(devices8):
    """moe.cc:39-98 parity: a Cache op's score_fn is polled each fit
    batch; its running average feeds a RecompileState trigger."""
    cfg = FFConfig(batch_size=8, num_devices=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 8], name="x")
    t = ff.cache(x, num_batches=4, score_fn=lambda m: 0.9)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8)
    rng = np.random.RandomState(0)
    ff.fit(rng.randn(32, 8).astype(np.float32),
           rng.randint(0, 4, 32).astype(np.int32), epochs=2, verbose=False)
    op = ff._cache_ops[0]
    assert op.trigger == pytest.approx(0.9)
    assert len(op.score_history) == 4  # bounded by num_batches
    r = RecompileState(lambda m: m._cache_ops[0].trigger > 0.5,
                       lambda m: None, ff)
    assert ff.recompile_on_condition(r)
