"""Resilience subsystem tests (resilience/): deterministic fault
injection, retry/backoff supervision, crash-restore bit-identity, and
elastic re-search + recompile on a degraded mesh — all on the hermetic
8-device CPU mesh, no hardware has to die.
"""
import numpy as np
import pytest

import flexflow_tpu
from flexflow_tpu import (
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.executor import NonFiniteLossError
from flexflow_tpu.fftype import ActiMode
from flexflow_tpu.resilience import (
    Fault,
    FaultKind,
    FaultPlan,
    RestartBudgetExhausted,
    RetryPolicy,
    StepFault,
    TrainingSupervisor,
)
from flexflow_tpu.strategy import data_parallel_strategy

NO_SLEEP = lambda s: None  # noqa: E731


def _model(devices, seed=0, strategy=None, **cfg_over):
    cfg = FFConfig(batch_size=16, num_devices=len(devices), seed=seed,
                   **cfg_over)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = ff.dense(x, 32, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
               strategy=strategy, devices=devices, seed=seed)
    return ff


def _data(n=128, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 8).astype(np.float32)
    ys = rng.randint(0, 4, size=n).astype(np.int32)
    return xs, ys


def _weights_equal(a, b):
    import jax

    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- fault plan / retry policy units ------------------------------------

def test_fault_plan_seeded_deterministic_and_fires_once():
    a = FaultPlan.seeded(seed=3, num_steps=20, count=3)
    b = FaultPlan.seeded(seed=3, num_steps=20, count=3)
    assert [f.step for f in a.faults] == [f.step for f in b.faults]
    assert len({f.step for f in a.faults}) == 3
    step = a.faults[0].step
    with pytest.raises(StepFault):
        a.check_step(step)
    a.check_step(step)  # fired -> silent on replay after a restore
    assert len(a.remaining()) == 2


def test_fault_plan_json_round_trip():
    plan = FaultPlan([
        Fault(step=4, kind=FaultKind.DEVICE_LOSS, payload={"survivors": 4}),
        Fault(step=7, kind=FaultKind.CHECKPOINT_WRITE),
    ])
    back = FaultPlan.from_json(plan.to_json())
    assert [(f.step, f.kind, f.payload) for f in back.faults] == [
        (f.step, f.kind, f.payload) for f in plan.faults
    ]


def test_fault_plan_corrupt_batch_poisons_floats_once():
    plan = FaultPlan.single(2, FaultKind.NAN_LOSS)
    inputs = {"x": np.ones((4, 3), np.float32),
              "idx": np.arange(4, dtype=np.int32)}
    out = plan.corrupt_batch(2, inputs)
    assert np.isnan(out["x"]).all()
    np.testing.assert_array_equal(out["idx"], inputs["idx"])  # ints untouched
    again = plan.corrupt_batch(2, inputs)
    assert not np.isnan(again["x"]).any()  # one-shot


def test_retry_policy_backoff_deterministic_capped():
    p = RetryPolicy(max_restarts=3, base_backoff=0.5, multiplier=2.0,
                    max_backoff=2.0, jitter=0.25, seed=7)
    seq = [p.backoff(i) for i in (1, 2, 3, 6)]
    assert seq == [p.backoff(i) for i in (1, 2, 3, 6)]  # seeded jitter
    assert abs(seq[0] - 0.5) <= 0.5 * 0.25
    assert abs(seq[1] - 1.0) <= 1.0 * 0.25
    assert seq[3] <= 2.0 * 1.25  # capped before jitter
    assert p.admits(3) and not p.admits(4)
    with pytest.raises(ValueError):
        RetryPolicy(max_restarts=-1)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)


# -- crash-restore bit-identity -----------------------------------------

@pytest.mark.parametrize(
    "kind", [FaultKind.STEP_EXCEPTION, FaultKind.HOST_PREEMPTION]
)
def test_crash_restore_bit_identical(devices8, tmp_path, kind):
    """Acceptance: a seeded FaultPlan crashing at an arbitrary step must
    restore and reach weights bit-identical to the fault-free run at the
    same step count on the same mesh."""
    import jax

    xs, ys = _data(128)

    ff_clean = _model(devices8, seed=11)
    clean = TrainingSupervisor(ff_clean, str(tmp_path / "clean"),
                               checkpoint_every=2, sleep=NO_SLEEP)
    rep_clean = clean.run(xs, ys, num_steps=7)

    ff_fault = _model(devices8, seed=11)
    fault = TrainingSupervisor(
        ff_fault, str(tmp_path / "fault"), checkpoint_every=2,
        fault_plan=FaultPlan.single(5, kind), sleep=NO_SLEEP,
    )
    rep_fault = fault.run(xs, ys, num_steps=7)

    assert rep_clean.final_step == rep_fault.final_step == 7
    _weights_equal(ff_clean.get_weights(), ff_fault.get_weights())
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(ff_clean._rng)),
        np.asarray(jax.random.key_data(ff_fault._rng)),
    )
    assert rep_fault.losses == rep_clean.losses  # replay, not drift
    assert rep_clean.counters["restarts"] == 0
    assert rep_fault.counters["restarts"] == 1
    assert rep_fault.counters["retries"] == 1
    assert rep_fault.counters["lost_steps"] == 1  # ckpt@4, crash@5


def test_seeded_fault_plan_run_bit_identical(devices8, tmp_path):
    """Acceptance, seeded form: crashes at rng-chosen arbitrary steps
    still converge to the fault-free weights at the same step count."""
    xs, ys = _data(160)
    ff_clean = _model(devices8, seed=21)
    TrainingSupervisor(ff_clean, str(tmp_path / "clean"), checkpoint_every=3,
                       sleep=NO_SLEEP).run(xs, ys, num_steps=10)

    ff = _model(devices8, seed=21)
    plan = FaultPlan.seeded(
        seed=123, num_steps=10, count=2,
        kinds=(FaultKind.STEP_EXCEPTION, FaultKind.HOST_PREEMPTION),
    )
    rep = TrainingSupervisor(ff, str(tmp_path / "fault"), checkpoint_every=3,
                             fault_plan=plan, sleep=NO_SLEEP
                             ).run(xs, ys, num_steps=10)
    assert rep.final_step == 10
    assert rep.counters["restarts"] == 2
    assert not plan.remaining()
    _weights_equal(ff_clean.get_weights(), ff.get_weights())


def test_restart_budget_exhausted(devices8, tmp_path):
    xs, ys = _data()
    ff = _model(devices8)
    plan = FaultPlan([Fault(step=s, kind=FaultKind.STEP_EXCEPTION)
                      for s in (2, 3, 4)])
    sup = TrainingSupervisor(
        ff, str(tmp_path), checkpoint_every=2, fault_plan=plan,
        retry=RetryPolicy(max_restarts=2, base_backoff=0.0), sleep=NO_SLEEP,
    )
    with pytest.raises(RestartBudgetExhausted):
        sup.run(xs, ys, num_steps=8)
    assert sup.counters["retries"] == 3


def test_backoff_delays_follow_policy(devices8, tmp_path):
    xs, ys = _data()
    ff = _model(devices8)
    policy = RetryPolicy(max_restarts=5, base_backoff=0.5, jitter=0.25,
                         seed=3)
    delays = []
    plan = FaultPlan([Fault(step=s, kind=FaultKind.STEP_EXCEPTION)
                      for s in (2, 3)])
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             fault_plan=plan, retry=policy,
                             sleep=delays.append)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert delays == [policy.backoff(1), policy.backoff(2)]


def test_checkpoint_write_fault_is_survived(devices8, tmp_path):
    """A failed periodic save costs nothing but that save: training
    continues and the next cadence point writes a fresh checkpoint."""
    xs, ys = _data()
    ff = _model(devices8)
    plan = FaultPlan.single(3, FaultKind.CHECKPOINT_WRITE)
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             fault_plan=plan, sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert rep.counters["checkpoint_failures"] == 1
    assert rep.counters["restarts"] == 0
    assert sup.manager.latest_step() == 6  # save@4 failed, save@6 landed
    assert 4 not in sup.manager.all_steps()


# -- nan_policy ----------------------------------------------------------

def test_nan_policy_raise_propagates(devices8, tmp_path):
    xs, ys = _data()
    ff = _model(devices8)  # nan_policy defaults to "raise"
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             fault_plan=FaultPlan.single(3, FaultKind.NAN_LOSS),
                             sleep=NO_SLEEP)
    with pytest.raises(NonFiniteLossError):
        sup.run(xs, ys, num_steps=6)


def test_nan_policy_skip_step_counts_and_continues(devices8, tmp_path):
    xs, ys = _data()
    ff = _model(devices8, nan_policy="skip_step")
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             fault_plan=FaultPlan.single(3, FaultKind.NAN_LOSS),
                             sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert rep.counters["skipped_steps"] == 1
    assert rep.counters["restarts"] == 0
    assert len(rep.losses) == 5  # the poisoned batch recorded nothing
    assert all(np.isfinite(v) for v in rep.losses)
    for leaf in np.asarray(ff.get_parameter("dense_0", "kernel")).ravel():
        assert np.isfinite(leaf)


def test_nan_policy_restore_recovers_bit_identical(devices8, tmp_path):
    """restore policy: a transient NaN rolls back to the last checkpoint
    and replays — ending bit-identical to a clean run (the poisoned
    batch was transient, so the replay sees clean data)."""
    xs, ys = _data(128)
    ff_clean = _model(devices8, seed=5)
    clean = TrainingSupervisor(ff_clean, str(tmp_path / "clean"),
                               checkpoint_every=2, sleep=NO_SLEEP)
    clean.run(xs, ys, num_steps=6)

    ff = _model(devices8, seed=5, nan_policy="restore")
    sup = TrainingSupervisor(ff, str(tmp_path / "nan"), checkpoint_every=2,
                             fault_plan=FaultPlan.single(3, FaultKind.NAN_LOSS),
                             sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert rep.counters["restarts"] == 1
    assert all(np.isfinite(v) for v in rep.losses)
    _weights_equal(ff_clean.get_weights(), ff.get_weights())


def test_skip_then_restore_losses_stay_aligned(devices8, tmp_path):
    """A skipped step records no loss, so a later restore must truncate
    the loss record by STEP, not by list position — losses and weights
    both stay identical to a restore-free run with the same skip."""
    xs, ys = _data(128)
    ff_clean = _model(devices8, seed=13, nan_policy="skip_step")
    clean = TrainingSupervisor(
        ff_clean, str(tmp_path / "clean"), checkpoint_every=2,
        fault_plan=FaultPlan.single(2, FaultKind.NAN_LOSS), sleep=NO_SLEEP,
    )
    rep_clean = clean.run(xs, ys, num_steps=7)
    assert len(rep_clean.losses) == 6  # step 2 recorded nothing

    ff = _model(devices8, seed=13, nan_policy="skip_step")
    plan = FaultPlan([Fault(step=2, kind=FaultKind.NAN_LOSS),
                      Fault(step=5, kind=FaultKind.STEP_EXCEPTION)])
    sup = TrainingSupervisor(ff, str(tmp_path / "fault"), checkpoint_every=2,
                             fault_plan=plan, sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=7)
    assert rep.counters["skipped_steps"] == 1
    assert rep.counters["restarts"] == 1
    assert rep.losses == rep_clean.losses  # no duplicate/missing entries
    _weights_equal(ff_clean.get_weights(), ff.get_weights())


# -- elastic recovery on a degraded mesh --------------------------------

def test_device_loss_elastic_resume_data_parallel(devices8, tmp_path):
    """8 -> 4 device loss: re-search on the surviving mesh (data-parallel
    fallback under search_budget=0), recompile, reshard-restore, and
    finish with a valid 4-device strategy — no manual intervention."""
    xs, ys = _data(128)
    ff = _model(devices8, seed=4)
    assert ff.mesh.devices.size == 8
    plan = FaultPlan.single(3, FaultKind.DEVICE_LOSS, survivors=4)
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             fault_plan=plan, sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert rep.counters["device_losses"] == 1
    assert rep.counters["re_searches"] == 1
    assert rep.counters["restarts"] == 1
    assert ff.mesh.devices.size == 4
    assert ff.strategy.total_devices == 4
    assert all(np.isfinite(v) for v in rep.losses)
    out = np.asarray(ff.forward({"x": xs[:16]}))
    assert np.isfinite(out).all()


def test_device_loss_carries_trained_state(devices8, tmp_path):
    """The restore after recompile reshards the checkpointed weights
    onto the surviving mesh: the recovery point equals the last durable
    pre-loss weights, not a fresh init."""
    xs, ys = _data(128)
    ff_clean = _model(devices8, seed=9)
    clean = TrainingSupervisor(ff_clean, str(tmp_path / "clean"),
                               checkpoint_every=4, sleep=NO_SLEEP)
    clean.run(xs, ys, num_steps=4)
    w4 = ff_clean.get_weights()  # durable state at the loss point

    ff = _model(devices8, seed=9)
    sup = TrainingSupervisor(
        ff, str(tmp_path / "loss"), checkpoint_every=4,
        fault_plan=FaultPlan.single(4, FaultKind.DEVICE_LOSS, survivors=2),
        sleep=NO_SLEEP,
    )
    # steps 0-3 run on the full mesh, ckpt@4 lands, then the loss fires
    # at step 4 -> recompile to 2 devices, reshard-restore, finish step 4
    rep = sup.run(xs, ys, num_steps=5)
    assert rep.final_step == 5
    assert rep.counters["device_losses"] == 1
    assert rep.counters["lost_steps"] == 0  # ckpt@4 == the loss point
    assert ff.mesh.devices.size == 2
    # rewind to the checkpoint the recovery restored from: it must be
    # the clean run's step-4 state, resharded onto the 2-device mesh
    step = sup.manager.restore(ff)
    assert step == 4
    assert ff.mesh.devices.size == 2
    _weights_equal(ff.get_weights(), w4)


@pytest.mark.slow
def test_device_loss_researches_with_unity(devices8, tmp_path):
    """Degraded-mesh re-search with the real Unity search: 8 -> 4, the
    supervisor searches a fresh strategy for the surviving topology and
    training completes under it."""
    xs, ys = _data(128)
    ff = _model(devices8, seed=1, strategy=data_parallel_strategy(8),
                search_budget=5, rewrite_depth=1, rewrite_max_variants=1)
    plan = FaultPlan.single(3, FaultKind.DEVICE_LOSS, survivors=4)
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             fault_plan=plan, sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert rep.counters["re_searches"] == 1
    assert 1 <= ff.strategy.total_devices <= 4  # valid on survivors
    assert ff.mesh.devices.size == ff.strategy.total_devices
    assert all(np.isfinite(v) for v in rep.losses)


@pytest.mark.slow
def test_device_loss_pipeline_candidate_restores(devices8, tmp_path):
    """ISSUE 9 satellite — the ROADMAP 8->4 repro, with the pipeline
    exclusion LIFTED: 8->4 device loss on a 3x64-dense MLP (batch 16,
    budget 50, enable_parameter_parallel) makes the degraded-mesh
    re-search return a PIPELINE candidate; checkpoint restore now maps
    the per-op-keyed saved state onto the '__pipeline__' stacked
    layout (checkpoint._adapt_saved_layout), so the supervisor keeps
    whatever candidate the search picks and recovery completes
    through a reshard-restore onto it."""
    cfg = FFConfig(batch_size=16, num_devices=8, search_budget=50,
                   enable_parameter_parallel=True, rewrite_depth=1,
                   rewrite_max_variants=1)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 8], name="x")
    t = x
    for _ in range(3):
        t = ff.dense(t, 64, activation=ActiMode.RELU)
    t = ff.dense(t, 4)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8)
    xs, ys = _data(128)
    plan = FaultPlan.single(3, FaultKind.DEVICE_LOSS, survivors=4)
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             fault_plan=plan, sleep=NO_SLEEP)
    rep = sup.run(xs, ys, num_steps=6)
    assert rep.final_step == 6
    assert rep.counters["device_losses"] == 1
    # the exclusion (and its counter) are gone: the re-searched winner
    # — historically a pipeline strategy on this exact repro — is kept
    assert "re_search_pipeline_excluded" not in rep.counters
    assert ff.strategy.pipeline is not None
    assert ff.strategy.total_devices <= 4
    assert all(np.isfinite(v) for v in rep.losses)


# -- fit integration -----------------------------------------------------

def test_fit_resilient_entrypoint(devices8, tmp_path):
    xs, ys = _data(128)
    ff = _model(devices8, seed=2, checkpoint_every=2,
                checkpoint_dir=str(tmp_path / "fr"), retry_backoff=0.0)
    rep = ff.fit_resilient(
        xs, ys, epochs=1,
        fault_plan=FaultPlan.single(2, FaultKind.STEP_EXCEPTION),
    )
    assert rep.final_step == 8  # 128 rows / batch 16
    assert rep.counters["restarts"] == 1
    assert len(rep.losses) == 8


def test_fit_resilient_requires_directory(devices8):
    xs, ys = _data(32)
    ff = _model(devices8)
    with pytest.raises(ValueError, match="checkpoint directory"):
        ff.fit_resilient(xs, ys, epochs=1)


def test_supervisor_counters_logged(devices8, tmp_path, caplog):
    """Satellite: counters flow through RecursiveLogger.counters so
    bench runs can report recovery overhead."""
    import logging

    xs, ys = _data(64)
    ff = _model(devices8)
    sup = TrainingSupervisor(ff, str(tmp_path), checkpoint_every=2,
                             sleep=NO_SLEEP)
    with caplog.at_level(logging.INFO, logger="flexflow_tpu.resilience"):
        rep = sup.run(xs, ys, num_steps=4)
    assert rep.counters["checkpoints"] == 3  # anchor@0 + 2 + 4
    assert rep.counters["checkpoint_time_s"] > 0
    text = caplog.text
    assert "supervisor:" in text and "restarts=0" in text
    assert "checkpoint_time_s=" in text


def test_package_exports():
    assert flexflow_tpu.FaultPlan is FaultPlan
    assert flexflow_tpu.TrainingSupervisor is TrainingSupervisor
    assert flexflow_tpu.RetryPolicy is RetryPolicy
    assert flexflow_tpu.FaultKind is FaultKind
