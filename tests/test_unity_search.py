"""Unity substitution-DP search tests.

SURVEY §4 notes the reference never tests its search in isolation
(exercised only via osdi22ae scripts); we test it hermetically —
including a brute-force property check on a tiny graph (SURVEY §7 hard
part 1 calls for exactly this).
"""
import itertools

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.fftype import ActiMode, LossType, OperatorType
from flexflow_tpu.ops.op import ShardConfig
from flexflow_tpu.pcg.substitution import (
    generate_all_pcg_xfers,
    load_substitution_rules,
    op_options,
)
from flexflow_tpu.pcg.unity import UnitySearch
from flexflow_tpu.sim.machine_model import TpuPodModel
from flexflow_tpu.sim.simulator import OpCostModel, Simulator
from flexflow_tpu.strategy import Strategy, apply_strategy, assign_views

pytestmark = pytest.mark.slow  # search/train-heavy: full tier only


def build_mlp(hidden=2048, batch=64, layers=2):
    ff = FFModel(FFConfig())
    x = ff.create_tensor([batch, hidden], name="x")
    t = x
    for i in range(layers):
        t = ff.dense(t, hidden, activation=ActiMode.RELU, name=f"fc{i}")
    return ff


def build_transformer(batch=8, seq=32, hidden=64, layers=4, heads=4):
    from flexflow_tpu.models.transformer import build_bert

    ff = FFModel(FFConfig(batch_size=batch))
    build_bert(ff, batch_size=batch, seq_length=seq, hidden_size=hidden,
               num_layers=layers, num_heads=heads, intermediate_size=hidden * 4)
    return ff


def make_search(ff, n, **kw):
    machine = TpuPodModel(topology=(n,))
    cm = OpCostModel(machine)
    return UnitySearch(ff.layers, n, machine, cm, **kw), machine, cm


# ---------------------------------------------------------------------------
# xfer catalog
# ---------------------------------------------------------------------------

def test_xfer_catalog_options():
    ff = build_mlp(hidden=64, batch=8)
    xfers = generate_all_pcg_xfers()
    fc0 = next(op for op in ff.layers.ops if op.name == "fc0")
    opts = op_options(fc0, {"data": 2, "model": 2}, xfers)
    shards = [c.shard for c in opts]
    assert ShardConfig() in shards
    assert ShardConfig(channel=2) in shards
    # channel comes in both keep-sharded and +combine variants
    # (create_partition_linear_combine's trailing Combine)
    chained = [c for c in opts if c.shard == ShardConfig(channel=2) and c.out_chain]
    assert chained and chained[0].out_chain[0][0] == "combine"
    # reduction gated behind enable_parameter_parallel
    assert ShardConfig(reduction=2) not in shards
    opts_pp = op_options(fc0, {"model": 2}, xfers, enable_parameter_parallel=True)
    assert ShardConfig(reduction=2) in [c.shard for c in opts_pp]


def test_substitution_json_loader(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(
        '{"rules": [{"name": "my_rule", "op_type": "linear", "kind": "channel"}]}'
    )
    rules = load_substitution_rules(str(p))
    assert len(rules) == 1
    assert rules[0].op_type == OperatorType.LINEAR
    with pytest.raises(ValueError):
        p2 = tmp_path / "bad.json"
        p2.write_text('{"rules": [{"op_type": "nope", "kind": "channel"}]}')
        load_substitution_rules(str(p2))


# ---------------------------------------------------------------------------
# graph splitting
# ---------------------------------------------------------------------------

def test_segments_split_at_bottlenecks():
    ff = build_mlp(layers=4)
    search, _, _ = make_search(ff, 4)
    segments, boundaries = search._segments()
    # chain graph: every op boundary is a single-tensor cut
    assert len(segments) >= 4
    assert boundaries[-1] is None
    for b in boundaries[:-1]:
        assert b is not None


def test_transformer_layer_segments_share_cache():
    ff = build_transformer(layers=4)
    search, _, _ = make_search(ff, 4)
    s = search.optimize()
    assert s is not None
    # identical stacked layers must hit the segment cache (Unity's
    # cached_graph_costs trick) — strictly fewer evals than a no-cache run
    assert search.cache_hits > 0


# ---------------------------------------------------------------------------
# search quality
# ---------------------------------------------------------------------------

def simulate_strategy(ff, strategy, machine, cost_model):
    g = apply_strategy(ff.layers, strategy)
    assign_views(g, strategy.mesh_axes)
    sim = Simulator(machine, cost_model)
    return sim.simulate(g, strategy.mesh_axes, training=True)


def test_unity_beats_or_matches_data_parallel():
    # small batch + big weights: pure DP pays a huge grad allreduce, so
    # the search should find a better hybrid (or at worst match DP)
    ff = build_mlp(hidden=4096, batch=8)
    search, machine, cm = make_search(ff, 8)
    best = search.optimize()
    assert best is not None
    from flexflow_tpu.strategy import data_parallel_strategy

    t_best = simulate_strategy(ff, best, machine, cm).total_time
    t_dp = simulate_strategy(ff, data_parallel_strategy(8), machine, cm).total_time
    assert t_best <= t_dp * 1.001


def test_unity_brute_force_property():
    """DP result must match exhaustive enumeration over the same space
    on a tiny chain graph (fixed mesh factorization)."""
    ff = build_mlp(hidden=256, batch=16, layers=3)
    n = 4
    search, machine, cm = make_search(ff, n)
    best = search.optimize()
    assert best is not None
    t_best = simulate_strategy(ff, best, machine, cm).total_time

    # brute force: same factorizations x per-op channel options
    xfers = generate_all_pcg_xfers()
    from flexflow_tpu.pcg.mcmc import _factorizations

    t_min, s_min = np.inf, None
    for dp, tp, ep in _factorizations(n):
        if ep > 1:
            continue
        mesh_axes = {}
        if dp > 1:
            mesh_axes["data"] = dp
        if tp > 1:
            mesh_axes["model"] = tp
        if not mesh_axes:
            mesh_axes["data"] = 1
        cand_ops = [op for op in ff.layers.ops
                    if len(op_options(op, mesh_axes, xfers)) > 1]
        opt_lists = [op_options(op, mesh_axes, xfers) for op in cand_ops]
        for combo in itertools.product(*opt_lists) if opt_lists else [()]:
            s = Strategy(mesh_axes=dict(mesh_axes))
            if dp > 1:
                s.edge_ops["__inputs__"] = [
                    ("repartition", {"dim": 0, "degree": dp})
                ]
            for op, choice in zip(cand_ops, combo):
                if not choice.shard.is_trivial():
                    s.shard_configs[op.name] = choice.shard
                if choice.out_chain:
                    s.edge_ops[op.outputs[0].name] = choice.chain_as_lists()
            try:
                t = simulate_strategy(ff, s, machine, cm).total_time
            except (ValueError,):
                continue
            if t < t_min:
                t_min, s_min = t, s
    # the DP space and cost decomposition differ slightly from the full
    # simulator (overlap credit applied per-op vs globally), so allow 5%
    assert t_best <= t_min * 1.05


def test_unity_memory_lambda_search():
    ff = build_mlp(hidden=2048, batch=64)
    search, machine, cm = make_search(ff, 8)
    free = search.optimize()
    assert free is not None
    mem_free = search._strategy_memory(free)
    # force a budget below the unconstrained strategy's footprint
    search2, _, _ = make_search(ff, 8)
    search2.memory_budget = max(1, mem_free // 2)
    constrained = search2.optimize_with_memory()
    assert constrained is not None
    # binary search should find a strategy within budget when one exists,
    # or at least not a worse-memory one than unconstrained
    assert search2._strategy_memory(constrained) <= mem_free


# ---------------------------------------------------------------------------
# end-to-end: compile with unity search and train a step
# ---------------------------------------------------------------------------

def test_compile_with_unity_search_runs(devices8):
    import jax

    batch = 16
    ff = build_mlp(hidden=64, batch=batch, layers=2)
    # classifier head so sparse CE works
    head = ff.dense(ff.layers.sink_op().outputs[0], 4, name="head")
    ff.config.search_budget = 50
    ff.config.num_devices = 8
    ff.compile(loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8)
    assert ff.strategy is not None
    x = np.random.RandomState(0).randn(batch, 64).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, batch).astype(np.int32)
    m = ff.train_step({"x": x}, y)
    assert np.isfinite(float(m["loss"]))


def test_substitutions_to_dot_tool():
    """tools/substitutions_to_dot renders every catalog rule."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from substitutions_to_dot import to_dot

    from flexflow_tpu.pcg.substitution import generate_all_pcg_xfers

    xfers = generate_all_pcg_xfers()
    dot = to_dot(xfers)
    assert dot.startswith("digraph")
    assert dot.count("subgraph cluster_") == len(xfers)
    for x in xfers:
        assert x.name in dot
