"""Resumable decode handoff (serving/handoff.py + the scheduler/front
pause-resume path, docs/SERVING.md "Mid-decode handoff"): an in-flight
generation is a first-class migratable object.  Covered here: the
ResumeRecord/HandoffPaused contracts, the migrate-vs-replay pricing,
live mid-decode migration off a draining replica (greedy AND seeded
sampling, token-identical to the uninterrupted run), decode-death
recovery through the resume record, the five-way handoff fault matrix
(torn / header / fabric / capacity / dest_death — every fault degrades
to replay with exact tokens and its own counter), terminate() routing
unfinishable generations onto the handoff path, the autoscaler's
KV-occupancy rebalance trigger, loadgen seed stamping, the config
knobs, and the offline FFKV frame verifier (tools/kvframe_fsck.py).
The slow section reruns the pause/resume token-identity oracle through
real trained engines on both paged-attention kernels."""
import threading
import time
import types

import numpy as np
import pytest

from flexflow_tpu.obs.metrics import MetricsRegistry
from flexflow_tpu.resilience.faults import Fault, FaultKind, FaultPlan
from flexflow_tpu.serving import (ContinuousScheduler, InProcessFabric,
                                  KVMigrator, MigrationCostModel,
                                  ServingAutoscaler, ServingFront)
from flexflow_tpu.serving.handoff import (HANDOFF_FAULTS, HandoffPaused,
                                          ResumeRecord,
                                          classify_handoff_fault)
from flexflow_tpu.serving.kv_transfer import (KVTransferError,
                                              pack_kv_blocks)

V = 16
NO_SLEEP = lambda s: None  # noqa: E731


class FakeKVModel:
    """Deterministic next-token model with an exportable KV surface:
    token t emits t+1 mod V, so completions have a closed form and any
    corruption shows up as wrong tokens."""

    def __init__(self, batch_slots=2, max_seq=32, page_size=4):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = 1 + batch_slots * self.max_blocks_per_seq
        self.vocab = V
        self.steps = 0
        self.kv = np.zeros((self.num_blocks, page_size, 2), np.float32)

    def reset(self):
        pass

    def step(self, tokens, seq_lens, block_tables):
        self.steps += 1
        logits = np.zeros((self.batch_slots, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits

    def export_block(self, block):
        return {"kv": np.array(self.kv[block])}

    def import_block(self, block, arrays):
        self.kv[block] = arrays["kv"]


class GatedModel(FakeKVModel):
    """Pins a generation mid-decode: the step that would cross
    `block_at` waits on the gate, so the pause service (queued behind
    it) runs with the sequence deterministically in flight."""

    def __init__(self, block_at=0, **kw):
        super().__init__(**kw)
        self.block_at = block_at
        self.gate = threading.Event()

    def step(self, tokens, seq_lens, block_tables):
        if self.block_at and self.steps + 1 >= self.block_at:
            self.gate.wait(10.0)
        return super().step(tokens, seq_lens, block_tables)


def expected(prompt, mnt):
    out = list(prompt)
    t = prompt[-1]
    for _ in range(mnt):
        t = (t + 1) % V
        out.append(t)
    return out


def kill_on_steps(steps, kind=FaultKind.HUNG_STEP):
    return FaultPlan([Fault(step=s, kind=kind) for s in steps])


def _wait_for(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.002)
    return False


def gated_fleet(reg=None, block_at=10, num_replicas=2, **kw):
    """Front over GatedModels (every replica gated at the same step
    count — dispatch decides the holder, the test finds it)."""
    models = {}

    def factory(rid, survivors=None):
        m = GatedModel(block_at=block_at)
        models[rid] = m
        return m

    front = ServingFront(factory, num_replicas=num_replicas,
                         handoff=True, registry=reg, sleep=NO_SLEEP,
                         **kw)
    return front, models


def find_pinned(front, models, timeout=10.0):
    """The replica whose gated model is blocked inside a step with a
    request in flight — the handoff source."""
    src = [None]

    def probe():
        for r in front.replicas:
            m = models.get(r.replica_id)
            if (m is not None and m.block_at
                    and m.steps >= m.block_at - 1 and r.outstanding):
                src[0] = r
                return True
        return False

    assert _wait_for(probe, timeout), "no replica pinned mid-decode"
    return src[0]


def release(models):
    for m in models.values():
        m.gate.set()


# -- resume record / fault classification units --------------------------

def test_resume_record_replays_prompt_plus_generated():
    rec = ResumeRecord([1, 2, 3], [4, 5], written=4, seed=9,
                       temperature=0.0, page_size=4)
    assert rec.replay_tokens() == [1, 2, 3, 4, 5]
    assert rec.written == 4 and rec.seed == 9
    assert rec.kv_tail is None  # stamped only by a verified handoff


def test_classify_handoff_fault_covers_the_matrix():
    assert classify_handoff_fault("no block verified") == "torn"
    assert classify_handoff_fault("torn") == "torn"
    assert classify_handoff_fault("capacity") == "capacity"
    for why in ("target gone", "target closed", "migrator closed",
                "device write"):
        assert classify_handoff_fault(why) == "dest_death"
    # a transfer failure splits on the exception: frame damage is
    # "header", anything else is the fabric itself
    assert classify_handoff_fault(
        "transfer", KVTransferError("bad magic")) == "header"
    assert classify_handoff_fault(
        "transfer", RuntimeError("link down")) == "fabric"
    assert classify_handoff_fault(None) == "fabric"
    for kind in ("torn", "header", "fabric", "capacity", "dest_death"):
        assert kind in HANDOFF_FAULTS


def test_decide_handoff_prices_blocks_against_replay():
    m = MigrationCostModel(fabric_kind="inproc")
    d = m.decide_handoff(written=40, page_size=4, block_bytes=4096,
                         chunk=4, step_s=5e-3)
    # 10 blocks over ICI ~ microseconds vs replaying 40 tokens
    assert d["decision"] == "handoff" and d["blocks"] == 10
    assert d["handoff_s"] < d["replay_s"]
    # a giant payload over DCN costs more than recomputing it
    big = MigrationCostModel(fabric_kind="blob").decide_handoff(
        written=8, page_size=4, block_bytes=10 << 30, chunk=0,
        step_s=5e-3)
    assert big["decision"] == "replay"
    assert big["handoff_s"] > big["replay_s"]
    # the longer a sequence has decoded, the more a handoff is worth
    short = m.decide_handoff(written=8, page_size=4, block_bytes=4096,
                             chunk=0, step_s=5e-3)
    assert d["replay_s"] > short["replay_s"]


def test_decide_handoff_nothing_written_replays():
    m = MigrationCostModel()
    d = m.decide_handoff(written=0, page_size=4, block_bytes=0,
                         chunk=0, step_s=5e-3)
    assert d["decision"] == "replay" and d["blocks"] == 0


# -- live mid-decode migration -------------------------------------------

def test_drain_migrates_live_generation_token_identical():
    """The tentpole e2e: a generation pinned mid-decode on a draining
    replica pauses, its KV blocks stream to a peer, and it resumes
    there token-identically — drain never waits out (or drops) the
    long generation."""
    reg = MetricsRegistry()
    front, models = gated_fleet(reg)
    try:
        prompt = [1, 2, 3, 4, 5, 6, 7]
        h = front.generate_async(prompt, 12)
        src = find_pinned(front, models)
        assert front.drain_replica(src)
        release(models)
        assert h.wait(30.0) == expected(prompt, 12)
        assert _wait_for(lambda: src.state == "retired")
        st = front.stats()
    finally:
        front.close()
    ho = st["handoff"]
    assert ho["requested"] >= 1 and ho["ok"] >= 1
    assert ho["migrate_decisions"] >= 1 and ho["faults"] == {}
    assert ho["kv_transfer"]["blocks_streamed"] >= 2
    assert ho["kv_transfer"]["bytes_streamed"] > 0
    assert reg.counter("serving/handoff_paused").value >= 1
    assert reg.counter("serving/handoff_resumed").value >= 1
    # a pause is not a failure: no retry burned, no requeue counted
    assert h.retries == 0
    assert h.resume is not None and h.resume.generated


def test_live_handoff_imports_the_partial_tail_block():
    """written = 7 prompt + ~3 generated is never page-aligned here,
    so the verified sub-page tail must land through import_block
    instead of replaying."""
    reg = MetricsRegistry()
    front, models = gated_fleet(reg)
    try:
        h = front.generate_async([1, 2, 3, 4, 5, 6, 7], 12)
        src = find_pinned(front, models)
        assert front.drain_replica(src)
        release(models)
        assert h.wait(30.0) == expected([1, 2, 3, 4, 5, 6, 7], 12)
    finally:
        front.close()
    assert reg.counter("serving/handoff_tail_imports").value >= 1
    # the resumed admission was a real prefix-cache hit on the dest
    assert h.resume.kv_tail is not None


def test_seeded_sampling_resumes_the_exact_rng_stream():
    """temperature > 0: the resume record carries the host RNG state,
    so the migrated continuation draws the exact tokens the
    uninterrupted run would have — same front seed, same output."""
    prompt, mnt, temp = [1, 2, 3, 4, 5, 6, 7], 12, 0.8
    oracle = ServingFront(
        lambda rid, survivors=None: FakeKVModel(), num_replicas=2,
        seed=42, sleep=NO_SLEEP)
    try:
        want = oracle.generate_async(prompt, mnt, temp).wait(30.0)
    finally:
        oracle.close()
    reg = MetricsRegistry()
    front, models = gated_fleet(reg, seed=42)
    try:
        h = front.generate_async(prompt, mnt, temp)
        src = find_pinned(front, models)
        assert front.drain_replica(src)
        release(models)
        got = h.wait(30.0)
    finally:
        front.close()
    assert got == want
    assert reg.counter("serving/handoff_resumed").value >= 1
    assert h.resume is not None and h.resume.rng_state is not None


# -- decode-death recovery through the resume record ---------------------

def test_replica_death_resumes_by_replay_not_from_scratch():
    """A dying scheduler stamps the resume record on its way out (the
    tokens live on the host — a dead device cannot tear them): the
    requeue replays prompt+generated and completes token-identically,
    counted as a handoff replay."""
    reg = MetricsRegistry()
    front = ServingFront(
        lambda rid, survivors=None: FakeKVModel(), num_replicas=2,
        registry=reg, sleep=NO_SLEEP, retry_backoff=0.0,
        fault_plans={0: kill_on_steps([4])},
    )
    try:
        reqs = [([1 + i, 2], 8) for i in range(6)]
        hs = [front.generate_async(p, m) for p, m in reqs]
        for h, (p, m) in zip(hs, reqs):
            assert h.wait(30.0) == expected(p, m)
        assert front.handoff_replays >= 1
    finally:
        front.close()
    assert reg.counter("serving/handoff_replays").value >= 1
    assert reg.counter("serving/handoff_resumed").value >= 1
    resumed = [h for h in hs if h.resume is not None]
    assert resumed and all(h.retries >= 1 for h in resumed)
    # death recovery replays the dead replica's progress, never
    # regenerates: the record held real generated tokens
    assert any(h.resume.generated for h in resumed)


# -- the five-way fault matrix -------------------------------------------

class TearingFabric(InProcessFabric):
    """Returns only the frame header: zero blocks verify."""

    def transfer(self, key, data):
        import struct

        got = super().transfer(key, data)
        hlen = struct.unpack("<I", got[4:8])[0]
        return got[:8 + hlen]


class MangledHeaderFabric(InProcessFabric):
    """Flips the magic: unpack raises KVTransferError."""

    def transfer(self, key, data):
        got = bytearray(super().transfer(key, data))
        got[0] ^= 0xFF
        return bytes(got)


class DeadFabric(InProcessFabric):
    def transfer(self, key, data):
        raise RuntimeError("fabric down")


def run_faulted_handoff(reg, front, models):
    prompt = [1, 2, 3, 4, 5, 6, 7]
    h = front.generate_async(prompt, 12)
    src = find_pinned(front, models)
    assert front.drain_replica(src)
    release(models)
    assert h.wait(30.0) == expected(prompt, 12)
    return h


@pytest.mark.parametrize("fabric_cls,kind", [
    (TearingFabric, "torn"),
    (MangledHeaderFabric, "header"),
    (DeadFabric, "fabric"),
])
def test_stream_faults_degrade_to_replay(fabric_cls, kind):
    """Torn stream / corrupt header / fabric outage: the live path
    fails, its own counter increments, and the resume record alone
    replays to the exact tokens."""
    reg = MetricsRegistry()
    front, models = gated_fleet(reg)
    front._handoff_mig = KVMigrator(fabric_cls(), registry=reg,
                                    logger=front.log)
    try:
        run_faulted_handoff(reg, front, models)
        st = front.stats()
    finally:
        front.close()
    ho = st["handoff"]
    assert ho["ok"] == 0 and ho["replays"] >= 1
    assert ho["faults"].get(kind, 0) >= 1
    assert reg.counter(f"serving/handoff_fault_{kind}").value >= 1
    assert reg.counter("serving/handoff_replays").value >= 1


class _StubDestFront(ServingFront):
    """Routes the KV stream at a caller-chosen destination engine (the
    request itself still resumes on the real fleet)."""

    stub_dest = None

    def _pick_handoff_dest(self, source, toks):
        return self.stub_dest


def gated_stub_fleet(reg, **kw):
    models = {}

    def factory(rid, survivors=None):
        m = GatedModel(block_at=12)
        models[rid] = m
        return m

    front = _StubDestFront(factory, num_replicas=2, handoff=True,
                           registry=reg, sleep=NO_SLEEP, **kw)
    return front, models


class TinyPoolModel(FakeKVModel):
    """One usable KV block: adoption of a multi-block stream must stop
    early — the capacity fault."""

    def __init__(self, num_blocks=2, **kw):
        super().__init__(**kw)
        self.num_blocks = num_blocks
        self.kv = np.zeros((num_blocks, self.page_size, 2), np.float32)


def test_capacity_exhaustion_on_destination_degrades_to_replay():
    reg = MetricsRegistry()
    front, models = gated_stub_fleet(reg)
    tiny = ContinuousScheduler(TinyPoolModel())
    front.stub_dest = types.SimpleNamespace(
        scheduler=tiny, replica_id=99, outstanding=0, role="decode")
    try:
        run_faulted_handoff(reg, front, models)
        st = front.stats()
    finally:
        front.close()
        tiny.close()
    ho = st["handoff"]
    assert ho["ok"] == 0 and ho["replays"] >= 1
    assert ho["faults"].get("capacity", 0) >= 1
    assert reg.counter("serving/handoff_fault_capacity").value >= 1


def test_destination_death_mid_stream_degrades_to_replay():
    reg = MetricsRegistry()
    front, models = gated_stub_fleet(reg)
    dead = ContinuousScheduler(FakeKVModel())
    dead.close()  # run_on_worker now refuses: the dest died
    front.stub_dest = types.SimpleNamespace(
        scheduler=dead, replica_id=99, outstanding=0, role="decode")
    try:
        run_faulted_handoff(reg, front, models)
        st = front.stats()
    finally:
        front.close()
    ho = st["handoff"]
    assert ho["ok"] == 0 and ho["replays"] >= 1
    assert ho["faults"].get("dest_death", 0) >= 1
    assert reg.counter("serving/handoff_fault_dest_death").value >= 1


# -- terminate / drain integration ---------------------------------------

def test_terminate_handoff_budget_is_deadline_over_step_ewma():
    """The unfinishable bar: remaining_over = time-left / measured
    per-step EWMA — a sequence that cannot finish inside the grace
    window takes the handoff path; one that can keeps decoding."""
    front, models = gated_fleet()
    try:
        captured = {}
        r = front.replicas[0]
        sched = r.scheduler
        sched.step_ms_ewma = 100.0  # 0.1s per step
        r.request_handoff = lambda **kw: captured.update(kw) or True
        front._terminate_handoff(r, time.monotonic() + 2.0)
        assert 15 <= captured["remaining_over"] <= 20  # ~2.0 / 0.1
        assert captured["export_kv"] is True
        # an unmeasured engine falls back to the default step cost
        sched.step_ms_ewma = 0.0
        front._terminate_handoff(r, time.monotonic() + 2.0)
        assert captured["remaining_over"] >= 1
        del r.request_handoff  # restore the class method for close()
    finally:
        release(models)
        front.close()


def test_unfinishable_generation_hands_off_before_the_bell():
    """A pinned long generation whose holder measures 100s/step can
    never finish inside the grace window: _terminate_handoff pauses
    it and it completes token-identically on the peer."""
    reg = MetricsRegistry()
    front, models = gated_fleet(reg)
    try:
        prompt = [1, 2, 3, 4, 5, 6, 7]
        h = front.generate_async(prompt, 12)
        src = find_pinned(front, models)
        src.scheduler.step_ms_ewma = 100_000.0
        front._terminate_handoff(src, time.monotonic() + 5.0)
        release(models)
        assert h.wait(30.0) == expected(prompt, 12)
    finally:
        front.close()
    assert reg.counter("serving/handoff_requested").value >= 1
    assert reg.counter("serving/handoff_resumed").value >= 1
    assert h.resume is not None


def test_terminate_completes_the_long_generation():
    """SIGTERM grace with handoff on: the in-flight long generation is
    never shed — terminate reports it completed and exact."""
    reg = MetricsRegistry()
    front, models = gated_fleet(reg)
    prompt = [1, 2, 3, 4, 5, 6, 7]
    h = front.generate_async(prompt, 20)
    find_pinned(front, models)
    release(models)
    report = front.terminate(deadline_s=20.0)
    assert h.wait(5.0) == expected(prompt, 20)
    assert report["shed"] == 0 and report["deadline_met"]
    assert report["completed_during_drain"] >= 1


# -- autoscaler KV-occupancy rebalance -----------------------------------

def test_autoscaler_rebalance_moves_a_whale_off_the_hot_pool():
    reg = MetricsRegistry()
    front, models = gated_fleet(reg)
    aut = ServingAutoscaler(front, 1, 2, rebalance_kv=0.8,
                            cooldown_s=5.0, registry=reg)
    try:
        prompt = [1, 2, 3, 4, 5, 6, 7]
        h = front.generate_async(prompt, 12)
        src = find_pinned(front, models)
        cool = [r for r in front.replicas if r is not src][0]
        src.scheduler.pool.occupancy = lambda: 0.95
        cool.scheduler.pool.occupancy = lambda: 0.10
        aut._maybe_rebalance({"t": 100.0})
        assert aut.rebalances == 1
        # its own cooldown: the hot pool cannot shed every tick
        aut._maybe_rebalance({"t": 101.0})
        assert aut.rebalances == 1
        release(models)
        assert h.wait(30.0) == expected(prompt, 12)
    finally:
        front.close()
    assert reg.counter("serving/handoff_rebalance").value == 1
    assert reg.counter("serving/handoff_resumed").value >= 1


def test_autoscaler_rejects_bad_rebalance_threshold():
    front, models = gated_fleet()
    try:
        with pytest.raises(ValueError, match="rebalance_kv"):
            ServingAutoscaler(front, 1, 2, rebalance_kv=1.5)
    finally:
        release(models)
        front.close()


# -- satellites: loadgen seed stamping + config knobs --------------------

def test_loadgen_records_carry_the_front_minted_seed():
    from flexflow_tpu.serving.loadgen import run_loadgen

    front = ServingFront(
        lambda rid, survivors=None: FakeKVModel(), num_replicas=2,
        seed=3, sleep=NO_SLEEP)
    try:
        rep = run_loadgen(front, [([1, 2], 4)] * 4, rate_rps=500.0,
                          detail=True, timeout_s=30.0)
    finally:
        front.close()
    recs = [r for r in rep["records"] if r["ok"]]
    assert len(recs) == 4
    seeds = [r["seed"] for r in recs]
    assert all(isinstance(s, int) for s in seeds)
    # distinct per request: a replayed record is independently exact
    assert len(set(seeds)) == 4


def test_config_handoff_knobs_parse_and_validate():
    from flexflow_tpu.config import FFConfig

    cfg = FFConfig.from_args(["--serving-handoff",
                              "--serving-rebalance-kv", "0.8"])
    assert cfg.serving_handoff is True
    assert cfg.serving_rebalance_kv == 0.8
    assert FFConfig.from_args([]).serving_handoff is False
    with pytest.raises(ValueError, match="needs --serving-handoff"):
        FFConfig.from_args(["--serving-rebalance-kv", "0.5"])
    with pytest.raises(ValueError, match="rebalance_kv must be"):
        FFConfig.from_args(["--serving-handoff",
                            "--serving-rebalance-kv", "1.5"])


# -- offline FFKV frame verifier (tools/kvframe_fsck.py) -----------------

def _frame(pages=((1, 2, 3, 4), (5, 6))):
    pages = [list(p) for p in pages]
    blocks = [{"kv": np.full((4, 2), float(p[0]), np.float32)}
              for p in pages]
    return pack_kv_blocks(pages, blocks, 4)


def test_kvframe_fsck_passes_a_good_frame(tmp_path):
    from tools import kvframe_fsck

    (tmp_path / "a.ffkv").write_bytes(_frame())
    assert kvframe_fsck.main([str(tmp_path)]) == 0
    assert kvframe_fsck.fsck_frame(_frame()) == []


def test_kvframe_fsck_flags_torn_and_corrupt_frames(tmp_path):
    from tools import kvframe_fsck

    good = _frame()
    (tmp_path / "torn.ffkv").write_bytes(good[:len(good) - 3])
    flipped = bytearray(good)
    flipped[-1] ^= 0xFF  # payload byte: crc mismatch
    (tmp_path / "crc.ffkv").write_bytes(bytes(flipped))
    assert kvframe_fsck.main([str(tmp_path)]) == 1
    report = kvframe_fsck.fsck_paths([str(tmp_path)])
    assert not report["frames"][str(tmp_path / "torn.ffkv")]["ok"]
    assert not report["frames"][str(tmp_path / "crc.ffkv")]["ok"]


def test_kvframe_fsck_flags_interior_partial_page():
    from tools import kvframe_fsck

    pages = [[1, 2], [3, 4, 5, 6]]  # only the LAST page may be partial
    blocks = [{"kv": np.zeros((4, 2), np.float32)} for _ in pages]
    problems = kvframe_fsck.fsck_frame(pack_kv_blocks(pages, blocks, 4))
    assert any("partial" in p for p in problems)


def test_kvframe_fsck_missing_path_is_usage_error(tmp_path):
    from tools import kvframe_fsck

    assert kvframe_fsck.main([str(tmp_path / "nope")]) == 2
    # an existing but frame-less directory is a finding, not usage
    assert kvframe_fsck.main([str(tmp_path)]) == 1


# -- real engines (full tier) --------------------------------------------

V_GPT, S_GPT, B_GPT = 32, 16, 4
PROMPT_GPT = [3, 5, 7, 2]
MNT_GPT = 11


@pytest.fixture(scope="module")
def trained(devices8):
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt

    ff = FFModel(FFConfig(batch_size=B_GPT, num_devices=1))
    build_gpt(ff, batch_size=B_GPT, seq_length=S_GPT, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V_GPT)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devices8[:1])
    rng = np.random.RandomState(0)
    start = rng.randint(0, V_GPT, (B_GPT, 1))
    step = rng.randint(1, 6, (B_GPT, 1))
    seq_ids = (start + step * np.arange(S_GPT + 1)) % V_GPT
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S_GPT, dtype=np.int32),
                          (B_GPT, S_GPT)).copy()
    for _ in range(40):
        ff.train_step({"input": ids, "positions": pos}, labels)
    return ff


def configure_serving(ff, kernel):
    cfg = ff.config
    cfg.serving_slots = 2
    cfg.kv_page_size = 4
    cfg.kv_pool_blocks = 12
    cfg.paged_kernel = kernel
    cfg.prefill_chunk = 4 if kernel == "pallas" else 0
    return cfg


def _pause_in_flight(front, h, attempts=400):
    """Catch the request mid-decode and pause it directly (the same
    scheduler service drain/terminate/rebalance use).  The window is
    the whole generation, so a handful of polls lands it."""
    for _ in range(attempts):
        for r in front.replicas:
            if r.outstanding and r.state == "live":
                r.request_handoff(remaining_over=0, export_kv=True)
                return True
        if h.event.is_set():
            return False
        time.sleep(0.001)
    return False


@pytest.mark.slow
@pytest.mark.parametrize("kernel", ["gather", "pallas"])
@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_mid_decode_handoff_token_identity_real_engine(
        trained, devices8, kernel, temperature):
    """The PR's acceptance oracle on real engines: a generation paused
    mid-decode and migrated (or replayed) across replicas is
    byte-identical to the uninterrupted run — greedy AND seeded
    sampling, both paged-attention kernels, invariant checker armed."""
    configure_serving(trained, kernel)
    attempts = 5  # the pause races a fast completion
    # the oracle mints the SAME per-request seed sequence (admission
    # order), so attempt i on the handoff front samples identically
    # to oracle request i
    oracle = ServingFront.from_trained(
        trained, num_replicas=2, devices=devices8[:1], seed=5,
        check_invariants=True)
    try:
        wants = [oracle.generate_async(
            PROMPT_GPT, MNT_GPT, temperature).wait(240.0)
            for _ in range(attempts)]
    finally:
        oracle.close()

    front = ServingFront.from_trained(
        trained, num_replicas=2, devices=devices8[:1], seed=5,
        handoff=True, check_invariants=True)
    try:
        paused = False
        for i in range(attempts):
            h = front.generate_async(PROMPT_GPT, MNT_GPT, temperature)
            _pause_in_flight(front, h)
            got = h.wait(240.0)
            assert got == wants[i]  # exact either way — that's the point
            if _wait_for(lambda: front.handoff_requested >= 1, 2.0):
                paused = True
                break
        st = front.stats()
    finally:
        front.close()
    assert paused, "generation never caught in flight"
    assert st["handoff"]["requested"] >= 1
    # every pause resolved: a live adopt or an exact replay
    assert (st["handoff"]["ok"] + st["handoff"]["replays"]
            == st["handoff"]["requested"])


@pytest.mark.slow
@pytest.mark.parametrize("kernel", ["gather", "pallas"])
def test_decode_death_replay_token_identity_real_engine(
        trained, devices8, kernel):
    """Kill a real decode replica mid-generation: the resume record
    replays on the survivor and every completion matches the
    fault-free oracle byte-for-byte."""
    configure_serving(trained, kernel)
    prompts = [PROMPT_GPT, [9, 4, 1], [8, 2], [5, 5, 5, 5]]
    mnts = [11, 8, 7, 6]
    oracle = ServingFront.from_trained(
        trained, num_replicas=2, devices=devices8[:1],
        check_invariants=True)
    try:
        want = [oracle.generate_async(p, m).wait(240.0)
                for p, m in zip(prompts, mnts)]
    finally:
        oracle.close()

    front = ServingFront.from_trained(
        trained, num_replicas=2, devices=devices8[:1],
        check_invariants=True, retry_backoff=0.0,
        fault_plans={0: kill_on_steps([6])})
    try:
        hs = [front.generate_async(p, m)
              for p, m in zip(prompts, mnts)]
        got = [h.wait(240.0) for h in hs]
    finally:
        front.close()
    assert got == want
    assert front.replicas[0].deaths == 1
