"""Graph-rewrite substitution engine tests.

Reference parity: GraphXfer match/apply (substitution.cc:1898-1945),
TASO merge rules (substitutions/graph_subst_3_v2.json), parallel-op
chain cancellation, and base_optimize's bounded rewrite enumeration
(substitution.cc:2229-2320) — here verified for semantic preservation
(the property the reference never tests hermetically).
"""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import ActiMode, OperatorType
from flexflow_tpu.pcg.rewrite import (
    CancelInverseParallel,
    FuseActivation,
    MergeParallelOps,
    apply_rewrites,
    cancel_all_inverse_parallel_ops,
    enumerate_variants,
    generate_rewrite_rules,
)
from flexflow_tpu.strategy import Strategy, data_parallel_strategy


def _mlp_with_relu(num_devices=1):
    cfg = FFConfig(batch_size=8, num_devices=num_devices)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16], name="x")
    t = ff.dense(x, 32, name="fc1")  # no fused activation
    t = ff.relu(t)
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t)
    return ff


def _branchy(num_devices=1):
    cfg = FFConfig(batch_size=8, num_devices=num_devices)
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16], name="x")
    a = ff.dense(x, 12, name="fa")
    b = ff.dense(x, 20, name="fb")
    t = ff.concat([a, b], axis=1)
    t = ff.dense(t, 4, name="fc")
    ff.softmax(t)
    return ff


def test_fuse_activation_match_and_apply():
    ff = _mlp_with_relu()
    rule = FuseActivation(OperatorType.LINEAR)
    matches = rule.find_matches(ff.layers)
    assert len(matches) == 1
    g2 = rule.apply(ff.layers, matches[0])
    assert g2 is not None
    types = [op.op_type for op in g2.topo_order()]
    assert OperatorType.ELEMENT_UNARY not in types
    fused = next(op for op in g2.ops if op.name == "fc1")
    assert fused.params.activation == ActiMode.RELU


def test_fuse_activation_numeric_equivalence(devices8):
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    ff_a = _mlp_with_relu()
    ff_a.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])
    out_a = ff_a.forward({"x": x})

    ff_b = _mlp_with_relu()
    s = data_parallel_strategy(1)
    s.rewrites = [["fuse_linear_activation", 0]]
    ff_b.compile(optimizer=SGDOptimizer(lr=0.01), strategy=s,
                 devices=devices8[:1])
    # names preserved by the fuse rule -> weights transfer directly
    ff_b.set_weights(ff_a.get_weights())
    out_b = ff_b.forward({"x": x})
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-5, atol=1e-5)


def test_merge_parallel_linears_numeric_equivalence(devices8):
    x = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    ff_a = _branchy()
    ff_a.compile(optimizer=SGDOptimizer(lr=0.01), devices=devices8[:1])

    ff_b = _branchy()
    rule = MergeParallelOps(OperatorType.LINEAR)
    matches = rule.find_matches(ff_b.layers)
    assert len(matches) == 1 and len(matches[0].ops) == 2
    s = data_parallel_strategy(1)
    s.rewrites = [["merge_parallel_linear", 0]]
    ff_b.compile(optimizer=SGDOptimizer(lr=0.01), strategy=s,
                 devices=devices8[:1])
    wb = ff_b.get_weights()
    assert "merged_fa" in wb
    # split the merged weight back into the unmerged model's params
    wa = ff_a.get_weights()
    wa["fa"]["kernel"] = wb["merged_fa"]["kernel"][:, :12]
    wa["fb"]["kernel"] = wb["merged_fa"]["kernel"][:, 12:]
    wa["fa"]["bias"] = wb["merged_fa"]["bias"][:12]
    wa["fb"]["bias"] = wb["merged_fa"]["bias"][12:]
    wa["fc"] = wb["fc"]
    ff_a.set_weights(wa)
    out_a = ff_a.forward({"x": x})
    out_b = ff_b.forward({"x": x})
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-5, atol=1e-5)


def test_cancel_inverse_parallel_ops():
    ff = _mlp_with_relu(num_devices=4)
    s = Strategy(mesh_axes={"data": 4})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 4})]
    # a pointless gather+rescatter boundary on fc1's output
    s.edge_ops["fc1.out0"] = [
        ("combine", {"dim": 0, "degree": 4}),
        ("repartition", {"dim": 0, "degree": 4}),
    ]
    from flexflow_tpu.strategy import apply_strategy

    g = apply_strategy(ff.layers, s)
    n_parallel = sum(1 for op in g.ops if op.is_parallel_op())
    g2 = cancel_all_inverse_parallel_ops(g)
    assert sum(1 for op in g2.ops if op.is_parallel_op()) == n_parallel - 2
    # shapes across the cancelled boundary unchanged
    fc2 = next(op for op in g2.ops if op.name == "fc2")
    assert fc2.inputs[0].shape.degrees != ()  # still a parallel shape


def test_cancelled_boundary_trains(devices8):
    """End-to-end: a strategy with a cancellable boundary compiles and
    the cancellation pass removed the pair before lowering."""
    ff = _mlp_with_relu(num_devices=4)
    s = Strategy(mesh_axes={"data": 4})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": 4})]
    s.edge_ops["fc1.out0"] = [
        ("combine", {"dim": 0, "degree": 4}),
        ("repartition", {"dim": 0, "degree": 4}),
    ]
    ff.compile(optimizer=SGDOptimizer(lr=0.01), strategy=s,
               devices=devices8[:4])
    assert not any(
        op.op_type in (OperatorType.COMBINE, OperatorType.REPARTITION)
        and op.name.startswith(("combine_fc1", "repartition_combine"))
        for op in ff.operators.ops
    )
    x = np.random.randn(8, 16).astype(np.float32)
    y = np.random.randint(0, 4, size=(8,))
    m = ff.train_step({"x": x}, y)
    assert np.isfinite(float(m["loss"]))


def test_enumerate_variants_semantics_preserved():
    """Property test vs brute force: every enumerated variant keeps the
    sink's logical output shape and has a valid topo order."""
    ff = _branchy()
    variants = enumerate_variants(ff.layers, generate_rewrite_rules(),
                                  max_depth=2, max_variants=12)
    assert len(variants) >= 2  # original + at least the merge
    ref_shape = ff.layers.sink_op().outputs[0].shape.logical_shape
    for g, trace in variants:
        g.topo_order()  # no cycles
        assert g.sink_op().outputs[0].shape.logical_shape == ref_shape
    traces = [tuple(map(tuple, t)) for _, t in variants]
    assert len(set(traces)) == len(traces)  # deduped


def test_apply_rewrites_replay_matches_enumeration():
    ff = _branchy()
    variants = enumerate_variants(ff.layers, generate_rewrite_rules(),
                                  max_depth=2, max_variants=12)
    for g, trace in variants[1:]:
        replayed = apply_rewrites(ff.layers, trace)
        assert replayed.hash_key() == g.hash_key()


def test_strategy_json_roundtrip_with_rewrites(tmp_path):
    s = data_parallel_strategy(4)
    s.rewrites = [["fuse_linear_activation", 0], ["merge_parallel_linear", 1]]
    p = tmp_path / "s.json"
    s.save(str(p))
    s2 = Strategy.load(str(p))
    assert s2.rewrites == [["fuse_linear_activation", 0],
                           ["merge_parallel_linear", 1]]


def test_json_rewrite_rule_loading(tmp_path):
    import json

    from flexflow_tpu.pcg.rewrite import load_rewrite_rules

    p = tmp_path / "rules.json"
    p.write_text(json.dumps({
        "rewrites": [
            {"type": "fuse_activation", "op_type": "linear"},
            {"type": "merge_parallel", "op_type": "conv2d"},
            {"type": "cancel_inverse_parallel_ops"},
        ]
    }))
    rules = load_rewrite_rules(str(p))
    assert [r.name for r in rules] == [
        "fuse_linear_activation",
        "merge_parallel_conv2d",
        "cancel_inverse_parallel_ops",
    ]
    with pytest.raises(ValueError):
        p2 = tmp_path / "bad.json"
        p2.write_text(json.dumps({"rewrites": [{"type": "nope"}]}))
        load_rewrite_rules(str(p2))


def test_unity_search_considers_rewrites():
    """The Unity DP ranks rewritten variants and records the winning
    trace on the strategy (InceptionV3-style branch merging improves
    simulated time)."""
    from flexflow_tpu.pcg.unity import UnitySearch
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel

    ff = _branchy(num_devices=4)
    machine = TpuPodModel(topology=(2, 2))
    search = UnitySearch(ff.layers, 4, machine, OpCostModel(machine))
    assert len(search._variants()) >= 2
    best = search.optimize()
    assert best is not None
    # searched strategy must be applicable end to end
    from flexflow_tpu.pcg.rewrite import apply_rewrites as rep
    from flexflow_tpu.strategy import apply_strategy, assign_views

    g = rep(ff.layers, best.rewrites) if best.rewrites else ff.layers
    pg = apply_strategy(g, best)
    assign_views(pg, best.mesh_axes)


def test_inception_search_applies_improving_rewrite(devices8):
    """VERDICT r1 #4 'done' criterion: InceptionV3's searched strategy
    applies >=1 graph rewrite (parallel 1x1-conv branch merge) that
    improves the simulated objective, and the rewritten strategy
    compiles and trains end to end."""
    from flexflow_tpu.models.inception import build_inception_v3
    from flexflow_tpu.pcg.unity import UnitySearch
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel

    ff = FFModel(FFConfig(batch_size=8, num_devices=4))
    build_inception_v3(ff, batch_size=8, image_size=75, channel_scale=0.25)
    machine = TpuPodModel(topology=(2, 2))
    search = UnitySearch(ff.layers, 4, machine, OpCostModel(machine),
                         rewrite_max_variants=3, event_rerank=False)
    collector = []
    for graph, trace in search._variants():
        search._set_graph(graph)
        before = len(collector)
        search._optimize_graph(0.0, collector)
        for i in range(before, len(collector)):
            collector[i][1].rewrites = [list(r) for r in trace]
    search._set_graph(search._base_graph)
    assert collector
    collector.sort(key=lambda c: c[0])
    best_obj, best, _ = collector[0]
    assert best.rewrites, "no rewrite in the winning inception strategy"
    # the same mesh WITHOUT the rewrite must be strictly worse
    unrewritten = [
        obj for obj, s, _ in collector
        if not s.rewrites and s.mesh_axes == best.mesh_axes
    ]
    assert unrewritten and best_obj < min(unrewritten)

    ff.compile(optimizer=SGDOptimizer(lr=0.01), strategy=best,
               devices=devices8[:4])
    assert any(op.name.startswith("merged_") for op in ff.operators.ops)
    x = np.random.RandomState(0).randn(8, 3, 75, 75).astype(np.float32)
    y = np.random.randint(0, 10, (8,))
    m = ff.train_step({"input": x}, y)
    assert np.isfinite(float(m["loss"]))
