"""Continuous-batching scheduler logic (serving/scheduler.py) against
a deterministic fake step model — admission/retirement interleaving,
fault isolation (in-flight fails, queued survives), close-drain, SLO
telemetry, and the serve_http satellites (timeout_s -> 503, degraded
health, continuous /v2/stats) — all without compiling a real model."""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from flexflow_tpu.obs.metrics import MetricsRegistry
from flexflow_tpu.serving import ContinuousScheduler, KVPool
from flexflow_tpu.serving.loadgen import run_loadgen, sample_workload
from flexflow_tpu.serving.server import serve_http

V = 16


class FakeStepModel:
    """Pure-host stand-in for PagedKVDecodeModel: the next token is
    always (input token + 1) % vocab, delivered as one-hot logits, so
    greedy expectations are computable in closed form.  Optional
    per-step delay (close-drain tests) and scripted failures.
    prefill_chunk/prefix_cache mirror the real model's knobs — the
    fake has no device cache, so prefill_step/copy_block just record
    calls (scheduler logic is what's under test here)."""

    def __init__(self, batch_slots=2, max_seq=32, page_size=4,
                 num_blocks=None, delay_s=0.0, prefill_chunk=0,
                 prefix_cache=True):
        self.batch_slots = batch_slots
        self.max_seq = max_seq
        self.page_size = page_size
        self.max_blocks_per_seq = max_seq // page_size
        self.num_blocks = (num_blocks if num_blocks is not None
                           else 1 + batch_slots * self.max_blocks_per_seq)
        self.vocab = V
        self.delay_s = delay_s
        self.prefill_chunk = prefill_chunk
        self.prefix_cache = prefix_cache
        self.steps = 0
        self.prefill_calls = 0
        self.copied_blocks = []
        self.fail_at_steps = set()
        self.resets = 0

    def reset(self):
        self.resets += 1

    def step(self, tokens, seq_lens, block_tables):
        self.steps += 1
        if self.steps in self.fail_at_steps:
            raise RuntimeError(f"injected step fault @{self.steps}")
        if self.delay_s:
            time.sleep(self.delay_s)
        logits = np.zeros((self.batch_slots, V), np.float32)
        nxt = (np.asarray(tokens) + 1) % V
        logits[np.arange(self.batch_slots), nxt] = 1.0
        return logits

    def prefill_step(self, tokens, positions, block_tables):
        self.prefill_calls += 1
        if self.delay_s:
            time.sleep(self.delay_s)

    def copy_block(self, src, dst):
        self.copied_blocks.append((src, dst))


def expected(prompt, mnt):
    out = list(prompt)
    t = prompt[-1]
    for _ in range(mnt):
        t = (t + 1) % V
        out.append(t)
    return out


def test_greedy_matches_closed_form_and_interleaves():
    sched = ContinuousScheduler(FakeStepModel(batch_slots=2))
    try:
        reqs = [([1, 2, 3], 4), ([5], 9), ([7, 8], 2), ([2, 4, 6, 8], 5),
                ([11], 3)]
        handles = [sched.generate_async(p, m) for p, m in reqs]
        for h, (p, m) in zip(handles, reqs):
            assert h.wait(30.0) == expected(p, m)
        assert sched.requests_done == len(reqs)
        # 5 requests through 2 slots: retirement freed slots mid-run
        assert sched.batches_run < sum(len(p) + m for p, m in reqs)
        st = sched.stats()
        assert st["kv_pool"]["used_blocks"] == 0  # all retired
        assert st["ttft"]["n"] == len(reqs)
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_mixed_temperatures_share_one_batch():
    """Static batching must segregate temperatures (one compiled scan
    per temperature); continuous samples host-side per row and takes
    any mix."""
    sched = ContinuousScheduler(FakeStepModel(batch_slots=2))
    try:
        h1 = sched.generate_async([3, 4], 5, temperature=0.0)
        h2 = sched.generate_async([5, 6], 5, temperature=1.0)
        r1, r2 = h1.wait(30.0), h2.wait(30.0)
        assert r1 == expected([3, 4], 5)
        assert len(r2) == 7 and all(0 <= t < V for t in r2)
    finally:
        sched.close()


def test_small_pool_queues_admissions():
    # pool fits ONE 8-token sequence (2 usable blocks of 4); the
    # second request queues until the first retires — never crashes
    model = FakeStepModel(batch_slots=2, num_blocks=3)
    reg = MetricsRegistry()
    sched = ContinuousScheduler(model, registry=reg)
    try:
        h1 = sched.generate_async([1, 2, 3], 5)  # 8 tokens: whole pool
        h2 = sched.generate_async([4, 5], 4)
        assert h1.wait(30.0) == expected([1, 2, 3], 5)
        assert h2.wait(30.0) == expected([4, 5], 4)
        assert reg.counter("serving/admissions_deferred").value > 0
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_unservable_request_fails_alone():
    model = FakeStepModel(batch_slots=2, num_blocks=2)  # 1 usable block
    sched = ContinuousScheduler(model)
    try:
        h = sched.generate_async([1, 2, 3], 8)  # needs 3 blocks
        with pytest.raises(ValueError, match="KV blocks"):
            h.wait(30.0)
        # the engine still serves what fits
        assert sched.generate([1], 2, timeout=30.0) == expected([1], 2)
    finally:
        sched.close()


def test_step_fault_fails_inflight_only_queued_survive():
    """ISSUE 6 satellite: an injected step exception mid-decode fails
    only the affected in-flight requests; queued requests survive and
    complete after the engine recovers."""
    model = FakeStepModel(batch_slots=2)
    model.fail_at_steps = {3}
    sched = ContinuousScheduler(model)
    try:
        # 2 admitted immediately (slots=2), 2 queued behind them
        inflight = [sched.generate_async([1, 2], 6),
                    sched.generate_async([3, 4], 6)]
        queued = [sched.generate_async([5, 6], 3),
                  sched.generate_async([7, 8], 4)]
        for h in inflight:
            with pytest.raises(RuntimeError, match="injected step fault"):
                h.wait(30.0)
        assert queued[0].wait(30.0) == expected([5, 6], 3)
        assert queued[1].wait(30.0) == expected([7, 8], 4)
        assert sched.step_failures == 1
        assert model.resets == 1  # donated-state rebuild ran
        assert sched.requests_done == 2
        sched.pool.check_invariants()
        assert sched.pool.used_blocks == 0
    finally:
        sched.close()


def test_close_during_inflight_drains_without_hanging():
    """ISSUE 6 satellite: close() during an in-flight continuous batch
    fails the waiters promptly instead of letting them sit out their
    full timeouts."""
    model = FakeStepModel(batch_slots=2, delay_s=0.05)
    sched = ContinuousScheduler(model)
    hs = [sched.generate_async([1, 2], 30) for _ in range(4)]
    time.sleep(0.1)  # let a batch get in flight
    t0 = time.monotonic()
    sched.close()
    assert time.monotonic() - t0 < 30.0
    for h in hs:
        with pytest.raises(RuntimeError, match="closed"):
            h.wait(5.0)
    assert not sched.worker_alive
    with pytest.raises(RuntimeError, match="closed"):
        sched.generate_async([1], 1)


def test_close_drains_even_when_step_is_wedged():
    """A device step that never returns must not park waiters for
    their full timeouts: close() force-drains after its deadline even
    though the worker thread is still stuck in model.step."""
    model = FakeStepModel(batch_slots=2, delay_s=10.0)  # "wedged"
    sched = ContinuousScheduler(model, close_timeout_s=0.5)
    h = sched.generate_async([1, 2], 20)
    time.sleep(0.2)  # let the worker enter the wedged step
    t0 = time.monotonic()
    sched.close()
    assert time.monotonic() - t0 < 5.0
    with pytest.raises(RuntimeError, match="closed"):
        h.wait(1.0)  # failed by the force-drain, not a timeout


def test_slo_metrics_drain_to_registry(tmp_path):
    reg = MetricsRegistry()
    sched = ContinuousScheduler(FakeStepModel(batch_slots=2),
                                registry=reg)
    try:
        sched.generate([1, 2], 5, timeout=30.0)
        sched.generate([3], 8, timeout=30.0)
    finally:
        sched.close()
    path = tmp_path / "run_telemetry.jsonl"
    assert reg.write_jsonl(str(path)) > 0
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    by_name = {r["name"]: r for r in recs if "name" in r}
    assert by_name["serving/requests_done"]["value"] == 2
    assert by_name["serving/ttft_ms"]["count"] == 2
    assert by_name["serving/steps"]["value"] == sched.batches_run
    assert by_name["serving/kv_occupancy"]["count"] > 0
    assert by_name["serving/kv_fragmentation"]["count"] > 0
    # the summary tool renders the new rows
    import importlib
    summary = importlib.import_module("tools.telemetry_summary")
    text = summary.summarize(recs)
    assert "Serving" in text and "ttft_ms" in text


def test_loadgen_against_fake_scheduler():
    sched = ContinuousScheduler(FakeStepModel(batch_slots=2))
    try:
        rng = np.random.RandomState(0)
        wl = sample_workload(rng, 8, V, prompt_len_range=(1, 4),
                             max_new_range=(2, 6), long_frac=0.25,
                             long_max_new_range=(10, 14))
        report = run_loadgen(sched, wl, rate_rps=200.0, seed=1,
                             timeout_s=30.0)
        assert report["completed"] == 8 and report["failures"] == 0
        assert report["tokens_generated"] == sum(m for _, m in wl)
        assert report["tokens_per_s"] > 0
        assert report["ttft"]["n"] == 8 and report["per_token"]["n"] > 0
    finally:
        sched.close()


# -- prefix cache + chunked prefill (scheduler logic, fake model) -------

def test_chunked_prefill_cuts_prompt_steps():
    """A long prompt through chunked prefill costs ~plen/C prefill
    dispatches plus the decode steps — and the closed-form greedy
    output is unchanged (the chunk program is acceleration, never
    semantics)."""
    model = FakeStepModel(batch_slots=2, prefill_chunk=4)
    sched = ContinuousScheduler(model, check_invariants=True)
    try:
        prompt = [(3 * i + 1) % V for i in range(20)]
        assert sched.generate(prompt, 4, timeout=30.0) == \
            expected(prompt, 4)
        assert model.prefill_calls > 0
        # unchunked would pay ~19 prefill steps; chunked pays ~19/4
        # chunk dispatches (plus the decode steps that ride along)
        assert sched.prefill_steps <= 6
        st = sched.stats()
        assert st["prefill_chunk"] == 4
        assert st["prefill_steps"] == sched.prefill_steps
    finally:
        sched.close()


def test_prefix_hit_skips_prefill_and_stamps_handle():
    model = FakeStepModel(batch_slots=2)
    reg = MetricsRegistry()
    sched = ContinuousScheduler(model, registry=reg,
                                check_invariants=True)
    try:
        prompt = list(range(1, 13))  # 12 tokens = 3 full pages of 4
        h1 = sched.generate_async(prompt + [13, 14], 3)
        assert h1.wait(30.0) == expected(prompt + [13, 14], 3)
        assert h1.prefix_hit_tokens == 0
        steps_cold = model.steps
        # same 12-token prefix, different tail: the cached blocks are
        # mapped at admission and those positions never prefill
        h2 = sched.generate_async(prompt + [20, 21], 3)
        assert h2.wait(30.0) == expected(prompt + [20, 21], 3)
        assert h2.prefix_hit_tokens == 12
        assert model.steps - steps_cold < steps_cold
        assert reg.counter("serving/prefix_hit_tokens").value >= 12
        st = sched.stats()["prefix_cache"]
        assert st["hits"] >= 1 and st["hit_tokens"] >= 12
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_full_prompt_hit_cows_and_matches_closed_form():
    """An identical repeated prompt is a FULL-prompt hit: only the
    last prompt token re-runs (for its logits), the shared tail block
    is copy-on-written first, and the output is byte-equal."""
    model = FakeStepModel(batch_slots=2)
    sched = ContinuousScheduler(model, check_invariants=True)
    try:
        prompt = list(range(1, 9))  # exactly 2 pages
        first = sched.generate(prompt, 5, timeout=30.0)
        steps_cold = model.steps
        again = sched.generate(prompt, 5, timeout=30.0)
        assert again == first == expected(prompt, 5)
        assert model.copied_blocks, "full hit must trigger COW"
        # replay cost: 1 re-run token + 5 decode steps, not 8 + 5
        assert model.steps - steps_cold <= 7
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_sharing_and_chunking_compose_with_faults():
    """The PR 6 fault discipline survives the new machinery: a step
    fault mid-decode fails in-flight only, the reset invalidates the
    prefix index (cached bytes were zeroed), and later same-prefix
    requests still complete correctly (re-prefilled, then re-cached)."""
    model = FakeStepModel(batch_slots=2, prefill_chunk=4)
    model.fail_at_steps = {2}
    sched = ContinuousScheduler(model, check_invariants=True)
    try:
        prompt = list(range(1, 13))
        h1 = sched.generate_async(prompt, 6)
        with pytest.raises(RuntimeError, match="injected step fault"):
            h1.wait(30.0)
        assert model.resets == 1
        assert sched.pool.cached_blocks == 0  # index invalidated
        assert sched.generate(prompt, 3, timeout=30.0) == \
            expected(prompt, 3)
        # and the re-run re-populated the cache for the NEXT hit
        h3 = sched.generate_async(prompt + [20], 3)
        assert h3.wait(30.0) == expected(prompt + [20], 3)
        assert h3.prefix_hit_tokens > 0
        sched.pool.check_invariants()
    finally:
        sched.close()


def test_prefix_cache_off_never_shares():
    model = FakeStepModel(batch_slots=2, prefix_cache=False)
    sched = ContinuousScheduler(model, check_invariants=True)
    try:
        prompt = list(range(1, 9))
        assert sched.generate(prompt, 3, timeout=30.0) == \
            expected(prompt, 3)
        h = sched.generate_async(prompt, 3)
        assert h.wait(30.0) == expected(prompt, 3)
        assert h.prefix_hit_tokens == 0
        assert sched.stats()["prefix_cache"]["hits"] == 0
    finally:
        sched.close()


def test_prefix_metrics_and_summary_render(tmp_path):
    reg = MetricsRegistry()
    model = FakeStepModel(batch_slots=2, prefill_chunk=4)
    sched = ContinuousScheduler(model, registry=reg)
    try:
        prompt = list(range(1, 13))
        sched.generate(prompt, 3, timeout=30.0)
        sched.generate(prompt + [20, 21], 3, timeout=30.0)
    finally:
        sched.close()
    path = tmp_path / "run_telemetry.jsonl"
    assert reg.write_jsonl(str(path)) > 0
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    by_name = {r["name"]: r for r in recs if "name" in r}
    assert by_name["serving/prefix_hit_tokens"]["value"] >= 12
    assert "serving/kv_shared_blocks" in by_name
    import importlib
    summary = importlib.import_module("tools.telemetry_summary")
    text = summary.summarize(recs)
    assert "prefix" in text  # the Serving section's prefix-cache rows


# -- serve_http satellites ----------------------------------------------

def _post(port, payload, path="/v2/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_http_generate_timeout_maps_to_503():
    """ISSUE 6 satellite: /v2/generate honors request-supplied
    timeout_s and maps TimeoutError to 503 (not a generic 400) — the
    request keeps decoding server-side."""
    model = FakeStepModel(batch_slots=2, delay_s=0.05)
    sched = ContinuousScheduler(model)
    server = serve_http(generator=sched, port=0, block=False)
    port = server.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"prompt": [1, 2], "max_new_tokens": 25,
                         "timeout_s": 0.05})
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert "TimeoutError" in body["error"] and body["retriable"]
        # a sane timeout still succeeds (and bad timeouts are 400s)
        status, out = _post(port, {"prompt": [1, 2], "max_new_tokens": 2,
                                   "timeout_s": 20.0})
        assert status == 200
        assert out["tokens"] == [expected([1, 2], 2)]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"prompt": [1], "timeout_s": -1})
        assert ei.value.code == 400
    finally:
        server.shutdown()
        sched.close()


def test_http_engine_fault_maps_to_500_not_400():
    """A server-side engine fault (here: a closed batcher) is the
    server's problem — 500 retriable, not a 400 client error."""
    sched = ContinuousScheduler(FakeStepModel(batch_slots=2))
    server = serve_http(generator=sched, port=0, block=False)
    port = server.server_address[1]
    try:
        sched.close()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"prompt": [1, 2], "max_new_tokens": 2})
        assert ei.value.code == 500
        assert json.loads(ei.value.read())["retriable"]
        # malformed requests still map to 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"max_new_tokens": 2})  # no prompt at all
        assert ei.value.code == 400
    finally:
        server.shutdown()
        sched.close()


def test_http_health_degrades_on_dead_worker():
    """ISSUE 6 satellite: a dead worker thread must flip /v2/health to
    "degraded" instead of leaving it green while requests time out."""
    sched = ContinuousScheduler(FakeStepModel(batch_slots=2))
    server = serve_http(generator=sched, port=0, block=False)
    port = server.server_address[1]
    try:
        sched.generate([1], 2, timeout=30.0)
        assert _get(port, "/v2/health")["status"] == "ok"
        stats = _get(port, "/v2/stats")
        # legacy shape unchanged...
        assert {"batches_run", "requests_done", "latency"} <= set(stats)
        # ...plus the continuous block
        cont = stats["continuous"]
        assert cont["mode"] == "continuous"
        assert "kv_pool" in cont and "ttft" in cont
        sched.close()  # worker thread exits
        # degraded rides a 503 so status-code-only probes see it too
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/v2/health")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "degraded"
    finally:
        server.shutdown()
        sched.close()
