"""Inference-serving tests (triton/ parity): engine bucketing matches
direct forward, dynamic batcher coalesces concurrent requests with
correct scatter, HTTP endpoint round-trips JSON."""
import json
import threading
import urllib.request

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.fftype import ActiMode, CompMode
from flexflow_tpu.serving import DynamicBatcher, InferenceEngine, serve_http


@pytest.fixture(scope="module")
def engine(devices8):
    cfg = FFConfig(batch_size=32, num_devices=8)
    ff = FFModel(cfg)
    x = ff.create_tensor([32, 8], name="x")
    t = ff.dense(x, 16, activation=ActiMode.TANH, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               comp_mode=CompMode.INFERENCE, devices=devices8)
    return InferenceEngine(ff, max_batch=32)


def test_engine_matches_direct_forward(engine):
    rng = np.random.RandomState(0)
    for n in (1, 3, 8, 17, 32, 50):
        xs = rng.randn(n, 8).astype(np.float32)
        got = engine.infer({"x": xs})
        assert got.shape == (n, 4)
        # padded/bucketed result must equal an exact-size run
        ref = engine.infer({"x": xs})
        np.testing.assert_allclose(got, ref, rtol=1e-6)
    # cross-check against model.forward on a full batch
    xs = rng.randn(32, 8).astype(np.float32)
    direct = np.asarray(engine.ff.forward({"x": xs}))
    np.testing.assert_allclose(engine.infer({"x": xs}), direct,
                               rtol=2e-5, atol=2e-5)


def test_dynamic_batcher_concurrent_requests(engine):
    batcher = DynamicBatcher(engine, max_batch=32, flush_timeout_s=0.01)
    rng = np.random.RandomState(1)
    reqs = [rng.randn(rng.randint(1, 5), 8).astype(np.float32)
            for _ in range(12)]
    results = [None] * len(reqs)

    def worker(i):
        results[i] = batcher.infer({"x": reqs[i]})

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
    for i, r in enumerate(results):
        assert r is not None and r.shape == (len(reqs[i]), 4)
        expected = engine.infer({"x": reqs[i]})
        np.testing.assert_allclose(r, expected, rtol=1e-5, atol=1e-5)
    assert batcher.batches_run <= len(reqs)  # some coalescing occurred
    batcher.close()


def test_http_endpoint(engine):
    batcher = DynamicBatcher(engine, max_batch=16, flush_timeout_s=0.002)
    server = serve_http(batcher, port=0, block=False)
    port = server.server_address[1]
    try:
        xs = np.random.RandomState(2).randn(3, 8).astype(np.float32)
        body = json.dumps({"inputs": {"x": xs.tolist()}}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v2/infer", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        got = np.asarray(out["outputs"], np.float32)
        expected = engine.infer({"x": xs})
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v2/health", timeout=10
        ) as resp:
            health = json.loads(resp.read())
        assert health["status"] == "ok"
    finally:
        server.shutdown()
        batcher.close()


def test_infer_async_and_latency_stats(engine):
    # generous flush window: all 12 requests are queued before the
    # assembler's deadline, so coalescing is deterministic
    batcher = DynamicBatcher(engine, max_batch=32, flush_timeout_s=0.25)
    try:
        rng = np.random.RandomState(7)
        handles = [
            batcher.infer_async({"x": rng.randn(2, 8).astype(np.float32)})
            for _ in range(12)
        ]
        outs = [h.wait(30.0) for h in handles]
        assert all(o.shape == (2, 4) for o in outs)
        stats = batcher.latency_stats()
        assert stats["n"] == 12
        assert 0 < stats["p50_ms"] <= stats["p95_ms"] <= stats["p99_ms"]
        assert batcher.requests_done == 12
        # coalescing really happened: strictly fewer device batches
        # than requests (24 samples / max_batch 32 -> 1-2 batches)
        assert batcher.batches_run < 12
    finally:
        batcher.close()


def test_batcher_oversize_request_chunks(engine):
    batcher = DynamicBatcher(engine, max_batch=32)
    try:
        rng = np.random.RandomState(8)
        xs = rng.randn(80, 8).astype(np.float32)  # > bucket cap
        out = batcher.infer({"x": xs})
        want = engine.infer({"x": xs})
        np.testing.assert_allclose(out, want, rtol=1e-6)
    finally:
        batcher.close()


def test_stats_endpoint(engine):
    batcher = DynamicBatcher(engine, max_batch=32)
    server = serve_http(batcher, port=0, block=False)
    try:
        port = server.server_address[1]
        rng = np.random.RandomState(9)
        batcher.infer({"x": rng.randn(3, 8).astype(np.float32)})
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v2/stats", timeout=10
        ) as r:
            stats = json.loads(r.read())
        assert stats["requests_done"] >= 1
        assert "latency" in stats and stats["latency"]["n"] >= 1
    finally:
        server.shutdown()
        batcher.close()


def test_from_onnx_serves(devices8, tmp_path):
    """ONNX file -> InferenceEngine.from_onnx -> bucketed inference,
    parity against direct numpy (the Triton backend's model source)."""
    from flexflow_tpu.onnx_frontend import protowire as pw
    from flexflow_tpu.serving.engine import InferenceEngine as IE

    rng = np.random.RandomState(11)
    w = rng.randn(4, 8).astype(np.float32)
    nodes = [
        pw.encode_node("Gemm", ["x", "w"], ["y"], name="fc", transB=1),
        pw.encode_node("Softmax", ["y"], ["p"], name="sm", axis=-1),
    ]
    data = pw.encode_model(nodes, [("x", [None, 8])], [("p", [None, 4])],
                           {"w": w})
    path = tmp_path / "m.onnx"
    path.write_bytes(data)
    eng = IE.from_onnx(str(path), batch_size=16, devices=devices8[:1])
    xs = rng.randn(5, 8).astype(np.float32)
    got = eng.infer({"x": xs})
    logits = xs @ w.T
    want = np.exp(logits - logits.max(-1, keepdims=True))
    want /= want.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_infer_after_close_raises(engine):
    """Submitting after close() fails fast instead of burning the full
    wait timeout on a dead assembler (ADVICE r03)."""
    batcher = DynamicBatcher(engine, max_batch=8)
    batcher.close()
    with pytest.raises(RuntimeError, match="closed"):
        batcher.infer_async({"x": np.zeros((2, 8), np.float32)})


def test_latency_percentiles_nearest_rank():
    """p95 of a small window is not simply the max (nearest-rank
    indexing; ADVICE r03)."""
    b = DynamicBatcher.__new__(DynamicBatcher)  # stats only, no threads
    import threading
    from collections import deque

    b._latencies = deque([i / 1000.0 for i in range(1, 21)])  # 1..20ms
    b._lat_lock = threading.Lock()
    stats = b.latency_stats()
    assert stats["n"] == 20
    assert stats["p50_ms"] == 10.0  # ceil(.5*20)=10th order stat
    assert stats["p95_ms"] == 19.0  # ceil(.95*20)=19th, not the max
    assert stats["p99_ms"] == 20.0
