"""Benchmark: BERT-base training throughput, samples/sec/chip.

Run on the real TPU chip by the driver.  Measures steady-state jitted
train-step time (forward + backward + optimizer) in bf16 on BERT-base
(12L, hidden 768, 12 heads, seq 128) and prints ONE JSON line.

vs_baseline anchors to BASELINE.md's north star — A100-NCCL per-GPU
throughput for BERT-base at seq 128 in mixed precision, taken as
~250 samples/s/GPU (A100 cards sustain roughly 230-280 samples/s on
BERT-base seq-128 fine-tuning; the reference repo publishes no absolute
number, BASELINE.md:3-5).
"""
from __future__ import annotations

import json
import time

import numpy as np

A100_BERT_BASE_SEQ128_SAMPLES_PER_SEC = 250.0


def main():
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_bert

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        batch, seq, hidden, layers, heads, inter = 64, 128, 768, 12, 12, 3072
    else:  # CPU smoke config so the bench always produces a line
        batch, seq, hidden, layers, heads, inter = 8, 32, 64, 2, 4, 128

    cfg = FFConfig(batch_size=batch, num_devices=1,
                   compute_dtype="bfloat16" if on_tpu else "float32")
    ff = FFModel(cfg)
    build_bert(ff, batch_size=batch, seq_length=seq, hidden_size=hidden,
               num_layers=layers, num_heads=heads, intermediate_size=inter)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        devices=[dev],
    )

    rng = np.random.RandomState(0)
    x = rng.randn(batch, seq, hidden).astype(np.float32)
    y = rng.randint(0, 2, batch).astype(np.int32)
    # stage the batch on-device once: the bench measures steady-state
    # step time (train data is device-resident via the dataloader's
    # prefetch in real runs; under axon the tunnel would otherwise add
    # a noisy ~25MB host->device copy per step)
    x = jax.device_put(x, ff.executor.input_shardings()["input"])
    y = jax.device_put(y, ff.executor.label_sharding())

    import sys

    print(f"bench: compiled model graph, starting warmup", file=sys.stderr)
    t_c = time.perf_counter()
    # warmup (compile + cache)
    for _ in range(3):
        m = ff.train_step({"input": x}, y)
    _ = float(m["loss"])  # hard fetch: tunnel block_until_ready is unreliable
    print(f"bench: warmup done in {time.perf_counter()-t_c:.1f}s", file=sys.stderr)

    # Steady-state step time: device-resident batch, long serial chain
    # (each step consumes the previous step's donated weights), one hard
    # value fetch of the final loss AND a weight leaf at the end — under
    # the axon tunnel, block_until_ready alone returns early, and any
    # per-step host round-trip adds ~80ms of tunnel latency that real
    # training (prefetched dataloader) never pays.
    iters = 50 if on_tpu else 5
    t0 = time.perf_counter()
    for _ in range(iters):
        m = ff.train_step({"input": x}, y)
    _ = float(m["loss"])
    _ = np.asarray(jax.tree.leaves(ff._weights)[0]).ravel()[0]
    dt = time.perf_counter() - t0

    samples_per_sec = iters * batch / dt
    result = {
        "metric": f"samples/sec/chip (BERT-base seq{seq} b{batch} train, bf16)"
        if on_tpu
        else f"samples/sec/chip (tiny-BERT CPU smoke seq{seq} b{batch})",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(
            samples_per_sec / A100_BERT_BASE_SEQ128_SAMPLES_PER_SEC, 4
        )
        if on_tpu
        else 0.0,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
