"""Benchmark: the BASELINE north star's two headline workloads on one chip.

Leg 1 — BERT-base (12L, hidden 768, 12 heads, seq 128) trained from REAL
token ids (embedding lookup -> encoder -> loss; `from_token_ids=True`),
bf16, samples/sec/chip.
Leg 2 — ResNet-50 (the torch.fx-imported bottleneck tower of
examples/python/pytorch/resnet50_search.py, BASELINE.json configs[1])
at 224px, bf16, compiled under the auto-searched strategy.

Prints ONE JSON line; `legs` carries both workloads' numbers.
vs_baseline anchors to A100-NCCL per-GPU throughput (the reference repo
publishes no absolute numbers, BASELINE.md:3-5): ~250 samples/s for
BERT-base seq-128 fine-tune, ~2500 img/s for ResNet-50 mixed-precision
training (DGX-A100 per-GPU MLPerf-era envelope).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

A100_BERT_BASE_SEQ128_SAMPLES_PER_SEC = 250.0
A100_RESNET50_SAMPLES_PER_SEC = 2500.0


def _steady_state(ff, inputs, labels, iters):
    """Steady-state seconds for `iters` steps: device-resident batch,
    long serial chain (each step consumes the previous step's donated
    weights), one hard value fetch per window — under the axon tunnel,
    block_until_ready alone returns early and per-step host round trips
    add ~80ms the real (prefetched-dataloader) training never pays.
    Two windows, best taken: one-off tunnel hiccups otherwise swing the
    recorded number by ~10% run to run."""
    import jax

    def window(n):
        t0 = time.perf_counter()
        for _ in range(n):
            m = ff.train_step(inputs, labels)
        _ = float(m["loss"])
        _ = np.asarray(jax.tree.leaves(ff._weights)[0]).ravel()[0]
        return time.perf_counter() - t0

    half = max(1, iters // 2)
    best = min(window(half) / half, window(half) / half)
    return best * iters


def bench_bert(dev, on_tpu):
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_bert

    if on_tpu:
        batch, seq, hidden, layers, heads, inter = 64, 128, 768, 12, 12, 3072
    else:
        batch, seq, hidden, layers, heads, inter = 8, 32, 64, 2, 4, 128

    cfg = FFConfig(batch_size=batch, num_devices=1,
                   compute_dtype="bfloat16" if on_tpu else "float32")
    ff = FFModel(cfg)
    build_bert(ff, batch_size=batch, seq_length=seq, hidden_size=hidden,
               num_layers=layers, num_heads=heads, intermediate_size=inter,
               from_token_ids=True)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        devices=[dev],
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 30522, size=(batch, seq)).astype(np.int32)
    y = rng.randint(0, 2, batch).astype(np.int32)
    ids = jax.device_put(ids, ff.executor.input_shardings()["input"])
    y = jax.device_put(y, ff.executor.label_sharding())

    print("bench[bert]: compiled, warming up", file=sys.stderr)
    t_c = time.perf_counter()
    for _ in range(3):
        m = ff.train_step({"input": ids}, y)
    _ = float(m["loss"])
    print(f"bench[bert]: warmup {time.perf_counter()-t_c:.1f}s",
          file=sys.stderr)
    iters = 50 if on_tpu else 5
    dt = _steady_state(ff, {"input": ids}, y, iters)
    sps = iters * batch / dt
    leg = {
        "workload": f"BERT-base seq{seq} b{batch} token-ids train, bf16",
        "samples_per_sec_per_chip": round(sps, 2),
        "vs_a100": round(sps / A100_BERT_BASE_SEQ128_SAMPLES_PER_SEC, 4),
    }
    if on_tpu:
        # simulator fidelity: measured-cost-calibrated per-op model vs
        # the real fused step (reference validates measure_operator_cost
        # against execution; XLA fusion makes per-op sums conservative —
        # the ratio is reported, not hidden)
        try:
            from flexflow_tpu.profiler import make_measure_fn
            from flexflow_tpu.sim.machine_model import (
                TpuPodModel,
                detect_device_spec,
            )
            from flexflow_tpu.sim.simulator import OpCostModel, Simulator

            machine = TpuPodModel(topology=(1,),
                                  device=detect_device_spec())
            cm = OpCostModel(machine,
                             measure_fn=make_measure_fn(device=dev))
            res = Simulator(machine, cm).simulate(
                ff.operators, {"data": 1}, training=True
            )
            actual_ms = dt / iters * 1e3
            leg["predicted_step_ms"] = round(res.total_time * 1e3, 2)
            leg["actual_step_ms"] = round(actual_ms, 2)
            leg["predicted_vs_actual"] = round(
                res.total_time * 1e3 / actual_ms, 3
            )
        except Exception as e:  # pragma: no cover - diagnostics only
            print(f"bench[bert]: prediction check failed: {e}",
                  file=sys.stderr)
    return leg


def bench_bert_long(dev, on_tpu):
    """Long-context leg: BERT-base at seq 2048 — the memory-efficient
    attention path (XLA's fused flash-style rewrite; ring attention
    takes over across chips via the sp strategy)."""
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_bert

    if on_tpu:
        batch, seq = 8, 2048
    else:
        batch, seq = 2, 128
    cfg = FFConfig(batch_size=batch, num_devices=1,
                   compute_dtype="bfloat16" if on_tpu else "float32")
    ff = FFModel(cfg)
    build_bert(ff, batch_size=batch, seq_length=seq, hidden_size=768,
               num_layers=12 if on_tpu else 2, num_heads=12,
               intermediate_size=3072 if on_tpu else 128,
               from_token_ids=True)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        devices=[dev],
    )
    rng = np.random.RandomState(0)
    ids = jax.device_put(
        rng.randint(0, 30522, size=(batch, seq)).astype(np.int32),
        ff.executor.input_shardings()["input"],
    )
    y = jax.device_put(rng.randint(0, 2, batch).astype(np.int32),
                       ff.executor.label_sharding())
    print("bench[bert-long]: compiled, warming up", file=sys.stderr)
    for _ in range(3):
        m = ff.train_step({"input": ids}, y)
    _ = float(m["loss"])
    iters = 20 if on_tpu else 3
    dt = _steady_state(ff, {"input": ids}, y, iters)
    tokens_per_sec = iters * batch * seq / dt
    dtype = "bf16" if on_tpu else "f32"
    return {
        "workload": f"BERT-base seq{seq} b{batch} long-context train, {dtype}",
        "samples_per_sec_per_chip": round(iters * batch / dt, 2),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 0),
    }


def bench_resnet50(dev, on_tpu):
    import jax

    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "examples", "python", "pytorch"))
    from resnet50_search import ResNet50

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.torch_frontend.model import PyTorchModel

    if on_tpu:
        batch, px, classes = 64, 224, 1000
    else:
        batch, px, classes = 4, 32, 10

    # auto-searched strategy per BASELINE.json configs[1] (single chip:
    # the search degenerates to the trivial mesh but the path runs;
    # calibration off keeps the bench inside its time box)
    cfg = FFConfig(batch_size=batch, num_devices=1, search_budget=1000,
                   search_algo="mcmc", search_calibrate=False,
                   compute_dtype="bfloat16" if on_tpu else "float32")
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 3, px, px], name="input")
    pt = PyTorchModel(ResNet50(classes=classes))
    (out,) = pt.torch_to_ff(ff, [x])
    ff.softmax(out)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        devices=[dev],
    )
    rng = np.random.RandomState(0)
    xs = rng.randn(batch, 3, px, px).astype(np.float32)
    ys = rng.randint(0, classes, batch).astype(np.int32)
    xs = jax.device_put(xs, ff.executor.input_shardings()["input"])
    ys = jax.device_put(ys, ff.executor.label_sharding())

    print("bench[resnet50]: compiled, warming up", file=sys.stderr)
    t_c = time.perf_counter()
    for _ in range(3):
        m = ff.train_step({"input": xs}, ys)
    _ = float(m["loss"])
    print(f"bench[resnet50]: warmup {time.perf_counter()-t_c:.1f}s",
          file=sys.stderr)
    iters = 20 if on_tpu else 3
    dt = _steady_state(ff, {"input": xs}, ys, iters)
    sps = iters * batch / dt
    return {
        "workload": f"ResNet-50 {px}px b{batch} fx-import train, bf16, "
                    f"searched strategy",
        "samples_per_sec_per_chip": round(sps, 2),
        "vs_a100": round(sps / A100_RESNET50_SAMPLES_PER_SEC, 4),
    }


def main():
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    import gc

    bert = bench_bert(dev, on_tpu)
    gc.collect()  # drop the previous leg's weights/opt state from HBM
    resnet = bench_resnet50(dev, on_tpu)
    gc.collect()
    bert_long = bench_bert_long(dev, on_tpu)
    geomean = float(np.sqrt(max(bert["vs_a100"], 1e-9)
                            * max(resnet["vs_a100"], 1e-9)))
    result = {
        # value is the BERT leg's samples/s (round-over-round
        # comparable); vs_baseline is the geomean of BOTH legs' vs-A100
        # ratios; per-leg numbers live under "legs"
        "metric": (
            "samples/sec/chip, BERT-base seq128 b64 token-ids bf16 "
            "(vs_baseline = geomean of bert_base+resnet50 legs vs A100)"
            if on_tpu else "CPU smoke: BERT tiny + ResNet tiny"
        ),
        "value": bert["samples_per_sec_per_chip"],
        "unit": "samples/s",
        "vs_baseline": round(geomean, 4) if on_tpu else 0.0,
        "legs": {"bert_base": bert, "resnet50": resnet,
                 "bert_long_context": bert_long},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
