"""Benchmark: the BASELINE north star's two headline workloads on one chip.

Leg definitions are FROZEN in `bench_manifest.json` (version field bumps
on any change, with the old->new delta explained in the leg's note) so
round-over-round numbers stay comparable.

Leg 1 — BERT-base trained from REAL token ids (embedding lookup ->
encoder -> loss), bf16, samples/sec/chip.
Leg 2 — ResNet-50 (the torch.fx-imported bottleneck tower of
examples/python/pytorch/resnet50_search.py, BASELINE.json configs[1]),
bf16, compiled under the auto-searched strategy, internal NHWC layout.
Leg 3 — BERT-base at seq 2048: the long-context path.

Prints ONE JSON line; `legs` carries all workloads' numbers.
vs_baseline anchors to A100-NCCL per-GPU throughput (the reference repo
publishes no absolute numbers, BASELINE.md:3-5).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# CPU smoke runs need a multi-device host for the tensor-parallel
# serving leg (tp=2 replica mesh); mirror tests/conftest.py's virtual
# 8-CPU topology.  Must land before jax initializes, and never touches
# the TPU path.
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

_HERE = os.path.dirname(os.path.abspath(__file__))
with open(os.path.join(_HERE, "bench_manifest.json")) as f:
    MANIFEST = json.load(f)
ANCHORS = MANIFEST["anchors"]


def _steady_state(ff, inputs, labels, iters, windows=None):
    """Best-of-N windows of `iters` serial steps, ONE hard sync each.

    The batch is device-resident and each step consumes the previous
    step's donated weights, so the chain is serial on-device; fetching
    the final loss drains it.  Window sizes are set in the manifest so
    the single ~80ms tunnel round trip is <2% of the window
    (manifest.timing.history records what the old 10-step/2-sync
    windows cost r01/r02)."""
    windows = windows or MANIFEST["timing"]["windows"]

    def window(n):
        t0 = time.perf_counter()
        for _ in range(n):
            m = ff.train_step(inputs, labels)
        _ = float(m["loss"])  # one hard sync: drains the serial chain
        return time.perf_counter() - t0

    best = min(window(iters) for _ in range(windows))
    return best / iters  # seconds per step


def _build_bert_leg(dev, on_tpu, leg):
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_bert

    if on_tpu:
        batch, seq = leg["batch"], leg["seq"]
        hidden, layers = leg["hidden"], leg["layers"]
        heads, inter = leg["heads"], leg["intermediate"]
        iters = leg["iters"]
    else:
        batch, seq, hidden, layers, heads, inter, iters = 8, 32, 64, 2, 4, 128, 3

    cfg = FFConfig(batch_size=batch, num_devices=1,
                   compute_dtype=leg["dtype"] if on_tpu else "float32")
    ff = FFModel(cfg)
    build_bert(ff, batch_size=batch, seq_length=seq, hidden_size=hidden,
               num_layers=layers, num_heads=heads, intermediate_size=inter,
               from_token_ids=True)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        devices=[dev],
    )
    rng = np.random.RandomState(0)
    ids = jax.device_put(
        rng.randint(0, 30522, size=(batch, seq)).astype(np.int32),
        ff.executor.input_shardings()["input"],
    )
    y = jax.device_put(rng.randint(0, 2, batch).astype(np.int32),
                       ff.executor.label_sharding())
    for _ in range(3):
        m = ff.train_step({"input": ids}, y)
    _ = float(m["loss"])
    dt = _steady_state(ff, {"input": ids}, y, iters)
    return ff, batch, seq, dt


def bench_bert(dev, on_tpu):
    leg = MANIFEST["legs"]["bert_base"]
    print("bench[bert]: compiling", file=sys.stderr)
    ff, batch, seq, dt = _build_bert_leg(dev, on_tpu, leg)
    sps = batch / dt
    out = {
        "workload": f"BERT-base seq{seq} b{batch} token-ids train, bf16",
        "samples_per_sec_per_chip": round(sps, 2),
        "vs_a100": round(
            sps / ANCHORS["a100_bert_base_seq128_samples_per_sec"], 4
        ),
    }
    if on_tpu:
        out["mfu"] = _mfu(ff, dt)
        out.update(_fidelity(ff, dev, dt, "bert", leg))
    return out


def _mfu(ff, dt):
    """Model FLOPs utilization against the bench chip's bf16 roofline
    peak (sim/machine_model.py detect_device_spec).  Forward FLOPs come
    from the ops' own cost hooks; training charges backward at 2x
    forward (the standard dL/dx + dL/dw accounting — embedding scatter
    and elementwise ops count their own hooks).  VERDICT r03 Missing #4:
    vs_a100 alone flattered soft anchors; MFU is anchor-free."""
    try:
        from flexflow_tpu.sim.machine_model import detect_device_spec

        spec = detect_device_spec()
        fwd = sum(op.flops() for op in ff.operators.compute_ops())
        return round(3.0 * fwd / (dt * spec.peak_flops), 4)
    except Exception:  # pragma: no cover - diagnostics only
        return None


def _fidelity(ff, dev, dt, tag, leg=None):
    """Simulator fidelity vs the measured step: segment-granularity
    calibration (profiler.measure_segment_costs times the executor's own
    fused segment bodies — the r02 per-op harness was blind to XLA
    fusion and predicted 0.45x..3.6x).  The ratio is reported, not
    hidden (reference validates measure_operator_cost the same way).
    Per-leg `calibration` overrides in the manifest take precedence
    (v5: the bert leg needs finer binning than the global default)."""
    try:
        from flexflow_tpu.profiler import measure_segment_costs
        from flexflow_tpu.sim.machine_model import (
            TpuPodModel,
            detect_device_spec,
        )
        from flexflow_tpu.sim.simulator import OpCostModel, Simulator

        machine = TpuPodModel(topology=(1,), device=detect_device_spec())
        calib = dict(MANIFEST.get("calibration", {}))
        calib.update((leg or {}).get("calibration", {}))
        seg_costs = measure_segment_costs(
            ff, device=dev,
            max_regions=calib.get("max_regions", 16),
            repeats=calib.get("repeats", 3),
            chain=calib.get("chain", 48),
        )
        covered = sum(len(g) for g, _ in seg_costs)
        res = Simulator(machine, OpCostModel(machine)).simulate(
            ff.operators, {"data": 1}, training=True,
            segment_costs=seg_costs,
        )
        actual_ms = dt * 1e3
        out = {
            "predicted_step_ms": round(res.total_time * 1e3, 2),
            "actual_step_ms": round(actual_ms, 2),
            "predicted_vs_actual": round(res.total_time * 1e3 / actual_ms, 3),
            "calibration": f"{len(seg_costs)} regions / {covered} ops measured",
        }
        # unified fidelity record (obs/fidelity.py, manifest v8): the
        # same schema fit-time telemetry emits, so bench captures and
        # run_telemetry.jsonl records are directly comparable.  Built
        # from the SAME SimResult as the predicted_* fields above (no
        # second simulation, no disagreeing numbers).
        try:
            from flexflow_tpu.obs.fidelity import fidelity_record

            out["fidelity_record"] = fidelity_record(
                ff, dt, steps_measured=(leg or {}).get("iters", 0),
                source=f"bench/{tag}", segment_costs=seg_costs,
                sim_result=res,
            )
        except Exception as e:
            print(f"bench[{tag}]: fidelity record failed: {e}",
                  file=sys.stderr)
        return out
    except Exception as e:  # pragma: no cover - diagnostics only
        print(f"bench[{tag}]: prediction check failed: {e}", file=sys.stderr)
        return {}


def bench_bert_long(dev, on_tpu):
    leg = MANIFEST["legs"]["bert_long_context"]
    print("bench[bert-long]: compiling", file=sys.stderr)
    ff, batch, seq, dt = _build_bert_leg(dev, on_tpu, leg)
    dtype = "bf16" if on_tpu else "f32"
    out = {
        "workload": f"BERT-base seq{seq} b{batch} long-context train, {dtype}",
        "samples_per_sec_per_chip": round(batch / dt, 2),
        "tokens_per_sec_per_chip": round(batch * seq / dt, 0),
    }
    if on_tpu:
        out["mfu"] = _mfu(ff, dt)
    return out


def bench_resnet50(dev, on_tpu):
    import jax

    sys.path.insert(0, os.path.join(_HERE, "examples", "python", "pytorch"))
    from resnet50_search import ResNet50

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.torch_frontend.model import PyTorchModel

    leg = MANIFEST["legs"]["resnet50"]
    if on_tpu:
        batch, px, classes, iters = (
            leg["batch"], leg["px"], leg["classes"], leg["iters"]
        )
    else:
        batch, px, classes, iters = 4, 32, 10, 3

    # auto-searched strategy per BASELINE.json configs[1] (single chip:
    # the search degenerates to the trivial mesh but the path runs;
    # calibration off keeps the bench inside its time box)
    cfg = FFConfig(batch_size=batch, num_devices=1,
                   search_budget=leg["search_budget"],
                   search_algo=leg["search_algo"], search_calibrate=False,
                   compute_dtype=leg["dtype"] if on_tpu else "float32")
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 3, px, px], name="input")
    pt = PyTorchModel(ResNet50(classes=classes))
    (out,) = pt.torch_to_ff(ff, [x])
    ff.softmax(out)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        devices=[dev],
    )
    rng = np.random.RandomState(0)
    xs = jax.device_put(rng.randn(batch, 3, px, px).astype(np.float32),
                        ff.executor.input_shardings()["input"])
    ys = jax.device_put(rng.randint(0, classes, batch).astype(np.int32),
                        ff.executor.label_sharding())

    print("bench[resnet50]: compiled, warming up", file=sys.stderr)
    for _ in range(3):
        m = ff.train_step({"input": xs}, ys)
    _ = float(m["loss"])
    dt = _steady_state(ff, {"input": xs}, ys, iters)
    sps = batch / dt
    out = {
        "workload": f"ResNet-50 {px}px b{batch} fx-import train, bf16, "
                    f"searched strategy, NHWC internal layout",
        "samples_per_sec_per_chip": round(sps, 2),
        "vs_a100": round(sps / ANCHORS["a100_resnet50_samples_per_sec"], 4),
    }
    if on_tpu:
        out["mfu"] = _mfu(ff, dt)
        out.update(_fidelity(ff, dev, dt, "resnet50", leg))
    return out


def bench_dlrm(dev, on_tpu):
    """DLRM (BASELINE configs[3]): the attribute-parallel embedding
    workload — single-chip this measures the four 1M-row gather +
    grad-scatter paths plus the interaction MLPs (reference dlrm.cc
    prints THROUGHPUT the same way).  No A100 anchor exists for this
    exact config; the leg tracks round-over-round regressions."""
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.dlrm import build_dlrm

    leg = MANIFEST["legs"]["dlrm"]
    if on_tpu:
        batch, tables, rows, iters = (
            leg["batch"], leg["tables"], leg["rows_per_table"], leg["iters"]
        )
    else:
        batch, tables, rows, iters = 16, 2, 1000, 3

    print("bench[dlrm]: compiling", file=sys.stderr)
    cfg = FFConfig(batch_size=batch, num_devices=1,
                   compute_dtype=leg["dtype"] if on_tpu else "float32")
    ff = FFModel(cfg)
    build_dlrm(ff, batch_size=batch, embedding_size=[rows] * tables,
               sparse_feature_size=leg["sparse_feature_size"],
               dense_feature_dim=leg["dense_feature_dim"],
               mlp_bot=leg["mlp_bot"], mlp_top=leg["mlp_top"])
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        devices=[dev],
    )
    rng = np.random.RandomState(0)
    shardings = ff.executor.input_shardings()
    inputs = {
        f"sparse_input_{i}": jax.device_put(
            rng.randint(0, rows, size=(batch, 1)).astype(np.int32),
            shardings[f"sparse_input_{i}"])
        for i in range(tables)
    }
    inputs["dense_input"] = jax.device_put(
        rng.randn(batch, leg["dense_feature_dim"]).astype(np.float32),
        shardings["dense_input"])
    y = jax.device_put(
        rng.rand(batch, leg["mlp_top"][-1]).astype(np.float32),
        ff.executor.label_sharding())
    for _ in range(3):
        m = ff.train_step(inputs, y)
    _ = float(m["loss"])
    dt = _steady_state(ff, inputs, y, iters)
    out = {
        "workload": f"DLRM b{batch} {tables}x{rows}-row tables train "
                    f"(embedding gather/scatter path)",
        "samples_per_sec_per_chip": round(batch / dt, 2),
    }
    if on_tpu:
        out["mfu"] = _mfu(ff, dt)
        # The honest utilization denominator for this bandwidth-bound
        # leg is HBM traffic, not FLOPs (VERDICT r4 #6).  Dominant
        # per-step bytes, from the model config (f32 weights/grads):
        #   per table: dense-grad buffer write (jax.grad materializes
        #   the scatter-add into a table-sized f32 buffer) + SGD update
        #   read w + read g + write w  =  4 x table bytes;
        #   gather/scatter rows themselves are noise at b<<rows.
        d = leg["sparse_feature_size"]
        table_bytes = rows * d * 4
        step_bytes = tables * 4 * table_bytes
        from flexflow_tpu.sim.machine_model import detect_device_spec

        peak = detect_device_spec().hbm_bandwidth
        out["hbm_gb_per_step"] = round(step_bytes / 1e9, 3)
        out["achieved_hbm_gbps"] = round(step_bytes / dt / 1e9, 1)
        out["hbm_utilization"] = round(step_bytes / dt / peak, 4)
    return out


def bench_moe_dispatch(dev, on_tpu):
    """MoE dispatch microbench: sort-based group_by+combine (the Pallas-
    era TPU trick, ops/moe_dispatch.py) vs the one-hot-matmul dispatch
    it replaces (group_by.cu's scatter in dense form), fixed
    tokens x experts.  Reports microseconds per dispatch+combine and the
    speedup (VERDICT r03 Missing #3)."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.ops.moe_dispatch import sort_combine, sort_group_by

    leg = MANIFEST["legs"]["moe_dispatch"]
    if on_tpu:
        tokens, experts, k, d = (leg["tokens"], leg["experts"], leg["k"],
                                 leg["d_model"])
        iters, windows = leg["iters"], MANIFEST["timing"]["windows"]
    else:
        tokens, experts, k, d, iters, windows = 256, 8, 2, 64, 3, 1

    capacity = max(1, int(leg["capacity_factor"] * tokens * k // experts))
    rng = np.random.RandomState(0)
    data = jax.device_put(
        rng.randn(tokens, d).astype(np.float32), dev)
    assign = jax.device_put(
        rng.randint(0, experts, size=(tokens, k)).astype(np.int32), dev)

    def sort_rows(data, assign):
        grouped = sort_group_by(data, assign, experts, capacity)
        rows, keep = sort_combine(grouped, assign, capacity)
        return rows

    def onehot_rows(data, assign, precision=None):
        # dense dispatch: [tokens*k, experts*cap] one-hot matmul (what
        # sort-based dispatch replaces; reference group_by.cu scatter)
        flat = assign.reshape(-1)
        bk = flat.shape[0]
        # position-within-expert via cumsum over one-hot (dense ranks)
        oh = jax.nn.one_hot(flat, experts, dtype=data.dtype)  # [bk, n]
        rank = (jnp.cumsum(oh, axis=0) - oh) * oh  # rank per token
        r = jnp.sum(rank, axis=1).astype(jnp.int32)
        keep = r < capacity
        slot_oh = (oh[:, :, None]
                   * jax.nn.one_hot(jnp.minimum(r, capacity - 1), capacity,
                                    dtype=data.dtype)[:, None, :])
        slot_oh = slot_oh.reshape(bk, experts * capacity)
        slot_oh = slot_oh * keep[:, None].astype(data.dtype)
        rows = jnp.repeat(data, k, axis=0)
        grouped = jnp.matmul(slot_oh.T, rows, precision=precision)  # [n*cap, d]
        back = jnp.matmul(slot_oh, grouped, precision=precision)  # combine
        return back

    sort_path = jax.jit(lambda d, a: jnp.sum(sort_rows(d, a)))
    onehot_path = jax.jit(lambda d, a: jnp.sum(onehot_rows(d, a)))

    # both paths implement the same capacity-bounded dispatch: each
    # (expert, slot) receives exactly one token row, so at exact matmul
    # precision the full row arrays must agree (TPU's default-precision
    # matmul truncates f32 operands to bf16 passes, which is why the
    # value check pins precision while the TIMED one-hot path keeps the
    # default — the realistic, faster dense dispatch); recorded in the
    # JSON so a silent divergence can't masquerade as a speedup
    match_fn = jax.jit(lambda d, a: jnp.all(jnp.isclose(
        sort_rows(d, a),
        onehot_rows(d, a, precision=jax.lax.Precision.HIGHEST),
        rtol=1e-4, atol=1e-5)))  # on-device: one boolean crosses the tunnel
    paths_match = bool(match_fn(data, assign))

    def time_fn(fn):
        _ = float(fn(data, assign))  # compile + warm

        def window():
            t0 = time.perf_counter()
            for _ in range(iters):
                r = fn(data, assign)
            _ = float(r)
            return (time.perf_counter() - t0) / iters

        return min(window() for _ in range(windows))

    t_sort = time_fn(sort_path)
    t_onehot = time_fn(onehot_path)
    return {
        "workload": f"MoE dispatch+combine {tokens} tok x {experts} experts "
                    f"k={k} cap_factor={leg['capacity_factor']}",
        "sort_dispatch_us": round(t_sort * 1e6, 1),
        "one_hot_dispatch_us": round(t_onehot * 1e6, 1),
        "sort_vs_one_hot_speedup": round(t_onehot / t_sort, 2),
        "paths_match": paths_match,
    }


def bench_weight_update(on_tpu):
    """ZeRO-1 weight-update microbench (manifest v7): the Adam update
    pass over the BERT-base parameter set, sharded along a dp mesh of
    all visible devices vs replicated.  Uses the executor's own spec
    machinery (parallel/zero.py) so a regression in the update path —
    compute or layout — moves these numbers.  update-ms is a serial
    chain of donated updates with one hard sync."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from flexflow_tpu.optimizer import AdamOptimizer
    from flexflow_tpu.parallel.zero import shard_update_sharding

    leg = MANIFEST["legs"]["weight_update"]
    if on_tpu:
        hidden, layers = leg["hidden"], leg["layers"]
        inter, vocab, iters = leg["intermediate"], leg["vocab"], leg["iters"]
    else:
        hidden, layers, inter, vocab, iters = 64, 2, 128, 1000, 3

    devs = jax.devices()
    dp = len(devs)
    mesh = Mesh(np.asarray(devs), ("data",))
    rep = NamedSharding(mesh, PartitionSpec())

    shapes = {"embed.weight": (vocab, hidden)}
    for i in range(layers):
        shapes.update({
            f"l{i}.qkv": (hidden, 3 * hidden),
            f"l{i}.proj": (hidden, hidden),
            f"l{i}.up": (hidden, inter),
            f"l{i}.down": (inter, hidden),
            f"l{i}.ln_scale": (hidden,),
            f"l{i}.ln_bias": (hidden,),
        })
    rng = np.random.RandomState(0)
    host_w = {k: rng.randn(*s).astype(np.float32) * 0.02
              for k, s in shapes.items()}
    host_g = {k: rng.randn(*s).astype(np.float32) * 1e-3
              for k, s in shapes.items()}
    opt = AdamOptimizer(alpha=1e-3)
    out = {
        "workload": f"Adam update, BERT-base param set "
                    f"({layers}L h{hidden}), dp={dp} "
                    f"(ZeRO-1 sharded vs replicated)",
        "dp": dp,
    }
    for mode in ("replicated", "sharded"):
        slot_sh = {
            k: (shard_update_sharding(rep, v.shape, mesh, "data")
                if mode == "sharded" else rep)
            for k, v in host_w.items()
        }
        weights = {k: jax.device_put(v, rep) for k, v in host_w.items()}
        grads = {k: jax.device_put(v, rep) for k, v in host_g.items()}
        state = opt.init_state(weights)
        state = {
            k: (jax.tree.map(lambda v, s: jax.device_put(v, s), sub, slot_sh)
                if isinstance(sub, dict) else jax.device_put(sub, rep))
            for k, sub in state.items()
        }

        def step(w, s, g, _sh=slot_sh, _mode=mode):
            if _mode == "sharded":
                g = jax.tree.map(jax.lax.with_sharding_constraint, g, _sh)
                w = jax.tree.map(jax.lax.with_sharding_constraint, w, _sh)
            nw, ns = opt.update(w, g, s)
            if _mode == "sharded":
                nw = jax.tree.map(
                    lambda v: jax.lax.with_sharding_constraint(v, rep), nw
                )
                ns = {
                    k: (jax.tree.map(
                        jax.lax.with_sharding_constraint, sub, _sh)
                        if isinstance(sub, dict) else sub)
                    for k, sub in ns.items()
                }
            return nw, ns

        jstep = jax.jit(step, donate_argnums=(0, 1))
        weights, state = jstep(weights, state, grads)  # compile + warm
        jax.block_until_ready(jax.tree.leaves(weights)[0])

        def window():
            nonlocal weights, state
            t0 = time.perf_counter()
            for _ in range(iters):
                weights, state = jstep(weights, state, grads)
            jax.block_until_ready(jax.tree.leaves(weights)[0])
            return (time.perf_counter() - t0) / iters

        dt = min(window() for _ in range(MANIFEST["timing"]["windows"]))
        slot_bytes = sum(
            int(np.prod(sub[k2].sharding.shard_shape(sub[k2].shape))
                * sub[k2].dtype.itemsize)
            for key, sub in state.items() if isinstance(sub, dict)
            for k2 in sub
        )
        out[f"update_ms_{mode}"] = round(dt * 1e3, 3)
        out[f"opt_state_mb_per_device_{mode}"] = round(
            slot_bytes / 2**20, 2
        )
    if out["update_ms_sharded"] > 0:
        out["sharded_vs_replicated_speedup"] = round(
            out["update_ms_replicated"] / out["update_ms_sharded"], 2
        )
    return out


def bench_zero_ladder(dev, on_tpu):
    """ZeRO-ladder leg (manifest v14): stages 0-3 through the REAL
    executor — per stage, the same dp-mesh Adam MLP is compiled with
    --zero-stage and the leg times GraphExecutor's wrapped update pass
    (the exact reduce-scatter / 1-over-dp-shard update / all-gather
    wiring fit runs) and records grad-buffer, master-weight-resident,
    and opt-state bytes/device from the actual NamedShardings.  On
    dp=1 every stage coincides (update-pass regression tracker); on
    multi-device captures grad bytes fall ~1/dp at stage >= 2 and
    weight-resident bytes at stage 3."""
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType
    from flexflow_tpu.fftype import ActiMode
    from flexflow_tpu.optimizer import AdamOptimizer
    from flexflow_tpu.strategy import data_parallel_strategy

    leg = MANIFEST["legs"]["zero_ladder"]
    if on_tpu:
        in_dim, hidden, layers = leg["input_dim"], leg["hidden"], leg["layers"]
        classes, batch, iters = leg["classes"], leg["batch"], leg["iters"]
    else:
        in_dim, hidden, layers, classes, batch, iters = 128, 256, 2, 64, 8, 3

    devs = jax.devices()
    dp = len(devs)
    out = {
        "workload": f"Adam MLP {layers}L h{hidden}, dp={dp}, "
                    f"executor update pass at --zero-stage 0..3",
        "dp": dp,
        "stages": {},
    }

    def tree_mb(shardings, leaves):
        """Per-device MB of `leaves` laid out per the sharding tree."""
        b = 0
        for op_name, entry in shardings.items():
            for wname, sh in entry.items():
                leaf = leaves[op_name][wname]
                b += int(np.prod(sh.shard_shape(leaf.shape))
                         * leaf.dtype.itemsize)
            # noqa: E501 — exact shard-shape sums, no estimate
        return round(b / 2**20, 3)

    for stage in (0, 1, 2, 3):
        cfg = FFConfig(batch_size=batch, num_devices=dp, zero_stage=stage)
        ff = FFModel(cfg)
        x = ff.create_tensor([batch, in_dim], name="x")
        t = x
        for _ in range(layers):
            t = ff.dense(t, hidden, activation=ActiMode.RELU)
        t = ff.dense(t, classes)
        ff.softmax(t)
        ff.compile(
            optimizer=AdamOptimizer(alpha=1e-3),
            loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
            strategy=data_parallel_strategy(dp),
            devices=devs,
        )
        ex = ff.executor
        grad_sh = ex.grad_shardings()
        grads = jax.tree.map(
            lambda v, s: jax.device_put(np.asarray(v) * 1e-3, s),
            ff._weights, grad_sh,
        )
        update_fn = ex._make_update_fn(ff.optimizer)
        jstep = jax.jit(update_fn, donate_argnums=(0, 2))
        weights, state = jstep(ff._weights, grads, ff._opt_state)
        jax.block_until_ready(jax.tree.leaves(weights)[0])

        def window():
            nonlocal weights, state
            t0 = time.perf_counter()
            for _ in range(iters):
                weights, state = jstep(weights, grads, state)
            jax.block_until_ready(jax.tree.leaves(weights)[0])
            return (time.perf_counter() - t0) / iters

        dt = min(window() for _ in range(MANIFEST["timing"]["windows"]))
        slot_b = sum(
            int(np.prod(leaf.sharding.shard_shape(leaf.shape))
                * leaf.dtype.itemsize)
            for sub in state.values() if isinstance(sub, dict)
            for entry in sub.values() for leaf in entry.values()
        )
        out["stages"][f"zero{stage}"] = {
            "update_ms": round(dt * 1e3, 3),
            "grad_mb_per_device": tree_mb(grad_sh, ff._weights),
            "weight_resident_mb_per_device": tree_mb(
                ex.master_weight_shardings(), ff._weights
            ),
            "opt_state_mb_per_device": round(slot_b / 2**20, 3),
            "fallback_leaves": len(ex.zero_fallback_leaves()),
        }
    s1 = out["stages"]["zero1"]
    s2, s3 = out["stages"]["zero2"], out["stages"]["zero3"]
    if s1["grad_mb_per_device"] > 0:
        out["grad_shrink_stage2"] = round(
            s1["grad_mb_per_device"] / max(s2["grad_mb_per_device"], 1e-9), 2
        )
    if s1["weight_resident_mb_per_device"] > 0:
        out["weight_shrink_stage3"] = round(
            s1["weight_resident_mb_per_device"]
            / max(s3["weight_resident_mb_per_device"], 1e-9), 2
        )
    return out


def bench_long_context(dev, on_tpu):
    """Searched-remat long-context leg (manifest v17, docs/PERF.md
    "Searched rematerialization"): the seq2048 BERT config under
    --memory-search with a modeled per-device HBM budget sized strictly
    between the all-on-remat and no-remat footprints.  The no-remat
    ladder cannot fit (OOM at the modeled ceiling); the search must
    choose a per-segment remat plan that does, at less simulated time
    than checkpointing everything.  The chosen plan is then LOWERED
    through the real executor (jax.checkpoint on exactly the chosen
    segments) and the leg logs predicted-vs-measured step time for it."""
    import dataclasses as _dc

    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_bert
    from flexflow_tpu.pcg.evaluator import IncrementalEvaluator
    from flexflow_tpu.pcg.unity import UnitySearch
    from flexflow_tpu.sim.machine_model import (
        TpuPodModel,
        detect_device_spec,
    )
    from flexflow_tpu.sim.simulator import (
        OpCostModel,
        Simulator,
        remat_segments,
    )
    from flexflow_tpu.strategy import data_parallel_strategy

    leg = MANIFEST["legs"]["long_context"]
    if on_tpu:
        batch, seq = leg["batch"], leg["seq"]
        hidden, layers = leg["hidden"], leg["layers"]
        heads, inter = leg["heads"], leg["intermediate"]
        iters, vocab = leg["iters"], 30522
    else:
        # smoke dims stay activation-dominated (small vocab/hidden,
        # larger batch x seq) so the remat decision is still exercised
        batch, seq, hidden, layers, heads, inter, iters = 32, 128, 64, 2, 4, 128, 3
        vocab = 512

    print("bench[long-context]: searching remat plan", file=sys.stderr)
    cfg = FFConfig(batch_size=batch, num_devices=1,
                   compute_dtype=leg["dtype"] if on_tpu else "float32")
    ff = FFModel(cfg)
    build_bert(ff, batch_size=batch, seq_length=seq, hidden_size=hidden,
               num_layers=layers, num_heads=heads, intermediate_size=inter,
               vocab_size=vocab, from_token_ids=True)
    machine = TpuPodModel(topology=(1,), device=detect_device_spec())
    sim = Simulator(machine)
    ev = IncrementalEvaluator(ff.layers, sim)
    dp = data_parallel_strategy(1)
    dense = ev.evaluate(dp)
    n_seg = len(remat_segments(dense.ops))
    all_on = ev.evaluate(_dc.replace(dp, remat=list(range(n_seg))))
    saved = dense.per_device_memory - all_on.per_device_memory
    budget = all_on.per_device_memory + int(saved * leg["budget_frac"])

    search = UnitySearch(ff.layers, 1, machine, OpCostModel(machine),
                         memory_budget=budget, enable_pipeline=False,
                         remat_search=True, budget=leg["search_budget"])
    chosen = search.optimize_with_memory()
    plan = list(chosen.remat or []) if chosen is not None else []
    res = ev.evaluate(chosen) if chosen is not None else dense
    out = {
        "workload": f"BERT-base seq{seq} b{batch} --memory-search with "
                    f"per-segment remat, modeled HBM budget between the "
                    f"all-on and no-remat footprints",
        "segments": n_seg,
        "remat_plan": ",".join(str(i) for i in plan),
        "remat_segments_on": len(plan),
        "modeled_budget_mb": round(budget / 2**20, 1),
        "no_remat_mb": round(dense.per_device_memory / 2**20, 1),
        "all_on_mb": round(all_on.per_device_memory / 2**20, 1),
        "chosen_mb": round(res.per_device_memory / 2**20, 1),
        # the acceptance triple: the dense ladder OOMs the modeled
        # ceiling, the chosen plan fits it, and costs less simulated
        # time than checkpointing everything
        "no_remat_fits_budget": bool(dense.per_device_memory <= budget),
        "chosen_fits_budget": bool(res.per_device_memory <= budget),
        "predicted_step_ms_no_remat": round(dense.total_time * 1e3, 3),
        "predicted_step_ms_all_on": round(all_on.total_time * 1e3, 3),
        "predicted_step_ms_chosen": round(res.total_time * 1e3, 3),
        "chosen_beats_all_on": bool(res.total_time < all_on.total_time),
        "predicted_recompute_ms": round(res.recompute_s * 1e3, 3),
        "remat_nontrivial": bool(
            plan and len(plan) < sum(
                1 for _, pure in remat_segments(dense.ops) if pure
            )
        ),
        "saved_activation_mb": round(
            (dense.activation_bytes - res.activation_bytes) / 2**20, 2
        ),
    }
    # the acceptance bar, asserted like the other legs' (a silent
    # search regression must fail the capture, not footnote it)
    assert not out["no_remat_fits_budget"]
    assert out["chosen_fits_budget"], out
    assert out["chosen_beats_all_on"], out

    # lower the chosen plan through the real executor and measure
    print("bench[long-context]: compiling chosen plan", file=sys.stderr)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        strategy=chosen if chosen is not None else dp,
        devices=[dev],
    )
    rng = np.random.RandomState(0)
    ids = jax.device_put(
        rng.randint(0, vocab, size=(batch, seq)).astype(np.int32),
        ff.executor.input_shardings()["input"],
    )
    y = jax.device_put(rng.randint(0, 2, batch).astype(np.int32),
                       ff.executor.label_sharding())
    for _ in range(3):
        m = ff.train_step({"input": ids}, y)
    _ = float(m["loss"])
    dt = _steady_state(ff, {"input": ids}, y, iters)
    out["measured_step_ms"] = round(dt * 1e3, 3)
    out["predicted_vs_measured"] = round(
        res.total_time / dt, 3
    ) if dt > 0 else None
    out["tokens_per_sec_per_chip"] = round(batch * seq / dt, 0)
    ex_plan = ff.executor._remat_plan
    out["executor_segments_checkpointed"] = (
        sum(1 for *_, pure in ex_plan if pure) if ex_plan else 0
    )
    return out


def bench_multi_slice(dev, on_tpu):
    """Multi-slice topology leg (manifest v16, docs/TOPOLOGY.md): the
    same model searched on a flat 1x8 mesh vs a 2x4 slice hierarchy
    with a simulated DCN ~20x slower than the effective ICI.  Reports
    the predicted step time on each machine, the searched placement
    (which mesh axis crosses the DCN boundary), whether the grad
    reduction lowers hierarchically, and the per-tier predicted comm
    bytes — asserting the searched strategy keeps the bulk of its
    traffic intra-slice (dcn_bytes < ici_bytes)."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.fftype import ActiMode
    from flexflow_tpu.pcg.evaluator import IncrementalEvaluator
    from flexflow_tpu.pcg.unity import UnitySearch
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel, Simulator
    from flexflow_tpu.topology.hierarchy import SliceHierarchy

    leg = MANIFEST["legs"]["multi_slice"]
    batch, hidden = leg["batch"], leg["hidden"]
    slices, dcn_bw = leg["slices"], leg["dcn_bandwidth"]
    n = leg["devices"]
    per_slice = n // slices
    print("bench[multi_slice]: searching flat vs hierarchy",
          file=sys.stderr)

    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor([batch, hidden], name="x")
    t = ff.dense(x, hidden, activation=ActiMode.RELU)
    t = ff.dense(t, hidden, activation=ActiMode.RELU)
    t = ff.dense(t, 8)
    ff.softmax(t)

    out = {
        "workload": f"{slices}x{per_slice} hierarchy vs 1x{n} flat, "
                    f"MLP b{batch} h{hidden}, unity search "
                    f"(simulator-driven; DCN {dcn_bw / 1e9:g} GB/s)",
        "machines": {},
    }
    machines = {
        "flat_1x8": TpuPodModel(topology=(n,)),
        f"hier_{slices}x{per_slice}": SliceHierarchy(
            topology=(per_slice,), slices=slices,
            dcn_bw_per_host=dcn_bw, dcn_latency=leg["dcn_latency"],
        ),
    }
    for name, machine in machines.items():
        search = UnitySearch(ff.layers, n, machine, OpCostModel(machine),
                             enable_pipeline=False)
        best = search.optimize()
        res = IncrementalEvaluator(ff.layers, Simulator(machine)).evaluate(
            best
        )
        tiers = res.comm_tiers
        entry = {
            "mesh_axes": dict(best.mesh_axes),
            "predicted_step_ms": round(res.total_time * 1e3, 4),
            "placement": best.search_stats["placement"],
            "hierarchical_reduction":
                best.search_stats["hierarchical_reduction"],
            "ici_comm_kb": round(tiers["ici_bytes"] / 1024.0, 2),
            "dcn_comm_kb": round(tiers["dcn_bytes"] / 1024.0, 2),
        }
        out["machines"][name] = entry
    hier = out["machines"][f"hier_{slices}x{per_slice}"]
    flat = out["machines"]["flat_1x8"]
    # the hierarchy-searched winner keeps the bulk of its comm on ICI
    out["dp_traffic_intra_slice"] = bool(
        hier["dcn_comm_kb"] < hier["ici_comm_kb"]
    )
    assert out["dp_traffic_intra_slice"], (
        "hierarchy search left more predicted bytes on DCN than ICI: "
        f"{hier}"
    )
    out["hier_vs_flat_predicted"] = round(
        hier["predicted_step_ms"] / max(flat["predicted_step_ms"], 1e-9), 3
    )
    return out


def _fsck_verdict(local_dir=None, remote_uri=None):
    """Post-bench verification (manifest v15): run the offline
    two-tier checkpoint verifier (tools/checkpoint_fsck.py) over the
    dirs a leg just produced, BEFORE they are cleaned up — a bench
    that published a corrupt checkpoint should say so in its own
    numbers, not pass silently."""
    from tools.checkpoint_fsck import fsck_local, fsck_remote

    out = {}
    problems = []
    if local_dir is not None:
        rep = fsck_local(local_dir)
        step_problems = [p for s in rep["steps"].values()
                         for p in s["problems"]]
        problems += rep["problems"] + step_problems
        out["local_steps_verified"] = sum(
            1 for s in rep["steps"].values() if s["ok"])
    if remote_uri is not None:
        rep = fsck_remote(remote_uri)
        step_problems = [p for s in rep.get("steps", {}).values()
                         for p in s["problems"]]
        problems += rep.get("problems", []) + step_problems
        out["remote_steps_verified"] = sum(
            1 for s in rep.get("steps", {}).values() if s["ok"])
    out["ok"] = not problems
    if problems:
        out["problems"] = problems[:5]
    return out


def bench_checkpoint(dev, on_tpu):
    """Checkpoint-stall microbench (manifest v9): the step-boundary
    stall of a full-train-state save under the durability layer
    (checkpoint.py).  Sync saves pay serialize + fsync + crc-verify +
    publish inline; async saves (`wait=False`) stall only for the
    device->host snapshot and hand the rest to the background writer —
    this leg records both stalls plus the writer's flush throughput, so
    a regression in either the snapshot path or the verified-write path
    moves a number."""
    import shutil
    import tempfile

    from flexflow_tpu import FFConfig, FFModel, LossType
    from flexflow_tpu.checkpoint import LocalCheckpointManager
    from flexflow_tpu.fftype import ActiMode
    from flexflow_tpu.optimizer import AdamOptimizer

    leg = MANIFEST["legs"]["checkpoint"]
    if on_tpu:
        in_dim, hidden, layers = leg["input_dim"], leg["hidden"], leg["layers"]
        classes, batch, iters = leg["classes"], leg["batch"], leg["iters"]
    else:
        in_dim, hidden, layers, classes, batch, iters = 256, 512, 3, 512, 16, 3

    cfg = FFConfig(batch_size=batch, num_devices=1)
    ff = FFModel(cfg)
    t = ff.create_tensor([batch, in_dim], name="x")
    for _ in range(layers):
        t = ff.dense(t, hidden, activation=ActiMode.RELU)
    t = ff.dense(t, classes)
    ff.softmax(t)
    # Adam: m/v slots triple the serialized state vs bare weights —
    # the realistic full-train-state payload
    ff.compile(optimizer=AdamOptimizer(alpha=1e-3),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    xs = rng.randn(batch, in_dim).astype(np.float32)
    ys = rng.randint(0, classes, size=batch).astype(np.int32)
    m = ff.train_step({"x": xs}, ys)  # materialize weights + slots
    _ = float(m["loss"])

    tmpdir = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        mgr = LocalCheckpointManager(tmpdir, max_to_keep=2)
        sync_stalls, async_stalls, flushes = [], [], []
        step = 0
        for _ in range(iters):
            step += 1
            t0 = time.perf_counter()
            mgr.save(ff, step, wait=True)
            sync_stalls.append(time.perf_counter() - t0)
        for _ in range(iters):
            step += 1
            t0 = time.perf_counter()
            mgr.save(ff, step, wait=False)
            t1 = time.perf_counter()
            async_stalls.append(t1 - t0)  # snapshot + enqueue only
            failures = mgr.drain()
            flushes.append(time.perf_counter() - t1)
            assert not failures, failures
        with open(os.path.join(mgr._path(step), "manifest.json")) as f:
            total_bytes = json.load(f)["total_bytes"]
        mgr.close()
        fsck = _fsck_verdict(local_dir=tmpdir)
        assert fsck["ok"], fsck
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    stall_sync = min(sync_stalls)
    stall_async = min(async_stalls)
    flush = min(flushes)
    return {
        "workload": f"full-train-state save ({layers}L h{hidden} Adam), "
                    "sync write vs async snapshot-only stall, crc32-verified",
        "state_mb": round(total_bytes / 2**20, 2),
        "stall_ms_sync": round(stall_sync * 1e3, 3),
        "stall_ms_async_snapshot": round(stall_async * 1e3, 3),
        "async_stall_below_sync": bool(stall_async < stall_sync),
        "sync_vs_async_stall_ratio": round(stall_sync / max(stall_async, 1e-9), 2),
        "flush_ms": round(flush * 1e3, 3),
        # serialize+fsync+verify+publish throughput of the background writer
        "write_mb_per_s": round(total_bytes / 2**20 / max(flush, 1e-9), 1),
        "fsck": fsck,
    }


def bench_cold_start(dev, on_tpu):
    """Cold-start leg (manifest v11): what the strategy store buys at
    process start.  Same model, same config, twice against one store
    root: the first `FFModel.compile` pays the Unity search and
    publishes; the second restores the strategy (search_stats records
    store_hit) — the leg reports both wall times and the speedup.
    When the host exposes >= 8 devices it also measures the resilience
    supervisor's elastic 8->4 device-loss recovery cold (re-search on
    the 4-survivor mesh) vs warm (the degraded-mesh key is already
    published), the store's second job after replica spin-up."""
    import shutil
    import tempfile

    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType
    from flexflow_tpu.fftype import ActiMode
    from flexflow_tpu.optimizer import SGDOptimizer

    leg = MANIFEST["legs"]["cold_start"]
    hidden, layers = leg["hidden"], leg["layers"]
    classes, batch = leg["classes"], leg["batch"]
    budget = leg["search_budget"]

    devs = jax.devices()
    n = min(len(devs), leg["devices_cap"])

    def build(store_root, ndev, **cfg_kw):
        cfg = FFConfig(batch_size=batch, num_devices=ndev,
                       search_budget=budget, strategy_store=store_root,
                       enable_parameter_parallel=True, **cfg_kw)
        ff = FFModel(cfg)
        t = ff.create_tensor([batch, leg["input_dim"]], name="x")
        for _ in range(layers):
            t = ff.dense(t, hidden, activation=ActiMode.RELU)
        t = ff.dense(t, classes)
        ff.softmax(t)
        return ff

    def timed_compile(store_root):
        ff = build(store_root, n)
        t0 = time.perf_counter()
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   devices=devs[:n])
        return time.perf_counter() - t0, ff

    tmpdir = tempfile.mkdtemp(prefix="cold_start_bench_")
    try:
        cold_s, ff_cold = timed_compile(tmpdir)
        warm_s, ff_warm = timed_compile(tmpdir)
        assert not ff_cold.strategy.search_stats.get("store_hit")
        assert ff_warm.strategy.search_stats.get("store_hit")
        result = {
            "workload": f"compile-with-search vs compile-with-warm-store "
                        f"({layers}L h{hidden} MLP, unity budget {budget}, "
                        f"{n} devices)",
            "compile_s_cold": round(cold_s, 3),
            "compile_s_warm": round(warm_s, 3),
            "warm_store_hit": True,
            "cold_vs_warm_speedup": round(cold_s / max(warm_s, 1e-9), 2),
        }
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    # -- elastic 8->4 recovery, warm vs cold store ----------------------
    result["elastic"] = None
    if len(devs) >= 8:
        from flexflow_tpu.resilience import FaultPlan
        from flexflow_tpu.resilience.faults import FaultKind

        steps, fault_step = leg["elastic_steps"], leg["elastic_fault_step"]
        rng = np.random.RandomState(0)
        xs = rng.randn(batch * 4, leg["input_dim"]).astype(np.float32)
        ys = rng.randint(0, classes, size=batch * 4).astype(np.int32)

        def run_once(store_root, ckpt_dir):
            ff = build(store_root, 8, checkpoint_every=1, max_restarts=3,
                       retry_backoff=0.0)
            ff.compile(optimizer=SGDOptimizer(lr=0.01),
                       loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                       devices=devs[:8])
            plan = FaultPlan.single(fault_step, FaultKind.DEVICE_LOSS,
                                    survivors=4)
            t0 = time.perf_counter()
            report = ff.fit_resilient(
                {"x": xs}, ys, num_steps=steps, batch_size=batch,
                directory=ckpt_dir, fault_plan=plan,
            )
            dt = time.perf_counter() - t0
            assert report.final_step == steps
            return dt, report.counters

        store2 = tempfile.mkdtemp(prefix="cold_start_elastic_")
        try:
            ck1 = tempfile.mkdtemp(prefix="cold_start_ck1_")
            ck2 = tempfile.mkdtemp(prefix="cold_start_ck2_")
            try:
                cold_run_s, cold_counters = run_once(store2, ck1)
                warm_run_s, warm_counters = run_once(store2, ck2)
                assert cold_counters["re_search_store_hits"] == 0
            finally:
                shutil.rmtree(ck1, ignore_errors=True)
                shutil.rmtree(ck2, ignore_errors=True)
            result["elastic"] = {
                "recovery_run_s_cold": round(cold_run_s, 3),
                "recovery_run_s_warm": round(warm_run_s, 3),
                "warm_re_search_store_hits": int(
                    warm_counters["re_search_store_hits"]
                ),
                "cold_vs_warm_speedup": round(
                    cold_run_s / max(warm_run_s, 1e-9), 2
                ),
            }
        finally:
            shutil.rmtree(store2, ignore_errors=True)
    return result


def bench_host_loss(dev, on_tpu):
    """Host-loss leg (manifest v13): what the durable offload tier
    costs in steady state and what it buys after a full host loss.

    Block 1 — steady-state overhead: the same supervised training run
    with the checkpoint mirror OFF vs ON (filesystem blob backend);
    the mirror uploads on a background thread, so the per-step delta
    should be noise.

    Block 2 — fresh-host recovery: after the offload-ON run, the
    entire local checkpoint directory AND strategy store are deleted
    (the host loss).  Time-to-first-step on a brand-new "host":
    compile (warm REMOTE strategy store — the search is skipped) +
    restore from REMOTE_LATEST + one training step, vs a fully cold
    start (fresh search, no checkpoint, training from step 0)."""
    import shutil
    import tempfile

    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType
    from flexflow_tpu.fftype import ActiMode
    from flexflow_tpu.optimizer import SGDOptimizer

    leg = MANIFEST["legs"]["host_loss"]
    hidden, layers = leg["hidden"], leg["layers"]
    classes, batch = leg["classes"], leg["batch"]
    steps, every = leg["steps"], leg["checkpoint_every"]

    devs = jax.devices()
    n = min(len(devs), 8)
    rng = np.random.RandomState(0)
    xs = rng.randn(batch * 4, leg["input_dim"]).astype(np.float32)
    ys = rng.randint(0, classes, size=batch * 4).astype(np.int32)

    def build(store_root=None, remote=None, budget=0):
        cfg = FFConfig(batch_size=batch, num_devices=n,
                       search_budget=budget, strategy_store=store_root,
                       remote_store=remote, checkpoint_every=every,
                       enable_parameter_parallel=bool(budget),
                       retry_backoff=0.0)
        ff = FFModel(cfg)
        t = ff.create_tensor([batch, leg["input_dim"]], name="x")
        for _ in range(layers):
            t = ff.dense(t, hidden, activation=ActiMode.RELU)
        t = ff.dense(t, classes)
        ff.softmax(t)
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   devices=devs[:n])
        return ff

    def run_steps(ff, ckpt_dir, num_steps, resume=False):
        t0 = time.perf_counter()
        report = ff.fit_resilient({"x": xs}, ys, num_steps=num_steps,
                                  batch_size=batch, directory=ckpt_dir,
                                  resume=resume)
        return time.perf_counter() - t0, report

    roots = {name: tempfile.mkdtemp(prefix=f"host_loss_{name}_")
             for name in ("ck_off", "ck_on", "blob", "store", "store2",
                          "ck_fresh", "ck_cold")}
    try:
        # -- block 1: steady-state step-time overhead, offload off/on --
        # both runs share the strategy store, so the OFF baseline
        # executes the SAME searched strategy (warm hit) and the delta
        # isolates the mirror, not a strategy difference.  The ON model
        # builds FIRST: its fresh search publishes through to the fleet
        # mirror (a warm local hit would not), which block 2 relies on
        ff_on = build(store_root=roots["store"], remote=roots["blob"],
                      budget=leg["search_budget"])
        ff_off = build(store_root=roots["store"],
                       budget=leg["search_budget"])
        assert ff_on.strategy.to_json() == ff_off.strategy.to_json()
        # identical 2-step warmup each, so neither timed run pays the
        # process's one-time XLA/first-touch costs
        for ff in (ff_off, ff_on):
            for _ in range(2):
                ff.train_step({"x": xs[:batch]}, ys[:batch])
        off_s, off_rep = run_steps(ff_off, roots["ck_off"], steps)
        on_s, on_rep = run_steps(ff_on, roots["ck_on"], steps)
        assert off_rep.final_step == steps and on_rep.final_step == steps
        assert on_rep.counters["offload_uploads"] >= 1
        step_ms_off = off_s / steps * 1e3
        step_ms_on = on_s / steps * 1e3
        del ff_off, ff_on

        # -- block 2: the host dies — local ckpts + store are GONE ----
        shutil.rmtree(roots["ck_on"])
        shutil.rmtree(roots["store"])

        t0 = time.perf_counter()
        ff_warm = build(store_root=roots["store2"], remote=roots["blob"],
                        budget=leg["search_budget"])
        warm_report = ff_warm.fit_resilient(
            {"x": xs}, ys, num_steps=steps + 1, batch_size=batch,
            directory=roots["ck_fresh"], resume=True,
        )
        warm_s = time.perf_counter() - t0
        assert warm_report.final_step == steps + 1
        warm_store_hit = bool(
            (ff_warm.strategy.search_stats or {}).get("store_hit")
        )

        t0 = time.perf_counter()
        # store_root="none" is the explicit opt-out: a bare None would
        # fall through to $FLEXFLOW_TPU_STORE_DIR and the "cold" compile
        # could warm-hit (and pollute) the user's fleet store
        ff_cold = build(store_root="none", budget=leg["search_budget"])
        cold_report = ff_cold.fit_resilient(
            {"x": xs}, ys, num_steps=1, batch_size=batch,
            directory=roots["ck_cold"],
        )
        cold_s = time.perf_counter() - t0
        assert cold_report.final_step == 1

        # post-bench verification: both tiers the drill produced must
        # fsck clean (every manifest crc, LATEST/REMOTE_LATEST intact)
        fsck = _fsck_verdict(local_dir=roots["ck_fresh"],
                             remote_uri=roots["blob"])
        assert fsck["ok"], fsck

        return {
            "workload": (
                f"{layers}L h{hidden} MLP, {steps} supervised steps, "
                f"checkpoint_every={every}, filesystem blob backend, "
                f"{n} devices"
            ),
            "step_ms_offload_off": round(step_ms_off, 2),
            "step_ms_offload_on": round(step_ms_on, 2),
            "offload_overhead_pct": round(
                (step_ms_on - step_ms_off) / max(step_ms_off, 1e-9) * 100, 1
            ),
            "offload_uploads": int(on_rep.counters["offload_uploads"]),
            "offload_bytes": int(on_rep.counters["offload_bytes"]),
            "recovery": {
                # fresh host: warm remote strategy store + remote restore
                "warm_remote_time_to_first_step_s": round(warm_s, 3),
                "warm_store_hit": warm_store_hit,
                "resumed_from_step": steps,
                # no remote tier: full search, training restarts at 0
                "cold_start_time_to_first_step_s": round(cold_s, 3),
                "progress_kept_steps": steps,
            },
            "fsck": fsck,
        }
    finally:
        for path in roots.values():
            shutil.rmtree(path, ignore_errors=True)


def bench_serving(dev, on_tpu):
    """Generation-serving throughput leg (manifest v10): the same
    mixed-length workload and Poisson arrival sequence through the
    STATIC tier (GenerationBatcher: coalesce -> one scan, every row
    padded to the batch's pow2 total bucket, dense per-slot caches)
    and the CONTINUOUS tier (ContinuousScheduler: iteration-level
    admit/retire on the paged KV pool).  Reports sustained tokens/s,
    p50/p99 TTFT and per-token latency, and the pool's peak block
    occupancy — the acceptance bar is continuous beating static on
    tokens/s under length heterogeneity."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.serving import (ContinuousScheduler,
                                      GenerationBatcher,
                                      GenerationEngine)
    from flexflow_tpu.serving.loadgen import run_loadgen, sample_workload

    leg = MANIFEST["legs"]["serving"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        rate = leg["offered_rps"]
        plen_range = tuple(leg["prompt_len_range"])
        mnt_range = tuple(leg["max_new_range"])
        long_frac = leg["long_frac"]
        long_range = tuple(leg["long_max_new_range"])
    else:
        # saturating smoke load: offered rps well above service rate so
        # a backlog forms and tokens/s measures the SCHEDULER, not the
        # arrival process.  The model is sized so one decode step's
        # compute outweighs the continuous loop's per-step host
        # dispatch — the regime iteration-level batching targets (on
        # a real chip the model is orders of magnitude past this).
        # Reply lengths are heavy-tailed (75% short, 25% long), the
        # canonical serving distribution: one long request pads a
        # whole static batch to its bucket.
        vocab, max_seq = 128, 64
        hidden, layers, heads, inter = 256, 3, 8, 512
        slots, page, n_req, rate = 8, 8, 96, 600.0
        plen_range, mnt_range = (2, 12), (2, 10)
        long_frac, long_range = 0.25, (40, 56)

    cfg = FFConfig(batch_size=slots, num_devices=1)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    wl_rng = np.random.RandomState(11)
    workload = sample_workload(wl_rng, n_req, vocab,
                               prompt_len_range=plen_range,
                               max_new_range=mnt_range,
                               long_frac=long_frac,
                               long_max_new_range=long_range)

    # -- static tier: warm every pow2 total bucket the workload can hit
    static_engine = GenerationEngine(ff, batch_size=slots, devices=[dev])
    need = min(max_seq, max(len(p) + m for p, m in workload))
    bucket = 1
    while bucket < need:
        bucket <<= 1
        total = min(bucket, max_seq)
        static_engine.generate([workload[0][0][:2]],
                               max_new_tokens=total - 2)
    static_b = GenerationBatcher(static_engine, flush_timeout_s=0.02)
    try:
        static_report = run_loadgen(static_b, workload, rate, seed=7)
    finally:
        static_b.close()

    # -- continuous tier: one step program, one warmup request.
    # Equal-HBM sizing, the paged pool's actual pitch: the pool gets
    # exactly the block count whose bytes equal the static tier's
    # dense [slots, max_seq] caches, and the freed headroom becomes
    # 2x the decode slots — heterogeneous lengths mean the pool's
    # sum-of-live-lengths fits twice the sequences static can hold.
    max_blocks = max_seq // page
    sched = ContinuousScheduler.from_trained(
        ff, batch_slots=2 * slots, page_size=page,
        num_blocks=1 + slots * max_blocks, devices=[dev])
    try:
        sched.generate(workload[0][0], 2)  # pays the single compile
        cont_report = run_loadgen(sched, workload, rate, seed=7)
        pool_stats = sched.stats()["kv_pool"]
    finally:
        sched.close()

    ratio = (cont_report.get("tokens_per_s", 0.0)
             / max(static_report.get("tokens_per_s", 0.0), 1e-9))
    return {
        "workload": (
            f"{n_req} reqs, prompts {plen_range}, max_new {mnt_range}, "
            f"Poisson {rate} rps offered, greedy, {slots} slots, "
            f"page {page}"
        ),
        "static": static_report,
        "continuous": cont_report,
        "continuous_vs_static_tokens_per_s": round(ratio, 3),
        "kv_pool_peak_occupancy": round(
            pool_stats["peak_used_blocks"]
            / max(pool_stats["usable_blocks"], 1), 4),
        "kv_pool_peak_used_blocks": pool_stats["peak_used_blocks"],
        "kv_pool_usable_blocks": pool_stats["usable_blocks"],
    }


def bench_serving_prefix(dev, on_tpu):
    """Prefix-cache + chunked-prefill throughput leg (manifest v18):
    the SAME shared-prefix workload (K system prompts, per-request
    unique tails) and arrival sequence through the PR 6 continuous
    tier (sharing off, one-token prefill) and the prefix-cached tier
    (COW block sharing + [slots, C] chunked prefill) at EQUAL KV pool
    bytes.  Reports tokens/s both ways, p50/p99 TTFT, prefix-cache
    hit/shared/eviction counters and the shared-block high-water mark;
    asserts greedy completions byte-identical across modes, with the
    kv_pool invariant checker running at EVERY scheduler step of both
    runs.  Acceptance bar: >= 1.3x the baseline's tokens/s with lower
    p50 TTFT on the shared-prefix smoke workload."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.serving import ContinuousScheduler
    from flexflow_tpu.serving.loadgen import (run_loadgen,
                                              sample_shared_prefix_workload)

    leg = MANIFEST["legs"]["serving_prefix"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        rate, chunk = leg["offered_rps"], leg["prefill_chunk"]
        n_prefixes, prefix_len = leg["num_prefixes"], leg["prefix_len"]
        tail_range = tuple(leg["tail_range"])
        mnt_range = tuple(leg["max_new_range"])
    else:
        # prefill-heavy smoke shape: long shared prefixes (half the
        # position table), short unique tails and replies — the
        # system-prompt regime where the PR 6 tier burns most of its
        # steps re-prefilling identical tokens one at a time
        vocab, max_seq = 128, 64
        hidden, layers, heads, inter = 256, 3, 8, 512
        slots, page, n_req, rate, chunk = 8, 8, 64, 600.0, 8
        n_prefixes, prefix_len = 4, 32
        tail_range, mnt_range = (1, 7), (2, 8)

    cfg = FFConfig(batch_size=slots, num_devices=1)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    wl_rng = np.random.RandomState(23)
    workload, prefixes = sample_shared_prefix_workload(
        wl_rng, n_req, vocab, num_prefixes=n_prefixes,
        prefix_len=prefix_len, tail_range=tail_range,
        max_new_range=mnt_range)

    # equal-HBM pitch (the serving leg's): both pools get the block
    # bytes of a dense [slots, max_seq] cache, spent on 2x slots
    max_blocks = max_seq // page
    num_blocks = 1 + slots * max_blocks
    warm_rng = np.random.RandomState(999)
    warm = warm_rng.randint(0, vocab, page).tolist()  # 1 aligned page

    def run_tier(prefix_cache, prefill_chunk):
        sched = ContinuousScheduler.from_trained(
            ff, batch_slots=2 * slots, page_size=page,
            num_blocks=num_blocks, devices=[dev],
            prefix_cache=prefix_cache, prefill_chunk=prefill_chunk,
            check_invariants=True)  # invariant sweep at EVERY step
        try:
            # warm every program before timing: decode, chunked
            # prefill, and (second warm call = full-prompt hit) the
            # COW block copy.  The warm prompt is disjoint from the
            # workload prefixes.
            sched.generate(warm, 2, timeout=120.0)
            sched.generate(warm, 2, timeout=120.0)
            report = run_loadgen(sched, workload, rate, seed=13,
                                 detail=True, record_tokens=True)
            stats = sched.stats()
            sched.pool.check_invariants()
            return report, stats
        finally:
            sched.close()

    base_report, base_stats = run_tier(False, 0)
    prefix_report, prefix_stats = run_tier(True, chunk)

    # greedy completions must be byte-identical across modes
    def by_idx(report):
        return {r["idx"]: r["tokens"] for r in report["records"]
                if r.get("ok")}
    base_toks, prefix_toks = by_idx(base_report), by_idx(prefix_report)
    assert set(base_toks) == set(prefix_toks), "completion sets differ"
    mismatched = sum(1 for i in base_toks
                     if base_toks[i] != prefix_toks[i])
    assert mismatched == 0, \
        f"{mismatched} completions differ between sharing on/off"

    hit_total = sum(r.get("prefix_hit_tokens", 0)
                    for r in prefix_report["records"])
    ratio = (prefix_report.get("tokens_per_s", 0.0)
             / max(base_report.get("tokens_per_s", 0.0), 1e-9))
    pc = prefix_stats["prefix_cache"]
    return {
        "workload": (
            f"{n_req} reqs over {n_prefixes} shared {prefix_len}-token "
            f"prefixes, tails {tail_range}, max_new {mnt_range}, "
            f"Poisson {rate} rps, greedy, {2 * slots} slots, "
            f"page {page}, chunk {chunk}, equal KV bytes"
        ),
        "baseline": base_report,
        "prefix_cached": prefix_report,
        "prefix_vs_baseline_tokens_per_s": round(ratio, 3),
        "speedup_at_least_1_3": bool(ratio >= 1.3),
        "ttft_p50_lower": bool(
            prefix_report.get("ttft", {}).get("p50_ms", 1e9)
            < base_report.get("ttft", {}).get("p50_ms", 0.0)),
        "prefix_hit_tokens": hit_total,
        "prefix_cache": pc,
        "kv_shared_blocks_high_water": pc["peak_shared_blocks"],
        "prefill_steps": prefix_stats["prefill_steps"],
        "completions_identical": True,  # asserted above
        "invariants_checked_every_step": True,  # check_invariants=True
    }


def bench_serving_paged_kernel(dev, on_tpu):
    """Fused PagedAttention leg (manifest v19): the SAME shared-prefix
    workload and arrival gaps through the paged continuous tier under
    both READ formulations at equal KV pool bytes — `gather` (the
    dense block-gather oracle) vs `pallas` (the fused kernel streaming
    blocks in place, ops/pallas/paged_attention.py; interpret-mode off
    TPU, so the CPU smoke's tokens/s ratio measures the emulator, not
    the kernel).  Asserts greedy completions token-identical across
    formulations and that the kernel's per-step KV reads undercut the
    dense-gather equivalent — blocks read scale with live tokens, not
    the table width (the serving/paged_kernel_* telemetry)."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.serving import ContinuousScheduler
    from flexflow_tpu.serving.loadgen import (run_loadgen,
                                              sample_shared_prefix_workload)

    leg = MANIFEST["legs"]["serving_paged_kernel"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        rate, chunk = leg["offered_rps"], leg["prefill_chunk"]
        n_prefixes, prefix_len = leg["num_prefixes"], leg["prefix_len"]
        tail_range = tuple(leg["tail_range"])
        mnt_range = tuple(leg["max_new_range"])
    else:
        # small smoke shape: the interpret-mode kernel emulates every
        # grid program, so keep rows * heads * table width modest
        vocab, max_seq = 128, 64
        hidden, layers, heads, inter = 128, 2, 4, 256
        slots, page, n_req, rate, chunk = 4, 8, 24, 400.0, 8
        n_prefixes, prefix_len = 3, 24
        tail_range, mnt_range = (1, 7), (2, 8)

    cfg = FFConfig(batch_size=slots, num_devices=1)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    wl_rng = np.random.RandomState(31)
    workload, _ = sample_shared_prefix_workload(
        wl_rng, n_req, vocab, num_prefixes=n_prefixes,
        prefix_len=prefix_len, tail_range=tail_range,
        max_new_range=mnt_range)
    max_blocks = max_seq // page
    num_blocks = 1 + slots * max_blocks  # identical KV HBM both tiers
    warm = np.random.RandomState(999).randint(0, vocab, page).tolist()

    def run_tier(paged_kernel):
        sched = ContinuousScheduler.from_trained(
            ff, batch_slots=2 * slots, page_size=page,
            num_blocks=num_blocks, devices=[dev],
            prefix_cache=True, prefill_chunk=chunk,
            paged_kernel=paged_kernel, check_invariants=True)
        try:
            sched.generate(warm, 2, timeout=120.0)
            sched.generate(warm, 2, timeout=120.0)  # full-hit COW warm
            report = run_loadgen(sched, workload, rate, seed=17,
                                 detail=True, record_tokens=True)
            return report, sched.stats()
        finally:
            sched.close()

    gather_report, gather_stats = run_tier("gather")
    kernel_report, kernel_stats = run_tier("pallas")

    def by_idx(report):
        return {r["idx"]: r["tokens"] for r in report["records"]
                if r.get("ok")}
    g_toks, k_toks = by_idx(gather_report), by_idx(kernel_report)
    assert set(g_toks) == set(k_toks), "completion sets differ"
    mismatched = sum(1 for i in g_toks if g_toks[i] != k_toks[i])
    assert mismatched == 0, \
        f"{mismatched} completions differ gather vs kernel"

    pk = kernel_stats["paged_kernel"]
    assert pk["formulation"] == "pallas"
    # THE traffic acceptance: per-step KV reads follow live tokens,
    # not slots * table_width (what the dense gather materializes)
    assert 0 < pk["blocks_read"] < pk["dense_blocks_equiv"], pk
    dispatches = (kernel_stats["steps"]
                  + kernel_stats["prefill_steps"] * chunk)
    ratio = (kernel_report.get("tokens_per_s", 0.0)
             / max(gather_report.get("tokens_per_s", 0.0), 1e-9))
    return {
        "workload": (
            f"{n_req} reqs over {n_prefixes} shared {prefix_len}-token "
            f"prefixes, tails {tail_range}, max_new {mnt_range}, "
            f"Poisson {rate} rps, greedy, {2 * slots} slots, "
            f"page {page}, chunk {chunk}, equal KV pool bytes"
        ),
        "gather": gather_report,
        "pallas": kernel_report,
        "kernel_vs_gather_tokens_per_s": round(ratio, 3),
        "kernel_real_on_this_backend": bool(on_tpu),  # CPU = interpreter
        "kv_blocks_read": pk["blocks_read"],
        "kv_dense_blocks_equiv": pk["dense_blocks_equiv"],
        "kv_read_fraction_of_dense": round(
            pk["blocks_read"] / max(pk["dense_blocks_equiv"], 1), 4),
        "kv_bytes_read": pk["bytes_read"],
        "kv_dense_bytes_avoided": pk["dense_bytes_avoided"],
        "kv_bytes_read_per_dispatch": round(
            pk["bytes_read"] / max(dispatches, 1), 1),
        "completions_identical": True,   # asserted above
        "reads_scale_with_live_tokens": True,  # asserted above
        "invariants_checked_every_step": True,  # check_invariants=True
    }


def bench_serving_gspmd(dev, on_tpu):
    """GSPMD tensor-parallel serving leg (manifest v20): the shared-
    prefix workload through the paged continuous tier single-chip
    (tp=1) and on a 2-chip replica mesh (tp=2) at EQUAL PER-CHIP KV
    POOL BYTES.  Head-sharded pools halve each block's per-chip bytes,
    so the tp=2 engine funds 2x the blocks — and 2x the decode slots —
    in the same per-chip HBM; the host-owned block-table machinery
    (prefix sharing, COW, chunked prefill) runs unchanged on the
    sharded physical blocks.  Greedy completions are asserted
    token-identical across degrees (the single-chip gather formulation
    is the oracle) with the kv_pool invariant checker at EVERY
    scheduler step of both runs.  Off TPU the mesh is virtual CPU
    devices, so tokens/s measures emulated collectives; the capacity
    (2x slots at equal per-chip bytes) + identity assertions are the
    acceptance bar."""
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.serving import ContinuousScheduler
    from flexflow_tpu.serving.loadgen import (run_loadgen,
                                              sample_shared_prefix_workload)

    leg = MANIFEST["legs"]["serving_gspmd"]
    devs = jax.devices()
    tp = leg["tp"]
    if len(devs) < tp:
        return {"skipped": (f"needs >= {tp} visible devices for the "
                            f"tp={tp} replica, have {len(devs)}")}
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        rate, chunk = leg["offered_rps"], leg["prefill_chunk"]
        n_prefixes, prefix_len = leg["num_prefixes"], leg["prefix_len"]
        tail_range = tuple(leg["tail_range"])
        mnt_range = tuple(leg["max_new_range"])
    else:
        # two engines compile (one under GSPMD search), so the smoke
        # shape is smaller than serving_prefix's
        vocab, max_seq = 64, 32
        hidden, layers, heads, inter = 64, 2, 4, 128
        slots, page, n_req, rate, chunk = 4, 4, 24, 600.0, 4
        n_prefixes, prefix_len = 2, 8
        tail_range, mnt_range = (1, 5), (2, 6)

    cfg = FFConfig(batch_size=slots, num_devices=1)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=devs[:1])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    wl_rng = np.random.RandomState(29)
    workload, _ = sample_shared_prefix_workload(
        wl_rng, n_req, vocab, num_prefixes=n_prefixes,
        prefix_len=prefix_len, tail_range=tail_range,
        max_new_range=mnt_range)

    # equal PER-CHIP bytes: each tp=2 block costs 1/2 per chip, so the
    # 2-chip pool funds 2x the blocks — spent on 2x the decode slots
    max_blocks = max_seq // page
    base_blocks = 1 + slots * max_blocks

    def run_degree(degree, n_slots, n_blocks):
        sched = ContinuousScheduler.from_trained(
            ff, batch_slots=n_slots, page_size=page,
            num_blocks=n_blocks, devices=devs[:degree],
            prefix_cache=True, prefill_chunk=chunk,
            check_invariants=True, tp=degree)  # audit at EVERY step
        try:
            report = run_loadgen(sched, workload, rate, seed=17,
                                 detail=True, record_tokens=True)
            stats = sched.stats()
            sched.pool.check_invariants()
            return report, stats
        finally:
            sched.close()

    base_report, base_stats = run_degree(1, slots, base_blocks)
    tp_report, tp_stats = run_degree(tp, tp * slots, tp * base_blocks)

    # greedy completions token-identical across degrees: the
    # single-chip gather formulation is the oracle
    def by_idx(report):
        return {r["idx"]: r["tokens"] for r in report["records"]
                if r.get("ok")}
    base_toks, tp_toks = by_idx(base_report), by_idx(tp_report)
    assert set(base_toks) == set(tp_toks), "completion sets differ"
    mismatched = sum(1 for i in base_toks
                     if base_toks[i] != tp_toks[i])
    assert mismatched == 0, \
        f"{mismatched} completions differ between tp=1 and tp={tp}"

    # the headline capacity claim, checked on the telemetry the
    # engines themselves report
    per_chip_1 = base_stats["tp"]["kv_pool_bytes_per_chip"]
    per_chip_tp = tp_stats["tp"]["kv_pool_bytes_per_chip"]
    assert per_chip_tp == per_chip_1, \
        f"per-chip pool bytes differ: {per_chip_1} vs {per_chip_tp}"
    assert tp_stats["tp"]["degree"] == tp
    assert tp_stats["tp"]["kv_block_bytes_per_chip"] * tp == \
        tp_stats["tp"]["kv_block_bytes"]

    ratio = (tp_report.get("tokens_per_s", 0.0)
             / max(base_report.get("tokens_per_s", 0.0), 1e-9))
    return {
        "workload": (
            f"{n_req} reqs over {n_prefixes} shared {prefix_len}-token "
            f"prefixes, tails {tail_range}, max_new {mnt_range}, "
            f"Poisson {rate} rps, greedy, page {page}, chunk {chunk}, "
            f"tp=1 ({slots} slots, {base_blocks} blocks) vs tp={tp} "
            f"({tp * slots} slots, {tp * base_blocks} blocks) at equal "
            f"per-chip KV bytes"
        ),
        "tp1": base_report,
        f"tp{tp}": tp_report,
        "tp_vs_tp1_tokens_per_s": round(ratio, 3),
        "kv_pool_bytes_per_chip": per_chip_1,
        "per_chip_bytes_equal": True,    # asserted above
        "slots": {"tp1": slots, f"tp{tp}": tp * slots},
        "slots_ratio_at_equal_per_chip_hbm": float(tp),
        "replica_mesh": tp_stats["tp"]["mesh_shape"],
        "prefix_cache_tp": tp_stats["prefix_cache"],
        "completions_identical": True,   # asserted above
        "invariants_checked_every_step": True,  # check_invariants=True
    }


def bench_serving_resilience(dev, on_tpu):
    """Replicated-front availability leg (manifest v12): the Poisson
    workload of the serving leg against a 2-replica ServingFront with
    a SEEDED replica kill (injected hung decode step -> StepWatchdog
    taxonomy -> supervised restart) fired mid-run.  Reports
    availability (completed/submitted — the acceptance bar is >= 0.99
    with the fault injected), p99 TTFT before/during/after the fault
    window, recovery time, and the requeue/restart counters.  Greedy
    decoding keeps every completion token-identical to a fault-free
    run — the front requeues stranded requests instead of failing
    them."""
    import time as _time

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.obs.metrics import MetricsRegistry
    from flexflow_tpu.resilience.faults import FaultKind, FaultPlan
    from flexflow_tpu.serving import ServingFront
    from flexflow_tpu.serving.loadgen import run_loadgen, sample_workload

    leg = MANIFEST["legs"]["serving_resilience"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        rate, kill_step = leg["offered_rps"], leg["kill_step"]
        plen_range = tuple(leg["prompt_len_range"])
        mnt_range = tuple(leg["max_new_range"])
    else:
        vocab, max_seq = 64, 64
        hidden, layers, heads, inter = 128, 2, 4, 256
        slots, page, n_req, rate = 4, 8, 48, 400.0
        plen_range, mnt_range = (2, 8), (2, 10)
        kill_step = 80  # ~mid-run: the smoke workload spans ~150 steps

    cfg = FFConfig(batch_size=slots, num_devices=1,
                   serving_slots=slots, kv_page_size=page,
                   serving_replicas=2, serving_step_timeout=0.0,
                   serving_max_restarts=3, request_retry_limit=3)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    reg = MetricsRegistry()
    front = ServingFront.from_trained(
        ff, devices=[dev], registry=reg, retry_backoff=0.01,
        fault_plans={0: FaultPlan.single(kill_step,
                                         FaultKind.HUNG_STEP)},
    )
    try:
        # warm BOTH replicas' decode-step compiles before timing: more
        # concurrent warm requests than one replica's slots forces the
        # dispatcher to spread them
        warm = [front.generate_async([1, 2], 2)
                for _ in range(2 * slots)]
        for h in warm:
            h.wait(300.0)
        wl_rng = np.random.RandomState(11)
        workload = sample_workload(wl_rng, n_req, vocab,
                                   prompt_len_range=plen_range,
                                   max_new_range=mnt_range)
        t0 = _time.monotonic()
        report = run_loadgen(front, workload, rate, seed=7,
                             detail=True)
        rep0 = front.replicas[0]
        # the rebuild pays a decode-twin compile, which can outlast a
        # short smoke run — wait it out so recovery time is recorded
        deadline = _time.monotonic() + 120.0
        while (_time.monotonic() < deadline
               and rep0.state == "restarting"):
            _time.sleep(0.05)
        death_s = (rep0.last_death_t - t0
                   if rep0.last_death_t is not None else None)
        recover_s = (rep0.last_live_t - t0
                     if rep0.last_death_t is not None
                     and rep0.last_live_t is not None
                     and rep0.last_live_t > rep0.last_death_t else None)
    finally:
        front.close()

    def p99(vals):
        return (round(float(np.percentile(vals, 99)) * 1e3, 2)
                if vals else None)

    records = report.pop("records", [])
    # the fault window runs from the death until the replica is LIVE
    # again; on short smoke runs recovery can postdate the last request
    fault_end = recover_s if recover_s is not None else float("inf")
    before = [r["ttft_s"] for r in records
              if r.get("ok") and death_s is not None
              and r["submit_s"] < death_s]
    during = [r["ttft_s"] for r in records
              if r.get("ok") and death_s is not None
              and death_s <= r["submit_s"] < fault_end]
    after = [r["ttft_s"] for r in records
             if r.get("ok") and recover_s is not None
             and r["submit_s"] >= fault_end]
    availability = report["completed"] / max(report["requests"], 1)
    return {
        "workload": (
            f"{n_req} reqs, Poisson {rate} rps, 2 replicas, "
            f"seeded replica-0 kill at decode step {kill_step}"
        ),
        "availability": round(availability, 4),
        "completed": report["completed"],
        "submitted": report["requests"],
        "failures": report["failures"],
        "fault": {
            "death_at_s": round(death_s, 3) if death_s is not None else None,
            "recovery_s": (round(rep0.last_recovery_s, 3)
                           if rep0.last_recovery_s is not None else None),
            "replica_deaths": sum(r.deaths for r in front.replicas),
            "replica_restarts": sum(r.restarts for r in front.replicas),
            "requeued_requests": front.requeued_requests,
        },
        "ttft_p99_ms": {
            "before_fault": p99(before),
            "during_fault": p99(during),
            "after_recovery": p99(after),
        },
        "tokens_per_s": report.get("tokens_per_s", 0.0),
    }


def bench_serving_disagg(dev, on_tpu):
    """Disaggregated prefill/decode fleet leg (manifest v21): the
    shared-prefix workload plus a sub-page prompt mix through a
    1-prefill + 1-decode DisaggServingFront vs the colocated 2-mixed
    ServingFront at EQUAL TOTAL CHIPS.  Multi-page prompts land on the
    migrate side of the dispatcher's cost model (KV blocks stream
    replica-to-replica and re-enter as a prefix-cache hit on the
    decode class); sub-page prompts have nothing block-aligned to ship
    and re-prefill — the leg asserts BOTH decisions fire, and that
    greedy completions are TOKEN-IDENTICAL between the two fleets (the
    colocated front is the oracle).  Reports per-class TTFT/per-token
    latency, migration decision/bytes counters, and the tokens/s
    ratio.  docs/SERVING.md "Disaggregated fleet"."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.obs.metrics import MetricsRegistry
    from flexflow_tpu.serving import DisaggServingFront, ServingFront
    from flexflow_tpu.serving.loadgen import (
        run_loadgen, sample_shared_prefix_workload, sample_workload)

    leg = MANIFEST["legs"]["serving_disagg"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        rate, chunk = leg["offered_rps"], leg["prefill_chunk"]
        n_prefixes, prefix_len = leg["num_prefixes"], leg["prefix_len"]
        tail_range = tuple(leg["tail_range"])
        mnt_range = tuple(leg["max_new_range"])
        n_sub = leg["subpage_requests"]
        sub_range = tuple(leg["subpage_len_range"])
    else:
        vocab, max_seq = 64, 32
        hidden, layers, heads, inter = 64, 2, 4, 128
        slots, page, n_req, rate, chunk = 4, 4, 24, 400.0, 4
        n_prefixes, prefix_len = 2, 8
        tail_range, mnt_range = (1, 4), (2, 6)
        n_sub, sub_range = 8, (2, 4)

    cfg = FFConfig(batch_size=slots, num_devices=1,
                   serving_slots=slots, kv_page_size=page,
                   serving_replicas=2, prefill_chunk=chunk)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    wl_rng = np.random.RandomState(31)
    shared_wl, _ = sample_shared_prefix_workload(
        wl_rng, n_req, vocab, num_prefixes=n_prefixes,
        prefix_len=prefix_len, tail_range=tail_range,
        max_new_range=mnt_range)
    # sub-page prompts: nothing block-aligned to ship — the cost
    # model's guaranteed re-prefill side
    sub_wl = sample_workload(wl_rng, n_sub, vocab,
                             prompt_len_range=sub_range,
                             max_new_range=mnt_range)
    workload = shared_wl + sub_wl

    def run_front(front):
        try:
            # warm every replica's compiles off the clock: a sub-page
            # prompt exercises the direct decode path, a multi-page
            # one the prefill pass + migration path
            warm = [front.generate_async([1, 2], 2)
                    for _ in range(2 * slots)]
            warm.append(front.generate_async(
                list(range(1, 2 * page + 2)), 2))
            for h in warm:
                h.wait(300.0)
            report = run_loadgen(front, workload, rate, seed=19,
                                 detail=True, record_tokens=True)
            return report, front.stats()
        finally:
            front.close()

    colo_report, _ = run_front(ServingFront.from_trained(
        ff, devices=[dev]))
    reg = MetricsRegistry()
    disagg_report, disagg_stats = run_front(
        DisaggServingFront.from_trained(
            ff, num_replicas=2, devices=[dev],
            roles=["prefill", "decode"], registry=reg))

    # greedy completions token-identical: the colocated fleet is the
    # oracle, migration is invisible in the output stream
    def by_idx(report):
        return {r["idx"]: r["tokens"] for r in report["records"]
                if r.get("ok")}
    colo_toks, disagg_toks = by_idx(colo_report), by_idx(disagg_report)
    assert set(colo_toks) == set(disagg_toks), "completion sets differ"
    mismatched = sum(1 for i in colo_toks
                     if colo_toks[i] != disagg_toks[i])
    assert mismatched == 0, \
        f"{mismatched} completions differ colocated vs disaggregated"

    dg = disagg_stats["disagg"]
    # both dispatcher decisions must fire, or the leg measured only
    # half the machinery
    assert dg["migrate_decisions"] > 0, "no migration was ever chosen"
    assert dg["reprefill_decisions"] > 0, \
        "no re-prefill was ever chosen (sub-page mix missing?)"
    roles = disagg_stats["roles"]
    for r in colo_report, disagg_report:
        r.pop("records", None)
    ratio = (disagg_report.get("tokens_per_s", 0.0)
             / max(colo_report.get("tokens_per_s", 0.0), 1e-9))
    return {
        "workload": (
            f"{n_req} shared-prefix reqs ({n_prefixes} x "
            f"{prefix_len}-token prefixes, tails {tail_range}) + "
            f"{n_sub} sub-page reqs {sub_range}, max_new {mnt_range}, "
            f"Poisson {rate} rps, greedy, page {page}, chunk {chunk}; "
            f"colocated 2-mixed vs prefill=1,decode=1 at equal chips"
        ),
        "colocated": colo_report,
        "disaggregated": disagg_report,
        "disagg_vs_colocated_tokens_per_s": round(ratio, 3),
        "decisions": {
            "migrate": dg["migrate_decisions"],
            "reprefill": dg["reprefill_decisions"],
            "migrations_ok": dg["migrations_ok"],
            "migrations_failed": dg["migrations_failed"],
        },
        "kv_transfer": dg["kv_transfer"],
        "per_class": {
            role: {
                "replicas": st["replicas"],
                "ttft_ms": st["ttft"],
                "per_token_ms": st["per_token"],
                "service_rate_rps": st["service_rate_rps"],
            } for role, st in roles.items()
        },
        "completions_identical": True,  # asserted above
        "both_decisions_exercised": True,  # asserted above
    }


def bench_serving_spec(dev, on_tpu):
    """Speculative-decoding leg (manifest v22): the SAME repetitive
    workload (sample_repetitive_workload: phrase-pool prompts with
    high n-gram self-overlap) and arrival sequence through four tiers
    at EQUAL KV pool bytes — the PR 6 continuous tier (no sharing,
    one-token prefill), the PR 14 tier (prefix cache + chunked
    prefill, `--spec-decode off`), and the PR 14 tier under
    `--spec-decode ngram` and `draft` (a 1-layer draft GPT trained on
    the same data).  The target is TRAINED on the phrase distribution
    so its greedy generations keep quoting phrases the context already
    contains — the regime prompt-lookup speculation feeds on.  Asserts
    greedy completions byte-identical across ALL modes (verify rides
    the lax.scan chunk twin, so acceptance is token-identical by
    construction) with the kv_pool invariant checker at every step,
    and that the speculative tiers accept > 1.5 draft tokens per
    verify round.  Reports tokens/s per tier, accept rates, and
    accepted-tokens/round."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.serving import ContinuousScheduler
    from flexflow_tpu.serving.loadgen import (run_loadgen,
                                              sample_repetitive_workload)

    leg = MANIFEST["legs"]["serving_spec"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        rate, chunk = leg["offered_rps"], leg["prefill_chunk"]
        spec_k = leg["spec_k"]
        n_tpl, ppt = leg["num_templates"], leg["phrases_per_template"]
        phrase_len = leg["phrase_len"]
        phrases_range = tuple(leg["prompt_phrases_range"])
        mnt_range = tuple(leg["max_new_range"])
        d_hidden, d_layers = leg["draft_hidden"], leg["draft_layers"]
        d_heads, d_inter = leg["draft_heads"], leg["draft_intermediate"]
        train_steps = leg["train_steps"]
    else:
        # smoke shape: a tiny vocab and a 4-phrase pool so both models
        # MEMORIZE the phrase grammar in a few hundred SGD steps —
        # within-phrase continuations become deterministic, which is
        # what makes the n-gram drafts keep getting accepted
        vocab, max_seq = 32, 64
        hidden, layers, heads, inter = 128, 2, 4, 256
        slots, page, n_req, rate, chunk = 8, 8, 24, 600.0, 8
        spec_k = 4
        n_tpl, ppt, phrase_len = 2, 2, 8
        phrases_range, mnt_range = (3, 5), (8, 16)
        d_hidden, d_layers, d_heads, d_inter = 32, 1, 2, 64
        train_steps = 300

    wl_rng = np.random.RandomState(23)
    workload, _ = sample_repetitive_workload(
        wl_rng, n_req, vocab, num_templates=n_tpl,
        phrases_per_template=ppt, phrase_len=phrase_len,
        prompt_phrases_range=phrases_range, max_new_range=mnt_range)

    # training corpus from the SAME phrase pools: a fresh seed-23 rng
    # redraws the identical pools (they come from the stream's first
    # draws), and long phrase chains trimmed to max_seq+1 give the
    # next-token rows that teach both models the phrase grammar
    n_phrases_per_row = -(-(max_seq + 1) // phrase_len)  # ceil
    corpus_reqs, _ = sample_repetitive_workload(
        np.random.RandomState(23), 256, vocab, num_templates=n_tpl,
        phrases_per_template=ppt, phrase_len=phrase_len,
        prompt_phrases_range=(n_phrases_per_row, n_phrases_per_row))
    corpus = np.stack([np.asarray(p[:max_seq + 1], np.int32)
                       for p, _ in corpus_reqs])

    def phrase_rows(rng, n_rows):
        return corpus[rng.randint(len(corpus), size=n_rows)]

    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()

    def make_model(h, n_layers, n_heads, i):
        cfg = FFConfig(batch_size=slots, num_devices=1)
        ff = FFModel(cfg)
        build_gpt(ff, batch_size=slots, seq_length=max_seq,
                  hidden_size=h, num_layers=n_layers, num_heads=n_heads,
                  intermediate_size=i, vocab_size=vocab)
        ff.compile(optimizer=SGDOptimizer(lr=0.5),
                   loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   devices=[dev])
        rng = np.random.RandomState(7)
        for _ in range(train_steps):
            rows = phrase_rows(rng, slots)
            ff.train_step({"input": rows[:, :-1], "positions": pos},
                          rows[:, 1:])
        return ff

    ff = make_model(hidden, layers, heads, inter)
    draft_ff = make_model(d_hidden, d_layers, d_heads, d_inter)

    # equal-HBM pitch across all four tiers
    max_blocks = max_seq // page
    num_blocks = 1 + slots * max_blocks
    warm_rng = np.random.RandomState(999)
    warm = warm_rng.randint(0, vocab, page).tolist()

    def run_tier(prefix_cache, prefill_chunk, spec, d_ff=None):
        sched = ContinuousScheduler.from_trained(
            ff, batch_slots=slots, page_size=page,
            num_blocks=num_blocks, devices=[dev],
            prefix_cache=prefix_cache, prefill_chunk=prefill_chunk,
            spec_decode=spec, spec_k=spec_k, draft_ff=d_ff,
            check_invariants=True)  # invariant sweep at EVERY step
        try:
            sched.generate(warm, 2, timeout=120.0)
            sched.generate(warm, 2, timeout=120.0)
            report = run_loadgen(sched, workload, rate, seed=13,
                                 detail=True, record_tokens=True)
            stats = sched.stats()
            sched.pool.check_invariants()
            return report, stats
        finally:
            sched.close()

    pr6_report, _ = run_tier(False, 0, "off")
    off_report, off_stats = run_tier(True, chunk, "off")
    ngram_report, ngram_stats = run_tier(True, chunk, "ngram")
    draft_report, draft_stats = run_tier(True, chunk, "draft", draft_ff)

    # greedy completions must be byte-identical across ALL modes
    def by_idx(report):
        return {r["idx"]: r["tokens"] for r in report["records"]
                if r.get("ok")}
    base_toks = by_idx(off_report)
    for name, rep in (("pr6", pr6_report), ("ngram", ngram_report),
                      ("draft", draft_report)):
        toks = by_idx(rep)
        assert set(toks) == set(base_toks), \
            f"{name}: completion set differs from spec-off"
        bad = sum(1 for i in base_toks if toks[i] != base_toks[i])
        assert bad == 0, f"{name}: {bad} completions differ from spec-off"

    for name, st in (("ngram", ngram_stats), ("draft", draft_stats)):
        spec = st["speculative"]
        assert spec["rounds"] > 0, f"{name}: no verify rounds ran"
        assert spec["accepted_per_round"] > 1.5, \
            (f"{name}: accepted-tokens/round "
             f"{spec['accepted_per_round']} <= 1.5")
        assert not spec["degraded"], f"{name}: engine degraded"

    def tps(rep):
        return rep.get("tokens_per_s", 0.0)

    return {
        "workload": (
            f"{n_req} reqs, {n_tpl} templates x {ppt} phrases x "
            f"{phrase_len} tokens, {phrases_range} phrases/prompt, "
            f"max_new {mnt_range}, Poisson {rate} rps, greedy, "
            f"{slots} slots, page {page}, chunk {chunk}, k {spec_k}, "
            f"equal KV bytes"
        ),
        "pr6_baseline": pr6_report,
        "off": off_report,
        "ngram": ngram_report,
        "draft": draft_report,
        "ngram_speculative": ngram_stats["speculative"],
        "draft_speculative": draft_stats["speculative"],
        "off_vs_pr6_tokens_per_s": round(
            tps(off_report) / max(tps(pr6_report), 1e-9), 3),
        "ngram_vs_off_tokens_per_s": round(
            tps(ngram_report) / max(tps(off_report), 1e-9), 3),
        "draft_vs_off_tokens_per_s": round(
            tps(draft_report) / max(tps(off_report), 1e-9), 3),
        "ngram_tokens_per_s_win": bool(
            tps(ngram_report) > tps(off_report)),
        "accepted_per_round_gt_1_5": True,  # asserted above
        "completions_identical": True,  # asserted above
        "invariants_checked_every_step": True,  # check_invariants=True
    }


def bench_serving_trace(dev, on_tpu):
    """Request-tracing leg (manifest v23): the disaggregated fleet
    under `--spec-decode ngram` with request tracing ON vs the
    identical traced-OFF twin (docs/OBSERVABILITY.md "Request
    tracing").  A repetitive multi-page workload (migrate side of the
    dispatcher's cost model, n-gram-draftable continuations) plus a
    sub-page mix (guaranteed re-prefill side) runs through both
    twins; the leg asserts greedy completions TOKEN-IDENTICAL (the
    tracer must be a pure observer), every completed request's
    trace_id resolving to exactly ONE connected trace tree (no
    orphan spans — kv_adopt joins via the FFKV frame header), a
    `migration` child present on every tree whose dispatch span
    priced `migrate`, spec verify rounds riding shared batch spans,
    and tracing overhead within 5% tokens/s on TPU captures (the CPU
    smoke bounds it loosely — tiny runs are noise-dominated)."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.obs.metrics import MetricsRegistry
    from flexflow_tpu.obs.reqtrace import ReqTracer
    from flexflow_tpu.serving import DisaggServingFront
    from flexflow_tpu.serving.loadgen import (
        run_loadgen, sample_repetitive_workload, sample_workload)
    from tools import trace_analyze

    leg = MANIFEST["legs"]["serving_trace"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        rate, chunk = leg["offered_rps"], leg["prefill_chunk"]
        spec_k = leg["spec_k"]
        n_tpl, ppt = leg["num_templates"], leg["phrases_per_template"]
        phrase_len = leg["phrase_len"]
        phrases_range = tuple(leg["prompt_phrases_range"])
        mnt_range = tuple(leg["max_new_range"])
        n_sub = leg["subpage_requests"]
        sub_range = tuple(leg["subpage_len_range"])
        sample = leg["trace_sample"]
    else:
        vocab, max_seq = 64, 64
        hidden, layers, heads, inter = 64, 2, 4, 128
        slots, page, n_req, rate, chunk = 4, 4, 16, 400.0, 4
        spec_k = 4
        n_tpl, ppt, phrase_len = 2, 2, 8
        phrases_range, mnt_range = (3, 5), (2, 6)
        n_sub, sub_range = 6, (2, 4)
        sample = 1.0

    cfg = FFConfig(batch_size=slots, num_devices=1,
                   serving_slots=slots, kv_page_size=page,
                   serving_replicas=2, prefill_chunk=chunk,
                   spec_decode="ngram", spec_k=spec_k,
                   trace_sample=sample)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    wl_rng = np.random.RandomState(47)
    # multi-page repetitive prompts: migrate-side AND n-gram-draftable
    rep_wl, _ = sample_repetitive_workload(
        wl_rng, n_req, vocab, num_templates=n_tpl,
        phrases_per_template=ppt, phrase_len=phrase_len,
        prompt_phrases_range=phrases_range, max_new_range=mnt_range)
    sub_wl = sample_workload(wl_rng, n_sub, vocab,
                             prompt_len_range=sub_range,
                             max_new_range=mnt_range)
    workload = rep_wl + sub_wl

    def run_front(tracer, reg):
        front = DisaggServingFront.from_trained(
            ff, num_replicas=2, devices=[dev],
            roles=["prefill", "decode"], registry=reg,
            reqtrace=tracer)
        try:
            warm = [front.generate_async([1, 2], 2)
                    for _ in range(2 * slots)]
            warm.append(front.generate_async(
                list(range(1, 2 * page + 2)), 2))
            for h in warm:
                h.wait(300.0)
            report = run_loadgen(front, workload, rate, seed=29,
                                 detail=True, record_tokens=True)
            return report, front.stats()
        finally:
            front.close()

    off_report, _ = run_front(None, None)
    reg = MetricsRegistry()
    tracer = ReqTracer(registry=reg, sample=sample)
    on_report, on_stats = run_front(tracer, reg)

    # the tracer is a pure observer: greedy completions identical
    def by_idx(report):
        return {r["idx"]: r["tokens"] for r in report["records"]
                if r.get("ok")}
    off_toks, on_toks = by_idx(off_report), by_idx(on_report)
    assert set(off_toks) == set(on_toks), "completion sets differ"
    bad = sum(1 for i in off_toks if off_toks[i] != on_toks[i])
    assert bad == 0, f"{bad} completions differ traced vs untraced"

    dg = on_stats["disagg"]
    assert dg["migrate_decisions"] > 0, "no migration was ever chosen"
    assert dg["reprefill_decisions"] > 0, \
        "no re-prefill was ever chosen (sub-page mix missing?)"
    # every completed request = exactly one connected trace tree; the
    # warm-up traces drain through the same analyzer
    traces, batch = trace_analyze.build_traces(tracer.spans)
    ok_records = [r for r in on_report["records"] if r.get("ok")]
    assert all("trace_id" in r for r in ok_records), \
        "a completed request's detail record has no trace_id"
    disconnected, missing_migration = [], []
    for r in ok_records:
        spans = traces.get(r["trace_id"])
        assert spans, f"no trace tree for {r['trace_id']}"
        ok, orphans = trace_analyze.check_connected(spans)
        if not ok:
            disconnected.append((r["trace_id"], orphans))
        names = {s["name"] for s in spans}
        migrated = any(s["name"] == "dispatch"
                       and s["args"].get("decision") == "migrate"
                       for s in spans)
        if migrated and "migration" not in names:
            missing_migration.append(r["trace_id"])
    assert not disconnected, f"disconnected trees: {disconnected}"
    assert not missing_migration, \
        f"migrate decision but no migration span: {missing_migration}"
    # spec verify rounds ride shared batch spans the decode spans ref
    n_spec_batch = sum(1 for b in batch.values()
                       if b["name"] == "spec_verify")
    spec_rounds = sum(
        s["args"].get("spec_rounds", 0)
        for spans in traces.values() for s in spans
        if s["name"] == "decode")
    assert n_spec_batch > 0, "no spec_verify batch spans recorded"

    def tps(rep):
        return rep.get("tokens_per_s", 0.0)

    for r in off_report, on_report:
        r.pop("records", None)
    ratio = tps(on_report) / max(tps(off_report), 1e-9)
    # the headline overhead bar on TPU captures; the CPU smoke's tiny
    # run is noise-dominated, so it only sanity-bounds the ratio
    floor = 0.95 if on_tpu else 0.5
    assert ratio >= floor, \
        f"tracing overhead too high: tokens/s ratio {ratio:.3f}"
    return {
        "workload": (
            f"{n_req} repetitive reqs ({n_tpl} templates x {ppt} "
            f"phrases x {phrase_len} tokens, {phrases_range} "
            f"phrases/prompt) + {n_sub} sub-page reqs {sub_range}, "
            f"max_new {mnt_range}, Poisson {rate} rps, greedy, page "
            f"{page}, chunk {chunk}, ngram k {spec_k}; "
            f"prefill=1,decode=1, traced (sample {sample}) vs untraced"
        ),
        "untraced": off_report,
        "traced": on_report,
        "traced_vs_untraced_tokens_per_s": round(ratio, 3),
        "trace_stats": tracer.stats(),
        "traces_connected": len(ok_records),
        "spec_verify_batch_spans": n_spec_batch,
        "spec_rounds": spec_rounds,
        "decisions": {
            "migrate": dg["migrate_decisions"],
            "reprefill": dg["reprefill_decisions"],
            "migrations_ok": dg["migrations_ok"],
            "migrations_failed": dg["migrations_failed"],
        },
        "completions_identical": True,   # asserted above
        "one_tree_per_request": True,    # asserted above
        "migration_children_present": True,  # asserted above
        "overhead_within_bar": True,     # asserted above
    }


class _FrameDumpFabric:
    """KVTransferFabric wrapper that tees every FFKV frame to a file
    so tools/kvframe_fsck.py can audit the exact bytes that crossed
    the fabric — the bench's offline-verifier leg."""

    def __init__(self, inner, dump_dir):
        self.inner = inner
        self.kind = inner.kind + "+dump"
        self.dump_dir = dump_dir
        self.frames = 0

    def transfer(self, key, data):
        import os as _os
        self.frames += 1
        path = _os.path.join(self.dump_dir,
                             f"frame{self.frames:04d}.ffkv")
        with open(path, "wb") as f:
            f.write(data)
        return self.inner.transfer(key, data)

    def stats(self):
        out = dict(self.inner.stats())
        out["frames_dumped"] = self.frames
        return out


def bench_serving_handoff(dev, on_tpu):
    """Resumable-decode-handoff leg (manifest v24): a long generation
    is pinned mid-decode on one replica of a colocated 2-replica
    ServingFront, then that replica is DRAINED — with `--serving-
    handoff` ON vs OFF (docs/SERVING.md "Mid-decode handoff").  OFF
    is the baseline semantics: drain waits the generation out, so the
    undisturbed completion doubles as the byte-identity oracle.  ON
    must pause the sequence at a step boundary, stream its KV blocks
    (prompt + generated, partial tail included) to the surviving
    replica as FFKV frames, resume mid-generation, and retire the
    source WITHOUT waiting out the generation — asserted as: the
    source retired while the long request was still running, every
    completion byte-identical to the OFF run, zero handoff faults,
    and >0 bytes/blocks streamed.  Every frame that crossed the
    fabric is teed to disk and tools/kvframe_fsck.py must pass over
    the dump (exit 0).  Reports drain wall-time both modes, migrated
    bytes/blocks, and the full handoff decision counters."""
    import shutil
    import tempfile

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.obs.metrics import MetricsRegistry
    from flexflow_tpu.serving import ServingFront
    from flexflow_tpu.serving.kv_transfer import (InProcessFabric,
                                                  KVMigrator)
    from flexflow_tpu.serving.loadgen import sample_workload
    from tools import kvframe_fsck

    leg = MANIFEST["legs"]["serving_handoff"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, chunk = leg["kv_page_size"], leg["prefill_chunk"]
        n_bg = leg["background_requests"]
        bg_range = tuple(leg["background_len_range"])
        bg_mnt = tuple(leg["background_max_new_range"])
        long_len, long_mnt = leg["long_prompt_len"], leg["long_max_new"]
    else:
        vocab, max_seq = 64, 64
        hidden, layers, heads, inter = 64, 2, 4, 128
        slots, page, chunk = 4, 4, 4
        n_bg, bg_range, bg_mnt = 6, (2, 6), (2, 6)
        long_len, long_mnt = 8, 40

    cfg = FFConfig(batch_size=slots, num_devices=1,
                   serving_slots=slots, kv_page_size=page,
                   serving_replicas=2, prefill_chunk=chunk)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    wl_rng = np.random.RandomState(53)
    bg_wl = sample_workload(wl_rng, n_bg, vocab,
                            prompt_len_range=bg_range,
                            max_new_range=bg_mnt)
    long_prompt = [int(t) for t in
                   wl_rng.randint(1, vocab, long_len)]

    def run(handoff, dump_dir=None):
        reg = MetricsRegistry()
        front = ServingFront.from_trained(ff, num_replicas=2,
                                          devices=[dev], registry=reg,
                                          handoff=handoff)
        fabric = None
        if dump_dir is not None:
            # pre-seat the lazy handoff migrator on a frame-dumping
            # fabric so every streamed block lands on disk for fsck
            fabric = _FrameDumpFabric(InProcessFabric(), dump_dir)
            front._handoff_mig = KVMigrator(
                fabric, registry=reg, logger=front.log)
        try:
            warm = [front.generate_async([1, 2], 2)
                    for _ in range(2 * slots)]
            for h in warm:
                h.wait(300.0)
            bg = [front.generate_async(p, m) for p, m in bg_wl]
            bg_toks = [h.wait(300.0) for h in bg]

            bases = {id(r): r.scheduler.stats()["tokens_generated"]
                     for r in front.replicas if r.alive}
            h_long = front.generate_async(long_prompt, long_mnt)
            holder, deadline = None, time.monotonic() + 60.0
            while time.monotonic() < deadline:
                for r in front.replicas:
                    if not r.alive or r.outstanding == 0:
                        continue
                    done = (r.scheduler.stats()["tokens_generated"]
                            - bases.get(id(r), 0))
                    if done >= 2:  # provably mid-decode, not prefill
                        holder = r
                        break
                if holder is not None or h_long.event.is_set():
                    break
                time.sleep(0.0005)
            assert holder is not None, \
                "long generation finished before it could be pinned"

            t0 = time.monotonic()
            assert front.drain_replica(holder), "drain refused"
            long_done_at_retire = None
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if holder.state == "retired":
                    long_done_at_retire = h_long.event.is_set()
                    break
                time.sleep(0.0005)
            drain_s = time.monotonic() - t0
            assert long_done_at_retire is not None, "drain never retired"
            long_toks = h_long.wait(300.0)

            st = front.stats()
            return {
                "long_tokens": long_toks,
                "bg_tokens": bg_toks,
                "drain_s": round(drain_s, 4),
                "long_done_at_retire": long_done_at_retire,
                "handoff": st.get("handoff"),
                "paused": reg.counter("serving/handoff_paused").value,
                "resumed": reg.counter("serving/handoff_resumed").value,
                "frames_dumped": fabric.frames if fabric else 0,
            }
        finally:
            front.close()

    off = run(False)
    dump_dir = tempfile.mkdtemp(prefix="ffkv_bench_")
    try:
        on = run(True, dump_dir=dump_dir)

        # OFF is the oracle: drain waited the generation out untouched
        assert off["long_done_at_retire"], \
            "baseline drain retired before the generation completed"
        assert off["paused"] == 0 and off["handoff"] is None

        # ON retired the source mid-generation and streamed the state
        assert not on["long_done_at_retire"], \
            "handoff drain waited out the generation"
        assert on["paused"] >= 1 and on["resumed"] >= 1, \
            f"no pause/resume: {on['paused']}/{on['resumed']}"
        ho = on["handoff"]
        assert ho and ho["ok"] >= 1, f"no successful handoff: {ho}"
        assert not ho["faults"], f"handoff faults fired: {ho['faults']}"
        kvt = ho.get("kv_transfer") or {}
        assert kvt.get("bytes_streamed", 0) > 0, f"no bytes moved: {kvt}"
        assert kvt.get("blocks_streamed", 0) > 0

        # byte-identity: pause/stream/resume is invisible in the output
        assert on["long_tokens"] == off["long_tokens"], \
            "handed-off long generation diverged from the oracle"
        assert on["bg_tokens"] == off["bg_tokens"], \
            "background completions diverged"

        # offline audit of the exact frames that crossed the fabric
        assert on["frames_dumped"] >= 1, "no FFKV frames dumped"
        fsck_rc = kvframe_fsck.main([dump_dir])
        assert fsck_rc == 0, f"kvframe_fsck found problems (rc {fsck_rc})"
    finally:
        shutil.rmtree(dump_dir, ignore_errors=True)

    if on_tpu:
        assert on["drain_s"] < off["drain_s"], \
            "handoff drain was not faster than waiting out the generation"
    for rep in (on, off):
        rep.pop("long_tokens", None)
        rep.pop("bg_tokens", None)
    return {
        "workload": (
            f"{n_bg} background reqs {bg_range} + one pinned "
            f"{long_len}-token prompt x {long_mnt} new tokens, greedy, "
            f"page {page}, chunk {chunk}; drain the holder, "
            f"--serving-handoff on vs off (colocated 2-replica)"
        ),
        "handoff_on": on,
        "handoff_off": off,
        "drain_speedup": round(
            off["drain_s"] / max(on["drain_s"], 1e-9), 2),
        "migrated": {
            "bytes": (on["handoff"] or {}).get(
                "kv_transfer", {}).get("bytes_streamed", 0),
            "blocks": (on["handoff"] or {}).get(
                "kv_transfer", {}).get("blocks_streamed", 0),
        },
        "decisions": {
            "requested": on["handoff"]["requested"],
            "ok": on["handoff"]["ok"],
            "replays": on["handoff"]["replays"],
            "migrate": on["handoff"]["migrate_decisions"],
            "replay": on["handoff"]["replay_decisions"],
        },
        "completions_identical": True,   # asserted above
        "retired_mid_generation": True,  # asserted above
        "kvframe_fsck_clean": True,      # asserted above
    }


def bench_autoscale(dev, on_tpu):
    """Autoscaling-front leg (manifest v15): a SEEDED square-wave
    burst trace against a ServingFront that starts at min_replicas
    with a ServingAutoscaler attached (serving/autoscaler.py).  The
    burst must scale the fleet UP (replicas spawned through the warm
    from_trained factory) and the post-burst calm must DRAIN it back
    down gracefully — in-flight slots run to completion, so
    requeued_requests stays 0 and a post-run token-identity audit
    (greedy re-generation of every completion on the settled fleet)
    must match byte-for-byte.  Availability acceptance is >= 0.99.
    The autoscaler tick history carries the replica-count timeline;
    TTFT records bucket into a per-second p99 timeline."""
    import time as _time

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.transformer import build_gpt
    from flexflow_tpu.obs.metrics import MetricsRegistry
    from flexflow_tpu.serving import ServingAutoscaler, ServingFront
    from flexflow_tpu.serving.loadgen import run_loadgen, sample_workload

    leg = MANIFEST["legs"]["autoscale"]
    if on_tpu:
        vocab, max_seq = leg["vocab"], leg["max_seq"]
        hidden, layers, heads = leg["hidden"], leg["layers"], leg["heads"]
        inter, slots = leg["intermediate"], leg["slots"]
        page, n_req = leg["kv_page_size"], leg["requests"]
        calm_rps, burst = leg["calm_rps"], leg["burst_factor"]
        period_s = leg["period_s"]
        plen_range = tuple(leg["prompt_len_range"])
        mnt_range = tuple(leg["max_new_range"])
    else:
        vocab, max_seq = 64, 64
        hidden, layers, heads, inter = 128, 2, 4, 256
        slots, page, n_req = 4, 8, 96
        # the burst must OUTRUN one replica's measured service rate on
        # CPU (~100-150 req/s at these lengths) or nothing scales
        calm_rps, burst, period_s = 40.0, 12.0, 0.5
        plen_range, mnt_range = (2, 8), (8, 24)
    min_r, max_r = leg["min_replicas"], leg["max_replicas"]

    cfg = FFConfig(batch_size=slots, num_devices=1,
                   serving_slots=slots, kv_page_size=page,
                   serving_replicas=min_r,
                   serving_min_replicas=min_r,
                   serving_max_replicas=max_r)
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=slots, seq_length=max_seq,
              hidden_size=hidden, num_layers=layers, num_heads=heads,
              intermediate_size=inter, vocab_size=vocab)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (slots, max_seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(max_seq, dtype=np.int32),
                          (slots, max_seq)).copy()
    ff.train_step({"input": ids, "positions": pos}, ids)  # real weights

    reg = MetricsRegistry()
    front = ServingFront.from_trained(ff, num_replicas=min_r,
                                      devices=[dev], registry=reg)
    scaler = ServingAutoscaler(
        front, min_r, max_r,
        interval_s=leg["interval_s"], cooldown_s=leg["cooldown_s"],
        queue_high=leg["queue_high"], queue_low=leg["queue_low"],
        drain_timeout_s=leg["drain_timeout_s"], registry=reg,
    )
    try:
        # warm the initial replica's decode compile before timing
        warm = [front.generate_async([1, 2], 2) for _ in range(slots)]
        for h in warm:
            h.wait(300.0)
        scaler.start()
        wl_rng = np.random.RandomState(11)
        workload = sample_workload(wl_rng, n_req, vocab,
                                   prompt_len_range=plen_range,
                                   max_new_range=mnt_range)
        t0 = _time.monotonic()
        report = run_loadgen(front, workload, calm_rps, seed=7,
                             detail=True, record_tokens=True,
                             arrival="square", burst_factor=burst,
                             period_s=period_s)
        def fleet_size():
            with front._cv:
                return len(front.replicas)

        # a scale-up decided near the end of the trace may still be
        # compiling (add_replica appends AFTER the build) — wait for
        # it to land before judging the drain-down
        # list() snapshot: the loop thread is still appending ticks
        max_fleet = max((e["replicas"] for e in list(scaler.history)),
                        default=min_r)
        # wait on the PEAK fleet, not the current one: when the trace
        # outlasts both the scale-up and the drain-down, the fleet is
        # already back at min_r and a current-size check would spin to
        # the full deadline
        spin_deadline = _time.monotonic() + 120.0
        while (_time.monotonic() < spin_deadline
               and (scaler._spawning
                    or (scaler.scale_ups > 0 and max_fleet <= min_r))):
            _time.sleep(0.05)
            max_fleet = max(max_fleet, fleet_size())
        # post-burst calm: the loop must drain back to min_replicas
        drain_deadline = _time.monotonic() + 120.0
        while _time.monotonic() < drain_deadline:
            max_fleet = max(max_fleet, fleet_size())
            if fleet_size() <= min_r and scaler._draining is None:
                break
            _time.sleep(0.05)
        scaler.stop()
        final_fleet = fleet_size()
        # token-identity audit: greedy decode is deterministic, so
        # every completion re-generated on the settled fleet must be
        # byte-identical — a drain that disturbed an in-flight slot
        # (or a requeue that lost prefix state) would show here
        records = report.pop("records", [])
        audited = mismatches = 0
        for r in records:
            if not r.get("ok") or "tokens" not in r:
                continue
            p, mnt = workload[r["idx"]]
            audited += 1
            if front.generate(p, mnt, timeout=120.0) != r["tokens"]:
                mismatches += 1
        availability = report["completed"] / max(report["requests"], 1)
        # p99-TTFT timeline: 1s submit-time buckets over the run
        buckets = {}
        for r in records:
            if r.get("ok") and "ttft_s" in r:
                buckets.setdefault(int(r["submit_s"]), []).append(
                    r["ttft_s"])
        ttft_timeline = [
            {"t_s": t, "n": len(v),
             "p99_ms": round(float(np.percentile(v, 99)) * 1e3, 2)}
            for t, v in sorted(buckets.items())
        ]
        # replica-count timeline from the autoscaler's tick history
        # (downsampled: keep every entry where the fleet size changed,
        # plus scale decisions)
        timeline = []
        last = None
        for e in scaler.history:
            if e["replicas"] != last or e["action"] != "hold":
                timeline.append({"t_s": round(e["t"] - t0, 2),
                                 "replicas": e["replicas"],
                                 "action": e["action"]})
                last = e["replicas"]
        return {
            "workload": (
                f"{n_req} reqs, square-wave {calm_rps}->"
                f"{calm_rps * burst} rps every {period_s}s, fleet "
                f"[{min_r}, {max_r}] starting at {min_r}"
            ),
            "availability": round(availability, 4),
            "completed": report["completed"],
            "submitted": report["requests"],
            "scale_ups": scaler.scale_ups,
            "scale_downs": scaler.scale_downs,
            "forced_retires": scaler.forced_retires,
            "max_fleet": max_fleet,
            "final_fleet": final_fleet,
            "scaled_up_on_burst": bool(scaler.scale_ups >= 1),
            "drained_down_after": bool(final_fleet == min_r
                                       and scaler.scale_downs >= 1),
            "requeued_requests": front.requeued_requests,
            "token_identity": {
                "audited": audited,
                "mismatches": mismatches,
                "identical": bool(audited > 0 and mismatches == 0),
            },
            "replica_timeline": timeline,
            "ttft_p99_timeline_ms": ttft_timeline,
            "tokens_per_s": report.get("tokens_per_s", 0.0),
        }
    finally:
        front.close()


def _outage_line(reason: str):
    # tunnel/backend outage: emit a diagnostic JSON line instead of a
    # stacktrace/hang so the capture records WHY there are no numbers
    print(json.dumps({
        "metric": "bench unavailable: TPU backend init failed",
        "value": 0.0,
        "unit": "samples/s",
        "vs_baseline": 0.0,
        "manifest_version": MANIFEST["version"],
        "error": reason[:300],
    }))


def main():
    import gc
    import socket

    import jax

    if (os.environ.get("PALLAS_AXON_POOL_IPS")
            and not os.environ.get("JAX_PLATFORMS", "").startswith("cpu")):
        # With the axon relay dead, device init HANGS (the interposer
        # dials the relay regardless of platform), so probe the relay's
        # loopback port with a plain TCP connect first — no jax client,
        # no wedge risk for concurrent chip jobs.
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(2.0)
        relay_up = s.connect_ex(("127.0.0.1", 8082)) == 0
        s.close()
        if not relay_up:
            _outage_line("axon relay (127.0.0.1:8082) is down")
            return
    elif os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # honor the env var through jax.config: under the axon
        # sitecustomize the env var alone routes through an interposer
        # that can hang on a dead relay (see tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    try:
        dev = jax.devices()[0]
    except Exception as e:
        _outage_line(f"{type(e).__name__}: {e}")
        return
    on_tpu = dev.platform != "cpu"

    bert = bench_bert(dev, on_tpu)
    gc.collect()  # drop the previous leg's weights/opt state from HBM
    resnet = bench_resnet50(dev, on_tpu)
    gc.collect()
    bert_long = bench_bert_long(dev, on_tpu)
    gc.collect()
    dlrm = bench_dlrm(dev, on_tpu)
    gc.collect()
    moe = bench_moe_dispatch(dev, on_tpu)
    gc.collect()
    wu = bench_weight_update(on_tpu)
    gc.collect()
    ladder = bench_zero_ladder(dev, on_tpu)
    gc.collect()
    ckpt = bench_checkpoint(dev, on_tpu)
    gc.collect()
    serving = bench_serving(dev, on_tpu)
    gc.collect()
    serving_prefix = bench_serving_prefix(dev, on_tpu)
    gc.collect()
    serving_paged_kernel = bench_serving_paged_kernel(dev, on_tpu)
    gc.collect()
    serving_gspmd = bench_serving_gspmd(dev, on_tpu)
    gc.collect()
    serving_resilience = bench_serving_resilience(dev, on_tpu)
    gc.collect()
    serving_disagg = bench_serving_disagg(dev, on_tpu)
    gc.collect()
    serving_spec = bench_serving_spec(dev, on_tpu)
    gc.collect()
    serving_trace = bench_serving_trace(dev, on_tpu)
    gc.collect()
    serving_handoff = bench_serving_handoff(dev, on_tpu)
    gc.collect()
    autoscale = bench_autoscale(dev, on_tpu)
    gc.collect()
    cold_start = bench_cold_start(dev, on_tpu)
    gc.collect()
    host_loss = bench_host_loss(dev, on_tpu)
    gc.collect()
    multi_slice = bench_multi_slice(dev, on_tpu)
    gc.collect()
    long_context = bench_long_context(dev, on_tpu)
    geomean = float(np.sqrt(max(bert["vs_a100"], 1e-9)
                            * max(resnet["vs_a100"], 1e-9)))
    result = {
        # value is the BERT leg's samples/s (round-over-round
        # comparable); vs_baseline is the geomean of BOTH headline
        # legs' vs-A100 ratios; per-leg numbers live under "legs"
        "metric": (
            "samples/sec/chip, BERT-base seq128 b64 token-ids bf16 "
            "(vs_baseline = geomean of bert_base+resnet50 legs vs A100)"
            if on_tpu else "CPU smoke: BERT tiny + ResNet tiny"
        ),
        "value": bert["samples_per_sec_per_chip"],
        "unit": "samples/s",
        "vs_baseline": round(geomean, 4) if on_tpu else 0.0,
        "manifest_version": MANIFEST["version"],
        "legs": {"bert_base": bert, "resnet50": resnet,
                 "bert_long_context": bert_long, "dlrm": dlrm,
                 "moe_dispatch": moe, "weight_update": wu,
                 "zero_ladder": ladder,
                 "checkpoint": ckpt, "serving": serving,
                 "serving_prefix": serving_prefix,
                 "serving_paged_kernel": serving_paged_kernel,
                 "serving_gspmd": serving_gspmd,
                 "serving_resilience": serving_resilience,
                 "serving_disagg": serving_disagg,
                 "serving_spec": serving_spec,
                 "serving_trace": serving_trace,
                 "serving_handoff": serving_handoff,
                 "autoscale": autoscale,
                 "cold_start": cold_start, "host_loss": host_loss,
                 "multi_slice": multi_slice,
                 "long_context": long_context},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
