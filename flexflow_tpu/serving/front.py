"""ServingFront: one admission queue, N supervised replicas.

PR 6's continuous engine is a single `ContinuousScheduler`: its death
takes the whole service down with every queued and in-flight request.
The front makes availability a property of the FLEET instead:

  * **one shared admission queue.**  Requests are validated and queued
    at the front; a dispatcher hands them to the least-loaded LIVE
    replica, capped at each replica's decode-slot count, so a replica
    death can only strand the bounded set it was actually running —
    the backlog stays at the front, untouched (queue handoff).
  * **supervised replicas** (serving/replica.py): each wraps a
    `ContinuousScheduler` + decode model under the resilience
    primitives — `StepWatchdog(step_timeout)` around the decode
    dispatch, seeded `FaultPlan` injection, jittered-backoff
    `RetryPolicy` with a restart budget, device-loss rebuilds on the
    surviving mesh warmed through the strategy store.
  * **requeue with a bounded retry count.**  A request stranded by a
    replica death (or failed by a transient step fault) goes back to
    the HEAD of the admission queue and runs again on a surviving
    replica — greedy decoding makes the retry token-identical.  A
    request that exhausts `request_retry_limit` fails with a 503
    RETRIABLE error, never a client error: the front never punishes a
    request it admitted.
  * **load shedding, not unbounded queueing.**  While ZERO replicas
    are live, new submissions are refused with `ServiceUnavailable`
    (HTTP 503 + Retry-After via server.py) instead of growing the
    queue without a server; already-admitted requests keep waiting for
    the restart.  If every replica goes PERMANENTLY dead (budget
    exhausted), the queue is failed retriably — no recovery is coming.

API-compatible with the batcher contract (generate / generate_async /
latency_stats / stats / close / worker_alive), plus `health()` for
/v2/health's ok | degraded | down aggregation.  Metrics
(serving/replica_restarts, replica_deaths, requeued_requests,
shed_requests, per-replica queue-depth gauges) ride the shared
obs.metrics registry.  docs/SERVING.md "Replicated front".
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from ..logger import resilience_logger
from ..resilience.faults import FaultPlan
from ..resilience.retry import RetryPolicy
from .replica import ServingReplica


class ServiceUnavailable(RuntimeError):
    """The front cannot take (or finish) this request right now; the
    client should back off and retry.  server.py maps it to HTTP 503
    with a Retry-After header from `retry_after_s`."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class FrontRequest:
    """Front-level future for one admitted request.  Mirrors the
    scheduler handle surface the loadgen and server consume (wait /
    t_submit / t_first_token / t_done / n_generated), independent of
    which replica — or how many, after requeues — ran it."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "event",
                 "result", "error", "t_submit", "t_first_token",
                 "t_done", "n_generated", "retries")

    def __init__(self, prompt, max_new_tokens, temperature):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.event = threading.Event()
        self.result: Optional[List[int]] = None
        self.error: Optional[Exception] = None
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.n_generated = 0
        self.retries = 0  # requeues consumed (replica deaths/faults)

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.event.wait(timeout):
            raise TimeoutError("generation request timed out")
        if self.error is not None:
            raise self.error
        return self.result


class ServingFront:
    """N supervised ContinuousScheduler replicas behind one queue.

    `model_factory(replica_id, survivors=None)` builds one replica's
    decode model (see ServingReplica).  `fault_plans` optionally maps
    replica id -> FaultPlan for seeded fault injection; `step_timeout`
    arms each replica's decode-step watchdog; `max_restarts` /
    `retry_backoff` bound each replica's supervised restarts;
    `request_retry_limit` bounds per-request requeues.
    """

    def __init__(
        self,
        model_factory: Callable,
        num_replicas: int = 2,
        *,
        eos_id: int = -1,
        registry=None,
        seed: int = 0,
        step_timeout: float = 0.0,
        max_restarts: int = 3,
        retry_backoff: float = 0.1,
        request_retry_limit: int = 2,
        fault_plans: Optional[Dict[int, FaultPlan]] = None,
        latency_window: int = 1024,
        close_timeout_s: float = 5.0,
        shed_retry_after_s: float = 1.0,
        sleep: Callable[[float], None] = time.sleep,
        logger=resilience_logger,
    ):
        if num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {num_replicas}")
        if request_retry_limit < 0:
            raise ValueError(
                f"request_retry_limit must be >= 0, "
                f"got {request_retry_limit}")
        self.registry = registry
        self.request_retry_limit = int(request_retry_limit)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.log = logger
        self._cv = threading.Condition()
        self._admission: "deque[FrontRequest]" = deque()
        self._closed = False
        self.requests_done = 0
        self.shed_requests = 0
        self.requeued_requests = 0
        self._latencies = deque(maxlen=latency_window)
        self._ttfts = deque(maxlen=latency_window)
        self._lat_lock = threading.Lock()
        plans = fault_plans or {}
        self.replicas: List[ServingReplica] = [
            ServingReplica(
                i, model_factory,
                eos_id=eos_id, registry=registry,
                seed=seed,
                step_timeout=step_timeout,
                retry=RetryPolicy(max_restarts=max_restarts,
                                  base_backoff=retry_backoff, seed=seed + i),
                fault_plan=plans.get(i),
                close_timeout_s=close_timeout_s,
                sleep=sleep,
                logger=logger,
            )
            for i in range(num_replicas)
        ]
        self.max_seq = self.replicas[0].scheduler.model.max_seq
        for r in self.replicas:
            r.on_state_change = self._on_replica_state
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="serving-front-dispatch",
        )
        self._dispatcher.start()

    @classmethod
    def from_trained(cls, ff_train, num_replicas: Optional[int] = None,
                     *, devices=None, eos_id: int = -1, registry=None,
                     fault_plans: Optional[Dict[int, FaultPlan]] = None,
                     **kw) -> "ServingFront":
        """Replicated front over a trained GPT, honoring the FFConfig
        serving knobs (--serving-replicas / --serving-step-timeout /
        --serving-max-restarts / --request-retry-limit plus the PR 6
        pool geometry).  Each replica compiles its own paged decode
        twin; with the strategy store configured the N-1 later compiles
        (and every post-death rebuild) restore instead of re-searching
        (docs/STORE.md).  A device-loss rebuild truncates `devices` to
        the surviving count."""
        from .scheduler import PagedKVDecodeModel

        cfg = ff_train.config

        def factory(replica_id, survivors=None):
            devs = devices
            if survivors is not None and devs is not None:
                devs = devs[:survivors]
            return PagedKVDecodeModel(
                ff_train,
                batch_slots=cfg.serving_slots,
                page_size=cfg.kv_page_size,
                num_blocks=cfg.kv_pool_blocks or None,
                devices=devs,
            )

        kw.setdefault("step_timeout", cfg.serving_step_timeout)
        kw.setdefault("max_restarts", cfg.serving_max_restarts)
        kw.setdefault("request_retry_limit", cfg.request_retry_limit)
        kw.setdefault("seed", cfg.seed)
        return cls(
            factory,
            cfg.serving_replicas if num_replicas is None else num_replicas,
            eos_id=eos_id, registry=registry, fault_plans=fault_plans,
            **kw,
        )

    # -- replica events --------------------------------------------------
    def _on_replica_state(self, replica: ServingReplica) -> None:
        with self._cv:
            self._cv.notify_all()

    def _live(self) -> List[ServingReplica]:
        return [r for r in self.replicas if r.alive]

    def _all_permanently_dead(self) -> bool:
        return all(r.state == "dead" for r in self.replicas)

    # -- client API ------------------------------------------------------
    def generate_async(self, prompt, max_new_tokens: int = 16,
                       temperature: float = 0.0) -> FrontRequest:
        if self._closed:
            raise RuntimeError("ServingFront is closed")
        # validate at admission (the batcher convention: a bad request
        # fails alone, synchronously, as a client error)
        req = FrontRequest(prompt, max_new_tokens, temperature)
        if not 1 <= len(req.prompt) < self.max_seq:
            raise ValueError(
                f"prompt length {len(req.prompt)} outside "
                f"[1, {self.max_seq})")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._cv:
            if not self._live():
                # all replicas down: shed instead of queueing against
                # a service that may never come back
                self.shed_requests += 1
                if self.registry is not None:
                    self.registry.counter("serving/shed_requests").inc()
                raise ServiceUnavailable(
                    "all serving replicas are down",
                    retry_after_s=self.shed_retry_after_s,
                )
            self._admission.append(req)
            self._cv.notify_all()
        return req

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 timeout: Optional[float] = 60.0) -> List[int]:
        return self.generate_async(
            prompt, max_new_tokens, temperature).wait(timeout)

    # -- dispatch --------------------------------------------------------
    def _pick_replica(self) -> Optional[ServingReplica]:
        """Least-outstanding live replica with dispatch headroom (the
        cap keeps the backlog at the FRONT, where a replica death
        can't strand it)."""
        best = None
        for r in self.replicas:
            sched = r.scheduler  # may concurrently flip to None on death
            if r.state != "live" or sched is None:
                continue
            if r.outstanding >= sched.model.batch_slots:
                continue
            if best is None or r.outstanding < best.outstanding:
                best = r
        return best

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                replica = None
                while not self._closed:
                    if self._admission:
                        if self._all_permanently_dead():
                            break
                        replica = self._pick_replica()
                        if replica is not None:
                            break
                    self._cv.wait(0.2)
                if self._closed:
                    return
                req = self._admission.popleft()
                if replica is None:  # every replica permanently dead
                    self._fail(req, ServiceUnavailable(
                        "all serving replicas are permanently dead "
                        "(restart budgets exhausted)",
                        retry_after_s=self.shed_retry_after_s,
                    ))
                    continue
                replica.outstanding += 1
                self._observe_depth(replica)
            try:
                replica.submit(
                    req.prompt, req.max_new_tokens, req.temperature,
                    on_done=lambda h, _req=req, _r=replica:
                        self._on_settle(_req, _r, h),
                )
            except ValueError as e:
                # pool geometry can never serve it: the request's
                # problem, fail alone
                with self._cv:
                    replica.outstanding -= 1
                    self._observe_depth(replica)
                self._fail(req, e)
            except Exception:
                # the replica died between pick and submit: back to the
                # queue head (dispatch never started — no retry spent)
                with self._cv:
                    replica.outstanding -= 1
                    self._observe_depth(replica)
                    self._admission.appendleft(req)

    def _observe_depth(self, replica: ServingReplica) -> None:
        if self.registry is not None:
            self.registry.gauge(
                f"serving/replica/{replica.replica_id}/queue_depth"
            ).set(replica.outstanding)

    # -- settlement ------------------------------------------------------
    def _fail(self, req: FrontRequest, err: Exception) -> None:
        req.error = err
        req.event.set()

    def _complete(self, req: FrontRequest, handle) -> None:
        req.result = handle.result
        req.n_generated = handle.n_generated
        req.t_first_token = handle.t_first_token
        req.t_done = handle.t_done or time.monotonic()
        with self._lat_lock:
            self._latencies.append(req.t_done - req.t_submit)
            if req.t_first_token is not None:
                self._ttfts.append(req.t_first_token - req.t_submit)
            # settles arrive from every replica's worker thread; the
            # += below is not atomic, so it rides the same lock
            self.requests_done += 1
        req.event.set()

    def _on_settle(self, req: FrontRequest, replica: ServingReplica,
                   handle) -> None:
        """Completion hook, fired once per replica-side handle on
        whichever thread settled it (decode loop, drain, or the
        submit-raced close path)."""
        with self._cv:
            replica.outstanding -= 1
            self._observe_depth(replica)
            self._cv.notify_all()
        err = handle.error
        if err is None:
            self._complete(req, handle)
            return
        if isinstance(err, ValueError):
            self._fail(req, err)  # unservable as posed, retry won't help
            return
        if self._closed:
            self._fail(req, RuntimeError("ServingFront is closed"))
            return
        # replica death, hung step, or transient step fault: the
        # request was ADMITTED, so it never gets a non-retriable error
        req.retries += 1
        if req.retries > self.request_retry_limit:
            self._fail(req, ServiceUnavailable(
                f"request failed {req.retries} times across replicas "
                f"(last: {type(err).__name__}: {err})",
                retry_after_s=self.shed_retry_after_s,
            ))
            return
        self.requeued_requests += 1
        if self.registry is not None:
            self.registry.counter("serving/requeued_requests").inc()
        with self._cv:
            if self._closed:
                # close() may have drained the queue between the check
                # above and here; a late requeue would park the client
                # for its full timeout with no dispatcher left
                self._fail(req, RuntimeError("ServingFront is closed"))
                return
            self._admission.appendleft(req)  # keep its seniority
            self._cv.notify_all()

    # -- stats / health --------------------------------------------------
    @property
    def worker_alive(self) -> bool:
        return self._dispatcher.is_alive() and not self._all_permanently_dead()

    @property
    def batches_run(self) -> int:
        return sum(r.stats()["batches_run"] for r in self.replicas)

    @property
    def tokens_generated(self) -> int:
        return sum(r.stats()["tokens_generated"] for r in self.replicas)

    def latency_stats(self) -> Dict[str, float]:
        from .batcher import latency_percentiles

        return latency_percentiles(self._latencies, self._lat_lock)

    def ttft_stats(self) -> Dict[str, float]:
        from .batcher import latency_percentiles

        return latency_percentiles(self._ttfts, self._lat_lock)

    def health(self) -> Dict:
        """ok = every replica live; degraded = some down, still
        serving; down = nothing live (server.py rides this to HTTP
        200/200/503)."""
        live = len(self._live())
        n = len(self.replicas)
        if self._closed or live == 0:
            status = "down"
        elif live == n:
            status = "ok"
        else:
            status = "degraded"
        return {
            "status": status,
            "replicas_live": live,
            "replicas": [
                {"id": r.replica_id, "state": r.state,
                 "restarts": r.restarts, "deaths": r.deaths}
                for r in self.replicas
            ],
        }

    def stats(self) -> Dict:
        with self._cv:
            queued = len(self._admission)
            replicas = [r.stats() for r in self.replicas]
        if self.registry is not None:
            self.registry.gauge("serving/replicas_live").set(
                len(self._live()))
        return {
            "mode": "replicated",
            "replicas_live": len(self._live()),
            "queue_depth": queued + sum(r["outstanding"]
                                        for r in replicas),
            "requests_done": self.requests_done,
            "requeued_requests": self.requeued_requests,
            "shed_requests": self.shed_requests,
            "tokens_generated": sum(r["tokens_generated"]
                                    for r in replicas),
            "steps": sum(r["batches_run"] for r in replicas),
            "ttft": self.ttft_stats(),
            "latency": self.latency_stats(),
            "replicas": replicas,
        }

    # -- shutdown --------------------------------------------------------
    def close(self, timeout_s: Optional[float] = None):
        """Stop dispatching, close every replica (each close is
        BOUNDED — a wedged decode step cannot hang front shutdown),
        and fail whatever is still queued, promptly."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._dispatcher.join(timeout=2.0)
        for r in self.replicas:
            r.close(timeout_s)
        err = RuntimeError("ServingFront is closed")
        with self._cv:
            while self._admission:
                self._fail(self._admission.popleft(), err)
